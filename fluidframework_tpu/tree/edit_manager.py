"""EditManager: trunk + concurrent-commit integration.

The role of the reference EditManager
(packages/dds/tree/src/core/edit-manager/editManager.ts:47): maintain
the *trunk* (sequenced commits in total order, each stored in trunk
coordinates — i.e. already rebased over everything before it) and a
*local branch* of optimistic commits; integrate each incoming
sequenced commit by rebasing it over the trunk commits its author had
not seen; rebase the local branch over each integrated remote commit.

The author-visibility rule: a commit from session S with reference
sequence number r was authored against trunk@r *plus S's own commits
sequenced in (r, now)* (a session's ops are FIFO). So the rebase set
is exactly the trunk commits in (r, now) from *other* sessions — which
is why the reference keeps per-peer branches as an optimization; we
recompute from the trunk window directly (the collab window is kept
small by MSN eviction, as zamboni does for merge-trees).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .changeset import Change, rebase_change
from .forest import Forest


@dataclass
class Commit:
    change: Change
    session: Any  # client/session id
    seq: int = 0  # sequence number once sequenced
    ref_seq: int = 0  # trunk seq the author had seen


class EditManager:
    def __init__(self, forest: Optional[Forest] = None, session: Any = None):
        self.session = session
        self.trunk: List[Commit] = []  # sequenced, trunk coordinates
        self.local: List[Commit] = []  # optimistic local commits
        self.forest = forest if forest is not None else Forest()
        self.trunk_seq = 0  # seq of the newest trunk commit

    # -------------------------------------------------------------- local

    def add_local(self, change: Change) -> Commit:
        """Record an optimistic local commit (already applied to the
        forest by the caller)."""
        commit = Commit(change=change, session=self.session, ref_seq=self.trunk_seq)
        self.local.append(commit)
        return commit

    # ----------------------------------------------------------- sequenced

    def _concurrent_window(self, commit: Commit) -> List[Change]:
        """Trunk changes the commit's author had not seen: sequenced
        after its ref_seq, from other sessions."""
        return [
            c.change
            for c in self.trunk
            if c.seq > commit.ref_seq and c.session != commit.session
        ]

    def integrate_remote(self, change: Change, session: Any, seq: int,
                         ref_seq: int) -> Change:
        """A sequenced commit from another session: rebase it into
        trunk coordinates, append to the trunk, and integrate via the
        INVERT-SANDWICH (the reference's SharedTreeBranch.rebaseOnto,
        shared-tree-core/branch.ts:50): unwind the optimistic local
        branch, apply the remote against sequenced state, then
        re-apply each local commit rebased over it. The sandwich —
        not a forward transform of the remote over the local branch —
        is what keeps state-dependent conflict resolutions (e.g. the
        move cycle guard) identical on every replica: each rebased
        change applies against the same sequenced-prefix state
        everywhere. Returns the trunk-coords change."""
        import copy as _copy

        commit = Commit(change=change, session=session, seq=seq, ref_seq=ref_seq)
        window = self._concurrent_window(commit)
        rebased = rebase_change(change, [op for ch in window for op in ch])
        commit.change = rebased
        self.trunk.append(commit)
        self.trunk_seq = seq
        from .changeset import invert

        for c in reversed(self.local):
            self.forest.apply(invert(c.change))
        applied = _copy.deepcopy(rebased)
        self.forest.apply(applied)
        commit.change = applied  # trunk keeps the capture-enriched form
        carry = applied
        for c in self.local:
            old = c.change
            c.change = rebase_change(old, carry, over_first=True)
            carry = rebase_change(carry, old, over_first=False)
            self.forest.apply(c.change)
        return applied

    def ack_local(self, seq: int) -> Commit:
        """Our oldest local commit was sequenced: it becomes the trunk
        head. Its change is already in trunk coordinates — the local
        branch was rebased over every interleaved remote commit."""
        assert self.local, "ack with empty local branch"
        commit = self.local.pop(0)
        commit.seq = seq
        commit.ref_seq = self.trunk_seq
        self.trunk.append(commit)
        self.trunk_seq = seq
        return commit

    # ------------------------------------------------------------ windows

    def evict_below(self, min_seq: int) -> int:
        """Drop trunk commits at/below the MSN (no future commit can
        reference past them — the trunk-eviction of editManager.ts)."""
        before = len(self.trunk)
        self.trunk = [c for c in self.trunk if c.seq > min_seq]
        # Watermark for consumers that rebase against trunk history
        # (branches refuse to rebase across an evicted window).
        self.evicted_seq = max(getattr(self, "evicted_seq", 0), min_seq)
        return before - len(self.trunk)
