"""Tree schema + typed editable views.

The reference's schema system + editable-tree proxy API
(packages/dds/tree/src/feature-libraries/{modular-schema,
editable-tree}/, src/core/schema-stored/): node types declare their
fields with KINDS, documents validate against the schema, and edits go
through typed node views instead of raw paths.

Field kinds (the reference's FieldKinds):
- "value":    exactly one child (or a leaf primitive value)
- "optional": zero or one child
- "sequence": any number of children

`TreeSchema` is stored data (rides the SharedTree summary); views are
ephemeral proxies resolving paths lazily so they stay valid as
siblings shift (the editable-tree anchor behavior, simplified to
re-resolution by index).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from .changeset import insert_op, remove_op, set_value_op


class FieldSchema:
    def __init__(self, kind: str, types: Optional[List[str]] = None):
        assert kind in ("value", "optional", "sequence"), kind
        self.kind = kind
        self.types = types  # allowed child node types (None = any)

    def to_json(self) -> dict:
        return {"kind": self.kind, "types": self.types}

    @staticmethod
    def from_json(data: dict) -> "FieldSchema":
        return FieldSchema(data["kind"], data.get("types"))


class NodeSchema:
    def __init__(self, name: str, fields: Optional[Dict[str, FieldSchema]] = None,
                 leaf: bool = False):
        self.name = name
        self.fields = fields or {}
        self.leaf = leaf  # leaf nodes carry a value, no fields

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "leaf": self.leaf,
            "fields": {k: f.to_json() for k, f in self.fields.items()},
        }

    @staticmethod
    def from_json(data: dict) -> "NodeSchema":
        return NodeSchema(
            data["name"],
            {k: FieldSchema.from_json(f) for k, f in data["fields"].items()},
            data.get("leaf", False),
        )


class TreeSchema:
    """A document schema: named node types + the root field."""

    def __init__(self, nodes: Optional[Dict[str, NodeSchema]] = None,
                 root: Optional[FieldSchema] = None):
        self.nodes = nodes or {}
        self.root = root or FieldSchema("sequence")

    def define(self, name: str, **fields: FieldSchema) -> NodeSchema:
        ns = NodeSchema(name, dict(fields))
        self.nodes[name] = ns
        return ns

    def define_leaf(self, name: str) -> NodeSchema:
        ns = NodeSchema(name, leaf=True)
        self.nodes[name] = ns
        return ns

    # -------------------------------------------------------- validation

    def validate_node(self, node: dict, errors: List[str], where: str) -> None:
        t = node.get("type")
        if t is None:
            return  # untyped nodes permitted only by untyped fields
        ns = self.nodes.get(t)
        if ns is None:
            errors.append(f"{where}: unknown node type {t!r}")
            return
        fields = node.get("fields", {})
        if ns.leaf and fields:
            errors.append(f"{where}: leaf type {t!r} has fields")
        for fname, children in fields.items():
            fs = ns.fields.get(fname)
            if fs is None:
                errors.append(f"{where}: field {fname!r} not in schema of {t!r}")
                continue
            n = len(children)
            if fs.kind == "value" and n != 1:
                errors.append(f"{where}.{fname}: value field has {n} children")
            if fs.kind == "optional" and n > 1:
                errors.append(f"{where}.{fname}: optional field has {n} children")
            for i, child in enumerate(children):
                if fs.types is not None and child.get("type") not in fs.types:
                    errors.append(
                        f"{where}.{fname}[{i}]: type {child.get('type')!r} "
                        f"not allowed (want {fs.types})"
                    )
                self.validate_node(child, errors, f"{where}.{fname}[{i}]")
        for fname, fs in ns.fields.items():
            if fs.kind == "value" and fname not in fields:
                errors.append(f"{where}: missing value field {fname!r} of {t!r}")

    def validate(self, root: dict) -> List[str]:
        """Errors for a whole document (root's synthetic node)."""
        errors: List[str] = []
        for i, child in enumerate(root.get("fields", {}).get("root", [])):
            if self.root.types is not None and child.get("type") not in self.root.types:
                errors.append(f"root[{i}]: type {child.get('type')!r} not allowed")
            self.validate_node(child, errors, f"root[{i}]")
        return errors

    # ----------------------------------------------------------- storage

    def to_json(self) -> dict:
        return {
            "nodes": {k: n.to_json() for k, n in self.nodes.items()},
            "root": self.root.to_json(),
        }

    @staticmethod
    def from_json(data: dict) -> "TreeSchema":
        return TreeSchema(
            {k: NodeSchema.from_json(n) for k, n in data["nodes"].items()},
            FieldSchema.from_json(data["root"]),
        )


# --------------------------------------------------------------------------
# typed editable views (editable-tree proxies)
# --------------------------------------------------------------------------


class NodeView:
    """Proxy for one node: field access returns child views; edits
    submit schema-checked changes through the owning SharedTree."""

    def __init__(self, tree, path: List[list]):
        self._tree = tree
        self._path = path

    def _node(self) -> dict:
        node = self._tree.forest.node_at(self._path)
        if node is None:
            raise KeyError(f"no node at {self._path}")
        return node

    @property
    def type(self) -> Optional[str]:
        return self._node().get("type")

    @property
    def value(self) -> Any:
        return self._node().get("value")

    def set_value(self, value: Any) -> None:
        self._tree.edit([set_value_op(self._path, value)])

    def field(self, name: str) -> "FieldView":
        return FieldView(self._tree, self._path, name)

    def __getitem__(self, name: str) -> "FieldView":
        return self.field(name)

    def __getattr__(self, name: str) -> "FieldView":
        # Attribute-style field access (editable-tree proxy idiom:
        # node.title instead of node["title"]). Underscored names are
        # real attributes.
        if name.startswith("_"):
            raise AttributeError(name)
        return self.field(name)


class FieldView:
    """Proxy for one field of a node (sequence/value/optional)."""

    def __init__(self, tree, parent_path: List[list], name: str):
        self._tree = tree
        self._parent = parent_path
        self._name = name

    def __len__(self) -> int:
        node = self._tree.forest.node_at(self._parent)
        if node is None:
            raise KeyError(f"no node at {self._parent}")
        kids = node.get("fields", {}).get(self._name, [])
        return len(kids)  # list OR ChunkedField (both sized)

    def node(self, index: int) -> NodeView:
        return NodeView(self._tree, self._parent + [[self._name, index]])

    def __getitem__(self, index: int) -> NodeView:
        return self.node(index)

    def insert(self, index: int, content: List[dict]) -> None:
        self._tree.schema_check_insert(self._parent, self._name, content)
        self._tree.edit([insert_op(self._parent, self._name, index, content)])

    def append(self, content: List[dict]) -> None:
        self.insert(len(self), content)

    def remove(self, index: int, count: int = 1) -> None:
        self._tree.edit([remove_op(self._parent, self._name, index, count)])

    def move_to(self, index: int, count: int, dst: "FieldView",
                dst_index: int) -> None:
        """Move children into another field (cross-field move through
        the proxy — reference editable-tree move editing)."""
        from .changeset import move_op

        self._tree.edit([
            move_op(self._parent, self._name, index, count,
                    dst._parent, dst._name, dst_index)
        ])

    def __iter__(self):
        for i in range(len(self)):
            yield self.node(i)

    def values(self) -> list:
        """Bulk child-value read (columnar on a chunked forest)."""
        forest = self._tree.forest
        if hasattr(forest, "column"):
            return list(forest.column(self._parent, self._name))
        return [n.value for n in self]
