"""SharedTree DDS: the channel binding for the rebase-based tree.

The role of reference `SharedTreeCore`/`SharedTree`
(packages/dds/tree/src/shared-tree-core/sharedTreeCore.ts:93,
shared-tree/sharedTree.ts:211): local edits apply optimistically and
ride the op stream as commits {change, refTrunkSeq}; incoming
sequenced commits integrate through the EditManager; reconnect
resubmits pending commits rebased to the current trunk (their changes
are maintained in up-to-date coordinates by the local-branch rebase,
so resubmission is direct).

Public editing API (the editable-tree role, simplified to explicit
calls): `insert_node`, `remove_node`, `set_value`, plus `view()` for
the current JSON tree and `generate_id()` via the id-compressor.
"""

from __future__ import annotations

import contextlib
import copy
import json
from typing import Any, List, Optional

from ..protocol.messages import SequencedMessage
from ..runtime.channel import ChannelFactory, ChannelStorage
from ..runtime.shared_object import SharedObject
from ..runtime.summary import SummaryTreeBuilder
from .changeset import Change, insert_op, move_op, remove_op, set_value_op
from .edit_manager import EditManager
from .forest import Forest
from .id_compressor import IdCompressor


class SharedTree(SharedObject):
    def initialize_local_core(self) -> None:
        self.forest = Forest()
        self.edits = EditManager(self.forest, session=None)
        self.id_compressor = IdCompressor(session_id=f"detached-{id(self)}")
        self.schema = None  # TreeSchema; rides ops + summary
        self._tx_branch = None  # open-transaction fork (see transaction API)
        self._tx_id_count = 0  # ids allocated inside the open transaction

    def on_connected(self) -> None:
        cid = self.runtime.client_id
        self.edits.session = cid
        self.id_compressor.session_id = str(cid)

    # ------------------------------------------------------------ editing

    def view(self) -> dict:
        if self._tx_branch is not None:
            return self._tx_branch.view()  # uncommitted transaction view
        return self.forest.to_json()

    def use_chunked_forest(self) -> None:
        """Swap this replica's storage to the chunked forest (columnar
        uniform chunks; chunked_forest.py). Storage-only: wire format,
        rebase, and views are unchanged, so replicas mix freely."""
        from .chunked_forest import ChunkedForest

        self.forest = ChunkedForest(self.forest.to_json())
        self.edits.forest = self.forest

    def generate_id(self) -> int:
        return self.id_compressor.generate_compressed_id()

    def _commit(self, change: Change, id_count: int = 0) -> None:
        """Apply locally + submit (SharedTreeCore.submitCommit)."""
        if self._tx_branch is not None:
            # An open transaction captures all edits; nothing rides
            # the wire until commit_transaction squashes and lands it.
            # id allocations accumulate so the squashed commit carries
            # the transaction's full idCount.
            self._tx_branch.edit(change)
            self._tx_id_count += id_count
            return
        self.forest.apply(change)
        if self.edits.session is None or self.services is None:
            # Detached: edits fold straight into the base forest.
            return
        commit = self.edits.add_local(change)
        self.submit_local_message(
            {
                "change": copy.deepcopy(change),
                "refTrunkSeq": commit.ref_seq,
                "idCount": id_count,
            },
            commit,
        )
        # The applied change carries its repair data (removed content,
        # prior values, move inverses) — the undo stack's capture hook.
        # (Empty id-carrier commits have nothing to undo.)
        if change:
            self.emit("localCommit", commit)

    def insert_node(self, path: List[list], field: str, index: int,
                    content: List[dict], id_count: int = 0) -> None:
        self._commit([insert_op(path, field, index, content)], id_count)

    def remove_node(self, path: List[list], field: str, index: int,
                    count: int = 1) -> None:
        self._commit([remove_op(path, field, index, count)])

    def set_value(self, path: List[list], value: Any) -> None:
        self._commit([set_value_op(path, value)])

    def move_node(self, path: List[list], field: str, index: int,
                  count: int, dst_path: List[list], dst_field: str,
                  dst_index: int) -> None:
        """Move nodes across arbitrary fields/parents (the reference's
        cross-field move, sequence-field moveOut/moveIn pairs composed
        through the move-effect table)."""
        self._commit([
            move_op(path, field, index, count, dst_path, dst_field,
                    dst_index)
        ])

    def edit(self, change: Change, id_count: int = 0) -> None:
        """Submit a multi-op changeset as one atomic commit."""
        self._commit(change, id_count)

    # ------------------------------------------------------ schema / views

    def set_schema(self, schema) -> None:
        """Install a document schema on every replica (the reference
        stores schema as shared data edited through schema changes —
        feature-libraries/modular-schema)."""
        self.schema = schema
        if self.edits.session is not None and self.services is not None:
            self.submit_local_message(
                {"schemaChange": schema.to_json()}, None
            )

    def schema_check_insert(self, parent_path, field, content) -> None:
        """Validate an insert against BOTH the inserted nodes' own
        schema and the target field's schema (allowed types, field
        existence, cardinality)."""
        if self.schema is None:
            return
        errors = []
        # Target-field checks.
        if not parent_path:
            fs = self.schema.root if field == "root" else None
        else:
            parent = self.forest.node_at(parent_path)
            ptype = (parent or {}).get("type")
            ns = self.schema.nodes.get(ptype) if ptype else None
            fs = ns.fields.get(field) if ns else None
            if ns is not None and fs is None:
                errors.append(
                    f"field {field!r} not in schema of {ptype!r}"
                )
        if fs is not None:
            for i, node in enumerate(content):
                if fs.types is not None and node.get("type") not in fs.types:
                    errors.append(
                        f"insert[{i}]: type {node.get('type')!r} not "
                        f"allowed in field {field!r} (want {fs.types})"
                    )
            if fs.kind in ("value", "optional"):
                parent = self.forest.node_at(parent_path) if parent_path else self.forest.root
                existing = len((parent or {}).get("fields", {}).get(field, []))
                limit = 1
                if existing + len(content) > limit:
                    errors.append(
                        f"field {field!r} ({fs.kind}) would hold "
                        f"{existing + len(content)} children"
                    )
        # Inserted-subtree checks.
        for i, node in enumerate(content):
            self.schema.validate_node(node, errors, f"insert[{i}]")
        if errors:
            raise ValueError("schema violation: " + "; ".join(errors))

    def validate(self):
        """Whole-document schema check; returns a list of errors."""
        if self.schema is None:
            return []
        return self.schema.validate(self.forest.root)

    def node(self, path):
        """Typed editable view of a node (editable-tree proxy)."""
        from .schema import NodeView

        return NodeView(self, list(path))

    def root_field(self, name: str):
        from .schema import FieldView

        return FieldView(self, [], name)

    def branch(self):
        """Fork an isolated branch (shared-tree-core/branch.ts:50)."""
        from .branch import SharedTreeBranch

        return SharedTreeBranch(self)

    # ------------------------------------------------------- transactions

    @property
    def in_transaction(self) -> bool:
        return self._tx_branch is not None

    def start_transaction(self) -> None:
        """Open a (nestable) transaction on the tree's main view
        (sharedTree.ts transaction API over branch.ts:95): edits
        accumulate on an internal fork; `commit_transaction` lands
        them as ONE atomic squashed wire commit; `abort_transaction`
        unwinds them via repair data. `view()` shows the in-progress
        transaction state."""
        if self._tx_branch is None:
            self._tx_branch = self.branch()
            self._tx_id_count = 0
        self._tx_branch.start_transaction()

    def commit_transaction(self) -> None:
        assert self._tx_branch is not None, "no open transaction"
        self._tx_branch.commit_transaction()
        if not self._tx_branch.in_transaction:
            branch, self._tx_branch = self._tx_branch, None
            try:
                # Squash left at most one commit; rebase it over
                # anything integrated mid-transaction.
                branch.rebase_onto()
            except BaseException:
                # Nothing was submitted yet: keep the transaction
                # open so the caller can retry later or abort
                # explicitly. (Only the rebase is inside the retry
                # window — once landing starts, commits are on the
                # wire and replaying them would double-apply.)
                self._tx_branch = branch
                branch._tx_marks.append(0)
                raise
            if any(branch.commits):
                branch.land(self._tx_id_count)
            else:
                # Squashed to nothing: the id allocation must still
                # ride the wire (same invariant as abort_transaction).
                branch.commits = []
                branch.merged = True
                if self._tx_id_count:
                    self.edit([], self._tx_id_count)
            self._tx_id_count = 0

    def abort_transaction(self) -> None:
        assert self._tx_branch is not None, "no open transaction"
        self._tx_branch.abort_transaction()
        if not self._tx_branch.in_transaction:
            self._tx_branch = None  # view falls back to the main forest
            if self._tx_id_count:
                # ids allocated inside the aborted transaction HAVE
                # advanced this session's local ordinal space — the
                # allocation must still ride the wire (as an empty
                # commit) or every replica's finalized count desyncs
                # from the author's and all later stable ids shift.
                self.edit([], self._tx_id_count)
            self._tx_id_count = 0

    @contextlib.contextmanager
    def transaction(self):
        """Context manager: commit on success, abort on exception."""
        self.start_transaction()
        try:
            yield self
        except BaseException:
            self.abort_transaction()
            raise
        else:
            self.commit_transaction()

    # ------------------------------------------------------------ inbound

    def process_core(self, msg: SequencedMessage, local: bool, local_metadata: Any) -> None:
        op = msg.contents
        if "schemaChange" in op:
            # Schema edits are not tree commits: they don't enter the
            # EditManager; last-writer-wins in SEQUENCE order — which
            # means the local echo must re-apply too (a concurrent
            # remote schema may have overwritten ours in between; our
            # sequenced-later op wins on every replica including us).
            from .schema import TreeSchema

            self.schema = TreeSchema.from_json(op["schemaChange"])
            self.emit("schemaChanged", local)
            return
        if local:
            commit = self.edits.ack_local(msg.sequence_number)
            if op.get("idCount"):
                self.id_compressor.finalize_range(
                    str(msg.client_id), op["idCount"]
                )
        else:
            self.edits.integrate_remote(
                op["change"], msg.client_id, msg.sequence_number,
                op["refTrunkSeq"],
            )
            if op.get("idCount"):
                self.id_compressor.finalize_range(
                    str(msg.client_id), op["idCount"]
                )
            self.emit("treeChanged", False)
        self.edits.evict_below(msg.minimum_sequence_number)

    def resubmit(self, content: Any, local_metadata: Any) -> None:
        """Reconnect: the local branch is already maintained in
        current-trunk coordinates by integrate_remote, so the pending
        commit resubmits with its change as now rebased."""
        if isinstance(content, dict) and "schemaChange" in content:
            self.submit_local_message(content, None)
            return
        commit = local_metadata
        if commit is None or all(c is not commit for c in self.edits.local):
            return  # sequenced during catch-up
        commit.ref_seq = self.edits.trunk_seq
        self.submit_local_message(
            {
                "change": copy.deepcopy(commit.change),
                "refTrunkSeq": commit.ref_seq,
                "idCount": content.get("idCount", 0),
            },
            commit,
        )

    def apply_stashed_op(self, content: Any) -> Any:
        if isinstance(content, dict) and "schemaChange" in content:
            from .schema import TreeSchema

            self.schema = TreeSchema.from_json(content["schemaChange"])
            self.submit_local_message(content, None)
            return None
        self._commit(content["change"], content.get("idCount", 0))
        return None

    # ---------------------------------------------------------- summaries

    def summarize_core(self):
        """Forest snapshot + trunk tail (commits above the MSN, still
        rebase-relevant) + id-compressor state (the reference's
        summary shape: forest + EditManager + idCompressor)."""
        return (
            SummaryTreeBuilder()
            .add_json_blob(
                "header",
                {
                    "trunkSeq": self.edits.trunk_seq,
                    "trunk": [
                        {
                            "change": c.change,
                            "session": c.session,
                            "seq": c.seq,
                            "refSeq": c.ref_seq,
                        }
                        for c in self.edits.trunk
                    ],
                },
            )
            .add_json_blob("forest", self.forest.to_json())
            .add_json_blob("idCompressor", self.id_compressor.serialize())
            .add_json_blob(
                "schema",
                self.schema.to_json() if self.schema is not None else None,
            )
            .summary
        )

    def load_core(self, storage: ChannelStorage) -> None:
        self.initialize_local_core()
        header = json.loads(storage.read("header"))
        self.forest.root = json.loads(storage.read("forest"))
        self.edits.trunk_seq = header["trunkSeq"]
        from .edit_manager import Commit

        self.edits.trunk = [
            Commit(
                change=c["change"], session=c["session"], seq=c["seq"],
                ref_seq=c["refSeq"],
            )
            for c in header["trunk"]
        ]
        self.id_compressor = IdCompressor.deserialize(
            json.loads(storage.read("idCompressor")),
            session_id=self.id_compressor.session_id,
        )
        if storage.contains("schema"):
            schema_json = json.loads(storage.read("schema"))
            if schema_json is not None:
                from .schema import TreeSchema

                self.schema = TreeSchema.from_json(schema_json)


class SharedTreeFactory(ChannelFactory):
    type_name = "https://graph.microsoft.com/types/tree"
    channel_class = SharedTree
