"""SharedTree: the rebase-based JSON document CRDT.

TPU-native re-design of the reference's new SharedTree
(packages/dds/tree, SURVEY.md §2.1): a forest of typed/valued nodes
edited through *changesets* that compose, invert, and rebase
(core/rebase/changeRebaser.ts laws); an EditManager
(core/edit-manager/editManager.ts:47) maintaining the trunk of
sequenced commits and rebasing concurrent edits into it; an
IdCompressor (id-compressor/idCompressor.ts:272) translating
session-local ids to compact final ids; and the SharedTree DDS
(shared-tree/sharedTree.ts:211) binding it all behind the channel seam.

Unlike the merge-tree family (tombstone CRDT), convergence here comes
from *operational transformation of changesets onto the total order*:
every replica rebases each incoming commit over the concurrent trunk
commits it had not seen, deterministically.
"""

from .branch import SharedTreeBranch
from .changeset import (
    compose,
    insert_op,
    move_op,
    invert,
    rebase_change,
    remove_op,
    set_value_op,
)
from .forest import Forest
from .edit_manager import Commit, EditManager
from .id_compressor import IdCompressor
from .rebase_kernel import rebase_batch, rebase_ops_columnar
from .schema import FieldSchema, NodeSchema, TreeSchema
from .shared_tree import SharedTree, SharedTreeFactory

__all__ = [
    "Commit",
    "EditManager",
    "FieldSchema",
    "Forest",
    "IdCompressor",
    "NodeSchema",
    "SharedTree",
    "SharedTreeBranch",
    "SharedTreeFactory",
    "TreeSchema",
    "rebase_batch",
    "rebase_ops_columnar",
    "compose",
    "insert_op",
    "invert",
    "rebase_change",
    "remove_op",
    "set_value_op",
]
