"""Forest: the tree state store.

The role of the reference's core/forest + object-forest
(packages/dds/tree/src/feature-libraries/object-forest): holds the
document tree and applies changesets. Nodes are plain dicts:

    {"type": str?, "value": any?, "fields": {name: [child, ...]}}

`apply` mutates the forest AND enriches the applied ops in place with
the data invert needs (removed content, prior values) — the reference
captures the same via repair data.
"""

from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional

from .changeset import Change


def make_node(node_type: Optional[str] = None, value: Any = None,
              fields: Optional[dict] = None) -> dict:
    out: dict = {}
    if node_type is not None:
        out["type"] = node_type
    if value is not None:
        out["value"] = value
    out["fields"] = dict(fields or {})
    return out


class Forest:
    def __init__(self, root: Optional[dict] = None):
        self.root = root if root is not None else make_node("root")

    # ---------------------------------------------------------- navigation

    def node_at(self, path: List[list]) -> Optional[dict]:
        node = self.root
        for field, index in path:
            children = node.get("fields", {}).get(field)
            if children is None or not (0 <= index < len(children)):
                return None
            node = children[index]
        return node

    def _field(self, path: List[list], field: str) -> Optional[list]:
        node = self.node_at(path)
        if node is None:
            return None
        return node.setdefault("fields", {}).setdefault(field, [])

    # -------------------------------------------------------------- apply

    def apply(self, change: Change) -> None:
        """Apply ops in order; ops are enriched in place: removes gain
        "content", setValues gain "prev" (for invert)."""
        for op in change:
            t = op["type"]
            if t == "insert":
                children = self._field(op["path"], op["field"])
                if children is None:
                    continue  # muted: target vanished (shouldn't happen post-rebase)
                index = min(op["index"], len(children))
                children[index:index] = copy.deepcopy(op["content"])
            elif t == "remove":
                children = self._field(op["path"], op["field"])
                if children is None:
                    continue
                index = op["index"]
                end = min(index + op["count"], len(children))
                op["content"] = copy.deepcopy(children[index:end])
                del children[index:end]
            elif t == "setValue":
                node = self.node_at(op["path"])
                if node is None:
                    continue
                op["prev"] = node.get("value")
                if op["value"] is None:
                    node.pop("value", None)
                else:
                    node["value"] = op["value"]

    # ------------------------------------------------------------- export

    def to_json(self) -> dict:
        return copy.deepcopy(self.root)

    def clone(self) -> "Forest":
        return Forest(copy.deepcopy(self.root))

    def node_count(self) -> int:
        def count(node: dict) -> int:
            return 1 + sum(
                count(c) for cs in node.get("fields", {}).values() for c in cs
            )

        return count(self.root)
