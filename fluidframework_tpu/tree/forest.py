"""Forest: the tree state store.

The role of the reference's core/forest + object-forest
(packages/dds/tree/src/feature-libraries/object-forest): holds the
document tree and applies changesets. Nodes are plain dicts:

    {"type": str?, "value": any?, "fields": {name: [child, ...]}}

`apply` mutates the forest AND enriches the applied ops in place with
the data invert needs (removed content, prior values) — the reference
captures the same via repair data.
"""

from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional

from .changeset import Change


def make_node(node_type: Optional[str] = None, value: Any = None,
              fields: Optional[dict] = None) -> dict:
    out: dict = {}
    if node_type is not None:
        out["type"] = node_type
    if value is not None:
        out["value"] = value
    out["fields"] = dict(fields or {})
    return out


class FieldOps:
    """Uniform mutation surface over a field container (plain list or
    chunked_forest.ChunkedField) for the shared move application."""

    def __init__(self, container, size, take, put):
        self.container = container
        self.size = size
        self.take = take  # (i, n) -> detached node list
        self.put = put  # (i, nodes) -> None


def apply_move_op(op: dict, resolve) -> None:
    """Shared move application: detach-then-attach with the pre-op ->
    post-detach coordinate conversion, the rebase-created-cycle guard
    (destination under a moved node => deterministic no-op), and exact
    inverse recording. `resolve(path, field)` returns a FieldOps or
    None; storage-specific forests supply it (Forest, ChunkedForest —
    ONE copy of the trickiest apply logic)."""
    src = resolve(op["path"], op["field"])
    if src is None:
        op["muted"] = True
        return
    i = min(op["index"], src.size())
    end = min(i + op["count"], src.size())
    n = max(end - i, 0)
    nodes = src.take(i, n)
    dpath = [list(s) for s in op["dst_path"]]
    plen = len(op["path"])
    if (len(dpath) > plen
            and dpath[:plen] == [list(s) for s in op["path"]]
            and dpath[plen][0] == op["field"]):
        k = dpath[plen][1]
        if i <= k < i + n:
            src.put(i, nodes)  # destination under a moved node: cycle
            op["muted"] = True
            return
        if k >= i + n:
            dpath[plen][1] = k - n
    dst = resolve(dpath, op["dst_field"])
    if dst is None:
        src.put(i, nodes)  # restore: no-op move
        op["muted"] = True
        return
    j = op["dst_index"]
    same = dst.container is src.container
    if same:
        j = j - n if j >= i + n else (i if j > i else j)
    j = min(max(j, 0), dst.size())
    dst.put(j, nodes)
    op["muted"] = False
    op["count"] = n
    inv_dst = i if (not same or i <= j) else i + n
    op["inverse"] = {
        "type": "move",
        "path": dpath, "field": op["dst_field"], "index": j, "count": n,
        "dst_path": [list(s) for s in op["path"]],
        "dst_field": op["field"], "dst_index": inv_dst,
    }


def canon_json(node: dict) -> dict:
    """Canonical JSON form of a node: empty field lists pruned; field
    containers may be plain lists or chunked (anything exposing
    to_nodes()). Values are DEEP-COPIED — snapshots must be isolated
    from the live tree (mutating a view must never corrupt replica
    state)."""
    out = {k: copy.deepcopy(v) for k, v in node.items() if k != "fields"}
    fields = {}
    for f, cs in node.get("fields", {}).items():
        kids = cs.to_nodes() if hasattr(cs, "to_nodes") else cs
        if kids:
            fields[f] = [canon_json(c) for c in kids]
    if fields:
        out["fields"] = fields
    return out


class Forest:
    def __init__(self, root: Optional[dict] = None):
        self.root = root if root is not None else make_node("root")

    # ---------------------------------------------------------- navigation

    def node_at(self, path: List[list]) -> Optional[dict]:
        node = self.root
        for field, index in path:
            children = node.get("fields", {}).get(field)
            if children is None or not (0 <= index < len(children)):
                return None
            node = children[index]
        return node

    def _field(self, path: List[list], field: str) -> Optional[list]:
        node = self.node_at(path)
        if node is None:
            return None
        return node.setdefault("fields", {}).setdefault(field, [])

    # -------------------------------------------------------------- apply

    def apply(self, change: Change) -> None:
        """Apply ops in order; ops are enriched in place: removes gain
        "content", setValues gain "prev" (for invert)."""
        for op in change:
            t = op["type"]
            if t == "insert":
                children = self._field(op["path"], op["field"])
                if children is None:
                    continue  # muted: target vanished (shouldn't happen post-rebase)
                index = min(op["index"], len(children))
                children[index:index] = copy.deepcopy(op["content"])
            elif t == "remove":
                children = self._field(op["path"], op["field"])
                if children is None:
                    continue
                index = op["index"]
                end = min(index + op["count"], len(children))
                op["content"] = copy.deepcopy(children[index:end])
                del children[index:end]
            elif t == "setValue":
                node = self.node_at(op["path"])
                if node is None:
                    continue
                op["prev"] = node.get("value")
                if op["value"] is None:
                    node.pop("value", None)
                else:
                    node["value"] = op["value"]
            elif t == "move":
                # Shared detach-then-attach application (cycle guard,
                # pre-op frame conversion, inverse recording).
                apply_move_op(op, self._resolve_field_ops)

    # ------------------------------------------------------------- export

    def _resolve_field_ops(self, path, field) -> Optional[FieldOps]:
        children = self._field(path, field)
        if children is None:
            return None

        def take(i, n):
            nodes = children[i:i + n]
            del children[i:i + n]
            return nodes

        def put(i, nodes):
            children[i:i] = nodes

        return FieldOps(children, lambda: len(children), take, put)

    def to_json(self) -> dict:
        """Canonical JSON form: empty field lists are pruned (an empty
        field is semantically absent; transient empties appear when
        unwound/muted moves materialize a destination field)."""
        return canon_json(self.root)

    def clone(self) -> "Forest":
        return Forest(copy.deepcopy(self.root))

    def node_count(self) -> int:
        def count(node: dict) -> int:
            return 1 + sum(
                count(c) for cs in node.get("fields", {}).values() for c in cs
            )

        return count(self.root)
