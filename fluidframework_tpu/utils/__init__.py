"""Shared utilities (the reference's common/lib/common-utils role)."""

from .events import EventEmitter

__all__ = ["EventEmitter"]
