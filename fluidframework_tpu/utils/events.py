"""Minimal synchronous event emitter.

The role of the reference's TypedEventEmitter
(common/lib/common-utils/src/typedEventEmitter.ts): listener
registration + synchronous dispatch, shared by DDSes, runtimes, and
services.
"""

from __future__ import annotations

from typing import Callable, Dict, List


class BufferedListener:
    """Mixin for connection-like objects: messages dispatched before a
    listener is assigned buffer and drain, in order, on assignment
    (the reference driver's early-op queueing,
    drivers/driver-base/src/documentDeltaConnection.ts:42).

    Subclasses call `_dispatch(msg)`; consumers assign `.listener`.
    """

    def __init__(self):
        self._listener = None
        self._backlog = []

    @property
    def listener(self):
        return self._listener

    @listener.setter
    def listener(self, fn) -> None:
        self._listener = fn
        if fn is not None:
            backlog, self._backlog = self._backlog, []
            for msg in backlog:
                fn(msg)

    def _dispatch(self, msg) -> None:
        if self._listener is None:
            self._backlog.append(msg)
        else:
            self._listener(msg)


class EventEmitter:
    def __init__(self):
        self._listeners: Dict[str, List[Callable]] = {}

    def on(self, event: str, fn: Callable) -> Callable:
        self._listeners.setdefault(event, []).append(fn)
        return fn

    def off(self, event: str, fn: Callable) -> None:
        handlers = self._listeners.get(event, [])
        if fn in handlers:
            handlers.remove(fn)

    def once(self, event: str, fn: Callable) -> Callable:
        def wrapper(*args):
            self.off(event, wrapper)
            fn(*args)

        return self.on(event, wrapper)

    def emit(self, event: str, *args) -> None:
        fns = self._listeners.get(event)
        if not fns:
            return  # no-listener fast path: zero allocations
        for fn in list(fns):
            fn(*args)
