"""Minimal synchronous event emitter.

The role of the reference's TypedEventEmitter
(common/lib/common-utils/src/typedEventEmitter.ts): listener
registration + synchronous dispatch, shared by DDSes, runtimes, and
services.
"""

from __future__ import annotations

from typing import Callable, Dict, List


class EventEmitter:
    def __init__(self):
        self._listeners: Dict[str, List[Callable]] = {}

    def on(self, event: str, fn: Callable) -> Callable:
        self._listeners.setdefault(event, []).append(fn)
        return fn

    def off(self, event: str, fn: Callable) -> None:
        handlers = self._listeners.get(event, [])
        if fn in handlers:
            handlers.remove(fn)

    def once(self, event: str, fn: Callable) -> Callable:
        def wrapper(*args):
            self.off(event, wrapper)
            fn(*args)

        return self.on(event, wrapper)

    def emit(self, event: str, *args) -> None:
        for fn in list(self._listeners.get(event, [])):
            fn(*args)
