"""Process-wide metrics: counters, gauges, fixed-bucket histograms.

The reference service emits structured per-operation metrics through
Lumberjack (server/routerlicious/packages/services-telemetry) and the
client stamps `ISequencedDocumentMessage.traces` for per-stage
latency. `utils.telemetry` mirrors the *event* side of that; this
module is the *aggregation* side: a lock-safe `MetricsRegistry` of
counters, gauges, and fixed-bucket histograms, labeled by
(role, doc, stage, ...), with Prometheus-text and JSON snapshot
encoders.

Design constraints (the observability contract of ISSUE 3):

- **Cheap** — instruments are plain attribute bumps under one lock;
  hot paths cache instrument objects at construction and record
  per-pump aggregates, never per-record work on the kernel path. A
  `set_enabled(False)` switch swaps the default registry for a no-op
  `NullRegistry` (the bench overhead guard measures against it).
- **Deterministic-safe** — metrics are observational only: nothing
  here feeds back into sequencing, so stamped output and chaos golden
  digests are unchanged with instrumentation on.
- **Per-process with explicit merge** — registries do NOT share state
  across processes; supervised children snapshot their registry into
  their heartbeat file and the supervisor folds the snapshots with
  `MetricsRegistry.merge` (counters/histograms add, gauges last-write).
"""

from __future__ import annotations

import json
import os
import threading
import time
from bisect import bisect_left
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "DEFAULT_LATENCY_BUCKETS_MS",
    "Counter",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "NullRegistry",
    "dump_snapshot_line",
    "format_report",
    "get_flight_recorder",
    "get_registry",
    "histogram_quantile",
    "histogram_stats",
    "merge_snapshots",
    "set_enabled",
    "set_flight_recorder",
    "set_registry",
    "slo_summary",
]

# Fixed latency buckets (ms): sub-millisecond ticks through 10s tails.
DEFAULT_LATENCY_BUCKETS_MS = (
    0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
    250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0,
)


def _label_key(labels: Dict[str, Any]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class Counter:
    """Monotone counter."""

    __slots__ = ("name", "labels", "value", "_lock")

    def __init__(self, name: str, labels: Dict[str, str], lock):
        self.name = name
        self.labels = labels
        self.value = 0.0
        self._lock = lock

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n


class Gauge:
    """Point-in-time value."""

    __slots__ = ("name", "labels", "value", "_lock")

    def __init__(self, name: str, labels: Dict[str, str], lock):
        self.name = name
        self.labels = labels
        self.value = 0.0
        self._lock = lock

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)


class Histogram:
    """Fixed-bucket histogram (Prometheus `le`-inclusive upper bounds
    plus an implicit +Inf overflow bucket)."""

    __slots__ = ("name", "labels", "bounds", "counts", "sum", "count",
                 "_lock")

    def __init__(self, name: str, labels: Dict[str, str],
                 bounds: Tuple[float, ...], lock):
        self.name = name
        self.labels = labels
        self.bounds = tuple(float(b) for b in bounds)
        if list(self.bounds) != sorted(set(self.bounds)):
            raise ValueError(f"histogram bounds must be sorted unique: {bounds}")
        self.counts = [0] * (len(self.bounds) + 1)
        self.sum = 0.0
        self.count = 0
        self._lock = lock

    def observe(self, v: float) -> None:
        # bisect_left: v == bound lands IN that bucket (le-inclusive).
        i = bisect_left(self.bounds, v)
        with self._lock:
            self.counts[i] += 1
            self.sum += v
            self.count += 1


class _NullInstrument:
    """Shared no-op instrument (disabled-registry mode)."""

    __slots__ = ()
    value = 0.0
    sum = 0.0
    count = 0

    def inc(self, n: float = 1.0) -> None:
        pass

    def dec(self, n: float = 1.0) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def observe(self, v: float) -> None:
        pass


_NULL_INSTRUMENT = _NullInstrument()


class NullRegistry:
    """Registry whose instruments do nothing — `set_enabled(False)`
    makes `get_registry()` return one, so instrumented components pay a
    single no-op call per record/pump."""

    namespace = "fluid"

    def counter(self, name: str, **labels) -> _NullInstrument:
        return _NULL_INSTRUMENT

    def gauge(self, name: str, **labels) -> _NullInstrument:
        return _NULL_INSTRUMENT

    def histogram(self, name: str, buckets=None, **labels) -> _NullInstrument:
        return _NULL_INSTRUMENT

    def snapshot(self) -> dict:
        return {"counters": [], "gauges": [], "histograms": []}

    def merge(self, snap: dict) -> None:
        pass

    def to_prometheus(self) -> str:
        return ""

    def reset(self) -> None:
        pass


class MetricsRegistry:
    """Lock-safe instrument registry with deterministic snapshots.

    One instance per process; instruments are create-or-return by
    (kind, name, labels) so call sites can either cache the instrument
    (hot paths) or re-look it up (cold paths)."""

    def __init__(self, namespace: str = "fluid"):
        self.namespace = namespace
        self._lock = threading.Lock()
        self._instruments: Dict[tuple, Any] = {}

    # ------------------------------------------------------ instruments

    def _get(self, kind: str, cls, name: str, labels: Dict[str, Any],
             *args):
        key = (kind, name, _label_key(labels))
        with self._lock:
            inst = self._instruments.get(key)
            if inst is None:
                for other_kind in ("counter", "gauge", "histogram"):
                    if other_kind != kind and any(
                        k[0] == other_kind and k[1] == name
                        for k in self._instruments
                    ):
                        raise ValueError(
                            f"metric {name!r} already registered as "
                            f"{other_kind}, not {kind}"
                        )
                inst = cls(name, dict(key[2]), *args, self._lock)
                self._instruments[key] = inst
            return inst

    def counter(self, name: str, **labels) -> Counter:
        return self._get("counter", Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get("gauge", Gauge, name, labels)

    def histogram(self, name: str,
                  buckets: Optional[Iterable[float]] = None,
                  **labels) -> Histogram:
        bounds = tuple(buckets) if buckets else DEFAULT_LATENCY_BUCKETS_MS
        h = self._get("histogram", Histogram, name, labels, bounds)
        if h.bounds != tuple(float(b) for b in bounds):
            raise ValueError(
                f"histogram {name!r} re-registered with different buckets"
            )
        return h

    def reset(self) -> None:
        with self._lock:
            self._instruments.clear()

    # --------------------------------------------------------- snapshot

    def snapshot(self) -> dict:
        """JSON-able, deterministic (sorted) state of every instrument.

        Histogram fields are copied UNDER the instruments' shared lock:
        counts, sum and count must come from one instant, or a
        concurrent `observe` between the field reads yields a torn
        snapshot whose explicit sum/count disagree with its buckets —
        and every downstream consumer (merge across process snapshots,
        quantile estimation, the mean column) silently inherits the
        skew."""
        counters, gauges, histograms = [], [], []
        with self._lock:
            items = sorted(self._instruments.items())
            for (kind, name, labels), inst in items:
                entry = {"name": name, "labels": dict(labels)}
                if kind == "counter":
                    counters.append({**entry, "value": inst.value})
                elif kind == "gauge":
                    gauges.append({**entry, "value": inst.value})
                else:
                    h = {
                        **entry, "buckets": list(inst.bounds),
                        "counts": list(inst.counts), "sum": inst.sum,
                        "count": inst.count,
                    }
                    histograms.append(h)
        for h in histograms:
            if h["count"] > 0:
                # Quantiles ride the snapshot (the /slo surface), but
                # they are DERIVED — merge() folds buckets/sum/count
                # and recomputes; None marks an estimate beyond the
                # last finite bucket (JSON has no Infinity).
                h["quantiles"] = {
                    q: (None if v == float("inf") else round(v, 4))
                    for q, v in (
                        ("p50", histogram_quantile(h, 0.5)),
                        ("p95", histogram_quantile(h, 0.95)),
                        ("p99", histogram_quantile(h, 0.99)),
                    )
                }
                h["mean"] = round(h["sum"] / h["count"], 4)
        return {"counters": counters, "gauges": gauges,
                "histograms": histograms}

    def merge(self, snap: dict) -> None:
        """Fold a child's `snapshot()` in: counters and histogram
        buckets ADD, gauges take the snapshot's value (children report
        disjoint label sets — e.g. role=... — so last-write is safe)."""
        for c in snap.get("counters", ()):
            self.counter(c["name"], **c["labels"]).inc(c["value"])
        for g in snap.get("gauges", ()):
            self.gauge(g["name"], **g["labels"]).set(g["value"])
        for h in snap.get("histograms", ()):
            inst = self.histogram(h["name"], buckets=h["buckets"],
                                  **h["labels"])
            with inst._lock:
                for i, n in enumerate(h["counts"]):
                    inst.counts[i] += n
                inst.sum += h["sum"]
                inst.count += h["count"]

    # ------------------------------------------------------- exposition

    @staticmethod
    def _fmt_labels(labels: Dict[str, str], extra: str = "") -> str:
        parts = []
        for k, v in sorted(labels.items()):
            esc = str(v).replace("\\", "\\\\").replace('"', '\\"')
            parts.append('%s="%s"' % (k, esc))
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    @staticmethod
    def _fmt_num(v: float) -> str:
        return str(int(v)) if float(v).is_integer() else repr(float(v))

    def to_prometheus(self) -> str:
        """Prometheus text exposition format (one scrape body)."""
        snap = self.snapshot()
        out: List[str] = []
        seen_type: set = set()

        def full(name: str) -> str:
            return f"{self.namespace}_{name}" if self.namespace else name

        for kind in ("counters", "gauges"):
            ptype = "counter" if kind == "counters" else "gauge"
            for m in snap[kind]:
                fname = full(m["name"])
                if fname not in seen_type:
                    out.append(f"# TYPE {fname} {ptype}")
                    seen_type.add(fname)
                out.append(
                    f"{fname}{self._fmt_labels(m['labels'])} "
                    f"{self._fmt_num(m['value'])}"
                )
        qlines: List[str] = []
        for m in snap["histograms"]:
            fname = full(m["name"])
            if fname not in seen_type:
                out.append(f"# TYPE {fname} histogram")
                seen_type.add(fname)
            cum = 0
            for bound, n in zip(
                list(m["buckets"]) + ["+Inf"], m["counts"]
            ):
                cum += n
                le = bound if bound == "+Inf" else self._fmt_num(bound)
                le_label = 'le="%s"' % le
                out.append(
                    f"{fname}_bucket"
                    f"{self._fmt_labels(m['labels'], le_label)} {cum}"
                )
            out.append(
                f"{fname}_sum{self._fmt_labels(m['labels'])} "
                f"{self._fmt_num(m['sum'])}"
            )
            out.append(
                f"{fname}_count{self._fmt_labels(m['labels'])} {m['count']}"
            )
            # Bucket-interpolated quantile estimates as a sibling gauge
            # family (`<name>_q{quantile=...}`) — NOT extra `<name>`
            # series, which a strict parser would reject under TYPE
            # histogram. Buffered and appended AFTER the histogram
            # loop: a metric family's samples must stay one contiguous
            # group, and a histogram name with several label sets
            # would otherwise interleave `<name>` and `<name>_q`.
            # Estimates beyond the last finite bucket are omitted
            # rather than faked.
            if m["count"] > 0:
                qname = f"{fname}_q"
                for q in (0.5, 0.95, 0.99):
                    v = histogram_quantile(m, q)
                    if v == float("inf"):
                        continue
                    if qname not in seen_type:
                        qlines.append(f"# TYPE {qname} gauge")
                        seen_type.add(qname)
                    qlabel = 'quantile="%s"' % q
                    qlines.append(
                        f"{qname}"
                        f"{self._fmt_labels(m['labels'], qlabel)}"
                        f" {self._fmt_num(round(v, 4))}"
                    )
        out.extend(qlines)
        return "\n".join(out) + ("\n" if out else "")


# ---------------------------------------------------------------------------
# default registry + enable switch
# ---------------------------------------------------------------------------

_default_registry: Any = MetricsRegistry()
_null_registry = NullRegistry()
_enabled = True


def get_registry():
    """The process's default registry (a `NullRegistry` while
    `set_enabled(False)` is in effect)."""
    return _default_registry if _enabled else _null_registry


def set_registry(registry) -> Any:
    """Swap the default registry; returns the previous one (bench
    isolation: fresh registry per measured run)."""
    global _default_registry
    old = _default_registry
    _default_registry = registry
    return old


def set_enabled(flag: bool) -> bool:
    """Toggle instrumentation process-wide. Components that cached
    instruments keep them; components constructed while disabled get
    no-ops. Returns the previous setting."""
    global _enabled
    old = _enabled
    _enabled = bool(flag)
    return old


# ---------------------------------------------------------------------------
# snapshot files + reporting (tools/metrics_report.py backend)
# ---------------------------------------------------------------------------


def dump_snapshot_line(path: str, snapshot: dict, **meta) -> None:
    """Append one JSONL line `{"t": ..., **meta, "snapshot": ...}` —
    the run-artifact form `tools/metrics_report.py` renders."""
    with open(path, "a") as f:
        f.write(json.dumps({"t": time.time(), **meta,
                            "snapshot": snapshot}) + "\n")


def merge_snapshots(snapshots: Iterable[dict]) -> MetricsRegistry:
    """Fold snapshots (or metrics.jsonl line dicts) into one registry."""
    reg = MetricsRegistry()
    for snap in snapshots:
        reg.merge(snap.get("snapshot", snap))
    return reg


def histogram_quantile(h: dict, q: float) -> float:
    """Estimate quantile `q` from a snapshot histogram entry by linear
    interpolation within its bucket; `inf` if it lands in overflow."""
    total = h["count"]
    if total <= 0:
        return 0.0
    target = q * total
    bounds = h["buckets"]
    cum = 0
    for i, n in enumerate(h["counts"]):
        if n == 0:
            continue
        if cum + n >= target:
            if i >= len(bounds):
                return float("inf")
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i]
            return lo + (hi - lo) * max(0.0, target - cum) / n
        cum += n
    return float("inf")


def histogram_stats(h: dict) -> dict:
    """The SLO-facing summary of one snapshot histogram entry:
    count, mean (exact, from the explicit sum), and bucket-interpolated
    p50/p95/p99. Quantiles landing beyond the last finite bucket come
    back as ``float("inf")`` — the caller decides how to render that
    (the JSON surfaces map it to None)."""
    count = int(h.get("count", 0))
    if count <= 0:
        return {"count": 0, "mean": 0.0, "p50": 0.0, "p95": 0.0,
                "p99": 0.0}
    return {
        "count": count,
        "mean": h["sum"] / count,
        "p50": histogram_quantile(h, 0.5),
        "p95": histogram_quantile(h, 0.95),
        "p99": histogram_quantile(h, 0.99),
    }


def slo_summary(snap: dict) -> dict:
    """The `/slo` endpoint body: every histogram with observations,
    reduced to its quantile summary (JSON-safe — beyond-last-bucket
    estimates become None). Counters/gauges are generally omitted
    (they live on `/metrics.json`) with one exception: admission
    FEEDBACK counters (``ingress_*`` — nacks by reason, throttles,
    admits) ride along under ``"counters"``, because an operator
    reading tail quantiles needs to see load the front door REFUSED
    next to the latency of the load it admitted — a clean p99 over a
    throttled stream is not a clean p99."""
    out = []
    for h in snap.get("histograms", ()):
        if not h.get("count"):
            continue
        stats = histogram_stats(h)
        out.append({
            "name": h["name"], "labels": dict(h.get("labels") or {}),
            "count": stats["count"],
            "mean": round(stats["mean"], 4),
            **{q: (None if stats[q] == float("inf")
                   else round(stats[q], 4))
               for q in ("p50", "p95", "p99")},
        })
    body: Dict[str, Any] = {"histograms": out}
    ingress = [
        {"name": c["name"], "labels": dict(c.get("labels") or {}),
         "value": c["value"]}
        for c in snap.get("counters", ())
        if str(c.get("name", "")).startswith("ingress_")
        and c.get("value")
    ]
    if ingress:
        body["counters"] = ingress
    return body


def _fmt_ms(v: float) -> str:
    if v == float("inf"):
        return ">max"
    if v >= 100:
        return f"{v:.0f}"
    return f"{v:.2f}"


def format_report(snapshots: Iterable[dict]) -> str:
    """Human table over merged snapshots: per-stage latency histograms
    (count/mean/p50/p90/p99), then counters and gauges."""
    snap = merge_snapshots(snapshots).snapshot()
    lines: List[str] = []

    def label_str(labels: dict) -> str:
        return ",".join(f"{k}={v}" for k, v in sorted(labels.items()))

    hists = [h for h in snap["histograms"] if h["count"] > 0]
    if hists:
        lines.append(
            f"{'histogram':<26} {'labels':<34} {'count':>9} "
            f"{'mean':>9} {'p50':>9} {'p95':>9} {'p99':>9}"
        )
        for h in hists:
            stats = histogram_stats(h)
            lines.append(
                f"{h['name']:<26} {label_str(h['labels']):<34} "
                f"{stats['count']:>9} {_fmt_ms(stats['mean']):>9} "
                f"{_fmt_ms(stats['p50']):>9} "
                f"{_fmt_ms(stats['p95']):>9} "
                f"{_fmt_ms(stats['p99']):>9}"
            )
    rows = [("counter", c) for c in snap["counters"] if c["value"]]
    rows += [("gauge", g) for g in snap["gauges"]]
    if rows:
        if hists:
            lines.append("")
        lines.append(f"{'kind':<8} {'metric':<30} {'labels':<34} {'value':>12}")
        for kind, m in rows:
            lines.append(
                f"{kind:<8} {m['name']:<30} {label_str(m['labels']):<34} "
                f"{MetricsRegistry._fmt_num(m['value']):>12}"
            )
    return "\n".join(lines) if lines else "(no metrics recorded)"


# ---------------------------------------------------------------------------
# slow-op flight recorder (the /traces surface)
# ---------------------------------------------------------------------------


class FlightRecorder:
    """Bounded ring buffer of SLOW-op span records.

    Histograms answer "what is p99"; they cannot answer "which ops
    were the p99" — a tail-latency regression report needs the exact
    slow ops attached (doc/client/seq plus every stage timestamp).
    This keeps the last `capacity` spans whose end-to-end latency
    either exceeds a fixed `threshold_ms` or, when none is set, the
    ROLLING p99 of the last `window` observations — so the buffer
    always holds the current tail, never a firehose.

    Two-phase API so the hot path never builds a span dict it is about
    to drop:

        if recorder.note(e2e_ms):          # updates the rolling window
            recorder.add(e2e_ms, {...})    # admit the full span

    Observational only and lock-safe; `snapshot()` returns the spans
    oldest-first, each as ``{"e2e_ms": ..., **span}``.
    """

    RECALC_EVERY = 32  # rolling-p99 refresh cadence (observations)

    def __init__(self, capacity: int = 128,
                 threshold_ms: Optional[float] = None,
                 window: int = 512, min_samples: int = 32):
        from collections import deque

        self.capacity = int(capacity)
        self.threshold_ms = threshold_ms
        self.min_samples = int(min_samples)
        self._spans = deque(maxlen=self.capacity)
        self._recent = deque(maxlen=int(window))
        self._rolling_p99 = float("inf")
        self._since_recalc = 0
        self.seen = 0
        self.recorded = 0
        self._lock = threading.Lock()

    def _refresh_p99(self) -> None:
        n = len(self._recent)
        if n < self.min_samples:
            self._rolling_p99 = float("inf")
            return
        ordered = sorted(self._recent)
        self._rolling_p99 = ordered[min(n - 1, int(0.99 * (n - 1)))]

    def note(self, e2e_ms: float) -> bool:
        """Fold one end-to-end latency into the rolling window; True
        iff the op qualifies for the buffer (the caller then builds
        the span and calls `add`)."""
        with self._lock:
            self.seen += 1
            self._recent.append(float(e2e_ms))
            self._since_recalc += 1
            if self._since_recalc >= self.RECALC_EVERY:
                self._since_recalc = 0
                self._refresh_p99()
            if self.threshold_ms is not None:
                return e2e_ms >= self.threshold_ms
            return e2e_ms >= self._rolling_p99

    def add(self, e2e_ms: float, span: Dict[str, Any]) -> None:
        with self._lock:
            self.recorded += 1
            self._spans.append({"e2e_ms": round(float(e2e_ms), 4),
                                **span})

    def observe(self, e2e_ms: float, span: Dict[str, Any]) -> bool:
        """One-shot form for cold paths: note + add when admitted."""
        if self.note(e2e_ms):
            self.add(e2e_ms, span)
            return True
        return False

    def snapshot(self) -> List[dict]:
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._recent.clear()
            self._rolling_p99 = float("inf")
            self._since_recalc = 0
            self.seen = 0
            self.recorded = 0


def _env_slow_threshold() -> Optional[float]:
    """`FLUID_TRACE_SLOW_MS`: a FIXED slow-op threshold (ms) for the
    process's default flight recorder — spans at/above it are kept
    instead of the rolling-p99 gate. The scenario/chaos harnesses set
    it ("0" = keep every span, ring-bounded) so a short run's /traces
    evidence does not depend on the rolling window having armed;
    unset (the default) keeps the adaptive production behavior."""
    v = os.environ.get("FLUID_TRACE_SLOW_MS", "")
    if not v:
        return None
    try:
        return float(v)
    except ValueError:
        return None


_default_recorder = FlightRecorder(threshold_ms=_env_slow_threshold())


def get_flight_recorder() -> FlightRecorder:
    """The process's default slow-op recorder (fed by the runtime's
    apply-side trace fold and, in wire-trace mode, the farm's
    broadcaster role; served by `monitor.MetricsServer` `/traces`)."""
    return _default_recorder


def set_flight_recorder(recorder: FlightRecorder) -> FlightRecorder:
    """Swap the default recorder; returns the previous one (bench/test
    isolation, like `set_registry`)."""
    global _default_recorder
    old = _default_recorder
    _default_recorder = recorder
    return old
