"""Version-compat seams for the narrow slice of jax API this repo
uses across jax releases.

`shard_map` moved twice upstream: old releases expose it only as
`jax.experimental.shard_map.shard_map` (replication-check kwarg
`check_rep`), newer ones promote it to `jax.shard_map` and rename the
kwarg to `check_vma`. The seed imported the promoted name on an older
runtime and every multi-chip path died on the ImportError
(tests/test_seqshard.py / tests/test_multichip.py — the one seed
capability never reproduced). `shard_map_compat` resolves whichever
spelling the installed jax provides, once, and maps the check kwarg to
the name that version understands.

jax is imported lazily so importing this module stays free for
scalar-only processes (the supervisor's rule for server modules).
"""

from __future__ import annotations

import inspect
from typing import Any, Optional

_SHARD_MAP = None  # resolved once per process
_CHECK_KWARG: Optional[str] = None


def resolve_shard_map():
    """The installed jax's `shard_map` callable plus the name of its
    replication/vma check kwarg (None when the version has neither).
    Raises ImportError only if NO known spelling exists."""
    global _SHARD_MAP, _CHECK_KWARG
    if _SHARD_MAP is None:
        try:
            from jax import shard_map as sm  # jax >= 0.6 promoted name
        except ImportError:
            from jax.experimental.shard_map import shard_map as sm
        _SHARD_MAP = sm
        params = inspect.signature(sm).parameters
        for name in ("check_vma", "check_rep"):
            if name in params:
                _CHECK_KWARG = name
                break
    return _SHARD_MAP, _CHECK_KWARG


def shard_map_compat(f, mesh, in_specs, out_specs, check: bool = False,
                     **kw: Any):
    """`shard_map(f, mesh, in_specs, out_specs)` under any supported
    jax: `check` feeds `check_vma` (new) or `check_rep` (old),
    whichever the installed version accepts."""
    sm, check_kwarg = resolve_shard_map()
    if check_kwarg is not None:
        kw.setdefault(check_kwarg, check)
    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)
