"""Telemetry: logger hierarchy, performance spans, structured metrics.

Mirrors the reference's client telemetry
(packages/utils/telemetry-utils/src/logger.ts — TelemetryLogger /
ChildLogger / PerformanceEvent / MockLogger) and the server's
Lumberjack structured-metric API
(server/routerlicious/packages/services-telemetry): one module serves
both roles, since the TPU build runs client and service in one
process tree.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional


class TelemetryLogger:
    """Base logger: `send(event)` with category/eventName properties
    (logger.ts TelemetryLogger)."""

    def __init__(self, namespace: str = "", properties: Optional[dict] = None):
        self.namespace = namespace
        self.properties = dict(properties or {})
        self._sinks: List[Callable[[dict], None]] = []

    def add_sink(self, fn: Callable[[dict], None]) -> None:
        self._sinks.append(fn)

    def send(self, event: dict) -> None:
        out = dict(self.properties)
        out.update(event)
        if self.namespace and "eventName" in out:
            out["eventName"] = f"{self.namespace}:{out['eventName']}"
        for fn in self._sinks:
            fn(out)

    # convenience categories (logger.ts sendTelemetryEvent & friends)
    def send_telemetry_event(self, name: str, **props) -> None:
        self.send({"category": "generic", "eventName": name, **props})

    def send_error_event(self, name: str, error: Any = None, **props) -> None:
        self.send(
            {"category": "error", "eventName": name, "error": repr(error), **props}
        )

    def send_performance_event(self, name: str, duration_ms: float, **props) -> None:
        self.send(
            {"category": "performance", "eventName": name,
             "durationMs": duration_ms, **props}
        )


class ChildLogger(TelemetryLogger):
    """Namespaced child forwarding to its parent (logger.ts ChildLogger)."""

    def __init__(self, parent: TelemetryLogger, namespace: str,
                 properties: Optional[dict] = None):
        full = f"{parent.namespace}:{namespace}" if parent.namespace else namespace
        super().__init__(full, {**parent.properties, **(properties or {})})
        self._parent = parent

    def send(self, event: dict) -> None:
        out = dict(self.properties)
        out.update(event)
        if "eventName" in out:
            out["eventName"] = f"{self.namespace}:{out['eventName']}"
        self._parent.send(out)  # parent applies its sinks

    @classmethod
    def create(cls, parent: TelemetryLogger, namespace: str,
               properties: Optional[dict] = None) -> "ChildLogger":
        return cls(parent, namespace, properties)


class PerformanceEvent:
    """Timed span reporting start/end/cancel (logger.ts
    PerformanceEvent). Use as a context manager."""

    def __init__(self, logger: TelemetryLogger, name: str, **props):
        self.logger = logger
        self.name = name
        self.props = props
        self._start = 0.0

    def __enter__(self) -> "PerformanceEvent":
        self._start = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        dur = (time.perf_counter() - self._start) * 1000
        if exc is None:
            self.logger.send_performance_event(self.name, dur, **self.props)
        else:
            self.logger.send_error_event(
                f"{self.name}_cancel", exc, durationMs=dur, **self.props
            )


class MockLogger(TelemetryLogger):
    """Captures events for assertions (mockLogger.ts)."""

    def __init__(self):
        super().__init__()
        self.events: List[dict] = []
        self.add_sink(self.events.append)

    def matches(self, expected: dict) -> bool:
        return any(
            all(e.get(k) == v for k, v in expected.items()) for e in self.events
        )


class Lumberjack:
    """Structured server metrics (services-telemetry): named metrics
    with properties + success/failure terminal states.

    `_sinks` is deliberately class-level (the reference Lumberjack is a
    process-global singleton), which makes sink hygiene the caller's
    job: a test that `add_sink`s and never removes leaks its sink into
    every later metric in the process. `remove_sink`/`reset` exist so
    callers can clean up; both mutate the SHARED list in place, so
    in-flight `LumberMetric`s (which hold a reference to it) see the
    change too."""

    _sinks: List[Callable[[dict], None]] = []

    @classmethod
    def add_sink(cls, fn: Callable[[dict], None]) -> None:
        cls._sinks.append(fn)

    @classmethod
    def remove_sink(cls, fn: Callable[[dict], None]) -> None:
        """Detach one sink; unknown sinks are a no-op (idempotent
        teardown)."""
        try:
            cls._sinks.remove(fn)
        except ValueError:
            pass

    @classmethod
    def reset(cls) -> None:
        """Drop every sink (test-suite teardown). In place: metrics
        created before the reset stop emitting rather than holding a
        stale sink list."""
        cls._sinks.clear()

    @classmethod
    def new_metric(cls, name: str, **props) -> "LumberMetric":
        return LumberMetric(name, props, cls._sinks)


class LumberMetric:
    def __init__(self, name: str, props: Dict[str, Any], sinks):
        self.name = name
        self.props = dict(props)
        self._sinks = sinks
        self._start = time.perf_counter()

    def set_property(self, key: str, value: Any) -> None:
        self.props[key] = value

    def _emit(self, status: str, message: str = "") -> None:
        event = {
            "metric": self.name,
            "status": status,
            "message": message,
            "durationMs": (time.perf_counter() - self._start) * 1000,
            **self.props,
        }
        for fn in self._sinks:
            fn(event)

    def success(self, message: str = "") -> None:
        self._emit("success", message)

    def error(self, message: str = "") -> None:
        self._emit("error", message)
