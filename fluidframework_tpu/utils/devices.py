"""CPU-CI device emulation: run multi-device code on hosts without a
multi-chip accelerator.

XLA's ``--xla_force_host_platform_device_count=N`` splits the host CPU
backend into N virtual devices — the project's standard way to compile
and CORRECTNESS-check mesh-sharded code (conftest.py forces 8 for the
test process; `__graft_entry__.dryrun_multichip` re-execs itself with
the flag). The flag only takes effect before the first jax import, so
anything that needs a specific count mid-process must subprocess: the
helpers here build that environment and spawn the child.

Emulation is honest about what it can measure: virtual devices
time-slicing fewer physical cores exercise correctness (bit-identity
across topologies) but NOT aggregate throughput scaling —
`parity_skip_reason` renders the loud-skip text benches and tests must
surface instead of printing a scheduler benchmark as a scaling number.

No jax import at module level: scalar processes pay nothing.
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Dict, List, Optional, Tuple

__all__ = [
    "forced_host_device_env",
    "parity_skip_reason",
    "run_forced_host_subprocess",
    "visible_devices",
]


def forced_host_device_env(n_devices: int,
                           base: Optional[Dict[str, str]] = None
                           ) -> Dict[str, str]:
    """A child-process environment with N virtual host CPU devices:
    os.environ (or `base`) with any previous force flag replaced and
    the platform pinned to cpu (the forced count exists only there)."""
    env = dict(os.environ if base is None else base)
    flags = [
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    flags.append(
        f"--xla_force_host_platform_device_count={int(n_devices)}"
    )
    env["XLA_FLAGS"] = " ".join(flags)
    env["JAX_PLATFORMS"] = "cpu"
    return env


def visible_devices() -> Tuple[str, int]:
    """(platform, count) of the default jax backend in THIS process.
    Imports jax (and initializes the backend) — call only where that
    is already paid for."""
    import jax

    devs = jax.devices()
    return (devs[0].platform if devs else "none", len(devs))


def parity_skip_reason(n_devices: int) -> Optional[str]:
    """None when aggregate throughput scaling at `n_devices` can be
    measured honestly on this host; else the loud-skip reason.

    Honest means the devices are real accelerator chips, or virtual
    host devices with at least one physical core each — N virtual
    devices time-slicing fewer cores measure the OS scheduler, not
    the sharding."""
    platform, count = visible_devices()
    if platform not in ("cpu", "none") and count >= n_devices:
        return None
    cores = os.cpu_count() or 1
    if cores >= n_devices:
        return None
    return (
        f"host has {cores} cores and no {n_devices}-device "
        f"accelerator ({count} {platform} visible): {n_devices} "
        f"forced-host devices would time-slice the cores and measure "
        f"the scheduler, not multi-device scaling"
    )


def run_forced_host_subprocess(
    code: str, n_devices: int, timeout_s: float = 900.0,
    cwd: Optional[str] = None, argv: Optional[List[str]] = None,
    env: Optional[Dict[str, str]] = None,
) -> subprocess.CompletedProcess:
    """Run ``python -c code [argv...]`` under N forced virtual host
    devices (the flag must precede the first jax import, hence the
    subprocess). Raises RuntimeError with both streams on a non-zero
    exit — a silently failed emulation child must not look like an
    empty result.

    `env` overrides the spawn environment verbatim (a caller on a
    real N-chip host wants the child un-forced but the same
    spawn/loud-failure contract); default is the forced-host env."""
    res = subprocess.run(
        [sys.executable, "-c", code] + list(argv or []),
        env=forced_host_device_env(n_devices) if env is None else env,
        capture_output=True, text=True, timeout=timeout_s, cwd=cwd,
    )
    if res.returncode != 0:
        raise RuntimeError(
            f"forced-host-device subprocess failed "
            f"(rc={res.returncode}, n_devices={n_devices})\n"
            f"stdout:\n{res.stdout}\nstderr:\n{res.stderr}"
        )
    return res
