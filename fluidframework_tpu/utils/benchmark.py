"""Statistical benchmark runner (the @fluid-tools/benchmark role).

The reference's harness (tools/benchmark/src/Runner.ts) runs each
benchmark many times and reports statistics, with a separate
memory-pressure mode (MemoryTestRunner.ts). This module provides the
same contract for the project's config benches: N timed repeats after
warm-up, mean/stddev/min/max/percentiles, and an optional memory mode
measuring per-run Python allocation peaks (tracemalloc) plus process
peak-RSS growth.
"""

from __future__ import annotations

import math
import time
from typing import Any, Callable, Dict, List, Optional


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = q * (len(sorted_vals) - 1)
    lo = int(math.floor(idx))
    hi = int(math.ceil(idx))
    if lo == hi:
        return sorted_vals[lo]
    frac = idx - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


def run_benchmark(
    fn: Callable[[], Any],
    repeats: int = 5,
    warmups: int = 1,
    memory: bool = False,
) -> Dict[str, Any]:
    """Run `fn` `warmups + repeats` times; time the repeats.

    Returns statistics over the timed runs (seconds):
    ``{"runs", "warmups", "mean", "stddev", "min", "max", "p50",
    "p90", "warm_seconds"}`` plus, with ``memory=True``,
    ``{"alloc_peak_mb_mean", "alloc_peak_mb_max", "rss_growth_mb"}``.
    """
    t0 = time.perf_counter()
    for _ in range(warmups):
        fn()
    warm_seconds = time.perf_counter() - t0

    times: List[float] = []
    rss_before = _peak_rss_mb()
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    alloc_peaks: List[float] = []
    if memory:
        # Memory is measured in a SEPARATE traced pass so tracemalloc
        # overhead never pollutes the timed runs (the reference keeps
        # Runner.ts and MemoryTestRunner.ts separate for the same
        # reason).
        import tracemalloc

        tracemalloc.start()
        fn()
        _, peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        alloc_peaks.append(peak / 1e6)
    mean = sum(times) / len(times)
    var = sum((t - mean) ** 2 for t in times) / len(times)
    srt = sorted(times)
    out: Dict[str, Any] = {
        "runs": repeats,
        "warmups": warmups,
        "mean": round(mean, 6),
        "stddev": round(math.sqrt(var), 6),
        "min": round(srt[0], 6),
        "max": round(srt[-1], 6),
        "p50": round(_percentile(srt, 0.5), 6),
        "p90": round(_percentile(srt, 0.9), 6),
        "warm_seconds": round(warm_seconds, 6),
    }
    if memory and alloc_peaks:
        out["alloc_peak_mb_mean"] = round(
            sum(alloc_peaks) / len(alloc_peaks), 3
        )
        out["alloc_peak_mb_max"] = round(max(alloc_peaks), 3)
        out["rss_growth_mb"] = round(_peak_rss_mb() - rss_before, 3)
    return out


def _peak_rss_mb() -> float:
    try:
        import resource

        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0
    except Exception:
        return 0.0
