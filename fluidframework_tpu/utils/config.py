"""Layered configuration / feature gates.

Mirrors the reference's config system
(packages/utils/telemetry-utils/src/config.ts:13,164):
`ConfigProvider` resolves typed values through an ordered provider
chain (first hit wins), and `MonitoringContext` bundles logger +
config — the pair injected at every constructor boundary.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Union

from .telemetry import TelemetryLogger

RawProvider = Union[Dict[str, Any], Callable[[str], Any]]


class ConfigProvider:
    """Ordered lookup over raw providers with typed accessors
    (CachedConfigProvider, config.ts:164)."""

    def __init__(self, providers: Optional[List[RawProvider]] = None):
        self._providers: List[Callable[[str], Any]] = []
        self._cache: Dict[str, Any] = {}
        for p in providers or []:
            self.add_provider(p)

    def add_provider(self, provider: RawProvider) -> None:
        if isinstance(provider, dict):
            self._providers.append(provider.get)
        else:
            self._providers.append(provider)
        self._cache.clear()

    def _raw(self, key: str) -> Any:
        if key in self._cache:
            return self._cache[key]
        for p in self._providers:
            try:
                value = p(key)
            except Exception:
                value = None
            if value is not None:
                self._cache[key] = value
                return value
        self._cache[key] = None
        return None

    def get_bool(self, key: str, default: Optional[bool] = None) -> Optional[bool]:
        v = self._raw(key)
        if isinstance(v, bool):
            return v
        if isinstance(v, str):
            if v.lower() in ("true", "1"):
                return True
            if v.lower() in ("false", "0"):
                return False
        return default

    def get_number(self, key: str, default: Optional[float] = None) -> Optional[float]:
        v = self._raw(key)
        if isinstance(v, bool):
            return default
        if isinstance(v, (int, float)):
            return v
        if isinstance(v, str):
            try:
                return float(v)
            except ValueError:
                return default
        return default

    def get_string(self, key: str, default: Optional[str] = None) -> Optional[str]:
        v = self._raw(key)
        return v if isinstance(v, str) else default


class MonitoringContext:
    """logger + config pair (mixinMonitoringContext, config.ts)."""

    def __init__(self, logger: Optional[TelemetryLogger] = None,
                 config: Optional[ConfigProvider] = None):
        self.logger = logger or TelemetryLogger()
        self.config = config or ConfigProvider()

    def child(self, namespace: str) -> "MonitoringContext":
        from .telemetry import ChildLogger

        return MonitoringContext(ChildLogger(self.logger, namespace), self.config)
