"""fluidframework_tpu — a TPU-native real-time collaboration framework.

A ground-up re-design of the capabilities of Fluid Framework
(reference: 16CentAstrology-Inc/FluidFramework) for TPU hardware:

- Distributed Data Structures (DDSes) with optimistic local replicas that
  converge by deterministic replay of a totally ordered op stream
  (reference: packages/dds/*).
- The merge hot path — merge-tree op application and sequence
  reconciliation (reference: packages/dds/merge-tree/src/mergeTree.ts) —
  is re-expressed as vectorized JAX/XLA kernels over a
  structure-of-arrays segment table (`fluidframework_tpu.ops`).
- A total-order sequencing service with MSN tracking (reference:
  server/routerlicious/packages/lambdas/src/deli/lambda.ts) with both a
  scalar in-proc implementation (`fluidframework_tpu.server`) and a
  batched JAX kernel that sequences thousands of documents at once.
- Runtime, summarization/checkpointing, reconnect-with-rebase, and the
  full test story (mock runtimes, seeded fuzz farms, in-proc orderer
  integration tests, replay harnesses).

This is not a port: data layouts, kernels and parallelism are designed
for XLA/TPU (SPMD over `jax.sharding.Mesh`, associative scans,
min-reductions), not translated from the reference's TypeScript.
"""

__version__ = "0.1.0"
