"""Parallel delta fetch: concurrent ranged op reads with retry.

Reference `parallelRequests`
(loader/driver-utils/src/parallelRequests.ts): a large catch-up gap is
split into ranges fetched concurrently (the service may also return
partial ranges), reassembled in order, with holes retried. Useful over
the real network boundary (drivers/socket_driver) where each request
pays a round trip; in-proc drivers resolve each range trivially.

Drivers expose `ops_from(doc_id, from_seq)`; ranged reads derive from
it (`_range`), so every driver works unchanged.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import List

from ..protocol.messages import SequencedMessage


def _range(driver, doc_id: str, lo: int, hi: int) -> List[SequencedMessage]:
    """Ops with lo < seq <= hi — server-side ranged when the driver
    supports `to_seq` (socket/local drivers do; anything else falls
    back to client-side clipping)."""
    try:
        return driver.ops_from(doc_id, lo, to_seq=hi)
    except TypeError:
        return [
            m for m in driver.ops_from(doc_id, lo) if m.sequence_number <= hi
        ]


def fetch_ops_parallel(
    driver,
    doc_id: str,
    from_seq: int,
    to_seq: int,
    chunk: int = 512,
    workers: int = 4,
    max_retries: int = 3,
) -> List[SequencedMessage]:
    """All ops with from_seq < seq <= to_seq, fetched as concurrent
    ranges and reassembled contiguously (holes retried)."""
    if to_seq <= from_seq:
        return []
    ranges = [
        (lo, min(lo + chunk, to_seq))
        for lo in range(from_seq, to_seq, chunk)
    ]
    out: List[SequencedMessage] = []
    with ThreadPoolExecutor(max_workers=workers) as pool:
        parts = list(
            pool.map(lambda r: _range(driver, doc_id, r[0], r[1]), ranges)
        )
    for (lo, hi), part in zip(ranges, parts):
        # Retry holes and transiently-empty ranges (a service may
        # serve partial results).
        tries = 0
        while tries < max_retries and (
            not part or part[-1].sequence_number < hi
        ):
            cursor = part[-1].sequence_number if part else lo
            more = _range(driver, doc_id, cursor, hi)
            if not more:
                tries += 1
                continue
            part.extend(more)
        out.extend(part)
    # Contiguity check (the reference asserts the same invariant).
    for a, b in zip(out, out[1:]):
        if b.sequence_number != a.sequence_number + 1:
            raise RuntimeError(
                f"op gap: {a.sequence_number} -> {b.sequence_number}"
            )
    return out
