"""Pausable, inspectable delta queue.

The reference DeltaQueue (loader/container-loader/src/deltaQueue.ts:15)
drains asynchronously and can pause/resume — the mechanism behind
batch-atomic processing and replay stepping. This synchronous version
keeps the same surface: push enqueues, an unpaused queue drains through
the handler, pause() holds delivery mid-stream, resume() continues.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Deque, Optional

from ..utils.events import EventEmitter


class DeltaQueue(EventEmitter):
    def __init__(self, handler: Callable[[Any], None]):
        super().__init__()
        self._handler = handler
        self._queue: Deque[Any] = deque()
        self._paused = False
        self._draining = False

    @property
    def length(self) -> int:
        return len(self._queue)

    @property
    def paused(self) -> bool:
        return self._paused

    def push(self, item: Any) -> None:
        self._queue.append(item)
        self._drain()

    def pause(self) -> None:
        self._paused = True

    def resume(self) -> None:
        self._paused = False
        self._drain()

    def process_one(self) -> bool:
        """Deliver a single item even while paused (replay stepping)."""
        if not self._queue:
            return False
        item = self._queue.popleft()
        self._handler(item)
        self.emit("op", item)
        return True

    def _drain(self) -> None:
        if self._draining:
            return
        self._draining = True
        try:
            while self._queue and not self._paused:
                self.process_one()
        finally:
            self._draining = False
        if not self._queue:
            self.emit("idle")
