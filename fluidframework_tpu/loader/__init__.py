"""Loader layer: container lifecycle over pluggable drivers.

The reference's packages/loader/container-loader role (SURVEY.md §1
L3): `Container` (load/createDetached/attach/close, container.ts:310),
pausable delta queues (deltaQueue.ts:15), `ConnectionManager`-style
auto-reconnect, `Audience` (audience.ts), and stashed-op close/resume
(closeAndGetPendingLocalState → applyStashedOp).
"""

from .container import Container, Loader
from .collab_window_tracker import CollabWindowTracker
from .connection_manager import ConnectionManager
from .delta_queue import DeltaQueue
from .parallel_fetch import fetch_ops_parallel
from .audience import Audience

__all__ = [
    "Audience", "CollabWindowTracker", "ConnectionManager", "Container",
    "DeltaQueue", "Loader", "fetch_ops_parallel",
]
