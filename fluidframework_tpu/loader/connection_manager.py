"""ConnectionManager: automatic reconnect with a backoff ladder.

Reference `ConnectionManager`
(loader/container-loader/src/connectionManager.ts:170): when the
transport drops, the loader retries the driver connection with
exponential delay until it succeeds or the retry budget is exhausted;
on success, the runtime's connect path replays pending ops (rebase +
resubmit). Here the ladder is synchronous and the sleep function is
injectable so tests run with zero wall-clock delay while still
asserting the delay schedule.
"""

from __future__ import annotations

import random
import time
from typing import Callable, List, Optional


class ConnectionManager:
    """Watches a Container's "disconnected" event and re-establishes
    the connection through the container's driver.

    Parameters mirror the reference's retry policy shape: delay
    doubles per attempt from `base_delay` up to `max_delay`
    (connectionManager.ts reconnect + driver-supplied retryAfter).
    `sleep` is injectable for tests; `delays` records the schedule
    actually used.

    `jitter` spreads the ladder by up to ±jitter·delay so a fleet of
    clients dropped by one server restart does not reconnect in
    lockstep (the thundering-herd guard). The jitter stream is seeded
    (`seed`) and private to this manager, so a given (seed, disconnect
    history) always reproduces the exact same schedule — chaos runs
    stay replayable.
    """

    def __init__(
        self,
        container,
        max_attempts: int = 8,
        base_delay: float = 0.05,
        max_delay: float = 5.0,
        sleep: Callable[[float], None] = time.sleep,
        jitter: float = 0.0,
        seed: Optional[int] = None,
    ):
        self.container = container
        self.max_attempts = max_attempts
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.sleep = sleep
        self.jitter = jitter
        self._rng = random.Random(seed)
        self.delays: List[float] = []
        self.enabled = True
        self._reconnecting = False
        container.on("disconnected", self._on_disconnected)

    def delay_for(self, attempt: int) -> float:
        delay = min(self.base_delay * (2 ** attempt), self.max_delay)
        if self.jitter:
            delay *= 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        # The cap binds AFTER jitter: the ladder never exceeds
        # max_delay no matter the draw.
        return min(delay, self.max_delay)

    def _on_disconnected(self) -> None:
        if not self.enabled or self._reconnecting:
            return
        if self.container.closed or self.container.doc_id is None:
            return
        self._reconnecting = True
        try:
            self._run_ladder()
        finally:
            self._reconnecting = False

    def _run_ladder(self) -> None:
        last_exc: Optional[BaseException] = None
        for attempt in range(self.max_attempts):
            if self.container.closed:
                return
            try:
                self.container.connect()
                # connect() can "succeed" yet leave the container
                # disconnected again (e.g. the replay flush was nacked
                # mid-connect, which detaches the connection while
                # _reconnecting suppresses the re-entrant event) —
                # success is the container BEING connected.
                if self.container.connected:
                    return
            except ConnectionError as exc:  # transient transport error
                last_exc = exc
                # A failure mid-connect (e.g. replay flush raising
                # after the transport was established) may leave a
                # half-wired connection whose listener still targets
                # the runtime; tear it down or the next attempt would
                # double-deliver every sequenced message.
                self.container.disconnect()
            if attempt + 1 < self.max_attempts:
                delay = self.delay_for(attempt)
                self.delays.append(delay)
                self.sleep(delay)
        self.container.emit("connectionFailure", last_exc)
