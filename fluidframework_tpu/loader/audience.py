"""Audience: the connected-client roster.

Reference loader/container-loader/src/audience.ts: a live view of the
quorum's membership with add/remove events, fed from the runtime's
protocol state.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ..utils.events import EventEmitter


class Audience(EventEmitter):
    def __init__(self, runtime=None):
        super().__init__()
        self._members: Dict[Any, Any] = {}
        if runtime is not None:
            self.bind(runtime)

    def bind(self, runtime) -> None:
        quorum = runtime.protocol.quorum
        for cid, member in quorum.members.items():
            self._members[cid] = member.detail
        quorum.on("addMember", self._on_add(quorum))
        quorum.on("removeMember", self._on_remove)

    def _on_add(self, quorum):
        def handler(client_id):
            member = quorum.members.get(client_id)
            self._members[client_id] = member.detail if member else None
            self.emit("addMember", client_id)

        return handler

    def _on_remove(self, client_id) -> None:
        self._members.pop(client_id, None)
        self.emit("removeMember", client_id)

    def get_members(self) -> Dict[Any, Any]:
        return dict(self._members)

    def get_member(self, client_id) -> Optional[Any]:
        return self._members.get(client_id)
