"""Container + Loader: lifecycle over a driver.

Reference Container (loader/container-loader/src/container.ts:310
load, :376 createDetached, :1056 attach) and Loader (loader.ts). A
*driver* here is any object with the document-service surface:

    create_document(doc_id, summary_wire) -> None
    load_document(doc_id) -> summary_wire | None
    connect(doc_id, client_id=None) -> connection
    ops_from(doc_id, from_seq) -> [SequencedMessage]

(drivers.local_driver adapts LocalServer/LocalOrderingService; replay
and file drivers provide read-only variants.)

Also implements stashed-op close/resume: `close_and_get_pending_state`
serializes unacked local ops (closeAndGetPendingLocalState), and
`Loader.resolve(..., pending_state=...)` re-applies them through each
DDS's applyStashedOp before connecting (client.ts:831 semantics).
"""

from __future__ import annotations

import json
import uuid
from typing import Any, Dict, List, Optional

from ..protocol.constants import PROVISIONAL_CLIENT
from ..protocol.mergetree_ops import op_to_json
from ..runtime.channel import ChannelRegistry
from ..runtime.container_runtime import ContainerRuntime, Envelope, FlushMode
from ..runtime.summary import SummaryTree
from ..utils.events import EventEmitter
from .audience import Audience


def _encode_stash_content(content: Any) -> Any:
    """Wire-encode a pending op's contents (sequence ops carry
    dataclasses in-proc)."""
    if isinstance(content, dict) and content.get("kind") == "seq":
        op = content["op"]
        return {"kind": "seq", "op": op if isinstance(op, dict) else op_to_json(op)}
    return content


class Container(EventEmitter):
    def __init__(self, runtime: ContainerRuntime, driver, doc_id: Optional[str]):
        super().__init__()
        self.runtime = runtime
        self.driver = driver
        self.doc_id = doc_id
        self.audience = Audience()
        self.closed = False
        runtime.on("connected", lambda cid: self.emit("connected", cid))
        runtime.on("disconnected", lambda: self.emit("disconnected"))
        # Bind the blob storage surface up front (re-binds the
        # registry a summary load may have created driver-less).
        runtime.attach_blob_manager(driver, lambda: self.doc_id)

    # ------------------------------------------------------------- state

    @property
    def attach_state(self) -> str:
        return "Attached" if self.doc_id is not None else "Detached"

    @property
    def connected(self) -> bool:
        return self.runtime.connection is not None

    @property
    def is_dirty(self) -> bool:
        return self.runtime.is_dirty

    # ---------------------------------------------------------- lifecycle

    def attach(self, doc_id: Optional[str] = None) -> str:
        """Persist the attach summary and go live (container.ts:1056)."""
        assert self.doc_id is None, "already attached"
        doc_id = doc_id or uuid.uuid4().hex[:12]
        self.driver.create_document(doc_id, self.runtime.summarize().to_json())
        self.doc_id = doc_id
        self.connect()
        return doc_id

    def connect(self, client_id: Optional[int] = None) -> None:
        assert self.doc_id is not None, "attach first"
        self.runtime.connect(self.driver.connect(self.doc_id, client_id))
        self.audience.bind(self.runtime)

    def disconnect(self) -> None:
        self.runtime.disconnect()

    def flush(self) -> None:
        self.runtime.flush()

    def create_blob(self, data: bytes) -> dict:
        """Upload an attachment blob and get a GC-tracked handle
        (reference IFluidContainer blob support, blobManager.ts:149)."""
        return self.runtime.blobs.create_blob(data)

    def get_blob(self, handle) -> bytes:
        return self.runtime.blobs.get_blob(handle)

    def close(self) -> None:
        # Mark closed BEFORE dropping the connection: the disconnect
        # event fires listeners (e.g. ConnectionManager's reconnect
        # ladder) that must see this as a deliberate close, not a
        # transport loss to recover from.
        self.closed = True
        self.disconnect()
        self.emit("closed")

    def close_and_get_pending_state(self) -> str:
        """Serialize unacked local ops for a later session
        (closeAndGetPendingLocalState). The summary captured here is
        the *acked* state; pending ops re-apply on top of it."""
        # Runtime-level attach ops (channel is None) are serialized
        # too: a dynamically created channel whose announcement was
        # unacked at close must reach the resumed session (its attach
        # summary rides the op contents), or the creator's channel
        # silently vanishes.
        pending = [
            {
                "datastore": pm.envelope.datastore,
                "channel": pm.envelope.channel,
                "contents": _encode_stash_content(pm.envelope.contents),
            }
            for pm in list(self.runtime._pending) + list(self.runtime._outbox)
            # Synthetic chunk pieces (datastore None) are transport
            # artifacts; the final chunk's entry owns the original op
            # and re-chunks on the resumed session's flush.
            if pm.envelope.datastore is not None
        ]
        state = {
            "docId": self.doc_id,
            "baseSeq": self.runtime.current_seq,
            "pending": pending,
        }
        self.close()
        return json.dumps(state)


class Loader:
    """Resolves containers against a driver (loader.ts Loader)."""

    def __init__(self, driver, registry: ChannelRegistry,
                 flush_mode: FlushMode = FlushMode.TURN_BASED):
        self.driver = driver
        self.registry = registry
        self.flush_mode = flush_mode

    def create_detached(self) -> Container:
        rt = ContainerRuntime(self.registry, flush_mode=self.flush_mode)
        return Container(rt, self.driver, None)

    def resolve(self, doc_id: str, connect: bool = True,
                pending_state: Optional[str] = None,
                client_id: Optional[int] = None) -> Container:
        """Load from the latest summary + catch up (container.ts:310 →
        :1374 load). With `pending_state`, stashed ops re-apply before
        connecting, then replay through resubmit on connect.

        Headless resolves (``connect=False``) against a driver that
        offers the summary service's ``catchup`` surface answer the
        whole boot — nearest summary + op tail — in one round trip and
        apply the tail directly, so a headless reader (the server-side
        summarizer agent, an export job) sees the current document
        without ever joining the quorum. Connecting resolves keep the
        classic load_document path: the join handshake fetches its own
        catch-up, so shipping the tail here would only be thrown
        away."""
        tail_ops = None
        if (pending_state is None and not connect
                and hasattr(self.driver, "catchup")):
            res = self.driver.catchup(doc_id, 0)
            wire = res["summary"]
            tail_ops = res["ops"]
        else:
            wire = self.driver.load_document(doc_id)
        if wire is None:
            raise KeyError(f"unknown document {doc_id!r}")
        rt = ContainerRuntime(self.registry, flush_mode=self.flush_mode)
        rt.load(SummaryTree.from_json(wire))
        container = Container(rt, self.driver, doc_id)
        if tail_ops is not None:
            # Headless catch-up: the summary's tail applies directly.
            for msg in tail_ops:
                if msg.sequence_number > rt.current_seq:
                    rt.process(msg)
        if pending_state is not None:
            state = json.loads(pending_state)
            assert state["docId"] == doc_id
            # Stashed ops recorded positions at the stashed session's
            # perspective (baseSeq). Re-applying them after a full
            # catch-up would land them at stale positions whenever
            # remote ops sequenced past the stash point (the reference
            # applyStashedOp preserves the op's original refSeq). So:
            # replay the op tail only UP TO baseSeq, apply the stash as
            # fresh pending local ops at that perspective
            # (IDeltaHandler.applyStashedOp, channel.ts:153), and let
            # the normal connect catch-up rebase them through the
            # pending-op path for anything sequenced later.
            rt._ever_connected = True
            # Channels must be *collaborating* for the stash to apply
            # as pending local ops (not detached content); a real
            # client id only arrives at connect, so stash under a
            # provisional identity — connect's resubmit path re-stamps
            # pending segments with the assigned id (client.ts:917).
            rt.client_id = PROVISIONAL_CLIENT
            for ds in rt.datastores.values():
                ds.attach_all()
            base = state["baseSeq"]
            # ops_from is part of the required driver surface (module
            # docstring); skipping this tail replay would re-apply the
            # stash at the summary perspective — the stale-position
            # bug — so its absence must fail loudly, not silently.
            # Ranged refetch where the driver supports it (every
            # in-tree driver does): only the (current, base] window is
            # fetched instead of the whole tail past the stash point —
            # a long-offline resume no longer pulls ops it will
            # immediately discard.
            try:
                tail = self.driver.ops_from(
                    doc_id, rt.current_seq, to_seq=base
                )
            except TypeError:  # minimal foreign driver: full tail
                tail = self.driver.ops_from(doc_id, rt.current_seq)
            for msg in tail:
                if msg.sequence_number > base:
                    break
                rt.process(msg)
            for stashed in state["pending"]:
                if stashed["channel"] is None:
                    # Pending attach op: realize the channel locally
                    # from its carried attach summary, then queue the
                    # announcement to resubmit as-is on connect.
                    rt._process_attach(
                        stashed["datastore"], stashed["contents"], local=False
                    )
                    rt._submit_op(
                        Envelope(stashed["datastore"], None, stashed["contents"]),
                        None,
                    )
                else:
                    ds = rt.get_datastore(stashed["datastore"])
                    ds.apply_stashed_op(stashed["channel"], stashed["contents"])
        if connect:
            container.connect(client_id)
        return container
