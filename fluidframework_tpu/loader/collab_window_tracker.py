"""CollabWindowTracker: noop heartbeats that keep the MSN moving.

Reference `CollabWindowTracker`
(loader/container-loader/src/collabWindowTracker.ts): the minimum
sequence number only advances when EVERY client's reference sequence
number advances, and a client's refSeq only advances when it submits
something. An idle reader would therefore pin the MSN (and with it
zamboni, proposal commits, and trunk eviction) forever. The tracker
watches processed remote ops and submits a NOOP once enough
unacknowledged remote traffic accumulates, advancing this client's
refSeq without any user edit.
"""

from __future__ import annotations

from ..protocol.messages import MessageType, SequencedMessage


class CollabWindowTracker:
    """Attach to a ContainerRuntime; submits NOOPs after `max_ops`
    remote ops arrive with no local submission in between."""

    def __init__(self, runtime, max_ops: int = 50):
        self.runtime = runtime
        self.max_ops = max_ops
        self._since_local = 0
        self.noops_sent = 0
        runtime.on("op", self._on_op)

    def _on_op(self, msg: SequencedMessage, local: bool) -> None:
        if local:
            self._since_local = 0
            return
        if msg.type != MessageType.OP:
            # Heartbeats must not count noops/system messages —
            # otherwise trackers feed each other (and their own echo)
            # in an endless noop cycle, the exact ack-loop the
            # reference's tracker filters out.
            return
        self._since_local += 1
        if (
            self._since_local >= self.max_ops
            and self.runtime.connection is not None
        ):
            self._since_local = 0
            self.noops_sent += 1
            self.runtime.submit_system_message(MessageType.NOOP, None)
