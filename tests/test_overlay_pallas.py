"""Differential tests: overlay PALLAS kernel vs numpy spec vs oracle.

Gates the device overlay engine (ops/overlay_pallas.py via
core/overlay_replay.py, run through the pallas interpreter on CPU)
bit-for-bit against:

- the numpy overlay reference (ops/overlay_ref.py) on the synthetic
  bench mix across chunk/window geometries, and
- the scalar oracle (core/mergetree.py) on real-concurrency farm
  streams (lagging refSeqs, insert tie-breaks, overlapping removes,
  multi-pair annotations — the mergeTreeOperationRunner.ts role).
"""

import numpy as np
import pytest

from fluidframework_tpu.core.mergetree import replay_passive
from fluidframework_tpu.core.overlay_replay import (
    OverlayDeviceReplica,
    OverlayKernelMessageReplica,
)
from fluidframework_tpu.ops.overlay_ref import OverlayReplica
from fluidframework_tpu.testing.digest import state_digest
from fluidframework_tpu.testing.farm import (
    FarmConfig,
    char_spans,
    run_sharedstring_farm,
)
from fluidframework_tpu.testing.synthetic import generate_stream


def _device_vs_numpy(n_ops, chunk, window, *, n_clients=64, seed=3,
                     msn_window=256):
    stream = generate_stream(
        n_ops, n_clients=n_clients, seed=seed, initial_len=64,
        window=msn_window,
    )
    ref = OverlayReplica(stream, initial_len=64, fold_interval=chunk)
    ref.replay()
    ref.check_errors()
    dev = OverlayDeviceReplica(
        stream, initial_len=64, chunk_size=chunk, window=window,
        interpret=True,
    )
    dev.replay()
    dev.check_errors()
    dev.verify_invariants()
    assert dev.get_text() == ref.get_text()
    assert state_digest(dev.annotated_spans()) == state_digest(
        ref.annotated_spans()
    )
    return dev


def test_device_matches_numpy_synthetic():
    dev = _device_vs_numpy(2000, chunk=256, window=1024)
    # The run must actually have exercised folding + settled space.
    assert int(dev.table.settled_len) > 0
    assert int(dev.cursor) > 0


def test_device_matches_numpy_tiny_chunks():
    _device_vs_numpy(800, chunk=128, window=1024, msn_window=64)


def test_device_matches_numpy_lagging_msn():
    # Large MSN lag: most rows stay unsettled across many folds.
    _device_vs_numpy(1500, chunk=256, window=2048, msn_window=1024)


def test_device_capacity_overflow_flags():
    stream = generate_stream(3000, n_clients=64, seed=3, initial_len=64,
                             window=2048)
    dev = OverlayDeviceReplica(
        stream, initial_len=64, chunk_size=256, window=1024,
        interpret=True,
    )
    dev.replay()
    with pytest.raises(RuntimeError, match="capacity overflow"):
        dev.check_errors()


def farm_device_vs_oracle(cfg: FarmConfig, chunk=64, window=1024):
    farm = run_sharedstring_farm(cfg)
    oracle = replay_passive(farm.stream, cfg.initial_text)
    r = OverlayKernelMessageReplica(
        initial=cfg.initial_text, chunk_size=chunk, window=window,
        interpret=True,
    )
    r.apply_messages(farm.stream)
    r.check_errors()
    r.verify_invariants()
    assert r.get_text() == oracle.get_text()
    assert char_spans(r.annotated_spans()) == char_spans(
        oracle.annotated_spans()
    )


@pytest.mark.parametrize("seed", range(4))
def test_farm_device_vs_oracle(seed):
    farm_device_vs_oracle(
        FarmConfig(num_clients=3, rounds=6, ops_per_client_per_round=3,
                   seed=seed)
    )


def test_farm_device_more_clients():
    farm_device_vs_oracle(
        FarmConfig(num_clients=8, rounds=5, ops_per_client_per_round=4,
                   seed=501),
        chunk=32,
    )


def test_farm_device_remove_heavy():
    farm_device_vs_oracle(
        FarmConfig(
            num_clients=4, rounds=8, ops_per_client_per_round=4, seed=12,
            insert_weight=0.35, remove_weight=0.55, annotate_weight=0.1,
            initial_text="the quick brown fox jumps over the lazy dog",
        )
    )


def test_farm_device_annotate_heavy():
    farm_device_vs_oracle(
        FarmConfig(
            num_clients=6, rounds=8, ops_per_client_per_round=4, seed=99,
            insert_weight=0.2, remove_weight=0.2, annotate_weight=0.6,
            initial_text="annotation heavy doc " * 4,
        )
    )


def test_long_document_exceeds_row_model_vmem_ceiling():
    """The round-2 engine hard-capped documents at 131,072 live rows
    (VMEM). The overlay window stays at a few hundred rows while the
    SETTLED document grows without bound — prove the decoupling by
    replaying a doc whose settled length far exceeds the window."""
    stream = generate_stream(
        4000, n_clients=32, seed=5, initial_len=64, window=128,
        insert_weight=0.9, remove_weight=0.05, annotate_weight=0.05,
        max_insert_len=8,
    )
    dev = OverlayDeviceReplica(
        stream, initial_len=64, chunk_size=256, window=1024,
        interpret=True,
    )
    dev.replay()
    dev.check_errors()
    ref = OverlayReplica(stream, initial_len=64, fold_interval=256)
    ref.replay()
    ref.check_errors()
    assert dev.get_text() == ref.get_text()
    # Settled document >> window table: the scale cliff is gone.
    assert int(dev.table.settled_len) > 10 * int(dev.table.n_rows)


def test_streaming_ingress_matches_prestaged():
    """Ingest-in-the-loop replay (segments fed host->device, transfer
    overlapping compute) is bit-identical to the pre-staged replay —
    table, fold log, and digests."""
    from fluidframework_tpu.core.overlay_replay import OverlayDeviceReplica
    from fluidframework_tpu.testing.digest import state_digest
    from fluidframework_tpu.testing.synthetic import generate_lagged_stream

    stream = generate_lagged_stream(
        600, n_clients=6, seed=88, window=48, initial_len=16
    )

    def rep():
        return OverlayDeviceReplica(
            stream, initial_len=16, chunk_size=64, window=1024,
            n_removers=10, interpret=True,
        )

    pre = rep()
    pre.replay()
    pre.check_errors()

    for n_segments in (1, 3, 8):
        sr = rep()
        sr.replay_streaming(n_segments=n_segments)
        sr.check_errors()
        assert state_digest(sr.annotated_spans()) == state_digest(
            pre.annotated_spans()
        ), f"n_segments={n_segments}"
        import numpy as np

        assert int(sr.cursor) == int(pre.cursor)
        assert (np.asarray(sr.counts) == np.asarray(pre.counts)).all()
