"""Interval collections: overlap search index, endpoint sidedness,
per-key property merge, and randomized convergence vs an O(n) scalar
model (reference intervalCollection.ts:958 findOverlappingIntervals,
sequencePlace.ts sides, the interval propertyManager)."""

import random

import pytest

from fluidframework_tpu.dds import StringFactory
from fluidframework_tpu.dds.sequence import SIDE_AFTER, SIDE_BEFORE
from fluidframework_tpu.runtime import ChannelRegistry
from fluidframework_tpu.testing.mocks import MultiClientHarness


def make_pair():
    registry = ChannelRegistry([StringFactory()])
    h = MultiClientHarness(
        2, registry, channel_types=[("text", StringFactory.type_name)]
    )
    a = h.runtimes[0].get_datastore("default").get_channel("text")
    b = h.runtimes[1].get_datastore("default").get_channel("text")
    return h, a, b


def naive_overlap(coll, start, end):
    eng = coll.sequence.engine
    out = []
    for iv in coll:
        s, e = iv.bounds(eng)
        if s <= end and e >= start:
            out.append(iv.interval_id)
    return sorted(out)


def test_overlap_index_matches_scan():
    h, a, b = make_pair()
    a.insert_text(0, "x" * 200)
    h.process_all()
    coll = a.get_interval_collection("c")
    rng = random.Random(7)
    for _ in range(60):
        s = rng.randrange(0, 180)
        e = min(199, s + rng.randrange(0, 40))
        coll.add(s, e)
    h.process_all()
    for _ in range(100):
        qs = rng.randrange(0, 200)
        qe = min(199, qs + rng.randrange(0, 50))
        got = sorted(
            iv.interval_id
            for iv in coll.find_overlapping_intervals(qs, qe)
        )
        assert got == naive_overlap(coll, qs, qe)


def test_overlap_index_invalidates_on_edits():
    h, a, b = make_pair()
    a.insert_text(0, "abcdefghij")
    h.process_all()
    coll = a.get_interval_collection("c")
    iv = coll.add(2, 5)
    h.process_all()
    assert [i.interval_id for i in coll.find_overlapping_intervals(2, 2)] == [
        iv.interval_id
    ]
    # An edit BEFORE the interval shifts it; the index must rebuild.
    a.insert_text(0, "ZZZZ")
    h.process_all()
    assert coll.find_overlapping_intervals(2, 2) == []
    assert [i.interval_id for i in coll.find_overlapping_intervals(6, 6)] == [
        iv.interval_id
    ]


def test_endpoint_sidedness_on_boundary_inserts():
    """before-endpoints expand with boundary inserts; after-endpoints
    do not (the reference's stickiness contract)."""
    h, a, b = make_pair()
    a.insert_text(0, "abcdef")
    h.process_all()
    coll = a.get_interval_collection("c")
    exp = coll.add(2, 4, start_side=SIDE_BEFORE, end_side=SIDE_BEFORE)
    fix = coll.add(2, 4, start_side=SIDE_AFTER, end_side=SIDE_AFTER)
    h.process_all()
    eng = a.engine
    assert exp.bounds(eng) == (2, 4)
    assert fix.bounds(eng) == (2, 4)
    # Insert exactly at the end boundary (position 4).
    b.insert_text(4, "XY")
    h.process_all()
    coll_b = b.get_interval_collection("c")
    for coll_x, eng_x in ((coll, a.engine), (coll_b, b.engine)):
        got = {
            iv.interval_id: iv.bounds(eng_x) for iv in coll_x
        }
        # before-end anchored to the char at 4: pushed right (expands).
        assert got[exp.interval_id] == (2, 6)
        # after-end anchored to char 3: boundary insert lands outside.
        assert got[fix.interval_id] == (2, 4)
    # Insert exactly at the start boundary (position 2).
    b.insert_text(2, "Q")
    h.process_all()
    eng = a.engine
    exp2 = coll.get_interval_by_id(exp.interval_id)
    fix2 = coll.get_interval_by_id(fix.interval_id)
    # before-start anchored at char 2: pushed right (shrinks from left).
    assert exp2.bounds(eng)[0] == 3
    # after-start anchored to char 1: insert at 2 lands after it... the
    # start stays put, absorbing the new text into the interval.
    assert fix2.bounds(eng)[0] == 2


def test_per_key_property_merge_lww():
    h, a, b = make_pair()
    a.insert_text(0, "hello world")
    h.process_all()
    ca = a.get_interval_collection("c")
    cb = b.get_interval_collection("c")
    iv = ca.add(0, 5, {"bold": 1, "color": "red"})
    h.process_all()
    # Concurrent per-key writes on DIFFERENT keys both land.
    ca.change_properties(iv.interval_id, {"bold": 2})
    cb.change_properties(iv.interval_id, {"color": "blue", "size": 9})
    h.process_all()
    pa = ca.get_interval_by_id(iv.interval_id).props
    pb = cb.get_interval_by_id(iv.interval_id).props
    assert pa == pb
    assert pa["bold"] == 2  # a's write to bold survives b's batch
    assert pa["color"] == "blue"
    assert pa["size"] == 9
    # None deletes converge.
    cb.change_properties(iv.interval_id, {"size": None})
    h.process_all()
    assert "size" not in ca.get_interval_by_id(iv.interval_id).props
    assert "size" not in cb.get_interval_by_id(iv.interval_id).props


@pytest.mark.parametrize("seed", range(3))
def test_interval_fuzz_convergence(seed):
    """Random interleaving of text edits + interval add/change/delete/
    props across two clients: resolved bounds, sides, and props
    converge, and the indexed query always equals the O(n) model."""
    h, a, b = make_pair()
    a.insert_text(0, "0123456789" * 6)
    h.process_all()
    rng = random.Random(seed)
    colls = [x.get_interval_collection("f") for x in (a, b)]
    strings = [a, b]
    for rnd in range(25):
        for idx in (0, 1):
            s_ch, coll = strings[idx], colls[idx]
            for _ in range(3):
                ln = s_ch.get_length()
                r = rng.random()
                if r < 0.30 or ln < 10:
                    pos = rng.randrange(0, ln + 1)
                    s_ch.insert_text(pos, "".join(
                        rng.choices("abz", k=rng.randint(1, 4))
                    ))
                elif r < 0.45:
                    st = rng.randrange(0, ln - 1)
                    s_ch.remove_text(st, min(ln, st + rng.randint(1, 5)))
                elif r < 0.70:
                    st = rng.randrange(0, ln)
                    en = min(ln - 1, st + rng.randrange(0, 12))
                    coll.add(
                        st, en,
                        {"k": rng.randint(0, 9)},
                        start_side=rng.choice([SIDE_BEFORE, SIDE_AFTER]),
                        end_side=rng.choice([SIDE_BEFORE, SIDE_AFTER]),
                    )
                elif coll.intervals:
                    iid = rng.choice(list(coll.intervals))
                    rr = rng.random()
                    if rr < 0.4:
                        st = rng.randrange(0, ln)
                        en = min(ln - 1, st + rng.randrange(0, 8))
                        coll.change(iid, st, en)
                    elif rr < 0.7:
                        coll.change_properties(
                            iid, {"k": rng.randint(0, 9),
                                  "m": rng.choice([1, None])}
                        )
                    else:
                        coll.remove_interval_by_id(iid)
        h.process_all()
        # Convergence of text + full interval state.
        assert a.get_text() == b.get_text()
        state = []
        for s_ch, coll in zip(strings, colls):
            eng = s_ch.engine
            state.append(sorted(
                (iv.interval_id, iv.bounds(eng), iv.start_side,
                 iv.end_side, tuple(sorted(iv.props.items())))
                for iv in coll
            ))
        assert state[0] == state[1], f"round {rnd} diverged"
        # Indexed query == O(n) model on both replicas.
        ln = a.get_length()
        for _ in range(5):
            qs = rng.randrange(0, max(ln, 1))
            qe = min(ln, qs + rng.randrange(0, 20))
            for coll in colls:
                got = sorted(
                    iv.interval_id
                    for iv in coll.find_overlapping_intervals(qs, qe)
                )
                assert got == naive_overlap(coll, qs, qe)


def test_incremental_index_no_full_rebuild():
    """Sequence edits must cost ZERO index work and queries must
    resolve only O(log n + k) endpoints — never all n (the former
    design re-resolved and re-sorted every endpoint per engine
    version bump)."""
    h, a, b = make_pair()
    a.insert_text(0, "x" * 2000)
    h.process_all()
    coll = a.get_interval_collection("perf")
    N = 300
    for i in range(N):
        s = (i * 6) % 1800
        coll.add(s, s + 3)
    h.process_all()

    eng = a.engine
    real = eng.resolve_reference
    counter = {"n": 0}

    def counting(ref):
        counter["n"] += 1
        return real(ref)

    eng.resolve_reference = counting
    try:
        # A burst of edits: no index maintenance -> no resolutions.
        for i in range(50):
            a.insert_text((i * 13) % a.get_length(), "yy")
        assert counter["n"] == 0, "sequence edits touched the index"
        # One query: far fewer resolutions than N endpoints.
        counter["n"] = 0
        got = coll.find_overlapping_intervals(900, 930)
        assert got, "query should find overlaps"
        assert counter["n"] < N, (
            f"query resolved {counter['n']} refs for {N} intervals "
            "(full-rebuild behavior)"
        )
    finally:
        eng.resolve_reference = real
    # Correctness after the burst: index equals the O(n) scan.
    ln = a.get_length()
    for q0, q1 in ((0, 50), (700, 1100), (ln - 60, ln)):
        want = sorted(
            iv.interval_id for iv in coll
            if iv.bounds(a.engine)[0] <= q1
            and iv.bounds(a.engine)[1] >= q0
        )
        got = sorted(
            iv.interval_id
            for iv in coll.find_overlapping_intervals(q0, q1)
        )
        assert got == want


@pytest.mark.parametrize("seed", range(4))
def test_incremental_index_survives_zamboni(seed):
    """Heavy removal + MSN advance (zamboni collection, reference
    slides) must not break the index's stable reference order: the
    indexed query equals the O(n) scan after every drain."""
    h, a, b = make_pair()
    a.insert_text(0, "0123456789" * 20)
    h.process_all()
    rng = random.Random(7000 + seed)
    coll = a.get_interval_collection("z")
    for i in range(40):
        s = rng.randrange(0, 180)
        coll.add(s, min(199, s + rng.randrange(0, 15)))
    h.process_all()
    for _ in range(30):
        ln = a.get_length()
        if ln > 30 and rng.random() < 0.6:
            st = rng.randrange(0, ln - 10)
            a.remove_text(st, st + rng.randint(1, 8))
        else:
            a.insert_text(rng.randrange(0, ln + 1), "ab")
        h.process_all()  # sequences + advances MSN -> zamboni slides
        ln = a.get_length()
        q0 = rng.randrange(0, max(ln - 5, 1))
        q1 = min(ln, q0 + rng.randrange(1, 30))
        want = sorted(
            iv.interval_id for iv in coll
            if iv.bounds(a.engine)[0] <= q1
            and iv.bounds(a.engine)[1] >= q0
        )
        got = sorted(
            iv.interval_id
            for iv in coll.find_overlapping_intervals(q0, q1)
        )
        assert got == want


def test_index_repairs_after_slide_past_pending_insert():
    """The review's order-inversion repro: a sequenced remote removal
    slides an interval's start reference past a pending-LOCAL insert
    (excluded slide target) carrying it past an interval anchored on
    that insert — the index must repair its order, not miss/false-
    positive forever."""
    h, a, b = make_pair()
    a.insert_text(0, "abcdef")
    h.process_all()
    coll = a.get_interval_collection("s")
    i1 = coll.add(2, 3)  # on 'c'
    h.process_all()
    # Pending local insert (NOT flushed) + an interval inside it.
    a.insert_text(3, "ZZ")
    i2 = coll.add(3, 4)  # inside the pending 'ZZ'
    # Remote removal of 'c' sequences: i1's ref slides past 'ZZ'.
    b.remove_text(2, 3)
    h.process_all()

    def brute(q0, q1):
        return sorted(
            iv.interval_id for iv in coll
            if iv.bounds(a.engine)[0] <= q1
            and iv.bounds(a.engine)[1] >= q0
        )

    for q0, q1 in ((4, 6), (0, 2), (0, 6), (2, 4)):
        got = sorted(
            iv.interval_id
            for iv in coll.find_overlapping_intervals(q0, q1)
        )
        assert got == brute(q0, q1), (q0, q1, got, brute(q0, q1))
