"""Unit tests for the scalar merge-tree oracle.

Scenario sources: reference merge-tree unit tests
(packages/dds/merge-tree/src/test/*.spec.ts) — basic editing, concurrent
insert tie-breaks, overlapping removes, annotate conflicts, ack flow.
"""

import pytest

from fluidframework_tpu.core.mergetree import CollabClient, MergeTreeEngine
from fluidframework_tpu.protocol.constants import NON_COLLAB_CLIENT, UNASSIGNED_SEQ
from fluidframework_tpu.protocol.messages import MessageType, SequencedMessage
from fluidframework_tpu.server.sequencer import DocumentSequencer


def make_msg(seq, msn, cid, cseq, ref, op):
    return SequencedMessage(
        sequence_number=seq,
        minimum_sequence_number=msn,
        client_id=cid,
        client_seq=cseq,
        ref_seq=ref,
        type=MessageType.OP,
        contents=op,
    )


class TestBasicEditing:
    def test_insert_into_empty(self):
        e = MergeTreeEngine()
        e.insert(0, "hello", 0, 1, 1)
        assert e.get_text() == "hello"

    def test_insert_middle_splits(self):
        e = MergeTreeEngine()
        e.insert(0, "held", 0, 1, 1)
        e.insert(3, "lo wor", 1, 1, 2)
        # "held" with "lo wor" at 3 -> "hel" + "lo wor" + "d"
        assert e.get_text() == "hello word"
        assert len(e.segments) == 3

    def test_remove_range(self):
        e = MergeTreeEngine()
        e.insert(0, "hello world", 0, 1, 1)
        e.remove_range(5, 11, 1, 1, 2)
        assert e.get_text() == "hello"

    def test_remove_middle(self):
        e = MergeTreeEngine()
        e.insert(0, "hello cruel world", 0, 1, 1)
        e.remove_range(5, 11, 1, 1, 2)
        assert e.get_text() == "hello world"

    def test_insert_at_end(self):
        e = MergeTreeEngine()
        e.insert(0, "ab", 0, 1, 1)
        e.insert(2, "cd", 1, 1, 2)
        assert e.get_text() == "abcd"

    def test_annotate(self):
        e = MergeTreeEngine()
        e.insert(0, "abcd", 0, 1, 1)
        e.annotate_range(1, 3, {"bold": True}, 1, 1, 2)
        spans = e.annotated_spans()
        assert spans == [("a", None), ("bc", {"bold": True}), ("d", None)]

    def test_annotate_null_deletes(self):
        e = MergeTreeEngine()
        e.insert(0, "ab", 0, 1, 1, props={"k": 1})
        e.annotate_range(0, 2, {"k": None}, 1, 1, 2)
        assert e.annotated_spans() == [("ab", None)]  # empty props normalize to None


class TestConcurrency:
    def test_concurrent_inserts_same_pos_later_seq_first(self):
        """Two clients insert at pos 0 concurrently (both refSeq 0): the
        op sequenced LATER lands closer to the position (breakTie:
        newSeq > segSeq => insert before)."""
        e = MergeTreeEngine()
        e.insert(0, "X", 0, 1, 1)  # client 1, seq 1, ref 0
        e.insert(0, "Y", 0, 2, 2)  # client 2, seq 2, ref 0 — concurrent
        assert e.get_text() == "YX"

    def test_concurrent_insert_not_in_removed_range(self):
        """A concurrent insert inside a concurrently-removed range
        survives the remove."""
        e = MergeTreeEngine()
        e.insert(0, "abcdef", 0, 1, 1)
        # client 2 inserts at 3 having seen seq 1
        e.insert(3, "XX", 1, 2, 2)
        # client 3 removes [1,5) also having seen only seq 1 (concurrent
        # with the insert)
        e.remove_range(1, 5, 1, 3, 3)
        assert e.get_text() == "aXXf"

    def test_overlapping_removes(self):
        e = MergeTreeEngine()
        e.insert(0, "abcdef", 0, 1, 1)
        e.remove_range(1, 4, 1, 2, 2)  # client 2 removes bcd
        e.remove_range(2, 5, 1, 3, 3)  # client 3 concurrently removes cde
        assert e.get_text() == "af"
        # the overlap keeps the earliest removedSeq
        removed = [s for s in e.segments if s.removed_seq is not None]
        assert all(s.removed_seq in (2, 3) for s in removed)

    def test_insert_at_boundary_of_removed(self):
        """Insert at a position whose neighbors were concurrently
        removed: tombstones (acked <= refSeq) are excluded from
        tie-breaks, invisible-but-live segments participate."""
        e = MergeTreeEngine()
        e.insert(0, "ab", 0, 1, 1)
        e.remove_range(0, 1, 1, 1, 2)  # remove 'a' (acked)
        # client 2 saw both ops (ref 2) and inserts at 0
        e.insert(0, "Z", 2, 2, 3)
        assert e.get_text() == "Zb"


class TestCollabClients:
    def _wire(self, n, initial=""):
        seqr = DocumentSequencer()
        clients = [CollabClient(i + 1, initial=initial, engine="python") for i in range(n)]
        for c in clients:
            seqr.join(c.client_id)
        for c in clients:
            c.engine.current_seq = seqr.seq
        return seqr, clients

    def _deliver(self, seqr, clients, msgs_by_client):
        out = []
        for cid, msg in msgs_by_client:
            s = seqr.sequence(cid, msg)
            assert isinstance(s, SequencedMessage)
            out.append(s)
        for m in out:
            for c in clients:
                c.apply_msg(m)

    def test_two_client_convergence(self):
        seqr, (a, b) = self._wire(2, initial="base")
        m1 = a.insert_local(0, "A")
        m2 = b.insert_local(4, "B")  # b hasn't seen m1
        self._deliver(seqr, [a, b], [(1, m1), (2, m2)])
        assert a.get_text() == b.get_text() == "AbaseB"

    def test_local_pending_then_remote(self):
        seqr, (a, b) = self._wire(2, initial="xy")
        ma = a.insert_local(1, "AA")  # a: xAAy pending
        mb = b.insert_local(1, "B")  # b: xBy pending
        # sequence b first, then a
        self._deliver(seqr, [a, b], [(2, mb), (1, ma)])
        assert a.get_text() == b.get_text()
        # a's op sequenced later -> lands before b's at the tie position
        assert a.get_text() == "xAABy"

    def test_remove_vs_insert_race(self):
        seqr, (a, b) = self._wire(2, initial="hello world")
        ma = a.remove_local(0, 5)
        mb = b.insert_local(5, "!!")
        self._deliver(seqr, [a, b], [(1, ma), (2, mb)])
        assert a.get_text() == b.get_text() == "!! world"

    def test_overlapping_remove_ack(self):
        seqr, (a, b) = self._wire(2, initial="abcd")
        ma = a.remove_local(1, 3)
        mb = b.remove_local(0, 2)
        self._deliver(seqr, [a, b], [(2, mb), (1, ma)])
        assert a.get_text() == b.get_text() == "d"

    def test_annotate_pending_shadows_remote(self):
        seqr, (a, b) = self._wire(2, initial="ab")
        ma = a.annotate_local(0, 2, {"c": "red"})
        mb = b.annotate_local(0, 2, {"c": "blue"})
        # b's annotate sequenced first; a's pending write shadows it,
        # and a's (sequenced later) wins everywhere.
        self._deliver(seqr, [a, b], [(2, mb), (1, ma)])
        sa = a.engine.annotated_spans()
        sb = b.engine.annotated_spans()
        assert sa == sb
        assert all(p == {"c": "red"} for _, p in sa)

    def test_annotate_remote_after_local_wins(self):
        seqr, (a, b) = self._wire(2, initial="ab")
        ma = a.annotate_local(0, 2, {"c": "red"})
        # a's op sequenced FIRST, then b annotates having seen it
        self._deliver(seqr, [a, b], [(1, ma)])
        mb = b.annotate_local(0, 2, {"c": "blue"})
        self._deliver(seqr, [a, b], [(2, mb)])
        sa = a.engine.annotated_spans()
        sb = b.engine.annotated_spans()
        assert sa == sb
        assert all(p == {"c": "blue"} for _, p in sa)

    def test_split_pending_insert_ack(self):
        """A pending local insert split by another local insert must ack
        both halves."""
        seqr, (a, b) = self._wire(2)
        m1 = a.insert_local(0, "abcd")
        m2 = a.insert_local(2, "XY")  # splits pending 'abcd'
        self._deliver(seqr, [a, b], [(1, m1), (1, m2)])
        assert a.get_text() == b.get_text() == "abXYcd"
        assert all(s.seq != UNASSIGNED_SEQ for s in a.engine.segments)
        assert not a.engine.pending

    def test_zamboni_drops_tombstones(self):
        seqr, (a, b) = self._wire(2, initial="abcdef")
        m = a.remove_local(0, 3)
        self._deliver(seqr, [a, b], [(1, m)])
        # push MSN forward with noop-ish traffic
        m2 = a.insert_local(3, "x")
        m3 = b.insert_local(0, "y")
        self._deliver(seqr, [a, b], [(1, m2), (2, m3)])
        assert a.get_text() == b.get_text()
        # after MSN passes the remove, tombstones are physically gone
        if a.engine.min_seq >= 2:
            assert all(s.removed_seq is None for s in a.engine.segments)


class TestSequencer:
    def test_msn_tracking(self):
        s = DocumentSequencer()
        s.join(1)
        s.join(2)
        from fluidframework_tpu.protocol.messages import DocumentMessage

        m = s.sequence(1, DocumentMessage(client_seq=1, ref_seq=2))
        assert m.sequence_number == 3
        # c2 joined when head seq was 1 => its refSeq is 1; MSN = min(2, 1)
        assert m.minimum_sequence_number == 1

    def test_nack_stale_refseq(self):
        from fluidframework_tpu.protocol.messages import DocumentMessage, NackMessage

        s = DocumentSequencer()
        s.join(1)
        s.min_seq = 10
        out = s.sequence(1, DocumentMessage(client_seq=1, ref_seq=3))
        assert isinstance(out, NackMessage)
        assert out.code == 400

    def test_nack_future_refseq(self):
        # A refSeq ahead of the head would wedge the MSN above seq and
        # permanently nack every honest client.
        from fluidframework_tpu.protocol.messages import DocumentMessage, NackMessage

        s = DocumentSequencer()
        s.join(1)
        s.join(2)
        out = s.sequence(1, DocumentMessage(client_seq=1, ref_seq=999))
        assert isinstance(out, NackMessage)
        assert out.code == 416
        # Honest traffic still flows afterwards.
        ok = s.sequence(1, DocumentMessage(client_seq=1, ref_seq=2))
        assert not isinstance(ok, NackMessage)
        assert s.min_seq <= s.seq

    def test_checkpoint_roundtrip(self):
        from fluidframework_tpu.protocol.messages import DocumentMessage

        s = DocumentSequencer("d1")
        s.join(1)
        s.sequence(1, DocumentMessage(client_seq=1, ref_seq=1))
        s2 = DocumentSequencer.restore(s.checkpoint())
        assert s2.seq == s.seq and s2.min_seq == s.min_seq
        m = s2.sequence(1, DocumentMessage(client_seq=2, ref_seq=2))
        assert m.sequence_number == s.seq + 1
