"""Op lifecycle (compression, chunking, boxcar) + attachment blobs.

Reference: opCompressor.ts:20, opSplitter.ts:22, pendingBoxcar.ts,
blobManager.ts:149. The service nacks ops over 768KB, so a >1MB op
only round-trips if the splitter kicks in.
"""

from __future__ import annotations

import pytest

from fluidframework_tpu.dds import MapFactory, StringFactory
from fluidframework_tpu.drivers import FaultInjectionDriver, LocalDriver
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.runtime import ChannelRegistry
from fluidframework_tpu.runtime.gc import make_handle
from fluidframework_tpu.runtime.op_lifecycle import (
    ChunkReassembler,
    compress_batch,
    decompress_batch,
    split_contents,
)
from fluidframework_tpu.server import LocalServer

REGISTRY = ChannelRegistry([MapFactory(), StringFactory()])


def make_pair():
    server = LocalServer()
    loader = Loader(LocalDriver(server), REGISTRY)
    c1 = loader.create_detached()
    ds = c1.runtime.create_datastore("default")
    ds.create_channel("m", MapFactory.type_name)
    ds.create_channel("s", StringFactory.type_name)
    doc = c1.attach()
    c2 = loader.resolve(doc)
    return c1, c2, loader, server, doc


def chan(c, cid="m"):
    return c.runtime.get_datastore("default").get_channel(cid)


def test_compress_roundtrip_unit():
    contents = [{"a": 1}, {"b": [1, 2, 3]}, {"c": "x" * 100}]
    packed = compress_batch(contents)
    assert len(packed) == 3
    assert "packedContents" in packed[0]
    assert packed[1] == {"placeholder": True}
    assert decompress_batch(packed[0]) == contents


def test_split_and_reassemble_unit():
    import random

    rng = random.Random(0)  # incompressible payload so chunking kicks in
    contents = {"data": "".join(chr(rng.randint(33, 0x2FFF)) for _ in range(9000))}
    chunks = split_contents(contents, 1024)
    assert chunks is not None and len(chunks) > 1
    r = ChunkReassembler()
    for ch in chunks[:-1]:
        done, _ = r.feed(5, ch)
        assert not done
    done, orig = r.feed(5, chunks[-1])
    assert done and orig == contents
    assert split_contents({"small": 1}, 1024) is None


def test_oversize_op_roundtrips_via_chunking():
    """A >1MB op would be nacked by alfred (MAX_OP_BYTES); the
    splitter must carry it through in chunks."""
    c1, c2, *_ = make_pair()
    big = "z" * 1_200_000
    chan(c1).set("big", big)
    c1.flush()
    assert chan(c2).get("big") == big
    assert chan(c1).get("big") == big
    assert not c1.runtime.is_dirty


def test_compressed_batch_roundtrips():
    c1, c2, *_ = make_pair()
    c1.runtime.compression_threshold = 64  # force compression
    for i in range(8):
        chan(c1).set(f"k{i}", "v" * 50)
    chan(c1, "s").insert_text(0, "hello compression")
    c1.flush()
    for i in range(8):
        assert chan(c2).get(f"k{i}") == "v" * 50
    assert chan(c2, "s").get_text() == "hello compression"
    assert not c1.runtime.is_dirty


def test_chunked_op_survives_reconnect():
    """Pending chunk pieces are synthetic: after a reconnect the
    original op resubmits (and re-chunks) whole."""
    server = LocalServer()
    fdriver = FaultInjectionDriver(LocalDriver(server))
    loader = Loader(fdriver, REGISTRY)
    c1 = loader.create_detached()
    ds = c1.runtime.create_datastore("default")
    ds.create_channel("m", MapFactory.type_name)
    doc = c1.attach()
    c2 = loader.resolve(doc)

    big = "w" * 1_000_000
    fdriver.drop_submits = True
    chan(c1).set("big", big)
    c1.flush()  # all chunks lost in flight
    fdriver.drop_submits = False
    fdriver.disconnect_all()
    c1.connect()
    c2.connect()
    c1.flush()
    assert chan(c2).get("big") == big
    assert not c1.runtime.is_dirty


def test_blob_create_fetch_and_gc():
    c1, c2, loader, server, doc = make_pair()
    payload = b"\x00\x01binary-blob" * 1000
    handle = c1.create_blob(payload)
    chan(c1).set("attachment", handle)
    c1.flush()

    # The other replica sees the handle and fetches out-of-band.
    h2 = chan(c2).get("attachment")
    assert c2.get_blob(h2) == payload
    assert c1.get_blob(handle) == payload

    # GC: referenced while the handle is reachable; swept after the
    # reference is dropped.
    gc = c1.runtime.attach_gc(sweep_grace=0)
    referenced, _ = gc.collect()
    blob_node = handle["url"]
    assert blob_node in referenced
    chan(c1).delete("attachment")
    c1.flush()
    deleted = gc.sweep()
    assert blob_node in deleted
    assert not c1.runtime.blobs.attached


def test_batch_atomicity_with_boxcar():
    """Boxcarred batches still apply atomically on receivers."""
    c1, c2, *_ = make_pair()
    seen = []
    c2.runtime.on("op", lambda m, local: seen.append(m.sequence_number))
    for i in range(5):
        chan(c1).set(f"x{i}", i)
    c1.flush()
    for i in range(5):
        assert chan(c2).get(f"x{i}") == i


def test_chunk_reassembler_restart_drops_stale_buffer():
    """ADVICE r2 (low): a sender that dies mid-chunk-stream and
    restarts with the same client id begins at chunk 0 again — the
    stale partial must be discarded, not crash every replica."""
    from fluidframework_tpu.runtime.op_lifecycle import (
        ChunkReassembler, split_serialized,
    )
    import json

    import hashlib

    incompressible = "".join(
        hashlib.sha256(str(i).encode()).hexdigest() for i in range(64)
    )
    blob = json.dumps({"payload": incompressible})
    chunks = split_serialized(blob, 600)
    assert chunks and len(chunks) >= 3
    r = ChunkReassembler()
    # Feed a partial stream, then "restart": fresh chunk 0 replaces it.
    r.feed(7, chunks[0])
    r.feed(7, chunks[1])
    out = None
    for c in chunks:
        complete, out = r.feed(7, c)
    assert complete and json.loads(json.dumps(out)) == json.loads(blob)
    # An orphan mid-stream chunk (no preceding 0) is ignored, not raised.
    complete, out = r.feed(9, chunks[2])
    assert not complete and out is None
    # ...and a subsequent clean stream still works.
    for c in chunks:
        complete, out = r.feed(9, c)
    assert complete


def test_approx_wire_size_is_conservative_fuzz():
    """approx_wire_size must NEVER under-estimate json.dumps' actual
    byte count (the outbox uses it to SKIP serialization when safely
    under the compression/chunking thresholds) — including json's
    2-byte ', '/': ' separators on list/dict-heavy payloads."""
    import random

    from fluidframework_tpu.runtime.op_lifecycle import (
        _dumps,
        approx_wire_size,
    )

    rng = random.Random(11)

    def gen(depth=0):
        r = rng.random()
        if depth > 3 or r < 0.25:
            return rng.choice([
                None, True, False, rng.randint(-10**9, 10**9),
                rng.random(),
                "".join(rng.choice("ab\x01é\\\" ") for _ in
                        range(rng.randint(0, 8))),
            ])
        if r < 0.6:
            return [gen(depth + 1) for _ in range(rng.randint(0, 6))]
        return {
            rng.choice([f"key{j}", f"k\x01{j}", f"clé{j}", f"键{j}"]):
                gen(depth + 1)
            for j in range(rng.randint(0, 5))
        }

    for _ in range(300):
        payload = gen()
        bound = approx_wire_size(payload, 1 << 30)
        if bound < 0:
            continue  # unboundable: caller serializes exactly
        actual = len(_dumps(payload))
        assert bound >= actual, (payload, bound, actual)
    # The advisor's exact repros.
    for payload in ([""] * 20, ["\x01"] * 5):
        assert approx_wire_size(payload, 1 << 30) >= len(_dumps(payload))
