"""Op lifecycle (compression, chunking, boxcar) + attachment blobs.

Reference: opCompressor.ts:20, opSplitter.ts:22, pendingBoxcar.ts,
blobManager.ts:149. The service nacks ops over 768KB, so a >1MB op
only round-trips if the splitter kicks in.
"""

from __future__ import annotations

import pytest

from fluidframework_tpu.dds import MapFactory, StringFactory
from fluidframework_tpu.drivers import FaultInjectionDriver, LocalDriver
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.runtime import ChannelRegistry
from fluidframework_tpu.runtime.gc import make_handle
from fluidframework_tpu.runtime.op_lifecycle import (
    ChunkReassembler,
    compress_batch,
    decompress_batch,
    split_contents,
)
from fluidframework_tpu.server import LocalServer

REGISTRY = ChannelRegistry([MapFactory(), StringFactory()])


def make_pair():
    server = LocalServer()
    loader = Loader(LocalDriver(server), REGISTRY)
    c1 = loader.create_detached()
    ds = c1.runtime.create_datastore("default")
    ds.create_channel("m", MapFactory.type_name)
    ds.create_channel("s", StringFactory.type_name)
    doc = c1.attach()
    c2 = loader.resolve(doc)
    return c1, c2, loader, server, doc


def chan(c, cid="m"):
    return c.runtime.get_datastore("default").get_channel(cid)


def test_compress_roundtrip_unit():
    contents = [{"a": 1}, {"b": [1, 2, 3]}, {"c": "x" * 100}]
    packed = compress_batch(contents)
    assert len(packed) == 3
    assert "packedContents" in packed[0]
    assert packed[1] == {"placeholder": True}
    assert decompress_batch(packed[0]) == contents


def test_split_and_reassemble_unit():
    import random

    rng = random.Random(0)  # incompressible payload so chunking kicks in
    contents = {"data": "".join(chr(rng.randint(33, 0x2FFF)) for _ in range(9000))}
    chunks = split_contents(contents, 1024)
    assert chunks is not None and len(chunks) > 1
    r = ChunkReassembler()
    for ch in chunks[:-1]:
        done, _ = r.feed(5, ch)
        assert not done
    done, orig = r.feed(5, chunks[-1])
    assert done and orig == contents
    assert split_contents({"small": 1}, 1024) is None


def test_oversize_op_roundtrips_via_chunking():
    """A >1MB op would be nacked by alfred (MAX_OP_BYTES); the
    splitter must carry it through in chunks."""
    c1, c2, *_ = make_pair()
    big = "z" * 1_200_000
    chan(c1).set("big", big)
    c1.flush()
    assert chan(c2).get("big") == big
    assert chan(c1).get("big") == big
    assert not c1.runtime.is_dirty


def test_compressed_batch_roundtrips():
    c1, c2, *_ = make_pair()
    c1.runtime.compression_threshold = 64  # force compression
    for i in range(8):
        chan(c1).set(f"k{i}", "v" * 50)
    chan(c1, "s").insert_text(0, "hello compression")
    c1.flush()
    for i in range(8):
        assert chan(c2).get(f"k{i}") == "v" * 50
    assert chan(c2, "s").get_text() == "hello compression"
    assert not c1.runtime.is_dirty


def test_chunked_op_survives_reconnect():
    """Pending chunk pieces are synthetic: after a reconnect the
    original op resubmits (and re-chunks) whole."""
    server = LocalServer()
    fdriver = FaultInjectionDriver(LocalDriver(server))
    loader = Loader(fdriver, REGISTRY)
    c1 = loader.create_detached()
    ds = c1.runtime.create_datastore("default")
    ds.create_channel("m", MapFactory.type_name)
    doc = c1.attach()
    c2 = loader.resolve(doc)

    big = "w" * 1_000_000
    fdriver.drop_submits = True
    chan(c1).set("big", big)
    c1.flush()  # all chunks lost in flight
    fdriver.drop_submits = False
    fdriver.disconnect_all()
    c1.connect()
    c2.connect()
    c1.flush()
    assert chan(c2).get("big") == big
    assert not c1.runtime.is_dirty


def test_blob_create_fetch_and_gc():
    c1, c2, loader, server, doc = make_pair()
    payload = b"\x00\x01binary-blob" * 1000
    handle = c1.create_blob(payload)
    chan(c1).set("attachment", handle)
    c1.flush()

    # The other replica sees the handle and fetches out-of-band.
    h2 = chan(c2).get("attachment")
    assert c2.get_blob(h2) == payload
    assert c1.get_blob(handle) == payload

    # GC: referenced while the handle is reachable; swept after the
    # reference is dropped.
    gc = c1.runtime.attach_gc(sweep_grace=0)
    referenced, _ = gc.collect()
    blob_node = handle["url"]
    assert blob_node in referenced
    chan(c1).delete("attachment")
    c1.flush()
    deleted = gc.sweep()
    assert blob_node in deleted
    assert not c1.runtime.blobs.attached


def test_batch_atomicity_with_boxcar():
    """Boxcarred batches still apply atomically on receivers."""
    c1, c2, *_ = make_pair()
    seen = []
    c2.runtime.on("op", lambda m, local: seen.append(m.sequence_number))
    for i in range(5):
        chan(c1).set(f"x{i}", i)
    c1.flush()
    for i in range(5):
        assert chan(c2).get(f"x{i}") == i


def test_chunk_reassembler_restart_drops_stale_buffer():
    """ADVICE r2 (low): a sender that dies mid-chunk-stream and
    restarts with the same client id begins at chunk 0 again — the
    stale partial must be discarded, not crash every replica."""
    from fluidframework_tpu.runtime.op_lifecycle import (
        ChunkReassembler, split_serialized,
    )
    import json

    import hashlib

    incompressible = "".join(
        hashlib.sha256(str(i).encode()).hexdigest() for i in range(64)
    )
    blob = json.dumps({"payload": incompressible})
    chunks = split_serialized(blob, 600)
    assert chunks and len(chunks) >= 3
    r = ChunkReassembler()
    # Feed a partial stream, then "restart": fresh chunk 0 replaces it.
    r.feed(7, chunks[0])
    r.feed(7, chunks[1])
    out = None
    for c in chunks:
        complete, out = r.feed(7, c)
    assert complete and json.loads(json.dumps(out)) == json.loads(blob)
    # An orphan mid-stream chunk (no preceding 0) is ignored, not raised.
    complete, out = r.feed(9, chunks[2])
    assert not complete and out is None
    # ...and a subsequent clean stream still works.
    for c in chunks:
        complete, out = r.feed(9, c)
    assert complete


def test_approx_wire_size_is_conservative_fuzz():
    """approx_wire_size must NEVER under-estimate json.dumps' actual
    byte count (the outbox uses it to SKIP serialization when safely
    under the compression/chunking thresholds) — including json's
    2-byte ', '/': ' separators on list/dict-heavy payloads."""
    import random

    from fluidframework_tpu.runtime.op_lifecycle import (
        _dumps,
        approx_wire_size,
    )

    rng = random.Random(11)

    def gen(depth=0):
        r = rng.random()
        if depth > 3 or r < 0.25:
            return rng.choice([
                None, True, False, rng.randint(-10**9, 10**9),
                rng.random(),
                "".join(rng.choice("ab\x01é\\\" ") for _ in
                        range(rng.randint(0, 8))),
            ])
        if r < 0.6:
            return [gen(depth + 1) for _ in range(rng.randint(0, 6))]
        return {
            rng.choice([f"key{j}", f"k\x01{j}", f"clé{j}", f"键{j}"]):
                gen(depth + 1)
            for j in range(rng.randint(0, 5))
        }

    for _ in range(300):
        payload = gen()
        bound = approx_wire_size(payload, 1 << 30)
        if bound < 0:
            continue  # unboundable: caller serializes exactly
        actual = len(_dumps(payload))
        assert bound >= actual, (payload, bound, actual)
    # The advisor's exact repros.
    for payload in ([""] * 20, ["\x01"] * 5):
        assert approx_wire_size(payload, 1 << 30) >= len(_dumps(payload))


# ---------------------------------------------------------------------------
# trace continuity (ISSUE 9): stage stamps survive restarts and
# fenced handoffs; restarted consumers never re-stamp
# ---------------------------------------------------------------------------


def test_trace_stamps_survive_localserver_restart_without_restamp():
    """The `trace_stage_once` contract, in-proc: a restarted server's
    scriptorium replays the shared deltas log through `_apply`, whose
    messages already carry their original "durable" stamp — the replay
    must neither duplicate the stage nor move its timestamp."""
    c1, c2, loader, server, doc = make_pair()
    chan(c1).set("k", "v")
    c1.flush()
    before = {
        m.sequence_number: list(m.traces)
        for m in server.ops_from(doc, 0)
    }
    assert before and all(
        [s for s, _ in tr].count("durable") == 1
        for tr in before.values() if any(s == "durable" for s, _ in tr)
    )
    server2 = LocalServer(
        log=server.log, storage=server.storage,
        checkpoints=server.checkpoints(),
    )
    server2.process_all()
    for m in server2.ops_from(doc, 0):
        stages = [s for s, _ in m.traces]
        assert stages.count("durable") <= 1, (
            f"restart re-stamped seq={m.sequence_number}: {m.traces}"
        )
        assert m.traces == before[m.sequence_number], (
            f"restart moved stamps for seq={m.sequence_number}"
        )


def test_wire_trace_stamps_survive_fenced_handoff_on_elastic_fabric(
        tmp_path, monkeypatch):
    """Wire-trace continuity across a kill + fenced takeover on the
    elastic fabric: records stamped by the dead owner keep their exact
    "tr" bytes (the successor's recovery replays them SILENTLY — no
    re-emission, no re-stamp), the successor stamps only the missing
    tail, and per-doc seqs stay contiguous."""
    import time as _time

    from fluidframework_tpu.server.queue import FencedError as _Fenced
    from fluidframework_tpu.server.shard_fabric import (
        ShardRouter,
        ShardWorker,
    )

    monkeypatch.setenv("FLUID_TRACE_WIRE", "1")
    shared = str(tmp_path)
    router = ShardRouter(shared, 1, elastic=True)
    wa = ShardWorker(shared, "wA", n_partitions=1, ttl_s=1.0,
                     elastic=True)
    wa.heartbeat()
    wa.sweep()
    docs = [f"doc{i}" for i in range(3)]
    first = [{"kind": "join", "doc": d, "client": 1} for d in docs] + [
        {"kind": "op", "doc": d, "client": 1, "clientSeq": i + 1,
         "refSeq": 0, "contents": {"i": i}, "tr_sub": _time.time()}
        for d in docs for i in range(4)
    ]
    router.append(first)
    deadline = _time.time() + 30
    def merged():
        out = []
        for t in router.deltas_topics():
            out.extend(r for r in t.read_from(0)
                       if isinstance(r, dict) and r.get("kind") == "op")
        return out
    while _time.time() < deadline and len(merged()) < len(first):
        wa.step()
    pre = merged()
    assert len(pre) == len(first)
    for r in pre:
        tr = r.get("tr")
        assert isinstance(tr, dict) and "stamp" in tr, r
        if "sub" in tr:
            assert tr["sub"] <= tr["stamp"]
    before = {(r["doc"], r["seq"]): r["tr"] for r in pre}
    victim = next(iter(wa.roles.values()))
    old_fence, old_owner = victim.fence, victim.owner
    out_topic = victim.out_topic
    # "SIGKILL": wA stops stepping, never releases; its lease expires.
    second = [
        {"kind": "op", "doc": d, "client": 1, "clientSeq": i + 1,
         "refSeq": 0, "contents": {"i": i}, "tr_sub": _time.time()}
        for d in docs for i in range(4, 7)
    ]
    router.append(second)
    _time.sleep(1.2)  # wA's lease TTL lapses
    wb = ShardWorker(shared, "wB", n_partitions=1, ttl_s=5.0,
                     elastic=True)
    wb.heartbeat()
    expected = len(first) + len(second)
    deadline = _time.time() + 30
    while _time.time() < deadline and len(merged()) < expected:
        wb.step()
    post = merged()
    assert len(post) == expected
    per = {}
    for r in post:
        per.setdefault(r["doc"], []).append(r["seq"])
    for d, seqs in per.items():
        assert sorted(seqs) == list(range(1, len(seqs) + 1)), (d, seqs)
    # Every pre-kill record's stamps are byte-identical after the
    # handoff (the successor re-polls the shared topic; it must never
    # re-stamp what the dead owner produced).
    for key, tr in before.items():
        match = [r for r in post if (r["doc"], r["seq"]) == key]
        assert len(match) == 1
        assert match[0]["tr"] == tr, (key, match[0]["tr"], tr)
    # And the handoff was FENCED: the dead owner's write is rejected.
    with pytest.raises(_Fenced):
        out_topic.append_many(
            [{"kind": "op", "doc": "zombie", "seq": -1}],
            fence=old_fence, owner=old_owner,
        )
    wb.stop()
