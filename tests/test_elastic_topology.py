"""Elastic partition topology: hash-range leases with live split and
merge (`server.queue.RangeLeaseStore` + `server.shard_fabric` elastic
mode), and the storage fault matrix (ENOSPC / stalled fsync) with
graceful degradation.

The paper's routerlicious layer map is a farm of independent lambda
consumers behind a partitioned ordering log where capacity follows
load without a restart; these tests prove the reproduction's form of
that elasticity: a topology change is just another fault the
fenced-handoff machinery survives — the parent's final fenced
checkpoint seeds the children, the children's (fabric-scoped, strictly
higher) fences reject the pre-split owner, the exactly-once ``inOff``
scan closes the durable gap, and the merged per-doc stream never
duplicates or skips a sequence number while N changes mid-run. The
multi-process supervised form under seeded faults lives in
tests/test_chaos_recovery.py; the rebalance-cost guard in
bench_configs ``config8_rebalance``.
"""

from __future__ import annotations

import json
import os
import time

import pytest

from fluidframework_tpu.server.queue import (
    HASH_SPACE,
    FencedError,
    RangeLeaseStore,
    doc_hash,
    initial_topology,
    lease_table,
    merge_ranges,
    range_containing,
    range_for_doc,
    split_ranges,
)
from fluidframework_tpu.server.shard_fabric import (
    ShardRouter,
    ShardWorker,
    control_result,
    range_lease_name,
    ranged_role_class,
    request_topology_change,
)
from fluidframework_tpu.server.supervisor import (
    DeliRole,
    _topic_path,
    unwrap_ranged_state,
)


def _workload(docs, n_clients=1, ops=6, base=0):
    recs = []
    for doc in docs:
        if base == 0:
            for c in range(1, n_clients + 1):
                recs.append({"kind": "join", "doc": doc, "client": c})
        for i in range(base, base + ops):
            for c in range(1, n_clients + 1):
                recs.append({"kind": "op", "doc": doc, "client": c,
                             "clientSeq": i + 1, "refSeq": 0,
                             "contents": {"i": i}})
    return recs


def _merged_ops(router):
    out = []
    for t in router.deltas_topics():
        out.extend(r for r in t.read_from(0)
                   if isinstance(r, dict) and r.get("kind") == "op")
    return out


def _drain(workers, router, expected, deadline_s=45):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        moved = sum(w.step() for w in workers)
        if len(_merged_ops(router)) >= expected and moved == 0:
            return _merged_ops(router)
    raise AssertionError(
        f"drain timed out: {len(_merged_ops(router))}/{expected}"
    )


def _assert_exactly_once(ops, per_doc_expected=None):
    per = {}
    for r in ops:
        per.setdefault(r["doc"], []).append(r["seq"])
    for doc, seqs in per.items():
        assert sorted(seqs) == list(range(1, len(seqs) + 1)), (
            doc, sorted(seqs)
        )
        if per_doc_expected is not None:
            assert len(seqs) == per_doc_expected, (doc, len(seqs))
    return per


# ---------------------------------------------------------------------------
# topology record + math
# ---------------------------------------------------------------------------


def test_initial_topology_covers_ring_contiguously():
    for n in (1, 3, 4, 7):
        t = initial_topology(n)
        assert t["epoch"] == 1 and len(t["ranges"]) == n
        assert t["ranges"][0]["lo"] == 0
        assert t["ranges"][-1]["hi"] == HASH_SPACE
        for a, b in zip(t["ranges"], t["ranges"][1:]):
            assert a["hi"] == b["lo"]
        assert t["history"] == [e["rid"] for e in t["ranges"]]
    with pytest.raises(ValueError):
        initial_topology(0)


def test_split_and_merge_math_round_trip():
    t = initial_topology(4)
    rid = t["ranges"][1]["rid"]
    t2 = split_ranges(t, rid)
    assert len(t2["ranges"]) == 5
    kids = [e for e in t2["ranges"] if e["preds"] == [rid]]
    assert len(kids) == 2
    assert kids[0]["hi"] == kids[1]["lo"]  # adjacent halves
    # Children are epoch-tagged: a merge recreating the parent's exact
    # bounds must NOT inherit its topics/checkpoint key.
    t2["epoch"] += 1  # as commit_topology would
    t3 = merge_ranges(t2, kids[0]["rid"], kids[1]["rid"])
    merged = next(e for e in t3["ranges"] if len(e["preds"]) == 2)
    assert (merged["lo"], merged["hi"]) == (
        t["ranges"][1]["lo"], t["ranges"][1]["hi"]
    )
    assert merged["rid"] != rid
    # History only grows: every rid ever live stays readable.
    assert set(t["history"]) < set(t3["history"])
    with pytest.raises(ValueError):
        merge_ranges(t3, t3["ranges"][0]["rid"], t3["ranges"][-1]["rid"])
    with pytest.raises(ValueError):
        split_ranges(t, "no-such-range")


def test_range_containing_matches_doc_hash():
    t = split_ranges(initial_topology(3), initial_topology(3)[
        "ranges"][0]["rid"])
    for d in ("a", "b", "doc7", "… unicode ✓", ""):
        h = doc_hash(d)
        e = range_containing(t, h)
        assert e["lo"] <= h < e["hi"]
        assert range_for_doc(t, d) == e


def test_store_bootstrap_commit_cas(tmp_path):
    shared = str(tmp_path)
    s = RangeLeaseStore(shared, "w0")
    topo = s.ensure_topology(4)
    # Idempotent: the first bootstrap wins, later arguments ignored.
    assert RangeLeaseStore(shared, "w1").ensure_topology(8) == topo
    t2 = split_ranges(topo, topo["ranges"][0]["rid"])
    assert s.commit_topology(t2, expect_epoch=1)
    assert s.read_topology()["epoch"] == 2
    # Stale CAS: a concurrent committer must lose, not interleave.
    assert not s.commit_topology(t2, expect_epoch=1)
    assert s.read_topology()["epoch"] == 2


def test_fabric_fences_comparable_across_keys(tmp_path):
    """Range leases draw from ONE fabric-wide monotonic counter: a
    successor's fence is strictly greater than every fence any other
    range ever held — the property its bind on a predecessor's topics
    rests on."""
    s = RangeLeaseStore(str(tmp_path), "w0")
    fences = [s.leases.try_acquire(f"deli-r{i}") for i in range(5)]
    assert fences == [1, 2, 3, 4, 5]


# ---------------------------------------------------------------------------
# elastic routing
# ---------------------------------------------------------------------------


def test_router_routes_by_epoch_and_keeps_history_readable(tmp_path):
    shared = str(tmp_path)
    router = ShardRouter(shared, 2, elastic=True)
    recs = _workload(["a", "b", "doc7", "x1"], ops=2)
    counts = router.append(recs)
    assert sum(counts.values()) == len(recs)
    store = RangeLeaseStore(shared, "admin")
    topo = store.read_topology()
    # Commit a split of the first range behind the router's back: the
    # next append must adopt the new epoch and route to the children.
    t2 = split_ranges(topo, topo["ranges"][0]["rid"])
    assert store.commit_topology(t2, topo["epoch"])
    more = [{"kind": "op", "doc": d, "client": 1, "clientSeq": 3,
             "refSeq": 0, "contents": None}
            for d in ("a", "b", "doc7", "x1")]
    counts2 = router.append(more)
    live = {e["rid"] for e in router.topology["ranges"]}
    assert router.topology["epoch"] == topo["epoch"] + 1
    assert set(counts2) <= live
    # The retired parent's topic stays on the merged read surface.
    names = router.deltas_topic_names()
    assert len(names) == len(router.topology["history"])
    retired = topo["ranges"][0]["rid"]
    assert f"deltas-{retired}" in names


def test_merged_reader_per_range_cursors(tmp_path):
    """Records written under epoch E stay readable after E+1, and the
    reader never re-delivers across a topology change (per-range
    cursors, not re-reads from zero)."""
    shared = str(tmp_path)
    router = ShardRouter(shared, 2, elastic=True)
    reader = router.merged_reader()
    # Sequenced records appear on deltas topics; write some directly.
    topo = router.topology
    t0 = router._topic(topo["ranges"][0]["deltas"])
    t0.append_many([{"kind": "op", "doc": "a", "seq": 1}])
    got = reader.poll()
    assert [r["seq"] for r in got] == [1]
    assert reader.poll() == []  # cursor held: no re-delivery
    # Split; the old topic gains a late record AND a child topic opens.
    store = RangeLeaseStore(shared, "admin")
    t2 = split_ranges(topo, topo["ranges"][0]["rid"])
    assert store.commit_topology(t2, topo["epoch"])
    t0.append_many([{"kind": "op", "doc": "a", "seq": 2}])
    child = next(e for e in router.store.read_topology()["ranges"]
                 if e["preds"])
    router._topic(child["deltas"]).append_many(
        [{"kind": "op", "doc": "a", "seq": 3}]
    )
    got = reader.poll()
    assert sorted(r["seq"] for r in got) == [2, 3]


# ---------------------------------------------------------------------------
# live split / merge, in-proc workers (fast)
# ---------------------------------------------------------------------------


def test_live_split_exactly_once_and_pre_split_owner_rejected(tmp_path):
    shared = str(tmp_path)
    router = ShardRouter(shared, 2, elastic=True)
    w = ShardWorker(shared, "wA", n_partitions=2, ttl_s=5.0,
                    elastic=True)
    w.heartbeat()
    w.sweep()
    docs = [f"doc{i}" for i in range(6)]
    first = _workload(docs, ops=4)
    router.append(first)
    _drain((w,), router, len(first))

    victim = sorted(w.roles)[0]
    deltas = w.roles[victim].out_topic
    old_fence, old_owner = w.roles[victim].fence, w.roles[victim].owner
    cid = request_topology_change(shared, {"op": "split",
                                           "rid": victim})
    deadline = time.time() + 20
    while time.time() < deadline and control_result(shared, cid) is None:
        w.step()
    res = control_result(shared, cid)
    assert res and res.get("op") == "split", res
    assert w.topology["epoch"] == 2

    second = _workload(docs, ops=4, base=4)
    router.append(second)
    ops = _drain((w,), router, len(first) + len(second))
    _assert_exactly_once(ops, per_doc_expected=9)

    # The demonstrable half of the handoff: the pre-split owner's
    # append with its old fence is REJECTED (the children bound
    # strictly higher fabric-scoped fences on the parent's topic).
    with pytest.raises(FencedError):
        deltas.append_many(
            [{"kind": "op", "doc": "zombie", "seq": -1}],
            fence=old_fence, owner=old_owner,
        )


def test_live_merge_exactly_once(tmp_path):
    shared = str(tmp_path)
    router = ShardRouter(shared, 4, elastic=True)
    w = ShardWorker(shared, "wA", n_partitions=4, ttl_s=5.0,
                    elastic=True)
    w.heartbeat()
    w.sweep()
    docs = [f"doc{i}" for i in range(8)]
    first = _workload(docs, ops=3)
    router.append(first)
    _drain((w,), router, len(first))

    ranges = sorted(w.topology["ranges"], key=lambda e: e["lo"])
    cid = request_topology_change(shared, {
        "op": "merge", "rids": [ranges[0]["rid"], ranges[1]["rid"]],
    })
    deadline = time.time() + 20
    while time.time() < deadline and control_result(shared, cid) is None:
        w.step()
    res = control_result(shared, cid)
    assert res and res.get("op") == "merge", res
    assert w.topology["epoch"] == 2
    assert len(w.topology["ranges"]) == 3
    merged = next(e for e in w.topology["ranges"] if e["preds"])
    assert sorted(merged["preds"]) == sorted(
        [ranges[0]["rid"], ranges[1]["rid"]]
    )

    second = _workload(docs, ops=3, base=3)
    router.append(second)
    ops = _drain((w,), router, len(first) + len(second))
    _assert_exactly_once(ops, per_doc_expected=7)


def test_split_two_workers_balance_over_ranges(tmp_path):
    """After a split the range count rises and a peer picks up the new
    capacity: target_partitions follows the LIVE range set."""
    shared = str(tmp_path)
    router = ShardRouter(shared, 2, elastic=True)
    wa = ShardWorker(shared, "wA", n_partitions=2, ttl_s=1.0,
                     elastic=True)
    wb = ShardWorker(shared, "wB", n_partitions=2, ttl_s=1.0,
                     elastic=True)
    for w in (wa, wb):
        w.heartbeat()
        w.sweep()
    recs = _workload([f"doc{i}" for i in range(6)], ops=2)
    router.append(recs)
    _drain((wa, wb), router, len(recs))
    owner_map = {k: w.slot for w in (wa, wb) for k in w.roles}
    assert len(owner_map) == 2  # both ranges owned
    victim = sorted(owner_map)[0]
    cid = request_topology_change(shared, {"op": "split",
                                           "rid": victim})
    deadline = time.time() + 25
    while time.time() < deadline:
        wa.step()
        wb.step()
        wa.heartbeat()
        wb.heartbeat()
        done = control_result(shared, cid)
        total = len(wa.roles) + len(wb.roles)
        bound = all(r.fence is not None
                    for w in (wa, wb) for r in w.roles.values())
        if done and total == 3 and bound:
            break
    assert control_result(shared, cid)
    assert len(wa.roles) + len(wb.roles) == 3
    assert wa.topology["epoch"] == wb.topology["epoch"] == 2
    wa.stop()
    wb.stop()


def test_split_survivor_closes_uncheckpointed_gap(tmp_path):
    """A parent that CRASHED before its final checkpoint (durable
    outputs beyond — or entirely without — a checkpoint) still splits
    exactly-once: the children's fence bind + durable-prefix scan
    silently replays what already landed and emits only the rest."""
    shared = str(tmp_path)
    router = ShardRouter(shared, 1, elastic=True)
    store = RangeLeaseStore(shared, "admin")
    topo = store.read_topology()
    parent = topo["ranges"][0]
    docs = [f"doc{i}" for i in range(4)]
    recs = _workload(docs, ops=5)
    router.append(recs)

    # The parent sequences everything but NEVER checkpoints (huge
    # cadence, no graceful release): its deltas are durable, its
    # checkpoint is absent — the worst crash window.
    cls = ranged_role_class(DeliRole, parent, topo["epoch"])
    role = cls(shared, owner="doomed", ttl_s=3600.0,
               ckpt_interval_s=3600.0)
    for _ in range(50):
        role.step(idle_sleep=0)
    durable = [r for r in role.out_topic.read_from(0)
               if isinstance(r, dict) and r.get("kind") == "op"]
    assert len(durable) == len(recs)
    assert role.ckpt.load(role.name) is None  # truly uncheckpointed
    # "Crash": drop the role, commit the split as an operator would
    # (the owner is dead, so no final checkpoint lands).
    t2 = split_ranges(topo, parent["rid"])
    assert store.commit_topology(t2, topo["epoch"])
    del role

    w = ShardWorker(shared, "wB", n_partitions=1, ttl_s=5.0,
                    elastic=True)
    w.heartbeat()
    w.sweep()
    second = _workload(docs, ops=5, base=5)
    router.append(second)
    ops = _drain((w,), router, len(recs) + len(second))
    _assert_exactly_once(ops, per_doc_expected=11)
    w.stop()


def test_ranged_checkpoint_restorable_by_classic_frontends(tmp_path):
    """The ranged checkpoint envelope (docs + predecessor cursors)
    unwraps for every deli restore path — a fabric checkpoint is not a
    dead end for the classic roles."""
    env = {"__ranged__": 1,
           "docs": {"d": {"doc_id": "d", "seq": 3, "min_seq": 1,
                          "clients": {"1": {"ref_seq": 1,
                                            "client_seq": 2,
                                            "last_update": 0.0}}}},
           "preds": {"r-old": 17}}
    assert unwrap_ranged_state(env) == env["docs"]
    assert unwrap_ranged_state(env["docs"]) == env["docs"]
    assert unwrap_ranged_state(None) is None
    role = DeliRole(str(tmp_path), owner="w", ttl_s=3600.0)
    role.restore_state(env)
    assert role.sequencers["d"].seq == 3


# ---------------------------------------------------------------------------
# disk fault matrix (graceful degradation)
# ---------------------------------------------------------------------------


def test_enospc_backoff_degraded_then_recovers(tmp_path, monkeypatch):
    shared = str(tmp_path / "shared")
    spec = str(tmp_path / "fault.json")
    monkeypatch.setenv("FLUID_DISK_FAULT", spec)
    router = ShardRouter(shared, 1)
    w = ShardWorker(shared, "wA", n_partitions=1, ttl_s=5.0)
    w.heartbeat()
    w.sweep()
    recs = _workload(["solo"], ops=4)
    router.append(recs)
    _drain((w,), router, len(recs))
    role = w.roles[0]
    assert role.degraded is False

    # ENOSPC on: the next durable write enters bounded-retry backoff;
    # the degraded flag must surface in the role heartbeat while it
    # waits. Clear the fault from WITHIN the backoff (on_retry writes
    # the heartbeat before sleeping) by racing a short fault window.
    # (Feed BEFORE arming the fault — the in-proc router shares the
    # env, and ingress is not the surface under test.)
    router.append(_workload(["solo"], ops=2, base=4))
    with open(spec, "w") as f:
        json.dump({"mode": "enospc", "kinds": ["topic"]}, f)

    cleared = {"done": False}
    real_sleep = time.sleep

    def clearing_sleep(s):
        # First backoff sleep observed -> assert visibility, then lift
        # the fault so the SAME write retries through.
        if not cleared["done"] and os.path.exists(spec):
            hb = json.load(open(role._hb_path))
            assert hb["degraded"] is True
            assert role.degraded is True
            os.remove(spec)
            cleared["done"] = True
        real_sleep(min(s, 0.01))

    monkeypatch.setattr(time, "sleep", clearing_sleep)
    try:
        _drain((w,), router, len(recs) + 2)
    finally:
        monkeypatch.setattr(time, "sleep", real_sleep)
    assert cleared["done"], "backoff never engaged"
    assert role.degraded is False  # recovery clears the flag
    ops = _merged_ops(router)
    _assert_exactly_once(ops, per_doc_expected=7)


def test_enospc_hard_fail_after_budget(tmp_path, monkeypatch):
    """A storage fault outlasting the retry budget HARD-FAILS (the
    record was never acknowledged; the supervisor restart is the next
    line of defense) — degradation must not become silent masking."""
    import errno

    shared = str(tmp_path / "shared")
    spec = str(tmp_path / "fault.json")
    monkeypatch.setenv("FLUID_DISK_FAULT", spec)
    router = ShardRouter(shared, 1)
    w = ShardWorker(shared, "wA", n_partitions=1, ttl_s=5.0)
    w.heartbeat()
    w.sweep()
    router.append(_workload(["solo"], ops=2))
    with open(spec, "w") as f:
        json.dump({"mode": "enospc", "kinds": ["topic"]}, f)
    monkeypatch.setattr(time, "sleep", lambda s: None)  # fast budget
    with pytest.raises(OSError) as exc_info:
        deadline = time.time() + 30
        while time.time() < deadline:
            w.step()
    assert exc_info.value.errno == errno.ENOSPC


def test_stalled_fsync_slows_but_never_reorders(tmp_path, monkeypatch):
    shared = str(tmp_path / "shared")
    spec = str(tmp_path / "fault.json")
    monkeypatch.setenv("FLUID_DISK_FAULT", spec)
    with open(spec, "w") as f:
        json.dump({"mode": "stall", "stall_s": 0.05,
                   "kinds": ["topic", "checkpoint"]}, f)
    router = ShardRouter(shared, 1)
    w = ShardWorker(shared, "wA", n_partitions=1, ttl_s=5.0)
    w.heartbeat()
    w.sweep()
    recs = _workload(["solo"], ops=6)
    router.append(recs)
    ops = _drain((w,), router, len(recs))
    _assert_exactly_once(ops, per_doc_expected=7)


def test_supervisor_health_surfaces_degraded_role(tmp_path):
    """The degraded flag rides the role heartbeat into
    `ShardFabricSupervisor.health()` — a fresh degraded role flips the
    fabric to degraded; a stale file does not pin it there."""
    from fluidframework_tpu.server.shard_fabric import (
        ShardFabricSupervisor,
    )

    shared = str(tmp_path)
    sup = ShardFabricSupervisor(shared, n_workers=1, n_partitions=2)
    hb_dir = os.path.join(shared, "hb")
    os.makedirs(hb_dir, exist_ok=True)
    with open(os.path.join(hb_dir, "deli-p1.json"), "w") as f:
        json.dump({"t": time.time(), "degraded": True}, f)
    assert sup.degraded_partitions() == ["deli-p1"]
    assert sup.health()["status"] == "degraded"
    # Stale (older than the heartbeat timeout): ignored.
    with open(os.path.join(hb_dir, "deli-p1.json"), "w") as f:
        json.dump({"t": time.time() - 10 * sup.heartbeat_timeout_s,
                   "degraded": True}, f)
    assert sup.degraded_partitions() == []


def test_lease_table_reports_fence_and_expiry(tmp_path):
    """Satellite: readers can tell a stale pre-split owner from the
    live one by the FENCE, not just the owner string."""
    store = RangeLeaseStore(str(tmp_path), "wA")
    rid = store.ensure_topology(1)["ranges"][0]["rid"]
    f1 = store.leases.try_acquire(range_lease_name(rid))
    tab = lease_table(os.path.join(str(tmp_path), "leases"))
    info = tab[range_lease_name(rid)]
    assert info["owner"] == "wA" and info["fence"] == f1
    assert info["expires"] > time.time()


def test_absorbed_pred_cursors_retired_and_restart_exactly_once(tmp_path):
    """ROADMAP item-2 follow-up: once a split child has drained its
    parent to quiescence (and the parent is dead in the topology by
    construction), the parent's `inSrc` cursor drops out of NEW
    checkpoints — replaced by a `done_preds` tombstone — and a
    restarted successor skips re-absorption entirely while
    exactly-once still holds across the restart."""
    shared = str(tmp_path)
    router = ShardRouter(shared, 1, elastic=True)
    w = ShardWorker(shared, "wA", n_partitions=1, ttl_s=5.0,
                    elastic=True)
    w.heartbeat()
    w.sweep()
    docs = [f"doc{i}" for i in range(6)]
    first = _workload(docs, ops=4)
    router.append(first)
    _drain((w,), router, len(first))
    parent_rid = sorted(w.roles)[0]
    cid = request_topology_change(shared, {"op": "split",
                                           "rid": parent_rid})
    deadline = time.time() + 20
    while time.time() < deadline and control_result(shared, cid) is None:
        w.step()
    assert control_result(shared, cid)
    second = _workload(docs, ops=4, base=4)
    router.append(second)
    _drain((w,), router, len(first) + len(second))

    # Children hold the parent's cursor until the retirement grace
    # passes; shrink it and pump the (quiescent) preds.
    children = dict(w.roles)
    assert len(children) == 2
    for role in children.values():
        assert parent_rid in role._preds
        role.pred_retire_s = 0.05
    deadline = time.time() + 20
    while time.time() < deadline and not all(
        r._preds[parent_rid]["done"] for r in children.values()
    ):
        w.step()
        time.sleep(0.01)
    for role in children.values():
        assert role._preds[parent_rid]["done"]
        role.checkpoint()
        st = role.ckpt.load(role.name)["state"]["state"]
        assert st.get("preds") in ({}, None), st  # cursor DROPPED
        assert st["done_preds"] == [parent_rid]  # tombstone instead
        assert role.metrics.counter(
            "shard_pred_cursors_retired_total",
            **role._metric_labels()).value >= 1

    # Graceful handoff, then a fresh worker restores the tombstoned
    # checkpoints: no re-absorption, and the stream stays exactly-once
    # across the restart.
    w.stop()
    w2 = ShardWorker(shared, "wB", n_partitions=1, ttl_s=5.0,
                     elastic=True)
    w2.heartbeat()
    w2.sweep()
    third = _workload(docs, ops=4, base=8)
    router.append(third)
    ops = _drain((w2,), router,
                 len(first) + len(second) + len(third))
    _assert_exactly_once(ops, per_doc_expected=13)
    for role in w2.roles.values():
        p = role._preds.get(parent_rid)
        assert p is not None and p["done"], (
            "restart lost the retirement tombstone"
        )
    w2.stop()


# ---------------------------------------------------------------------------
# per-partition downstream stages on the elastic fabric (front-door PR)
# ---------------------------------------------------------------------------


def _merged_stage_ops(router, base):
    from fluidframework_tpu.server.columnar_log import make_topic

    out = []
    for name in router.stage_topic_names(base):
        t = make_topic(_topic_path(router.shared_dir, name),
                       router.log_format)
        out.extend(r for r in t.read_from(0)
                   if isinstance(r, dict) and r.get("kind") == "op")
    return out


def _drain_downstream(workers, router, expected, deadline_s=45):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        moved = sum(w.step() for w in workers)
        if (len(_merged_ops(router)) >= expected
                and len(_merged_stage_ops(router, "durable")) >= expected
                and len(_merged_stage_ops(router, "broadcast"))
                >= expected and moved == 0):
            return
    raise AssertionError(
        f"downstream drain timed out: deltas="
        f"{len(_merged_ops(router))} durable="
        f"{len(_merged_stage_ops(router, 'durable'))} of {expected}"
    )


def test_ranged_downstream_split_hands_legs_exactly_once(tmp_path):
    """The routerlicious shape: EVERY stage partitioned. A live split
    mid-stream must hand each range's durable/broadcast legs (and the
    scribe fold) to the successors exactly-once — the per-range
    predecessor absorption generalized beyond the deli."""
    shared = str(tmp_path)
    router = ShardRouter(shared, 2, elastic=True)
    w = ShardWorker(shared, "wA", n_partitions=2, ttl_s=5.0,
                    elastic=True, downstream="split")
    w.heartbeat()
    w.sweep()
    assert all(len(v) == 3 for v in w.down_roles.values())
    docs = [f"doc{i}" for i in range(6)]
    first = _workload(docs, ops=4)
    router.append(first)
    _drain_downstream((w,), router, len(first))

    victim = sorted(w.roles)[0]
    cid = request_topology_change(shared, {"op": "split",
                                           "rid": victim})
    deadline = time.time() + 20
    while time.time() < deadline and control_result(shared, cid) is None:
        w.step()
    assert control_result(shared, cid), "split never committed"
    second = _workload(docs, ops=4, base=4)
    router.append(second)
    expected = len(first) + len(second)
    _drain_downstream((w,), router, expected)

    deltas_ops = _merged_ops(router)
    _assert_exactly_once(deltas_ops, per_doc_expected=9)
    # Both downstream legs carry exactly the sequenced stream —
    # across the split, via their own pred absorption.
    from fluidframework_tpu.server.supervisor import canonical_record

    want = sorted(
        (json.dumps(canonical_record(r), sort_keys=True)
         for r in deltas_ops)
    )
    for base in ("durable", "broadcast"):
        got_ops = _merged_stage_ops(router, base)
        _assert_exactly_once(got_ops, per_doc_expected=9)
        got = sorted(
            (json.dumps(canonical_record(r), sort_keys=True)
             for r in got_ops)
        )
        assert got == want, f"{base} leg diverged from deltas"
    # The out-topic-less ranged stage: scribe folds survived the
    # split too (absorbed silently from the pred deltas tail).
    total = 0
    for roles in w.down_roles.values():
        scribe = next(r for r in roles if r.role_base == "scribe")
        total += sum(int(st["count"]) for st in scribe.docs.values())
    assert total == len(deltas_ops)
    w.stop()


def test_columnar_pred_drain_keeps_encode_columns_fast_path(tmp_path):
    """ROADMAP item-1 follow-up b: a RANGED kernel deli's steady-state
    pred drain tags inSrc via the frame-level src column instead of
    falling back to dict emission — the encode_columns fast path stays
    engaged through an elastic split, differentially checked against
    the dict-path (json log) oracle."""
    from fluidframework_tpu.server.supervisor import canonical_record
    from fluidframework_tpu.utils.metrics import get_registry

    def run(log_format, impl, root):
        shared = os.path.join(str(tmp_path), root)
        router = ShardRouter(shared, 1, log_format, elastic=True)
        w = ShardWorker(shared, "wA", n_partitions=1, ttl_s=5.0,
                        elastic=True, deli_impl=impl,
                        log_format=log_format)
        w.heartbeat()
        w.sweep()
        docs = [f"doc{i}" for i in range(4)]
        first = _workload(docs, ops=3)
        router.append(first)
        _drain((w,), router, len(first))
        parent_rid = sorted(w.roles)[0]
        parent_raw = w.roles[parent_rid].in_topic
        cid = request_topology_change(shared, {"op": "split",
                                               "rid": parent_rid})
        deadline = time.time() + 20
        while time.time() < deadline \
                and control_result(shared, cid) is None:
            w.step()
        assert control_result(shared, cid)
        # Recovery-time absorption settles first, so the NEXT batch
        # exercises the STEADY-STATE pred drain (the src fast path).
        for _ in range(5):
            w.step()
        before = get_registry().counter(
            "codec_encode_columns_total", codec="columnar"
        ).value
        # A stale router lands records on the RETIRED parent topic:
        # the children's pred drains must absorb them.
        stale = _workload(docs, ops=3, base=3)
        parent_raw.append_many(stale)
        expected = len(first) + len(stale)
        ops = _drain((w,), router, expected)
        _assert_exactly_once(ops, per_doc_expected=7)
        after = get_registry().counter(
            "codec_encode_columns_total", codec="columnar"
        ).value
        # Pred-drained records must carry the inSrc tag either way.
        drained = [r for r in _merged_ops(router)
                   if r.get("inSrc") == parent_rid]
        assert drained, "no pred-drained records tagged inSrc"
        w.stop()
        return (sorted(json.dumps(canonical_record(r), sort_keys=True)
                       for r in ops), after - before, len(drained))

    cols, cols_delta, n_src = run("columnar", "kernel", "cols")
    oracle, _j, n_dict = run("json", "scalar", "oracle")
    # Differential: the src-tagged columnar drain reproduces the
    # dict-path oracle bit-identically (canonical form), tags the
    # same record set, and actually ran through encode_columns.
    assert cols == oracle
    assert n_src == n_dict
    assert cols_delta > 0, (
        "pred drain fell back to dict emission (encode_columns "
        "never engaged)"
    )


def test_merge_then_split_live_pred_consumer_deposed_no_dup(tmp_path):
    """The merge→split double-emission hole (caught by the front-door
    storm gate under full-suite contention): after A+B merge into M
    and M splits into C+D, the still-LIVE M may be mid-drain of A's
    tail when C recovers. C must depose M on EVERY pred topic —
    including M's own output — BEFORE scanning any of them; otherwise
    M lands more A-records after C's scan and the same record exists
    in durable-M and durable-C (a downstream-leg duplicate)."""
    from fluidframework_tpu.server.columnar_log import make_topic
    from fluidframework_tpu.server.supervisor import ScriptoriumRole

    shared = str(tmp_path)
    store = RangeLeaseStore(shared, "test")
    topo1 = store.ensure_topology(2)
    r1 = sorted(topo1["ranges"], key=lambda e: e["lo"])
    a, b = r1[0], r1[1]
    # Commit the merge (A+B -> M), then the split (M -> C, D).
    topo2 = merge_ranges(topo1, a["rid"], b["rid"])
    assert store.commit_topology(topo2, topo1["epoch"])
    topo2 = store.read_topology()
    m = topo2["ranges"][0]
    topo3 = split_ranges(topo2, m["rid"])
    # M's downstream consumer, built against epoch 2, still live.
    role_m = ranged_role_class(ScriptoriumRole, m, 2)(
        shared, "owner-m", ttl_s=30.0
    )
    # A's sequenced stream: ops for a doc in C's (lower) half.
    lo_doc = next(f"doc{i}" for i in range(64)
                  if doc_hash(f"doc{i}") < split_ranges(
                      topo2, m["rid"])["ranges"][0]["hi"])
    deltas_a = make_topic(_topic_path(shared, f"deltas-{a['rid']}"))
    mk = lambda s: {"kind": "op", "doc": lo_doc, "seq": s, "msn": s,
                    "client": 1, "clientSeq": s, "refSeq": 0,
                    "type": "op", "contents": {"s": s}, "inOff": s - 1}
    deltas_a.append_many([mk(1), mk(2)])
    role_m.step()           # M drains A's first two records
    role_m.checkpoint()     # cursors land; C will seed from this
    # More A-tail arrives (a stale writer); M has NOT drained it yet.
    deltas_a.append_many([mk(3), mk(4)])
    assert store.commit_topology(topo3, topo2["epoch"])
    c_entry = sorted(store.read_topology()["ranges"],
                     key=lambda e: e["lo"])[0]
    assert m["rid"] in c_entry["preds"]
    role_c = ranged_role_class(ScriptoriumRole, c_entry, 3)(
        shared, "owner-c", ttl_s=30.0
    )
    # Interleave the race at its exact window: the still-live M tries
    # to drain the same A-tail into ITS topic right after C absorbed
    # pred A but BEFORE C's absorb pass reaches pred M. Without the
    # up-front all-preds fence bind, M's append lands (C already
    # re-emitted those records) — the duplicate; with it, M is
    # deposed before C's first scan.
    raced = []
    orig_absorb = role_c._absorb_pred

    def hooked(prid):
        orig_absorb(prid)
        if prid == a["rid"]:
            try:
                role_m.step()
            except (FencedError, SystemExit) as exc:
                raced.append(type(exc).__name__)

    role_c._absorb_pred = hooked
    role_c.step()
    assert raced, "the live pred consumer was never deposed"
    ops = []
    for rid in store.read_topology()["history"]:
        t = make_topic(_topic_path(shared, f"durable-{rid}"))
        ops.extend(r for r in t.read_from(0)
                   if isinstance(r, dict) and r.get("kind") == "op")
    keys = [(r["doc"], r["seq"]) for r in ops]
    assert sorted(keys) == [(lo_doc, s) for s in (1, 2, 3, 4)], keys
