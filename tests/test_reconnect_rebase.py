"""Reconnect semantics for merge-tree DDSes: rebase-on-resubmit
(reference Client.regeneratePendingOp, client.ts:917), catch-up ack of
ops sequenced under the old identity, and no-loss delivery around the
connect window.
"""

from __future__ import annotations

from fluidframework_tpu.dds import MapFactory, StringFactory
from fluidframework_tpu.runtime import ChannelRegistry, ContainerRuntime
from fluidframework_tpu.server import LocalServer

REGISTRY = ChannelRegistry([StringFactory(), MapFactory()])


def mk(server, cid=None, doc="doc"):
    rt = ContainerRuntime(REGISTRY)
    ds = rt.create_datastore("default")
    ds.create_channel("s", StringFactory.type_name)
    ds.create_channel("m", MapFactory.type_name)
    rt.connect(server.connect(doc, cid))
    return rt


def C(rt, c="s"):
    return rt.get_datastore("default").get_channel(c)


def test_pending_insert_rebases_on_reconnect():
    """A pending insert whose position shifted due to remote edits must
    resubmit at the rebased position."""
    server = LocalServer(deferred=True)
    a_rt, b_rt = mk(server, 1), mk(server, 2)
    server.process_all()
    a, b = C(a_rt), C(b_rt)
    a.insert_text(0, "hello")
    a_rt.flush()
    server.process_all()

    # a inserts '!' at the end (pos 5), but is disconnected before it
    # sequences; meanwhile b prepends 'XXX'.
    a.insert_text(5, "!")
    a_rt.disconnect()
    server.process_all()
    b.insert_text(0, "XXX")
    b_rt.flush()
    server.process_all()

    a_rt.connect(server.connect("doc"))
    server.process_all()
    a_rt.flush()
    server.process_all()
    assert a.get_text() == b.get_text() == "XXXhello!"


def test_pending_remove_split_by_remote_insert_rebases():
    """A pending remove whose target range was split by a remote insert
    regenerates as per-segment ops and still converges."""
    server = LocalServer(deferred=True)
    a_rt, b_rt = mk(server, 1), mk(server, 2)
    server.process_all()
    a, b = C(a_rt), C(b_rt)
    a.insert_text(0, "abcdef")
    a_rt.flush()
    server.process_all()

    a.remove_text(1, 5)  # pending removal of 'bcde'
    a_rt.disconnect()
    server.process_all()
    b.insert_text(3, "XY")  # lands inside the pending-removed range
    b_rt.flush()
    server.process_all()
    assert b.get_text() == "abcXYdef"

    a_rt.connect(server.connect("doc"))
    server.process_all()
    a_rt.flush()
    server.process_all()
    texts = {a.get_text(), b.get_text()}
    assert texts == {"aXYf"}, texts


def test_op_sequenced_before_disconnect_not_double_applied():
    """An op that DID sequence under the old client id must be matched
    by catch-up as our own (acked), not applied remotely + resubmitted."""
    server = LocalServer()
    a_rt, b_rt = mk(server, 1), mk(server, 2)
    a, b = C(a_rt), C(b_rt)
    a.insert_text(0, "hello")
    a_rt.flush()

    # Submit; server sequences it, but simulate the echo being lost by
    # detaching the listener before flush.
    sock = a_rt.connection
    a.insert_text(5, "!")
    sock._listener = None  # drop live delivery (connection dying)
    a_rt.flush()  # server sequences the op; echo goes to the backlog
    sock.connected = False  # now the connection is really gone
    a_rt.connection = None

    a_rt.connect(server.connect("doc"))
    a_rt.flush()
    assert a.get_text() == b.get_text() == "hello!"
    assert not a_rt.is_dirty


def test_no_ops_lost_between_connect_and_listener():
    """Ops sequenced between server.connect() and runtime.connect()
    must be buffered, not dropped."""
    server = LocalServer()
    a_rt = mk(server, 1)
    sock_b = server.connect("doc", 2)  # socket exists, no runtime yet
    C(a_rt, "m").set("k", "v")
    a_rt.flush()  # sequenced while sock_b has no listener

    b_rt = ContainerRuntime(REGISTRY)
    ds = b_rt.create_datastore("default")
    ds.create_channel("s", StringFactory.type_name)
    ds.create_channel("m", MapFactory.type_name)
    b_rt.connect(sock_b)
    assert C(b_rt, "m").get("k") == "v"
    assert b_rt.current_seq == a_rt.current_seq


def test_duplicate_client_id_rejected():
    server = LocalServer()
    server.connect("doc", 7)
    try:
        server.connect("doc", 7)
    except ValueError as e:
        assert "already connected" in str(e)
    else:
        raise AssertionError("expected ValueError")


def test_malformed_propose_ignored():
    from fluidframework_tpu.protocol.messages import MessageType

    server = LocalServer()
    a_rt, b_rt = mk(server, 1), mk(server, 2)
    a_rt.submit_system_message(MessageType.PROPOSE, "junk")
    a_rt.submit_system_message(MessageType.PROPOSE, {"k": 1})
    # Stream keeps flowing for everyone.
    C(a_rt, "m").set("after", True)
    a_rt.flush()
    assert C(b_rt, "m").get("after") is True


def test_pending_remove_overlapped_by_sequenced_remote_remove():
    """A pending remove whose segments were ALSO removed by a sequenced
    remote remove must not cite them on resubmit (they are tombstones
    for every future perspective)."""
    server = LocalServer(deferred=True)
    a_rt, b_rt = mk(server, 1), mk(server, 2)
    server.process_all()
    a, b = C(a_rt), C(b_rt)
    a.insert_text(0, "abcdef")
    a_rt.flush()
    server.process_all()

    a.remove_text(1, 4)  # pending remove of 'bcd'
    a_rt.disconnect()
    server.process_all()
    b.remove_text(1, 4)  # same range, sequences first
    b_rt.flush()
    server.process_all()
    assert b.get_text() == "aef"

    a_rt.connect(server.connect("doc"))
    server.process_all()
    a_rt.flush()
    server.process_all()
    assert a.get_text() == b.get_text() == "aef"


def test_matrix_set_cell_rebases_on_reconnect():
    """A pending setCell survives a remote row insert: it re-targets by
    handle, not by stale position."""
    from fluidframework_tpu.dds import MatrixFactory

    reg = ChannelRegistry([MatrixFactory()])
    server = LocalServer(deferred=True)

    def mk_m(cid=None):
        rt = ContainerRuntime(reg)
        rt.create_datastore("default").create_channel(
            "x", MatrixFactory.type_name
        )
        rt.connect(server.connect("doc-m", cid))
        return rt

    a_rt, b_rt = mk_m(1), mk_m(2)
    server.process_all()
    a = a_rt.get_datastore("default").get_channel("x")
    b = b_rt.get_datastore("default").get_channel("x")
    a.insert_rows(0, 2)
    a.insert_cols(0, 1)
    a_rt.flush()
    server.process_all()

    a.set_cell(1, 0, "v")  # pending
    a_rt.disconnect()
    server.process_all()
    b.insert_rows(0, 1)  # shifts a's target row to index 2
    b_rt.flush()
    server.process_all()

    a_rt.connect(server.connect("doc-m"))
    server.process_all()
    a_rt.flush()
    server.process_all()
    assert a.to_dense() == b.to_dense()
    assert b.get_cell(2, 0) == "v"


def test_matrix_structural_op_rebases_on_reconnect():
    from fluidframework_tpu.dds import MatrixFactory

    reg = ChannelRegistry([MatrixFactory()])
    server = LocalServer(deferred=True)

    def mk_m(cid=None):
        rt = ContainerRuntime(reg)
        rt.create_datastore("default").create_channel(
            "x", MatrixFactory.type_name
        )
        rt.connect(server.connect("doc-n", cid))
        return rt

    a_rt, b_rt = mk_m(1), mk_m(2)
    server.process_all()
    a = a_rt.get_datastore("default").get_channel("x")
    b = b_rt.get_datastore("default").get_channel("x")
    a.insert_rows(0, 3)
    a.insert_cols(0, 1)
    a_rt.flush()
    server.process_all()
    a.set_cell(2, 0, "anchor")
    a_rt.flush()
    server.process_all()

    a.remove_rows(0, 1)  # pending structural op
    a_rt.disconnect()
    server.process_all()
    b.insert_rows(0, 2)
    b_rt.flush()
    server.process_all()

    a_rt.connect(server.connect("doc-n"))
    server.process_all()
    a_rt.flush()
    server.process_all()
    assert a.to_dense() == b.to_dense()
    assert a.row_count == 4  # 3 + 2 - 1
    assert b.get_cell(3, 0) == "anchor"


def test_protocol_state_rides_summary():
    """A summary-booted client sees pre-summary quorum membership (no
    duplicate summarizer election)."""
    from fluidframework_tpu.runtime.summary import SummaryTree
    from fluidframework_tpu.runtime.summary_manager import SummarizerElection

    server = LocalServer()
    a_rt = mk(server, 1)
    C(a_rt, "m").set("x", 1)
    a_rt.flush()
    wire = a_rt.summarize().to_json()

    cold = ContainerRuntime(REGISTRY)
    cold.load(SummaryTree.from_json(wire))
    cold.connect(server.connect("doc", 9))
    assert 1 in cold.protocol.quorum  # pre-summary join restored
    assert not SummarizerElection(cold).is_elected  # client 1 is older
    assert SummarizerElection(a_rt).is_elected
