"""Full lambda-pipeline integration tests.

The key property (mirroring how the reference's LocalOrderer runs the
*production* lambdas in-proc, localOrderer.ts:95): the same
ContainerRuntime + DDS scenarios that run against LocalOrderingService
run unchanged against the full alfred → deli → scriptorium/broadcaster/
scribe pipeline — plus pipeline-only behavior: summary ack/nack through
scribe, quorum proposals, lambda crash/checkpoint/restore.
"""

from __future__ import annotations

import pytest

from fluidframework_tpu.dds import MapFactory, StringFactory
from fluidframework_tpu.protocol.messages import MessageType
from fluidframework_tpu.runtime import ChannelRegistry, ContainerRuntime
from fluidframework_tpu.runtime.summary import SummaryTree
from fluidframework_tpu.runtime.summary_manager import SummaryManager
from fluidframework_tpu.server import LocalServer

REGISTRY = ChannelRegistry([MapFactory(), StringFactory()])


def connect_runtime(server, doc="doc", client_id=None, channels=(("s", StringFactory.type_name),)):
    rt = ContainerRuntime(REGISTRY)
    ds = rt.create_datastore("default")
    for cid, tname in channels:
        ds.create_channel(cid, tname)
    rt.connect(server.connect(doc, client_id))
    return rt


def chan(rt, cid="s"):
    return rt.get_datastore("default").get_channel(cid)


def test_collab_over_full_pipeline():
    server = LocalServer()
    a_rt = connect_runtime(server, client_id=1)
    b_rt = connect_runtime(server, client_id=2)
    a, b = chan(a_rt), chan(b_rt)
    a.insert_text(0, "hello pipeline")
    a_rt.flush()
    b.insert_text(0, ">> ")
    b_rt.flush()
    assert a.get_text() == b.get_text()
    assert ">> " in a.get_text() and "hello pipeline" in a.get_text()
    # durable op log is serving
    assert server.ops_from("doc", 0)[-1].sequence_number >= 2


def test_summary_flow_with_scribe_ack():
    server = LocalServer()
    rt1 = connect_runtime(server, client_id=1)
    rt2 = connect_runtime(server, client_id=2)
    mgr = SummaryManager(rt1, server, max_ops=3)
    assert mgr.election.is_elected  # client 1 joined first
    assert not SummaryManager(rt2, server, max_ops=3).election.is_elected

    s = chan(rt1)
    for i in range(4):
        s.insert_text(0, f"{i}")
        rt1.flush()
    acks = []
    mgr.collection.on("ack", acks.append)
    assert mgr.maybe_summarize()
    assert len(acks) == 1  # scribe validated & acked synchronously
    handle = acks[0]["handle"]
    assert server.storage.get_ref("doc") == handle

    # A cold client boots from the scribe-blessed summary + op tail.
    wire = server.download_summary("doc")
    cold = ContainerRuntime(REGISTRY)
    cold.load(SummaryTree.from_json(wire))
    cold.connect(server.connect("doc", client_id=9))
    assert chan(cold).get_text() == s.get_text()


def test_summary_nack_on_bogus_handle():
    server = LocalServer()
    rt = connect_runtime(server, client_id=1)
    mgr = SummaryManager(rt, server)
    nacks = []
    mgr.collection.on("nack", nacks.append)
    rt.submit_system_message(MessageType.SUMMARIZE, {"handle": "deadbeef"})
    assert len(nacks) == 1
    assert "unknown summary handle" in nacks[0]["message"]
    assert not mgr._summary_in_flight


def test_quorum_proposal_commits_on_msn():
    server = LocalServer()
    rt1 = connect_runtime(server, client_id=1)
    rt2 = connect_runtime(server, client_id=2)
    committed = []
    rt2.protocol.proposals.on(
        "approveProposal", lambda k, v, s: committed.append((k, v))
    )
    rt1.propose("code", {"package": "tpu-app@1"})
    # The proposal commits once the MSN passes it: both clients must
    # reference a seq >= proposal seq. Drive traffic from both.
    chan(rt1).insert_text(0, "x")
    rt1.flush()
    chan(rt2).insert_text(0, "y")
    rt2.flush()
    chan(rt1).insert_text(0, "z")
    rt1.flush()
    chan(rt2).insert_text(0, "w")
    rt2.flush()
    assert ("code", {"package": "tpu-app@1"}) in committed
    assert rt1.protocol.proposals.get("code") == {"package": "tpu-app@1"}
    assert rt2.protocol.proposals.get("code") == {"package": "tpu-app@1"}


def test_oversized_op_nacked():
    server = LocalServer()
    rt = connect_runtime(server, client_id=1, channels=(("m", MapFactory.type_name),))
    # Disable the client-side splitter and compressor (opSplitter.ts /
    # opCompressor.ts) so the raw oversized op reaches alfred and
    # exercises the size-nack path.
    rt.max_op_bytes = 1 << 30
    rt.compression_threshold = None
    nacks = []
    rt.on("nack", nacks.append)
    chan(rt, "m").set("big", "x" * (800 * 1024))
    rt.flush()
    assert len(nacks) == 1 and nacks[0].code == 413
    assert rt.connection is None  # nack is connection-fatal


def test_election_passes_to_next_oldest_on_leave():
    server = LocalServer()
    rt1 = connect_runtime(server, client_id=1)
    rt2 = connect_runtime(server, client_id=2)
    m2 = SummaryManager(rt2, server)
    assert not m2.election.is_elected
    rt1.connection.disconnect()
    # rt2 sees the leave; election moves to it.
    assert m2.election.elected_client_id == 2
    assert m2.election.is_elected


def test_lambda_crash_checkpoint_restore():
    """Kill the server mid-session; restore every lambda from its
    checkpoint over the durable log; clients reconnect and converge
    (the deli/scribe checkpoint contract, checkpointContext.ts)."""
    server = LocalServer()
    rt1 = connect_runtime(server, client_id=1)
    s = chan(rt1)
    s.insert_text(0, "before crash")
    rt1.flush()
    cps = server.checkpoints()
    log, storage = server.log, server.storage

    # "Crash": build a fresh server from checkpoints + durable log.
    server2 = LocalServer(storage=storage, checkpoints=cps, log=log)
    # Sequencer state survived:
    assert server2.deli.sequencers["doc"].seq == server.deli.sequencers["doc"].seq
    # Old runtime reconnects (new client id) and continues.
    rt1.disconnect()
    rt1.connect(server2.connect("doc"))
    s.insert_text(0, "after restore ")
    rt1.flush()

    rt2 = connect_runtime(server2, client_id=77)
    assert chan(rt2).get_text() == s.get_text() == "after restore before crash"


def test_checkpoint_restore_preserves_quorum_and_protocol():
    server = LocalServer()
    rt1 = connect_runtime(server, client_id=1)
    rt1.propose("k", "v")
    chan(rt1).insert_text(0, "ab")
    rt1.flush()
    cps = server.checkpoints()
    server2 = LocalServer(storage=server.storage, checkpoints=cps, log=server.log)
    proto = server2.scribe.protocol["doc"]
    assert 1 in proto.quorum
    # MSN == head with one client at head, so the proposal committed.
    assert proto.proposals.get("k") == "v"


def test_incremental_summary_reserializes_only_touched_channel():
    """summarizerNode dirty tracking (reference summary/summarizerNode):
    a 1-op change re-serializes only the touched channel; everything
    else reuses its cached subtree — and the summary boots correctly."""
    from fluidframework_tpu.runtime.summary import (
        SummarizerNodeCache,
        SummaryTree,
    )

    server = LocalServer()
    rt = connect_runtime(
        server, client_id=1,
        channels=(("s", StringFactory.type_name),
                  ("m", MapFactory.type_name),
                  ("m2", MapFactory.type_name)),
    )
    chan(rt, "s").insert_text(0, "seed")
    chan(rt, "m").set("a", 1)
    chan(rt, "m2").set("b", 2)
    rt.flush()

    cache = SummarizerNodeCache()
    cache.begin_pass()
    first = rt.summarize(cache=cache)
    assert cache.reserialized == 3 and cache.reused == 0

    chan(rt, "m").set("a", 99)  # touch ONE channel
    rt.flush()
    cache.begin_pass()
    second = rt.summarize(cache=cache)
    assert cache.reserialized == 1, "only the touched channel"
    assert cache.reused == 2

    # The incremental summary boots a correct replica.
    rt2 = ContainerRuntime(REGISTRY)
    rt2.load(SummaryTree.from_json(second.to_json()))
    assert chan(rt2, "m").get("a") == 99
    assert chan(rt2, "s").get_text() == "seed"
    assert chan(rt2, "m2").get("b") == 2

    # No changes at all: everything reuses.
    cache.begin_pass()
    rt.summarize(cache=cache)
    assert cache.reserialized == 0 and cache.reused == 3
