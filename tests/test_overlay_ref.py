"""Differential tests: overlay engine (numpy reference) vs the oracle.

The overlay model (ops/overlay_ref.py) keeps settled content as a
virtual coordinate space and only unsettled rows in the table — the
O(collab window) design behind the pallas overlay kernel. These tests
gate its SEMANTICS against the scalar oracle on real-concurrency farm
streams (lagging refSeqs, tie-breaks, overlapping removes) and against
the scan engine on the synthetic bench mix, across fold cadences from
"every op" to "never".
"""

import pytest

from fluidframework_tpu.core.mergetree import replay_passive
from fluidframework_tpu.ops.overlay_ref import OverlayMessageReplica, OverlayReplica
from fluidframework_tpu.testing.farm import (
    FarmConfig,
    char_spans,
    run_sharedstring_farm,
)


def overlay_vs_oracle(cfg: FarmConfig, fold_intervals=(1, 7, 10_000),
                      n_removers=4):
    farm = run_sharedstring_farm(cfg)
    oracle = replay_passive(farm.stream, cfg.initial_text)
    for fold_iv in fold_intervals:
        r = OverlayMessageReplica(
            initial=cfg.initial_text, fold_interval=fold_iv,
            n_removers=n_removers,
        )
        r.apply_messages(farm.stream)
        r.check_errors()
        r.doc.verify_invariants()
        assert r.get_text() == oracle.get_text(), f"fold={fold_iv}"
        assert char_spans(r.annotated_spans()) == char_spans(
            oracle.annotated_spans()
        ), f"fold={fold_iv}"


@pytest.mark.parametrize("seed", range(6))
def test_overlay_matches_oracle_small(seed):
    overlay_vs_oracle(
        FarmConfig(num_clients=3, rounds=8, ops_per_client_per_round=3,
                   seed=seed)
    )


@pytest.mark.parametrize("seed", range(3))
def test_overlay_matches_oracle_more_clients(seed):
    overlay_vs_oracle(
        FarmConfig(num_clients=8, rounds=6, ops_per_client_per_round=4,
                   seed=500 + seed),
        # 8 concurrent clients can stack >4 removers on a hot row.
        n_removers=8,
    )


def test_overlay_insert_heavy_from_empty():
    overlay_vs_oracle(
        FarmConfig(num_clients=4, rounds=10, ops_per_client_per_round=5,
                   seed=11, insert_weight=0.85, remove_weight=0.1,
                   annotate_weight=0.05, initial_text="")
    )


def test_overlay_remove_heavy():
    overlay_vs_oracle(
        FarmConfig(
            num_clients=4, rounds=10, ops_per_client_per_round=4, seed=12,
            insert_weight=0.35, remove_weight=0.55, annotate_weight=0.1,
            initial_text="the quick brown fox jumps over the lazy dog",
        )
    )


def test_overlay_annotate_heavy():
    # Annotations are the fragmentation driver in the row model; here
    # they fold into settled props and the window stays small.
    overlay_vs_oracle(
        FarmConfig(
            num_clients=6, rounds=10, ops_per_client_per_round=4, seed=99,
            insert_weight=0.2, remove_weight=0.2, annotate_weight=0.6,
            initial_text="annotation heavy doc " * 4,
        )
    )


def test_overlay_matches_scan_engine_synthetic():
    """Bench-mix stream: overlay vs the scan engine, window stats."""
    from fluidframework_tpu.core.columnar_replay import ColumnarReplica
    from fluidframework_tpu.testing.digest import state_digest
    from fluidframework_tpu.testing.synthetic import generate_stream

    stream = generate_stream(4000, n_clients=64, seed=3, initial_len=64,
                             window=256)
    scan = ColumnarReplica(stream, initial_len=64, engine="scan",
                           chunk_size=256, capacity=4096)
    scan.replay()
    scan.check_errors()
    ov = OverlayReplica(stream, initial_len=64, fold_interval=256)
    ov.replay()
    ov.check_errors()
    ov.doc.verify_invariants()
    assert state_digest(ov.annotated_spans()) == state_digest(
        scan.annotated_spans()
    )
    # The whole point: the overlay window stays O(collab window), far
    # below the row-model's live row count (which holds every settled
    # annotation boundary).
    assert ov.doc.peak_rows < 2500
    assert int(scan.table.n_rows) > ov.doc.n
