"""Loader + driver tests: Container lifecycle, delta-queue pausing,
audience, stashed-op close/resume, replay/file drivers, fault
injection (the reference's loader + drivers + stashed-op e2e shapes).
"""

from __future__ import annotations

import os

import pytest

from fluidframework_tpu.dds import MapFactory, StringFactory
from fluidframework_tpu.drivers import (
    FaultInjectionDriver,
    FileDriver,
    LocalDriver,
    ReplayDriver,
)
from fluidframework_tpu.drivers.file_driver import message_to_json
from fluidframework_tpu.loader import Container, DeltaQueue, Loader
from fluidframework_tpu.runtime import ChannelRegistry
from fluidframework_tpu.server import LocalServer

REGISTRY = ChannelRegistry([MapFactory(), StringFactory()])


def make_loader(server=None):
    server = server or LocalServer()
    return Loader(LocalDriver(server), REGISTRY), server


def seed_container(loader):
    c = loader.create_detached()
    ds = c.runtime.create_datastore("default")
    ds.create_channel("s", StringFactory.type_name)
    ds.create_channel("m", MapFactory.type_name)
    return c


def chan(c, cid="s"):
    return c.runtime.get_datastore("default").get_channel(cid)


def test_container_lifecycle_and_audience():
    loader, server = make_loader()
    c1 = seed_container(loader)
    chan(c1).insert_text(0, "content")
    doc = c1.attach()
    assert c1.attach_state == "Attached" and c1.connected

    c2 = loader.resolve(doc)
    assert chan(c2).get_text() == "content"
    # Audience reflects the quorum on both sides.
    assert set(c2.audience.get_members()) == {c1.runtime.client_id, c2.runtime.client_id}
    left = []
    c2.audience.on("removeMember", left.append)
    c1.disconnect()
    assert left == [c1.runtime.client_id]


def test_stashed_ops_close_and_resume():
    """closeAndGetPendingLocalState → new session applies stashed ops
    and converges (client.ts:831 applyStashedOp path)."""
    loader, server = make_loader()
    c1 = seed_container(loader)
    chan(c1).insert_text(0, "base")
    doc = c1.attach()
    c2 = loader.resolve(doc)

    # Unflushed edits at close time.
    chan(c1).insert_text(4, "+tail")
    chan(c1, "m").set("draft", True)
    state = c1.close_and_get_pending_state()
    assert c1.closed

    # A later session resumes with the stashed ops.
    c3 = loader.resolve(doc, pending_state=state)
    assert chan(c3).get_text() == "base+tail"
    assert chan(c2).get_text() == "base+tail"
    assert chan(c2, "m").get("draft") is True
    assert not c3.is_dirty


def test_delta_queue_pause_resume_step():
    seen = []
    q = DeltaQueue(seen.append)
    q.push(1)
    assert seen == [1]
    q.pause()
    q.push(2)
    q.push(3)
    assert seen == [1] and q.length == 2
    assert q.process_one()  # stepping while paused
    assert seen == [1, 2]
    q.resume()
    assert seen == [1, 2, 3] and q.length == 0


def test_replay_driver_stepping_and_readonly():
    loader, server = make_loader()
    c1 = seed_container(loader)
    doc = c1.attach()
    chan(c1).insert_text(0, "abc")
    c1.flush()
    chan(c1, "m").set("k", 1)
    c1.flush()

    stream = server.ops_from(doc, 0)
    replay = ReplayDriver({doc: stream})
    rloader = Loader(replay, REGISTRY)
    rc = rloader.create_detached()
    ds = rc.runtime.create_datastore("default")
    ds.create_channel("s", StringFactory.type_name)
    ds.create_channel("m", MapFactory.type_name)
    rc.doc_id = doc
    rc.connect()

    assert chan(rc).get_text() == ""  # nothing delivered yet
    replay.step(doc, len(stream) - 1)
    replay.replay_all(doc)
    assert chan(rc).get_text() == "abc"
    assert chan(rc, "m").get("k") == 1
    with pytest.raises(RuntimeError, match="read-only"):
        chan(rc).insert_text(0, "x")
        rc.runtime.flush()


def test_file_driver_record_and_replay(tmp_path):
    loader, server = make_loader()
    c1 = seed_container(loader)
    doc = c1.attach()
    chan(c1).insert_text(0, "persisted text")
    chan(c1).annotate_range(0, 9, {"bold": True})
    c1.flush()

    fd = FileDriver(str(tmp_path))
    fd.record(doc, server.download_summary(doc), server.ops_from(doc, 0))
    assert os.path.exists(tmp_path / doc / "ops.jsonl")

    floader = Loader(FileDriver(str(tmp_path)), REGISTRY)
    fc = floader.resolve(doc, connect=False)
    fc.connect()
    floader.driver.replay_all(doc)
    assert chan(fc).get_text() == "persisted text"
    assert chan(fc).annotated_spans() == chan(c1).annotated_spans()


def test_fault_injection_reconnect_flow():
    server = LocalServer()
    fdriver = FaultInjectionDriver(LocalDriver(server))
    loader = Loader(fdriver, REGISTRY)
    c1 = seed_container(loader)
    doc = c1.attach()
    c2 = loader.resolve(doc)

    chan(c1).insert_text(0, "before ")
    c1.runtime.flush()
    # Kill every connection mid-session with a pending local op.
    chan(c1).insert_text(0, "pending-")
    fdriver.disconnect_all()
    assert not c1.connected and not c2.connected
    # Both sides reconnect; the pending op replays.
    c1.connect()
    c2.connect()
    c1.runtime.flush()
    assert chan(c1).get_text() == chan(c2).get_text() == "pending-before "


def test_fault_injection_submit_failures():
    server = LocalServer()
    fdriver = FaultInjectionDriver(LocalDriver(server))
    loader = Loader(fdriver, REGISTRY)
    c1 = seed_container(loader)
    doc = c1.attach()
    fdriver.submits_fail = True
    chan(c1, "m").set("x", 1)
    with pytest.raises(ConnectionError, match="injected"):
        c1.runtime.flush()
    fdriver.submits_fail = False

def test_stashed_interval_ops_resume():
    """Stashed interval-collection ops re-apply on resume (the
    applyStashedOp path the round-1 snapshot left NotImplemented)."""
    loader, server = make_loader()
    c1 = seed_container(loader)
    chan(c1).insert_text(0, "hello world")
    doc = c1.attach()
    c2 = loader.resolve(doc)

    coll = chan(c1).get_interval_collection("comments")
    iv = coll.add(0, 5, {"author": "me"})
    state = c1.close_and_get_pending_state()

    c3 = loader.resolve(doc, pending_state=state)
    coll3 = chan(c3).get_interval_collection("comments")
    assert iv.interval_id in coll3.intervals
    assert coll3.intervals[iv.interval_id].props == {"author": "me"}
    # The resubmitted op reached the other replica too.
    coll2 = chan(c2).get_interval_collection("comments")
    assert iv.interval_id in coll2.intervals
    assert not c3.is_dirty


def test_delete_subdirectory_rollback():
    """orderSequentially abort restores a deleted subdirectory tree
    (round-1 NotImplementedError path in dds/map.py)."""
    from fluidframework_tpu.dds import DirectoryFactory

    registry = ChannelRegistry([DirectoryFactory()])
    loader = Loader(LocalDriver(LocalServer()), registry)
    c1 = loader.create_detached()
    ds = c1.runtime.create_datastore("default")
    d = ds.create_channel("d", DirectoryFactory.type_name)
    c1.attach()
    sub = d.root.create_subdirectory("config")
    sub.set("mode", "fast")
    sub.create_subdirectory("nested").set("deep", 1)
    c1.flush()

    with pytest.raises(RuntimeError, match="abort"):
        def tx():
            d.root.delete_subdirectory("config")
            raise RuntimeError("abort")
        c1.runtime.order_sequentially(tx)
    restored = d.root.get_subdirectory("config")
    assert restored is not None
    assert restored.get("mode") == "fast"
    assert restored.get_subdirectory("nested").get("deep") == 1


def test_collab_window_tracker_advances_msn():
    """An idle reader pins the MSN; the tracker's noop heartbeats
    unpin it (collabWindowTracker.ts role)."""
    from fluidframework_tpu.loader import CollabWindowTracker

    def run(with_tracker):
        loader, server = make_loader()
        writer = seed_container(loader)
        doc = writer.attach()
        reader = loader.resolve(doc)  # never edits
        tracker = (
            CollabWindowTracker(reader.runtime, max_ops=5)
            if with_tracker else None
        )
        join_head = server.deli.sequencers[doc].seq
        for i in range(12):
            chan(writer).insert_text(0, f"{i}")
            writer.flush()
        return server.deli.sequencers[doc].min_seq, join_head, tracker

    msn_without, join_without, _ = run(False)
    msn_with, join_with, tracker = run(True)
    # Without heartbeats the idle reader pins the MSN at its join
    # point; with them the MSN advances past it.
    assert msn_without <= join_without
    assert tracker.noops_sent >= 2
    assert msn_with > join_with


def test_parallel_fetch_contiguous():
    from fluidframework_tpu.loader import fetch_ops_parallel

    loader, server = make_loader()
    c1 = seed_container(loader)
    doc = c1.attach()
    for i in range(40):
        chan(c1).insert_text(0, "x")
        c1.flush()
    head = server.deli.sequencers[doc].seq
    ops = fetch_ops_parallel(loader.driver, doc, 0, head, chunk=7, workers=3)
    assert [m.sequence_number for m in ops] == list(range(1, head + 1))
    # Partial window.
    ops = fetch_ops_parallel(loader.driver, doc, 10, 25, chunk=4)
    assert [m.sequence_number for m in ops] == list(range(11, 26))
