"""Loader + driver tests: Container lifecycle, delta-queue pausing,
audience, stashed-op close/resume, replay/file drivers, fault
injection (the reference's loader + drivers + stashed-op e2e shapes).
"""

from __future__ import annotations

import os

import pytest

from fluidframework_tpu.dds import MapFactory, StringFactory
from fluidframework_tpu.drivers import (
    FaultInjectionDriver,
    FileDriver,
    LocalDriver,
    ReplayDriver,
)
from fluidframework_tpu.drivers.file_driver import message_to_json
from fluidframework_tpu.loader import Container, DeltaQueue, Loader
from fluidframework_tpu.runtime import ChannelRegistry
from fluidframework_tpu.server import LocalServer

REGISTRY = ChannelRegistry([MapFactory(), StringFactory()])


def make_loader(server=None):
    server = server or LocalServer()
    return Loader(LocalDriver(server), REGISTRY), server


def seed_container(loader):
    c = loader.create_detached()
    ds = c.runtime.create_datastore("default")
    ds.create_channel("s", StringFactory.type_name)
    ds.create_channel("m", MapFactory.type_name)
    return c


def chan(c, cid="s"):
    return c.runtime.get_datastore("default").get_channel(cid)


def test_container_lifecycle_and_audience():
    loader, server = make_loader()
    c1 = seed_container(loader)
    chan(c1).insert_text(0, "content")
    doc = c1.attach()
    assert c1.attach_state == "Attached" and c1.connected

    c2 = loader.resolve(doc)
    assert chan(c2).get_text() == "content"
    # Audience reflects the quorum on both sides.
    assert set(c2.audience.get_members()) == {c1.runtime.client_id, c2.runtime.client_id}
    left = []
    c2.audience.on("removeMember", left.append)
    c1.disconnect()
    assert left == [c1.runtime.client_id]


def test_stashed_ops_close_and_resume():
    """closeAndGetPendingLocalState → new session applies stashed ops
    and converges (client.ts:831 applyStashedOp path)."""
    loader, server = make_loader()
    c1 = seed_container(loader)
    chan(c1).insert_text(0, "base")
    doc = c1.attach()
    c2 = loader.resolve(doc)

    # Unflushed edits at close time.
    chan(c1).insert_text(4, "+tail")
    chan(c1, "m").set("draft", True)
    state = c1.close_and_get_pending_state()
    assert c1.closed

    # A later session resumes with the stashed ops.
    c3 = loader.resolve(doc, pending_state=state)
    assert chan(c3).get_text() == "base+tail"
    assert chan(c2).get_text() == "base+tail"
    assert chan(c2, "m").get("draft") is True
    assert not c3.is_dirty


def test_delta_queue_pause_resume_step():
    seen = []
    q = DeltaQueue(seen.append)
    q.push(1)
    assert seen == [1]
    q.pause()
    q.push(2)
    q.push(3)
    assert seen == [1] and q.length == 2
    assert q.process_one()  # stepping while paused
    assert seen == [1, 2]
    q.resume()
    assert seen == [1, 2, 3] and q.length == 0


def test_replay_driver_stepping_and_readonly():
    loader, server = make_loader()
    c1 = seed_container(loader)
    doc = c1.attach()
    chan(c1).insert_text(0, "abc")
    c1.flush()
    chan(c1, "m").set("k", 1)
    c1.flush()

    stream = server.ops_from(doc, 0)
    replay = ReplayDriver({doc: stream})
    rloader = Loader(replay, REGISTRY)
    rc = rloader.create_detached()
    ds = rc.runtime.create_datastore("default")
    ds.create_channel("s", StringFactory.type_name)
    ds.create_channel("m", MapFactory.type_name)
    rc.doc_id = doc
    rc.connect()

    assert chan(rc).get_text() == ""  # nothing delivered yet
    replay.step(doc, len(stream) - 1)
    replay.replay_all(doc)
    assert chan(rc).get_text() == "abc"
    assert chan(rc, "m").get("k") == 1
    with pytest.raises(RuntimeError, match="read-only"):
        chan(rc).insert_text(0, "x")
        rc.runtime.flush()


def test_file_driver_record_and_replay(tmp_path):
    loader, server = make_loader()
    c1 = seed_container(loader)
    doc = c1.attach()
    chan(c1).insert_text(0, "persisted text")
    chan(c1).annotate_range(0, 9, {"bold": True})
    c1.flush()

    fd = FileDriver(str(tmp_path))
    fd.record(doc, server.download_summary(doc), server.ops_from(doc, 0))
    assert os.path.exists(tmp_path / doc / "ops.jsonl")

    floader = Loader(FileDriver(str(tmp_path)), REGISTRY)
    fc = floader.resolve(doc, connect=False)
    fc.connect()
    floader.driver.replay_all(doc)
    assert chan(fc).get_text() == "persisted text"
    assert chan(fc).annotated_spans() == chan(c1).annotated_spans()


def test_fault_injection_reconnect_flow():
    server = LocalServer()
    fdriver = FaultInjectionDriver(LocalDriver(server))
    loader = Loader(fdriver, REGISTRY)
    c1 = seed_container(loader)
    doc = c1.attach()
    c2 = loader.resolve(doc)

    chan(c1).insert_text(0, "before ")
    c1.runtime.flush()
    # Kill every connection mid-session with a pending local op.
    chan(c1).insert_text(0, "pending-")
    fdriver.disconnect_all()
    assert not c1.connected and not c2.connected
    # Both sides reconnect; the pending op replays.
    c1.connect()
    c2.connect()
    c1.runtime.flush()
    assert chan(c1).get_text() == chan(c2).get_text() == "pending-before "


def test_fault_injection_submit_failures():
    server = LocalServer()
    fdriver = FaultInjectionDriver(LocalDriver(server))
    loader = Loader(fdriver, REGISTRY)
    c1 = seed_container(loader)
    doc = c1.attach()
    fdriver.submits_fail = True
    chan(c1, "m").set("x", 1)
    with pytest.raises(ConnectionError, match="injected"):
        c1.runtime.flush()
    fdriver.submits_fail = False

def test_stashed_interval_ops_resume():
    """Stashed interval-collection ops re-apply on resume (the
    applyStashedOp path the round-1 snapshot left NotImplemented)."""
    loader, server = make_loader()
    c1 = seed_container(loader)
    chan(c1).insert_text(0, "hello world")
    doc = c1.attach()
    c2 = loader.resolve(doc)

    coll = chan(c1).get_interval_collection("comments")
    iv = coll.add(0, 5, {"author": "me"})
    state = c1.close_and_get_pending_state()

    c3 = loader.resolve(doc, pending_state=state)
    coll3 = chan(c3).get_interval_collection("comments")
    assert iv.interval_id in coll3.intervals
    assert coll3.intervals[iv.interval_id].props == {"author": "me"}
    # The resubmitted op reached the other replica too.
    coll2 = chan(c2).get_interval_collection("comments")
    assert iv.interval_id in coll2.intervals
    assert not c3.is_dirty


def test_delete_subdirectory_rollback():
    """orderSequentially abort restores a deleted subdirectory tree
    (round-1 NotImplementedError path in dds/map.py)."""
    from fluidframework_tpu.dds import DirectoryFactory

    registry = ChannelRegistry([DirectoryFactory()])
    loader = Loader(LocalDriver(LocalServer()), registry)
    c1 = loader.create_detached()
    ds = c1.runtime.create_datastore("default")
    d = ds.create_channel("d", DirectoryFactory.type_name)
    c1.attach()
    sub = d.root.create_subdirectory("config")
    sub.set("mode", "fast")
    sub.create_subdirectory("nested").set("deep", 1)
    c1.flush()

    with pytest.raises(RuntimeError, match="abort"):
        def tx():
            d.root.delete_subdirectory("config")
            raise RuntimeError("abort")
        c1.runtime.order_sequentially(tx)
    restored = d.root.get_subdirectory("config")
    assert restored is not None
    assert restored.get("mode") == "fast"
    assert restored.get_subdirectory("nested").get("deep") == 1


def test_collab_window_tracker_advances_msn():
    """An idle reader pins the MSN; the tracker's noop heartbeats
    unpin it (collabWindowTracker.ts role)."""
    from fluidframework_tpu.loader import CollabWindowTracker

    def run(with_tracker):
        loader, server = make_loader()
        writer = seed_container(loader)
        doc = writer.attach()
        reader = loader.resolve(doc)  # never edits
        tracker = (
            CollabWindowTracker(reader.runtime, max_ops=5)
            if with_tracker else None
        )
        join_head = server.deli.sequencers[doc].seq
        for i in range(12):
            chan(writer).insert_text(0, f"{i}")
            writer.flush()
        return server.deli.sequencers[doc].min_seq, join_head, tracker

    msn_without, join_without, _ = run(False)
    msn_with, join_with, tracker = run(True)
    # Without heartbeats the idle reader pins the MSN at its join
    # point; with them the MSN advances past it.
    assert msn_without <= join_without
    assert tracker.noops_sent >= 2
    assert msn_with > join_with


def test_parallel_fetch_contiguous():
    from fluidframework_tpu.loader import fetch_ops_parallel

    loader, server = make_loader()
    c1 = seed_container(loader)
    doc = c1.attach()
    for i in range(40):
        chan(c1).insert_text(0, "x")
        c1.flush()
    head = server.deli.sequencers[doc].seq
    ops = fetch_ops_parallel(loader.driver, doc, 0, head, chunk=7, workers=3)
    assert [m.sequence_number for m in ops] == list(range(1, head + 1))
    # Partial window.
    ops = fetch_ops_parallel(loader.driver, doc, 10, 25, chunk=4)
    assert [m.sequence_number for m in ops] == list(range(11, 26))


# ------------------------------------------------------ driver-web-cache


def test_cached_driver_snapshot_and_blob_tiers(tmp_path):
    """The driver-web-cache role (FluidCache.ts): snapshots cache with
    TTL (fresh hits skip the service; stale refetch; service failure
    falls back to stale), blobs cache forever (content-addressed)."""
    from fluidframework_tpu.drivers.web_cache import CachedDriver

    calls = {"load": 0, "blob": 0}

    class FakeDriver:
        def load_document(self, doc_id):
            calls["load"] += 1
            if calls.get("fail"):
                raise ConnectionError("service down")
            return f"wire-{doc_id}-v{calls['load']}"

        def read_blob(self, doc_id, blob_id):
            calls["blob"] += 1
            return f"{doc_id}:{blob_id}".encode()

        def ops_from(self, doc_id, a, b=None):
            return ["passthrough"]

    d = CachedDriver(FakeDriver(), str(tmp_path), snapshot_ttl_s=100.0)
    assert d.load_document("doc") == "wire-doc-v1"
    assert d.load_document("doc") == "wire-doc-v1"  # fresh hit
    assert calls["load"] == 1 and d.hits == 1

    # A SECOND CachedDriver over the same dir (a new session) also
    # boots from cache — the returning-client fast boot.
    d2 = CachedDriver(FakeDriver(), str(tmp_path), snapshot_ttl_s=100.0)
    assert d2.load_document("doc") == "wire-doc-v1"
    assert d2.hits == 1 and calls["load"] == 1

    # Blob: cached forever; second read never touches the service.
    assert d.read_blob("doc", "b1") == b"doc:b1"
    assert d.read_blob("doc", "b1") == b"doc:b1"
    assert calls["blob"] == 1

    # TTL expiry refetches.
    d3 = CachedDriver(FakeDriver(), str(tmp_path), snapshot_ttl_s=0.0)
    assert d3.load_document("doc") == "wire-doc-v2"
    assert calls["load"] == 2

    # Service failure: stale fallback (offline boot).
    calls["fail"] = True
    d4 = CachedDriver(FakeDriver(), str(tmp_path), snapshot_ttl_s=0.0)
    assert d4.load_document("doc") == "wire-doc-v2"
    # ...and strict mode raises instead.
    d5 = CachedDriver(FakeDriver(), str(tmp_path), snapshot_ttl_s=0.0,
                      allow_stale_on_error=False)
    with pytest.raises(ConnectionError):
        d5.load_document("doc")
    del calls["fail"]

    # Pass-through surface + expiry sweep.
    assert d.ops_from("doc", 0) == ["passthrough"]
    assert d3.clear_expired() >= 1


def test_cached_driver_over_socket_boot(tmp_path):
    """End-to-end: a TpuClient boots the SAME document twice through a
    CachedDriver over TCP — the second boot's summary load is a cache
    hit (zero service summary fetches)."""
    import subprocess
    import sys
    import time as _time

    REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    from fluidframework_tpu.dds import MapFactory
    from fluidframework_tpu.drivers.socket_driver import SocketDriver
    from fluidframework_tpu.drivers.web_cache import CachedDriver
    from fluidframework_tpu.framework.fluid_static import (
        ContainerSchema,
        TpuClient,
    )

    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "socket_server_main.py"),
         "--allow-anonymous"],
        stdout=subprocess.PIPE, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=REPO,
    )
    try:
        line = proc.stdout.readline().strip()
        assert line.startswith("LISTENING"), line
        _, host, port = line.split()
        port = int(port)
        schema = ContainerSchema({"kv": MapFactory.type_name})
        c = TpuClient(SocketDriver(host, port)).create_container(schema)
        c.initial_objects["kv"].set("k", "v")
        doc = c.attach()
        c.flush()
        _time.sleep(0.3)

        cached = CachedDriver(SocketDriver(host, port), str(tmp_path))
        c1 = TpuClient(cached).get_container(doc, schema)
        assert c1.initial_objects["kv"].get("k") == "v"
        assert cached.misses >= 1
        cached2 = CachedDriver(SocketDriver(host, port), str(tmp_path))
        c2 = TpuClient(cached2).get_container(doc, schema)
        assert c2.initial_objects["kv"].get("k") == "v"
        assert cached2.hits >= 1 and cached2.misses == 0
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_cached_driver_malformed_entries_degrade(tmp_path):
    """Corrupt-but-parseable cache files are a MISS, never a crash."""
    from fluidframework_tpu.drivers.web_cache import CachedDriver

    class FakeDriver:
        def load_document(self, doc_id):
            return "fresh"

    d = CachedDriver(FakeDriver(), str(tmp_path))
    path = d._key("snap", "doc")
    with open(path, "w") as f:
        f.write("[1, 2, 3]")  # valid JSON, wrong shape
    assert d.load_document("doc") == "fresh"
    assert d.misses == 1
    with open(path, "w") as f:
        f.write('{"unrelated": true}')
    assert d.clear_expired() >= 1  # malformed entries sweep away
