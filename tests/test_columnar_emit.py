"""Columnar emission end-to-end (ISSUE 11): record-batch codec v2
(nested boxcar blobs), the pre-columnized emit path
(`ColumnarRecords` / `encode_columns` / the kernel deli's verdict →
column emission), and the fused durable+broadcast hop
(`ScriptoriumBroadcasterRole`) — plus the columnar backward tail scan
summary catch-up rides."""

from __future__ import annotations

import json
import os
import random

import numpy as np
import pytest

from fluidframework_tpu.protocol import record_batch as rb
from fluidframework_tpu.server.columnar_log import (
    ColumnarFileTopic,
    make_topic,
    tail_records_reverse,
)
from fluidframework_tpu.server.deli_kernel import KernelDeliRole
from fluidframework_tpu.server.supervisor import (
    FUSED_PIPELINE_ROLES,
    BroadcasterRole,
    DeliRole,
    ScriptoriumBroadcasterRole,
    ScriptoriumRole,
    fused_roles,
)
from fluidframework_tpu.utils import metrics as M


# ---------------------------------------------------------------------------
# codec v2
# ---------------------------------------------------------------------------


def _random_records(rng: random.Random, n: int):
    recs = []
    for i in range(n):
        r = rng.random()
        doc = f"doc{rng.randrange(4)}"
        if r < 0.35:
            recs.append({"kind": "op", "doc": doc,
                         "client": rng.randrange(5),
                         "clientSeq": i, "refSeq": 0,
                         "contents": {"i": i, "s": "x" * rng.randrange(6)}})
        elif r < 0.55:
            ops = [{"clientSeq": i + k, "refSeq": 0,
                    "contents": [i, k, {"nested": True}]}
                   for k in range(rng.randrange(0, 4))]
            recs.append({"kind": "boxcar", "doc": doc,
                         "client": rng.randrange(5), "ops": ops})
        elif r < 0.7:
            recs.append({"kind": "op", "doc": doc, "seq": i + 1,
                         "msn": i // 2, "client": 1, "clientSeq": i,
                         "refSeq": 0, "type": "op", "contents": None,
                         "inOff": i})
        elif r < 0.8:
            recs.append({"kind": "nack", "doc": doc, "client": 2,
                         "clientSeq": i, "code": 422,
                         "reason": "out of order", "inOff": i})
        elif r < 0.9:
            recs.append({"kind": rng.choice(["join", "leave"]),
                         "doc": doc, "client": rng.randrange(5)})
        else:
            recs.append({"arbitrary": [i, None, {"deep": "value"}]})
    return recs


@pytest.mark.parametrize("version", [1, 2])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_v2_roundtrip_property(version, seed):
    """Both frame revs round-trip arbitrary streams to identical plain
    values — nested/empty boxcars included — and stamp their version
    byte per frame."""
    rng = random.Random(seed)
    recs = _random_records(rng, 120)
    frame = rb.encode_batch(recs, fence=9, owner="t", version=version)
    batch, end, n = rb.decode_batch(frame)
    assert (n, end) == (len(recs), len(frame))
    assert batch.version == version
    assert batch.records() == recs


def test_v2_boxcar_nested_offsets_pass_through():
    """A v2 boxcar's per-op ints read as values and its contents slice
    out as RAW blob handles (no once-per-boxcar JSON decode); v1 keeps
    the decoded-values contract."""
    box = {"kind": "boxcar", "doc": "d", "client": 3, "ops": [
        {"clientSeq": 5, "refSeq": 1, "contents": {"a": [1, 2]}},
        {"clientSeq": 6, "refSeq": 1, "contents": "text"},
    ]}
    b2, _, _ = rb.decode_batch(rb.encode_batch([box], version=2))
    ops = b2.boxcar(0)
    assert [(c, r) for c, r, _ in ops] == [(5, 1), (6, 1)]
    assert all(isinstance(v, rb.JsonBlob) for _, _, v in ops)
    assert ops[0][2].raw == b'{"a":[1,2]}'  # raw bytes, untouched
    b1, _, _ = rb.decode_batch(rb.encode_batch([box], version=1))
    assert [v for _, _, v in b1.boxcar(0)] == [{"a": [1, 2]}, "text"]
    # decoded record form is version-independent
    assert b1.record(0) == b2.record(0) == box


def test_v1_v2_mixed_stream_one_file(tmp_path):
    """v1 and v2 frames (and JSON lines) coexist in one topic file —
    the no-migration upgrade path: offsets stable, records identical.
    The v1 frames are written raw (a v1-era file's on-disk form)."""
    rng = random.Random(3)
    recs = _random_records(rng, 90)
    path = str(tmp_path / "mixed.jsonl")
    t = ColumnarFileTopic(path)
    with open(path, "ab") as f:  # the v1-era prefix
        f.write(rb.encode_batch(recs[:30], version=1))
    t.append_many(recs[30:60])  # current writer: v2 frames
    with open(path, "ab") as f:
        f.write(json.dumps(recs[60]).encode() + b"\n")
    t.append_many(recs[61:])
    entries, nxt = t.read_entries(0)
    assert nxt == len(recs)
    assert [v for _, v in entries] == recs


def test_v2_crc_corruption_skips_but_counts(tmp_path):
    """CRC/torn rules hold on v2 frames: a corrupt frame skips whole
    but keeps its record slots; a torn v2 tail is invisible until
    complete."""
    path = str(tmp_path / "t.jsonl")
    t = ColumnarFileTopic(path)
    recs = _random_records(random.Random(4), 40)
    t.append_many(recs[:20])
    size_1 = os.path.getsize(path)
    t.append_many(recs[20:])
    # flip a payload byte inside the SECOND frame
    with open(path, "r+b") as f:
        f.seek(size_1 + rb.HEADER.size + 10)
        b0 = f.read(1)
        f.seek(size_1 + rb.HEADER.size + 10)
        f.write(bytes([b0[0] ^ 0xFF]))
    entries, nxt = t.read_entries(0)
    assert [v for _, v in entries] == recs[:20]
    assert nxt == len(recs)  # skipped frame still counts its slots
    # torn tail: append a clipped v2 frame; readers must not consume it
    frame = rb.encode_batch(recs[:5], version=2)
    with open(path, "ab") as f:
        f.write(frame[:len(frame) // 2])
    entries2, nxt2 = t.read_entries(0)
    assert nxt2 == nxt and [v for _, v in entries2] == recs[:20]


def test_classify_hoist_matches_per_record_classification():
    """The homogeneous-run hoist must classify EXACTLY like per-record
    `_classify` — including runs broken by value-level failures (a
    non-i64 client mid-run) — and produce byte-stable frames."""
    rng = random.Random(7)
    recs = _random_records(rng, 400)
    # adversarial same-key-set value breaks inside runs
    for i in range(0, 390, 13):
        bad = dict(recs[i])
        if bad.get("kind") == "op" and "clientSeq" in bad \
                and "seq" not in bad:
            bad["client"] = 1 << 70  # same keys, not i64 -> generic
            recs.insert(i + 1, bad)
    frame = rb.encode_batch(recs)
    batch, _, _ = rb.decode_batch(frame)
    assert batch.kind.tolist() == [rb._classify(r) for r in recs]
    assert batch.records() == recs
    assert rb.encode_batch(recs) == frame  # deterministic


# ---------------------------------------------------------------------------
# ColumnarRecords / encode_columns
# ---------------------------------------------------------------------------


def test_columnar_records_splice_and_passthrough():
    seqs = [{"kind": "op", "doc": f"d{i % 2}", "seq": i + 1, "msn": 0,
             "client": 1, "clientSeq": i, "refSeq": 0, "type": "op",
             "contents": {"i": i}, "inOff": i} for i in range(10)]
    src, _, _ = rb.decode_batch(rb.encode_batch(seqs))
    cr = rb.ColumnarRecords.from_batch(
        src, np.arange(3, 8), np.arange(103, 108)
    )
    assert len(cr) == 5
    assert cr.record(0) == {**seqs[3], "inOff": 103}
    assert rb.count_records([seqs[0], cr, seqs[9]]) == 7
    out, _, n = rb.decode_batch(
        rb.encode_batch([seqs[0], cr, seqs[9]])
    )
    assert n == 7
    assert out.records() == [seqs[0]] + [
        {**seqs[i], "inOff": 100 + i} for i in range(3, 8)
    ] + [seqs[9]]
    # non-contiguous row gather (the fused role's nack-splitting path)
    cr2 = rb.ColumnarRecords.from_batch(
        src, np.array([1, 2, 6, 9]), np.array([1, 2, 6, 9])
    )
    assert [r["seq"] for r in cr2.records()] == [2, 3, 7, 10]
    # encode_columns counts its records
    reg = M.get_registry()
    c = reg.counter("codec_encode_columns_total", codec="columnar")
    before = c.value
    rb.encode_columns([cr, cr2])
    assert c.value - before == 9


def test_columnar_records_reject_boxcars():
    box = {"kind": "boxcar", "doc": "d", "client": 1,
           "ops": [{"clientSeq": 1, "refSeq": 0, "contents": None}]}
    src, _, _ = rb.decode_batch(rb.encode_batch([box]))
    with pytest.raises(ValueError):
        rb.ColumnarRecords.from_batch(src, np.array([0]), np.array([0]))


def test_mask_runs():
    assert rb.mask_runs(np.array([], bool)) == []
    assert rb.mask_runs(np.array([1, 1, 0, 0, 0, 1])) == [
        (1, 0, 2), (0, 2, 5), (1, 5, 6)
    ]
    assert rb.mask_runs(np.array([True])) == [(True, 0, 1)]


# ---------------------------------------------------------------------------
# kernel columnar emission differential
# ---------------------------------------------------------------------------


def _boxcar_heavy_workload(seed=11, n_docs=3, n_clients=3, n=260):
    rng = random.Random(seed)
    recs = []
    for d in range(n_docs):
        for c in range(1, n_clients + 1):
            recs.append({"kind": "join", "doc": f"doc{d}", "client": c})
    cs = {}
    for i in range(n):
        d = rng.randrange(n_docs)
        c = rng.randrange(1, n_clients + 1)
        k = cs.setdefault((d, c), 0) + 1
        if rng.random() < 0.3:
            ops = []
            for _ in range(rng.randint(2, 4)):
                ops.append({"clientSeq": k, "refSeq": 0,
                            "contents": {"i": i}})
                k += 1
            cs[(d, c)] = k - 1
            recs.append({"kind": "boxcar", "doc": f"doc{d}",
                         "client": c, "ops": ops})
        else:
            cs[(d, c)] = k
            recs.append({"kind": "op", "doc": f"doc{d}", "client": c,
                         "clientSeq": k, "refSeq": 0,
                         "contents": {"i": i}})
    # riders: resubmission (silent dedup), unknown-client nack,
    # out-of-order nack, duplicate join, leave, nacked boxcar tail
    recs.append(recs[n_docs * n_clients])
    recs.append({"kind": "op", "doc": "doc0", "client": 99,
                 "clientSeq": 1, "refSeq": 0, "contents": None})
    recs.append({"kind": "op", "doc": "doc1", "client": 1,
                 "clientSeq": 999, "refSeq": 0, "contents": None})
    recs.append({"kind": "join", "doc": "doc0", "client": 1})
    recs.append({"kind": "leave", "doc": "doc2", "client": 2})
    k31 = cs.get((2, 1), 0)
    recs.append({"kind": "boxcar", "doc": "doc2", "client": 1,
                 "ops": [{"clientSeq": k31 + 1, "refSeq": 0,
                          "contents": 1},
                         {"clientSeq": 999, "refSeq": 0, "contents": 2},
                         {"clientSeq": k31 + 3, "refSeq": 0,
                          "contents": 3}]})
    return recs


def _drive_role(cls, shared, log_format, owner):
    raw = make_topic(os.path.join(shared, "topics", "rawdeltas.jsonl"),
                     log_format)
    recs = _boxcar_heavy_workload()
    for lo in range(0, len(recs), 48):
        raw.append_many(recs[lo:lo + 48])
    role = cls(str(shared), owner=owner, ttl_s=3600.0,
               log_format=log_format, batch=64)
    idle = 0
    while idle < 3:
        idle = 0 if role.step(idle_sleep=0.001) else idle + 1
    out = make_topic(os.path.join(shared, "topics", "deltas.jsonl"),
                     log_format)
    return out.read_from(0)


def test_kernel_columnar_emit_matches_scalar_boxcar_heavy(tmp_path):
    """THE emission differential: the kernel role's pre-columnized
    emit (verdict arrays → ColumnarRecords → one spliced frame) must
    write the byte-identical canonical stream the scalar dict-path
    oracle writes — boxcars, nacks, dedup, join/leave churn and all —
    and every emitted record must actually ride `encode_columns`."""
    reg = M.get_registry()
    c = reg.counter("codec_encode_columns_total", codec="columnar")
    a = _drive_role(DeliRole, str(tmp_path / "s"), "columnar", "s")
    before = c.value
    b = _drive_role(KernelDeliRole, str(tmp_path / "k"), "columnar", "k")
    assert a == b  # reason text included: same mirror-order rule
    assert c.value - before >= len(b)


def test_kernel_emit_trace_mode_falls_back_to_dicts(tmp_path):
    """Wire tracing adds a side "tr" key (generic schema) — the role
    must take the dict path and still produce the same canonical
    stream."""
    a = _drive_role(DeliRole, str(tmp_path / "s"), "columnar", "s")
    os.environ["FLUID_TRACE_WIRE"] = "1"
    try:
        b = _drive_role(KernelDeliRole, str(tmp_path / "k"),
                        "columnar", "k")
    finally:
        del os.environ["FLUID_TRACE_WIRE"]
    strip = lambda rs: [  # noqa: E731
        {k: v for k, v in r.items() if k != "tr"} for r in rs
    ]
    assert strip(a) == strip(b)


def test_kernel_columnar_emit_v1_ingest(tmp_path):
    """A v1-era raw topic (JSON boxcar blobs) feeds the same kernel
    emission: migration needs no drained topics."""
    shared = tmp_path / "k1"
    os.makedirs(shared / "topics")
    raw_path = str(shared / "topics" / "rawdeltas.jsonl")
    recs = _boxcar_heavy_workload()
    with open(raw_path, "ab") as f:  # v1 frames, written raw
        for lo in range(0, len(recs), 48):
            f.write(rb.encode_batch(recs[lo:lo + 48], version=1))
    role = KernelDeliRole(str(shared), owner="k1", ttl_s=3600.0,
                          log_format="columnar", batch=64)
    idle = 0
    while idle < 3:
        idle = 0 if role.step(idle_sleep=0.001) else idle + 1
    got = make_topic(str(shared / "topics" / "deltas.jsonl"),
                     "columnar").read_from(0)
    want = _drive_role(DeliRole, str(tmp_path / "s"), "columnar", "s")
    assert got == want


# ---------------------------------------------------------------------------
# fused durable+broadcast hop
# ---------------------------------------------------------------------------


def _drive_downstream(shared, roles, log_format, crash_step=None):
    deli = KernelDeliRole(str(shared), owner="d", ttl_s=3600.0,
                          log_format=log_format)
    idle = 0
    while idle < 3:
        idle = 0 if deli.step(idle_sleep=0.001) else idle + 1
    steps = 0
    for r in roles:
        idle = 0
        while idle < 3:
            moved = r.step(idle_sleep=0.001)
            steps += 1
            if crash_step is not None and steps == crash_step:
                # crash: drop the consumer mid-stream; a successor
                # takes over (the lapsed-lease handoff, instant here)
                r.leases.release(r.name)
                r = type(r)(str(shared), owner="successor",
                            ttl_s=3600.0, log_format=log_format,
                            batch=r.batch)
                crash_step = None
                idle = 0
                continue
            idle = 0 if moved else idle + 1
    dur = make_topic(os.path.join(shared, "topics", "durable.jsonl"),
                     log_format).read_from(0)
    bc = make_topic(os.path.join(shared, "topics", "broadcast.jsonl"),
                    log_format).read_from(0)
    return dur, bc


def _stage_raw(shared, log_format):
    raw = make_topic(os.path.join(shared, "topics", "rawdeltas.jsonl"),
                     log_format)
    recs = _boxcar_heavy_workload()
    for lo in range(0, len(recs), 48):
        raw.append_many(recs[lo:lo + 48])


@pytest.mark.parametrize("log_format", ["json", "columnar"])
def test_fused_hop_matches_split_pair(log_format, tmp_path):
    """The fused consumer must write EXACTLY the split pair's durable
    and broadcast streams (nacks broadcast-only), on both wire
    forms."""
    s1 = str(tmp_path / "split")
    _stage_raw(s1, log_format)
    d1, b1 = _drive_downstream(s1, [
        ScriptoriumRole(s1, owner="s", ttl_s=3600.0,
                        log_format=log_format, batch=37),
        BroadcasterRole(s1, owner="b", ttl_s=3600.0,
                        log_format=log_format, batch=37),
    ], log_format)
    s2 = str(tmp_path / "fused")
    _stage_raw(s2, log_format)
    d2, b2 = _drive_downstream(s2, [
        ScriptoriumBroadcasterRole(s2, owner="f", ttl_s=3600.0,
                                   log_format=log_format, batch=37),
    ], log_format)
    assert d1 == d2
    assert b1 == b2
    assert any(r.get("kind") == "nack" for r in b1)
    assert not any(r.get("kind") == "nack" for r in d1)


@pytest.mark.parametrize("log_format", ["json", "columnar"])
def test_fused_hop_crash_recovers_both_legs_exactly_once(
        log_format, tmp_path):
    """A fused consumer killed mid-stream (checkpoint behind its
    appends, broadcast leg unfsynced) must resume with zero dup/skip
    on BOTH topics — the two-topic generalization of the inOff
    recovery contract."""
    s1 = str(tmp_path / "ref")
    _stage_raw(s1, log_format)
    d1, b1 = _drive_downstream(s1, [
        ScriptoriumBroadcasterRole(s1, owner="f", ttl_s=3600.0,
                                   log_format=log_format, batch=37),
    ], log_format)
    s2 = str(tmp_path / "crash")
    _stage_raw(s2, log_format)
    d2, b2 = _drive_downstream(s2, [
        ScriptoriumBroadcasterRole(s2, owner="f", ttl_s=3600.0,
                                   log_format=log_format, batch=37),
    ], log_format, crash_step=3)
    assert d1 == d2
    assert b1 == b2


def test_fused_roles_helper():
    assert FUSED_PIPELINE_ROLES == (
        "deli", "scriptorium_broadcaster", "scribe"
    )
    assert fused_roles(("deli", "scriptorium", "scribe", "broadcaster",
                        "summarizer")) == (
        "deli", "scriptorium_broadcaster", "scribe", "summarizer"
    )


@pytest.mark.chaos
def test_chaos_fused_hop_kill_torn_converges():
    """The acceptance gate: kill+torn chaos on the FUSED farm (kernel
    deli, columnar topics, boxcars) converges bit-identical to the
    scalar golden with zero dup/skip — the unfsynced broadcast leg
    regenerates exactly-once through recovery."""
    from fluidframework_tpu.testing.chaos import ChaosConfig, run_chaos

    res = run_chaos(ChaosConfig(
        seed=17, faults=("kill", "torn"), n_docs=2, n_clients=3,
        ops_per_client=24, timeout_s=240.0, fused_hop=True,
        deli_impl="kernel", log_format="columnar", boxcar_rate=0.3,
    ))
    assert res.converged, res.detail
    assert res.duplicate_seqs == 0 and res.skipped_seqs == 0


def test_chaos_rejects_fused_hop_on_fabric():
    from fluidframework_tpu.testing.chaos import ChaosConfig, run_chaos

    with pytest.raises(ValueError, match="fused_hop"):
        run_chaos(ChaosConfig(faults=("kill",), n_partitions=2,
                              fused_hop=True))


def test_sidecar_only_advances_over_fsynced_data(tmp_path, monkeypatch):
    """The file-global sidecar invariant (review finding): a FRESH
    topic instance's empty append (a successor's fence bind) scanning
    over a dead writer's never-fsynced frames must fsync the data
    BEFORE the sidecar names it — the local `_unsynced` flag cannot
    see another process's unsynced appends."""
    path = str(tmp_path / "t.jsonl")
    w1 = ColumnarFileTopic(path)
    w1.append_many([_seq_op("A", 1)])
    w1.append_many([_seq_op("A", 2)], fsync=False)  # dies unsynced
    clen_before = json.load(open(path + ".clen"))["len"]
    fsyncs = []
    from fluidframework_tpu.server import columnar_log as cl

    real = cl.fsync_file
    monkeypatch.setattr(cl, "fsync_file",
                        lambda f, kind="topic": (fsyncs.append(kind),
                                                 real(f, kind)))
    w2 = ColumnarFileTopic(path)  # the successor (fresh instance)
    w2.append_many([], fence=1, owner="succ")  # fence bind
    clen_after = json.load(open(path + ".clen"))["len"]
    assert clen_after > clen_before  # sidecar did advance...
    assert "topic" in fsyncs  # ...but only after a data fsync


# ---------------------------------------------------------------------------
# columnar backward tail scan (summary catch-up)
# ---------------------------------------------------------------------------


def _seq_op(doc, seq):
    return {"kind": "op", "doc": doc, "seq": seq, "msn": 0,
            "client": 1, "clientSeq": seq, "refSeq": 0, "type": "op",
            "contents": {"s": seq}, "inOff": seq}


def _grow_log(topic, frames, per_frame=20, start=(0, 0)):
    sa, sb = start
    for i in range(frames):
        batch = []
        for j in range(per_frame):
            if (i + j) % 2 == 0:
                sa += 1
                batch.append(_seq_op("A", sa))
            else:
                sb += 1
                batch.append(_seq_op("B", sb))
        topic.append_many(batch)
    return sa, sb


def test_reverse_tail_matches_forward(tmp_path):
    t = ColumnarFileTopic(str(tmp_path / "d.jsonl"))
    sa, sb = _grow_log(t, 60)
    ops = tail_records_reverse(t, "A", sa - 15, None)
    assert ops is not None
    assert [r["seq"] for r in ops] == list(range(sa - 14, sa + 1))
    fwd = [r for _, r in t.read_entries(0)[0]
           if r.get("doc") == "B" and r.get("kind") == "op"]
    assert tail_records_reverse(t, "B", 0, None) == fwd
    # upto bound
    assert [r["seq"] for r in
            tail_records_reverse(t, "A", sa - 10, sa - 5)] == \
        list(range(sa - 9, sa - 4))


def test_reverse_tail_flat_in_log_length(tmp_path):
    """The satellite's flat-join-cost claim, measured: the bytes a
    reverse catch-up scans stay ~CONSTANT as the log grows 4x (the
    forward skip grows linearly)."""
    reg = M.get_registry()
    c = reg.counter("catchup_tail_scan_bytes_total",
                    mode="reverse-columnar")

    def scanned(frames):
        t = ColumnarFileTopic(str(tmp_path / f"d{frames}.jsonl"))
        sa, _ = _grow_log(t, frames)
        before = c.value
        ops = tail_records_reverse(t, "A", sa - 10, None)
        assert ops is not None and len(ops) == 10
        return c.value - before

    small, big = scanned(100), scanned(400)
    assert big <= small * 2, (small, big)  # flat, not linear


def test_reverse_tail_torn_and_stale_sidecar(tmp_path):
    t = ColumnarFileTopic(str(tmp_path / "d.jsonl"))
    sa, _ = _grow_log(t, 30)
    want = tail_records_reverse(t, "A", sa - 12, None)
    with open(t.path, "ab") as f:
        f.write(b"FRB1torn-in-flight")
    assert tail_records_reverse(t, "A", sa - 12, None) == want
    # stale-LOW sidecar (crash before the sidecar update): the forward
    # suffix parse covers the gap
    data = open(t.path, "rb").read()
    _, end, _ = rb.decode_batch(data, 0)
    with open(t.path + ".clen", "w") as f:
        json.dump({"len": end}, f)
    assert tail_records_reverse(ColumnarFileTopic(t.path), "A",
                                sa - 12, None) == want
    # no sidecar at all: anchorless -> None (caller falls forward)
    os.remove(t.path + ".clen")
    assert tail_records_reverse(ColumnarFileTopic(t.path), "A", 0,
                                None) is None


def test_reverse_tail_json_prefix_falls_forward_not_misparse(tmp_path):
    """A JSON-era prefix breaks the backward frame chain: the scan
    must either stop cleanly above it (base reached) or return None —
    never fabricate records."""
    path = str(tmp_path / "m.jsonl")
    with open(path, "w") as f:
        for s in range(1, 6):
            f.write(json.dumps(_seq_op("A", s)) + "\n")
    t = ColumnarFileTopic(path)
    sa, _ = _grow_log(t, 10, start=(5, 0))
    # base above the JSON era: chain stops inside the frame region
    ops = tail_records_reverse(t, "A", sa - 5, None)
    assert ops is not None
    assert [r["seq"] for r in ops] == list(range(sa - 4, sa + 1))
    # base inside the JSON era: cannot anchor -> fall forward
    assert tail_records_reverse(t, "A", 0, None) is None


@pytest.mark.parametrize("log_format", ["json", "columnar"])
def test_read_catchup_reverse_equivalence(log_format, tmp_path):
    """`read_catchup` returns the same tail through the reverse scan
    as through the forward skip, at both log formats."""
    from fluidframework_tpu.server.summarizer import read_catchup

    shared = str(tmp_path)
    os.makedirs(os.path.join(shared, "topics"), exist_ok=True)
    t = make_topic(os.path.join(shared, "topics", "deltas.jsonl"),
                   log_format)
    n = 300
    ops = [_seq_op("A", s + 1) for s in range(n)]
    for lo in range(0, n, 25):
        t.append_many(ops[lo:lo + 25])
    base_seq, base_off = 240, 239

    class _Idx:
        def poll(self):
            pass

        def nearest(self, doc, seq):
            return {"doc": doc, "seq": base_seq, "off": base_off,
                    "handle": "h", "count": base_seq,
                    "form": "ops"}

    class _Store:
        def get(self, h):
            return json.dumps({"form": "ops", "records": []}).encode()

    cu = read_catchup(shared, "A", log_format, index=_Idx(),
                      store=_Store())
    assert [r["seq"] for r in cu["ops"]] == list(range(241, n + 1))
