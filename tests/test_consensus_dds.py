"""Consensus-family DDS tests: queue leases, versioned registers,
task locks, pacts, ink, summary blocks — including quorum-leave
cleanup driven through the real protocol stream.
"""

from __future__ import annotations

import pytest

from fluidframework_tpu.dds import (
    READ_ATOMIC,
    READ_LWW,
    ConsensusQueueFactory,
    InkFactory,
    PactMapFactory,
    RegisterCollectionFactory,
    SummaryBlockFactory,
    TaskManagerFactory,
)
from fluidframework_tpu.runtime import ChannelRegistry, ContainerRuntime
from fluidframework_tpu.runtime.summary import SummaryTree
from fluidframework_tpu.testing.mocks import MultiClientHarness

REGISTRY = ChannelRegistry(
    [
        ConsensusQueueFactory(),
        RegisterCollectionFactory(),
        TaskManagerFactory(),
        PactMapFactory(),
        InkFactory(),
        SummaryBlockFactory(),
    ]
)


def make_harness(n, channels):
    return MultiClientHarness(n, REGISTRY, channel_types=list(channels))


# ------------------------------------------------------------ ConsensusQueue


def test_queue_acquire_order_and_complete():
    h = make_harness(2, [("q", ConsensusQueueFactory.type_name)])
    a, b = h.channel(0, "q"), h.channel(1, "q")
    a.add("job1")
    a.add("job2")
    h.process_all()
    got_a, got_b = [], []
    a.acquire(got_a.append)
    b.acquire(got_b.append)
    h.process_all()
    assert got_a[0]["value"] == "job1"  # a's acquire sequenced first
    assert got_b[0]["value"] == "job2"
    assert a.in_flight == b.in_flight
    a.complete(got_a[0]["id"])
    h.process_all()
    assert got_a[0]["id"] not in b.in_flight


def test_queue_release_and_leave_requeue():
    h = make_harness(2, [("q", ConsensusQueueFactory.type_name)])
    a, b = h.channel(0, "q"), h.channel(1, "q")
    a.add("task")
    h.process_all()
    got = []
    b.acquire(got.append)
    h.process_all()
    assert got[0]["value"] == "task" and len(b.queue) == 0
    # b leaves: its lease returns to the queue on every replica.
    h.runtimes[1].connection.disconnect()
    h.process_all()
    assert len(a.queue) == 1 and a.queue[0]["value"] == "task"
    assert not a.in_flight


def test_queue_acquire_empty_returns_none():
    h = make_harness(1, [("q", ConsensusQueueFactory.type_name)])
    a = h.channel(0, "q")
    got = []
    a.acquire(got.append)
    h.process_all()
    assert got == [None]


# ------------------------------------------------ ConsensusRegisterCollection


def test_register_concurrent_writes_keep_versions():
    h = make_harness(2, [("r", RegisterCollectionFactory.type_name)])
    a, b = h.channel(0, "r"), h.channel(1, "r")
    a.write("k", "from-a")
    b.write("k", "from-b")  # concurrent: b hasn't seen a's write
    h.process_all()
    # Both versions survive; atomic = first sequenced, LWW = last.
    assert a.read_versions("k") == b.read_versions("k") == ["from-a", "from-b"]
    assert a.read("k", READ_ATOMIC) == "from-a"
    assert a.read("k", READ_LWW) == "from-b"
    # A later (non-concurrent) write supersedes all seen versions.
    a.write("k", "final")
    h.process_all()
    assert b.read_versions("k") == ["final"]


# ------------------------------------------------------------- TaskManager


def test_task_manager_lock_passes_on_abandon_and_leave():
    h = make_harness(3, [("t", TaskManagerFactory.type_name)])
    ts = [h.channel(i, "t") for i in range(3)]
    for t in ts:
        t.volunteer_for_task("leader")
    h.process_all()
    assert ts[0].assigned("leader")
    assert not ts[1].assigned("leader")
    ts[0].abandon("leader")
    h.process_all()
    assert ts[1].assigned("leader")
    assert ts[2].queued("leader")
    # Holder crashes: lock passes via quorum leave.
    h.runtimes[1].connection.disconnect()
    h.process_all()
    assert ts[2].assigned("leader")


# ---------------------------------------------------------------- PactMap


def test_pact_map_first_sequenced_wins_commits_on_msn():
    h = make_harness(2, [("p", PactMapFactory.type_name)])
    a, b = h.channel(0, "p"), h.channel(1, "p")
    a.set("color", "red")
    b.set("color", "blue")  # concurrent competing set: loses
    h.process_all()
    # Committing needs the MSN to pass the set's seq: keep traffic
    # flowing from both clients.
    a.set("other", 1)
    b.set("other2", 2)
    h.process_all()
    a.set("tick", 3)
    b.set("tick2", 4)
    h.process_all()
    assert a.get("color") == b.get("color") == "red"


# ------------------------------------------------------------------- Ink


def test_ink_strokes_converge():
    h = make_harness(2, [("i", InkFactory.type_name)])
    a, b = h.channel(0, "i"), h.channel(1, "i")
    sid = a.create_stroke({"color": "black"})
    a.append_point(sid, 0, 0)
    a.append_point(sid, 1, 1)
    sid2 = b.create_stroke({"color": "red"})
    b.append_point(sid2, 5, 5)
    h.process_all()
    assert len(a.get_strokes()) == len(b.get_strokes()) == 2
    assert a.get_stroke(sid)["points"] == b.get_stroke(sid)["points"]
    assert a.get_stroke(sid2)["pen"] == {"color": "red"}


# ------------------------------------------------------- SharedSummaryBlock


def test_summary_block_travels_via_summary_only():
    h = make_harness(1, [("sb", SummaryBlockFactory.type_name)])
    sb = h.channel(0, "sb")
    sb.set("format", {"v": 2})
    h.process_all()
    wire = h.runtimes[0].summarize().to_json()
    rt = ContainerRuntime(REGISTRY)
    rt.load(SummaryTree.from_json(wire))
    assert rt.get_datastore("default").get_channel("sb").get("format") == {"v": 2}


# --------------------------------------------------------- summary roundtrip


def test_consensus_summaries_roundtrip():
    h = make_harness(2, [
        ("q", ConsensusQueueFactory.type_name),
        ("r", RegisterCollectionFactory.type_name),
        ("p", PactMapFactory.type_name),
        ("i", InkFactory.type_name),
    ])
    q, r, p, i = (h.channel(0, c) for c in "qrpi")
    q.add("pending-job")
    r.write("reg", 42)
    p.set("pact", "v")
    sid = i.create_stroke({})
    i.append_point(sid, 1, 2)
    h.process_all()
    h.process_all()
    wire = h.runtimes[0].summarize().to_json()
    rt = ContainerRuntime(REGISTRY)
    rt.load(SummaryTree.from_json(wire))
    ds = rt.get_datastore("default")
    assert ds.get_channel("q").queue[0]["value"] == "pending-job"
    assert ds.get_channel("r").read("reg") == 42
    assert ds.get_channel("i").get_stroke(sid)["points"] == [
        {"x": 1, "y": 2, "pressure": 1.0}
    ]
