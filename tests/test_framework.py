"""Framework-layer tests: TpuClient/FluidContainer/ContainerSchema,
DataObject, undo-redo, attributor, agent-scheduler, telemetry, config.
"""

from __future__ import annotations

import pytest

from fluidframework_tpu.dds import (
    CounterFactory,
    MapFactory,
    SharedCounter,
    SharedMap,
    SharedString,
    StringFactory,
    TaskManagerFactory,
)
from fluidframework_tpu.framework import (
    AgentScheduler,
    Attributor,
    ContainerSchema,
    DataObject,
    DataObjectFactory,
    TpuClient,
    UndoRedoStackManager,
)
from fluidframework_tpu.framework.attributor import mixin_attributor
from fluidframework_tpu.framework.undo_redo import (
    SharedMapUndoRedoHandler,
    SharedStringUndoRedoHandler,
)
from fluidframework_tpu.server import LocalServer
from fluidframework_tpu.utils.config import ConfigProvider, MonitoringContext
from fluidframework_tpu.utils.telemetry import (
    ChildLogger,
    Lumberjack,
    MockLogger,
    PerformanceEvent,
)

SCHEMA = ContainerSchema(
    initial_objects={
        "text": StringFactory,
        "meta": MapFactory.type_name,
        "count": CounterFactory(),
    }
)


def test_client_create_attach_get_flow():
    server = LocalServer()
    client = TpuClient(server)
    c = client.create_container(SCHEMA)
    assert c.attach_state == "Detached"
    text = c.initial_objects["text"]
    text.insert_text(0, "draft")
    doc_id = c.attach()
    assert c.attach_state == "Attached"
    text.insert_text(0, "live ")
    c.flush()

    c2 = client.get_container(doc_id, SCHEMA)
    objs = c2.initial_objects
    assert objs["text"].get_text() == "live draft"
    objs["count"].increment(5)
    c2.flush()
    assert c.initial_objects["count"].value == 5


def test_container_dynamic_create():
    server = LocalServer()
    client = TpuClient(server)
    c = client.create_container(SCHEMA)
    c.attach()
    dyn = c.create(MapFactory, "extra")
    dyn.set("k", 1)
    c.flush()
    c2 = client.get_container(c.doc_id, SCHEMA)
    assert c2.runtime.get_datastore("default").get_channel("extra").get("k") == 1


def test_data_object_lifecycle():
    server = LocalServer()
    client = TpuClient(server)

    events = []

    class Todo(DataObject):
        def initializing_first_time(self, props=None):
            events.append("first")
            self.root.set("title", (props or {}).get("title", "untitled"))

        def initializing_from_existing(self):
            events.append("existing")

        def has_initialized(self):
            events.append("ready")

    factory = DataObjectFactory(Todo)
    c = client.create_container(ContainerSchema())
    ds = c.runtime.get_datastore("default")
    todo = factory.create(ds, {"title": "shopping"})
    assert todo.root.get("title") == "shopping"
    doc_id = c.attach()

    c2 = client.get_container(doc_id, ContainerSchema())
    todo2 = factory.load(c2.runtime.get_datastore("default"))
    assert todo2.root.get("title") == "shopping"
    assert events == ["first", "ready", "existing", "ready"]


# ----------------------------------------------------------------- undo/redo


def test_map_undo_redo():
    server = LocalServer()
    client = TpuClient(server)
    c = client.create_container(SCHEMA)
    c.attach()
    m: SharedMap = c.initial_objects["meta"]
    stack = UndoRedoStackManager()
    SharedMapUndoRedoHandler(stack, m)

    m.set("k", 1)
    stack.close_current_operation()
    m.set("k", 2)
    m.set("j", 9)
    stack.close_current_operation()
    c.flush()

    assert stack.undo_operation()
    c.flush()
    assert m.get("k") == 1 and not m.has("j")
    assert stack.undo_operation()
    c.flush()
    assert not m.has("k")
    assert stack.redo_operation()
    c.flush()
    assert m.get("k") == 1


def test_string_undo_redo():
    server = LocalServer()
    client = TpuClient(server)
    c = client.create_container(SCHEMA)
    c.attach()
    s: SharedString = c.initial_objects["text"]
    stack = UndoRedoStackManager()
    SharedStringUndoRedoHandler(stack, s)

    s.insert_text(0, "hello world")
    stack.close_current_operation()
    s.remove_text(0, 6)
    stack.close_current_operation()
    c.flush()
    assert s.get_text() == "world"

    assert stack.undo_operation()
    c.flush()
    assert s.get_text() == "hello world"
    assert stack.undo_operation()
    c.flush()
    assert s.get_text() == ""
    assert stack.redo_operation()
    c.flush()
    assert s.get_text() == "hello world"


# ---------------------------------------------------------------- attributor


def test_attributor_records_and_roundtrips():
    server = LocalServer()
    client = TpuClient(server)
    c = client.create_container(SCHEMA)
    c.attach()
    att = mixin_attributor(c.runtime)
    c2 = client.get_container(c.doc_id, SCHEMA)
    c.initial_objects["meta"].set("a", 1)
    c.flush()
    c2.initial_objects["meta"].set("b", 2)
    c2.flush()
    assert len(att) == 2
    entries = sorted(att.entries.items())
    assert entries[0][1]["client"] != entries[1][1]["client"]
    restored = Attributor.deserialize(att.serialize())
    assert restored.entries.keys() == att.entries.keys()
    for k in att.entries:
        assert restored.entries[k]["client"] == att.entries[k]["client"]
        assert abs(
            restored.entries[k]["timestamp"] - att.entries[k]["timestamp"]
        ) < 0.01


# ------------------------------------------------------------ agent scheduler


def test_agent_scheduler_failover():
    server = LocalServer()
    client = TpuClient(server)
    schema = ContainerSchema(initial_objects={"tasks": TaskManagerFactory})
    c1 = client.create_container(schema)
    doc = c1.attach()
    c2 = client.get_container(doc, schema)
    s1 = AgentScheduler(c1.initial_objects["tasks"])
    s2 = AgentScheduler(c2.initial_objects["tasks"])
    runs = []
    s1.pick("indexer", lambda: runs.append("c1"))
    c1.flush()
    s2.pick("indexer", lambda: runs.append("c2"))
    c2.flush()
    assert runs == ["c1"]
    assert s1.picked("indexer") and not s2.picked("indexer")
    c1.disconnect()  # holder leaves: task fails over
    assert runs == ["c1", "c2"]
    assert s2.picked("indexer")


# ------------------------------------------------------- telemetry & config


def test_telemetry_hierarchy_and_perf():
    log = MockLogger()
    child = ChildLogger(log, "runtime")
    child.send_telemetry_event("opProcessed", seq=5)
    assert log.matches({"eventName": "runtime:opProcessed", "seq": 5})
    with PerformanceEvent(child, "summarize"):
        pass
    assert any(
        e["category"] == "performance" and e["eventName"] == "runtime:summarize"
        for e in log.events
    )


def test_lumberjack_metrics():
    events = []
    # Lumberjack sinks are process-global; detach in teardown or this
    # test's sink would observe every later test's metrics.
    Lumberjack.add_sink(events.append)
    try:
        m = Lumberjack.new_metric("DeliProcessBatch", doc="d1")
        m.set_property("ops", 42)
        m.success("done")
        assert events[-1]["metric"] == "DeliProcessBatch"
        assert events[-1]["status"] == "success"
        assert events[-1]["ops"] == 42
    finally:
        Lumberjack.remove_sink(events.append)
    # The detached sink no longer observes anything.
    n = len(events)
    Lumberjack.new_metric("AfterDetach").success()
    assert len(events) == n
    # remove_sink is idempotent; reset clears in place so in-flight
    # metrics (holding the shared list) stop emitting too.
    Lumberjack.remove_sink(events.append)
    other = []
    Lumberjack.add_sink(other.append)
    inflight = Lumberjack.new_metric("InFlight")
    Lumberjack.reset()
    inflight.success()
    assert other == [] and Lumberjack._sinks == []


def test_config_provider_layering():
    cfg = ConfigProvider([{"Fluid.GC.Enabled": "true"}])
    cfg.add_provider({"Fluid.GC.Enabled": "false", "Fluid.Op.Max": 42})
    # First provider wins.
    assert cfg.get_bool("Fluid.GC.Enabled") is True
    assert cfg.get_number("Fluid.Op.Max") == 42
    assert cfg.get_string("Missing", "dflt") == "dflt"
    mc = MonitoringContext(MockLogger(), cfg)
    assert mc.child("sub").config is cfg
