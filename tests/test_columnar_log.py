"""Columnar binary op-log: codec round-trip, corruption recovery,
fencing, and cross-format migration.

The record-batch codec (`protocol.record_batch`) and its topic
(`server.columnar_log.ColumnarFileTopic`) must honor the exact
`SharedFileTopic` contract — torn tails never consumed, corrupt units
skipped but counted, fenced appends rejected with `FencedError`,
record offsets identical across every reader — while carrying the
raw-op fields as columns the kernel deli ingests with zero per-record
JSON decode. Mixed JSONL + binary histories replay in one file, so a
farm can switch formats across a restart mid-stream."""

from __future__ import annotations

import json
import os
import random

import pytest

from fluidframework_tpu.protocol.record_batch import (
    JsonBlob,
    K_GENERIC,
    K_RAW_OP,
    RecordBatch,
    decode_batch,
    encode_batch,
)
from fluidframework_tpu.server.columnar_log import (
    ColumnarFileTopic,
    ColumnarTailReader,
    make_tail_reader,
    make_topic,
)
from fluidframework_tpu.server.queue import FencedError, SharedFileTopic
from fluidframework_tpu.server.supervisor import DeliRole


# ---------------------------------------------------------------------------
# codec round-trip
# ---------------------------------------------------------------------------


def gen_records(seed: int, n: int = 400):
    """Random wire records across every columnar kind, plus generic
    odds-and-ends the codec must round-trip losslessly."""
    rng = random.Random(seed)
    recs = []
    for i in range(n):
        doc = f"doc{rng.randrange(7)}"
        r = rng.random()
        if r < 0.25:
            recs.append({"kind": "op", "doc": doc,
                         "client": rng.randint(-5, 10**7),
                         "clientSeq": rng.randrange(100),
                         "refSeq": rng.randrange(50),
                         "contents": rng.choice([
                             None, 0, "x", {"v": i}, [1, {"a": None}],
                         ])})
        elif r < 0.4:
            recs.append({"kind": rng.choice(["join", "leave"]),
                         "doc": doc, "client": rng.randint(-3, 99)})
        elif r < 0.5:
            recs.append({"kind": "boxcar", "doc": doc, "client": i,
                         "ops": [
                             {"clientSeq": j + 1, "refSeq": 0,
                              "contents": {"j": j}}
                             for j in range(rng.randrange(4))
                         ]})
        elif r < 0.7:
            recs.append({"kind": "op", "doc": doc, "seq": i + 1,
                         "msn": rng.randrange(i + 1),
                         "client": rng.randrange(64),
                         "clientSeq": rng.randrange(100),
                         "refSeq": 0,
                         "type": rng.choice(["op", "join", "leave"]),
                         "contents": {"v": rng.randrange(999)},
                         "inOff": i})
        elif r < 0.8:
            recs.append({"kind": "nack", "doc": doc,
                         "client": rng.randrange(64),
                         "clientSeq": rng.randrange(100), "code": 422,
                         "reason": "clientSeq 9, expected 2",
                         "inOff": i})
        else:
            # Generic: wrong key sets, non-dicts, nested values, floats
            recs.append(rng.choice([
                {"kind": "op", "doc": doc, "client": 1.5,  # float id
                 "clientSeq": 1, "refSeq": 0, "contents": None},
                {"weird": True, "deep": {"a": [i, None, "s"]}},
                ["bare", "list", i],
                "just a string",
                {"kind": "op", "doc": doc, "extra": 1, "client": 2,
                 "clientSeq": 1, "refSeq": 0, "contents": 0},
            ]))
    return recs


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_codec_roundtrip_property(seed):
    recs = gen_records(seed)
    frame = encode_batch(recs, fence=7, owner="w-1")
    batch, end, n = decode_batch(frame)
    assert end == len(frame) and n == len(recs)
    assert batch.fence == 7 and batch.owner == "w-1"
    assert batch.records() == recs
    # Per-record access matches bulk decode.
    assert [batch.record(i) for i in range(n)] == recs


def test_codec_columns_expose_raw_op_fields():
    recs = [
        {"kind": "op", "doc": "a", "client": 3, "clientSeq": 5,
         "refSeq": 2, "contents": {"v": 1}},
        {"kind": "join", "doc": "b", "client": -9},
        {"weird": 1},
    ]
    batch, _, _ = decode_batch(encode_batch(recs))
    assert batch.kind[0] == K_RAW_OP
    assert batch.kind[2] == K_GENERIC
    assert batch.docs[batch.doc_idx[0]] == "a"
    assert batch.docs[batch.doc_idx[1]] == "b"
    assert int(batch.client[0]) == 3
    assert int(batch.client_seq[0]) == 5
    assert int(batch.ref_seq[0]) == 2
    assert int(batch.client[1]) == -9
    # Blob side-by-side: contents bytes are directly reusable.
    assert json.loads(batch.blob(0)) == {"v": 1}


def test_jsonblob_passthrough_and_equality():
    blob = JsonBlob(b'{"v": 3}')
    assert blob == {"v": 3}
    assert blob == JsonBlob(b'{"v":3}')
    assert repr(blob) == repr({"v": 3})
    # A record carrying a JsonBlob encodes from the raw bytes (no
    # re-encode) and decodes to the plain value.
    rec = {"kind": "op", "doc": "d", "client": 1, "clientSeq": 1,
           "refSeq": 0, "contents": blob}
    batch, _, _ = decode_batch(encode_batch([rec]))
    assert batch.records()[0]["contents"] == {"v": 3}


def test_torn_frame_not_consumed_then_resumed():
    frame = encode_batch([{"k": i} for i in range(3)])
    for cut in (4, 10, len(frame) - 1):
        batch, end, n = decode_batch(frame[:cut])
        assert batch is None and n == -1 and end == 0
    batch, end, n = decode_batch(frame)
    assert batch is not None and n == 3


# ---------------------------------------------------------------------------
# topic semantics
# ---------------------------------------------------------------------------


def test_topic_offsets_and_tailreader_parity(tmp_path):
    topic = ColumnarFileTopic(str(tmp_path / "t.jsonl"))
    recs = gen_records(3, 120)
    for lo in range(0, len(recs), 17):
        topic.append_many(recs[lo:lo + 17])
    entries, nxt = topic.read_entries(0)
    assert [v for _, v in entries] == recs
    assert nxt == len(recs)
    # Arbitrary offsets + caps behave like SharedFileTopic.
    entries, nxt = topic.read_entries(40, max_count=10)
    assert [i for i, _ in entries] == list(range(40, 50)) and nxt == 50
    # TailReader offset translation lands mid-batch correctly.
    r = ColumnarTailReader(topic, 40)
    got = r.poll()
    assert [i for i, _ in got] == list(range(40, len(recs)))
    assert r.next_line == len(recs)
    # Beyond-EOF offsets never re-deliver earlier records.
    r2 = ColumnarTailReader(topic, len(recs) + 5)
    assert r2.poll() == []
    topic.append_many(recs[:8])  # 8 more records
    got = r2.poll()
    assert [i for i, _ in got] == [len(recs) + 5, len(recs) + 6,
                                   len(recs) + 7]


def test_crc_corruption_skips_batch_but_keeps_count(tmp_path):
    path = str(tmp_path / "t.jsonl")
    topic = ColumnarFileTopic(path)
    topic.append_many([{"k": i} for i in range(5)])
    topic.append_many([{"k": i} for i in range(5, 8)])
    data = bytearray(open(path, "rb").read())
    data[40] ^= 0xFF  # flip a byte inside the first frame's payload
    open(path, "wb").write(bytes(data))
    entries, nxt = topic.read_entries(0)
    # First batch skipped, its 5 records still counted; second intact.
    assert nxt == 8
    assert [(i, v["k"]) for i, v in entries] == [(5, 5), (6, 6), (7, 7)]


def test_torn_tail_invisible_and_sealed_by_next_append(tmp_path):
    path = str(tmp_path / "t.jsonl")
    topic = ColumnarFileTopic(path)
    topic.append_many([{"k": 0}])
    reader = ColumnarTailReader(topic)
    assert len(reader.poll()) == 1
    # A writer dies mid-append: raw junk past the committed length.
    with open(path, "ab") as f:
        f.write(b'\x00garbage{"torn": tru')
    assert topic.read_entries(0)[1] == 1  # invisible to offset readers
    assert reader.poll() == []  # and to tail readers
    topic.append_many([{"k": 1}])  # seals (truncates) the junk
    got = reader.poll()
    assert [(i, v["k"]) for i, v in got] == [(1, 1)]
    entries, nxt = topic.read_entries(0)
    assert nxt == 2 and [v["k"] for _, v in entries] == [0, 1]


def test_fenced_append_rejected_and_stamped(tmp_path):
    topic = ColumnarFileTopic(str(tmp_path / "t.jsonl"))
    assert topic.append_many([{"k": 1}], fence=5, owner="a") > 0
    with pytest.raises(FencedError):
        topic.append_many([{"k": 2}], fence=4, owner="b")
    with pytest.raises(FencedError):
        topic.append_many([], fence=5, owner="b")  # empty still gates
    # The accepted fence is stamped into the frame header for audit.
    data = open(topic.path, "rb").read()
    batch, _, _ = decode_batch(data)
    assert batch.fence == 5 and batch.owner == "a"
    assert topic.latest_fence() == (5, "a")


def test_mixed_history_json_then_columnar(tmp_path):
    """A topic written as JSONL continues as a columnar log in the
    SAME file: offsets count straight through both regions."""
    path = str(tmp_path / "t.jsonl")
    SharedFileTopic(path).append_many([{"j": i} for i in range(4)])
    topic = make_topic(path, "columnar")
    topic.append_many([{"c": i} for i in range(3)])
    entries, nxt = topic.read_entries(0)
    assert nxt == 7
    assert [v for _, v in entries] == \
        [{"j": i} for i in range(4)] + [{"c": i} for i in range(3)]
    # Incremental reader sees the same stream.
    assert [v for _, v in ColumnarTailReader(topic).poll()] == \
        [v for _, v in entries]


def test_codec_metrics_reported():
    from fluidframework_tpu.utils import metrics as M

    reg = M.MetricsRegistry()
    prev = M.set_registry(reg)
    try:
        frame = encode_batch([{"k": 1}, {"k": 2}])
        batch, _, _ = decode_batch(frame)
        batch.records()
    finally:
        M.set_registry(prev)
    assert reg.counter("codec_encode_records_total",
                       codec="columnar").value == 2
    assert reg.counter("codec_encode_bytes_total",
                       codec="columnar").value == len(frame)
    assert reg.counter("codec_decode_records_total",
                       codec="columnar").value == 2
    # And the report tool renders them.
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    from metrics_report import codec_report

    text = codec_report(reg.snapshot())
    assert "encode" in text and "decode" in text


# ---------------------------------------------------------------------------
# farm-level migration (JSON log -> columnar log mid-stream)
# ---------------------------------------------------------------------------


def _wire_workload(n_docs=2, n_clients=2, ops=6):
    recs = []
    for d in range(n_docs):
        doc = f"doc{d}"
        for c in range(1, n_clients + 1):
            recs.append({"kind": "join", "doc": doc, "client": c})
        for i in range(ops):
            for c in range(1, n_clients + 1):
                recs.append({"kind": "op", "doc": doc, "client": c,
                             "clientSeq": i + 1, "refSeq": 0,
                             "contents": {"i": i, "c": c}})
    return recs


def _oracle(recs, scratch):
    role = DeliRole(str(scratch), owner="oracle", ttl_s=3600.0)
    out = []
    for i, r in enumerate(recs):
        role.process(i, r, out)
    role.flush_batch(out)
    return [{k: v for k, v in r.items() if k != "reason"} for r in out]


@pytest.mark.parametrize("impl", ["scalar", "kernel"])
def test_cross_format_migration_via_checkpoint_restore(impl, tmp_path):
    """Run half the stream over JSONL topics, checkpoint, then restart
    the role with log_format="columnar" over the SAME topic files and
    finish: offsets and the output stream must be seamless (zero dup,
    zero skip, oracle-identical)."""
    if impl == "kernel":
        from fluidframework_tpu.server.deli_kernel import KernelDeliRole
        role_cls = KernelDeliRole
    else:
        role_cls = DeliRole

    shared = str(tmp_path / "farm")
    recs = _wire_workload()
    half = len(recs) // 2
    raw_path = os.path.join(shared, "topics", "rawdeltas.jsonl")
    SharedFileTopic(raw_path).append_many(recs[:half])

    r1 = role_cls(shared, owner="g1", ttl_s=3600.0, batch=16,
                  log_format="json")
    while r1.step():
        pass
    r1.checkpoint()
    r1.leases.release("deli")

    # The columnar era: same topic files, binary appends from here on.
    make_topic(raw_path, "columnar").append_many(recs[half:])
    r2 = role_cls(shared, owner="g2", ttl_s=3600.0, batch=16,
                  log_format="columnar")
    while r2.step():
        pass

    deltas = make_topic(
        os.path.join(shared, "topics", "deltas.jsonl"), "columnar"
    )
    got = [{k: v for k, v in r.items() if k not in ("reason", "inOff")}
           for r in deltas.read_from(0)]
    want = [{k: v for k, v in r.items() if k != "inOff"}
            for r in _oracle(recs, tmp_path / "oracle")]
    assert got == want


def test_localserver_columnar_persist_and_format_switch(tmp_path):
    """LocalServer(log_format="columnar") persists journals as record
    batches; a restart — including a restart that SWITCHES formats —
    resumes the documents (checkpoint/restore interop)."""
    from fluidframework_tpu.dds import StringFactory
    from fluidframework_tpu.runtime import ChannelRegistry, ContainerRuntime
    from fluidframework_tpu.server import LocalServer

    registry = ChannelRegistry([StringFactory()])
    persist = str(tmp_path / "srv")

    def connect(server, cid):
        rt = ContainerRuntime(registry)
        rt.create_datastore("default").create_channel(
            "s", StringFactory.type_name
        )
        rt.connect(server.connect("doc", cid))
        return rt

    srv = LocalServer(persist_dir=persist, log_format="json")
    rt1 = connect(srv, 1)
    s1 = rt1.get_datastore("default").get_channel("s")
    s1.insert_text(0, "json era")
    rt1.flush()

    # Restart columnar over the same persist_dir (mid-journal switch).
    srv2 = LocalServer(persist_dir=persist, log_format="columnar")
    rt2 = connect(srv2, 5)
    s2 = rt2.get_datastore("default").get_channel("s")
    assert s2.get_text() == "json era"
    s2.insert_text(0, "col era>")
    rt2.flush()

    # And once more, proving the columnar journal replays too.
    srv3 = LocalServer(persist_dir=persist, log_format="columnar")
    rt3 = connect(srv3, 9)
    assert rt3.get_datastore("default").get_channel("s").get_text() == \
        "col era>json era"


def test_localserver_rejects_unknown_log_format():
    from fluidframework_tpu.server import LocalServer

    with pytest.raises(ValueError):
        LocalServer(log_format="colmnar")


def test_tailreader_next_line_holds_at_beyond_eof_offset(tmp_path):
    """A checkpointed offset ahead of the topic must KEEP
    next_line == offset while idle (the TailReader contract) — a
    consumer's staleness check (`reader.next_line != offset`) must not
    rebuild the reader in a loop."""
    topic = ColumnarFileTopic(str(tmp_path / "t.jsonl"))
    topic.append_many([{"k": i} for i in range(3)])
    r = ColumnarTailReader(topic, 7)
    assert r.next_line == 7
    assert r.poll() == []
    assert r.next_line == 7  # unchanged: nothing below 7 delivered
    topic.append_many([{"k": i} for i in range(3, 9)])  # records 3..8
    got = r.poll()
    assert [(i, v["k"]) for i, v in got] == [(7, 7), (8, 8)]
    assert r.next_line == 9


def test_read_entries_max_count_zero_matches_sharedfiletopic(tmp_path):
    """max_count=0 takes nothing and leaves the offset alone — the
    SharedFileTopic drop-in contract."""
    topic = ColumnarFileTopic(str(tmp_path / "t.jsonl"))
    topic.append_many([{"k": 1}, {"k": 2}])
    assert topic.read_entries(0, max_count=0) == ([], 0)
    assert topic.read_entries(1, max_count=0) == ([], 1)


def test_journal_corruption_keeps_offsets_stable(tmp_path):
    """LocalServer journal replay holds a LOST_RECORD slot for a
    CRC-corrupt frame instead of dropping it, so lambda checkpoints
    citing absolute offsets stay aligned after restart (the columnar
    skip-but-COUNT rule applied to the in-proc journal)."""
    from fluidframework_tpu.server.log import LOST_RECORD, LogTopic

    path = str(tmp_path / "topic.jsonl")
    t = LogTopic("t", path, log_format="columnar")
    t.append_many([{"k": i} for i in range(4)])
    t.append_many([{"k": 9}])
    t._file.close()
    data = bytearray(open(path, "rb").read())
    data[40] ^= 0xFF  # corrupt the first frame's payload in place
    open(path, "wb").write(bytes(data))
    t2 = LogTopic("t", path, log_format="columnar")
    # 4 lost slots + the intact second frame, offsets unchanged.
    assert t2.head == 5
    assert t2.read(0, 4) == [LOST_RECORD] * 4
    assert t2.read(4) == [{"k": 9}]
    # The deli frontends treat the placeholder as a no-op record.
    from fluidframework_tpu.server.lambdas import DeliLambda
    from fluidframework_tpu.server.log import MessageLog

    log = MessageLog()
    log.topic("rawdeltas").append_many(
        [LOST_RECORD, {"doc": "d", "kind": "join", "client": 1},
         LOST_RECORD]
    )
    deli = DeliLambda(log)
    assert deli.pump() == 3
    assert len(log.topic("deltas").read(0)) == 1  # only the join stamped


def test_format_round_trip_never_truncates_acknowledged_records(tmp_path):
    """columnar -> json -> columnar over one topic file: the dormant
    committed-length sidecar from the first columnar era must NOT hide
    or truncate the JSON era's acknowledged records — the sealer
    extends over complete units and only a torn suffix is removed."""
    path = str(tmp_path / "t.jsonl")
    ColumnarFileTopic(path).append_many([{"era": "col", "k": i}
                                         for i in range(3)])
    # JSON era: SharedFileTopic appends lines, sidecar goes stale.
    SharedFileTopic(path).append_many([{"era": "json", "k": i}
                                       for i in range(4)])
    # Columnar again: reads see everything, appends lose nothing.
    # (The JSON appender sealed the binary tail with a newline — one
    # counted blank-line unit between the eras, delivered to no one.)
    topic = ColumnarFileTopic(path)
    entries, nxt = topic.read_entries(0)
    assert nxt == 8 and len(entries) == 7
    topic.append_many([{"era": "col2", "k": 0}])
    entries, nxt = topic.read_entries(0)
    assert nxt == 9
    assert [v["era"] for _, v in entries] == \
        ["col"] * 3 + ["json"] * 4 + ["col2"]


# ---------------------------------------------------------------------------
# frame-header corruption: bounded magic-resync
# ---------------------------------------------------------------------------


def _poison_header(path, frame_start):
    """Garble a frame's version byte in place (extent unknowable)."""
    data = bytearray(open(path, "rb").read())
    data[frame_start + 4] = 0x63
    open(path, "wb").write(bytes(data))


def test_header_corruption_resyncs_instead_of_stalling(tmp_path):
    """A corrupted frame HEADER used to read as a torn tail and stall
    readers forever; the bounded magic-scan now skips-but-counts the
    poisoned region (ONE record slot) and resumes at the next valid
    frame."""
    path = str(tmp_path / "t.jsonl")
    topic = ColumnarFileTopic(path)
    topic.append_many([{"k": i} for i in range(3)])
    first_len = os.path.getsize(path)
    topic.append_many([{"k": 3}, {"k": 4}])
    topic.append_many([{"k": 5}])
    _poison_header(path, first_len)  # second frame's header

    entries, nxt = topic.read_entries(0)
    # Frame 1 (3 records) + poison slot (1) + frame 3 (1 record).
    assert [(i, v["k"]) for i, v in entries] == \
        [(0, 0), (1, 1), (2, 2), (4, 5)]
    assert nxt == 5
    # The incremental reader agrees (offset parity across readers).
    r = ColumnarTailReader(topic)
    got = r.poll()
    assert [(i, v["k"]) for i, v in got] == \
        [(0, 0), (1, 1), (2, 2), (4, 5)]
    assert r.next_line == 5
    # And the stream keeps flowing past the poison.
    topic.append_many([{"k": 6}])
    assert [(i, v["k"]) for i, v in r.poll()] == [(5, 6)]


def test_header_corruption_resyncs_to_json_lines(tmp_path):
    """Mixed history: a poisoned frame followed by JSONL records
    resyncs at the first complete parseable line. The JSON appender's
    torn-tail SEAL newline delimits the junk, so even the first line
    after the poison survives."""
    path = str(tmp_path / "t.jsonl")
    topic = ColumnarFileTopic(path)
    topic.append_many([{"k": 0}])
    first_len = os.path.getsize(path)
    topic.append_many([{"k": 1}])
    SharedFileTopic(path).append_many([{"j": 0}, {"j": 1}, {"j": 2}])
    _poison_header(path, first_len)

    entries, nxt = topic.read_entries(0)
    # Frame 1 + poison slot (the garbled frame 2, sealed by the JSON
    # appender's newline) + every json line.
    assert [v for _, v in entries] == \
        [{"k": 0}, {"j": 0}, {"j": 1}, {"j": 2}]
    assert [i for i, _ in entries] == [0, 2, 3, 4]
    assert nxt == 5


def test_header_corruption_waits_for_unconfirmed_resync(tmp_path):
    """Poison followed by a TORN frame (an append that may still be in
    flight) must not be consumed yet — the scan resumes on a later
    poll once the frame completes."""
    from fluidframework_tpu.protocol.record_batch import encode_batch

    path = str(tmp_path / "t.jsonl")
    topic = ColumnarFileTopic(path)
    topic.append_many([{"k": 0}])
    first_len = os.path.getsize(path)
    topic.append_many([{"k": 1}])
    _poison_header(path, first_len)
    tail_frame = encode_batch([{"k": 2}])
    with open(path, "ab") as f:
        f.write(tail_frame[:len(tail_frame) - 3])  # torn candidate
    entries, nxt = topic.read_entries(0)
    assert [v for _, v in entries] == [{"k": 0}] and nxt == 1
    with open(path, "ab") as f:
        f.write(tail_frame[len(tail_frame) - 3:])  # append completes
    entries, nxt = topic.read_entries(0)
    assert [v for _, v in entries] == [{"k": 0}, {"k": 2}]
    assert nxt == 3


def test_journal_replay_counts_poisoned_region_one_slot(tmp_path):
    """LocalServer journal replay holds ONE LOST_RECORD slot for a
    header-poisoned region (the resync rule applied to the in-proc
    journal), so later records keep their offsets."""
    from fluidframework_tpu.server.log import LOST_RECORD, LogTopic

    path = str(tmp_path / "topic.jsonl")
    t = LogTopic("t", path, log_format="columnar")
    t.append_many([{"k": 0}, {"k": 1}])
    t._file.flush()
    first_len = os.path.getsize(path)
    t.append_many([{"k": 2}])
    t.append_many([{"k": 3}])
    t._file.close()
    _poison_header(path, first_len)
    t2 = LogTopic("t", path, log_format="columnar")
    assert t2.head == 4  # 2 + 1 poison slot + 1
    assert t2.read(0) == [{"k": 0}, {"k": 1}, LOST_RECORD, {"k": 3}]


# ---------------------------------------------------------------------------
# scalar DeliRole columnar ingest (batch columns, no lazy JSON)
# ---------------------------------------------------------------------------


def test_scalar_role_columnar_ingest_matches_json(tmp_path):
    """`DeliRole.process_batch` (columnar batch-column ingest) must
    produce the byte-identical stream the same role produces over a
    JSONL topic — including boxcar atomicity, duplicate-join drops,
    resubmission dedup and nacks."""
    recs = _wire_workload(n_docs=2, n_clients=3, ops=10)
    # Adversarial riders: resubmission (dup op), duplicate join, a
    # boxcar, an unknown-client nack, a foreign record.
    recs += [
        recs[len(recs) // 2],                      # resubmission
        {"kind": "join", "doc": "doc0", "client": 1},   # dup join
        {"kind": "boxcar", "doc": "doc1", "client": 2, "ops": [
            {"clientSeq": 11, "refSeq": 0, "contents": {"b": 0}},
            {"clientSeq": 12, "refSeq": 0, "contents": {"b": 1}},
        ]},
        {"kind": "op", "doc": "doc0", "client": 99, "clientSeq": 1,
         "refSeq": 0, "contents": None},           # unknown client
        {"weird": True},                           # foreign junk
    ]

    json_shared = str(tmp_path / "json")
    SharedFileTopic(
        os.path.join(json_shared, "topics", "rawdeltas.jsonl")
    ).append_many(recs)
    rj = DeliRole(json_shared, owner="j", ttl_s=3600.0, batch=32,
                  log_format="json")
    while rj.step():
        pass

    col_shared = str(tmp_path / "col")
    col_raw = make_topic(
        os.path.join(col_shared, "topics", "rawdeltas.jsonl"), "columnar"
    )
    for lo in range(0, len(recs), 16):
        col_raw.append_many(recs[lo:lo + 16])
    rc = DeliRole(col_shared, owner="c", ttl_s=3600.0, batch=32,
                  log_format="columnar")
    assert rc.ingest_batches and rc.out_columnar
    while rc.step():
        pass

    def canon(shared):
        deltas = make_topic(
            os.path.join(shared, "topics", "deltas.jsonl"), "columnar"
        )
        return [{k: v for k, v in r.items()
                 if k not in ("reason", "inOff")}
                for r in deltas.read_from(0)]

    got_json, got_col = canon(json_shared), canon(col_shared)
    assert got_col == got_json
    assert any(r["kind"] == "nack" for r in got_json)  # riders fired


def test_scalar_role_columnar_blob_passthrough(tmp_path):
    """Over a columnar out topic, standalone op contents must ride as
    raw pre-encoded blobs (JsonBlob) end to end — the kernel role's
    zero-JSON rule, now on the scalar path too."""
    from fluidframework_tpu.protocol.record_batch import JsonBlob

    shared = str(tmp_path / "farm")
    raw = make_topic(
        os.path.join(shared, "topics", "rawdeltas.jsonl"), "columnar"
    )
    raw.append_many([
        {"kind": "join", "doc": "d", "client": 1},
        {"kind": "op", "doc": "d", "client": 1, "clientSeq": 1,
         "refSeq": 0, "contents": {"v": 42}},
    ])
    role = DeliRole(shared, owner="w", ttl_s=3600.0,
                    log_format="columnar")
    role.fence = 1
    out = []
    reader = make_tail_reader(role.in_topic)
    for unit in reader.poll_batches(64):
        role.process_batch(unit[1], unit[2], out)
    ops = [r for r in out if r.get("type") == "op"]
    assert ops and isinstance(ops[0]["contents"], JsonBlob)
    assert ops[0]["contents"] == {"v": 42}
