"""Differential suite: the batched kernel deli vs the scalar oracle,
wired into the LIVE pipeline.

Identical random traffic — joins, leaves, boxcars (including
mid-boxcar nacks), control messages, resubmissions — is driven through
the scalar `DeliLambda`/`DeliRole` and the kernel
`KernelDeliLambda`/`KernelDeliRole`; stamps, nack codes, and MSNs must
match exactly (the deli ticketing contract). Checkpoints are
interchangeable across impls (scalar is the restore fallback), doc
slots grow/evict transparently, and a chaos kill-fault run with the
kernel deli converges bit-identical to the scalar golden with zero
duplicate/skipped seqs (exactly-once preserved under batching).
"""

from __future__ import annotations

import random

import pytest

from fluidframework_tpu.protocol.messages import (
    DocumentMessage,
    MessageType,
    SequencedMessage,
)
from fluidframework_tpu.server.deli_kernel import (
    KernelDeliLambda,
    KernelDeliRole,
)
from fluidframework_tpu.server.lambdas import DeliLambda
from fluidframework_tpu.server.log import MessageLog
from fluidframework_tpu.server.supervisor import DeliRole


# ---------------------------------------------------------------------------
# traffic generators
# ---------------------------------------------------------------------------


def gen_raw_traffic(seed: int, n: int = 300, docs: int = 3,
                    clients: int = 4):
    """In-proc raw records: joins/leaves/controls/boxcars/ops with
    deliberately invalid submissions (clientSeq gaps, future/stale
    refSeqs, unknown clients) sprinkled in. A shadow model only shapes
    plausibility; correctness is judged by the oracle."""
    rng = random.Random(seed)
    recs = []
    state = {}
    conn = {d: set() for d in range(docs)}
    seqg = {d: 0 for d in range(docs)}
    for _ in range(n):
        d = rng.randrange(docs)
        doc = f"doc{d}"
        r = rng.random()
        if r < 0.10 or not conn[d]:
            c = rng.randrange(1, clients + 1)
            recs.append({"doc": doc, "kind": "join", "client": c})
            conn[d].add(c)
            state[(d, c)] = 0
            seqg[d] += 1
        elif r < 0.15:
            c = rng.randrange(1, clients + 1)
            was = c in conn[d]
            recs.append({"doc": doc, "kind": "leave", "client": c})
            conn[d].discard(c)
            if was:
                seqg[d] += 1
        elif r < 0.20:
            recs.append({"doc": doc, "kind": "control",
                         "type": MessageType.SUMMARY_ACK,
                         "contents": {"handle": "h", "n": rng.randrange(9)}})
            seqg[d] += 1
        elif r < 0.35:
            c = rng.choice(sorted(conn[d]))
            msgs = []
            for _ in range(rng.randrange(2, 6)):
                cs = state[(d, c)] + 1
                ref = rng.randint(max(0, seqg[d] - 3), seqg[d])
                bad = rng.random()
                if bad < 0.15:
                    cs += rng.randint(1, 2)  # clientSeq gap -> nack
                elif bad < 0.22:
                    ref = seqg[d] + rng.randint(1, 4)  # future refSeq
                msgs.append(DocumentMessage(client_seq=cs, ref_seq=ref,
                                            contents={"b": 1}))
                if cs == state[(d, c)] + 1 and 0 <= ref <= seqg[d]:
                    state[(d, c)] = cs
                    seqg[d] += 1
                else:
                    break  # shadow: the rest of the boxcar aborts
            recs.append({"doc": doc, "kind": "boxcar", "client": c,
                         "msgs": msgs})
        else:
            c = rng.choice(sorted(conn[d]))
            cs = state[(d, c)] + 1
            ref = rng.randint(max(0, seqg[d] - 3), seqg[d])
            bad = rng.random()
            if bad < 0.06:
                cs += 1
            elif bad < 0.10:
                ref = seqg[d] + 2
            elif bad < 0.14:
                c2 = rng.randrange(1, clients + 1)
                if c2 not in conn[d]:
                    c = c2  # unknown client
            recs.append({"doc": doc, "kind": "op", "client": c,
                         "msg": DocumentMessage(client_seq=cs, ref_seq=ref,
                                                contents={"v": rng.randrange(99)})})
            if (c in conn[d] and cs == state.get((d, c), -10) + 1
                    and 0 <= ref <= seqg[d]):
                state[(d, c)] = cs
                seqg[d] += 1
    return recs


def norm_entry(e):
    """Deltas entry minus the timestamp (wall-clock differs by impl)."""
    m = e["msg"]
    if isinstance(m, SequencedMessage):
        return (e["doc"], e["kind"], m.sequence_number,
                m.minimum_sequence_number, m.client_id, m.client_seq,
                m.ref_seq, str(m.type), repr(m.contents))
    return (e["doc"], e["kind"], e["client"], m.client_seq, m.code)


def run_inproc(deli_cls, recs, checkpoint=None, log=None, **kw):
    log = log or MessageLog()
    for r in recs:
        log.topic("rawdeltas").append(r)
    deli = deli_cls(log, checkpoint, **kw)
    while deli.pump():
        pass
    return log, deli


# ---------------------------------------------------------------------------
# in-proc differential
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_inproc_kernel_matches_scalar(seed):
    recs = gen_raw_traffic(seed)
    log1, _ = run_inproc(DeliLambda, recs)
    # Small max_pump forces many micro-batches (multi-chunk coverage).
    log2, _ = run_inproc(KernelDeliLambda, recs, max_pump=37)
    o1 = [norm_entry(e) for e in log1.topic("deltas").read(0)]
    o2 = [norm_entry(e) for e in log2.topic("deltas").read(0)]
    assert o1 == o2
    assert o1, "traffic produced no outputs?"


def test_boxcar_abort_masks_rest_of_batch():
    """A mid-boxcar nack must abort the REST of the boxcar — and only
    that boxcar — identically in both impls."""
    msgs = [
        DocumentMessage(client_seq=1, ref_seq=0),
        DocumentMessage(client_seq=5, ref_seq=0),  # gap -> nack 422
        DocumentMessage(client_seq=2, ref_seq=0),  # masked out
    ]
    recs = [
        {"doc": "d", "kind": "join", "client": 1},
        {"doc": "d", "kind": "boxcar", "client": 1, "msgs": msgs},
        # A later standalone op still sequences (abort is boxcar-local).
        {"doc": "d", "kind": "op", "client": 1,
         "msg": DocumentMessage(client_seq=2, ref_seq=0)},
    ]
    log1, _ = run_inproc(DeliLambda, recs)
    log2, _ = run_inproc(KernelDeliLambda, recs)
    o1 = [norm_entry(e) for e in log1.topic("deltas").read(0)]
    o2 = [norm_entry(e) for e in log2.topic("deltas").read(0)]
    assert o1 == o2
    kinds = [e[1] for e in o1]
    assert kinds == ["op", "op", "nack", "op"]  # join, op1, nack, op2


def test_control_messages_stamp_via_system_path():
    recs = [
        {"doc": "d", "kind": "control", "type": MessageType.SUMMARY_ACK,
         "contents": {"handle": "x"}},
        {"doc": "d", "kind": "join", "client": 1},
        {"doc": "d", "kind": "control", "type": MessageType.SUMMARY_NACK,
         "contents": {"message": "no"}},
    ]
    log1, _ = run_inproc(DeliLambda, recs)
    log2, _ = run_inproc(KernelDeliLambda, recs)
    o1 = [norm_entry(e) for e in log1.topic("deltas").read(0)]
    o2 = [norm_entry(e) for e in log2.topic("deltas").read(0)]
    assert o1 == o2
    m = log2.topic("deltas").read(0)[0]["msg"]
    assert m.client_id == -1 and m.sequence_number == 1


@pytest.mark.parametrize("seed", [3, 4])
def test_checkpoint_restore_cross_impl(seed):
    """Run half the stream, checkpoint, restore into EITHER impl,
    finish — all four (impl x impl) paths emit identical tails."""
    recs = gen_raw_traffic(seed, n=240)
    half = len(recs) // 2

    log_a, deli_a = run_inproc(DeliLambda, recs[:half])
    log_b, deli_b = run_inproc(KernelDeliLambda, recs[:half])
    cp_a, cp_b = deli_a.checkpoint(), deli_b.checkpoint()
    assert cp_a["offset"] == cp_b["offset"]

    tails = []
    for cp, base in ((cp_a, "scalar"), (cp_b, "kernel")):
        for cls in (DeliLambda, KernelDeliLambda):
            log = MessageLog()
            for r in recs[:half]:
                log.topic("rawdeltas").append(r)  # replayed topic
            mark = log.topic("deltas").head
            for r in recs[half:]:
                log.topic("rawdeltas").append(r)
            deli = cls(log, cp)
            while deli.pump():
                pass
            tails.append([norm_entry(e)
                          for e in log.topic("deltas").read(mark)])
    assert tails[0] == tails[1] == tails[2] == tails[3]
    assert tails[0], "no tail outputs?"


def test_doc_slot_grow_and_evict():
    """Many docs through a tiny resident budget: slots grow, evict
    (park), and reload transparently — outputs stay oracle-identical."""
    rng = random.Random(9)
    recs = []
    for d in range(40):
        recs.append({"doc": f"doc{d}", "kind": "join", "client": 1})
    for i in range(6):
        for d in rng.sample(range(40), 25):
            recs.append({"doc": f"doc{d}", "kind": "op", "client": 1,
                         "msg": DocumentMessage(client_seq=i + 1, ref_seq=0,
                                                contents=i)})
    log1, _ = run_inproc(DeliLambda, recs)
    # Small pumps keep the per-pump active set under the resident
    # budget, so allocation pressure must evict (park) cold docs.
    log2, deli2 = run_inproc(KernelDeliLambda, recs, max_pump=16,
                             n_docs=4, max_resident=8)
    o1 = [norm_entry(e) for e in log1.topic("deltas").read(0)]
    o2 = [norm_entry(e) for e in log2.topic("deltas").read(0)]
    assert o1 == o2
    pool = deli2.core.pool
    assert len(pool.docs) == 40  # every doc accounted for (some parked)
    assert pool.resident_docs() < 40  # eviction actually happened
    # Checkpoint covers parked docs too.
    assert len(deli2.checkpoint()["docs"]) == 40


def test_foreign_and_negative_client_ids_match_oracle():
    """Arbitrary client ids — negative, huge, never-joined — must get
    the oracle's verdicts via the per-doc column map (an unknown id
    rides the scratch column and can never alias a real client's
    state). Covers: op from unknown id between valid ops, join/leave
    of a negative id (the scalar oracle ACCEPTS those), boxcar from an
    unknown id."""
    recs = [
        {"doc": "d", "kind": "join", "client": 1},
        {"doc": "d", "kind": "op", "client": 1,
         "msg": DocumentMessage(client_seq=1, ref_seq=0)},
        # unknown ids probing between client 1's valid ops
        {"doc": "d", "kind": "op", "client": -1,
         "msg": DocumentMessage(client_seq=1, ref_seq=0)},
        {"doc": "d", "kind": "op", "client": 10**6,
         "msg": DocumentMessage(client_seq=1, ref_seq=0)},
        {"doc": "d", "kind": "leave", "client": -7},  # unknown: no stamp
        {"doc": "d", "kind": "op", "client": 1,
         "msg": DocumentMessage(client_seq=2, ref_seq=1)},
        # the oracle happily admits a negative id; so must the kernel
        {"doc": "d", "kind": "join", "client": -3},
        {"doc": "d", "kind": "op", "client": -3,
         "msg": DocumentMessage(client_seq=1, ref_seq=0)},
        {"doc": "d", "kind": "boxcar", "client": -9, "msgs": [
            DocumentMessage(client_seq=1, ref_seq=0),
            DocumentMessage(client_seq=2, ref_seq=0),  # aborted tail
        ]},
        {"doc": "d", "kind": "leave", "client": -3},
        {"doc": "d", "kind": "op", "client": 1,
         "msg": DocumentMessage(client_seq=3, ref_seq=2)},
    ]
    log1, _ = run_inproc(DeliLambda, recs)
    log2, _ = run_inproc(KernelDeliLambda, recs, max_pump=3)
    o1 = [norm_entry(e) for e in log1.topic("deltas").read(0)]
    o2 = [norm_entry(e) for e in log2.topic("deltas").read(0)]
    assert o1 == o2
    # and in the role frontend (wire records, dedup mode)
    import tempfile

    wire = [
        {"kind": "join", "doc": "d", "client": 1},
        {"kind": "op", "doc": "d", "client": 1, "clientSeq": 1,
         "refSeq": 0, "contents": 1},
        {"kind": "op", "doc": "d", "client": -1, "clientSeq": 1,
         "refSeq": 0, "contents": 2},
        {"kind": "join", "doc": "d", "client": -2},
        {"kind": "op", "doc": "d", "client": -2, "clientSeq": 1,
         "refSeq": 0, "contents": 3},
        {"kind": "op", "doc": "d", "client": 1, "clientSeq": 2,
         "refSeq": 0, "contents": 4},
    ]
    r1 = DeliRole(tempfile.mkdtemp(), owner="s", ttl_s=3600.0)
    r2 = KernelDeliRole(tempfile.mkdtemp(), owner="k", ttl_s=3600.0)
    w1, w2 = [], []
    for i, r in enumerate(wire):
        r1.process(i, r, w1)
        r2.process(i, r, w2)
    r1.flush_batch(w1)
    r2.flush_batch(w2)
    assert [strip_reason(x) for x in w1] == [strip_reason(x) for x in w2]


def test_tailreader_beyond_eof_offset_never_redelivers(tmp_path):
    """A checkpointed line offset past the topic's current end (file
    truncated/restored) must behave like read_entries: deliver nothing
    below the offset, ever — not clamp and re-deliver old lines."""
    from fluidframework_tpu.server.queue import SharedFileTopic, TailReader

    topic = SharedFileTopic(str(tmp_path / "t.jsonl"))
    topic.append_many([{"i": i} for i in range(5)])
    r = TailReader(topic, line_offset=8)  # 3 lines beyond EOF
    assert r.next_line == 8
    assert r.poll() == []
    topic.append_many([{"i": i} for i in range(5, 12)])  # lines 5..11
    got = r.poll()
    # lines 5..7 swallowed silently (below the offset); 8..11 delivered
    assert [(i, v["i"]) for i, v in got] == [(8, 8), (9, 9), (10, 10),
                                            (11, 11)]
    assert r.next_line == 12
    # parity with the non-incremental reader
    entries, nxt = topic.read_entries(8)
    assert entries == got and nxt == 12


def test_seqpool_resident_budget_enforced():
    """max_resident is a working budget, not a hint: once resident docs
    reach it, cold docs are parked to make room instead of growing."""
    from fluidframework_tpu.server.deli_kernel import SeqPool

    pool = SeqPool(n_docs=4, n_clients=2, max_resident=6)
    for pump in range(10):
        pool.begin()
        for d in range(pump * 3, pump * 3 + 3):  # 3 active docs/pump
            pool.touch(f"doc{d}")
        pool._loads = []  # state rows unused here; budget is the point
        assert pool.resident_docs() <= 6, (pump, pool.resident_docs())
    assert len(pool.docs) == 30  # every doc still accounted for


def test_localserver_rejects_unknown_deli_impl():
    from fluidframework_tpu.server import LocalServer

    with pytest.raises(ValueError):
        LocalServer(deli_impl="kernl")


def test_localserver_kernel_deli_end_to_end():
    """LocalServer(deli_impl="kernel") is a drop-in: clients collab and
    converge through the full lambda pipeline, and a restart from
    checkpoints (restored by the SCALAR impl — the fallback) works."""
    from fluidframework_tpu.dds import StringFactory
    from fluidframework_tpu.runtime import ChannelRegistry, ContainerRuntime
    from fluidframework_tpu.server import LocalServer

    registry = ChannelRegistry([StringFactory()])

    def connect(server, client_id):
        rt = ContainerRuntime(registry)
        rt.create_datastore("default").create_channel(
            "s", StringFactory.type_name
        )
        rt.connect(server.connect("doc", client_id))
        return rt

    server = LocalServer(deli_impl="kernel")
    rt1, rt2 = connect(server, 1), connect(server, 2)
    s1 = rt1.get_datastore("default").get_channel("s")
    s2 = rt2.get_datastore("default").get_channel("s")
    s1.insert_text(0, "hello kernel")
    rt1.flush()
    s2.insert_text(0, ">> ")
    rt2.flush()
    assert s1.get_text() == s2.get_text() == ">> hello kernel"

    # Restart on the scalar impl from the kernel's checkpoints.
    server2 = LocalServer(storage=server.storage, log=server.log,
                          checkpoints=server.checkpoints(),
                          deli_impl="scalar")
    assert server2.deli.sequencers["doc"].seq == \
        server.deli.checkpoint()["docs"]["doc"]["seq"]
    rt3 = connect(server2, 9)
    assert rt3.get_datastore("default").get_channel("s").get_text() == \
        ">> hello kernel"


# ---------------------------------------------------------------------------
# supervised-role differential (wire records + dedup)
# ---------------------------------------------------------------------------


def gen_wire_traffic(seed: int, docs: int = 3, clients: int = 3,
                     ops: int = 15):
    """Wire records incl. duplicate joins + whole-batch resubmissions
    (at-least-once ingress) and junk records."""
    rng = random.Random(seed)
    recs, sent = [], []
    queues = {}
    for d in range(docs):
        doc = f"doc{d}"
        for c in range(1, clients + 1):
            recs.append({"kind": "join", "doc": doc, "client": c})
            recs.append({"kind": "join", "doc": doc, "client": c})  # dup
            queues[(doc, c)] = [
                {"kind": "op", "doc": doc, "client": c, "clientSeq": i + 1,
                 "refSeq": 0, "contents": {"v": rng.randint(0, 99)}}
                for i in range(ops)
            ]
    keys = list(queues)
    while keys:
        k = rng.choice(keys)
        r = queues[k].pop(0)
        recs.append(r)
        sent.append(r)
        if rng.random() < 0.08:
            recs.extend(rng.sample(sent, min(3, len(sent))))  # resubmit
        if not queues[k]:
            keys.remove(k)
    recs.append({"junk": 1})
    recs.append({"kind": "leave", "doc": "doc0", "client": 77})  # unknown
    recs.append({"kind": "leave", "doc": "doc0", "client": 1})
    return recs


def strip_reason(r):
    return {k: v for k, v in r.items() if k != "reason"}


@pytest.mark.parametrize("seed", [0, 5])
def test_role_differential_with_resubmissions(seed, tmp_path):
    recs = gen_wire_traffic(seed)
    scalar = DeliRole(str(tmp_path / "s"), owner="s", ttl_s=3600.0)
    kernel = KernelDeliRole(str(tmp_path / "k"), owner="k", ttl_s=3600.0)
    out1, out2 = [], []
    for i, r in enumerate(recs):
        scalar.process(i, r, out1)
    scalar.flush_batch(out1)
    for i, r in enumerate(recs):
        kernel.process(i, r, out2)
        if i % 23 == 22:
            kernel.flush_batch(out2)  # many micro-batches
    kernel.flush_batch(out2)
    assert [strip_reason(r) for r in out1] == [strip_reason(r) for r in out2]
    # inOff bookkeeping (the exactly-once recovery key) is per-record.
    assert all("inOff" in r for r in out2)
    # snapshot interop both ways
    s1, s2 = scalar.snapshot_state(), kernel.snapshot_state()
    assert set(s1) == set(s2)
    for doc in s1:
        assert s1[doc]["seq"] == s2[doc]["seq"]
        assert s1[doc]["min_seq"] == s2[doc]["min_seq"]
        assert {c: (v["ref_seq"], v["client_seq"])
                for c, v in s1[doc]["clients"].items()} == \
               {c: (v["ref_seq"], v["client_seq"])
                for c, v in s2[doc]["clients"].items()}


def test_role_recovery_gap_replay(tmp_path):
    """The exactly-once crash window: outputs durable past the
    checkpoint must not re-stamp after a kernel-role restart."""
    from fluidframework_tpu.server.queue import SharedFileTopic

    shared = str(tmp_path)
    recs = gen_wire_traffic(7, docs=2, clients=2, ops=8)
    raw = SharedFileTopic(str(tmp_path / "topics" / "rawdeltas.jsonl"))
    raw.append_many(recs)

    role = KernelDeliRole(shared, owner="k1", ttl_s=3600.0, batch=16)
    # Crash after 3 steps (the first acquires the lease + recovers):
    # outputs appended, checkpoint taken per step.
    for _ in range(3):
        role.step()
    deltas = SharedFileTopic(str(tmp_path / "topics" / "deltas.jsonl"))
    before = deltas.read_from(0)
    assert before, "no durable outputs before the crash?"
    role.leases.release("deli")  # the "crashed" owner's lease lapses

    # New incarnation: recovery scans the durable prefix, silently
    # replays, then finishes the stream.
    role2 = KernelDeliRole(shared, owner="k2", ttl_s=3600.0, batch=16)
    role2.step()  # acquire + recover + first batch
    while role2.step():
        pass
    after = deltas.read_from(0)

    # Zero duplicate/skipped seqs per doc; stream matches the scalar
    # oracle run in one shot.
    oracle = DeliRole(str(tmp_path / "oracle"), owner="o", ttl_s=3600.0)
    expect = []
    for i, r in enumerate(recs):
        oracle.process(i, r, expect)
    got_ops = [strip_reason(r) for r in after
               if isinstance(r, dict) and r.get("kind") in ("op", "nack")]
    want_ops = [strip_reason(r) for r in expect]
    assert got_ops == want_ops


# ---------------------------------------------------------------------------
# chaos: exactly-once under kill faults with the kernel deli
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_chaos_kill_kernel_deli_converges():
    from fluidframework_tpu.testing.chaos import ChaosConfig, run_chaos

    res = run_chaos(ChaosConfig(
        seed=0, faults=("kill",), n_docs=2, n_clients=2,
        ops_per_client=10, deli_impl="kernel", timeout_s=150.0,
    ))
    assert res.duplicate_seqs == 0, res.detail
    assert res.skipped_seqs == 0, res.detail
    assert res.digest == res.golden_digest, res.detail
    assert res.converged, res.detail


# ---------------------------------------------------------------------------
# column reclaim (ROADMAP (c)) + hot/cold eviction (ROADMAP (e))
# ---------------------------------------------------------------------------


def test_client_churn_compaction_bounds_column_axis():
    """A long-lived doc with heavy client churn must NOT grow the
    kernel's column axis until restart: the live compaction trigger
    reclaims departed clients' columns, so the pool width stays
    bounded by the CONCURRENT client count — and verdicts stay
    oracle-identical through every compaction."""
    recs = []
    for wave in range(60):  # 120 distinct client ids, 2 live at a time
        a, b = 2 * wave + 1, 2 * wave + 2
        for c in (a, b):
            recs.append({"doc": "hot", "kind": "join", "client": c})
        for i in range(3):
            for c in (a, b):
                recs.append({"doc": "hot", "kind": "op", "client": c,
                             "msg": DocumentMessage(client_seq=i + 1,
                                                    ref_seq=0,
                                                    contents=wave)})
        for c in (a, b):
            recs.append({"doc": "hot", "kind": "leave", "client": c})
    log1, _ = run_inproc(DeliLambda, recs)
    log2, deli2 = run_inproc(KernelDeliLambda, recs, max_pump=16)
    o1 = [norm_entry(e) for e in log1.topic("deltas").read(0)]
    o2 = [norm_entry(e) for e in log2.topic("deltas").read(0)]
    assert o1 == o2
    pool = deli2.core.pool
    # 120 ids churned through; without reclaim the map (and the [D, C]
    # column axis) would hold all of them. The live trigger keeps the
    # map within the churn bound (2*live + 8, plus one pump's joins).
    assert len(pool.docs["hot"]["cmap"]) <= 16
    assert pool.n_clients <= 32, pool.n_clients
    # Checkpoint sweeps compact the remainder (and state stays
    # scalar-compatible).
    cp = deli2.checkpoint()
    assert cp["docs"]["hot"]["clients"] == {}
    assert pool.docs["hot"]["cmap"] == {}


def test_compaction_of_resident_doc_reloads_row():
    """Compacting a RESIDENT doc remaps columns under live state: the
    queued row reload must carry the mirror over, so a client that
    joined before compaction keeps sequencing correctly after."""
    from fluidframework_tpu.server.deli_kernel import SeqPool

    recs = [{"doc": "d", "kind": "join", "client": 50}]
    for c in range(1, 20):
        recs.append({"doc": "d", "kind": "join", "client": c})
        recs.append({"doc": "d", "kind": "leave", "client": c})
    # client 50 keeps working across the churn that triggers compaction
    for i in range(4):
        recs.append({"doc": "d", "kind": "op", "client": 50,
                     "msg": DocumentMessage(client_seq=i + 1, ref_seq=0,
                                            contents=i)})
    log1, _ = run_inproc(DeliLambda, recs)
    log2, deli2 = run_inproc(KernelDeliLambda, recs, max_pump=7)
    o1 = [norm_entry(e) for e in log1.topic("deltas").read(0)]
    o2 = [norm_entry(e) for e in log2.topic("deltas").read(0)]
    assert o1 == o2
    # The live trigger fired at least once under the churn (client 50
    # keeps column 1 through every remap); the checkpoint sweep then
    # reclaims whatever the last waves left behind.
    cmap = deli2.core.pool.docs["d"]["cmap"]
    assert cmap[50] == 1 and len(cmap) <= 12
    deli2.checkpoint()
    assert deli2.core.pool.docs["d"]["cmap"] == {50: 1}


def test_eviction_prefers_msn_cold_docs():
    """Under resident pressure the pool parks the doc whose MSN has
    caught its head (quiescent) ahead of an older-touched but still
    LAGGING doc (ROADMAP (e): hot/cold by MSN progress, not pure
    LRU-by-pump)."""
    from fluidframework_tpu.server.deli_kernel import SeqPool

    pool = SeqPool(n_docs=2, n_clients=4, max_resident=2)
    pool.begin()
    pool.touch("lagging")
    pool.touch("cold")
    # lagging: a client holds refSeq 0 behind head 5 (msn < seq).
    pool.docs["lagging"].update(seq=5, min_seq=0,
                                clients={1: [0, 2]})
    # cold: everyone caught up (msn == seq) — the eviction candidate,
    # despite being the more recently touched of the two.
    pool.docs["cold"].update(seq=5, min_seq=5, clients={1: [5, 2]})
    pool.begin()  # new pump: nothing active yet
    pool.touch("newdoc")  # needs a slot -> must evict one of the two
    assert pool.docs["cold"]["slot"] is None, "cold doc not evicted"
    assert pool.docs["lagging"]["slot"] is not None
    from fluidframework_tpu.utils.metrics import get_registry

    assert get_registry().counter(
        "deli_pool_evictions_by_policy_total", policy="msn_cold"
    ).value >= 1


def test_pack_submissions_accepts_precolumnized_input():
    """ops/sequencer_kernel.pack_submissions: 1-D column arrays in,
    dense [D, B] chunks out, per-doc order preserved and chunk
    spill-over indexed correctly."""
    import numpy as np

    from fluidframework_tpu.ops.sequencer_kernel import (
        NO_GROUP,
        SUB_OP,
        SUB_PAD,
        pack_submissions,
    )

    n = 40
    slot = np.array([i % 3 for i in range(n)])
    kind = np.full(n, SUB_OP)
    client = np.arange(n) % 5
    cseq = np.arange(n)
    ref = np.zeros(n, np.int64)
    grp = np.full(n, NO_GROUP)
    chunks = list(pack_submissions(slot, kind, client, cseq, ref, grp,
                                   n_docs=3, max_cols=8))
    assert len(chunks) == 2  # 14 subs/doc spill past max_cols=8
    seen = np.full(n, -1, np.int64)
    for sel, sl, ic, kind2, client2, cseq2, ref2, grp2 in chunks:
        assert kind2.shape[0] == 3
        seen[sel] = cseq2[sl, ic]
        assert (kind2[sl, ic] == SUB_OP).all()
    assert (seen == cseq).all()  # every submission packed exactly once


def test_add_columns_matches_per_record_add():
    """PackedDeliCore.add_columns (bulk, pre-columnized) and add()
    (per record) must produce identical verdicts for the same
    submissions."""
    import numpy as np

    from fluidframework_tpu.ops.sequencer_kernel import (
        SUB_JOIN,
        SUB_OP,
    )
    from fluidframework_tpu.server.deli_kernel import PackedDeliCore

    def drive(bulk: bool):
        core = PackedDeliCore()
        core.begin()
        h = core.touch("d")
        slot = h["slot"]
        core.add(slot, SUB_JOIN, 1)
        core.add(slot, SUB_JOIN, 2)
        if bulk:
            j = core.add_columns(
                np.full(6, slot), SUB_OP,
                np.array([1, 2, 1, 2, 1, 1]),
                np.array([1, 1, 2, 2, 3, 9]),  # 9 -> out-of-order nack
                np.zeros(6, np.int64),
            )
            handles = list(range(j, j + 6))
        else:
            handles = [
                core.add(slot, SUB_OP, c, q, 0)
                for c, q in ((1, 1), (2, 1), (1, 2), (2, 2), (1, 3),
                             (1, 9))
            ]
        res = core.run()
        return [(res.seq[h], res.nack[h]) for h in handles]

    assert drive(True) == drive(False)


# ---------------------------------------------------------------------------
# columnar wire ingest + boxcar schema rev differential
# ---------------------------------------------------------------------------


def gen_boxcar_wire(seed: int, docs: int = 2, clients: int = 3,
                    ops: int = 12):
    """Wire traffic where batches ride BOXCAR records (the ROADMAP (d)
    schema rev), including mid-boxcar nacks and whole-boxcar
    resubmissions."""
    rng = random.Random(seed)
    recs, queues = [], {}
    for d in range(docs):
        doc = f"doc{d}"
        for c in range(1, clients + 1):
            recs.append({"kind": "join", "doc": doc, "client": c})
            queues[(doc, c)] = [
                {"clientSeq": i + 1, "refSeq": 0,
                 "contents": {"v": rng.randrange(99)}}
                for i in range(ops)
            ]
    sent = []
    keys = list(queues)
    while keys:
        doc, c = rng.choice(keys)
        q = queues[(doc, c)]
        n = min(len(q), rng.randint(1, 4))
        box = [q.pop(0) for _ in range(n)]
        if rng.random() < 0.15:  # inject a clientSeq gap -> nack+abort
            box[-1] = dict(box[-1], clientSeq=box[-1]["clientSeq"] + 3)
        rec = {"kind": "boxcar", "doc": doc, "client": c, "ops": box}
        recs.append(rec)
        sent.append(rec)
        if rng.random() < 0.12 and sent:  # lost-ack boxcar resubmit
            recs.append(rng.choice(sent))
        if not q:
            keys.remove((doc, c))
    return recs


@pytest.mark.parametrize("seed", [0, 3])
def test_boxcar_wire_records_scalar_vs_kernel(seed, tmp_path):
    """The boxcar wire schema rev sequences atomically and identically
    through the scalar role and the kernel role's group machinery."""
    recs = gen_boxcar_wire(seed)
    scalar = DeliRole(str(tmp_path / "s"), owner="s", ttl_s=3600.0)
    kernel = KernelDeliRole(str(tmp_path / "k"), owner="k", ttl_s=3600.0)
    o1, o2 = [], []
    for i, r in enumerate(recs):
        scalar.process(i, r, o1)
    scalar.flush_batch(o1)
    for i, r in enumerate(recs):
        kernel.process(i, r, o2)
        if i % 11 == 10:
            kernel.flush_batch(o2)
    kernel.flush_batch(o2)
    assert [strip_reason(r) for r in o1] == [strip_reason(r) for r in o2]
    assert any(r["kind"] == "nack" for r in o1), "no boxcar aborts hit"


@pytest.mark.parametrize("seed", [0, 5])
def test_columnar_ingest_matches_json_roles(seed, tmp_path):
    """The kernel role fed whole RecordBatch frames over a columnar
    topic (zero per-record JSON decode, blob pass-through) emits the
    exact stream the scalar JSON-topic role does — including boxcars,
    resubmissions, junk records, and unknown clients."""
    import os

    from fluidframework_tpu.server.columnar_log import make_topic

    recs = gen_wire_traffic(seed, ops=8) + gen_boxcar_wire(seed + 1)
    scalar = DeliRole(str(tmp_path / "s"), owner="s", ttl_s=3600.0)
    o1 = []
    for i, r in enumerate(recs):
        scalar.process(i, r, o1)
    scalar.flush_batch(o1)

    shared = str(tmp_path / "k")
    raw = make_topic(os.path.join(shared, "topics", "rawdeltas.jsonl"),
                     "columnar")
    for lo in range(0, len(recs), 13):  # many frames per step
        raw.append_many(recs[lo:lo + 13])
    role = KernelDeliRole(shared, owner="k", ttl_s=3600.0, batch=29,
                          log_format="columnar")
    while role.step():
        pass
    deltas = make_topic(os.path.join(shared, "topics", "deltas.jsonl"),
                        "columnar")
    o2 = deltas.read_from(0)
    assert [strip_reason(r) for r in o1] == [strip_reason(r) for r in o2]


@pytest.mark.parametrize("impl", ["scalar", "kernel"])
def test_recovery_completes_partially_durable_boxcar_outputs(impl, tmp_path):
    """A wire boxcar emits SEVERAL outputs for one input offset; a
    crash mid-append can leave only a durable PREFIX of them. Recovery
    must re-emit exactly the missing tail — no duplicates, no skipped
    seqs (the 1:N extension of the exactly-once inOff contract)."""
    from fluidframework_tpu.server.queue import SharedFileTopic

    shared = str(tmp_path)
    recs = [
        {"kind": "join", "doc": "d", "client": 1},
        {"kind": "boxcar", "doc": "d", "client": 1, "ops": [
            {"clientSeq": i + 1, "refSeq": 0, "contents": {"i": i}}
            for i in range(4)
        ]},
        {"kind": "op", "doc": "d", "client": 1, "clientSeq": 5,
         "refSeq": 0, "contents": {"i": 99}},
    ]
    raw = SharedFileTopic(str(tmp_path / "topics" / "rawdeltas.jsonl"))
    raw.append_many(recs[:2])

    role_cls = KernelDeliRole if impl == "kernel" else DeliRole
    r1 = role_cls(shared, owner="g1", ttl_s=3600.0, batch=16)
    while r1.step():
        pass
    deltas = SharedFileTopic(str(tmp_path / "topics" / "deltas.jsonl"))
    full = deltas.read_from(0)
    assert len(full) == 5  # join + 4 boxcar ops
    # Simulate the crash: clip the topic to a PREFIX of the boxcar's
    # outputs (join + 2 of its 4 ops durable) and discard the
    # checkpoint progress past the join, as a crash before the
    # checkpoint write would.
    lines = open(deltas.path, "rb").read().splitlines(keepends=True)
    open(deltas.path, "wb").write(b"".join(lines[:3]))
    r1.ckpt.save("deli", {"offset": 0, "state": None}, fence=r1.fence,
                 owner=r1.owner)
    r1.leases.release("deli")

    raw.append_many(recs[2:])  # more traffic after the crash
    r2 = role_cls(shared, owner="g2", ttl_s=3600.0, batch=16)
    while r2.step():
        pass
    got = [strip_reason(r) for r in deltas.read_from(0)]
    want = [strip_reason(r) for r in full]
    # The regenerated tail matches what the crashed run would have
    # written, plus the post-crash op — each seq exactly once.
    oracle = DeliRole(str(tmp_path / "oracle"), owner="o", ttl_s=3600.0)
    expect = []
    for i, r in enumerate(recs):
        oracle.process(i, r, expect)
    oracle.flush_batch(expect)
    assert got == [strip_reason(r) for r in expect]
    assert [r["seq"] for r in got] == list(range(1, 7))
