"""Durable storage tier: state survives PROCESS restarts.

The round-2 gap: the C++/Python content store and the message log
were in-memory maps — a server restart lost every summary, blob, and
sequenced op. Now the store persists blobs as content-addressed
object files with an fsynced refs journal (the gitrest role,
server/gitrest/packages/gitrest-base), topics journal to disk (Kafka
retention), summaries are stored SHREDDED (tree-structured, one
object per channel blob — shreddedSummaryDocumentStorageService
role), and lambda checkpoints persist. The headline test kills the
socket server with SIGKILL and boots a client off the restarted
process.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from fluidframework_tpu.dds import MapFactory, StringFactory
from fluidframework_tpu.drivers.socket_driver import SocketDriver
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.runtime import ChannelRegistry
from fluidframework_tpu.server import ContentAddressedStore, LocalServer

REGISTRY = ChannelRegistry([MapFactory(), StringFactory()])
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------ store layer


@pytest.mark.parametrize("native", [True, False])
def test_store_persists_across_reopen(tmp_path, native):
    d = str(tmp_path / ("n" if native else "p"))
    st = ContentAddressedStore(prefer_native=native, directory=d)
    keys = [st.put(f"blob {i}".encode()) for i in range(20)]
    st.set_ref("doc", keys[7])
    st.set_ref("doc", keys[9])  # journal: last writer wins
    del st
    st2 = ContentAddressedStore(prefer_native=native, directory=d)
    assert st2.get_ref("doc") == keys[9]
    assert st2.get(keys[3]) == b"blob 3"
    assert st2.contains(keys[19])
    assert not st2.contains("ff" * 32)


def test_store_backends_share_layout(tmp_path):
    d = str(tmp_path / "shared")
    a = ContentAddressedStore(prefer_native=True, directory=d)
    if a.backend != "native":
        pytest.skip("no native store")
    k = a.put(b"cross-backend")
    a.set_ref("r", k)
    del a
    b = ContentAddressedStore(prefer_native=False, directory=d)
    assert b.get(b.get_ref("r")) == b"cross-backend"


# ------------------------------------------------------- shredded summary


def test_summaries_store_shredded_and_dedup(tmp_path):
    """Channel blobs become separate content-addressed objects; an
    incremental summary (one changed channel) adds only that blob."""
    from fluidframework_tpu.runtime.summary import SummaryTree

    srv = LocalServer(persist_dir=str(tmp_path / "srv"))

    def summary_wire(text_a, text_b):
        t = SummaryTree()
        ds = SummaryTree()
        ds.add_blob("chanA", text_a)
        ds.add_blob("chanB", text_b)
        t.add_tree("default", ds)
        return t.to_json()

    h1 = srv.upload_summary(summary_wire("aaaa" * 100, "bbbb" * 100))
    objects = str(tmp_path / "srv" / "store" / "objects")

    def object_count():
        return sum(len(fs) for _, _, fs in os.walk(objects))

    n1 = object_count()
    assert n1 >= 3  # two channel blobs + manifest
    # Incremental: only chanB changed -> one new blob + new manifest.
    h2 = srv.upload_summary(summary_wire("aaaa" * 100, "BBBB" * 100))
    n2 = object_count()
    assert n2 == n1 + 2, (n1, n2)
    # Round trip both summaries.
    for h, tb in ((h1, "bbbb" * 100), (h2, "BBBB" * 100)):
        srv.storage.set_ref("doc", h)
        wire = srv.download_summary("doc")
        tree = SummaryTree.from_json(wire)
        assert tree.get_tree("default").get_blob("chanB") == tb


# ---------------------------------------------------- in-proc restart


def test_local_server_restart_from_disk(tmp_path):
    """LocalServer(persist_dir=...) resumes documents in a FRESH
    instance with no shared objects (simulated process restart)."""
    from fluidframework_tpu.core import CollabClient

    d = str(tmp_path / "srv")
    srv = LocalServer(persist_dir=d)
    sock = srv.connect("doc", client_id=1)
    client = CollabClient(1, initial="")
    sock.listener = client.apply_msg
    srv.process_all()
    client.engine.current_seq = srv.deli.sequencers["doc"].seq
    for i, word in enumerate(["durable ", "state ", "rocks"]):
        pos = len(client.get_text())
        sock.submit(client.insert_local(pos, word))
    srv.process_all()
    assert client.get_text() == "durable state rocks"
    srv.log.sync()

    # Fresh instance on the same dir: op tail replays for catch-up.
    srv2 = LocalServer(persist_dir=d)
    ops = srv2.ops_from("doc", 0)
    replayed = CollabClient(99, initial="")
    from fluidframework_tpu.core.mergetree import replay_passive

    passive = replay_passive(ops, "")
    assert passive.get_text() == "durable state rocks"
    # Sequencer resumes past the old head: a new client's ops extend.
    sock2 = srv2.connect("doc", client_id=2)
    c2 = CollabClient(2, initial="")
    sock2.listener = c2.apply_msg
    for m in srv2.ops_from("doc", 0):
        c2.apply_msg(m)
    srv2.process_all()
    c2.engine.current_seq = srv2.deli.sequencers["doc"].seq
    sock2.submit(c2.insert_local(len(c2.get_text()), "!"))
    srv2.process_all()
    assert c2.get_text() == "durable state rocks!"


# ------------------------------------------------- cross-process restart


def _spawn_server(storage_dir):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "socket_server_main.py"),
         "--storage-dir", storage_dir, "--allow-anonymous"],
        stdout=subprocess.PIPE, text=True, env=env, cwd=REPO,
    )
    line = proc.stdout.readline().strip()
    assert line.startswith("LISTENING"), line
    _, host, port = line.split()
    return proc, host, int(port)


def test_socket_server_sigkill_restart(tmp_path):
    """Kill -9 the service; a restarted process on the same storage
    dir serves the document from persisted summary + op tail."""
    d = str(tmp_path / "srv")
    proc, host, port = _spawn_server(d)
    try:
        loader = Loader(SocketDriver(host, port), REGISTRY)
        c1 = loader.create_detached()
        ds = c1.runtime.create_datastore("default")
        ds.create_channel("s", StringFactory.type_name)
        doc = c1.attach()
        s = c1.runtime.get_datastore("default").get_channel("s")
        s.insert_text(0, "persisted across murder")
        c1.flush()
        # The attach summary checkpoints creation state (shredded in
        # the durable store); subsequent ops ride the journaled tail.
        s.insert_text(0, ">> ")
        c1.flush()
        time.sleep(0.3)
        c1.disconnect()
    finally:
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=10)

    proc2, host2, port2 = _spawn_server(d)
    try:
        loader2 = Loader(SocketDriver(host2, port2), REGISTRY)
        c2 = loader2.resolve(doc)
        s2 = c2.runtime.get_datastore("default").get_channel("s")
        assert s2.get_text() == ">> persisted across murder"
        # And the revived service still sequences new ops.
        s2.insert_text(0, "alive: ")
        c2.flush()
        time.sleep(0.3)
        assert s2.get_text() == "alive: >> persisted across murder"
    finally:
        proc2.send_signal(signal.SIGKILL)
        proc2.wait(timeout=10)
