"""Aux subsystems: DeltaScheduler/Throttler, op-stream analyzer,
cross-engine replay validator, DDS interceptions, debugger driver,
copier/foreman/moira lambdas, and the layer-check lint."""

import os
import sys

import pytest

from fluidframework_tpu.testing.farm import FarmConfig, run_sharedstring_farm

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------- scheduler / throttler


def test_delta_scheduler_slices_and_yields():
    from fluidframework_tpu.loader.delta_queue import DeltaQueue
    from fluidframework_tpu.runtime.delta_scheduler import DeltaScheduler

    seen = []
    q = DeltaQueue(seen.append)
    q.pause()
    for i in range(500):
        q.push(i)
    yields = []
    sched = DeltaScheduler(q, slice_ms=0.0, yield_hook=lambda: yields.append(1))
    n = sched.drain()
    assert n == 500 and seen == list(range(500))
    # slice_ms=0 forces a yield after every message but the last.
    assert sched.yields == 499 and len(yields) == 499
    assert sched.busy_ms >= 0


def test_drain_sliced_catch_up_path():
    from fluidframework_tpu.runtime.delta_scheduler import drain_sliced

    out = []
    n = drain_sliced(range(100), out.append, slice_ms=0.0)
    assert n == 100 and out == list(range(100))


def test_throttler_window():
    from fluidframework_tpu.runtime.delta_scheduler import Throttler

    clock = [0.0]
    t = Throttler(max_delay_ms=5000, window_ms=10_000,
                  delay_per_attempt_ms=1000, now=lambda: clock[0])
    assert t.get_delay() == 0  # first attempt free
    assert t.get_delay() == 1000
    assert t.get_delay() == 2000
    clock[0] += 11.0  # attempts age out of the window
    assert t.get_delay() == 0
    for _ in range(10):
        d = t.get_delay()
    assert d == 5000  # capped


# -------------------------------------------------------------- analyzer


def test_analyzer_reports_stream_statistics():
    from fluidframework_tpu.tooling import analyze_messages

    farm = run_sharedstring_farm(
        FarmConfig(num_clients=3, rounds=6, ops_per_client_per_round=3,
                   seed=4)
    )
    stats = analyze_messages(farm.stream)
    assert stats["messages"] == len(farm.stream)
    assert stats["types"]["OP"] > 0 and stats["types"]["CLIENT_JOIN"] == 3
    assert stats["clients"]["count"] >= 3
    assert stats["opSizeBytes"]["count"] == stats["types"]["OP"]
    assert stats["msnLag"]["max"] >= 0


# ------------------------------------------------------ replay validator


def test_replay_validator_cross_engine_identity():
    from fluidframework_tpu.tooling import validate_replay

    farm = run_sharedstring_farm(
        FarmConfig(num_clients=4, rounds=6, ops_per_client_per_round=3,
                   seed=9)
    )
    report = validate_replay(
        farm.stream, initial="hello world",
        engines=["oracle", "overlay", "kernel"], stages=3,
    )
    assert report["ok"], report["mismatches"]
    assert len(report["stages"]) >= 3


def test_replay_validator_catches_divergence():
    from fluidframework_tpu.tooling import validate_replay

    farm = run_sharedstring_farm(
        FarmConfig(num_clients=2, rounds=3, ops_per_client_per_round=2,
                   seed=5)
    )
    # Tamper: drop one op for the second engine by giving it a
    # different stream via a wrapper engine name — instead, corrupt
    # the stream between stages by comparing different initials.
    good = validate_replay(farm.stream, initial="hello world",
                           engines=["oracle", "overlay"], stages=2)
    assert good["ok"]
    bad = validate_replay(
        farm.stream[:-2] + farm.stream[-1:], initial="hello world",
        engines=["oracle"], stages=2,
    )
    # Single engine can't mismatch itself; tamper check is that the
    # digests change when the stream changes.
    assert bad["digests"]["oracle"][-1] != good["digests"]["oracle"][-1]


# ---------------------------------------------------------- interceptions


def test_shared_string_interception_stamps_props():
    from fluidframework_tpu.dds import MapFactory, StringFactory
    from fluidframework_tpu.framework.interceptions import (
        SharedMapWithInterception,
        SharedStringWithInterception,
        create_attribution_interceptor,
    )
    from fluidframework_tpu.runtime import ChannelRegistry
    from fluidframework_tpu.testing.mocks import MultiClientHarness

    h = MultiClientHarness(
        2, ChannelRegistry([StringFactory(), MapFactory()]),
        channel_types=[("s", StringFactory.type_name),
                       ("m", MapFactory.type_name)],
    )
    raw = h.runtimes[0].get_datastore("default").get_channel("s")
    s = SharedStringWithInterception(
        raw, create_attribution_interceptor(lambda: "alice")
    )
    s.insert_text(0, "hi")
    s.annotate_range(0, 1, {"bold": True})
    h.process_all()
    peer = h.runtimes[1].get_datastore("default").get_channel("s")
    spans = peer.annotated_spans()
    assert all(p and p.get("author") == "alice" for _, p in spans), spans
    assert spans[0][1].get("bold") is True

    m = SharedMapWithInterception(
        h.runtimes[0].get_datastore("default").get_channel("m"),
        lambda k, v: {"v": v, "by": "alice"},
    )
    m.set("k", 7)
    h.process_all()
    assert h.runtimes[1].get_datastore("default").get_channel("m").get(
        "k") == {"v": 7, "by": "alice"}


# -------------------------------------------------------------- debugger


def test_debugger_driver_records_and_steps():
    from fluidframework_tpu.dds import StringFactory
    from fluidframework_tpu.drivers import LocalDriver
    from fluidframework_tpu.drivers.debugger import DebugDriver
    from fluidframework_tpu.loader import Loader
    from fluidframework_tpu.runtime import ChannelRegistry
    from fluidframework_tpu.server import LocalServer

    registry = ChannelRegistry([StringFactory()])
    server = LocalServer()
    loader = Loader(LocalDriver(server), registry)
    c0 = loader.create_detached()
    c0.runtime.create_datastore("default").create_channel(
        "s", StringFactory.type_name
    )
    doc = c0.attach()

    dbg = DebugDriver(LocalDriver(server))
    loader2 = Loader(dbg, registry)
    c1 = loader2.resolve(doc)
    s1 = c1.runtime.get_datastore("default").get_channel("s")
    s0 = c0.runtime.get_datastore("default").get_channel("s")

    s0.insert_text(0, "abc")
    c0.flush()
    # Paused: the debugged container hasn't seen the ops yet.
    assert s1.get_text() == "" and dbg.controller.pending > 0
    stepped = dbg.controller.step()
    assert stepped >= 1
    dbg.controller.play()
    assert s1.get_text() == "abc"
    assert dbg.controller.recorded  # the stream is on record
    # Live mode: subsequent ops deliver immediately.
    s0.insert_text(3, "!")
    c0.flush()
    assert s1.get_text() == "abc!"


# ------------------------------------------------------------ aux lambdas


def test_copier_foreman_moira():
    from fluidframework_tpu.core import CollabClient
    from fluidframework_tpu.protocol.messages import (
        DocumentMessage,
        MessageType,
    )
    from fluidframework_tpu.server import LocalServer
    from fluidframework_tpu.server.aux_lambdas import (
        CopierLambda,
        ForemanLambda,
        MoiraLambda,
    )

    srv = LocalServer()
    copier = CopierLambda(srv.log, srv.storage)
    foreman = ForemanLambda(srv.log)
    revisions = []
    moira = MoiraLambda(srv.log, sink=revisions.append)

    class Agent:
        def __init__(self):
            self.tasks = []

        def assign(self, doc, task):
            self.tasks.append((doc, task))

    agent = Agent()
    foreman.register_agent(agent)

    sock = srv.connect("doc", client_id=1)
    client = CollabClient(1, initial="")
    sock.listener = client.apply_msg
    srv.process_all()
    client.engine.current_seq = srv.deli.sequencers["doc"].seq
    sock.submit(client.insert_local(0, "hello"))
    sock.submit_raw = getattr(sock, "submit_raw", None)
    # A help-task request rides the op stream as plain contents.
    srv.log.topic("rawdeltas").append(
        {"doc": "doc", "kind": "control", "type": MessageType.OP,
         "contents": {"helpTask": "translate"}}
    )
    # A summary cycle for moira.
    handle = srv.upload_summary('{"entries": {}}')
    srv.log.topic("rawdeltas").append(
        {"doc": "doc", "kind": "control", "type": MessageType.SUMMARIZE,
         "contents": {"handle": handle}}
    )
    srv.process_all()
    copier.pump()
    foreman.pump()
    moira.pump()

    assert copier.archived_chunks("doc") >= 1
    archived = copier.read_archive("doc")
    assert any(e.get("kind") == "join" for e in archived)
    assert agent.tasks == [("doc", "translate")]
    assert revisions and revisions[0]["handle"] == handle
    # Checkpoint/resume contract.
    cp = copier.checkpoint()
    copier2 = CopierLambda(srv.log, srv.storage, cp)
    assert copier2.pump() == 0  # nothing new


def test_copier_archives_sharded_ingress():
    """The archive contract is EVERY raw record: with a sharded server
    the ingress lands on ``rawdeltas-p{k}``, and the copier must find
    those topics too (it used to watch only the flat topic and silently
    archive nothing)."""
    from fluidframework_tpu.core import CollabClient
    from fluidframework_tpu.server import LocalServer
    from fluidframework_tpu.server.aux_lambdas import CopierLambda
    from fluidframework_tpu.server.queue import partition_of
    from fluidframework_tpu.server.shard_fabric import spread_doc_names

    srv = LocalServer(n_partitions=4)
    copier = CopierLambda(srv.log, srv.storage)
    docs = spread_doc_names(4, 1)  # one doc homed in each partition
    for i, doc in enumerate(docs):
        sock = srv.connect(doc, client_id=1)
        client = CollabClient(1, initial="")
        sock.listener = client.apply_msg
        srv.process_all()
        deli = srv.delis[partition_of(doc, 4)]
        client.engine.current_seq = deli.sequencers[doc].seq
        sock.submit(client.insert_local(0, f"hi{i}"))
    srv.process_all()
    assert copier.pump() > 0
    for doc in docs:
        archived = copier.read_archive(doc)
        assert any(e.get("kind") == "join" for e in archived), doc
        assert any(e.get("kind") == "op" for e in archived), doc
    # Checkpoint carries per-partition offsets; resume sees nothing new.
    cp = copier.checkpoint()
    assert set(cp["offsets"]) > {"rawdeltas"}
    copier2 = CopierLambda(srv.log, srv.storage, cp)
    assert copier2.pump() == 0


# ------------------------------------------------------------ layer check


def test_layer_check_clean():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import layer_check

    violations = layer_check.check(REPO)
    assert violations == [], "\n".join(violations)


# ------------------------------------------------------ bench trend ledger


def test_bench_trend_append_gate_and_skip(tmp_path):
    """tools/bench_trend.py: results append to the ledger's trend
    section; a >tolerance drop vs the best prior run fails; skipped
    gate results are recorded but never gated (and never count as a
    'best prior')."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        from bench_trend import append_and_gate, headline
    finally:
        sys.path.pop(0)

    ledger = str(tmp_path / "ledger.json")
    assert headline({"ops_per_sec": 10.0}) == ("ops_per_sec", 10.0)
    assert headline({"note": "x"}) is None
    r1 = {"metric": "m", "ops_per_sec": 1000.0, "unit": "records/s"}
    assert append_and_gate(ledger, [r1]) == []
    # Within tolerance: fine.
    assert append_and_gate(ledger, [{"metric": "m",
                                     "ops_per_sec": 850.0}]) == []
    # Skipped results are recorded, not gated.
    assert append_and_gate(ledger, [{"metric": "m", "ops_per_sec": 1.0,
                                     "skipped": "small host"}]) == []
    # A >20% drop vs the BEST prior (1000, not 850) fails loudly.
    fails = append_and_gate(ledger, [{"metric": "m",
                                      "ops_per_sec": 700.0}])
    assert len(fails) == 1 and "regressed" in fails[0]
    # The regression was still RECORDED.
    import json as _json

    with open(ledger) as f:
        runs = _json.load(f)["trend"]["m"]
    assert [r.get("value") for r in runs] == [1000.0, 850.0, 1.0, 700.0]
    assert runs[2]["skipped"] is True
    # A result with no headline appends ungated.
    assert append_and_gate(ledger, [{"metric": "m2", "weird": 1}]) == []


def test_metrics_report_renders_slow_ops(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        from metrics_report import slow_ops_report
    finally:
        sys.path.pop(0)

    lines = [
        {"snapshot": {}, "slow_ops": [
            {"e2e_ms": 5.0, "doc": "a", "seq": 1, "client": 1,
             "clientSeq": 1, "stages": {"sub": 0.0}},
            {"e2e_ms": 9.0, "doc": "b", "seq": 2, "client": 2,
             "clientSeq": 1, "stages": {"sub": 0.0}},
        ]},
        {"snapshot": {}},
    ]
    out = slow_ops_report(lines)
    assert "2 spans" in out
    assert out.index("doc=b") < out.index("doc=a")  # slowest first
    assert slow_ops_report([{"snapshot": {}}]) == ""
