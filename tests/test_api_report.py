"""Public-API surface regression (the api-report / api-extractor
role): the checked-in reports under api_report/ are the public-API
contract; any surface change must be re-approved by regenerating them
(python tools/api_report.py) and reviewing the diff."""

import os
import sys

import pytest

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
)

import api_report  # noqa: E402


@pytest.mark.parametrize("pkg", api_report.PACKAGES)
def test_api_surface_pinned(pkg):
    path = os.path.join(api_report.REPORT_DIR, pkg + ".api.txt")
    assert os.path.exists(path), (
        f"missing API report for {pkg}; run tools/api_report.py"
    )
    want = open(path).read()
    got = api_report.render(pkg)
    assert got == want, (
        f"public API of {pkg} changed; review the diff and run "
        "tools/api_report.py to re-approve"
    )


def test_no_orphaned_reports():
    """A package removed from PACKAGES must not leave a stale report
    silently pinning a deleted surface."""
    expected = {pkg + ".api.txt" for pkg in api_report.PACKAGES}
    on_disk = {
        f for f in os.listdir(api_report.REPORT_DIR)
        if f.endswith(".api.txt")
    }
    assert on_disk == expected, (
        f"orphaned/missing API reports: {on_disk ^ expected}; run "
        "tools/api_report.py"
    )
