"""Sequence-sharding one document across shards (SURVEY §2.6 row 3).

Differential gates: `parallel.seqshard_ref.SeqShardedOverlay` (numpy
spec of the cross-shard rules) must match the single-doc overlay
engine digest-for-digest on honest lagged streams, through folds and
rebalances; `parallel.seqshard` (the shard_map form) must match both
on the virtual device mesh.
"""

from __future__ import annotations

import numpy as np
import pytest

from fluidframework_tpu.ops.overlay_ref import OverlayReplica
from fluidframework_tpu.parallel.seqshard_ref import SeqShardedOverlay
from fluidframework_tpu.testing.digest import state_digest
from fluidframework_tpu.testing.synthetic import generate_lagged_stream


def _single(stream, initial_len, fold_interval=2048):
    ref = OverlayReplica(
        stream, initial_len=initial_len, fold_interval=fold_interval,
        n_removers=10,
    )
    ref.replay()
    ref.check_errors()
    return ref


@pytest.mark.parametrize("seed", range(8))
@pytest.mark.parametrize("n_shards", [2, 4])
def test_seqshard_ref_matches_single_doc(seed, n_shards):
    n_ops = 300
    initial = 40
    stream = generate_lagged_stream(
        n_ops, n_clients=6, seed=300 + seed, window=48,
        initial_len=initial,
    )
    ref = _single(stream, initial)
    sharded = SeqShardedOverlay(
        stream, n_shards, initial_len=initial, n_removers=10,
    )
    sharded.replay()
    sharded.check_errors()
    sharded.verify_invariants()
    assert state_digest(sharded.annotated_spans()) == state_digest(
        ref.annotated_spans()
    )
    assert sharded.attribution_spans() == ref.attribution_spans()


@pytest.mark.parametrize("seed", range(4))
def test_seqshard_ref_fold_cadence_invariance(seed):
    """Folding every 16 ops on the shards vs every 2048 on the single
    doc: settle-merge is semantics-preserving on both sides, so
    digests still agree — and the fold is ENTIRELY shard-local."""
    n_ops, initial = 256, 32
    stream = generate_lagged_stream(
        n_ops, n_clients=5, seed=400 + seed, window=32,
        initial_len=initial,
    )
    ref = _single(stream, initial)
    sharded = SeqShardedOverlay(
        stream, 3, initial_len=initial, fold_interval=16, n_removers=10,
    )
    sharded.replay()
    sharded.check_errors()
    assert state_digest(sharded.annotated_spans()) == state_digest(
        ref.annotated_spans()
    )


@pytest.mark.parametrize("seed", range(4))
def test_seqshard_ref_rebalance(seed):
    """Boundary segment exchange mid-stream: rebalancing to even
    shard sizes (splitting straddling spans) preserves the document."""
    n_ops, initial = 240, 24
    stream = generate_lagged_stream(
        n_ops, n_clients=5, seed=500 + seed, window=32,
        initial_len=initial,
    )
    ref = _single(stream, initial)
    sharded = SeqShardedOverlay(
        stream, 4, initial_len=initial, n_removers=10,
    )
    s = stream
    for i in range(len(s)):
        sharded.apply(
            int(s.op_type[i]), int(s.pos1[i]), int(s.pos2[i]),
            int(s.seq[i]), int(s.ref_seq[i]), int(s.client[i]),
            int(s.buf_start[i]), int(s.ins_len[i]),
            [int(s.prop_key[i])], [int(s.prop_val[i])],
        )
        if (i + 1) % 64 == 0:
            sharded.fold(int(s.min_seq[i]))
            sharded.rebalance()
            sharded.verify_invariants()
            # Rebalance actually evens the shards out.
            sizes = [sh.S for sh in sharded.shards]
            assert max(sizes) - min(sizes) <= 1
    sharded.fold(int(s.min_seq[len(s) - 1]))
    sharded.check_errors()
    assert state_digest(sharded.annotated_spans()) == state_digest(
        ref.annotated_spans()
    )


@pytest.mark.parametrize("n_dev", [2, 4])
def test_seqshard_compiled_matches_single_doc(n_dev):
    """The shard_map form on the virtual mesh: one document
    sequence-sharded across devices, digest-identical to the
    single-device overlay replay."""
    import jax

    if len(jax.devices()) < n_dev:
        pytest.skip(f"needs {n_dev} (virtual) devices")
    from fluidframework_tpu.parallel.mesh import make_docs_mesh
    from fluidframework_tpu.parallel.seqshard import run_sequence_sharded

    initial = 36
    stream = generate_lagged_stream(
        220, n_clients=6, seed=77, window=40, initial_len=initial,
    )
    ref = _single(stream, initial)
    mesh = make_docs_mesh(n_dev, axis="seq")
    sharded, gerr = run_sequence_sharded(
        stream, mesh, initial, capacity=2048,
    )
    assert gerr == 0
    assert state_digest(sharded.annotated_spans()) == state_digest(
        ref.annotated_spans()
    )


def test_seqshard_window_exceeds_single_device():
    """The live window (fold-free rows) exceeds ONE shard's capacity:
    only the sharded engine can hold it — the case sequence sharding
    exists for."""
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 (virtual) devices")
    from fluidframework_tpu.parallel.mesh import make_docs_mesh
    from fluidframework_tpu.parallel.seqshard import run_sequence_sharded

    initial = 48
    stream = generate_lagged_stream(
        600, n_clients=8, seed=13, window=64, initial_len=initial,
    )
    ref = _single(stream, initial)
    cap = 448  # > any one shard's occupancy, < the total window
    mesh = make_docs_mesh(4, axis="seq")
    sharded, gerr = run_sequence_sharded(
        stream, mesh, initial, capacity=cap,
    )
    assert gerr == 0
    total_rows = sum(sh.n for sh in sharded.shards)
    assert total_rows > cap, (
        f"window {total_rows} must exceed one device's capacity {cap}"
    )
    assert state_digest(sharded.annotated_spans()) == state_digest(
        ref.annotated_spans()
    )


def test_seqshard_skewed_boundaries():
    """All edits landing in one shard's range still converge (the
    degenerate skew a doc-sharded mesh cannot handle at all)."""
    n_ops, initial = 200, 100
    stream = generate_lagged_stream(
        n_ops, n_clients=4, seed=7, window=24, initial_len=initial,
    )
    ref = _single(stream, initial)
    for n_shards in (2, 5, 8):
        sharded = SeqShardedOverlay(
            stream, n_shards, initial_len=initial, n_removers=10,
        )
        sharded.replay()
        sharded.check_errors()
        assert state_digest(sharded.annotated_spans()) == state_digest(
            ref.annotated_spans()
        ), f"n_shards={n_shards}"
