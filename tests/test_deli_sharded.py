"""Multi-device deli: the [D, C] sequencer pool sharded across a
device mesh (`shard_map` over `PartitionSpec('docs')`).

Differential gates against the single-device kernel (itself gated
against the scalar oracle): identical verdicts — stamps, nacks, MSNs,
boxcar aborts, resubmission dedup — whatever the device count, plus
cross-topology checkpoint interop (4-device ⇄ 1-device ⇄ scalar
`DocumentSequencer`, bit-identical replay) and a chaos kill+lease run
whose sharded-kernel farm converges bit-identical to the scalar
golden. Runs on the conftest-forced 8 virtual host CPU devices — the
code is identical on a real multi-chip slice.
"""

from __future__ import annotations

import random

import jax
import pytest

from fluidframework_tpu.ops.sequencer_kernel import (
    NO_GROUP,
    SUB_JOIN,
    SUB_LEAVE,
    SUB_OP,
    SUB_SYSTEM,
)
from fluidframework_tpu.server.deli_kernel import (
    KernelDeliLambda,
    PackedDeliCore,
    mesh_for_devices,
)
from fluidframework_tpu.server.lambdas import DeliLambda, LocalServer
from fluidframework_tpu.server.log import MessageLog
from fluidframework_tpu.protocol.messages import (
    DocumentMessage,
    SequencedMessage,
)


def _need_devices(n: int) -> None:
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} (virtual) devices")


# ---------------------------------------------------------------------------
# core-level differential
# ---------------------------------------------------------------------------


def drive_core(core: PackedDeliCore, seed: int, pumps: int = 4,
               per_pump: int = 80, docs: int = 6, clients: int = 5):
    """Seeded mixed traffic straight into a PackedDeliCore: joins,
    leaves, system stamps, standalone ops (some invalid), atomic
    boxcars, and verbatim RESUBMISSIONS (the dedup path). Returns the
    flat verdict tuples per pump."""
    rng = random.Random(seed)
    results = []
    recent: list = []
    for _ in range(pumps):
        core.begin()
        for _ in range(per_pump):
            doc = f"doc{rng.randrange(docs)}"
            h = core.touch(doc)
            slot = h["slot"]
            r = rng.random()
            if r < 0.15:
                cid = rng.randrange(1, clients + 1)
                core.add(slot, SUB_JOIN, core.pool.col_of_join(h, cid))
            elif r < 0.22:
                cid = rng.randrange(1, clients + 1)
                core.add(slot, SUB_LEAVE, h["cmap"].get(cid, 0))
            elif r < 0.27:
                core.add(slot, SUB_SYSTEM)
            elif r < 0.4:
                g = core.new_group(slot)
                col = rng.randrange(0, clients + 1)
                for k in range(rng.randrange(2, 5)):
                    core.add(slot, SUB_OP, col, rng.randrange(1, 9),
                             rng.randrange(0, 5), g)
            elif r < 0.5 and recent:
                core.add(*rng.choice(recent))  # resubmission -> dedup
            else:
                sub = (slot, SUB_OP, rng.randrange(0, clients + 1),
                       rng.randrange(1, 9), rng.randrange(0, 5),
                       NO_GROUP)
                recent.append(sub)
                if len(recent) > 32:
                    recent.pop(0)
                core.add(*sub)
        res = core.run()
        results.append((res.seq, res.msn, res.nack, res.skipped))
    return results


@pytest.mark.parametrize("n_dev", [2, 4, 8])
def test_sharded_core_matches_single_device(n_dev):
    _need_devices(n_dev)
    single = drive_core(PackedDeliCore(dedup=True), seed=11)
    sharded = drive_core(
        PackedDeliCore(dedup=True, mesh=mesh_for_devices(n_dev)),
        seed=11,
    )
    assert sharded == single


def test_sharded_pool_growth_keeps_device_multiple():
    _need_devices(4)
    core = PackedDeliCore(n_docs=2, dedup=True, mesh=mesh_for_devices(4))
    single = drive_core(PackedDeliCore(n_docs=2, dedup=True), seed=3,
                        docs=24)
    sharded = drive_core(core, seed=3, docs=24)
    assert sharded == single
    assert core.pool.n_docs % 4 == 0
    assert core.pool.n_docs >= 24  # grew past the initial 4-multiple


def test_sharded_pool_evict_park_matches():
    """max_resident forces park/reload churn: the sharded pool's host
    mirror and row scatter must behave exactly like the single-device
    pool's (verdicts identical through evictions)."""
    _need_devices(2)
    single = drive_core(
        PackedDeliCore(dedup=True, max_resident=3), seed=5, docs=10
    )
    sharded = drive_core(
        PackedDeliCore(dedup=True, max_resident=3,
                       mesh=mesh_for_devices(2)),
        seed=5, docs=10,
    )
    assert sharded == single


def test_sharded_checkpoint_format_is_topology_free():
    _need_devices(4)
    a = PackedDeliCore(dedup=True)
    b = PackedDeliCore(dedup=True, mesh=mesh_for_devices(4))
    drive_core(a, seed=9)
    drive_core(b, seed=9)
    assert a.pool.checkpoint_docs() == b.pool.checkpoint_docs()


# ---------------------------------------------------------------------------
# lambda-level differential + checkpoint interop
# ---------------------------------------------------------------------------


def gen_raw(seed: int, n: int = 240, docs: int = 4, clients: int = 4):
    """Raw in-proc ingress records (the KernelDeliLambda wire): joins,
    leaves, ops with seeded invalid submissions, boxcars."""
    rng = random.Random(seed)
    recs = []
    conn = {d: set() for d in range(docs)}
    cseq: dict = {}
    for _ in range(n):
        d = rng.randrange(docs)
        doc = f"doc{d}"
        r = rng.random()
        if r < 0.12 or not conn[d]:
            c = rng.randrange(1, clients + 1)
            recs.append({"doc": doc, "kind": "join", "client": c})
            conn[d].add(c)
            cseq[(d, c)] = cseq.get((d, c), 0)
        elif r < 0.17:
            c = rng.randrange(1, clients + 1)
            recs.append({"doc": doc, "kind": "leave", "client": c})
            conn[d].discard(c)
        elif r < 0.3:
            c = rng.choice(sorted(conn[d]))
            msgs = []
            for _ in range(rng.randrange(2, 5)):
                cs = cseq[(d, c)] + 1
                cseq[(d, c)] = cs
                msgs.append(DocumentMessage(
                    client_seq=cs, ref_seq=0, contents={"b": 1}
                ))
            recs.append({"doc": doc, "kind": "boxcar", "client": c,
                         "msgs": msgs})
        else:
            c = rng.choice(sorted(conn[d]))
            cs = cseq[(d, c)] + 1
            if rng.random() < 0.08:
                cs += 1  # clientSeq gap -> nack
            else:
                cseq[(d, c)] = cs
            recs.append({"doc": doc, "kind": "op", "client": c,
                         "msg": DocumentMessage(
                             client_seq=cs, ref_seq=0,
                             contents={"v": rng.randrange(99)})})
    return recs


def norm(entries):
    out = []
    for e in entries:
        m = e["msg"]
        if isinstance(m, SequencedMessage):
            out.append((e["doc"], e["kind"], m.sequence_number,
                        m.minimum_sequence_number, m.client_id,
                        m.client_seq, m.ref_seq, str(m.type), m.contents))
        else:
            out.append((e["doc"], e["kind"], m.client_id, m.client_seq,
                        m.code))
    return out


def _run_lambda(recs, deli_devices=None, checkpoint=None, log=None,
                scalar=False):
    log = log or MessageLog()
    log.topic("rawdeltas").append_many(recs)
    if scalar:
        lam = DeliLambda(log, checkpoint)
    else:
        lam = KernelDeliLambda(log, checkpoint,
                               deli_devices=deli_devices)
    while lam.pump():
        pass
    return lam, log


@pytest.mark.parametrize("n_dev", [2, 4])
def test_kernel_lambda_sharded_matches_scalar(n_dev, seed=21):
    _need_devices(n_dev)
    recs = gen_raw(seed)
    _, slog = _run_lambda(recs, scalar=True)
    _, klog = _run_lambda(recs, deli_devices=n_dev)
    assert norm(klog.topic("deltas").read(0)) == \
        norm(slog.topic("deltas").read(0))


def _interop(prefix, suffix, first, second):
    """Run `prefix` under topology `first`, checkpoint, restore under
    `second`, run `suffix`; return the normalized full deltas.
    Topology: int device count for the kernel lambda, "scalar" for
    the scalar DeliLambda."""
    def build(log, ckpt, topo):
        if topo == "scalar":
            return DeliLambda(log, ckpt)
        return KernelDeliLambda(log, ckpt, deli_devices=topo)

    log = MessageLog()
    log.topic("rawdeltas").append_many(prefix)
    a = build(log, None, first)
    while a.pump():
        pass
    ckpt = a.checkpoint()
    log.topic("rawdeltas").append_many(suffix)
    b = build(log, ckpt, second)
    while b.pump():
        pass
    return norm(log.topic("deltas").read(0))


def test_cross_topology_checkpoint_interop():
    """The satellite contract: a checkpoint written by the 4-device
    sharded kernel restores into the single-device kernel and the
    scalar `DocumentSequencer` path (and back, and sharded→sharded
    with a different N), with bit-identical replay of the suffix."""
    _need_devices(4)
    recs = gen_raw(33, n=300)
    prefix, suffix = recs[:150], recs[150:]
    want = _interop(prefix, suffix, "scalar", "scalar")
    assert _interop(prefix, suffix, 4, 1) == want
    assert _interop(prefix, suffix, 4, "scalar") == want
    assert _interop(prefix, suffix, "scalar", 4) == want
    assert _interop(prefix, suffix, 1, 4) == want
    assert _interop(prefix, suffix, 4, 2) == want


def test_local_server_deli_devices_validation():
    with pytest.raises(ValueError, match="deli_devices"):
        LocalServer(deli_devices=4)  # scalar impl has no device axis


def test_local_server_sharded_end_to_end():
    _need_devices(2)
    ref = LocalServer(deli_impl="kernel")
    srv = LocalServer(deli_impl="kernel", deli_devices=2)
    for s in (ref, srv):
        conns = [s.connect("docA"), s.connect("docA")]
        for i in range(30):
            conns[i % 2].submit(DocumentMessage(
                client_seq=i // 2 + 1, ref_seq=0, contents={"i": i}
            ))
        s.process_all()
    want = [m.sequence_number for m in ref.scriptorium.ops_from("docA", 0)]
    got = [m.sequence_number for m in srv.scriptorium.ops_from("docA", 0)]
    assert got == want
    assert srv.deli.core.pool._n_shards == 2


# ---------------------------------------------------------------------------
# role-level differential (the supervised-farm datapath)
# ---------------------------------------------------------------------------


def test_kernel_role_sharded_pipeline_matches_scalar(tmp_path):
    _need_devices(2)
    from fluidframework_tpu.testing.deli_bench import (
        build_pipeline_workload,
        run_pipeline,
        _read_canonical,
    )
    from fluidframework_tpu.server.queue import SharedFileTopic

    workload = build_pipeline_workload(16, 4, 2)
    raw = str(tmp_path / "rawdeltas.jsonl")
    SharedFileTopic(raw).append_many(workload)
    scal = run_pipeline("scalar", raw, str(tmp_path), batch=64)
    shard = run_pipeline("kernel", raw, str(tmp_path), batch=64,
                         deli_devices=2)
    assert _read_canonical(shard["out_path"]) == \
        _read_canonical(scal["out_path"])


# ---------------------------------------------------------------------------
# device-emulation helper + config validation
# ---------------------------------------------------------------------------


def test_forced_host_device_env_and_subprocess():
    from fluidframework_tpu.utils.devices import (
        forced_host_device_env,
        run_forced_host_subprocess,
    )

    env = forced_host_device_env(3, base={"XLA_FLAGS":
                                          "--xla_force_host_platform_device_count=9 --foo"})
    assert "--xla_force_host_platform_device_count=3" in env["XLA_FLAGS"]
    assert "=9" not in env["XLA_FLAGS"]
    assert "--foo" in env["XLA_FLAGS"]
    assert env["JAX_PLATFORMS"] == "cpu"
    res = run_forced_host_subprocess(
        "import jax; print(len(jax.devices()))", 3, timeout_s=300,
    )
    assert res.stdout.strip().splitlines()[-1] == "3"


def test_forced_host_subprocess_failure_is_loud():
    from fluidframework_tpu.utils.devices import run_forced_host_subprocess

    with pytest.raises(RuntimeError, match="rc=7"):
        run_forced_host_subprocess("raise SystemExit(7)", 2)


def test_multichip_bench_rounds_docs_to_device_multiple():
    # Regression: a doc count not divisible by every requested device
    # count crashed the sharded child's device_put. The bench must
    # round ONCE (lcm of all N) so every topology still sequences the
    # identical workload and the digest gate stays meaningful.
    from fluidframework_tpu.testing.deli_bench import run_multichip_bench

    res = run_multichip_bench(devices=(1, 2), n_docs=3, ops_per_doc=2,
                              n_clients=2, repeats=1)
    assert res["docs"] == 4  # 3 rounded up to lcm(1, 2) * 2
    assert len({r["digest"] for r in res["runs"]}) == 1


def test_parity_skip_reason_shape():
    import os

    from fluidframework_tpu.utils.devices import parity_skip_reason

    cores = os.cpu_count() or 1
    assert parity_skip_reason(1) is None  # one device is always honest
    big = parity_skip_reason(cores * 64)
    # A count far past the host's cores must be refused with a reason
    # naming the core deficit (unless real accelerators cover it, not
    # the case under the conftest cpu pin).
    assert big is not None and "cores" in big


def test_devices_require_kernel_impl_everywhere(tmp_path):
    from fluidframework_tpu.server.supervisor import (
        ServiceSupervisor,
        serve_role,
    )
    from fluidframework_tpu.testing.chaos import ChaosConfig, run_chaos

    with pytest.raises(ValueError, match="kernel"):
        ServiceSupervisor(str(tmp_path), deli_impl="scalar",
                          deli_devices=4)
    with pytest.raises(ValueError, match="kernel"):
        serve_role(str(tmp_path), "deli", "o", deli_impl="scalar",
                   deli_devices=4)
    with pytest.raises(ValueError, match="kernel"):
        serve_role(str(tmp_path), "scriptorium", "o",
                   deli_impl="kernel", deli_devices=4)
    with pytest.raises(ValueError, match="kernel"):
        run_chaos(ChaosConfig(deli_impl="scalar", deli_devices=2))


def test_supervisor_child_cmd_carries_devices(tmp_path):
    from fluidframework_tpu.server.supervisor import ServiceSupervisor

    sup = ServiceSupervisor(str(tmp_path), deli_impl="kernel",
                            deli_devices=2)
    cmd = sup._child_cmd("deli", "deli-g1")
    assert "--deli-devices" in cmd
    assert cmd[cmd.index("--deli-devices") + 1] == "2"
    # Non-deli roles never get the flag (they'd refuse it).
    assert "--deli-devices" not in sup._child_cmd("scribe", "scribe-g1")
    env = sup._child_env()
    assert "--xla_force_host_platform_device_count=2" in env["XLA_FLAGS"]


# ---------------------------------------------------------------------------
# scoped re-place (PR-6 follow-up (b))
# ---------------------------------------------------------------------------


def test_scoped_scatter_skips_untouched_shards():
    """Restoring a parked doc re-places ONLY the device slab that owns
    its slot row: every untouched shard keeps its buffer by IDENTITY
    (same unsafe_buffer_pointer), so a grow/park cycle at large D no
    longer re-transfers the whole pool across the mesh."""
    import numpy as np

    from fluidframework_tpu.server.deli_kernel import SeqPool

    _need_devices(4)
    pool = SeqPool(n_docs=8, n_clients=4, mesh=mesh_for_devices(4))
    for i in range(8):
        pool.touch(f"d{i}")
    pool.prepare()
    assert pool._placed
    fields = ("seq", "min_seq", "connected", "ref_seq", "client_seq")
    ptrs0 = {
        name: [s.data.unsafe_buffer_pointer()
               for s in getattr(pool.state, name).addressable_shards]
        for name in fields
    }
    # Park + touch a doc whose slot lives in shard 0 — the only slab
    # whose buffers may change.
    victim = pool.slot_owner[0]
    pool.docs[victim]["clients"] = {1: [0, 3]}
    pool.docs[victim]["cmap"] = {1: 1}
    pool.park(victim)
    pool.begin()
    h = pool.touch(victim)
    rows = pool.n_docs // 4
    assert h["slot"] // rows == 0
    pool.prepare()
    for name in fields:
        cur = [s.data.unsafe_buffer_pointer()
               for s in getattr(pool.state, name).addressable_shards]
        assert cur[1:] == ptrs0[name][1:], (
            name, "untouched shards were re-transferred"
        )
        assert cur[0] != ptrs0[name][0], (name, "row never scattered")
    # The scattered values actually landed where the kernel reads.
    row = np.asarray(
        pool.state.client_seq.addressable_shards[0].data
    )[h["slot"]]
    assert row[1] == 3
    # And growth still re-places everything (new shape, new buffers) —
    # the scoped path must not break the grow invariant.
    pool._need_clients = 16
    pool.prepare()
    assert pool.state.connected.shape[1] >= 16
    assert pool._placed


def test_scoped_scatter_differential_verdicts_unchanged():
    """The scoped scatter is a pure placement optimization: a sharded
    lambda that churns docs through park/restore still produces
    bit-identical verdicts to the scalar oracle."""
    _need_devices(2)
    log_a, log_b = MessageLog(), MessageLog()
    kern = KernelDeliLambda(log_a, deli_devices=2, n_docs=2,
                            max_resident=2)
    oracle = DeliLambda(log_b)
    rng = random.Random(11)
    docs = [f"doc{i}" for i in range(6)]  # > max_resident: churn
    seqs = {d: 0 for d in docs}
    for d in docs:
        for log in (log_a, log_b):
            log.topic("rawdeltas").append(
                {"kind": "join", "doc": d, "client": 1}
            )
    for i in range(40):
        d = rng.choice(docs)
        seqs[d] += 1
        for log in (log_a, log_b):
            log.topic("rawdeltas").append({
                "kind": "op", "doc": d, "client": 1,
                "msg": DocumentMessage(client_seq=seqs[d], ref_seq=0,
                                       contents={"i": i}),
            })
        kern.pump()
        oracle.pump()
    a = [(e["doc"], e["msg"].sequence_number,
          e["msg"].minimum_sequence_number)
         for e in log_a.topic("deltas").read(0) if e["kind"] == "op"]
    b = [(e["doc"], e["msg"].sequence_number,
          e["msg"].minimum_sequence_number)
         for e in log_b.topic("deltas").read(0) if e["kind"] == "op"]
    assert a == b


# ---------------------------------------------------------------------------
# the chaos acceptance gate
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_chaos_kill_lease_sharded_kernel_converges():
    """Acceptance: the sharded-kernel farm's output is bit-identical
    to the (single-device, scalar-path) golden across a chaos
    kill+lease run — zero duplicated/skipped sequence numbers, with
    the deli child running the pool over a 2-device mesh."""
    _need_devices(2)
    from fluidframework_tpu.testing.chaos import ChaosConfig, run_chaos

    res = run_chaos(ChaosConfig(
        seed=6, faults=("kill", "lease"), n_docs=2, n_clients=3,
        ops_per_client=18, deli_impl="kernel", deli_devices=2,
        timeout_s=240.0,
    ))
    assert res.converged, (res.detail, res.events)
    assert res.duplicate_seqs == 0 and res.skipped_seqs == 0
    assert res.digest == res.golden_digest
    assert res.fence_rejections > 0  # the lease fault demonstrably bit
