"""SharedMatrix convergence tests (reference
packages/dds/matrix/src/test/matrix.spec.ts shapes): concurrent
row/col structure edits + cell writes over the runtime stack.
"""

from __future__ import annotations

import random

from fluidframework_tpu.dds import MatrixFactory
from fluidframework_tpu.runtime import ChannelRegistry, ContainerRuntime
from fluidframework_tpu.runtime.summary import SummaryTree
from fluidframework_tpu.testing.mocks import MultiClientHarness

REGISTRY = ChannelRegistry([MatrixFactory()])


def make_harness(n=2):
    return MultiClientHarness(n, REGISTRY, channel_types=[("x", MatrixFactory.type_name)])


def test_basic_grid_and_cells():
    h = make_harness()
    a, b = h.channel(0, "x"), h.channel(1, "x")
    a.insert_rows(0, 2)
    a.insert_cols(0, 3)
    h.process_all()
    assert (b.row_count, b.col_count) == (2, 3)
    a.set_cell(0, 0, "tl")
    b.set_cell(1, 2, "br")
    h.process_all()
    assert a.to_dense() == b.to_dense() == [["tl", None, None], [None, None, "br"]]


def test_cells_track_row_col_inserts():
    h = make_harness()
    a, b = h.channel(0, "x"), h.channel(1, "x")
    a.insert_rows(0, 2)
    a.insert_cols(0, 2)
    h.process_all()
    a.set_cell(1, 1, "v")
    h.process_all()
    # Concurrent structural edits shift positions but not cell identity.
    a.insert_rows(0, 1)
    b.insert_cols(1, 2)
    h.process_all()
    assert a.to_dense() == b.to_dense()
    assert a.get_cell(2, 3) == "v"  # slid by 1 row and 2 cols


def test_concurrent_set_cell_lww_with_pending_shadow():
    h = make_harness()
    a, b = h.channel(0, "x"), h.channel(1, "x")
    a.insert_rows(0, 1)
    a.insert_cols(0, 1)
    h.process_all()
    b.set_cell(0, 0, "from-b")
    h.runtimes[1].flush()
    a.set_cell(0, 0, "from-a")  # pending when b's arrives
    h.service.process_all()
    assert a.get_cell(0, 0) == "from-a"  # shadowed
    h.process_all()
    assert a.get_cell(0, 0) == "from-a"
    assert b.get_cell(0, 0) == "from-a"  # a sequenced later: LWW


def test_remove_rows_drops_cells_from_view():
    h = make_harness()
    a, b = h.channel(0, "x"), h.channel(1, "x")
    a.insert_rows(0, 3)
    a.insert_cols(0, 2)
    h.process_all()
    a.set_cell(1, 0, "gone")
    a.set_cell(2, 1, "stays")
    h.process_all()
    b.remove_rows(1, 1)
    h.process_all()
    assert a.row_count == 2
    assert a.to_dense() == b.to_dense() == [[None, None], [None, "stays"]]


def test_random_structure_fuzz_converges():
    h = make_harness()
    a, b = h.channel(0, "x"), h.channel(1, "x")
    a.insert_rows(0, 4)
    a.insert_cols(0, 4)
    h.process_all()
    rng = random.Random(7)
    chans = [a, b]
    for step in range(25):
        for m in chans:
            r = rng.random()
            if r < 0.3 and m.row_count < 12:
                m.insert_rows(rng.randint(0, m.row_count), rng.randint(1, 2))
            elif r < 0.45 and m.row_count > 2:
                m.remove_rows(rng.randint(0, m.row_count - 1), 1)
            elif r < 0.6 and m.col_count < 12:
                m.insert_cols(rng.randint(0, m.col_count), 1)
            elif r < 0.7 and m.col_count > 2:
                m.remove_cols(rng.randint(0, m.col_count - 1), 1)
            elif m.row_count and m.col_count:
                m.set_cell(
                    rng.randint(0, m.row_count - 1),
                    rng.randint(0, m.col_count - 1),
                    step,
                )
        h.process_all()
    assert a.to_dense() == b.to_dense()
    assert (a.row_count, a.col_count) == (b.row_count, b.col_count)


def test_matrix_summary_roundtrip():
    h = make_harness()
    a = h.channel(0, "x")
    a.insert_rows(0, 2)
    a.insert_cols(0, 2)
    h.process_all()
    a.set_cell(0, 1, {"rich": [1, 2]})
    h.process_all()
    wire = h.runtimes[0].summarize().to_json()
    rt = ContainerRuntime(REGISTRY)
    rt.load(SummaryTree.from_json(wire))
    m = rt.get_datastore("default").get_channel("x")
    assert m.to_dense() == a.to_dense()
    # Rejoin and collaborate.
    rt.connect(h.service.connect(h.doc_id, client_id=33))
    m.set_cell(1, 0, "post-load")
    rt.flush()
    h.process_all()
    assert h.channel(1, "x").get_cell(1, 0) == "post-load"
