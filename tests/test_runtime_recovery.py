"""Regression tests for runtime recovery paths (reconnect, nack,
catch-up, rollback, dirty-summarize) — the failure-detection /
elastic-recovery semantics of SURVEY.md §5.
"""

from __future__ import annotations

import pytest

from fluidframework_tpu.dds import CounterFactory, MapFactory, StringFactory
from fluidframework_tpu.protocol.messages import DocumentMessage, MessageType
from fluidframework_tpu.runtime import ChannelRegistry, ContainerRuntime
from fluidframework_tpu.runtime.summary import SummaryTree
from fluidframework_tpu.testing.mocks import MultiClientHarness

REGISTRY = ChannelRegistry([MapFactory(), CounterFactory(), StringFactory()])


def make_harness(n=2, channels=(("m", MapFactory.type_name),)):
    return MultiClientHarness(n, REGISTRY, channel_types=list(channels))


def test_map_clear_keeps_pending_bookkeeping():
    """set/clear/set in one turn with an interleaved remote set must
    converge with the local value winning (mapKernel keeps pending
    counts across a local clear)."""
    h = make_harness()
    a, b = h.channel(0, "m"), h.channel(1, "m")
    a.set("k", 1)
    a.clear()
    a.set("k", 3)
    h.runtimes[0].flush()
    b.set("k", 9)
    h.runtimes[1].flush()
    h.process_all()
    # Sequence order: a:set(1), a:clear, a:set(3), b:set(9) → LWW = 9.
    assert a.get("k") == 9
    assert b.get("k") == 9


def test_map_clear_pending_shadow_remote_between():
    """Remote op sequenced between our clear and our later set: our set
    wins (it sequences last) and replicas converge."""
    h = make_harness()
    a, b = h.channel(0, "m"), h.channel(1, "m")
    b.set("k", 9)
    h.runtimes[1].flush()  # b's op sequences first
    a.set("k", 1)
    a.clear()
    a.set("k", 3)
    h.runtimes[0].flush()
    h.process_all()
    assert a.get("k") == 3
    assert b.get("k") == 3


def test_reconnect_resets_client_seq_and_replays_pending():
    """Disconnect with unacked ops; reconnect under a new client id must
    restart clientSeq at 1 and replay the pending ops (no 422 nack)."""
    h = make_harness()
    rt = h.runtimes[0]
    a, b = h.channel(0, "m"), h.channel(1, "m")
    a.set("before", 1)
    h.process_all()
    # Submit and lose the connection before the op is sequenced.
    a.set("lost", 2)
    rt.flush()
    conn = rt.connection
    # Simulate connection loss: drop the pending op server-side too by
    # disconnecting before drain (the queued message was already
    # sequenced in this in-proc service, so instead simulate by
    # clearing delivery: here we just reconnect — replay must be
    # harmless/idempotent at the map level since its op will sequence
    # again under the new identity).
    conn.disconnect()
    nacks = []
    rt.on("nack", nacks.append)
    rt2_conn = h.service.connect(h.doc_id, client_id=11)
    rt.connect(rt2_conn)
    rt.flush()
    h.process_all()
    assert not nacks, [n.reason for n in nacks]
    assert b.get("lost") == 2
    assert a.get("lost") == 2
    assert not rt.is_dirty


def test_late_joiner_catches_up_from_op_log():
    """Ops sequenced between a summary and connect() must be fetched
    (delta catch-up), not silently skipped."""
    h = make_harness()
    a = h.channel(0, "m")
    a.set("k", "v1")
    h.process_all()
    wire = h.runtimes[0].summarize().to_json()

    # More traffic after the summary.
    a.set("k", "v2")
    a.set("extra", True)
    h.process_all()

    cold = ContainerRuntime(REGISTRY)
    cold.load(SummaryTree.from_json(wire))
    cold.connect(h.service.connect(h.doc_id, client_id=42))
    m = cold.get_datastore("default").get_channel("m")
    assert m.get("k") == "v2"  # caught up
    assert m.get("extra") is True
    assert cold.current_seq == h.sequencer.seq  # fully caught up


def test_summarize_refuses_dirty():
    h = make_harness()
    a = h.channel(0, "m")
    a.set("k", 1)
    with pytest.raises(RuntimeError, match="pending local changes"):
        h.runtimes[0].summarize()
    h.process_all()
    h.runtimes[0].summarize()  # clean now


def test_order_sequentially_rolls_back_and_drops_ops():
    h = make_harness(channels=(("m", MapFactory.type_name), ("n", CounterFactory.type_name)))
    rt = h.runtimes[0]
    m, n = h.channel(0, "m"), h.channel(0, "n")
    m.set("keep", 1)
    h.process_all()

    def cb():
        m.set("keep", 2)
        m.set("other", 3)
        n.increment(10)
        raise ValueError("abort")

    with pytest.raises(ValueError, match="abort"):
        rt.order_sequentially(cb)
    # Local state restored...
    assert m.get("keep") == 1
    assert not m.has("other")
    assert n.value == 0
    # ...and nothing leaks to the wire.
    h.process_all()
    assert h.channel(1, "m").get("keep") == 1
    assert not h.channel(1, "m").has("other")
    assert h.channel(1, "n").value == 0
    assert not rt.is_dirty


def test_stale_refseq_nack_disconnects_then_reconnect_replays():
    """A nack drops the connection with pending ops intact (the
    reference client's response to a deli nack, lambda.ts:967);
    reconnecting replays them with fresh perspectives and clientSeqs."""
    h = make_harness()
    rt = h.runtimes[0]
    a, b = h.channel(0, "m"), h.channel(1, "m")
    a.set("x", 1)
    h.process_all()
    a.set("y", 2)
    pm = rt._outbox[0]
    pm.ref_seq = -5  # simulate a stale perspective
    nacks = []
    rt.on("nack", nacks.append)
    rt.flush()
    h.process_all()
    assert len(nacks) == 1 and nacks[0].code == 400
    assert rt.connection is None  # nack is connection-fatal
    # Edits while disconnected queue up.
    a.set("offline", 3)
    # Reconnect: pending + queued ops replay and converge.
    rt.connect(h.service.connect(h.doc_id, client_id=21))
    h.process_all()
    assert b.get("y") == 2 and b.get("offline") == 3
    assert a.get("y") == 2 and a.get("x") == 1
    assert not rt.is_dirty

