"""Snapshot catch-up surface: LocalServer/LocalOrderingService
`catchup`, the in-proc summarizer agent, the socket `catchup` RPC,
the Loader fast path, and the doorbell-woken farm read front end
(`FarmTailPusher` / `FarmReadServer`)."""

from __future__ import annotations

import json
import os
import socket
import threading
import time

import pytest

from fluidframework_tpu.dds import MapFactory, StringFactory
from fluidframework_tpu.drivers import LocalDriver
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.runtime import ChannelRegistry
from fluidframework_tpu.server import LocalServer
from fluidframework_tpu.server.summarizer import summarize_document

REGISTRY = ChannelRegistry([MapFactory(), StringFactory()])


def make_doc(server):
    loader = Loader(LocalDriver(server), REGISTRY)
    c = loader.create_detached()
    ds = c.runtime.create_datastore("default")
    ds.create_channel("s", StringFactory.type_name)
    return loader, c


def text(c):
    return c.runtime.get_datastore("default").get_channel("s")


# ---------------------------------------------------------------------------
# LocalServer catch-up + the summarizer agent
# ---------------------------------------------------------------------------


def test_local_server_catchup_serves_summary_plus_tail():
    server = LocalServer()
    loader, c1 = make_doc(server)
    text(c1).insert_text(0, "hello")
    doc = c1.attach()
    for i in range(20):
        text(c1).insert_text(0, f"{i}:")
    c1.flush()

    # No summary beyond the attach one: the tail is ~the whole log.
    before = server.catchup(doc)
    assert before["summarySeq"] == 0  # attach summary covers seq 0
    long_tail = len(before["ops"])

    # The server-side summarizer agent (the reference's summarizer
    # client): headless resolve, upload, re-point the ref.
    handle, base = summarize_document(server, REGISTRY, doc)
    assert base > 0 and server.storage.get_ref(doc) == handle

    after = server.catchup(doc)
    assert after["summarySeq"] == base
    assert len(after["ops"]) < long_tail
    assert all(m.sequence_number > base for m in after["ops"])

    # A joiner boots from the summary + short tail, bit-identical.
    c2 = loader.resolve(doc)
    assert text(c2).get_text() == text(c1).get_text()

    # Headless resolve (connect=False) applies the tail through the
    # catchup fast path — current state without joining the quorum.
    for i in range(5):
        text(c1).insert_text(0, "x")
    c1.flush()
    c3 = loader.resolve(doc, connect=False)
    assert text(c3).get_text() == text(c1).get_text()
    assert not c3.connected


def test_summarizer_agent_keeps_tail_short_over_time():
    server = LocalServer()
    loader, c1 = make_doc(server)
    text(c1).insert_text(0, "seed")
    doc = c1.attach()
    for round_ in range(3):
        for i in range(10):
            text(c1).insert_text(0, f"{round_}.{i},")
        c1.flush()
        summarize_document(server, REGISTRY, doc)
        cu = server.catchup(doc)
        # The tail past each fresh summary stays near-empty.
        assert len(cu["ops"]) <= 1
    c2 = loader.resolve(doc)
    assert text(c2).get_text() == text(c1).get_text()


def test_local_ordering_service_catchup():
    from fluidframework_tpu.server.local_service import (
        LocalOrderingService,
    )
    from fluidframework_tpu.protocol.messages import DocumentMessage

    svc = LocalOrderingService()
    conn = svc.connect("d", 1)
    for i in range(1, 6):
        conn.submit(DocumentMessage(client_seq=i, ref_seq=0,
                                    contents={"i": i}))
    assert [m.sequence_number for m in svc.ops_from("d", 2, to_seq=4)] \
        == [3, 4]
    svc.set_summary("d", 4, "WIRE")
    cu = svc.catchup("d")
    assert cu["summary"] == "WIRE" and cu["summarySeq"] == 4
    assert all(m.sequence_number > 4 for m in cu["ops"])


# ---------------------------------------------------------------------------
# socket RPC + driver + loader fast path over TCP
# ---------------------------------------------------------------------------


def test_socket_catchup_round_trip():
    from fluidframework_tpu.drivers.socket_driver import SocketDriver
    from fluidframework_tpu.server.socket_service import SocketDeltaServer

    server = LocalServer()
    srv = SocketDeltaServer(server, allow_anonymous=True).start()
    try:
        driver = SocketDriver(srv.host, srv.port)
        loader = Loader(driver, REGISTRY)
        _, c1 = make_doc(server)
        text(c1).insert_text(0, "over tcp")
        doc = c1.attach()
        for i in range(8):
            text(c1).insert_text(0, f"{i}")
        c1.flush()
        summarize_document(server, REGISTRY, doc)

        cu = driver.catchup(doc)
        assert cu["summarySeq"] > 0
        assert all(
            m.sequence_number > cu["summarySeq"] for m in cu["ops"]
        )
        # Loader over the socket driver rides the same fast path.
        c2 = loader.resolve(doc, connect=False)
        assert text(c2).get_text() == text(c1).get_text()
        c3 = loader.resolve(doc)
        assert text(c3).get_text() == text(c1).get_text()
        c3.disconnect()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# doorbell-woken farm read front end
# ---------------------------------------------------------------------------


def _farm_dir(tmp_path, n_ops=60, summary_ops=16):
    """An offline farm state: deltas topic + summaries + broadcast."""
    from fluidframework_tpu.server.columnar_log import make_topic
    from tests.test_summarizer import drive_direct, generic_records

    shared = str(tmp_path)
    os.makedirs(os.path.join(shared, "topics"), exist_ok=True)
    recs = generic_records("doc0", n_ops=n_ops)
    drive_direct(shared, recs, summary_ops=summary_ops)
    make_topic(os.path.join(shared, "topics", "broadcast.jsonl"),
               "json").append_many(recs)
    return shared, recs


def test_farm_tail_pusher_subscribe_and_wait(tmp_path):
    from fluidframework_tpu.server.queue import SharedFileTopic
    from fluidframework_tpu.server.socket_service import FarmTailPusher

    path = os.path.join(str(tmp_path), "topics", "broadcast.jsonl")
    topic = SharedFileTopic(path)
    pusher = FarmTailPusher(path, "json", poll_s=0.5).start()
    try:
        got = []
        pusher.subscribe("d", got.extend)
        # The long-poll rides the doorbell: a waiter parked BEFORE the
        # append wakes when the ring lands, well inside the 0.5s poll
        # fallback.
        result = {}

        def waiter():
            t0 = time.perf_counter()
            ok = pusher.wait_for("d", 3, timeout_s=5.0)
            result["ok"] = ok
            result["s"] = time.perf_counter() - t0

        th = threading.Thread(target=waiter)
        th.start()
        time.sleep(0.15)
        topic.append_many([
            {"kind": "op", "doc": "d", "seq": s, "msn": 0, "client": 1,
             "clientSeq": s, "refSeq": 0, "type": "op", "contents": s}
            for s in (1, 2, 3)
        ])
        th.join(timeout=5)
        assert result["ok"]
        deadline = time.time() + 5
        while len(got) < 3 and time.time() < deadline:
            time.sleep(0.01)
        assert [r["seq"] for r in got] == [1, 2, 3]
        assert pusher.head_seq["d"] == 3
    finally:
        pusher.stop()


def test_farm_tail_pusher_poll_fallback(tmp_path, monkeypatch):
    """FLUID_DOORBELL=0 degrades to the bounded-timeout poll — same
    records, just the old latency."""
    monkeypatch.setenv("FLUID_DOORBELL", "0")
    from fluidframework_tpu.server.queue import SharedFileTopic
    from fluidframework_tpu.server.socket_service import FarmTailPusher

    path = os.path.join(str(tmp_path), "topics", "broadcast.jsonl")
    topic = SharedFileTopic(path)
    pusher = FarmTailPusher(path, "json", poll_s=0.02).start()
    try:
        assert pusher._bell is None
        got = []
        pusher.subscribe("d", got.extend)
        topic.append({"kind": "op", "doc": "d", "seq": 1, "msn": 0,
                      "client": 1, "clientSeq": 1, "refSeq": 0,
                      "type": "op", "contents": 0})
        assert pusher.wait_for("d", 1, timeout_s=5.0)
        deadline = time.time() + 5
        while not got and time.time() < deadline:
            time.sleep(0.01)
        assert got and got[0]["seq"] == 1
    finally:
        pusher.stop()


def _rpc(host, port, sock=None, **req):
    from fluidframework_tpu.server.framing import read_frame, write_frame

    s = sock or socket.create_connection((host, port))
    f = s.makefile("rwb")
    req.setdefault("id", 1)
    write_frame(f, req)
    while True:
        resp = read_frame(f)
        assert resp is not None
        if "event" in resp:
            continue  # push frame racing the response
        break
    if sock is None:
        s.close()
    if "error" in resp:
        raise RuntimeError(resp["error"])
    return resp["result"]


def test_farm_read_server_catchup_and_push(tmp_path):
    from fluidframework_tpu.server.columnar_log import make_topic
    from fluidframework_tpu.server.framing import read_frame, write_frame
    from fluidframework_tpu.server.socket_service import FarmReadServer
    from fluidframework_tpu.server.summarizer import (
        SummaryReplica,
    )

    shared, recs = _farm_dir(tmp_path)
    srv = FarmReadServer(shared).start()
    try:
        # Catch-up RPC: nearest summary manifest + blob + tail.
        res = _rpc(srv.host, srv.port, cmd="catchup", docId="doc0")
        assert res["manifest"] is not None
        boot = SummaryReplica(res["blob"])
        boot.apply_records(res["ops"])
        cold = SummaryReplica(None)
        cold.apply_records(recs)
        assert boot.state_digest() == cold.state_digest()

        # Live subscription + a waitSeq catch-up riding the same
        # doorbell wakeup.
        s = socket.create_connection((srv.host, srv.port))
        f = s.makefile("rwb")
        write_frame(f, {"id": 1, "cmd": "subscribe", "docId": "doc0"})
        sub = read_frame(f)
        assert sub["result"]["headSeq"] >= recs[-1]["seq"]

        next_seq = recs[-1]["seq"] + 1
        waited = {}

        def late_catchup():
            waited["res"] = _rpc(
                srv.host, srv.port, cmd="catchup", docId="doc0",
                waitSeq=next_seq, timeout=10.0,
            )

        th = threading.Thread(target=late_catchup)
        th.start()
        time.sleep(0.1)
        newrec = {"kind": "op", "doc": "doc0", "seq": next_seq,
                  "msn": 0, "client": 1, "clientSeq": 999, "refSeq": 0,
                  "type": "op", "contents": {"late": True}}
        make_topic(os.path.join(shared, "topics", "broadcast.jsonl"),
                   "json").append(newrec)
        make_topic(os.path.join(shared, "topics", "deltas.jsonl"),
                   "json").append(newrec)
        # The subscribed socket receives the push frame.
        pushed = read_frame(f)
        assert pushed["event"] == "recs"
        assert pushed["recs"][-1]["seq"] == next_seq
        th.join(timeout=10)
        assert any(int(r["seq"]) == next_seq
                   for r in waited["res"]["ops"])
        s.close()
    finally:
        srv.stop()
