"""SharedTree depth: schema + typed views, branch API, batched rebase
kernel (reference: modular-schema / editable-tree, shared-tree-core/
branch.ts:50, editManager.ts trunk rebase — config 4)."""

from __future__ import annotations

import random

import numpy as np
import pytest

from fluidframework_tpu.tree.changeset import (
    insert_op,
    move_op,
    rebase_change,
    remove_op,
)
from fluidframework_tpu.tree.rebase_kernel import (
    K_INSERT,
    K_MOVE,
    K_REMOVE,
    rebase_ops_columnar,
)
from fluidframework_tpu.tree.schema import FieldSchema, TreeSchema
from fluidframework_tpu.testing.mocks import MultiClientHarness


def make_harness(n=2):
    from fluidframework_tpu.runtime import ChannelRegistry
    from fluidframework_tpu.tree.shared_tree import SharedTreeFactory

    return MultiClientHarness(
        n,
        ChannelRegistry([SharedTreeFactory()]),
        channel_types=[("t", SharedTreeFactory.type_name)],
    )


def leaf(value, type_=None):
    node = {"value": value, "fields": {}}
    if type_:
        node["type"] = type_
    return node


# ------------------------------------------------------------------ schema

def make_schema():
    s = TreeSchema(root=FieldSchema("sequence", types=["todo"]))
    s.define_leaf("text")
    s.define(
        "todo",
        title=FieldSchema("value", types=["text"]),
        items=FieldSchema("sequence", types=["todo"]),
    )
    return s


def todo(title):
    return {
        "type": "todo",
        "fields": {"title": [{"type": "text", "value": title, "fields": {}}]},
    }


def test_schema_propagates_and_validates():
    h = make_harness()
    a, b = h.channel(0, "t"), h.channel(1, "t")
    a.set_schema(make_schema())
    h.process_all()
    assert b.schema is not None and "todo" in b.schema.nodes

    a.root_field("root").append([todo("write tests")])
    h.process_all()
    assert a.validate() == [] and b.validate() == []

    # Schema-violating insert through the typed view is rejected.
    with pytest.raises(ValueError, match="schema violation"):
        a.root_field("root").append([{"type": "nope", "fields": {}}])

    # Value-field arity violation is caught by whole-doc validation.
    bad = {"type": "todo", "fields": {}}
    a.insert_node([], "root", 1, [bad])  # raw path API bypasses checks
    h.process_all()
    assert any("missing value field" in e for e in a.validate())


def test_typed_view_navigation_and_editing():
    h = make_harness()
    a, b = h.channel(0, "t"), h.channel(1, "t")
    a.set_schema(make_schema())
    a.root_field("root").append([todo("one"), todo("two")])
    h.process_all()

    root = b.root_field("root")
    assert len(root) == 2
    assert root[1]["title"][0].value == "two"
    root[0]["title"][0].set_value("ONE")
    root[0]["items"].insert(0, [todo("sub")])
    h.process_all()
    assert a.root_field("root")[0]["title"][0].value == "ONE"
    assert a.root_field("root")[0]["items"][0]["title"][0].value == "sub"
    a.root_field("root").remove(1)
    h.process_all()
    assert len(b.root_field("root")) == 1


def test_schema_survives_summary_boot():
    from fluidframework_tpu.runtime import ChannelRegistry, ContainerRuntime
    from fluidframework_tpu.runtime.summary import SummaryTree
    from fluidframework_tpu.tree.shared_tree import SharedTreeFactory

    h = make_harness()
    a = h.channel(0, "t")
    a.set_schema(make_schema())
    a.root_field("root").append([todo("persisted")])
    h.process_all()
    wire = h.runtimes[0].summarize().to_json()
    rt = ContainerRuntime(ChannelRegistry([SharedTreeFactory()]))
    rt.load(SummaryTree.from_json(wire))
    c = rt.get_datastore("default").get_channel("t")
    assert c.schema is not None and "todo" in c.schema.nodes
    assert c.validate() == []


# ------------------------------------------------------------------ branch

def test_branch_fork_edit_merge():
    h = make_harness()
    a, b = h.channel(0, "t"), h.channel(1, "t")
    a.insert_node([], "L", 0, [leaf("base")])
    h.process_all()

    br = a.branch()
    br.insert_node([], "L", 1, [leaf("branch-work")])
    br.set_value([["L", 0]], "base-edited-on-branch")
    # Branch edits are invisible to the main line and other replicas.
    assert [n["value"] for n in a.view()["fields"]["L"]] == ["base"]
    assert [n["value"] for n in br.view()["fields"]["L"]] == [
        "base-edited-on-branch", "branch-work"]

    # Main line advances concurrently.
    b.insert_node([], "L", 0, [leaf("main-first")])
    h.process_all()

    br.rebase_onto()
    assert [n["value"] for n in br.view()["fields"]["L"]] == [
        "main-first", "base-edited-on-branch", "branch-work"]

    br.merge_into()
    h.process_all()
    assert a.view() == b.view()
    assert [n["value"] for n in b.view()["fields"]["L"]] == [
        "main-first", "base-edited-on-branch", "branch-work"]


def test_branch_rebase_mutes_over_main_remove():
    h = make_harness()
    a, b = h.channel(0, "t"), h.channel(1, "t")
    a.insert_node([], "L", 0, [leaf("x"), leaf("y")])
    h.process_all()
    br = a.branch()
    br.set_value([["L", 1]], "y2")  # edits node y on the branch
    b.remove_node([], "L", 1)  # main removes y
    h.process_all()
    br.merge_into()
    h.process_all()
    # The branch edit of the removed node muted; replicas converge.
    assert a.view() == b.view()
    assert [n["value"] for n in a.view()["fields"]["L"]] == ["x"]


# ------------------------------------------------------- batched rebase

def _col_to_op(row):
    kind, idx, cnt = int(row[0]), int(row[1]), int(row[2])
    dst = int(row[3]) if len(row) > 3 else 0
    if kind == K_INSERT:
        return insert_op([], "f", idx, [{"value": v, "fields": {}}
                                        for v in range(cnt)])
    if kind == K_REMOVE:
        return remove_op([], "f", idx, cnt)
    return move_op([], "f", idx, cnt, [], "f", dst)


def _scalar_rebase(ops, base):
    """Oracle: changeset.rebase_op over single-field op dicts. Returns
    a LIST OF PIECES per op (splits yield several, in the scalar
    path's sequentialized order); muted ops yield []."""
    out = []
    for row in ops:
        op = _col_to_op(row)
        base_ops = [_col_to_op(b) for b in base]
        rebased = rebase_change([op], base_ops, over_first=True)
        pieces = []
        for r in rebased:
            if r["type"] == "insert":
                pieces.append((K_INSERT, r["index"], len(r["content"])))
            elif r["type"] == "remove":
                if r["count"] > 0:
                    pieces.append((K_REMOVE, r["index"], r["count"]))
            elif r["type"] == "move":
                if r["count"] > 0:
                    pieces.append(
                        (K_MOVE, r["index"], r["count"], r["dst_index"])
                    )
        out.append(pieces)
    return out


def _kernel_pieces(got, spares, n):
    pieces = []
    gk, gi, gc, gd = got[n]
    if gc > 0:
        if gk == K_MOVE:
            pieces.append((int(gk), int(gi), int(gc), int(gd)))
        else:
            pieces.append((int(gk), int(gi), int(gc)))
    sk, si, sc = spares[n]
    if sc > 0:
        pieces.append((int(sk), int(si), int(sc)))
    return pieces


@pytest.mark.parametrize("seed", range(10))
def test_rebase_kernel_matches_scalar(seed):
    rng = random.Random(seed)
    N, M = 64, 16
    ops = np.array(
        [
            (rng.choice([K_INSERT, K_REMOVE]), rng.randint(0, 30),
             rng.randint(1, 4))
            for _ in range(N)
        ],
        np.int32,
    )
    base = np.array(
        [
            (rng.choice([K_INSERT, K_REMOVE]), rng.randint(0, 30),
             rng.randint(1, 4))
            for _ in range(M)
        ],
        np.int32,
    )
    got, spares, flagged = rebase_ops_columnar(ops, base)
    want = _scalar_rebase(ops, base)
    assert flagged.sum() < N // 8  # double-splits only: rare
    for n in range(N):
        if flagged[n]:
            continue  # double-split: routed through the scalar path
        assert _kernel_pieces(got, spares, n) == want[n], (
            f"op {n}: {tuple(ops[n])} over base -> kernel "
            f"{_kernel_pieces(got, spares, n)} vs scalar {want[n]}"
        )


@pytest.mark.parametrize("seed", range(10))
def test_rebase_kernel_matches_scalar_with_moves(seed):
    """Full-calculus differential: pending AND base streams carry MOVE
    marks. Flagged ops (competing claims, mutual containment, 3-piece
    overlaps, double splits) reroute to the scalar path and are
    excluded; everything else must match the scalar oracle
    piece-for-piece including the move's destination gap."""
    rng = random.Random(1000 + seed)
    N, M = 64, 12

    def _row():
        kind = rng.choice([K_INSERT, K_REMOVE, K_MOVE])
        return (kind, rng.randint(0, 30), rng.randint(1, 4),
                rng.randint(0, 30) if kind == K_MOVE else 0)

    ops = np.array([_row() for _ in range(N)], np.int32)
    base = np.array([_row() for _ in range(M)], np.int32)
    got, spares, flagged = rebase_ops_columnar(ops, base)
    want = _scalar_rebase(ops, base)
    assert flagged.sum() < N // 2  # arbitration corners only
    checked = 0
    for n in range(N):
        if flagged[n]:
            continue  # rerouted through the scalar path
        checked += 1
        assert _kernel_pieces(got, spares, n) == want[n], (
            f"op {n}: {tuple(ops[n])} over base -> kernel "
            f"{_kernel_pieces(got, spares, n)} vs scalar {want[n]}"
        )
    assert checked > N // 2  # the native path carries the bulk


def test_rebase_kernel_scales():
    """Config-4 shape: 100k pending ops over a 64-commit window in one
    dispatch (smoke: correctness spot checks + no error)."""
    rng = np.random.default_rng(0)
    N, M = 100_000, 64
    ops = np.stack(
        [
            rng.integers(0, 2, N), rng.integers(0, 1000, N),
            rng.integers(1, 4, N),
        ],
        axis=1,
    ).astype(np.int32)
    base = np.stack(
        [
            rng.integers(0, 2, M), rng.integers(0, 1000, M),
            rng.integers(1, 4, M),
        ],
        axis=1,
    ).astype(np.int32)
    got, spares, flagged = rebase_ops_columnar(ops, base)
    assert got.shape == (N, 4)
    # Spot-check a sample against the scalar oracle.
    sample = rng.integers(0, N, 20)
    want = _scalar_rebase(ops[sample], base)
    for j, n in enumerate(sample):
        if flagged[n]:
            continue
        assert _kernel_pieces(got, spares, n) == want[j]


# ------------------------------------------------ id-compressor clusters


def test_id_compressor_million_ids_cluster_reuse():
    """1M ids across interleaved sessions: cluster expansion keeps the
    cluster count tiny, translations bisect (fast), and state
    round-trips through serialization (idCompressor.ts:272 scale)."""
    import time

    from fluidframework_tpu.tree.id_compressor import IdCompressor

    c = IdCompressor("A", cluster_capacity=2048)
    ids = []
    t0 = time.perf_counter()
    BATCH, ROUNDS = 1000, 1000  # 1M ids for session A
    for r in range(ROUNDS):
        ids.extend(c.generate_compressed_id() for _ in range(BATCH))
        c.finalize_range("A", BATCH)
        if r % 100 == 0:
            c.finalize_range("B", 50)  # interleaved foreign ranges
    dt = time.perf_counter() - t0
    assert dt < 30, f"1M ids took {dt:.1f}s"
    # Expansion keeps the dominant writer in FEW clusters, not 1M/512.
    assert c.cluster_count() < 50, c.cluster_count()
    # After the first finalize, capacity exists: later ids are EAGER
    # finals (non-negative straight from generate).
    assert any(i >= 0 for i in ids)
    # Interleaved foreign clusters occasionally steal the final-space
    # tip (forcing a fresh cluster at the next finalize), so a few
    # batches fall back to locals — but the steady state is eager.
    eager = sum(1 for i in ids if i >= 0)
    assert eager > 0.6 * len(ids), eager
    # Round-trip translation spot checks across the whole space.
    for k in (0, 1, BATCH, 12345, 999_999):
        i = ids[k]
        final = c.normalize_to_op_space(i)
        assert final >= 0
        session, ordinal = c.decompress(final)
        assert session == "A" and ordinal == k + 1
    data = c.serialize()
    c2 = IdCompressor.deserialize(data)
    assert c2.decompress(c.normalize_to_op_space(ids[777_777])) == (
        "A", 777_778
    )
    assert c2.cluster_count() == c.cluster_count()


def test_id_compressor_eager_finals_match_finalization():
    """Eager finals must equal the finals later finalization assigns
    (identity fixed at allocation)."""
    from fluidframework_tpu.tree.id_compressor import IdCompressor

    c = IdCompressor("S", cluster_capacity=8)
    first = [c.generate_compressed_id() for _ in range(4)]
    assert all(i < 0 for i in first)  # no cluster yet: locals
    c.finalize_range("S", 4)
    eager = [c.generate_compressed_id() for _ in range(4)]
    assert all(i >= 0 for i in eager)  # inside reserved capacity
    before = [c.normalize_to_op_space(i) for i in eager]
    c.finalize_range("S", 4)
    after = [c.normalize_to_op_space(i) for i in eager]
    assert before == after
    assert [c.decompress(f)[1] for f in after] == [5, 6, 7, 8]


def test_editable_proxy_attributes_iteration_and_moves():
    """Editable-tree proxy: attribute field access, iteration, bulk
    values, and cross-field moves through the proxy — round-tripping
    through summary + concurrent rebase (editableTree.ts role)."""
    h = make_harness()
    a, b = h.channel(0, "t"), h.channel(1, "t")
    a.set_schema(make_schema())
    a.root_field("root").append([todo("first"), todo("second")])
    h.process_all()

    first = b.root_field("root")[0]
    assert first.title[0].value == "first"  # attribute-style access
    assert [t.title[0].value for t in b.root_field("root")] == [
        "first", "second"
    ]
    # Cross-field move through the proxy, concurrent with an edit.
    a.root_field("root")[0].items.append([todo("sub-a"), todo("sub-b")])
    h.process_all()
    src = b.root_field("root")[0].items
    dst = b.root_field("root")[1].items
    src.move_to(0, 1, dst, 0)
    a.root_field("root")[0].items[0].title[0].set_value("edited")
    h.process_all()
    assert a.view() == b.view()
    moved = a.root_field("root")[1].items
    assert len(moved) == 1 and moved[0].title[0].value == "edited"  # followed

    # Proxy edits round-trip through a summary boot.
    from fluidframework_tpu.runtime import ChannelRegistry, ContainerRuntime
    from fluidframework_tpu.runtime.summary import SummaryTree
    from fluidframework_tpu.tree.shared_tree import SharedTreeFactory

    wire = h.runtimes[0].summarize().to_json()
    rt = ContainerRuntime(ChannelRegistry([SharedTreeFactory()]))
    rt.load(SummaryTree.from_json(wire))
    c = rt.get_datastore("default").get_channel("t")
    assert c.root_field("root")[1].items[0].title[0].value == "edited"
    assert c.validate() == []


# ----------------------------------------------- id-compressor depth


def test_id_compressor_stable_ids_roundtrip():
    """StableId space (idCompressor.ts decompress/recompress): a
    session's consecutive ids are consecutive UUIDs off its base;
    stable ids survive finalization and recompress on any replica."""
    from fluidframework_tpu.tree.id_compressor import IdCompressor
    import uuid as _uuid

    a = IdCompressor("11111111-1111-1111-1111-111111111111")
    b = IdCompressor("22222222-2222-2222-2222-222222222222")
    locals_a = [a.generate_compressed_id() for _ in range(5)]
    stables = [a.stable_id_of(i) for i in locals_a]
    # Consecutive UUID arithmetic off the session base.
    nums = [_uuid.UUID(s).int for s in stables]
    assert nums == list(range(nums[0], nums[0] + 5))
    # Finalize on both replicas in the same order.
    for c in (a, b):
        c.finalize_range("11111111-1111-1111-1111-111111111111", 5)
    finals = [a.normalize_to_op_space(i) for i in locals_a]
    assert all(f >= 0 for f in finals)
    # Stable identity is preserved across spaces and replicas.
    for lo, fi, st in zip(locals_a, finals, stables):
        assert a.stable_id_of(fi) == st
        assert b.stable_id_of(fi) == st
        assert a.recompress(st) == fi
        assert b.recompress(st) == fi


def test_id_compressor_recompress_unknown():
    from fluidframework_tpu.tree.id_compressor import IdCompressor

    c = IdCompressor("s1")
    with pytest.raises(KeyError):
        c.recompress("99999999-9999-4999-8999-999999999999")


def test_id_compressor_binary_serialization():
    """The compact binary persisted form (idCompressor.ts serialize):
    round-trips exactly, resumes generation/finalization, and is
    materially smaller than the JSON object form."""
    import json

    from fluidframework_tpu.tree.id_compressor import IdCompressor

    a = IdCompressor("sessA", cluster_capacity=8)
    peers = [f"peer{i}" for i in range(6)]
    rng = random.Random(9)
    for step in range(200):
        n = rng.randint(1, 7)
        for _ in range(n):
            a.generate_compressed_id()
        a.finalize_range("sessA", n)
        p = rng.choice(peers)
        a.finalize_range(p, rng.randint(1, 9))
    blob = a.serialize_binary()
    back = IdCompressor.deserialize_binary(blob)
    assert back.session_id == "sessA"
    assert back.serialize() == a.serialize()  # full state equality
    # Resumes: new ids + finalization continue the same mapping.
    x1, x2 = a.generate_compressed_id(), back.generate_compressed_id()
    assert x1 == x2
    a.finalize_range("sessA", 1)
    back.finalize_range("sessA", 1)
    assert a.serialize() == back.serialize()
    # Compact: beats the JSON form by a wide margin.
    assert len(blob) < len(json.dumps(a.serialize())) / 2
    # A reader adopting a different identity keeps the shared state
    # but not the serializer's local counter.
    reader = IdCompressor.deserialize_binary(blob, session_id="other")
    assert reader._local_count == 0
    assert reader.decompress(0) == a.decompress(0)


def test_id_compressor_eager_final_recompress():
    """Eager finals round-trip through stable ids BEFORE their
    finalize catches up (identity is reserved at cluster allocation),
    on the owner and on peers."""
    from fluidframework_tpu.tree.id_compressor import IdCompressor

    a = IdCompressor("33333333-3333-3333-3333-333333333333",
                     cluster_capacity=4)
    b = IdCompressor("44444444-4444-4444-4444-444444444444",
                     cluster_capacity=4)
    for _ in range(2):
        a.generate_compressed_id()
    for c in (a, b):
        c.finalize_range("33333333-3333-3333-3333-333333333333", 2)
    eager = a.generate_compressed_id()
    assert eager >= 0  # eager final from reserved headroom
    st = a.stable_id_of(eager)
    assert a.recompress(st) == eager
    assert b.recompress(st) == eager  # peer resolves reserved identity


def test_id_compressor_binary_rejects_truncation():
    from fluidframework_tpu.tree.id_compressor import IdCompressor

    a = IdCompressor("sessT")
    a.generate_compressed_id()
    a.finalize_range("sessT", 1)
    blob = a.serialize_binary()
    for cut in (3, 7, len(blob) // 2, len(blob) - 1):
        with pytest.raises(ValueError):
            IdCompressor.deserialize_binary(blob[:cut])


def test_stable_id_arithmetic_respects_uuid_regions():
    """Stable-id offsets carry AROUND the v4 version nibble and
    variant bits (numericUuid.ts): adds crossing a region boundary
    still produce valid v4 UUIDs, and recompress inverts them."""
    import uuid as _uuid

    from fluidframework_tpu.tree.id_compressor import (
        IdCompressor,
        _uuid_add,
        session_uuid,
    )

    # A session UUID whose low value bits sit at the region boundary.
    base = session_uuid("ffffffff-ffff-4fff-bfff-ffffffffffff")
    for off in (0, 1, 5, 1 << 40):
        u = _uuid.UUID(_uuid_add(base, off))
        assert u.version == 4, (off, str(u))
        assert str(u)[19] in "89ab", (off, str(u))
    c = IdCompressor(session_id="ffffffff-ffff-4fff-bfff-ffffffffffff")
    ids = [c.generate_compressed_id() for _ in range(4)]
    for i in ids:
        stable = c.stable_id_of(i)
        assert _uuid.UUID(stable).version == 4
        assert c.recompress(stable) == i
