"""Per-segment attribution: key = insert seq, carried through splits,
acks, zamboni, settled-run packing, and the overlay fold log —
bit-identically across the scalar oracle, native C++, and overlay
engines (the attributionCollection.ts / attributionPolicy.ts /
attributor.ts:42 roles)."""

import random

import numpy as np
import pytest

from fluidframework_tpu.core.mergetree import CollabClient
from fluidframework_tpu.native import load_hostmerge
from fluidframework_tpu.testing.farm import FarmConfig, random_op_for
from fluidframework_tpu.server.sequencer import DocumentSequencer

needs_native = pytest.mark.skipif(
    load_hostmerge() is None, reason="needs the native hostmerge engine"
)


def normalize(spans):
    out = []
    for ln, key in spans:
        if out and out[-1][1] == key:
            out[-1] = (out[-1][0] + ln, key)
        else:
            out.append((ln, key))
    return out


def run_attribution_farm(seed, num_clients=3, rounds=30, engines=None):
    """Mixed native+oracle farm with attribution tracking on; returns
    per-client attribution spans after full convergence."""
    cfg = FarmConfig(
        num_clients=num_clients, rounds=rounds,
        ops_per_client_per_round=4, seed=seed,
    )
    rng = random.Random(seed)
    seqr = DocumentSequencer("attr")
    clients = []
    for i in range(num_clients):
        kind = (engines or ["auto"])[i % len(engines or ["auto"])]
        seqr.join(i + 1)
        c = CollabClient(i + 1, initial=cfg.initial_text, engine=kind)
        c.engine.enable_attribution()
        clients.append(c)
    for c in clients:
        c.engine.current_seq = seqr.seq
    for rnd in range(rounds):
        submissions = []
        for c in clients:
            for _ in range(cfg.ops_per_client_per_round):
                m = random_op_for(c, rng, cfg)
                if m is not None:
                    submissions.append((c.client_id, m))
        per_client = {c.client_id: [] for c in clients}
        for cid, m in submissions:
            per_client[cid].append(m)
        sequenced = []
        while any(per_client.values()):
            cid = rng.choice([c for c, q in per_client.items() if q])
            sequenced.append(seqr.sequence(cid, per_client[cid].pop(0)))
        for c in clients:
            c.apply_msgs(sequenced)
    return clients


@needs_native
@pytest.mark.parametrize("seed", range(4))
def test_attribution_converges_native_vs_oracle(seed):
    clients = run_attribution_farm(seed, engines=["native", "python"])
    spans = [normalize(c.engine.attribution_spans()) for c in clients]
    assert all(s == spans[0] for s in spans), (
        f"divergent attribution (seed {seed})"
    )
    # Every acked visible character attributes to a real sequence
    # number (no UNASSIGNED residue after full convergence).
    assert all(key >= 0 for _, key in spans[0])


@needs_native
def test_attribution_survives_zamboni_and_packing():
    """Long farm with an advancing MSN: the native engine collects
    tombstones and auto-packs settled runs; attribution runs must
    still match the never-coalescing oracle exactly."""
    clients = run_attribution_farm(
        11, num_clients=2, rounds=120, engines=["native", "python"]
    )
    native, oracle = clients
    seg_count = int(native.engine._lib.hm_segment_count(native.engine._ptr))
    n_oracle = len(oracle.engine.segments)
    assert seg_count < n_oracle, (
        "packing never engaged — the survival claim is vacuous "
        f"({seg_count} vs {n_oracle} segments)"
    )
    assert normalize(native.engine.attribution_spans()) == normalize(
        oracle.engine.attribution_spans()
    )


@needs_native
def test_overlay_device_attribution_matches_oracle():
    """The overlay fold log's ins_seq column reconstructs per-position
    attribution identical to the oracle on a lagged stream."""
    from fluidframework_tpu.core.mergetree import replay_passive
    from fluidframework_tpu.core.overlay_replay import OverlayDeviceReplica
    from fluidframework_tpu.ops.overlay_ref import OverlayReplica
    from fluidframework_tpu.testing.synthetic import generate_lagged_stream

    s = generate_lagged_stream(
        1500, n_clients=24, seed=5, window=96, initial_len=24
    )
    oracle = replay_passive(
        s.as_messages(), initial="".join(map(chr, s.text[:24]))
    )
    oracle.enable_attribution()
    want = normalize(oracle.attribution_spans())

    dev = OverlayDeviceReplica(
        s, initial_len=24, chunk_size=128, window=1024, n_removers=12,
        interpret=True,
    )
    dev.prepare()
    dev.replay()
    dev.check_errors()
    assert normalize(dev.attribution_spans()) == want

    # The numpy overlay spec agrees too.
    ref = OverlayReplica(s, initial_len=24, fold_interval=64,
                         n_removers=12)
    ref.replay()
    ref.check_errors()
    assert normalize(ref.attribution_spans()) == want


def test_sharedstring_attribution_and_attributor():
    """End-to-end: SharedString attribution keys resolve to
    {client, timestamp} through a mixin Attributor, and the packed
    summary encoding round-trips."""
    from fluidframework_tpu.dds import StringFactory
    from fluidframework_tpu.framework.attributor import (
        Attributor,
        mixin_attributor,
    )
    from fluidframework_tpu.runtime import ChannelRegistry
    from fluidframework_tpu.testing.mocks import MultiClientHarness

    registry = ChannelRegistry([StringFactory()])
    h = MultiClientHarness(
        2, registry, channel_types=[("text", StringFactory.type_name)]
    )
    attributors = [mixin_attributor(rt) for rt in h.runtimes]
    a = h.runtimes[0].get_datastore("default").get_channel("text")
    b = h.runtimes[1].get_datastore("default").get_channel("text")
    a.enable_attribution()
    b.enable_attribution()
    a.insert_text(0, "hello")
    h.process_all()
    b.insert_text(5, " world")
    h.process_all()
    assert a.get_text() == b.get_text() == "hello world"
    key_h = a.attribution_at(0)
    key_w = a.attribution_at(7)
    assert key_h != key_w
    ent_h = attributors[0].entry_at(a, 0)
    ent_w = attributors[0].entry_at(a, 7)
    assert ent_h is not None and ent_w is not None
    assert ent_h["client"] != ent_w["client"]
    # Both replicas attribute identically.
    assert normalize(a.attribution_spans()) == normalize(
        b.attribution_spans()
    )
    # Packed (deflate + interning) summary encoding round-trips
    # (timestamps quantize to milliseconds on the wire, as in the
    # reference's serialized form).
    packed = attributors[0].serialize_packed()
    back = Attributor.deserialize_packed(packed)
    assert set(back.entries) == set(attributors[0].entries)
    for k, e in attributors[0].entries.items():
        assert back.entries[k]["client"] == e["client"]
        assert abs(back.entries[k]["timestamp"] - e["timestamp"]) < 1e-3
