"""Retention plane: fenced op-log truncation + castore GC.

Covers the ISSUE-14 tentpole surfaces:

- `ColumnarFileTopic.truncate_prefix` — logical offsets stable across
  physical reclaim, idempotence, append/tail-reader survival;
- `columnar_log.tail_records_reverse` edge cases (empty log,
  single-frame log, truncated-prefix log, a stop_at seek landing
  exactly on a frame boundary);
- `RetentionRole` — coverage/consumer/producer clamps, the
  commit-then-reclaim ordering with roll-forward recovery, and the
  mark-and-sweep GC (roots, grace, epoch pins, re-put recreation);
- manifest ``byteOff`` + summary-aware reconnect
  (`FarmReadServer.catchup` rebase semantics);
- the chaos gate: kill-mid-truncate / kill-mid-GC converge
  bit-identical with zero dup/skip (marked chaos).
"""

import json
import os
import time

import pytest

from fluidframework_tpu.server.columnar_log import (
    ColumnarFileTopic,
    ColumnarTailReader,
    make_topic,
    tail_records_reverse,
)
from fluidframework_tpu.server.castore import ContentAddressedStore
from fluidframework_tpu.server.retention import (
    PIN_TTL_S,
    RetentionRole,
    clear_pin,
    disk_usage,
    live_pin_floor,
    write_pin,
)
from fluidframework_tpu.server.summarizer import (
    SummarizerRole,
    SummaryReplica,
    open_summary_store,
    read_catchup,
)
from fluidframework_tpu.server.supervisor import DeliRole, ScribeRole


def _op(doc, i, client=1):
    return {"kind": "op", "doc": doc, "seq": i + 1, "msn": 0,
            "client": client, "clientSeq": i + 1, "refSeq": 0,
            "type": "op", "contents": {"i": i}, "inOff": i}


def _fill(topic, n=12, per_frame=3, doc="d0"):
    recs = [_op(doc, i) for i in range(n)]
    for lo in range(0, n, per_frame):
        topic.append_many(recs[lo:lo + per_frame], fence=1, owner="w")
    return recs


# ---------------------------------------------------------------------------
# truncate_prefix
# ---------------------------------------------------------------------------


class TestTruncatePrefix:
    def test_cut_lands_on_frame_boundary_offsets_stable(self, tmp_path):
        t = ColumnarFileTopic(str(tmp_path / "t.jsonl"))
        _fill(t, n=12, per_frame=3)
        # Requested 7: the greatest frame boundary <= 7 is 6.
        assert t.truncate_prefix(7) == t.base_offsets()
        assert t.base_offsets()[0] == 6
        entries, nxt = t.read_entries(0)
        assert [i for i, _ in entries] == list(range(6, 12))
        assert nxt == 12
        # Logical offsets survive a subsequent append.
        t.append_many([_op("d0", 12)], fence=1, owner="w")
        entries, nxt = t.read_entries(0)
        assert [i for i, _ in entries] == list(range(6, 13))

    def test_noop_and_idempotent(self, tmp_path):
        t = ColumnarFileTopic(str(tmp_path / "t.jsonl"))
        _fill(t, n=9, per_frame=3)
        r1 = t.truncate_prefix(6)
        assert r1[0] == 6
        # Re-executing the same (or a lower) cut is a no-op: the base
        # only grows — the roll-forward idempotence contract.
        assert t.truncate_prefix(6) == r1
        assert t.truncate_prefix(3) == r1
        # dry_run plans without touching anything.
        plan = t.truncate_prefix(9, dry_run=True)
        assert plan[0] == 9 and t.base_offsets()[0] == 6

    def test_min_bytes_hysteresis(self, tmp_path):
        t = ColumnarFileTopic(str(tmp_path / "t.jsonl"))
        _fill(t, n=6, per_frame=3)
        big = 10 * os.path.getsize(t.path)
        assert t.truncate_prefix(3, min_bytes=big)[0] == 0
        assert t.truncate_prefix(3)[0] == 3

    def test_tail_reader_survives_concurrent_truncation(self, tmp_path):
        t = ColumnarFileTopic(str(tmp_path / "t.jsonl"))
        _fill(t, n=9, per_frame=3)
        r = ColumnarTailReader(t, 0)
        assert [i for i, _ in r.poll()] == list(range(9))
        t.append_many([_op("d0", 9), _op("d0", 10)], fence=1, owner="w")
        t.truncate_prefix(9)
        # The reader's logical position is PAST the cut: it sees only
        # the new records, none duplicated, none lost.
        assert [i for i, _ in r.poll()] == [9, 10]

    def test_cold_reader_jumps_to_base(self, tmp_path):
        t = ColumnarFileTopic(str(tmp_path / "t.jsonl"))
        _fill(t, n=9, per_frame=3)
        t.truncate_prefix(6)
        r = ColumnarTailReader(t, 0)
        assert [i for i, _ in r.poll()] == [6, 7, 8]
        assert r.next_line == 9

    def test_fence_gate_untouched(self, tmp_path):
        from fluidframework_tpu.server.queue import FencedError

        t = ColumnarFileTopic(str(tmp_path / "t.jsonl"))
        _fill(t, n=6, per_frame=3)
        t.truncate_prefix(3)
        # Truncation binds no fence: the writer's fence still stands,
        # and a stale fence is still rejected.
        with pytest.raises(FencedError):
            t.append_many([_op("d0", 6)], fence=0, owner="zombie")
        t.append_many([_op("d0", 6)], fence=1, owner="w")


# ---------------------------------------------------------------------------
# tail_records_reverse edge cases (ISSUE 14 satellite)
# ---------------------------------------------------------------------------


class TestReverseTailEdges:
    def test_empty_log(self, tmp_path):
        t = ColumnarFileTopic(str(tmp_path / "t.jsonl"))
        # No sidecar yet: the scan cannot anchor -> None (caller falls
        # forward, which yields nothing).
        assert tail_records_reverse(t, "d0", 0, None) is None
        t.append_many([], fence=1, owner="w")
        got = tail_records_reverse(t, "d0", 0, None)
        assert got == [] or got is None

    def test_single_frame_log(self, tmp_path):
        t = ColumnarFileTopic(str(tmp_path / "t.jsonl"))
        t.append_many([_op("d0", i) for i in range(4)],
                      fence=1, owner="w")
        got = tail_records_reverse(t, "d0", 0, None)
        assert [r["seq"] for r in got] == [1, 2, 3, 4]
        assert tail_records_reverse(t, "d0", 4, None) == []
        # Bounded above.
        assert [r["seq"] for r in
                tail_records_reverse(t, "d0", 1, 3)] == [2, 3]

    def test_truncated_prefix_log(self, tmp_path):
        t = ColumnarFileTopic(str(tmp_path / "t.jsonl"))
        _fill(t, n=12, per_frame=3)
        t.truncate_prefix(6)
        got = tail_records_reverse(t, "d0", 6, None)
        assert [r["seq"] for r in got] == list(range(7, 13))
        # A base below the truncation point still answers correctly —
        # the surviving suffix holds every record above it, and the
        # walk floors at the truncation header.
        got = tail_records_reverse(t, "d0", 0, None)
        assert [r["seq"] for r in got] == list(range(7, 13))

    def test_stop_at_exactly_on_frame_boundary(self, tmp_path):
        # Semantics at a boundary-aligned stop: frames strictly above
        # the boundary are collected, the frame ENDING at it is not
        # descended past.
        t = ColumnarFileTopic(str(tmp_path / "t.jsonl"))
        recs = [_op("d0", i) for i in range(9)]
        t.append_many(recs[:3], fence=1, owner="w")
        boundary = os.path.getsize(t.path)  # logical == physical here
        t.append_many(recs[3:6], fence=1, owner="w")
        t.append_many(recs[6:9], fence=1, owner="w")
        got = tail_records_reverse(t, "d0", 3, None, stop_at=boundary)
        assert [r["seq"] for r in got] == [4, 5, 6, 7, 8, 9]

    def test_stop_at_bounds_scan_bytes(self, tmp_path):
        # The O(tail) evidence: on a file much larger than the read
        # block, a stop_at near the end keeps the scan to the tail
        # region instead of the whole log.
        t = ColumnarFileTopic(str(tmp_path / "t.jsonl"))
        pad = "x" * 2000
        boundary = None
        base_seq = 0
        for i in range(200):
            rec = _op("d0", i)
            rec["contents"] = {"i": i, "pad": pad}
            t.append_many([rec], fence=1, owner="w")
            if i == 179:
                boundary = os.path.getsize(t.path)
                base_seq = i + 1
        from fluidframework_tpu.utils import metrics as M

        reg = M.MetricsRegistry()
        prev = M.set_registry(reg)
        try:
            got = tail_records_reverse(t, "d0", base_seq, None,
                                       stop_at=boundary)
        finally:
            M.set_registry(prev)
        assert [r["seq"] for r in got] == list(range(base_seq + 1, 201))
        scanned = sum(
            c["value"] for c in reg.snapshot()["counters"]
            if c["name"] == "catchup_tail_scan_bytes_total"
        )
        assert 0 < scanned < os.path.getsize(t.path) / 2


# ---------------------------------------------------------------------------
# the role: clamps, commit/roll-forward, GC
# ---------------------------------------------------------------------------


def _mini_farm(tmp_path, consumers=("scribe", "summarizer"),
               summary_ops=16, **ret_kw):
    d = str(tmp_path)
    os.makedirs(os.path.join(d, "topics"), exist_ok=True)
    fmt = "columnar"
    raw = make_topic(os.path.join(d, "topics", "rawdeltas.jsonl"), fmt)
    deli = DeliRole(d, "deli-1", ttl_s=3600.0, log_format=fmt,
                    ckpt_interval_s=0.0)
    summ = SummarizerRole(d, "summ-1", ttl_s=3600.0, log_format=fmt,
                          summary_ops=summary_ops, ckpt_interval_s=0.0)
    scribe = ScribeRole(d, "scribe-1", ttl_s=3600.0, log_format=fmt,
                        ckpt_interval_s=0.0)
    kw = dict(consumers=consumers, interval_s=0.0, gc_interval_s=1e9,
              min_reclaim_bytes=1, keep_tail=4, gc_grace_s=0.0)
    kw.update(ret_kw)
    ret = RetentionRole(d, "ret-1", ttl_s=3600.0, log_format=fmt, **kw)
    return d, raw, deli, summ, scribe, ret


def _feed_cycle(raw, n_ops=120, n_clients=2, doc="doc0", chunk=20):
    recs = [{"kind": "join", "doc": doc, "client": c}
            for c in range(1, n_clients + 1)]
    recs += [{"kind": "op", "doc": doc, "client": 1 + (i % n_clients),
              "clientSeq": i // n_clients + 1, "refSeq": 0,
              "contents": {"i": i}} for i in range(n_ops)]
    chunks = [recs[lo:lo + chunk] for lo in range(0, len(recs), chunk)]
    for ch in chunks:
        raw.append_many(ch)
        yield


class TestRetentionRole:
    def test_truncates_behind_summaries_and_consumers(self, tmp_path):
        d, raw, deli, summ, scribe, ret = _mini_farm(tmp_path)
        for _ in _feed_cycle(raw):
            for r in (deli, summ, scribe, ret):
                r.step(idle_sleep=0)
        for _ in range(4):
            for r in (deli, summ, scribe, ret):
                r.step(idle_sleep=0)
        deltas = make_topic(os.path.join(d, "topics", "deltas.jsonl"),
                            "columnar")
        assert deltas.base_offsets()[0] > 0
        assert raw.base_offsets()[0] > 0
        rt = make_topic(os.path.join(d, "topics", "retention.jsonl"),
                        "columnar")
        commits = [r for _, r in rt.read_entries(0)[0]
                   if isinstance(r, dict) and r.get("kind") == "truncate"]
        assert commits
        # Every commit was rolled fully forward (base >= newest cut).
        newest = max(int(r["records"]) for r in commits
                     if r["topic"] == "deltas")
        assert deltas.base_offsets()[0] >= newest
        # Catch-up over the truncated log still boots exactly.
        store = open_summary_store(d)
        cu = read_catchup(d, "doc0", "columnar", store=store)
        assert cu["manifest"] is not None
        assert isinstance(cu["manifest"].get("byteOff"), int)
        # The floor is scoped to the byte space it was stamped in —
        # a reader scanning a DIFFERENT topic (elastic pred-era
        # manifest through the merged index) must not use it.
        assert cu["manifest"].get("byteTopic") == "deltas"
        boot = SummaryReplica(cu["blob"])
        boot.apply_records(cu["ops"])
        assert boot.seq == 122  # 2 joins + 120 ops, nothing lost

    def test_lagging_consumer_blocks_truncation(self, tmp_path):
        # A consumer key with NO checkpoint reads as offset 0: the
        # conservative clamp — a tracked consumer must never find its
        # input truncated.
        d, raw, deli, summ, scribe, ret = _mini_farm(
            tmp_path, consumers=("scribe", "summarizer", "broadcaster")
        )
        for _ in _feed_cycle(raw):
            for r in (deli, summ, scribe, ret):
                r.step(idle_sleep=0)
        for _ in range(3):
            for r in (deli, summ, scribe, ret):
                r.step(idle_sleep=0)
        deltas = make_topic(os.path.join(d, "topics", "deltas.jsonl"),
                            "columnar")
        assert deltas.base_offsets()[0] == 0  # blocked by "broadcaster"
        assert raw.base_offsets()[0] > 0  # rawdeltas clamps on deli only

    def test_producer_floor_keeps_recovery_window(self, tmp_path):
        d, raw, deli, summ, scribe, ret = _mini_farm(tmp_path)
        for _ in _feed_cycle(raw):
            for r in (deli, summ, scribe, ret):
                r.step(idle_sleep=0)
        for _ in range(3):
            for r in (deli, summ, scribe, ret):
                r.step(idle_sleep=0)
        deltas = make_topic(os.path.join(d, "topics", "deltas.jsonl"),
                            "columnar")
        base = deltas.base_offsets()[0]
        assert base > 0
        # Every surviving record with an inOff below the deli's
        # checkpointed offset is fine; none at/past it were reclaimed
        # (they are the deli's exactly-once recovery scan window).
        deli_off = ret._ckpt_offset("deli")
        entries, _ = deltas.read_entries(0)
        in_offs = [r.get("inOff", -1) for _, r in entries
                   if isinstance(r, dict)]
        # The whole recovery window survives: every inOff >= deli_off
        # that was ever emitted is still present (here the stream is
        # fully checkpointed, so just sanity-check the clamp held).
        assert all(isinstance(i, int) for i in in_offs)
        assert ret._producer_floor("deltas") == deli_off

    def test_commit_without_reclaim_rolls_forward(self, tmp_path):
        """Torn truncate: the fenced commit record lands, the process
        dies before the physical cut — recovery must roll it
        forward."""
        d, raw, deli, summ, scribe, ret = _mini_farm(
            tmp_path, interval_s=1e9,  # the role itself never reclaims
        )
        ret._retain_t = ret._gc_t = time.time()  # arm the interval
        for _ in _feed_cycle(raw):
            for r in (deli, summ, scribe):
                r.step(idle_sleep=0)
        for _ in range(3):
            for r in (deli, summ, scribe):
                r.step(idle_sleep=0)
        # Drive retention's INPUT fold only, then hand-commit a cut
        # without executing it (the crash window).
        while ret.step(idle_sleep=0) > 0:
            pass
        deltas = make_topic(os.path.join(d, "topics", "deltas.jsonl"),
                            "columnar")
        plan = deltas.truncate_prefix(40, dry_run=True)
        assert plan[0] > 0
        ret.out_topic.append_many(
            [{"kind": "truncate", "topic": "deltas",
              "records": plan[0], "bytes": plan[1]}],
            fence=ret.fence, owner=ret.owner,
        )
        assert deltas.base_offsets()[0] == 0  # not executed yet
        # A fresh incarnation recovers: the committed cut executes.
        ret2 = RetentionRole(d, "ret-2", ttl_s=3600.0,
                             log_format="columnar",
                             consumers=("scribe", "summarizer"),
                             interval_s=1e9, gc_interval_s=1e9)
        ret.leases.release("retention")
        ret2.step(idle_sleep=0)
        assert ret2.fence is not None
        assert deltas.base_offsets()[0] >= plan[0]

    def test_gc_sweeps_unreferenced_keeps_roots_and_pins(self, tmp_path):
        d, raw, deli, summ, scribe, ret = _mini_farm(
            tmp_path, gc_interval_s=0.0, keep_summaries=1
        )
        for _ in _feed_cycle(raw, n_ops=160):
            for r in (deli, summ, scribe, ret):
                r.step(idle_sleep=0)
        for _ in range(4):
            for r in (deli, summ, scribe, ret):
                r.step(idle_sleep=0)
        store = ret._store
        assert store is not None
        blobs = {k for k, *_ in store.list_blobs()}
        # Exactly the newest manifest's handle per doc survives.
        roots = {hs[-1][1] for hs in ret.handles.values()}
        assert roots <= blobs
        rt = make_topic(os.path.join(d, "topics", "retention.jsonl"),
                        "columnar")
        gc_recs = [r for _, r in rt.read_entries(0)[0]
                   if isinstance(r, dict) and r.get("kind") == "gc"]
        assert gc_recs and sum(r["deleted"] for r in gc_recs) > 0
        # A deleted handle is recreated by a content-addressed re-put
        # (the recovery-safety property pin expiry rests on).
        payload = b'{"probe": 1}'
        h = store.put(payload)
        assert store.get(h) == payload
        store.delete_blob(h)
        h2 = store.put(payload)
        assert h2 == h and store.get(h) == payload

    def test_gc_honors_prepoll_pin_floor_after_unpin(self, tmp_path):
        # The unpin-after-poll race: a summarizer round's (manifest
        # append + unpin) can land BETWEEN the retention step's
        # summaries poll and the sweep — the manifest is durable but
        # unread (not a root), and a post-poll pin read would see no
        # pin and delete the round's blobs permanently. `step`
        # therefore captures the pin floor BEFORE its poll and the
        # sweep must honor that pre-poll floor even though the pin
        # file is gone by sweep time.
        d, raw, deli, summ, scribe, ret = _mini_farm(
            tmp_path, gc_interval_s=1e9, gc_grace_s=0.0
        )
        ret.step(idle_sleep=0)  # acquire the lease/fence
        store = ContentAddressedStore(
            prefer_native=False, directory=os.path.join(d, "store"))
        t0 = write_pin(d, "summarizer")
        h = store.put(b'{"round": "in-flight"}')  # mtime >= t0
        clear_pin(d, "summarizer")  # round ended after our "poll"
        ret._gc_pass(pin_floor=t0)
        # Fresh instances: the putter's in-memory cache would mask a
        # deleted file.
        fresh = ContentAddressedStore(
            prefer_native=False, directory=os.path.join(d, "store"))
        assert fresh.contains(h), \
            "pre-poll pin floor must protect the round's blobs"
        # Without the captured floor (the old post-poll read: no live
        # pins left) the same blob is swept — the floor is the only
        # thing protecting it.
        ret._gc_pass()
        fresh = ContentAddressedStore(
            prefer_native=False, directory=os.path.join(d, "store"))
        assert not fresh.contains(h)

    def test_catchup_below_retention_horizon_is_loud(self, tmp_path):
        # A seq-bounded catch-up can resolve an OLDER manifest that
        # is still discoverable (a quiet doc holds the manifest-topic
        # cut back) but whose blob the GC swept (only the newest
        # keep_summaries are roots). With the covered op prefix also
        # truncated, the historical state is unrecoverable — the read
        # must refuse loudly, never silently return partial state
        # from a replay that resumes at the truncation base.
        d, raw, deli, summ, scribe, ret = _mini_farm(tmp_path)
        for _ in _feed_cycle(raw):
            for r in (deli, summ, scribe, ret):
                r.step(idle_sleep=0)
        for _ in range(4):
            for r in (deli, summ, scribe, ret):
                r.step(idle_sleep=0)
        deltas = make_topic(os.path.join(d, "topics", "deltas.jsonl"),
                            "columnar")
        assert deltas.base_offsets()[0] > 0
        st = make_topic(os.path.join(d, "topics", "summaries.jsonl"),
                        "columnar")
        mans = [r for _, r in st.read_entries(0)[0]
                if isinstance(r, dict) and r.get("kind") == "summary"]
        assert len(mans) >= 2
        store = ContentAddressedStore(
            prefer_native=False, directory=os.path.join(d, "store"))
        old = mans[0]
        store.delete_blob(old["handle"])  # what the sweep does
        with pytest.raises(LookupError, match="retention horizon"):
            read_catchup(d, "doc0", "columnar", seq=int(old["seq"]))
        # The UNBOUNDED read still answers from the newest manifest.
        cu = read_catchup(d, "doc0", "columnar")
        assert cu["manifest"] is not None and cu["blob"] is not None

    def test_catchup_swept_blob_intact_log_full_replay(self, tmp_path):
        # Same sweep, but the op log was never truncated (base 0):
        # the full-replay fallback is complete and correct, so the
        # read answers instead of raising.
        d, raw, deli, summ, scribe, _ret = _mini_farm(tmp_path)
        for _ in _feed_cycle(raw, n_ops=60):
            for r in (deli, summ, scribe):
                r.step(idle_sleep=0)
        for _ in range(3):
            for r in (deli, summ, scribe):
                r.step(idle_sleep=0)
        st = make_topic(os.path.join(d, "topics", "summaries.jsonl"),
                        "columnar")
        mans = [r for _, r in st.read_entries(0)[0]
                if isinstance(r, dict) and r.get("kind") == "summary"]
        assert len(mans) >= 2
        store = ContentAddressedStore(
            prefer_native=False, directory=os.path.join(d, "store"))
        old = mans[0]
        store.delete_blob(old["handle"])
        cu = read_catchup(d, "doc0", "columnar", seq=int(old["seq"]))
        assert cu["manifest"] is None and cu["blob"] is None
        # Complete tail from the log's (intact) start — joins
        # sequence as records too, so seqs run 1..old_seq.
        assert [int(r["seq"]) for r in cu["ops"]] == \
            list(range(1, int(old["seq"]) + 1))

    def test_pin_floor_protects_inflight_blobs(self, tmp_path):
        d = str(tmp_path)
        assert live_pin_floor(d) is None
        write_pin(d, "summarizer")
        floor = live_pin_floor(d)
        assert floor is not None and floor <= time.time()
        clear_pin(d, "summarizer")
        assert live_pin_floor(d) is None

    def test_pin_heartbeat_keeps_original_floor(self, tmp_path):
        # An emission round longer than PIN_TTL_S heartbeats the pin
        # by rewriting it with its ORIGINAL floor: liveness is the
        # file mtime, the floor is the recorded t — so blobs put
        # early in the round stay covered while dead-writer expiry
        # (stale mtime) still works.
        d = str(tmp_path)
        t0 = write_pin(d, "summarizer")
        pin_path = os.path.join(d, "store", "pins", "summarizer.json")
        stale = time.time() - (PIN_TTL_S + 5.0)
        os.utime(pin_path, (stale, stale))
        assert live_pin_floor(d) is None  # stale heartbeat = dead writer
        assert write_pin(d, "summarizer", t0) == t0  # the heartbeat
        assert live_pin_floor(d) == t0  # floor preserved, liveness back
        clear_pin(d, "summarizer")

    def test_prune_handles_spares_recovery_window(self, tmp_path):
        # Manifests with inOff at/past the summarizer's checkpointed
        # input offset are inside its exactly-once recovery scan:
        # pruning must keep ALL of them (even past the keep-depth
        # cap) or `_summaries_cut` reclaims manifests a restart
        # re-emits, forking the summary stream.
        _, _, _, _, _, ret = _mini_farm(tmp_path, keep_summaries=1)
        ret.handles = {
            "d0": [[s, f"h{s}", s, s] for s in range(10)]
        }
        ret._producer_floor = lambda base: 4
        ret._prune_handles()
        assert [e[0] for e in ret.handles["d0"]] == list(range(4, 10))
        # No producer present: plain keep-depth bound applies.
        ret._producer_floor = lambda base: None
        ret._prune_handles()
        assert [e[0] for e in ret.handles["d0"]] == [8, 9]

    def test_delete_blob_spares_freshly_reput_blob(self, tmp_path):
        # The sweep's stat→unlink race: a blob re-put (mtime
        # refreshed) after the sweep's listing must survive the
        # delete — `older_than` re-checks freshness under the
        # quarantine rename.
        store = ContentAddressedStore(
            prefer_native=False, directory=str(tmp_path / "store"))
        h = store.put(b'{"gc": 1}')
        path = os.path.join(
            str(tmp_path / "store"), "objects", h[:2], h)
        bar = time.time() - 30.0
        assert store.delete_blob(h, older_than=bar) is False
        assert os.path.exists(path) and store.get(h) == b'{"gc": 1}'
        old = bar - 3600.0
        os.utime(path, (old, old))
        assert store.delete_blob(h, older_than=bar) is True
        assert not os.path.exists(path)

    def test_sweep_tmp_reclaims_dead_writer_staging(self, tmp_path):
        # A kill between a tmp write and its rename orphans the
        # staging file; nothing else removes it and disk_usage counts
        # it. The sweep is age-gated so a live writer's tmp survives.
        store = ContentAddressedStore(
            prefer_native=False, directory=str(tmp_path / "store"))
        h = store.put(b'{"keep": 1}')
        sdir = os.path.join(str(tmp_path / "store"), "objects", h[:2])
        stale = os.path.join(sdir, f"{h}.tmp.99999")
        fresh = os.path.join(sdir, f"{h}.tmp.gc88888")
        for p in (stale, fresh):
            with open(p, "wb") as f:
                f.write(b"x")
        old = time.time() - 3600.0
        os.utime(stale, (old, old))
        assert store.sweep_tmp() == 1
        assert not os.path.exists(stale)
        assert os.path.exists(fresh)  # young: could be in flight
        assert store.get(h) == b'{"keep": 1}'

    def test_truncate_sweeps_orphaned_trunc_tmp(self, tmp_path):
        # Same orphan class on the topic side: a kill between the
        # trunc tmp write and its rename. The flock serializes
        # truncators, so the next truncate call reclaims any sibling.
        t = make_topic(str(tmp_path / "t.jsonl"), "columnar")
        _fill(t, n=6, per_frame=3)
        orphan = str(tmp_path / "t.jsonl.trunc.tmp.99999")
        with open(orphan, "wb") as f:
            f.write(b"x" * 64)
        t.truncate_prefix(3)
        assert not os.path.exists(orphan)
        assert t.base_offsets()[0] == 3

    def test_dedup_reput_refreshes_blob_mtime(self, tmp_path):
        # The sweep's pin floor compares blob MTIMES: a deduplicated
        # re-put (file already on disk, backend skips the write) must
        # stamp the file fresh, or a recovery re-put of a
        # not-yet-referenced blob could be swept before its re-emitted
        # manifest lands.
        store = ContentAddressedStore(
            prefer_native=False, directory=str(tmp_path / "store"))
        h = store.put(b'{"reput": 1}')
        path = os.path.join(
            str(tmp_path / "store"), "objects", h[:2], h)
        old = time.time() - 3600.0
        os.utime(path, (old, old))
        assert store.put(b'{"reput": 1}') == h
        assert os.stat(path).st_mtime >= time.time() - 60.0

    def test_meta_pruning_bounds_manifests_and_commits(self, tmp_path):
        d, raw, deli, summ, scribe, ret = _mini_farm(
            tmp_path,
            topics=("deltas", "rawdeltas", "summaries", "retention"),
            keep_summaries=2, summary_ops=8,
        )
        for _ in _feed_cycle(raw, n_ops=200):
            for r in (deli, summ, scribe, ret):
                r.step(idle_sleep=0)
        for _ in range(4):
            for r in (deli, summ, scribe, ret):
                r.step(idle_sleep=0)
        summaries = make_topic(
            os.path.join(d, "topics", "summaries.jsonl"), "columnar"
        )
        assert summaries.base_offsets()[0] > 0
        # The surviving manifests still include the newest per doc —
        # catch-up discovery is intact.
        store = open_summary_store(d)
        cu = read_catchup(d, "doc0", "columnar", store=store)
        assert cu["manifest"] is not None
        boot = SummaryReplica(cu["blob"])
        boot.apply_records(cu["ops"])
        assert boot.seq == 202

    def test_requires_columnar(self, tmp_path):
        with pytest.raises(ValueError, match="columnar"):
            RetentionRole(str(tmp_path), "r1", log_format="json")

    def test_disk_usage_shape(self, tmp_path):
        u = disk_usage(str(tmp_path))
        assert set(u) == {"log_bytes", "castore_bytes", "total_bytes"}


# ---------------------------------------------------------------------------
# summary-aware reconnect
# ---------------------------------------------------------------------------


def test_farm_catchup_rebases_long_offline_sessions(tmp_path):
    from fluidframework_tpu.server.socket_service import FarmReadServer

    d, raw, deli, summ, scribe, ret = _mini_farm(tmp_path)
    for _ in _feed_cycle(raw):
        for r in (deli, summ, scribe, ret):
            r.step(idle_sleep=0)
    for _ in range(4):
        for r in (deli, summ, scribe, ret):
            r.step(idle_sleep=0)
    srv = FarmReadServer(d, log_format="columnar").start()
    try:
        full = srv.catchup("doc0")
        base = full["manifest"]["seq"]
        # Short gap (at/past the summary): op gap only, no blob — the
        # session keeps its state and applies the tail.
        short = srv.catchup("doc0", from_seq=base + 2)
        assert short["blob"] is None and not short["rebase"]
        assert all(int(r["seq"]) > base + 2 for r in short["ops"])
        # Long offline (below the summary; the op gap is partially
        # RECLAIMED): the session must reboot from the summary.
        long_off = srv.catchup("doc0", from_seq=1)
        assert long_off["rebase"] and long_off["blob"] is not None
        boot = SummaryReplica(long_off["blob"])
        boot.apply_records(long_off["ops"])
        cold = SummaryReplica(full["blob"])
        cold.apply_records(full["ops"])
        assert boot.state_digest() == cold.state_digest()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# chaos gate (kill-mid-truncate / kill-mid-GC)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_chaos_kill_mid_truncate_and_gc_converges():
    """ISSUE 14 acceptance: the retention role in the kill schedule
    plus the two seeded kill points (between the fenced truncate
    commit and the physical reclaim; mid-GC-sweep) — the farm must
    converge bit-identical with zero dup/skip, every committed cut
    rolled forward, and summaries still boot-equal to a cold replay
    off the untruncated durable leg."""
    from fluidframework_tpu.testing.chaos import ChaosConfig, run_chaos

    res = run_chaos(ChaosConfig(
        seed=14, faults=("kill",), n_docs=2, n_clients=3,
        ops_per_client=40, timeout_s=300.0, deli_impl="scalar",
        log_format="columnar", summarizer=True, summary_ops=16,
        retention=True,
    ))
    assert res.converged, res.detail
    assert res.retention_ok and res.truncations > 0
    assert res.retention_base_records > 0
    assert res.duplicate_seqs == 0 and res.skipped_seqs == 0
    assert res.summaries_ok
    # Both seeded kill points demonstrably fired (the role restarted
    # at least twice beyond any scheduled SIGKILL).
    assert res.restarts.get("retention", 0) >= 2


# ---------------------------------------------------------------------------
# the churn gate, scaled (the config14 shape)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_week_of_traffic_churn_scaled():
    from fluidframework_tpu.testing.scenarios import run_week_of_traffic

    res = run_week_of_traffic(
        cycles=3, hot_writers=6, cold_docs=1, cold_clients=2,
        ops_per_writer=12, summary_ops=24, rate_hz=800.0,
        stampede_sessions=8, swarm_sessions=12, keep_tail=48,
        timeout_s=120.0,
    )
    assert res["retention"] and res["truncations"] > 0
    assert res["retention_disk_mb"] > 0
    usage = res["disk_bytes_per_cycle"]
    assert max(usage[2:]) <= 1.35 * usage[1]


# ---------------------------------------------------------------------------
# front-door retention (ISSUE 15 satellite): ingress + nacks topics
# ---------------------------------------------------------------------------


class TestFrontDoorRetention:
    def _feed_front_door(self, d, n_ops=60, bad_every=10):
        """Columnar front door: feed the ingress topic (auth off —
        no tenants.json), drain the admission role, return it."""
        from fluidframework_tpu.server.ingress import IngressRole

        os.makedirs(os.path.join(d, "topics"), exist_ok=True)
        ing_t = make_topic(os.path.join(d, "topics", "ingress.jsonl"),
                           "columnar")
        recs = []
        for i in range(n_ops):
            if bad_every and i % bad_every == bad_every - 1:
                # Oversized record -> a nack on the nacks topic.
                recs.append({"kind": "op", "doc": "d0", "client": 1,
                             "clientSeq": i + 1, "refSeq": 0,
                             "contents": {"x": "z" * 300000}})
            else:
                recs.append({"kind": "op", "doc": "d0", "client": 1,
                             "clientSeq": i + 1, "refSeq": 0,
                             "contents": {"i": i}})
        # Feed + pump per chunk so admissions/nacks land across many
        # frames (a realistic steady state — frame boundaries are what
        # the truncate cut can land on).
        ing = IngressRole(d, "ing-1", ttl_s=3600.0,
                          log_format="columnar", ckpt_interval_s=0.0)
        for lo in range(0, len(recs), 8):
            ing_t.append_many(recs[lo:lo + 8], fence=1, owner="feeder")
            while ing.step(idle_sleep=0) > 0:
                pass
        ing.checkpoint()
        return ing, ing_t

    def test_ingress_and_nacks_truncate_behind_admission(self, tmp_path):
        """PR 14 follow-up: with the front door's topics managed, the
        `ingress` prefix reclaims behind the ADMISSION role's own
        input checkpoint (its consumer floor) and `nacks` behind its
        producer recovery window — both commit-then-reclaim fenced."""
        d = str(tmp_path)
        ing, ing_t = self._feed_front_door(d)
        nacks_t = make_topic(os.path.join(d, "topics", "nacks.jsonl"),
                             "columnar")
        assert ing_t.base_offsets()[0] == 0
        n_nacks = sum(1 for r in nacks_t.read_from(0)
                      if isinstance(r, dict))
        assert n_nacks > 0
        ret = RetentionRole(
            d, "ret-1", ttl_s=3600.0, log_format="columnar",
            topics=("ingress", "nacks"), consumers=(),
            interval_s=0.0, gc_interval_s=1e9, min_reclaim_bytes=1,
            keep_tail=4,
        )
        ret.step(idle_sleep=0)
        ret._retain_pass()
        # Ingress prefix reclaimed up to (checkpoint - keep_tail).
        base_r, _ = ing_t.base_offsets()
        assert base_r > 0
        assert base_r <= ing.offset - 0  # never past the admission ckpt
        # Nacks reclaimed too, behind the producer recovery window.
        nbase, _ = nacks_t.base_offsets()
        assert nbase > 0
        commits = [r for r in ret.out_topic.read_entries(0)[0]
                   if isinstance(r[1], dict)
                   and r[1].get("kind") == "truncate"]
        assert {c[1]["topic"] for c in commits} == {"ingress", "nacks"}

    def test_exactly_once_across_ingress_truncate(self, tmp_path):
        """The gate the satellite names: truncate the ingress topic
        behind the admission checkpoint, RESTART the front door with
        no fresh checkpoint write, and every admission/nack decision
        lands exactly once — the recovery scan never needs the
        reclaimed prefix, and logical offsets survive the cut."""
        from fluidframework_tpu.server.ingress import IngressRole

        d = str(tmp_path)
        ing, ing_t = self._feed_front_door(d)
        raw_t = make_topic(
            os.path.join(d, "topics", "rawdeltas.jsonl"), "columnar"
        )
        admitted0 = [r for r in raw_t.read_from(0)
                     if isinstance(r, dict)]
        assert admitted0
        ret = RetentionRole(
            d, "ret-1", ttl_s=3600.0, log_format="columnar",
            topics=("ingress", "nacks"), consumers=(),
            interval_s=0.0, gc_interval_s=1e9, min_reclaim_bytes=1,
            keep_tail=2,
        )
        ret.step(idle_sleep=0)
        ret._retain_pass()
        assert ing_t.base_offsets()[0] > 0
        # Feed a tail past the cut, then restart the admission role
        # WITHOUT the first instance checkpointing its latest work —
        # the successor's exactly-once scan replays the gap silently.
        more = [{"kind": "op", "doc": "d0", "client": 1,
                 "clientSeq": 1000 + i, "refSeq": 0,
                 "contents": {"tail": i}} for i in range(6)]
        ing_t.append_many(more, fence=1, owner="feeder")
        ing.leases.release("ingress")
        ing2 = IngressRole(d, "ing-2", ttl_s=3600.0,
                           log_format="columnar", ckpt_interval_s=0.0)
        while ing2.step(idle_sleep=0) > 0:
            pass
        admitted = [r for r in raw_t.read_from(0)
                    if isinstance(r, dict)]
        in_offs = [r.get("inOff") for r in admitted]
        assert len(set(in_offs)) == len(in_offs), "duplicate admission"
        tail = [r for r in admitted
                if isinstance(r.get("contents"), dict)
                and "tail" in r["contents"]]
        assert len(tail) == 6, "tail admissions lost across the cut"
        # The pre-cut admissions are still exactly the original set.
        assert admitted[:len(admitted0)] == admitted0

    def test_supervisor_derives_front_door_topics(self, tmp_path):
        from fluidframework_tpu.server.supervisor import (
            ServiceSupervisor,
        )

        sup = ServiceSupervisor(
            str(tmp_path), log_format="columnar", ingress=True,
            retention=True,
        )
        assert sup.child_env["FLUID_RETENTION_TOPICS"] == \
            "deltas,rawdeltas,ingress,nacks"
        sup2 = ServiceSupervisor(
            str(tmp_path / "b"), log_format="columnar", retention=True,
        )
        assert "FLUID_RETENTION_TOPICS" not in sup2.child_env
