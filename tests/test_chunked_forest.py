"""Chunked forest: differential fuzz vs the object forest + columnar
storage properties (reference feature-libraries/chunked-forest)."""

import copy
import random

import numpy as np
import pytest

from fluidframework_tpu.tree import (
    Forest,
    insert_op,
    invert,
    move_op,
    remove_op,
    set_value_op,
)
from fluidframework_tpu.tree.chunked_forest import ChunkedForest
from fluidframework_tpu.tree.forest import make_node


def bulk_leaves(n, type_="num", base=0):
    return [make_node(type_, base + i) for i in range(n)]


def test_bulk_leaf_insert_forms_uniform_chunks():
    f = ChunkedForest()
    f.apply([insert_op([], "data", 0, bulk_leaves(1000))])
    assert f.uniform_ratio([], "data") > 0.99
    col = f.column([], "data")
    assert len(col) == 1000 and col[0] == 0 and col[999] == 999
    # One edit splits only locally: ratio stays high.
    f.apply([set_value_op([["data", 500]], -1)])
    assert f.column([], "data")[500] == -1
    assert f.uniform_ratio([], "data") > 0.9


def test_mixed_content_chunking():
    f = ChunkedForest()
    branchy = make_node("obj")
    branchy["fields"]["sub"] = bulk_leaves(3)
    f.apply([insert_op([], "x", 0,
                       bulk_leaves(5) + [branchy] + bulk_leaves(5, "str"))])
    j = f.to_json()
    assert len(j["fields"]["x"]) == 11
    assert j["fields"]["x"][5]["fields"]["sub"][2]["value"] == 2


def random_change(rng, forest, n_ops):
    sim = forest.clone()
    out = []
    for _ in range(n_ops):
        kind = rng.choice(["insert", "insert", "remove", "set", "move"])
        field = rng.choice(["a", "b"])
        kids = sim.to_json().get("fields", {}).get(field, [])
        if kind == "insert" or not kids:
            n = rng.randint(1, 5)
            op = insert_op([], field, rng.randint(0, len(kids)),
                           bulk_leaves(n, rng.choice(["num", "str"]),
                                       rng.randint(0, 99)))
        elif kind == "remove":
            i = rng.randrange(len(kids))
            op = remove_op([], field, i, rng.randint(1, min(3, len(kids) - i)))
        elif kind == "set":
            op = set_value_op([[field, rng.randrange(len(kids))]],
                              rng.randint(0, 999))
        else:
            i = rng.randrange(len(kids))
            cnt = rng.randint(1, min(3, len(kids) - i))
            dfield = rng.choice(["a", "b"])
            dlen = len(sim.to_json().get("fields", {}).get(dfield, []))
            op = move_op([], field, i, cnt, [], dfield,
                         rng.randint(0, dlen))
        sim.apply([copy.deepcopy(op)])
        out.append(op)
    return out


@pytest.mark.parametrize("seed", range(25))
def test_chunked_matches_object_forest(seed):
    """Differential fuzz: identical JSON state after every change,
    including capture enrichment driving invert round-trips."""
    rng = random.Random(seed)
    obj = Forest()
    chk = ChunkedForest()
    for _ in range(6):
        change = random_change(rng, obj, rng.randint(1, 4))
        c1 = copy.deepcopy(change)
        c2 = copy.deepcopy(change)
        obj.apply(c1)
        chk.apply(c2)
        assert obj.to_json() == chk.to_json(), f"seed {seed}"
    # Invert round-trip through the CHUNKED captures.
    before = chk.to_json()
    change = random_change(rng, obj, 3)
    applied = copy.deepcopy(change)
    chk.apply(applied)
    chk.apply(invert(applied))
    assert chk.to_json() == before


def test_shared_tree_on_chunked_forest():
    """SharedTree runs on the chunked forest end-to-end (flag), with
    convergence against an object-forest replica."""
    from fluidframework_tpu.runtime import ChannelRegistry
    from fluidframework_tpu.testing.mocks import MultiClientHarness
    from fluidframework_tpu.tree.shared_tree import SharedTreeFactory

    reg = ChannelRegistry([SharedTreeFactory()])
    h = MultiClientHarness(
        2, reg, channel_types=[("t", SharedTreeFactory.type_name)]
    )
    t0 = h.runtimes[0].get_datastore("default").get_channel("t")
    t1 = h.runtimes[1].get_datastore("default").get_channel("t")
    t0.use_chunked_forest()
    t0.insert_node([], "rows", 0, bulk_leaves(100))
    h.process_all()
    t1.remove_node([], "rows", 10, 5)
    t0.set_value([["rows", 0]], "edited")
    t0.move_node([], "rows", 50, 3, [], "archive", 0)
    h.process_all()
    assert t0.view() == t1.view()
    assert t0.forest.uniform_ratio([], "rows") > 0.5
    col = t0.forest.column([], "archive")
    assert list(col) == [50, 51, 52]
