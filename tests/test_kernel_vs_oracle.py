"""Differential tests: JAX merge-tree kernel vs the scalar oracle.

The convergence contract (SURVEY.md §3.3): every replica replaying the
same totally ordered op stream reaches identical state. The farm
produces concurrent multi-client streams through the real sequencer; a
passive scalar replica (`replay_passive`) and the TPU `KernelReplica`
both replay them; final text and per-character annotations must match
exactly (the kernel-vs-reference differential strategy of SURVEY.md §4).
"""

import random
import string

import pytest

from fluidframework_tpu.core.kernel_replica import KernelReplica
from fluidframework_tpu.core.mergetree import CollabClient, replay_passive
from fluidframework_tpu.server.sequencer import DocumentSequencer
from fluidframework_tpu.testing.farm import FarmConfig, char_spans, run_sharedstring_farm


def replay_and_compare(cfg: FarmConfig, **replica_kw):
    farm = run_sharedstring_farm(cfg)
    oracle = replay_passive(farm.stream, cfg.initial_text)
    assert oracle.get_text() == farm.final_text

    replica = KernelReplica(initial=cfg.initial_text, **replica_kw)
    replica.apply_messages(farm.stream)
    replica.check_errors()
    assert replica.get_text() == farm.final_text
    assert char_spans(replica.annotated_spans()) == char_spans(
        oracle.annotated_spans()
    )
    return replica


@pytest.mark.parametrize("seed", range(6))
def test_kernel_matches_oracle_small(seed):
    replay_and_compare(
        FarmConfig(num_clients=3, rounds=8, ops_per_client_per_round=3, seed=seed),
        chunk_size=16,
        capacity=256,
    )


@pytest.mark.parametrize("seed", range(3))
def test_kernel_matches_oracle_more_clients(seed):
    replay_and_compare(
        FarmConfig(
            num_clients=8, rounds=6, ops_per_client_per_round=4, seed=500 + seed
        ),
        chunk_size=64,
        capacity=512,
        # 8 concurrent clients can stack >4 removers on a hot row.
        n_removers=8,
    )


def test_kernel_insert_heavy_from_empty():
    replay_and_compare(
        FarmConfig(
            num_clients=4,
            rounds=10,
            ops_per_client_per_round=5,
            seed=11,
            insert_weight=0.85,
            remove_weight=0.1,
            annotate_weight=0.05,
            initial_text="",
        ),
        chunk_size=32,
        capacity=512,
    )


def test_kernel_remove_heavy():
    replay_and_compare(
        FarmConfig(
            num_clients=4,
            rounds=10,
            ops_per_client_per_round=4,
            seed=12,
            insert_weight=0.35,
            remove_weight=0.55,
            annotate_weight=0.1,
            initial_text="the quick brown fox jumps over the lazy dog",
        ),
        chunk_size=32,
        capacity=512,
    )


def test_kernel_tiny_chunks_exercise_boundaries():
    # chunk_size=1: every op is its own jit call; padding/flush logic
    # must be semantics-free.
    replay_and_compare(
        FarmConfig(num_clients=3, rounds=4, ops_per_client_per_round=2, seed=3),
        chunk_size=1,
        capacity=256,
    )


def test_kernel_compaction_mid_stream():
    # Tiny capacity + low watermark forces repeated compactions; the
    # final state must be unaffected.
    replica = replay_and_compare(
        FarmConfig(num_clients=4, rounds=12, ops_per_client_per_round=4, seed=77),
        chunk_size=16,
        capacity=128,
        compact_watermark=0.3,
    )
    assert int(replica.table.n_rows) <= replica.capacity


def test_kernel_insert_with_none_prop_matches_oracle():
    # None-valued insert props are absent on both engines (the
    # null-deletes convention; kernel dictionary encoding can't
    # materialize PROP_DELETE on a new segment).
    from fluidframework_tpu.protocol.mergetree_ops import InsertOp
    from fluidframework_tpu.protocol.messages import (
        MessageType,
        SequencedMessage,
    )

    stream = [
        SequencedMessage(
            sequence_number=1,
            minimum_sequence_number=0,
            client_id=1,
            client_seq=1,
            ref_seq=0,
            type=MessageType.OP,
            contents=InsertOp(pos=0, text="abc", props={"k": None, "b": 1}),
        )
    ]
    oracle = replay_passive(stream)
    replica = KernelReplica(chunk_size=4, capacity=64)
    replica.apply_messages(stream)
    replica.check_errors()
    assert replica.get_text() == oracle.get_text() == "abc"
    assert char_spans(replica.annotated_spans()) == char_spans(
        oracle.annotated_spans()
    ) == [("a", (("b", 1),)), ("b", (("b", 1),)), ("c", (("b", 1),))]


def test_kernel_sequential_inserts_deterministic():
    # Single writer, pure append/typing pattern.
    seqr = DocumentSequencer("d")
    client = CollabClient(1)
    seqr.join(1)
    client.engine.current_seq = seqr.seq
    stream = []
    rng = random.Random(5)
    for _ in range(200):
        text = "".join(rng.choice(string.ascii_lowercase) for _ in range(3))
        pos = rng.randint(0, len(client.get_text()))
        msg = client.insert_local(pos, text)
        out = seqr.sequence(1, msg)
        client.apply_msg(out)
        stream.append(out)
    replica = KernelReplica(chunk_size=64, capacity=2048)
    replica.apply_messages(stream)
    replica.check_errors()
    assert replica.get_text() == client.get_text()
