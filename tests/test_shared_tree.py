"""SharedTree tests: rebase laws (the verifyChangeRebaser contract,
packages/dds/tree/src/core/rebase/verifyChangeRebaser.ts), TP1
convergence of the transform, id-compressor semantics, and
multi-client fuzz through the production runtime stack.
"""

from __future__ import annotations

import random

import pytest

from fluidframework_tpu.runtime import ChannelRegistry, ContainerRuntime
from fluidframework_tpu.runtime.summary import SummaryTree
from fluidframework_tpu.testing.mocks import MultiClientHarness
from fluidframework_tpu.tree import (
    Forest,
    IdCompressor,
    SharedTreeFactory,
    compose,
    insert_op,
    invert,
    rebase_change,
    remove_op,
    set_value_op,
)
from fluidframework_tpu.tree.forest import make_node

REGISTRY = ChannelRegistry([SharedTreeFactory()])


def leaf(v):
    return make_node("leaf", value=v)


def seeded_forest():
    f = Forest()
    f.root["fields"]["items"] = [leaf(i) for i in range(6)]
    f.root["fields"]["items"][2]["fields"]["sub"] = [leaf("x"), leaf("y")]
    return f


def random_change(rng, forest):
    """One random valid op against `forest`."""
    items = forest.root["fields"]["items"]
    r = rng.random()
    if r < 0.4:
        return [insert_op([], "items", rng.randint(0, len(items)),
                          [leaf(rng.randint(100, 999))])]
    if r < 0.7 and items:
        i = rng.randrange(len(items))
        count = min(rng.randint(1, 2), len(items) - i)
        return [remove_op([], "items", i, count)]
    if items:
        i = rng.randrange(len(items))
        return [set_value_op([["items", i]], rng.randint(0, 99))]
    return [insert_op([], "items", 0, [leaf(0)])]


# ------------------------------------------------------------- rebase laws


@pytest.mark.parametrize("seed", range(8))
def test_tp1_convergence(seed):
    """apply(S, A ∘ T(B,A)) == apply(S, B ∘ T(A,B)) with priority:
    A sequenced first."""
    rng = random.Random(seed)
    for _ in range(40):
        S = seeded_forest()
        A = random_change(rng, S)
        B = random_change(rng, S)
        left = S.clone()
        left.apply([dict(op) for op in A])
        left.apply(rebase_change(B, A, over_first=True))
        right = S.clone()
        right.apply([dict(op) for op in B])
        right.apply(rebase_change(A, B, over_first=False))
        assert left.to_json() == right.to_json(), (A, B)


@pytest.mark.parametrize("seed", range(4))
def test_invert_roundtrip(seed):
    rng = random.Random(100 + seed)
    for _ in range(30):
        S = seeded_forest()
        before = S.to_json()
        change = random_change(rng, S)
        applied = [dict(op) for op in change]
        S.apply(applied)  # enriches with content/prev
        S.apply(invert(applied))
        assert S.to_json() == before


def test_rebase_over_composition_equals_sequential():
    rng = random.Random(7)
    S = seeded_forest()
    A = random_change(rng, S)
    SA = S.clone()
    SA.apply([dict(o) for o in A])
    B = random_change(rng, SA)  # B authored after A
    C = random_change(rng, S)  # C concurrent with both
    seq = rebase_change(rebase_change(C, A), B)
    comp = rebase_change(C, compose([A, B]))
    SL, SR = SA.clone(), SA.clone()
    SL.apply([dict(o) for o in B])
    SR.apply([dict(o) for o in B])
    SL.apply(seq)
    SR.apply(comp)
    assert SL.to_json() == SR.to_json()


def test_nested_edit_muted_by_ancestor_remove():
    S = seeded_forest()
    edit = [set_value_op([["items", 2], ["sub", 0]], "changed")]
    kill = [remove_op([], "items", 2, 1)]
    rebased = rebase_change(edit, kill)
    assert rebased == []  # muted: its subtree is gone


def test_nested_path_shifts_with_sibling_edits():
    S = seeded_forest()
    edit = [set_value_op([["items", 2], ["sub", 1]], "z")]
    shift = [insert_op([], "items", 0, [leaf("new")])]
    rebased = rebase_change(edit, shift)
    assert rebased[0]["path"] == [["items", 3], ["sub", 1]]


# ------------------------------------------------------------ id compressor


def test_id_compressor_finalization_consistency():
    a = IdCompressor("A", cluster_capacity=4)
    b = IdCompressor("B", cluster_capacity=4)
    ids = [a.generate_compressed_id() for _ in range(3)]
    assert ids == [-1, -2, -3]
    # Both replicas finalize the same ranges in the same order.
    for c in (a, b):
        c.finalize_range("A", 3)
        c.finalize_range("B", 2)
        c.finalize_range("A", 2)
    # A's locals map to finals identically on both.
    finals_on_a = [a.normalize_to_op_space(i) for i in ids]
    finals_on_b = [a._local_to_final("A", i) for i in ids]
    assert finals_on_a == finals_on_b
    assert b.decompress(finals_on_a[0]) == ("A", 1)
    # Cluster growth: A's 4th/5th ids spill into a new cluster.
    assert a._local_to_final("A", -5) is not None
    rt = IdCompressor.deserialize(a.serialize())
    assert rt.decompress(finals_on_a[2]) == ("A", 3)


# ----------------------------------------------------- DDS through runtime


def make_harness(n=2):
    return MultiClientHarness(
        n, REGISTRY, channel_types=[("t", SharedTreeFactory.type_name)]
    )


def test_tree_basic_convergence():
    h = make_harness()
    a, b = h.channel(0, "t"), h.channel(1, "t")
    a.insert_node([], "todo", 0, [leaf("buy milk")])
    h.process_all()
    b.insert_node([], "todo", 1, [leaf("walk dog")])
    a.set_value([["todo", 0]], "buy oat milk")
    h.process_all()
    assert a.view() == b.view()
    todos = a.view()["fields"]["todo"]
    assert [t["value"] for t in todos] == ["buy oat milk", "walk dog"]


def test_tree_concurrent_same_index_inserts():
    h = make_harness()
    a, b = h.channel(0, "t"), h.channel(1, "t")
    a.insert_node([], "L", 0, [leaf("A")])
    b.insert_node([], "L", 0, [leaf("B")])
    h.process_all()
    assert a.view() == b.view()
    # a's op sequenced first: its content lands first.
    assert [n["value"] for n in a.view()["fields"]["L"]] == ["A", "B"]


def test_tree_concurrent_remove_and_edit():
    h = make_harness()
    a, b = h.channel(0, "t"), h.channel(1, "t")
    a.edit([insert_op([], "L", 0, [leaf(i) for i in range(5)])])
    h.process_all()
    a.remove_node([], "L", 1, 3)
    b.set_value([["L", 2]], "edited")  # inside a's removed range: muted
    b.set_value([["L", 4]], "kept")  # outside: survives, slides to 1
    h.process_all()
    assert a.view() == b.view()
    vals = [n["value"] for n in a.view()["fields"]["L"]]
    assert vals == [0, "kept"]


def test_tree_fuzz_convergence():
    h = make_harness(3)
    chans = [h.channel(i, "t") for i in range(3)]
    chans[0].edit([insert_op([], "items", 0, [leaf(i) for i in range(4)])])
    h.process_all()
    rng = random.Random(11)
    for _ in range(25):
        for c in chans:
            c.edit(random_change(rng, c.forest))
        h.process_all()
    views = [c.view() for c in chans]
    assert views[0] == views[1] == views[2]


def test_tree_summary_roundtrip_and_rejoin():
    h = make_harness()
    a = h.channel(0, "t")
    a.insert_node([], "doc", 0, [make_node("para", fields={"runs": [leaf("hi")]})])
    a.set_value([["doc", 0], ["runs", 0]], "hello")
    h.process_all()
    wire = h.runtimes[0].summarize().to_json()
    rt = ContainerRuntime(REGISTRY)
    rt.load(SummaryTree.from_json(wire))
    t = rt.get_datastore("default").get_channel("t")
    assert t.view() == a.view()
    rt.connect(h.service.connect(h.doc_id, client_id=31))
    t.insert_node([], "doc", 1, [leaf("appended")])
    rt.flush()
    h.process_all()
    assert h.channel(1, "t").view() == t.view()


def test_tree_ids_travel_with_commits():
    h = make_harness()
    a, b = h.channel(0, "t"), h.channel(1, "t")
    nid = a.generate_id()
    a.insert_node([], "k", 0, [make_node("n", value=nid)], id_count=1)
    h.process_all()
    # Both replicas finalized a's range identically.
    fa = a.id_compressor.normalize_to_op_space(nid)
    assert fa >= 0
    assert b.id_compressor.decompress(fa) == (str(1), 1)
