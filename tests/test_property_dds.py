"""PropertyDDS family (experimental/PropertyDDS role): typed
templates, the nested changeset algebra (apply/squash laws), and
SharedPropertyTree convergence through the runtime stack."""

import random

import pytest

from fluidframework_tpu.experimental import (
    ChangeSet,
    PropertySet,
    PropertyTemplate,
    SharedPropertyTree,
    SharedPropertyTreeFactory,
)
from fluidframework_tpu.experimental.property_dds import _Registry
from fluidframework_tpu.runtime import ChannelRegistry
from fluidframework_tpu.testing.mocks import MultiClientHarness


POINT = PropertyTemplate(
    "test:point-1.0.0",
    [{"id": "x", "typeid": "Float64"},
     {"id": "y", "typeid": "Float64"},
     {"id": "label", "typeid": "String"}],
)


def make_registry():
    r = _Registry()
    r.register(POINT)
    return r


def test_template_validation():
    with pytest.raises(ValueError):
        PropertyTemplate("t", [{"id": "a", "typeid": "Int32"},
                               {"id": "a", "typeid": "Int32"}])
    with pytest.raises(ValueError):
        PropertyTemplate("t", [{"id": "a"}])


def test_typed_property_set():
    ps = PropertySet("test:point-1.0.0", make_registry())
    assert ps.get("x") == 0.0 and ps.get("label") == ""
    ps.set_value("x", 3)  # Int32 widens into Float64
    assert ps.get("x") == 3.0
    with pytest.raises(TypeError):
        ps.set_value("label", 7)
    with pytest.raises(KeyError):
        ps.get("nope")
    # Round-trip.
    back = PropertySet.from_json(ps.to_json(), make_registry())
    assert back.to_json() == ps.to_json()


def test_changeset_apply_and_squash_laws():
    reg = make_registry()

    def fresh():
        ps = PropertySet("NodeProperty", reg)
        return ps

    a = ChangeSet({"insert": {"p": {
        "typeid": "test:point-1.0.0",
        "fields": {"x": {"value": 1.0, "typeid": "Float64"},
                   "y": {"value": 2.0, "typeid": "Float64"},
                   "label": {"value": "P", "typeid": "String"}},
    }}})
    b = ChangeSet({"modify": {"p": {"modify": {"x": {"value": 9.0}}}}})
    c = ChangeSet({"remove": ["p"]})

    # squash(a, b) applied == a then b applied (the squash law).
    s1, s2 = fresh(), fresh()
    a.apply(s1)
    b.apply(s1)
    a.squash(b).apply(s2)
    assert s1.to_json() == s2.to_json()
    # modify-after-insert folded INTO the insert payload.
    assert a.squash(b).data["insert"]["p"]["fields"]["x"]["value"] == 9.0
    # remove cancels a pending insert.
    assert "p" not in a.squash(c).data.get("insert", {})
    s3 = fresh()
    a.squash(c).apply(s3)
    assert "p" not in s3.to_json()["fields"]
    # modify of a concurrently removed child mutes.
    s4 = fresh()
    c2 = ChangeSet({"modify": {"ghost": {"value": 1}}})
    c2.apply(s4)
    assert s4.to_json()["fields"] == {}


def make_pair():
    registry = ChannelRegistry([SharedPropertyTreeFactory()])
    h = MultiClientHarness(
        2, registry,
        channel_types=[("props", SharedPropertyTreeFactory.type_name)],
    )
    a = h.runtimes[0].get_datastore("default").get_channel("props")
    b = h.runtimes[1].get_datastore("default").get_channel("props")
    for t in (a, b):
        t.register_template(POINT)
    return h, a, b


def test_shared_property_tree_convergence():
    h, a, b = make_pair()
    a.insert_property("origin", "test:point-1.0.0")
    a.set_value("origin.label", "O")
    a.commit()
    h.process_all()
    assert b.root.get("origin.label") == "O"

    # Concurrent leaf writes: last-sequenced wins on both replicas.
    a.set_value("origin.x", 1.0)
    a.commit()
    b.set_value("origin.x", 2.0)
    b.commit()
    h.process_all()
    assert a.root.get("origin.x") == b.root.get("origin.x")

    # Concurrent modify vs remove: the removal mutes the edit.
    a.set_value("origin.y", 5.0)
    a.commit()
    b.remove_property("origin")
    b.commit()
    h.process_all()
    assert a.root.to_json() == b.root.to_json()


def test_shared_property_tree_summary_boot():
    from fluidframework_tpu.runtime import ContainerRuntime
    from fluidframework_tpu.runtime.summary import SummaryTree

    h, a, b = make_pair()
    a.insert_property("cfg", "NodeProperty")
    a.insert_property("cfg.depth", "Int32")
    a.set_value("cfg.depth", 4)
    a.commit()
    h.process_all()
    wire = h.runtimes[0].summarize().to_json()
    registry = ChannelRegistry([SharedPropertyTreeFactory()])
    cold = ContainerRuntime(registry)
    cold.load(SummaryTree.from_json(wire))
    tree = cold.get_datastore("default").get_channel("props")
    assert tree.root.get("cfg.depth") == 4


@pytest.mark.parametrize("seed", range(6))
def test_property_tree_fuzz_convergence(seed):
    """Concurrent insert/set/remove AND the remove+reinsert composite
    (the racing-structural hotspot) across two clients: replicas
    converge every round."""
    h, a, b = make_pair()
    a.insert_property("n", "NodeProperty")
    a.commit()
    h.process_all()
    rng = random.Random(1000 + seed)
    names = [f"k{i}" for i in range(4)]
    for rnd in range(25):
        for t in (a, b):
            for _ in range(3):
                name = rng.choice(names)
                path = f"n.{name}"
                exists = name in t.root.get("n")._children
                r = rng.random()
                if not exists and r < 0.55:
                    t.insert_property(path, "Int32")
                elif exists and r < 0.45:
                    t.set_value(path, rng.randint(0, 99))
                elif exists and r < 0.8:
                    t.remove_property(path)
                elif exists:
                    t.remove_property(path)
                    t.insert_property(path, "Int32")
                    t.set_value(path, rng.randint(100, 199))
            t.commit()
        h.process_all()
        assert a.root.to_json() == b.root.to_json(), f"round {rnd}"


def test_pending_insert_survives_racing_remove():
    """B re-inserts a name while A concurrently removes it: B's insert
    sequences later, so every replica — including B, whose optimistic
    insert the remove popped — ends with B's payload."""
    h, a, b = make_pair()
    a.insert_property("k", "Int32")
    a.commit()
    h.process_all()
    a.remove_property("k")
    a.commit()
    b.remove_property("k")
    b.insert_property("k", "Int32")
    b.set_value("k", 7)
    b.commit()
    h.process_all()
    assert a.root.to_json() == b.root.to_json()
    assert b.root.get("k") == 7


def test_nested_modify_vs_replaced_child_shapes_mute():
    """A nested modify arriving after its target container was
    replaced by a primitive (or vice versa) mutes instead of
    crashing/clobbering — on every replica."""
    h, a, b = make_pair()
    a.insert_property("c", "NodeProperty")
    a.insert_property("c.x", "Int32")
    a.commit()
    h.process_all()
    # A replaces container c with an Int32; B edits c.x concurrently.
    a.remove_property("c")
    a.insert_property("c", "Int32")
    a.set_value("c", 1)
    a.commit()
    b.set_value("c.x", 5)
    b.commit()
    h.process_all()
    assert a.root.to_json() == b.root.to_json()
    assert a.root.get("c") == 1


def test_echo_respects_later_pending_commits():
    """An earlier commit's echo must not clobber optimistic values of
    a LATER still-pending commit."""
    h, a, b = make_pair()
    a.insert_property("k", "Int32")
    a.commit()
    h.process_all()
    a.set_value("k", 1)
    a.commit()
    h.runtimes[0].flush()
    h.service.process_all()  # sequence commit 1 without delivering 2
    a.set_value("k", 2)
    a.commit()
    assert a.root.get("k") == 2  # optimistic value survives the echo
    h.process_all()
    assert a.root.get("k") == b.root.get("k") == 2


# ---------------------------------------------------------------------------
# rebase semantics (round 5: changeset rebase replaces apply-time LWW)
# ---------------------------------------------------------------------------


def _pair():
    from fluidframework_tpu.experimental.property_dds import (
        SharedPropertyTreeFactory,
    )
    from fluidframework_tpu.runtime import ChannelRegistry
    from fluidframework_tpu.testing.mocks import MultiClientHarness

    reg = ChannelRegistry([SharedPropertyTreeFactory()])
    h = MultiClientHarness(
        2, reg, channel_types=[("p", SharedPropertyTreeFactory.type_name)]
    )
    a = h.runtimes[0].get_datastore("default").get_channel("p")
    b = h.runtimes[1].get_datastore("default").get_channel("p")
    return h, a, b


def test_rebase_remove_wins_over_modify():
    """The reference's remove-over-modify law: a concurrent modify of
    a removed subtree drops on every replica."""
    h, a, b = _pair()
    a.insert_property("cfg", "NodeProperty")
    a.insert_property("cfg.n", "Int32")
    a.commit()
    h.process_all()
    a.remove_property("cfg")
    b.set_value("cfg.n", 42)
    a.commit()
    b.commit()
    h.process_all()
    assert a.root.to_json() == b.root.to_json()
    assert "cfg" not in a.root._children


def test_rebase_concurrent_structural_inserts():
    """Concurrent sibling inserts both survive; same-name concurrent
    inserts resolve later-sequenced-wins — identically everywhere."""
    h, a, b = _pair()
    a.insert_property("left", "Int32")
    b.insert_property("right", "Int32")
    a.insert_property("both", "Int32")
    a.set_value("both", 1)
    b.insert_property("both", "Int32")
    b.set_value("both", 2)
    a.commit()
    b.commit()
    h.process_all()
    assert a.root.to_json() == b.root.to_json()
    assert "left" in a.root._children and "right" in a.root._children
    # b sequenced after a: its insert payload won.
    assert a.root.get("both") == 2


def test_array_concurrent_inserts_adjust_indices():
    """Index-adjusting array rebase: concurrent inserts at different
    positions both land, earlier-sequenced content first on ties."""
    h, a, b = _pair()
    a.insert_property("arr", "Array")
    a.array_insert("arr", 0, [10, 20, 30, 40])
    a.commit()
    h.process_all()
    a.array_insert("arr", 1, ["a1", "a2"])   # sequences first
    b.array_insert("arr", 3, ["b1"])
    a.commit()
    b.commit()
    h.process_all()
    assert a.root.get("arr") == b.root.get("arr")
    assert a.root.get("arr") == [10, "a1", "a2", 20, 30, "b1", 40]


def test_array_remove_vs_set_and_overlapping_removes():
    h, a, b = _pair()
    a.insert_property("arr", "Array")
    a.array_insert("arr", 0, list(range(8)))
    a.commit()
    h.process_all()
    # a removes [2, 6); b sets index 3 (inside) and 7 (outside).
    a.array_remove("arr", 2, 4)
    b.array_set("arr", 3, 99)
    b.array_set("arr", 7, 77)
    a.commit()
    b.commit()
    h.process_all()
    assert a.root.get("arr") == b.root.get("arr")
    # Removal wins over the inside set; the outside set slid left.
    assert a.root.get("arr") == [0, 1, 6, 77]
    # Overlapping removes clip, never double-remove.
    a.array_remove("arr", 1, 2)
    b.array_remove("arr", 2, 2)
    a.commit()
    b.commit()
    h.process_all()
    assert a.root.get("arr") == b.root.get("arr")
    assert a.root.get("arr") == [0]


def test_rebase_fuzz_concurrent_structural_edits():
    """Randomized concurrent structural + leaf + array edits across
    two clients with batched commits: replicas converge after every
    drain (the rebase-semantics convergence bar)."""
    import random

    rng = random.Random(99)
    h, a, b = _pair()
    a.insert_property("arr", "Array")
    a.insert_property("m", "NodeProperty")
    a.commit()
    h.process_all()
    names = [f"k{i}" for i in range(6)]
    for rnd in range(30):
        for t in (a, b):
            for _ in range(3):
                r = rng.random()
                arr = t.root.get("arr")
                if r < 0.25:
                    n = rng.choice(names)
                    if n not in t.root.get("m")._children:
                        t.insert_property(f"m.{n}", "Int32")
                    else:
                        t.set_value(f"m.{n}", rng.randint(0, 99))
                elif r < 0.4:
                    n = rng.choice(names)
                    if n in t.root.get("m")._children:
                        t.remove_property(f"m.{n}")
                elif r < 0.65:
                    t.array_insert(
                        "arr", rng.randint(0, len(arr)),
                        [rng.randint(100, 999)],
                    )
                elif r < 0.8 and arr:
                    i = rng.randrange(len(arr))
                    t.array_remove(
                        "arr", i, min(len(arr) - i, rng.randint(1, 3))
                    )
                elif arr:
                    t.array_set(
                        "arr", rng.randrange(len(arr)),
                        rng.randint(1000, 1999),
                    )
            t.commit()
        h.process_all()
        assert a.root.to_json() == b.root.to_json(), f"round {rnd}"
