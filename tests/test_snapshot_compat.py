"""Back-compat corpus: boot every PINNED summary fixture + op tail.

The packages/test/snapshots role: tests/fixtures/summary_v*.json were
produced by earlier code (tools/make_compat_fixture.py at the round
that introduced each format version) and are never regenerated — a
loader change that cannot boot an old summary, or a DDS change that
replays its op tail differently, fails here.
"""

import glob
import json
import os

import pytest

from fluidframework_tpu.dds import MapFactory, MatrixFactory, StringFactory
from fluidframework_tpu.drivers.file_driver import message_from_json
from fluidframework_tpu.runtime import ChannelRegistry, ContainerRuntime
from fluidframework_tpu.runtime.container_runtime import (
    SUMMARY_FORMAT_VERSION,
)
from fluidframework_tpu.runtime.summary import SummaryTree

FIXTURES = sorted(
    glob.glob(
        os.path.join(os.path.dirname(__file__), "fixtures", "summary_v*.json")
    )
)


def registry():
    return ChannelRegistry([MapFactory(), StringFactory(), MatrixFactory()])


def test_corpus_exists_and_covers_current_version():
    assert FIXTURES, "no pinned summary fixtures"
    versions = [json.load(open(p))["formatVersion"] for p in FIXTURES]
    assert SUMMARY_FORMAT_VERSION in versions, (
        "current summary format has no pinned fixture — run "
        "tools/make_compat_fixture.py and check the output in"
    )


@pytest.mark.parametrize("path", FIXTURES, ids=os.path.basename)
def test_boot_pinned_fixture(path):
    with open(path) as f:
        fx = json.load(f)
    rt = ContainerRuntime(registry())
    rt.load(SummaryTree.from_json(fx["wire"]))
    assert rt.current_seq == fx["summarySeq"]
    # Replay the recorded post-summary op tail (catch-up).
    for row in fx["tail"]:
        rt.process(message_from_json(row))
    ds = rt.get_datastore("default")
    expect = fx["expect"]
    assert ds.get_channel("text").get_text() == expect["text"]
    kv = ds.get_channel("kv")
    for k, v in expect["kv"].items():
        assert kv.get(k) == v
    grid = ds.get_channel("grid")
    for key, v in expect["grid_cells"].items():
        r, c = map(int, key.split(","))
        assert grid.get_cell(r, c) == v


def test_future_format_version_refused():
    with open(FIXTURES[-1]) as f:
        fx = json.load(f)
    tree = SummaryTree.from_json(fx["wire"])
    meta = json.loads(tree.get_blob(".metadata"))
    meta["formatVersion"] = SUMMARY_FORMAT_VERSION + 1
    # Rebuild the tree with a bumped version: the loader must refuse
    # rather than misread a future format.
    from fluidframework_tpu.runtime.summary import SummaryTreeBuilder

    b = SummaryTreeBuilder()
    for name, node in tree.entries.items():
        if name == ".metadata":
            b.add_json_blob(".metadata", meta)
        elif isinstance(node, SummaryTree):
            b.add_tree(name, node)
        else:
            b.add_blob(name, node)
    rt = ContainerRuntime(registry())
    with pytest.raises(ValueError, match="unsupported summary format"):
        rt.load(b.summary)
