"""The supervised admission front door (`server.ingress`).

Alfred's contract, enforced at the farm's edge: token-validated,
size-capped, rate-limited, backpressure-gated admission BEFORE the
sequencer — every rejection a signed nack record on the `nacks`
topic, every admitted record stamped with its ingress offset, and the
whole thing exactly-once across restarts (nacks never duplicate,
admitted submits never drop). Codec-side: raw kinds carry the `inOff`
admission stamp on the existing in_off column, and frames carry a
frame-level `inSrc` tag (FLAG_SRC), so neither admission nor elastic
pred drains cost the columnar fast path.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np
import pytest

from fluidframework_tpu.protocol import record_batch as rb
from fluidframework_tpu.server.columnar_log import (
    ColumnarFileTopic,
    make_topic,
)
from fluidframework_tpu.server.ingress import (
    NACK_AUTH,
    NACK_RATE,
    NACK_SIZE,
    IngressRole,
    load_tenants,
    sign_nack,
    verify_nack,
    write_tenants,
)
from fluidframework_tpu.server.queue import (
    RangeLeaseStore,
    SharedFileTopic,
    partition_of,
    split_ranges,
)
from fluidframework_tpu.server.riddler import sign_token
from fluidframework_tpu.server.supervisor import DeliRole, _topic_path


def _ing_topic(d, log_format="json"):
    return make_topic(os.path.join(str(d), "topics", "ingress.jsonl"),
                      log_format)


def _nacks(d, log_format="json"):
    t = make_topic(os.path.join(str(d), "topics", "nacks.jsonl"),
                   log_format)
    return [r for r in t.read_from(0)
            if isinstance(r, dict) and r.get("kind") == "nack"]


def _raw(d, name="rawdeltas", log_format="json"):
    t = make_topic(_topic_path(str(d), name), log_format)
    return [r for r in t.read_from(0) if isinstance(r, dict)]


def _op(doc, client, cseq, contents=None, **extra):
    return {"kind": "op", "doc": doc, "client": client,
            "clientSeq": cseq, "refSeq": 0,
            "contents": contents if contents is not None else {"c": cseq},
            **extra}


# ---------------------------------------------------------------------------
# codec: admission stamp + frame src tag
# ---------------------------------------------------------------------------


class TestCodecFrontDoor:
    def test_raw_kinds_round_trip_with_inoff(self):
        recs = [
            {**_op("d1", 3, 1), "inOff": 7},
            {"kind": "join", "doc": "d1", "client": 4, "inOff": 8},
            {"kind": "leave", "doc": "d1", "client": 4, "inOff": 9},
            {"kind": "boxcar", "doc": "d2", "client": 3, "inOff": 10,
             "ops": [{"clientSeq": 2, "refSeq": 0, "contents": "x"}]},
        ]
        batch, _end, n = rb.decode_batch(rb.encode_batch(recs))
        assert n == 4
        # The admission stamp rides the EXISTING in_off column — the
        # kinds stay columnar, not K_GENERIC.
        assert batch.kind.tolist() == [
            rb.K_RAW_OP, rb.K_RAW_JOIN, rb.K_RAW_LEAVE, rb.K_RAW_BOXCAR
        ]
        assert batch.in_off.tolist() == [7, 8, 9, 10]
        assert batch.records() == recs

    def test_negative_inoff_rides_generic_losslessly(self):
        # The in_off column encodes absence as -1: a record carrying a
        # NEGATIVE inOff must fall to K_GENERIC (else decode would
        # silently drop the key — the lossless contract).
        recs = [
            {**_op("d", 1, 2), "inOff": -1},
            {"kind": "join", "doc": "d", "client": 1, "inOff": -7},
        ]
        batch, _e, _n = rb.decode_batch(rb.encode_batch(recs))
        assert batch.kind.tolist() == [rb.K_GENERIC, rb.K_GENERIC]
        assert batch.records() == recs

    def test_raw_kinds_without_inoff_unchanged(self):
        recs = [_op("d", 1, 1, None),
                {"kind": "join", "doc": "d", "client": 2}]
        batch, _e, _n = rb.decode_batch(rb.encode_batch(recs))
        assert batch.kind.tolist() == [rb.K_RAW_OP, rb.K_RAW_JOIN]
        assert batch.records() == recs  # no phantom inOff key

    def test_homogeneous_run_hoist_matches_classify_with_inoff(self):
        # Same key set, one record with a NON-int inOff mid-run: the
        # hoisted revalidator must demote exactly that record.
        recs = [{**_op("d", 1, i + 1), "inOff": i} for i in range(6)]
        recs[3] = {**recs[3], "inOff": "nope"}
        batch, _e, _n = rb.decode_batch(rb.encode_batch(recs))
        kinds = batch.kind.tolist()
        assert kinds[3] == rb.K_GENERIC
        assert all(k == rb.K_RAW_OP for i, k in enumerate(kinds)
                   if i != 3)
        assert [rb._classify(r) for r in recs] == kinds

    def test_frame_src_tags_every_decoded_record(self):
        recs = [
            {"kind": "op", "doc": "d", "seq": 1, "msn": 1, "client": 2,
             "clientSeq": 1, "refSeq": 0, "type": "op", "contents": 1,
             "inOff": 5},
            {"kind": "nack", "doc": "d", "client": 2, "clientSeq": 2,
             "code": 7, "reason": "r", "inOff": 6},
            {"kind": "weird", "doc": "d", "x": 1},  # generic stray
        ]
        frame = rb.encode_batch(recs, src="r-abc")
        batch, _e, _n = rb.decode_batch(frame)
        assert batch.src == "r-abc"
        for rec in batch.records():
            assert rec["inSrc"] == "r-abc"
        # CRC covers the flag byte: flip it and the frame is rejected.
        broken = bytearray(frame)
        broken[5] = 0  # flags byte
        b2, _e2, n2 = rb.decode_batch(bytes(broken))
        assert b2 is None and n2 == 3  # skip-but-count

    def test_src_frame_passthrough_drops_tag_like_dict_strip(self):
        # ColumnarRecords.from_batch re-emits WITHOUT the tag (the
        # downstream stages strip inSrc on the dict path — both paths
        # must agree).
        recs = [{"kind": "op", "doc": "d", "seq": 1, "msn": 1,
                 "client": 2, "clientSeq": 1, "refSeq": 0,
                 "type": "op", "contents": 1, "inOff": 5}]
        batch, _e, _n = rb.decode_batch(rb.encode_batch(recs, src="rX"))
        seg = rb.ColumnarRecords.from_batch(
            batch, np.array([0]), np.array([11])
        )
        assert "inSrc" not in seg.record(0)
        out, _e2, _n2 = rb.decode_batch(rb.encode_columns(seg))
        assert "inSrc" not in out.records()[0]

    def test_explicit_per_record_tag_still_wins(self):
        # A record that ALREADY carries inSrc (recovery's dict path)
        # keeps its own tag even inside a src frame.
        recs = [{"kind": "op", "doc": "d", "seq": 1, "msn": 1,
                 "client": 2, "clientSeq": 1, "refSeq": 0,
                 "type": "op", "contents": 1, "inOff": 5,
                 "inSrc": "r-own"}]
        batch, _e, _n = rb.decode_batch(rb.encode_batch(recs,
                                                        src="r-frame"))
        assert batch.records()[0]["inSrc"] == "r-own"


# ---------------------------------------------------------------------------
# admission taxonomy
# ---------------------------------------------------------------------------


class TestAdmission:
    def test_auth_nack_signed_never_routed(self, tmp_path):
        key = write_tenants(str(tmp_path), {"t1": "k1"}) and "k1"
        assert load_tenants(str(tmp_path)) == {"t1": "k1"}
        tok = sign_token("k1", "t1", "docA", ["doc:write"])
        _ing_topic(tmp_path).append_many([
            _op("docA", 1, 1, tenant="t1", token=tok),
            _op("docA", 2, 1, tenant="t1", token=tok[:-4] + "zzzz"),
            _op("docA", 3, 1, tenant="t1",
                token=sign_token("k1", "t1", "OTHER", ["doc:write"])),
            _op("docA", 4, 1, tenant="nobody", token=tok),
            _op("docA", 5, 1),  # no credentials at all
        ])
        ing = IngressRole(str(tmp_path), "i1", ttl_s=60.0)
        ing.step()
        raw = _raw(tmp_path)
        assert [r["client"] for r in raw] == [1]
        assert raw[0]["inOff"] == 0
        assert "token" not in raw[0] and "tenant" not in raw[0]
        nacks = _nacks(tmp_path)
        assert [n["client"] for n in nacks] == [2, 3, 4, 5]
        assert all(n["code"] == NACK_AUTH for n in nacks)
        # Signed where the tenant resolves; verifiable; forgery fails.
        for n in nacks[:2]:
            assert verify_nack("k1", n)
            assert not verify_nack("other-key", n)
            forged = {**n, "reason": "all good actually"}
            forged["sig"] = n["sig"]
            assert not verify_nack("k1", forged)
        assert "sig" not in nacks[2]  # unknown tenant: no key to sign

    def test_expired_token_nacked_through_cache(self, tmp_path):
        write_tenants(str(tmp_path), {"t1": "k1"})
        # Token expiries are whole seconds (the JWT shape): 1.5s is
        # the shortest lifetime that reliably covers the first step.
        tok = sign_token("k1", "t1", "docA", ["doc:write"],
                         lifetime_s=1.5)
        ing = IngressRole(str(tmp_path), "i1", ttl_s=60.0)
        t = _ing_topic(tmp_path)
        t.append_many([_op("docA", 1, 1, tenant="t1", token=tok)])
        ing.step()
        assert len(_raw(tmp_path)) == 1  # valid while fresh (cached)
        time.sleep(1.6)
        t.append_many([_op("docA", 1, 2, tenant="t1", token=tok)])
        ing.step()
        # The cache stores the expiry; a stale cached token still nacks.
        assert len(_raw(tmp_path)) == 1
        assert _nacks(tmp_path)[-1]["code"] == NACK_AUTH

    def test_session_auth_covers_bare_records(self, tmp_path):
        """The alfred connection shape: one auth record opens a
        session; subsequent BARE records from that (doc, client)
        inherit it — no per-record credentials, so the op stream
        keeps the columnar schema. No session, no entry."""
        write_tenants(str(tmp_path), {"t1": "k1"})
        tok = sign_token("k1", "t1", "docA", ["doc:write"])
        ing = IngressRole(str(tmp_path), "i1", ttl_s=60.0)
        t = _ing_topic(tmp_path)
        t.append_many([
            {"kind": "auth", "doc": "docA", "client": 1,
             "tenant": "t1", "token": tok},
            _op("docA", 1, 1),          # bare: session admits it
            _op("docA", 2, 1),          # bare, NO session: nacked
            {"kind": "auth", "doc": "docA", "client": 3,
             "tenant": "t1", "token": "garbage"},  # bad session open
            _op("docA", 3, 1),          # its session never opened
        ])
        ing.step()
        raw = _raw(tmp_path)
        assert [r["client"] for r in raw] == [1]
        assert "token" not in raw[0]
        nacks = _nacks(tmp_path)
        assert [n["client"] for n in nacks] == [2, 3, 3]
        assert all(n["code"] == NACK_AUTH for n in nacks)
        # Sessions survive a restart (checkpointed state).
        ing.checkpoint()
        ing.leases.release("ingress")
        ing2 = IngressRole(str(tmp_path), "i2", ttl_s=60.0)
        t.append_many([_op("docA", 1, 2)])
        ing2.step()
        assert [r["clientSeq"] for r in _raw(tmp_path)
                if r["client"] == 1] == [1, 2]

    def test_session_expiry_enforced(self, tmp_path):
        write_tenants(str(tmp_path), {"t1": "k1"})
        tok = sign_token("k1", "t1", "docA", ["doc:write"],
                         lifetime_s=1.5)
        ing = IngressRole(str(tmp_path), "i1", ttl_s=60.0)
        t = _ing_topic(tmp_path)
        t.append_many([
            {"kind": "auth", "doc": "docA", "client": 1,
             "tenant": "t1", "token": tok},
            _op("docA", 1, 1),
        ])
        ing.step()
        assert len(_raw(tmp_path)) == 1
        time.sleep(1.6)
        t.append_many([_op("docA", 1, 2)])
        ing.step()
        assert len(_raw(tmp_path)) == 1  # session lapsed with the token
        assert _nacks(tmp_path)[-1]["code"] == NACK_AUTH

    def test_size_caps_record_and_boxcar(self, tmp_path):
        ing = IngressRole(str(tmp_path), "i1", ttl_s=60.0,
                          max_record_bytes=64, max_boxcar_ops=2)
        _ing_topic(tmp_path).append_many([
            _op("d", 1, 1, {"pad": "x" * 100}),
            {"kind": "boxcar", "doc": "d", "client": 1, "ops": [
                {"clientSeq": i + 1, "refSeq": 0, "contents": i}
                for i in range(3)
            ]},
            {"kind": "boxcar", "doc": "d", "client": 1, "ops": [
                {"clientSeq": 1, "refSeq": 0,
                 "contents": "y" * 60}, {"clientSeq": 2, "refSeq": 0,
                                         "contents": "y" * 60},
            ]},
            _op("d", 1, 1, {"ok": 1}),
        ])
        ing.step()
        assert len(_raw(tmp_path)) == 1
        nacks = _nacks(tmp_path)
        assert [n["code"] for n in nacks] == [NACK_SIZE] * 3
        assert all(n["reason"].startswith("size:") for n in nacks)

    def test_rate_limit_token_bucket_refills(self, tmp_path):
        ing = IngressRole(str(tmp_path), "i1", ttl_s=60.0,
                          rate_limit=20.0, rate_burst=2.0)
        t = _ing_topic(tmp_path)
        t.append_many([_op("d", 1, i + 1) for i in range(4)])
        ing.step()
        assert len(_raw(tmp_path)) == 2  # burst of 2
        nacks = _nacks(tmp_path)
        assert len(nacks) == 2
        assert all(n["code"] == NACK_RATE
                   and n["reason"].startswith("rate:")
                   and n["retryAfter"] > 0 for n in nacks)
        time.sleep(0.15)  # ~3 tokens refill at 20/s
        t.append_many([_op("d", 1, 3), _op("d", 1, 4)])
        ing.step()
        assert len(_raw(tmp_path)) == 4  # the retried tail admits

    def test_backpressure_gate_closes_and_reopens(self, tmp_path):
        ing = IngressRole(str(tmp_path), "i1", ttl_s=60.0,
                          backlog_max=4, backlog_poll_s=0.0)
        deli = DeliRole(str(tmp_path), "d1", ttl_s=60.0, batch=64)
        t = _ing_topic(tmp_path)
        t.append_many([_op("hot", 1, i + 1) for i in range(10)])
        ing.step()
        raw_n = len(_raw(tmp_path))
        assert raw_n == 4  # admitted up to the budget
        nacks = _nacks(tmp_path)
        assert len(nacks) == 6
        assert all(n["code"] == NACK_RATE
                   and n["reason"].startswith("backpressure:")
                   and n["retryAfter"] > 0 for n in nacks)
        # Overload is VISIBLE: the heartbeat exports degraded.
        ing.heartbeat(force=True)
        with open(os.path.join(str(tmp_path), "hb",
                               "ingress.json")) as f:
            assert json.load(f)["degraded"] is True
        # Drain/retry rounds: the deli catches up, its checkpoint
        # advances, the gate reopens a budget's worth at a time, and
        # the retried tail eventually admits in full.
        next_cseq = 5
        for _ in range(8):
            while deli.step() > 0:
                pass
            deli.checkpoint()
            n_raw = len(_raw(tmp_path))
            if n_raw >= 10:
                break
            t.append_many([_op("hot", 1, i + 1)
                           for i in range(next_cseq - 1, 10)])
            ing.step()
            next_cseq = len(_raw(tmp_path)) + 1
        assert len(_raw(tmp_path)) == 10
        # Fully drained + one more admitted record to refresh the
        # backlog view: overload clears from the health surface.
        while deli.step() > 0:
            pass
        deli.checkpoint()
        t.append_many([_op("hot", 1, 11)])
        ing.step()
        assert len(_raw(tmp_path)) == 11
        ing.heartbeat(force=True)
        with open(os.path.join(str(tmp_path), "hb",
                               "ingress.json")) as f:
            assert json.load(f)["degraded"] is False

    def test_malformed_records_dropped_not_nacked(self, tmp_path):
        ing = IngressRole(str(tmp_path), "i1", ttl_s=60.0)
        _ing_topic(tmp_path).append_many([
            "just a string",
            {"kind": "op", "doc": "d"},  # no client
            {"kind": "op", "doc": "d", "client": "notint",
             "clientSeq": 1, "refSeq": 0, "contents": 1},
            {"kind": "unknown", "doc": "d", "client": 1},
            _op("d", 1, 1),
        ])
        ing.step()
        assert len(_raw(tmp_path)) == 1
        assert _nacks(tmp_path) == []
        assert ing._m_dropped.value == 4


# ---------------------------------------------------------------------------
# routing + exactly-once
# ---------------------------------------------------------------------------


class TestRoutingRecovery:
    def test_static_partitions_route_by_hash(self, tmp_path):
        ing = IngressRole(str(tmp_path), "i1", ttl_s=60.0,
                          n_partitions=4)
        docs = [f"doc{i}" for i in range(12)]
        _ing_topic(tmp_path).append_many(
            [_op(d, 1, 1) for d in docs]
        )
        ing.step()
        for d in docs:
            p = partition_of(d, 4)
            assert any(r["doc"] == d for r in
                       _raw(tmp_path, f"rawdeltas-p{p}"))

    def test_elastic_routing_follows_epoch(self, tmp_path):
        store = RangeLeaseStore(str(tmp_path), "test")
        topo = store.ensure_topology(1)
        rid0 = topo["ranges"][0]["rid"]
        ing = IngressRole(str(tmp_path), "i1", ttl_s=60.0,
                          n_partitions=1, elastic=True)
        t = _ing_topic(tmp_path)
        t.append_many([_op("docZ", 1, 1)])
        ing.step()
        assert len(_raw(tmp_path, f"rawdeltas-{rid0}")) == 1
        # Commit a split; the NEXT admit routes to a child range.
        assert store.commit_topology(
            split_ranges(topo, rid0), topo["epoch"]
        )
        t.append_many([_op("docZ", 1, 2)])
        ing.step()
        children = store.read_topology()["ranges"]
        hits = [e["rid"] for e in children
                if any(r["clientSeq"] == 2 for r in
                       _raw(tmp_path, e["raw"]))]
        assert len(hits) == 1

    def test_exactly_once_across_restart_no_checkpoint(self, tmp_path):
        """The widest crash window: the first incarnation never wrote
        a checkpoint — recovery must rebuild from the durable outputs
        alone, re-emitting nothing that landed, dropping nothing."""
        write_tenants(str(tmp_path), {"t1": "k1"})
        tok = {d: sign_token("k1", "t1", d, ["doc:write"])
               for d in ("a", "b", "c")}
        good = [_op(d, 1, i + 1, tenant="t1", token=tok[d])
                for i in range(4) for d in ("a", "b", "c")]
        bad = [_op("a", 9, 1, tenant="t1", token="x.y.z"),
               _op("b", 9, 1, tenant="nobody", token=tok["b"])]
        t = _ing_topic(tmp_path)
        t.append_many(good[:6] + bad)
        ing1 = IngressRole(str(tmp_path), "gen1", ttl_s=60.0,
                           n_partitions=2, ckpt_interval_s=3600.0)
        ing1.step()
        assert ing1._ckpt_dirty  # nothing checkpointed — by design
        n_nacks_1 = len(_nacks(tmp_path))
        assert n_nacks_1 == 2
        ing1.leases.release("ingress")  # crash (no final checkpoint)
        t.append_many(good[6:])
        ing2 = IngressRole(str(tmp_path), "gen2", ttl_s=60.0,
                           n_partitions=2)
        for _ in range(4):
            ing2.step()
        admitted = (_raw(tmp_path, "rawdeltas-p0")
                    + _raw(tmp_path, "rawdeltas-p1"))
        keys = [(r["doc"], r["client"], r["clientSeq"])
                for r in admitted]
        assert sorted(keys) == sorted(
            (r["doc"], r["client"], r["clientSeq"]) for r in good
        )
        assert sorted(r["inOff"] for r in admitted) == sorted(
            i for i, r in enumerate(good[:6] + bad + good[6:])
            if r["client"] != 9
        )
        # Nacks exactly once too: recovery saw them durable and
        # re-decided WITHOUT re-emitting.
        assert len(_nacks(tmp_path)) == 2

    def test_columnar_ingress_keeps_fast_path(self, tmp_path):
        """Admitted records on a columnar fabric classify as raw
        kinds (inOff via the column), not K_GENERIC."""
        ing = IngressRole(str(tmp_path), "i1", ttl_s=60.0,
                          log_format="columnar")
        _ing_topic(tmp_path, "columnar").append_many(
            [_op("d", 1, i + 1) for i in range(8)]
        )
        ing.step()
        raw = make_topic(_topic_path(str(tmp_path), "rawdeltas"),
                         "columnar")
        assert isinstance(raw, ColumnarFileTopic)
        with open(raw.path, "rb") as f:
            batch, _e, _n = rb.decode_batch(f.read())
        assert batch is not None
        assert (batch.kind == rb.K_RAW_OP).all()
        assert batch.in_off.tolist() == list(range(8))


# ---------------------------------------------------------------------------
# autoscale policy (pure decision logic)
# ---------------------------------------------------------------------------


class TestAutoscalePolicy:
    def _topo(self, *bounds):
        rs = [{"rid": f"r{i}", "lo": lo, "hi": hi, "preds": []}
              for i, (lo, hi) in enumerate(zip(bounds, bounds[1:]))]
        return {"epoch": 1, "ranges": rs}

    def test_split_needs_sustained_heat(self):
        from fluidframework_tpu.server.shard_fabric import AutoscalePolicy

        pol = AutoscalePolicy(split_rate=100.0, merge_rate=1.0,
                              sustain_s=2.0, min_interval_s=0.0)
        topo = self._topo(0, 50, 100)
        assert pol.observe(0.0, {"r0": 500.0, "r1": 0.0}, topo) is None
        assert pol.observe(1.0, {"r0": 500.0, "r1": 0.0}, topo) is None
        cmd = pol.observe(2.5, {"r0": 500.0, "r1": 0.0}, topo)
        assert cmd == {"op": "split", "rid": "r0",
                       "why": "autoscale-hot"}
        # A cooled range resets its clock: no flap.
        assert pol.observe(3.0, {"r0": 0.0, "r1": 0.0}, topo) is None

    def test_min_interval_and_max_ranges(self):
        from fluidframework_tpu.server.shard_fabric import AutoscalePolicy

        pol = AutoscalePolicy(split_rate=10.0, merge_rate=1.0,
                              sustain_s=0.0, min_interval_s=100.0,
                              max_ranges=2)
        topo = self._topo(0, 50, 100)
        assert pol.observe(0.0, {"r0": 500.0, "r1": 500.0},
                           topo) is None  # at max_ranges already
        pol.max_ranges = 4
        cmd = pol.observe(1.0, {"r0": 500.0, "r1": 500.0}, topo)
        assert cmd is not None and cmd["op"] == "split"
        # min-interval: the second hot range must wait.
        assert pol.observe(2.0, {"r0": 500.0, "r1": 500.0},
                           topo) is None

    def test_merge_adjacent_cold_pair(self):
        from fluidframework_tpu.server.shard_fabric import AutoscalePolicy

        pol = AutoscalePolicy(split_rate=100.0, merge_rate=5.0,
                              sustain_s=1.0, min_interval_s=0.0,
                              min_ranges=1)
        topo = self._topo(0, 50, 100)
        assert pol.observe(0.0, {"r0": 0.0, "r1": 0.0}, topo) is None
        cmd = pol.observe(1.5, {"r0": 0.0, "r1": 0.0}, topo)
        assert cmd == {"op": "merge", "rids": ["r0", "r1"],
                       "why": "autoscale-cold"}

    def test_hysteresis_band_is_quiet(self):
        from fluidframework_tpu.server.shard_fabric import AutoscalePolicy

        pol = AutoscalePolicy(split_rate=100.0, merge_rate=5.0,
                              sustain_s=0.0, min_interval_s=0.0)
        topo = self._topo(0, 50, 100)
        # Between the thresholds: neither hot nor cold, forever.
        for t in range(10):
            assert pol.observe(float(t), {"r0": 50.0, "r1": 50.0},
                               topo) is None

    def test_latency_trigger_marks_hottest(self):
        from fluidframework_tpu.server.shard_fabric import AutoscalePolicy

        pol = AutoscalePolicy(split_rate=1000.0, merge_rate=1.0,
                              sustain_s=0.0, min_interval_s=0.0,
                              p99_hot_ms=50.0)
        topo = self._topo(0, 50, 100)
        # Rates below split_rate, but the farm p99 is burning: the
        # hottest range splits.
        cmd = pol.observe(0.0, {"r0": 100.0, "r1": 10.0}, topo,
                          p99_ms=200.0)
        assert cmd is not None and cmd["rid"] == "r0"

    def test_rates_clamp_counter_resets(self):
        from fluidframework_tpu.server.shard_fabric import AutoscalePolicy

        pol = AutoscalePolicy(split_rate=10.0, merge_rate=1.0)
        assert pol.rates(0.0, {"r0": 100.0}) is None
        r = pol.rates(1.0, {"r0": 40.0})  # worker restart reset
        assert r == {"r0": 0.0}

    def test_merge_rate_must_sit_below_split_rate(self):
        from fluidframework_tpu.server.shard_fabric import AutoscalePolicy

        with pytest.raises(ValueError):
            AutoscalePolicy(split_rate=10.0, merge_rate=10.0)


# ---------------------------------------------------------------------------
# supervised farm end to end
# ---------------------------------------------------------------------------


class TestSupervisedFrontDoor:
    def test_classic_farm_with_ingress_role(self, tmp_path):
        """ServiceSupervisor(ingress=True): submits cross the front
        door into the classic four-role farm; the unauthorized one is
        nacked, the valid ones sequence end to end."""
        from fluidframework_tpu.server.supervisor import (
            PIPELINE_ROLES,
            ServiceSupervisor,
        )

        d = str(tmp_path)
        write_tenants(d, {"t1": "k1"})
        tok = sign_token("k1", "t1", "docA", ["doc:write"])
        sup = ServiceSupervisor(
            d, roles=PIPELINE_ROLES, ingress=True, ttl_s=0.75,
        ).start()
        try:
            assert sup.roles[0] == "ingress"
            t = _ing_topic(tmp_path)
            t.append_many(
                [{"kind": "join", "doc": "docA", "client": 1,
                  "tenant": "t1", "token": tok}]
                + [_op("docA", 1, i + 1, tenant="t1", token=tok)
                   for i in range(5)]
                + [_op("docA", 7, 1, tenant="t1", token="bad.tok.en")]
            )
            durable = SharedFileTopic(
                os.path.join(d, "topics", "durable.jsonl")
            )
            deadline = time.time() + 60
            ops = []
            while time.time() < deadline:
                sup.poll_once()
                ops = [r for r in durable.read_from(0)
                       if isinstance(r, dict) and r.get("kind") == "op"
                       and r.get("type") == "op"]
                if len(ops) >= 5 and _nacks(tmp_path):
                    break
                time.sleep(0.02)
        finally:
            sup.stop()
        assert len(ops) == 5 and all(o["client"] == 1 for o in ops)
        nacks = _nacks(tmp_path)
        assert len(nacks) == 1 and nacks[0]["client"] == 7
        assert verify_nack("k1", nacks[0])
        h = sup.health()
        assert "ingress" in h["roles"]

    def test_farm_read_server_pushes_nacks(self, tmp_path):
        """The socket layer tails the nacks topic: a subscribed
        session receives its doc's rejections as `nacks` pushes."""
        import socket

        from fluidframework_tpu.server.framing import (
            read_frame,
            write_frame,
        )
        from fluidframework_tpu.server.socket_service import (
            FarmReadServer,
        )

        d = str(tmp_path)
        os.makedirs(os.path.join(d, "topics"), exist_ok=True)
        srv = FarmReadServer(d, nacks=True).start()
        try:
            conn = socket.create_connection((srv.host, srv.port))
            f = conn.makefile("rwb")
            write_frame(f, {"id": 1, "cmd": "subscribe",
                            "docId": "docA"})
            f.flush()
            assert read_frame(f)["result"]["docId"] == "docA"
            nacks_topic = make_topic(
                os.path.join(d, "topics", "nacks.jsonl")
            )
            nacks_topic.append_many([
                {"kind": "nack", "doc": "docA", "client": 5,
                 "clientSeq": 1, "code": 429,
                 "reason": "backpressure: hot", "inOff": 3,
                 "retryAfter": 0.25},
            ])
            conn.settimeout(10)
            push = read_frame(f)
            assert push["event"] == "nacks"
            assert push["recs"][0]["code"] == 429
            conn.close()
        finally:
            srv.stop()
