"""Cross-process collaboration over the TCP socket boundary.

The round-1 gap: every client↔server "boundary" was a Python call in
one interpreter. Here the ordering service runs in a SEPARATE PROCESS
(tools/socket_server_main.py) and containers reach it only through
drivers.socket_driver — the reference's socket.io boundary shape
(documentDeltaConnection.ts:42 / alfred index.ts:211).
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

import pytest

from fluidframework_tpu.dds import MapFactory, StringFactory
from fluidframework_tpu.drivers.socket_driver import SocketDriver
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.runtime import ChannelRegistry

REGISTRY = ChannelRegistry([MapFactory(), StringFactory()])
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def wait_until(cond, timeout=10.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


@pytest.fixture()
def server_process():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "socket_server_main.py"),
         "--allow-anonymous"],
        stdout=subprocess.PIPE, text=True, env=env, cwd=REPO,
    )
    line = proc.stdout.readline().strip()
    assert line.startswith("LISTENING"), line
    _, host, port = line.split()
    yield host, int(port)
    proc.terminate()
    proc.wait(timeout=10)


def make_container(host, port, doc=None):
    loader = Loader(SocketDriver(host, port), REGISTRY)
    if doc is None:
        c = loader.create_detached()
        ds = c.runtime.create_datastore("default")
        ds.create_channel("s", StringFactory.type_name)
        ds.create_channel("m", MapFactory.type_name)
        return loader, c
    return loader, loader.resolve(doc)


def chan(c, cid="s"):
    return c.runtime.get_datastore("default").get_channel(cid)


def test_cross_process_convergence(server_process):
    host, port = server_process
    loader, c1 = make_container(host, port)
    chan(c1).insert_text(0, "hello across processes")
    doc = c1.attach()

    _, c2 = make_container(host, port, doc)
    assert chan(c2).get_text() == "hello across processes"

    chan(c2).insert_text(0, ">> ")
    c2.flush()
    assert wait_until(
        lambda: chan(c1).get_text() == ">> hello across processes"
    ), chan(c1).get_text()

    chan(c1, "m").set("k", {"nested": [1, 2, 3]})
    c1.flush()
    assert wait_until(lambda: chan(c2, "m").get("k") == {"nested": [1, 2, 3]})
    assert not c1.is_dirty and not c2.is_dirty


def test_third_process_editor(server_process):
    """A THIRD process edits the document and exits; both local
    containers observe its edit through the pipeline."""
    host, port = server_process
    loader, c1 = make_container(host, port)
    chan(c1).insert_text(0, "base")
    doc = c1.attach()

    editor = (
        "import sys; sys.path.insert(0, %r)\n"
        "from fluidframework_tpu.dds import MapFactory, StringFactory\n"
        "from fluidframework_tpu.drivers.socket_driver import SocketDriver\n"
        "from fluidframework_tpu.loader import Loader\n"
        "from fluidframework_tpu.runtime import ChannelRegistry\n"
        "reg = ChannelRegistry([MapFactory(), StringFactory()])\n"
        "loader = Loader(SocketDriver(%r, %d), reg)\n"
        "c = loader.resolve(%r)\n"
        "s = c.runtime.get_datastore('default').get_channel('s')\n"
        "s.insert_text(4, ' edited-elsewhere')\n"
        "c.flush()\n"
        "c.disconnect()\n"
    ) % (REPO, host, port, doc)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    subprocess.run(
        [sys.executable, "-c", editor], check=True, env=env, cwd=REPO,
        timeout=60,
    )
    assert wait_until(
        lambda: chan(c1).get_text() == "base edited-elsewhere"
    ), chan(c1).get_text()


def test_socket_disconnect_propagates(server_process):
    host, port = server_process
    loader, c1 = make_container(host, port)
    doc = c1.attach()
    events = []
    c1.on("disconnected", lambda: events.append(1))
    # Kill the transport from the client side; the runtime must see it.
    import socket as _socket

    c1.runtime.connection._sock.shutdown(_socket.SHUT_RDWR)
    assert wait_until(lambda: not c1.connected)
    assert events
    # Reconnect and keep working.
    c1.connect()
    chan(c1).insert_text(0, "after reconnect ")
    c1.flush()
    _, c2 = make_container(host, port, doc)
    assert "after reconnect" in chan(c2).get_text()


def test_socket_blobs(server_process):
    host, port = server_process
    loader, c1 = make_container(host, port)
    doc = c1.attach()
    handle = c1.create_blob(b"cross-process blob \x00\x01" * 100)
    chan(c1, "m").set("file", handle)
    c1.flush()
    _, c2 = make_container(host, port, doc)
    assert wait_until(lambda: chan(c2, "m").get("file") is not None)
    assert c2.get_blob(chan(c2, "m").get("file")) == (
        b"cross-process blob \x00\x01" * 100
    )


def test_rpc_from_event_callback_does_not_deadlock(server_process):
    """ADVICE r2 (high): an RPC issued from inside an op/nack callback
    used to wedge forever — callbacks ran on the socket READER thread,
    the only thread that can deliver RPC responses. Events now dispatch
    from a separate thread, so a callback-issued _call completes."""
    host, port = server_process
    from fluidframework_tpu.drivers.socket_driver import _SocketConnection

    a = _SocketConnection(host, port, "dead-doc", None)
    b = _SocketConnection(host, port, "dead-doc", None)
    results = []

    def on_op(msg):
        # catch_up is a blocking RPC on the same connection.
        results.append(len(a.catch_up(0)))

    a.listener = on_op
    from fluidframework_tpu.protocol.messages import DocumentMessage, MessageType

    b.submit(DocumentMessage(client_seq=1, ref_seq=0, type=MessageType.OP,
                             contents={"k": 1}))
    assert wait_until(lambda: results), "callback RPC deadlocked"
    assert results[0] >= 1

    # disconnect() issued from inside a callback must also complete.
    done = []

    def on_op2(msg):
        a.disconnect()
        done.append(1)

    a.listener = on_op2
    b.submit(DocumentMessage(client_seq=2, ref_seq=0, type=MessageType.OP,
                             contents={"k": 2}))
    assert wait_until(lambda: done), "disconnect from callback deadlocked"
    b.disconnect()


# ---------------------------------------------------------------------------
# Auth/tenancy (the riddler role + alfred token gate, riddler/
# tenantManager.ts, alfred/index.ts:595)
# ---------------------------------------------------------------------------

TENANT, KEY = "acme", "s3cret-key"


@pytest.fixture()
def secure_server():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "socket_server_main.py"),
         "--tenant", f"{TENANT}:{KEY}"],
        stdout=subprocess.PIPE, text=True, env=env, cwd=REPO,
    )
    line = proc.stdout.readline().strip()
    assert line.startswith("LISTENING"), line
    _, host, port = line.split()
    yield host, int(port)
    proc.terminate()
    proc.wait(timeout=10)


def _token(doc, scopes=None, key=KEY, tenant=TENANT, lifetime=3600.0):
    from fluidframework_tpu.server.riddler import (
        SCOPE_READ, SCOPE_WRITE, sign_token,
    )

    return sign_token(
        key, tenant, doc,
        scopes if scopes is not None else [SCOPE_READ, SCOPE_WRITE],
        lifetime_s=lifetime,
    )


def test_unauthenticated_connect_refused(secure_server):
    host, port = secure_server
    loader = Loader(SocketDriver(host, port), REGISTRY)
    c = loader.create_detached()
    c.runtime.create_datastore("default").create_channel(
        "s", StringFactory.type_name
    )
    with pytest.raises(RuntimeError, match="missing tenant credentials"):
        c.attach(doc_id="doc1")


def test_authenticated_flow_and_token_binding(secure_server):
    host, port = secure_server
    doc = "doc-auth"
    drv = SocketDriver(host, port, tenant_id=TENANT, token=_token(doc))
    loader = Loader(drv, REGISTRY)
    c = loader.create_detached()
    c.runtime.create_datastore("default").create_channel(
        "s", StringFactory.type_name
    )
    c.attach(doc_id=doc)
    chan(c).insert_text(0, "hi")
    c.runtime.flush()

    # Second client with its own valid token converges.
    drv2 = SocketDriver(host, port, tenant_id=TENANT, token=_token(doc))
    l2 = Loader(drv2, REGISTRY)
    c2 = l2.resolve(doc)
    assert wait_until(lambda: chan(c2).get_text() == "hi")

    # A token bound to ANOTHER document is refused.
    bad = SocketDriver(host, port, tenant_id=TENANT,
                       token=_token("other-doc"))
    with pytest.raises(RuntimeError, match="token document mismatch"):
        bad.load_document(doc)
    # Wrong signing key is refused.
    forged = SocketDriver(host, port, tenant_id=TENANT,
                          token=_token(doc, key="wrong-key"))
    with pytest.raises(RuntimeError, match="bad token signature"):
        forged.load_document(doc)
    # Unknown tenant is refused.
    ghost = SocketDriver(host, port, tenant_id="ghost",
                         token=_token(doc, tenant="ghost"))
    with pytest.raises(RuntimeError, match="unknown tenant"):
        ghost.load_document(doc)
    # Expired token is refused.
    stale = SocketDriver(host, port, tenant_id=TENANT,
                         token=_token(doc, lifetime=-5.0))
    with pytest.raises(RuntimeError, match="token expired"):
        stale.load_document(doc)


def test_read_scope_cannot_write(secure_server):
    from fluidframework_tpu.server.riddler import SCOPE_READ

    host, port = secure_server
    doc = "doc-ro"
    rw = SocketDriver(host, port, tenant_id=TENANT, token=_token(doc))
    loader = Loader(rw, REGISTRY)
    c = loader.create_detached()
    c.runtime.create_datastore("default").create_channel(
        "s", StringFactory.type_name
    )
    c.attach(doc_id=doc)

    ro = SocketDriver(host, port, tenant_id=TENANT,
                      token=_token(doc, scopes=[SCOPE_READ]))
    # Reads work...
    assert ro.load_document(doc) is not None
    assert ro.ops_from(doc, 0) is not None
    # ...writes are refused (connect is a write: it joins the quorum).
    with pytest.raises(RuntimeError, match="doc:write required"):
        ro.connect(doc)
    with pytest.raises(RuntimeError, match="doc:write required"):
        ro.upload_blob(doc, b"x")


def test_malformed_token_signature_raises_auth_error():
    """A token whose signature segment is not valid base64 must raise
    AuthError (the documented auth-nack contract), never a bare
    binascii/ValueError."""
    import pytest

    from fluidframework_tpu.server.riddler import (
        AuthError,
        TenantManager,
    )

    reg = TenantManager()
    reg.create_tenant("acme")
    with pytest.raises(AuthError):
        reg.validate_token("e30.e30.!!!not-base64!!!", "acme")
    with pytest.raises(AuthError):
        reg.validate_token("a.b", "acme")
    # Signed-but-malformed payloads are auth failures too.
    import base64 as _b64
    import hashlib as _hashlib
    import hmac as _hmac
    import json as _json

    def _signed(payload_obj):
        key = reg.get_key("acme")
        head = _b64.urlsafe_b64encode(b"{}").decode().rstrip("=")
        body = _b64.urlsafe_b64encode(
            _json.dumps(payload_obj).encode()
        ).decode().rstrip("=")
        sig = _b64.urlsafe_b64encode(_hmac.new(
            key.encode(), f"{head}.{body}".encode(), _hashlib.sha256
        ).digest()).decode().rstrip("=")
        return f"{head}.{body}.{sig}"

    with pytest.raises(AuthError):
        reg.validate_token(_signed([1, 2]), "acme")  # non-object claims
    with pytest.raises(AuthError):
        reg.validate_token(
            _signed({"tenantId": "acme", "exp": "never"}), "acme"
        )  # non-numeric expiry


def test_socket_server_secure_by_default():
    """Constructing a TCP front door without tenants and without the
    explicit allow_anonymous opt-out must refuse (alfred validates
    tokens unconditionally — open mode cannot happen by accident)."""
    import pytest as _pytest

    from fluidframework_tpu.server import LocalServer
    from fluidframework_tpu.server.socket_service import SocketDeltaServer

    with _pytest.raises(ValueError, match="secure by default"):
        SocketDeltaServer(LocalServer(), port=0)


def test_tpu_client_token_provider_over_secure_server(secure_server):
    """The public client path end-to-end over a SECURE server: a
    TpuClient with an InsecureTokenProvider creates, attaches, and
    loads containers over TCP with per-document credentials — and the
    same client WITHOUT credentials is refused."""
    from fluidframework_tpu.dds import MapFactory
    from fluidframework_tpu.framework.fluid_static import (
        ContainerSchema,
        InsecureTokenProvider,
        TpuClient,
    )

    host, port = secure_server
    schema = ContainerSchema({"kv": MapFactory.type_name})
    provider = InsecureTokenProvider(TENANT, KEY)
    client = TpuClient(
        SocketDriver(host, port), token_provider=provider
    )
    c = client.create_container(schema)
    kv = c.initial_objects["kv"]
    kv.set("who", "authorized")
    doc = c.attach()
    c.flush()
    time.sleep(0.3)

    c2 = TpuClient(
        SocketDriver(host, port), token_provider=provider
    ).get_container(doc, schema)
    assert c2.initial_objects["kv"].get("who") == "authorized"

    # No credentials -> refused at the front door.
    bare = TpuClient(SocketDriver(host, port))
    with pytest.raises(RuntimeError, match="missing tenant credentials"):
        bare.get_container(doc, schema)


def test_socket_connection_gap_refetch_and_dup_drop(server_process):
    """The live-stream continuity guard on the delta connection: a
    duplicated push is dropped, and a push that jumps past a hole is
    preceded by a ranged refetch (ops_from(from, to) over the same
    socket) so the listener always sees a contiguous stream."""
    from fluidframework_tpu.drivers.file_driver import message_to_json

    host, port = server_process
    loader, c1 = make_container(host, port)
    chan(c1).insert_text(0, "base")
    doc = c1.attach()
    c1.flush()

    drv = SocketDriver(host, port)
    conn = drv.connect(doc)
    got = []
    conn.listener = got.append
    for ch_ in "xyz":
        chan(c1).insert_text(0, ch_)
        c1.flush()
    assert wait_until(lambda: len(got) >= 3)
    delivered = [m.sequence_number for m in got]
    assert delivered == sorted(delivered)
    base_seq = conn.last_seq

    # Duplicated delivery: re-pushing the last op must be dropped.
    dup_wire = message_to_json(got[-1])
    before = len(got)
    conn._deliver(dup_wire, got.append)
    assert len(got) == before and conn.dup_drops >= 1

    # Delayed/lost frames: roll the guard back to simulate pushes the
    # edge never delivered, then push the HEAD op — the guard must
    # refetch the hole from the server before delivering it.
    hole_from = delivered[0] - 1  # everything after the first live op
    conn.last_seq = hole_from
    conn.gap_refetches = 0
    head_wire = message_to_json(got[-1])
    replay = []
    conn._deliver(head_wire, replay.append)
    assert conn.gap_refetches == 1
    seqs = [m.sequence_number for m in replay]
    assert seqs == list(range(hole_from + 1, base_seq + 1)), seqs
    conn.disconnect()


def test_cached_driver_token_provider_over_secure_server(
    secure_server, tmp_path
):
    """Satellite (ADVICE.md low): a CachedDriver-wrapped SocketDriver
    must DELEGATE token_provider assignment to the wrapped driver —
    before the fix the assignment landed on the wrapper and every
    request went out unauthenticated against a secure server. E2E:
    create + reload through the cache tier with per-document
    credentials, and verify the provider reached the inner driver."""
    from fluidframework_tpu.dds import MapFactory
    from fluidframework_tpu.drivers.web_cache import CachedDriver
    from fluidframework_tpu.framework.fluid_static import (
        ContainerSchema,
        InsecureTokenProvider,
        TpuClient,
    )

    host, port = secure_server
    schema = ContainerSchema({"kv": MapFactory.type_name})
    provider = InsecureTokenProvider(TENANT, KEY)

    cached = CachedDriver(SocketDriver(host, port), str(tmp_path))
    client = TpuClient(cached, token_provider=provider)
    # The provider must live on the INNER driver, not the wrapper.
    assert cached.inner.token_provider is provider
    assert "token_provider" not in vars(cached)
    c = client.create_container(schema)
    c.initial_objects["kv"].set("who", "cached+authorized")
    doc = c.attach()
    c.flush()
    time.sleep(0.3)

    # Second boot through a fresh cache-wrapped driver: summary load is
    # authenticated, then cached; the cached reload still works.
    cached2 = CachedDriver(SocketDriver(host, port), str(tmp_path))
    c2 = TpuClient(cached2, token_provider=provider).get_container(
        doc, schema
    )
    assert c2.initial_objects["kv"].get("who") == "cached+authorized"
    assert cached2.misses >= 1  # first load: authenticated fetch, cached

    # Third boot from the same cache dir: snapshot load is a local hit
    # (no service summary fetch), yet the live connection still
    # authenticates per document through the delegated provider.
    cached3 = CachedDriver(SocketDriver(host, port), str(tmp_path))
    c3 = TpuClient(cached3, token_provider=provider).get_container(
        doc, schema
    )
    assert c3.initial_objects["kv"].get("who") == "cached+authorized"
    assert cached3.hits >= 1

    # A cache-wrapped driver WITHOUT credentials is still refused —
    # the wrapper must not mask the auth failure either.
    bare = TpuClient(CachedDriver(SocketDriver(host, port),
                                  str(tmp_path / "bare")))
    with pytest.raises(RuntimeError, match="missing tenant credentials"):
        bare.create_container(schema).attach()

    # The fault-injection wrapper delegates the seam the same way — a
    # doubly-wrapped Cached(FaultInjection(Socket)) stack still lands
    # the provider on the innermost driver.
    from fluidframework_tpu.drivers import FaultInjectionDriver

    fi = FaultInjectionDriver(SocketDriver(host, port))
    stack = CachedDriver(fi, str(tmp_path / "stacked"))
    TpuClient(stack, token_provider=provider)
    assert fi.inner.token_provider is provider
    assert "token_provider" not in vars(fi)
