"""Gates for the lagged-refSeq synthetic stream (the honest headline
workload): real per-client perspective lag, cross-engine convergence,
and the passive-replica settled-segment packing that keeps the
generator's view oracle O(window).

Reference analog: mergeTreeOperationRunner.ts interleaves clients that
have not seen each other's ops; every engine must resolve those ops at
their lagging perspectives and still converge.
"""

import numpy as np
import pytest

from fluidframework_tpu.core.mergetree import replay_passive
from fluidframework_tpu.native import load_hostmerge

pytestmark = pytest.mark.skipif(
    load_hostmerge() is None,
    reason="lagged generator needs the native hostmerge engine",
)
from fluidframework_tpu.testing.digest import state_digest
from fluidframework_tpu.testing.synthetic import (
    generate_lagged_stream,
    generate_stream,
)

N_OPS = 2000
N_CLIENTS = 64
WINDOW = 256
SEED = 3


@pytest.fixture(scope="module")
def lagged_stream():
    return generate_lagged_stream(
        N_OPS, n_clients=N_CLIENTS, seed=SEED, window=WINDOW,
        initial_len=32,
    )


@pytest.fixture(scope="module")
def oracle_digest(lagged_stream):
    eng = replay_passive(
        lagged_stream.as_messages(),
        initial="".join(map(chr, lagged_stream.text[:32])),
    )
    return state_digest(eng.annotated_spans())


def test_stream_has_real_lag(lagged_stream):
    s = lagged_stream
    lag = s.seq - 1 - s.ref_seq
    assert np.all(lag >= 0)
    assert np.all(s.ref_seq >= s.min_seq)
    lagged_frac = np.mean(lag > 0)
    assert lagged_frac > 0.4, f"only {lagged_frac:.0%} ops lag"
    assert np.max(lag) >= WINDOW // 2
    # Per-client refSeq is non-decreasing (a client cannot unsee ops).
    for c in range(1, N_CLIENTS + 1):
        refs = s.ref_seq[s.client == c]
        assert np.all(np.diff(refs) >= 0)


def test_lag_exercises_concurrency(lagged_stream):
    """Ops must routinely resolve against state containing concurrent
    (unseen) inserts — the partialLengths.ts:256 workload."""
    s = lagged_stream
    ins_seqs = s.seq[s.op_type == 0]
    concurrent = 0
    for i in np.nonzero(s.seq - 1 - s.ref_seq > 0)[0][:500]:
        lo, hi = s.ref_seq[i], s.seq[i]
        if np.any((ins_seqs > lo) & (ins_seqs < hi)):
            concurrent += 1
    assert concurrent > 300


def test_overlay_numpy_matches_oracle(lagged_stream, oracle_digest):
    from fluidframework_tpu.ops.overlay_ref import OverlayMessageReplica

    rep = OverlayMessageReplica(
        initial="".join(map(chr, lagged_stream.text[:32])),
        fold_interval=64, n_removers=16,
    )
    rep.apply_messages(list(lagged_stream.as_messages()))
    assert rep.doc.error == 0
    assert state_digest(rep.annotated_spans()) == oracle_digest


def test_overlay_pallas_matches_oracle(lagged_stream, oracle_digest):
    from fluidframework_tpu.core.overlay_replay import (
        OverlayKernelMessageReplica,
    )

    rep = OverlayKernelMessageReplica(
        initial="".join(map(chr, lagged_stream.text[:32])),
        chunk_size=64, window=1024, n_removers=16, interpret=True,
    )
    rep.apply_messages(list(lagged_stream.as_messages()))
    rep.check_errors()
    assert state_digest(rep.annotated_spans()) == oracle_digest


def test_native_engine_matches_oracle(lagged_stream, oracle_digest):
    from fluidframework_tpu.core.native_engine import NativeMergeEngine

    eng = NativeMergeEngine(local_client_id=-3)
    eng.load("".join(map(chr, lagged_stream.text[:32])))
    for msg in lagged_stream.as_messages():
        eng.apply_sequenced(msg)
    assert state_digest(eng.annotated_spans()) == oracle_digest


def test_pack_settled_preserves_state(lagged_stream, oracle_digest):
    """hm_pack_settled (the generator's O(window) guarantee) must not
    change visible document state."""
    from fluidframework_tpu.core.native_engine import NativeMergeEngine

    eng = NativeMergeEngine(local_client_id=-3)
    eng.load("".join(map(chr, lagged_stream.text[:32])))
    for i, msg in enumerate(lagged_stream.as_messages()):
        eng.apply_sequenced(msg)
        if i % 97 == 0:
            eng.pack_settled()
            eng.verify_invariants()
    eng.pack_settled()
    assert state_digest(eng.annotated_spans()) == oracle_digest


def test_cache_roundtrip(tmp_path):
    a = generate_lagged_stream(
        300, n_clients=16, seed=11, window=64, initial_len=16,
        cache_dir=str(tmp_path),
    )
    b = generate_lagged_stream(
        300, n_clients=16, seed=11, window=64, initial_len=16,
        cache_dir=str(tmp_path),
    )
    for f in a.__dataclass_fields__:
        assert np.array_equal(getattr(a, f), getattr(b, f)), f


def test_lagged_defaults_match_headline_params():
    """The headline bench shape (1024 clients) generates cleanly."""
    s = generate_lagged_stream(3000, seed=7, initial_len=64)
    t = generate_stream(3000, seed=7, initial_len=64)
    # Same op-mix machinery: types drawn from the same weights.
    assert abs(
        np.mean(s.op_type == 0) - np.mean(t.op_type == 0)
    ) < 0.05
