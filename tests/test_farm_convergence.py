"""Seeded multi-client fuzz farms: replica convergence.

The oracle-level equivalent of the reference's conflict farm
(packages/dds/merge-tree/src/test/client.conflictFarm.spec.ts).
"""

import pytest

from fluidframework_tpu.testing.farm import FarmConfig, run_sharedstring_farm


@pytest.mark.parametrize("seed", range(8))
def test_conflict_farm_small(seed):
    run_sharedstring_farm(
        FarmConfig(num_clients=3, rounds=10, ops_per_client_per_round=3, seed=seed)
    )


@pytest.mark.parametrize("seed", range(4))
def test_conflict_farm_more_clients(seed):
    run_sharedstring_farm(
        FarmConfig(
            num_clients=8,
            rounds=8,
            ops_per_client_per_round=4,
            seed=1000 + seed,
        )
    )


def test_conflict_farm_insert_heavy():
    run_sharedstring_farm(
        FarmConfig(
            num_clients=5,
            rounds=12,
            ops_per_client_per_round=5,
            seed=42,
            insert_weight=0.8,
            remove_weight=0.1,
            annotate_weight=0.1,
            initial_text="",
        )
    )


def test_conflict_farm_remove_heavy():
    run_sharedstring_farm(
        FarmConfig(
            num_clients=4,
            rounds=12,
            ops_per_client_per_round=4,
            seed=7,
            insert_weight=0.35,
            remove_weight=0.55,
            annotate_weight=0.10,
            initial_text="the quick brown fox jumps over the lazy dog",
        )
    )
