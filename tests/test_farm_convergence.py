"""Seeded multi-client fuzz farms: replica convergence.

The oracle-level equivalent of the reference's conflict farm
(packages/dds/merge-tree/src/test/client.conflictFarm.spec.ts).
"""

import pytest

from fluidframework_tpu.testing.farm import FarmConfig, run_sharedstring_farm


@pytest.mark.parametrize("seed", range(8))
def test_conflict_farm_small(seed):
    run_sharedstring_farm(
        FarmConfig(num_clients=3, rounds=10, ops_per_client_per_round=3, seed=seed)
    )


@pytest.mark.parametrize("seed", range(4))
def test_conflict_farm_more_clients(seed):
    run_sharedstring_farm(
        FarmConfig(
            num_clients=8,
            rounds=8,
            ops_per_client_per_round=4,
            seed=1000 + seed,
        )
    )


def test_conflict_farm_insert_heavy():
    run_sharedstring_farm(
        FarmConfig(
            num_clients=5,
            rounds=12,
            ops_per_client_per_round=5,
            seed=42,
            insert_weight=0.8,
            remove_weight=0.1,
            annotate_weight=0.1,
            initial_text="",
        )
    )


def test_conflict_farm_remove_heavy():
    run_sharedstring_farm(
        FarmConfig(
            num_clients=4,
            rounds=12,
            ops_per_client_per_round=4,
            seed=7,
            insert_weight=0.35,
            remove_weight=0.55,
            annotate_weight=0.10,
            initial_text="the quick brown fox jumps over the lazy dog",
        )
    )


# ------------------------------------------------------- scaled matrices

@pytest.mark.parametrize("seed", range(3))
def test_farm_16_clients_hundreds_of_rounds(seed):
    """The reference's conflict-farm scale (client.conflictFarm.spec.ts
    runs up to 32 clients x hundreds of rounds): 16 clients, 150
    rounds, with the exhaustive invariant verifier sampling every 25
    rounds (partialLengths.ts:336 verifier role)."""
    run_sharedstring_farm(
        FarmConfig(
            num_clients=16,
            rounds=150,
            ops_per_client_per_round=2,
            seed=100 + seed,
            verify_invariants_every=25,
        )
    )


def test_farm_invariant_verifier_catches_corruption():
    """The verifier must actually detect broken state."""
    from fluidframework_tpu.core.mergetree import CollabClient

    c = CollabClient(1, initial="hello", engine="python")
    c.engine.segments[0].removed_clients.append(9)  # remover w/o removal
    with pytest.raises(AssertionError):
        c.engine.verify_invariants()


@pytest.mark.parametrize("seed", range(4))
def test_stash_resume_farm(seed):
    """Container-level farm with random close/stash/resume cycles
    (the applyStashedOpFarm shape, client.applyStashedOpFarm.spec.ts)."""
    import random as _random

    from fluidframework_tpu.dds import MapFactory, StringFactory
    from fluidframework_tpu.drivers import LocalDriver
    from fluidframework_tpu.loader import Loader
    from fluidframework_tpu.runtime import ChannelRegistry
    from fluidframework_tpu.server import LocalServer

    rng = _random.Random(seed)
    registry = ChannelRegistry([MapFactory(), StringFactory()])
    loader = Loader(LocalDriver(LocalServer()), registry)
    c0 = loader.create_detached()
    ds = c0.runtime.create_datastore("default")
    ds.create_channel("s", StringFactory.type_name)
    c0.runtime.get_datastore("default").get_channel("s").insert_text(0, "seed")
    doc = c0.attach()
    containers = [c0] + [loader.resolve(doc) for _ in range(2)]

    def s(c):
        return c.runtime.get_datastore("default").get_channel("s")

    for _ in range(10):
        for i, c in enumerate(list(containers)):
            n = len(s(c).get_text())
            for _ in range(rng.randint(0, 2)):
                r = rng.random()
                if r < 0.6 or n == 0:
                    s(c).insert_text(rng.randint(0, n), rng.choice("xyz"))
                    n += 1
                else:
                    k = rng.randint(0, n - 1)
                    s(c).remove_range(k, k + 1)
                    n -= 1
            if rng.random() < 0.3:
                # Close with pending state; resume as a new session.
                state = c.close_and_get_pending_state()
                containers[i] = loader.resolve(doc, pending_state=state)
            else:
                c.flush()
        for c in containers:
            c.flush()
    texts = {s(c).get_text() for c in containers}
    assert len(texts) == 1, f"divergence (seed {seed}): {texts}"


@pytest.mark.parametrize("seed", range(4))
def test_rollback_farm(seed):
    """Random orderSequentially aborts interleaved with normal edits
    (the rollbackFarm shape, client.rollbackFarm.spec.ts): aborted
    work must leave no trace and replicas must converge."""
    import random as _random

    from fluidframework_tpu.dds import MapFactory, StringFactory
    from fluidframework_tpu.runtime import ChannelRegistry
    from fluidframework_tpu.testing.mocks import MultiClientHarness

    rng = _random.Random(seed)
    registry = ChannelRegistry([MapFactory(), StringFactory()])
    h = MultiClientHarness(
        3, registry,
        channel_types=[("m", MapFactory.type_name),
                       ("s", StringFactory.type_name)],
    )

    def m(i):
        return h.runtimes[i].get_datastore("default").get_channel("m")

    def s(i):
        return h.runtimes[i].get_datastore("default").get_channel("s")

    def random_string_op(i):
        ch = s(i)
        n = len(ch.get_text())
        r = rng.random()
        if r < 0.5 or n == 0:
            ch.insert_text(rng.randint(0, n), rng.choice("abcdef") * 2)
        elif r < 0.8:
            a = rng.randrange(n)
            ch.remove_range(a, min(n, a + rng.randint(1, 3)))
        else:
            a = rng.randrange(n)
            ch.annotate_range(a, min(n, a + rng.randint(1, 3)),
                              {"mark": rng.randint(0, 9)})

    for rnd in range(20):
        for i in range(3):
            if rng.random() < 0.35:
                try:
                    def tx(i=i, rnd=rnd):
                        # Mixed map + STRING work, all aborted: the
                        # string ops roll back through the merge-tree
                        # rollback path (mergeTree.ts:2057).
                        m(i).set(f"tx{rnd}", i)
                        s(i).insert_text(0, "ROLLEDBACK")
                        random_string_op(i)
                        m(i).delete(f"k{rng.randint(0, 5)}")
                        raise RuntimeError("abort")
                    h.runtimes[i].order_sequentially(tx)
                except RuntimeError:
                    pass
            m(i).set(f"k{rng.randint(0, 5)}", rng.randint(0, 99))
            random_string_op(i)
        h.process_all()
    views = [
        {k: m(i).get(k) for k in sorted(m(i).keys())} for i in range(3)
    ]
    assert views[0] == views[1] == views[2]
    assert not any(k.startswith("tx") for k in views[0])
    texts = {s(i).get_text() for i in range(3)}
    assert len(texts) == 1, texts
    assert "ROLLEDBACK" not in texts.pop()
    from fluidframework_tpu.testing.farm import char_spans

    spans = [char_spans(s(i).engine.annotated_spans()) for i in range(3)]
    assert spans[0] == spans[1] == spans[2]
