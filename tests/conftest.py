"""Test configuration.

Forces JAX onto the host CPU platform with 8 virtual devices so
multi-chip sharding tests (jax.sharding.Mesh over documents/sequence
axes) compile and run without TPU hardware, per the project's multi-chip
validation strategy. Must run before the first `import jax` anywhere in
the test process.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# The environment's site customization may register an accelerator
# plugin and override jax_platforms at interpreter start; the env var
# alone is then ignored. Re-assert CPU before any backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
