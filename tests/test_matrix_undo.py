"""SharedMatrix undo/conflict machinery (the productSet/bspSet role,
packages/dds/matrix/src/{productSet,bspSet}.ts): set-cell undo with
prior values, axis insert/remove undo with cell payload restoration,
all addressed by stable handles so undo survives CONCURRENT row/col
permutation from other clients."""

import random

import pytest

from fluidframework_tpu.dds import MatrixFactory
from fluidframework_tpu.framework.undo_redo import (
    SharedMatrixUndoRedoHandler,
    UndoRedoStackManager,
)
from fluidframework_tpu.runtime import ChannelRegistry
from fluidframework_tpu.testing.mocks import MultiClientHarness


def make(n=2):
    h = MultiClientHarness(
        n, ChannelRegistry([MatrixFactory()]),
        channel_types=[("mx", MatrixFactory.type_name)],
    )
    return h, [
        h.runtimes[i].get_datastore("default").get_channel("mx")
        for i in range(n)
    ]


def test_set_cell_undo_redo_basic():
    h, (a, b) = make()
    a.insert_rows(0, 2)
    a.insert_cols(0, 2)
    h.process_all()
    stack = UndoRedoStackManager()
    SharedMatrixUndoRedoHandler(stack, a)
    a.set_cell(0, 0, "x")
    stack.close_current_operation()
    a.set_cell(0, 0, "y")
    stack.close_current_operation()
    h.process_all()
    assert b.get_cell(0, 0) == "y"
    stack.undo_operation()
    h.process_all()
    assert a.get_cell(0, 0) == "x" and b.get_cell(0, 0) == "x"
    stack.undo_operation()
    h.process_all()
    assert a.get_cell(0, 0) is None and b.get_cell(0, 0) is None
    stack.redo_operation()
    h.process_all()
    assert b.get_cell(0, 0) == "x"


def test_undo_survives_concurrent_permutation():
    """Client A sets a cell; client B concurrently inserts rows/cols
    BEFORE it (shifting positions). A's undo still hits the right
    cell (handle addressing)."""
    h, (a, b) = make()
    a.insert_rows(0, 3)
    a.insert_cols(0, 3)
    h.process_all()
    stack = UndoRedoStackManager()
    SharedMatrixUndoRedoHandler(stack, a)
    a.set_cell(1, 1, "target")
    stack.close_current_operation()
    h.process_all()
    # Concurrent permutation: the target cell shifts to (3, 2).
    b.insert_rows(0, 2)
    b.insert_cols(0, 1)
    h.process_all()
    assert a.get_cell(3, 2) == "target"
    stack.undo_operation()
    h.process_all()
    assert a.get_cell(3, 2) is None and b.get_cell(3, 2) is None


def test_axis_insert_undo_removes_rows():
    h, (a, b) = make()
    a.insert_rows(0, 2)
    a.insert_cols(0, 2)
    h.process_all()
    stack = UndoRedoStackManager()
    SharedMatrixUndoRedoHandler(stack, a)
    a.insert_rows(1, 2)
    stack.close_current_operation()
    a.set_cell(1, 0, "in-new-row")
    stack.close_current_operation()
    h.process_all()
    assert a.row_count == 4
    stack.undo_operation()  # undo the set
    stack.undo_operation()  # undo the insert: rows disappear
    h.process_all()
    assert a.row_count == 2 and b.row_count == 2
    assert a.to_dense() == b.to_dense()


def test_axis_remove_undo_restores_cells():
    h, (a, b) = make()
    a.insert_rows(0, 3)
    a.insert_cols(0, 2)
    for r in range(3):
        for c in range(2):
            a.set_cell(r, c, f"{r}.{c}")
    h.process_all()
    stack = UndoRedoStackManager()
    SharedMatrixUndoRedoHandler(stack, a)
    a.remove_rows(1, 1)
    stack.close_current_operation()
    h.process_all()
    assert a.row_count == 2
    stack.undo_operation()
    h.process_all()
    assert a.row_count == 3 and b.row_count == 3
    assert a.to_dense() == b.to_dense()
    assert a.get_cell(1, 0) == "1.0" and b.get_cell(1, 1) == "1.1"


@pytest.mark.parametrize("seed", range(4))
def test_matrix_undo_concurrent_farm(seed):
    """The verdict's gate: matrix undo survives concurrent row/col
    insert + setCell farms — random mixed edits on both clients, with
    client A undoing a random subset of its operations, and replicas
    always converging."""
    rng = random.Random(seed)
    h, (a, b) = make()
    a.insert_rows(0, 4)
    a.insert_cols(0, 4)
    h.process_all()
    stack = UndoRedoStackManager()
    SharedMatrixUndoRedoHandler(stack, a)

    def random_edit(mx, undoable):
        r = rng.random()
        if r < 0.55 and mx.row_count and mx.col_count:
            mx.set_cell(rng.randrange(mx.row_count),
                        rng.randrange(mx.col_count), rng.randint(0, 99))
        elif r < 0.7:
            mx.insert_rows(rng.randint(0, mx.row_count), 1)
        elif r < 0.85:
            mx.insert_cols(rng.randint(0, mx.col_count), 1)
        elif mx.row_count > 1:
            mx.remove_rows(rng.randrange(mx.row_count), 1)
        if undoable:
            stack.close_current_operation()

    for rnd in range(12):
        for _ in range(2):
            random_edit(a, undoable=True)
        for _ in range(2):
            random_edit(b, undoable=False)
        h.process_all()
        while rng.random() < 0.4 and stack.undo_stack_size:
            stack.undo_operation()
            h.process_all()
        assert a.to_dense() == b.to_dense(), f"seed {seed} round {rnd}"
