"""GC tests: handle discovery, mark, unreferenced tracking, sweep
(reference packages/runtime/container-runtime/src/gc + the standalone
garbage-collector package).
"""

from __future__ import annotations

from fluidframework_tpu.dds import MapFactory
from fluidframework_tpu.runtime import ChannelRegistry
from fluidframework_tpu.runtime.gc import (
    GarbageCollector,
    find_handles,
    make_handle,
    run_garbage_collection,
)
from fluidframework_tpu.testing.mocks import MultiClientHarness

REGISTRY = ChannelRegistry([MapFactory()])


def test_find_handles_nested():
    v = {
        "a": make_handle("/x"),
        "b": [1, {"c": make_handle("/y/z")}],
        "d": "not a handle",
    }
    assert sorted(find_handles(v)) == ["/x", "/y/z"]


def test_run_garbage_collection_marks():
    graph = {
        "/root": ["/a"],
        "/a": ["/b"],
        "/b": [],
        "/orphan": ["/a"],  # unreferenced, even though it refs /a
    }
    ref, unref = run_garbage_collection(graph, ["/root"])
    assert ref == {"/root", "/a", "/b"}
    assert unref == {"/orphan"}


def make_rt():
    h = MultiClientHarness(1, REGISTRY, channel_types=[("root-map", MapFactory.type_name)])
    return h, h.runtimes[0]


def test_gc_lifecycle_mark_revive_sweep():
    h, rt = make_rt()
    root_map = h.channel(0, "root-map")

    # A non-root datastore is alive only via handles.
    aux = rt.create_datastore("aux", root=False)
    aux_map = aux.create_channel("data", MapFactory.type_name)
    aux.attach_all()
    root_map.set("ref", aux_map.handle)
    h.process_all()

    gc = GarbageCollector(rt, sweep_grace=2)
    ref, unref = gc.collect()
    assert "/aux" in ref and "/aux/data" in ref
    assert not unref

    # Drop the reference: aux becomes unreferenced (tracked, not yet swept).
    root_map.delete("ref")
    h.process_all()
    ref, unref = gc.collect()
    assert "/aux" in unref and "/aux/data" in unref
    since = gc.unreferenced_since["/aux"]

    # Revive before the grace expires.
    root_map.set("ref", aux.handle)
    h.process_all()
    ref, unref = gc.collect()
    assert "/aux" in ref
    assert "/aux" not in gc.unreferenced_since

    # Drop again and let the grace window pass.
    root_map.delete("ref")
    h.process_all()
    gc.collect()
    assert gc.sweep() == []  # grace not yet elapsed
    for i in range(3):
        root_map.set(f"tick{i}", i)
    h.process_all()
    deleted = gc.sweep()
    assert "/aux" in deleted and "/aux/data" in deleted
    assert "aux" not in rt.datastores


def test_gc_state_roundtrip():
    h, rt = make_rt()
    aux = rt.create_datastore("aux", root=False)
    aux.create_channel("data", MapFactory.type_name)
    aux.attach_all()
    gc = GarbageCollector(rt)
    gc.collect()
    assert "/aux" in gc.unreferenced_since
    gc2 = GarbageCollector(rt)
    gc2.load_state(gc.state())
    assert gc2.unreferenced_since == gc.unreferenced_since
