"""Smoke-run every example (the reference ships examples/ apps; these
are the user-facing end-to-end surfaces)."""

import os
import subprocess
import sys

import pytest

EXAMPLES = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"
)


@pytest.mark.parametrize(
    "name", ["collab_text.py", "todo_app.py", "tpu_replay.py"]
)
def test_example_runs(name):
    env = dict(os.environ, JAX_PLATFORMS="cpu", REPLAY_OPS="800")
    res = subprocess.run(
        [sys.executable, os.path.join(EXAMPLES, name)],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert res.returncode == 0, res.stderr[-2000:]
    assert res.stdout.strip()
