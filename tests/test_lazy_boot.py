"""Lazy/partial summary boot: the RemoteChannelContext /
snapshotV1.ts:31-37 contract — a container boots and catches up
reading only per-channel attribute headers; channel bodies (e.g. a
large merge-tree's segment chunks) parse on FIRST ACCESS, and ops for
unrealized channels queue until then."""

import pytest

from fluidframework_tpu.dds import MapFactory, MatrixFactory, StringFactory
from fluidframework_tpu.runtime import ChannelRegistry, ContainerRuntime
from fluidframework_tpu.runtime.summary import SummaryTree
from fluidframework_tpu.testing.mocks import MultiClientHarness


def registry():
    return ChannelRegistry([MapFactory(), StringFactory(), MatrixFactory()])


@pytest.fixture(scope="module")
def big_doc():
    """A summarized session with a LARGE string body + map + matrix,
    and a recorded post-summary op tail."""
    h = MultiClientHarness(
        2, registry(),
        channel_types=[
            ("text", StringFactory.type_name),
            ("kv", MapFactory.type_name),
            ("grid", MatrixFactory.type_name),
        ],
    )
    ds = h.runtimes[0].get_datastore("default")
    text, kv = ds.get_channel("text"), ds.get_channel("kv")
    # ~60k chars in many segments (multiple 10k body chunks).
    for i in range(60):
        text.insert_text(0, f"chunk-{i:03d}-" + "x" * 1000)
    kv.set("k", 1)
    h.process_all()
    wire = h.runtimes[0].summarize().to_json()
    seq0 = h.runtimes[0].current_seq
    text.insert_text(0, "HEAD:")
    kv.set("k", 2)
    h.process_all()
    from fluidframework_tpu.drivers.file_driver import message_to_json

    tail = [message_to_json(m) for m in h.service.ops_from("doc", seq0)]
    return wire, tail, text.get_text(), h


def test_boot_realizes_nothing_and_queues_tail(big_doc):
    wire, tail, want_text, _ = big_doc
    from fluidframework_tpu.drivers.file_driver import message_from_json

    rt = ContainerRuntime(registry())
    rt.load(SummaryTree.from_json(wire))
    ds = rt.get_datastore("default")
    assert ds.realized_channels == []  # O(header) boot
    # Catch-up: the tail routes without materializing any channel.
    for row in tail:
        rt.process(message_from_json(row))
    assert ds.realized_channels == []
    # First read realizes ONLY the touched channel and replays its
    # queued tail ops.
    assert ds.get_channel("text").get_text() == want_text
    assert ds.realized_channels == ["text"]
    assert ds.get_channel("kv").get("k") == 2
    assert ds.realized_channels == ["kv", "text"]
    assert ds.has_channel("grid")
    assert "grid" not in ds.realized_channels


def test_boot_touches_only_header_bytes(big_doc, monkeypatch):
    """The large string body is never flattened/parsed at boot or
    during catch-up — only on first read (the 'touches O(header)
    bytes' contract)."""
    wire, tail, _, _ = big_doc
    from fluidframework_tpu.drivers.file_driver import message_from_json

    flattened = []
    orig = SummaryTree.flatten

    def spy(self):
        out = orig(self)
        flattened.append(sum(len(str(v)) for v in out.values()))
        return out

    monkeypatch.setattr(SummaryTree, "flatten", spy)
    rt = ContainerRuntime(registry())
    rt.load(SummaryTree.from_json(wire))
    for row in tail:
        rt.process(message_from_json(row))
    assert flattened == []  # zero body bytes touched by boot+catch-up
    rt.get_datastore("default").get_channel("kv")
    assert len(flattened) == 1 and flattened[0] < 2000  # kv only


def test_summarize_without_realizing(big_doc):
    """A freshly booted (all-lazy) runtime can summarize by reusing
    the loaded subtrees verbatim, and the result boots correctly."""
    wire, tail, want_text, _ = big_doc
    from fluidframework_tpu.drivers.file_driver import message_from_json

    rt = ContainerRuntime(registry())
    rt.load(SummaryTree.from_json(wire))
    ds = rt.get_datastore("default")
    rewire = rt.summarize().to_json()
    assert ds.realized_channels == []  # summarize stayed lazy
    rt2 = ContainerRuntime(registry())
    rt2.load(SummaryTree.from_json(rewire))
    for row in tail:
        rt2.process(message_from_json(row))
    assert (
        rt2.get_datastore("default").get_channel("text").get_text()
        == want_text
    )
