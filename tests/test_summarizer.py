"""Summary service (server/summarizer.py): the merge-tree summarizer
role, snapshot catch-up, and the exactly-once/no-fork contracts.

The core claim under test: **summary(seq=k) + tail replay is
bit-identical to full replay** (document-state digests), for seeded
workloads across engines (merge-tree kernel fold vs generic ops form)
and both log formats, including restarts mid-stream and a torn
manifest append — and restarts can never fork a summary (the canonical
serialized form is a pure function of the op prefix, so re-emitted
blobs are byte- and handle-identical)."""

from __future__ import annotations

import json
import os
import time

import pytest

from fluidframework_tpu.protocol.mergetree_ops import op_to_json
from fluidframework_tpu.server.columnar_log import (
    make_tail_reader,
    make_topic,
)
from fluidframework_tpu.server.summarizer import (
    SummarizerRole,
    SummaryIndex,
    SummaryReplica,
    open_summary_store,
    read_catchup,
)
from fluidframework_tpu.testing.deli_bench import build_mergetree_stream
from fluidframework_tpu.testing.farm import FarmConfig, run_sharedstring_farm


def wire_records(doc, stream):
    """Farm SequencedMessages -> deltas-topic wire records."""
    recs = []
    for m in stream:
        contents = m.contents
        if hasattr(contents, "__dataclass_fields__"):
            contents = op_to_json(contents)
        recs.append({
            "kind": "op", "doc": doc, "seq": m.sequence_number,
            "msn": m.minimum_sequence_number, "client": m.client_id,
            "clientSeq": m.client_seq, "refSeq": m.ref_seq,
            "type": m.type.value, "contents": contents,
        })
    return recs


def farm_records(doc="doc0", seed=7, rounds=10):
    res = run_sharedstring_farm(FarmConfig(
        num_clients=3, rounds=rounds, ops_per_client_per_round=4,
        seed=seed, multi_key_annotates=True, initial_text="",
    ))
    return wire_records(doc, res.stream), res.final_text


def generic_records(doc, n_ops=60, n_clients=3, seed=1):
    """Sequenced records with opaque contents (the ops-form engine)."""
    import random

    rng = random.Random(seed)
    recs = []
    seq = 0
    for c in range(1, n_clients + 1):
        seq += 1
        recs.append({"kind": "op", "doc": doc, "seq": seq, "msn": 0,
                     "client": c, "clientSeq": 0, "refSeq": seq - 1,
                     "type": "join", "contents": c})
    cseq = {c: 0 for c in range(1, n_clients + 1)}
    for i in range(n_ops):
        c = rng.randint(1, n_clients)
        seq += 1
        cseq[c] += 1
        recs.append({"kind": "op", "doc": doc, "seq": seq,
                     "msn": max(0, seq - 8), "client": c,
                     "clientSeq": cseq[c], "refSeq": seq - 1,
                     "type": "op",
                     "contents": {"v": rng.randint(0, 999), "i": i}})
    return recs


def drive_direct(shared, records, summary_ops=32, log_format="json",
                 batch=512, append_first=True):
    """Run the role datapath (no lease loop) to quiescence — the
    `run_pipeline` pattern."""
    deltas = make_topic(
        os.path.join(shared, "topics", "deltas.jsonl"), log_format
    )
    if append_first:
        deltas.append_many(records)
    role = SummarizerRole(shared, owner="direct", ttl_s=3600.0,
                          log_format=log_format,
                          summary_ops=summary_ops)
    role.fence = 1
    reader = make_tail_reader(deltas)
    while True:
        entries = reader.poll(batch)
        if not entries:
            break
        out = []
        for li, rec in entries:
            role.process(li, rec, out)
        role.flush_batch(out)
        if out:
            role.out_topic.append_many(out, fence=1, owner="direct")
        role.offset = reader.next_line
    return role


def run_stepped(shared, summary_ops=16, owner="g1", max_steps=500,
                until_offset=None, log_format="json", **kw):
    """Run the role through the REAL `step()` machinery (lease, fenced
    append, checkpoint, recovery) until the input is drained or
    `max_steps` pass."""
    role = SummarizerRole(shared, owner=owner, ttl_s=2.0, batch=64,
                          ckpt_interval_s=0.0, log_format=log_format,
                          summary_ops=summary_ops, **kw)
    for _ in range(max_steps):
        role.step(idle_sleep=0.01)
        if until_offset is not None and role.offset >= until_offset:
            break
    return role


def manifests_of(shared, log_format="json", name="summaries"):
    topic = make_topic(
        os.path.join(shared, "topics", f"{name}.jsonl"), log_format
    )
    return [r for r in topic.read_from(0)
            if isinstance(r, dict) and r.get("kind") == "summary"]


def assert_all_boots_equal(shared, doc, records, log_format="json"):
    """EVERY manifest's summary + tail must equal the cold replay."""
    store = open_summary_store(shared)
    cold = SummaryReplica(None)
    cold.apply_records(records)
    idx = SummaryIndex(shared, log_format)
    idx.poll()
    mans = idx.manifests.get(doc, [])
    assert mans, "no summaries emitted"
    for m in mans:
        blob = json.loads(store.get(m["handle"]).decode())
        rep = SummaryReplica(blob)
        rep.apply_records([r for r in records if r["seq"] > m["seq"]])
        assert rep.state_digest() == cold.state_digest(), (
            f"boot from summary seq={m['seq']} diverges"
        )
    return mans, cold


# ---------------------------------------------------------------------------
# differential: summary + tail == full replay
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("log_format", ["json", "columnar"])
def test_mergetree_summary_tail_equals_full_replay(tmp_path, log_format):
    records, final_text = farm_records()
    drive_direct(str(tmp_path), records, summary_ops=32,
                 log_format=log_format)
    mans, cold = assert_all_boots_equal(
        str(tmp_path), "doc0", records, log_format
    )
    assert all(m["form"] == "mergetree" for m in mans)
    assert cold.get_text() == final_text
    # read_catchup end-to-end: nearest summary + tail off the topic.
    cu = read_catchup(str(tmp_path), "doc0", log_format,
                      store=open_summary_store(str(tmp_path)))
    rep = SummaryReplica(cu["blob"])
    rep.apply_records(cu["ops"])
    assert rep.state_digest() == cold.state_digest()
    assert rep.get_text() == final_text
    # The tail is the post-summary suffix, not the log.
    assert len(cu["ops"]) < len(records) / 2


@pytest.mark.parametrize("seed", [3, 11])
def test_mergetree_differential_seeded(tmp_path, seed):
    records, _ = farm_records(seed=seed, rounds=8)
    drive_direct(str(tmp_path), records, summary_ops=24)
    assert_all_boots_equal(str(tmp_path), "doc0", records)


def test_ops_form_generic_docs(tmp_path):
    records = generic_records("gdoc", n_ops=70)
    drive_direct(str(tmp_path), records, summary_ops=20)
    mans, cold = assert_all_boots_equal(str(tmp_path), "gdoc", records)
    assert all(m["form"] == "ops" for m in mans)
    # Expected deterministic cadence count.
    assert len(mans) == len(records) // 20


def test_synthetic_stream_differential(tmp_path):
    """The bench generator's stream shape (trailing msn window,
    bounded doc) through the same gate."""
    records = build_mergetree_stream(600, n_clients=3, seed=4)
    drive_direct(str(tmp_path), records, summary_ops=128)
    assert_all_boots_equal(str(tmp_path), "doc0", records)


def test_stacked_multi_doc_fold(tmp_path):
    """Several docs triggering in one pump fold through ONE vmapped
    kernel dispatch (`apply_op_batch_docs_jit`) — and stay correct."""
    per_doc = {}
    interleaved = []
    streams = {}
    for d, seed in enumerate([5, 6, 7]):
        recs, _ = farm_records(doc=f"d{d}", seed=seed, rounds=6)
        streams[f"d{d}"] = recs
        per_doc[f"d{d}"] = recs
    # Round-robin interleave so all docs trigger inside one big pump.
    iters = [list(v) for v in per_doc.values()]
    while any(iters):
        for it in iters:
            if it:
                interleaved.append(it.pop(0))
    role = drive_direct(str(tmp_path), interleaved, summary_ops=24,
                        batch=100_000)
    assert role._m_stacked.value > 0, "stacked fold path never ran"
    for doc, recs in streams.items():
        assert_all_boots_equal(str(tmp_path), doc, recs)


# ---------------------------------------------------------------------------
# restarts: exactly-once, no fork, torn manifests
# ---------------------------------------------------------------------------


def test_restart_mid_stream_reemits_identical_summaries(tmp_path):
    """A summarizer killed mid-stream and restarted (fresh owner,
    fenced checkpoint + inOff recovery) must produce the EXACT manifest
    sequence of an uninterrupted run — same seqs, same byte-identical
    content-addressed handles, no duplicates."""
    records, _ = farm_records(seed=9, rounds=8)
    # Uninterrupted reference run.
    ref_dir = str(tmp_path / "ref")
    os.makedirs(ref_dir)
    drive_direct(ref_dir, records, summary_ops=16)
    ref = [(m["doc"], m["seq"], m["handle"])
           for m in manifests_of(ref_dir)]
    assert ref

    # Interrupted run: first life consumes ~half through step(), dies
    # (abandoned, lease expires), successor finishes.
    cut_dir = str(tmp_path / "cut")
    os.makedirs(os.path.join(cut_dir, "topics"))
    make_topic(os.path.join(cut_dir, "topics", "deltas.jsonl"),
               "json").append_many(records)
    half = len(records) // 2
    run_stepped(cut_dir, summary_ops=16, owner="g1",
                until_offset=half)
    time.sleep(2.2)  # the dead owner's lease must expire
    run_stepped(cut_dir, summary_ops=16, owner="g2",
                until_offset=len(records))
    got = [(m["doc"], m["seq"], m["handle"])
           for m in manifests_of(cut_dir)]
    assert got == ref, "restart forked or duplicated summaries"
    assert_all_boots_equal(cut_dir, "doc0", records)


def test_torn_manifest_append_reemitted(tmp_path):
    """A crash that clips the manifest append (torn tail) leaves the
    torn summary invisible; recovery re-emits exactly the missing
    manifest — no duplicate, byte-identical."""
    records, _ = farm_records(seed=13, rounds=8)
    shared = str(tmp_path)
    os.makedirs(os.path.join(shared, "topics"))
    make_topic(os.path.join(shared, "topics", "deltas.jsonl"),
               "json").append_many(records)
    run_stepped(shared, summary_ops=16, owner="g1",
                until_offset=len(records))
    full = manifests_of(shared)
    assert len(full) >= 2
    # Clip the LAST manifest line off the summaries topic (a writer
    # that died mid-append; the torn-tail rules make it invisible).
    path = os.path.join(shared, "topics", "summaries.jsonl")
    with open(path, "rb") as f:
        data = f.read()
    cut = data[:-1].rfind(b"\n") + 1
    with open(path, "wb") as f:
        f.write(data[:cut + 3])  # leave a torn, newline-less remnant
    assert len(manifests_of(shared)) == len(full) - 1
    # ALSO roll the checkpoint back before the clipped manifest's
    # trigger, so recovery actually re-processes it (a checkpoint at
    # the head would just resume past the gap).
    from fluidframework_tpu.server.queue import FencedCheckpointStore

    ck = FencedCheckpointStore(os.path.join(shared, "checkpoints"))
    env = ck.load("summarizer")
    prev_off = full[-2]["off"] + 1  # state as of the second-last one
    # Rebuild the state deterministically: a fresh role replays from
    # scratch up to prev_off (cheaper: just drop the checkpoint — the
    # successor replays the whole topic silently).
    assert env is not None
    os.remove(os.path.join(shared, "checkpoints",
                           "summarizer.ckpt.json"))
    del prev_off
    time.sleep(2.2)  # lease expiry
    run_stepped(shared, summary_ops=16, owner="g2",
                until_offset=len(records))
    after = manifests_of(shared)
    assert [(m["doc"], m["seq"], m["handle"]) for m in after] == \
        [(m["doc"], m["seq"], m["handle"]) for m in full]
    assert_all_boots_equal(shared, "doc0", records)


def test_freeze_on_undecodable_op(tmp_path):
    """A merge-tree doc hitting an undecodable op FREEZES its
    summaries (no new manifests, loud metric) instead of emitting a
    wrong one; earlier summaries still boot."""
    records, _ = farm_records(seed=21, rounds=8)
    bad_at = 40
    poisoned = list(records[:bad_at])
    last = records[bad_at - 1]
    poisoned.append({**last, "seq": last["seq"] + 1,
                     "contents": {"type": 42, "weird": True}})
    for r in records[bad_at:]:
        poisoned.append({**r, "seq": r["seq"] + 1})
    role = drive_direct(str(tmp_path), poisoned, summary_ops=16)
    mans = manifests_of(str(tmp_path))
    assert mans and all(m["seq"] <= bad_at for m in mans)
    assert role._m_frozen.value == 1
    # The pre-freeze summary still boots against its own-era tail.
    store = open_summary_store(str(tmp_path))
    blob = json.loads(store.get(mans[-1]["handle"]).decode())
    rep = SummaryReplica(blob)
    ok_tail = [r for r in records
               if mans[-1]["seq"] < r["seq"] <= bad_at]
    rep.apply_records(ok_tail)
    cold = SummaryReplica(None)
    cold.apply_records(records[:bad_at])
    assert rep.state_digest() == cold.state_digest()


# ---------------------------------------------------------------------------
# index / reader semantics
# ---------------------------------------------------------------------------


def test_summary_index_nearest(tmp_path):
    topic = make_topic(
        os.path.join(str(tmp_path), "topics", "summaries.jsonl"), "json"
    )
    topic.append_many([
        {"kind": "summary", "doc": "a", "seq": s, "msn": 0, "count": s,
         "form": "ops", "handle": f"h{s}", "bytes": 1, "off": s,
         "inOff": s}
        for s in (10, 20, 30)
    ])
    idx = SummaryIndex(str(tmp_path))
    idx.poll()
    assert idx.nearest("a")["seq"] == 30
    assert idx.nearest("a", 25)["seq"] == 20
    assert idx.nearest("a", 10)["seq"] == 10
    assert idx.nearest("a", 9) is None
    assert idx.nearest("b") is None
    # Incremental: a later manifest appears on the next poll.
    topic.append({"kind": "summary", "doc": "a", "seq": 40, "msn": 0,
                  "count": 40, "form": "ops", "handle": "h40",
                  "bytes": 1, "off": 40, "inOff": 40})
    idx.poll()
    assert idx.nearest("a")["seq"] == 40


# ---------------------------------------------------------------------------
# kernel-deli wire tracing (PR 9 follow-up b)
# ---------------------------------------------------------------------------


def test_kernel_deli_trace_parity(tmp_path, monkeypatch):
    """With FLUID_TRACE_WIRE on, the kernel deli's records carry the
    same span structure as the scalar role's — tr.stamp on every op,
    tr.sub threaded from the ingress record — with identical canonical
    streams and identical submit_to_stamp observation counts."""
    from fluidframework_tpu.server.deli_kernel import KernelDeliRole
    from fluidframework_tpu.server.queue import SharedFileTopic
    from fluidframework_tpu.server.supervisor import (
        DeliRole,
        canonical_record,
    )
    from fluidframework_tpu.utils import metrics as M

    monkeypatch.setenv("FLUID_TRACE_WIRE", "1")
    now = time.time()
    raws = []
    for c in (1, 2):
        raws.append({"kind": "join", "doc": "d", "client": c})
    for i in range(1, 6):
        for c in (1, 2):
            raws.append({"kind": "op", "doc": "d", "client": c,
                         "clientSeq": i, "refSeq": 0,
                         "contents": {"i": i}, "tr_sub": now})
    raws.append({"kind": "boxcar", "doc": "d", "client": 1,
                 "ops": [{"clientSeq": 6, "refSeq": 0, "contents": 1},
                         {"clientSeq": 7, "refSeq": 0, "contents": 2}],
                 "tr_sub": now})

    outs = {}
    counts = {}
    for impl, cls in (("scalar", DeliRole), ("kernel", KernelDeliRole)):
        d = str(tmp_path / impl)
        os.makedirs(os.path.join(d, "topics"), exist_ok=True)
        SharedFileTopic(
            os.path.join(d, "topics", "rawdeltas.jsonl")
        ).append_many(raws)
        reg = M.MetricsRegistry()
        prev = M.set_registry(reg)
        try:
            role = cls(d, owner=impl, ttl_s=3600.0)
        finally:
            M.set_registry(prev)
        assert role.trace_wire
        role.fence = 1
        out = []
        for li, rec in enumerate(raws):
            role.process(li, rec, out)
        role.flush_batch(out)
        outs[impl] = out
        counts[impl] = reg.histogram(
            "op_stage_ms", stage="submit_to_stamp"
        ).count

    canon = [canonical_record(r) for r in outs["scalar"]]
    assert canon == [canonical_record(r) for r in outs["kernel"]]
    assert counts["scalar"] == counts["kernel"] > 0
    for rec in outs["kernel"]:
        if rec.get("kind") != "op":
            continue
        tr = rec.get("tr")
        assert isinstance(tr, dict) and "stamp" in tr
        if rec["type"] == "op":
            assert tr["sub"] == now and tr["sub"] <= tr["stamp"]


# ---------------------------------------------------------------------------
# farm + fabric integration
# ---------------------------------------------------------------------------


def test_supervised_farm_emits_summaries(tmp_path):
    """The five-role supervised farm end to end: raw records in,
    summary manifests out (the summarizer as a ROLES member)."""
    from fluidframework_tpu.server.queue import SharedFileTopic
    from fluidframework_tpu.server.supervisor import (
        ROLES,
        ServiceSupervisor,
    )

    assert "summarizer" in ROLES
    shared = str(tmp_path)
    sup = ServiceSupervisor(shared, ttl_s=0.75, summary_ops=8).start()
    try:
        raw = SharedFileTopic(
            os.path.join(shared, "topics", "rawdeltas.jsonl")
        )
        recs = generic_records("fdoc", n_ops=30, n_clients=2)
        # Re-shape into raw ingress records (strip seq stamps).
        ingress = []
        for r in recs:
            if r["type"] == "join":
                ingress.append({"kind": "join", "doc": "fdoc",
                                "client": r["client"]})
            elif r["type"] == "op":
                ingress.append({"kind": "op", "doc": "fdoc",
                                "client": r["client"],
                                "clientSeq": r["clientSeq"],
                                "refSeq": 0,
                                "contents": r["contents"]})
        raw.append_many(ingress)
        deadline = time.time() + 90
        mans = []
        while time.time() < deadline:
            sup.poll_once()
            mans = manifests_of(shared)
            if len(mans) >= len(ingress) // 8:
                break
            time.sleep(0.05)
        assert len(mans) >= len(ingress) // 8
    finally:
        sup.stop()
    # Boot-equivalence against the farm's own deltas stream.
    deltas = make_topic(os.path.join(shared, "topics", "deltas.jsonl"),
                        "json")
    ops = [r for r in deltas.read_from(0)
           if isinstance(r, dict) and r.get("kind") == "op"]
    cu = read_catchup(shared, "fdoc", "json",
                      store=open_summary_store(shared))
    boot = SummaryReplica(cu["blob"])
    boot.apply_records(cu["ops"])
    cold = SummaryReplica(None)
    cold.apply_records(ops)
    assert boot.state_digest() == cold.state_digest()


def test_shard_worker_per_partition_summarizer(tmp_path):
    """The static fabric seam: ShardWorker(summarize=True) runs one
    summarizer per owned partition (deltas-p{k} → summaries-p{k});
    SummaryIndex(partitions=N) merges the manifest topics."""
    from fluidframework_tpu.server.queue import record_partition
    from fluidframework_tpu.server.shard_fabric import (
        ShardRouter,
        ShardWorker,
        spread_doc_names,
    )

    shared = str(tmp_path)
    n_p = 2
    docs = spread_doc_names(2, n_p)
    router = ShardRouter(shared, n_p, "json")
    worker = ShardWorker(shared, "w0", n_partitions=n_p, ttl_s=5.0,
                         summarize=True, summary_ops=8,
                         ckpt_interval_s=0.0)
    workload = []
    for doc in docs:
        for c in (1, 2):
            workload.append({"kind": "join", "doc": doc, "client": c})
        for i in range(1, 16):
            for c in (1, 2):
                workload.append({
                    "kind": "op", "doc": doc, "client": c,
                    "clientSeq": i, "refSeq": 0, "contents": {"i": i},
                })
    router.append(workload)
    per_doc = 2 + 2 * 15
    expected = 2 * (per_doc // 8)
    deadline = time.time() + 60
    while time.time() < deadline:
        worker.step()
        total = sum(
            len(manifests_of(shared, name=f"summaries-p{k}"))
            for k in range(n_p)
        )
        if total >= expected:
            break
        time.sleep(0.01)
    worker.stop()
    assert total >= expected
    idx = SummaryIndex(shared, partitions=n_p)
    idx.poll()
    store = open_summary_store(shared)
    for doc in docs:
        k = record_partition({"doc": doc}, n_p)
        cu = read_catchup(shared, doc, "json", index=idx, store=store,
                          deltas_topic=f"deltas-p{k}")
        assert cu["manifest"] is not None
        boot = SummaryReplica(cu["blob"])
        boot.apply_records(cu["ops"])
        deltas = make_topic(
            os.path.join(shared, "topics", f"deltas-p{k}.jsonl"), "json"
        )
        cold = SummaryReplica(None)
        cold.apply_records([
            r for r in deltas.read_from(0)
            if isinstance(r, dict) and r.get("kind") == "op"
            and r.get("doc") == doc
        ])
        assert boot.state_digest() == cold.state_digest()


def test_elastic_summarize_accepted(tmp_path):
    """REGRESSION for the retained absorb path: `summarize=True` on
    the ELASTIC fabric used to be a loud ValueError ("static-partition
    only") — the elastic summarizer now absorbs predecessor ranges'
    fold state, so the old rejection can no longer be raised and a
    ranged summarizer role is actually constructed per owned range."""
    from fluidframework_tpu.server.shard_fabric import ShardWorker
    from fluidframework_tpu.server.summarizer import SummarizerRole

    w = ShardWorker(str(tmp_path), "w0", n_partitions=2, elastic=True,
                    summarize=True, ttl_s=3600.0)
    try:
        w.sweep()
        assert w.summ_roles, "elastic worker built no summarizer roles"
        for rid, role in w.summ_roles.items():
            assert isinstance(role, SummarizerRole)
            assert role.rid == rid  # ranged identity, not partitioned
            assert role.in_topic_name == f"deltas-{rid}"
            assert role.out_topic_name == f"summaries-{rid}"
    finally:
        w.stop()


def test_elastic_summarizer_absorbs_across_live_split(tmp_path):
    """The absorb path itself: a live split mid-stream hands each
    range's summarizer state to the successors (seed from the parent's
    final fold checkpoint sliced by hash range, fence-bound pred
    manifest topics, exactly-once manifest re-emission) — and every
    doc's newest summary + tail boots bit-identical to a cold replay
    of the merged stream."""
    import time as _time

    from fluidframework_tpu.server.shard_fabric import (
        ShardRouter,
        ShardWorker,
        control_result,
        request_topology_change,
    )
    from fluidframework_tpu.server.summarizer import SummaryIndex

    d = str(tmp_path)
    w = ShardWorker(d, "w0", n_partitions=1, elastic=True,
                    summarize=True, ttl_s=5.0, summary_ops=8)
    w.sweep()
    router = ShardRouter(d, 1, elastic=True)
    docs = [f"doc{i}" for i in range(4)]
    recs = [{"kind": "join", "doc": doc, "client": 1} for doc in docs]
    for i in range(40):
        for doc in docs:
            recs.append({"kind": "op", "doc": doc, "client": 1,
                         "clientSeq": i + 1, "refSeq": 0,
                         "contents": {"i": i}})
    half = len(recs) // 2
    try:
        router.append(recs[:half])
        for _ in range(8):
            w.step()
        rid = list(w.roles)[0]
        cid = request_topology_change(d, {"op": "split", "rid": rid})
        deadline = _time.time() + 30
        while control_result(d, cid) is None and _time.time() < deadline:
            w.step()
            _time.sleep(0.02)
        assert control_result(d, cid), "split never committed"
        router.append(recs[half:])
        for _ in range(40):
            w.step()
        idx = SummaryIndex(
            d, topics=router.stage_topic_names("summaries")
        )
        idx.poll()
        store = open_summary_store(d)
        all_ops = [r for r in router.merged_reader("deltas").poll()
                   if isinstance(r, dict) and r.get("kind") == "op"]
        for doc in docs:
            man = idx.nearest(doc)
            assert man is not None, f"no manifest for {doc}"
            blob = json.loads(store.get(man["handle"]).decode())
            boot = SummaryReplica(blob)
            boot.apply_records(sorted(
                (r for r in all_ops
                 if r["doc"] == doc and r["seq"] > man["seq"]),
                key=lambda r: r["seq"],
            ))
            cold = SummaryReplica(None)
            cold.apply_records(sorted(
                (r for r in all_ops if r["doc"] == doc),
                key=lambda r: r["seq"],
            ))
            assert boot.state_digest() == cold.state_digest(), (
                f"elastic summary boot diverged for {doc}"
            )
    finally:
        w.stop()


# ---------------------------------------------------------------------------
# chaos: summarizer kill never forks a summary
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_chaos_summarizer_kill_never_forks(tmp_path):
    """The acceptance gate: SIGKILL the whole farm (summarizer
    included) mid-stream; the run must converge bit-identical with
    zero dup/skip AND summary integrity — deterministic manifest
    count, one handle per (doc, seq), summary + tail == cold replay."""
    from fluidframework_tpu.testing.chaos import ChaosConfig, run_chaos

    res = run_chaos(ChaosConfig(
        seed=5, faults=("kill",), n_docs=2, n_clients=2,
        ops_per_client=23, timeout_s=240.0,
        summarizer=True, summary_ops=12,
        shared_dir=str(tmp_path),
    ))
    assert res.converged, res.detail
    assert res.summaries_ok
    assert res.summary_manifests > 0
    assert res.duplicate_seqs == 0 and res.skipped_seqs == 0
    assert res.restarts.get("summarizer", 0) >= 1


def test_chaos_summarizer_sharded_rejected():
    from fluidframework_tpu.testing.chaos import ChaosConfig, run_chaos

    with pytest.raises(ValueError, match="single-partition"):
        run_chaos(ChaosConfig(summarizer=True, n_partitions=2,
                              faults=("kill",)))


# ---------------------------------------------------------------------------
# cross-impl: identical summaries whatever deli produced the stream
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("log_format", ["json", "columnar"])
def test_summaries_identical_across_deli_impls(tmp_path, log_format):
    """Raw merge-tree submissions through the SCALAR and the KERNEL
    deli, each feeding its own summarizer: the manifest sequences —
    content-addressed handles included — must be identical (the deltas
    streams are bit-identical by the deli gates, and the summarizer is
    deterministic over them), for both log formats."""
    import random
    import string

    from fluidframework_tpu.server.deli_kernel import KernelDeliRole
    from fluidframework_tpu.server.supervisor import DeliRole

    rng = random.Random(31)
    raws = [{"kind": "join", "doc": "x", "client": 1}]
    length = 0
    for i in range(60):
        if length == 0 or rng.random() < 0.6:
            pos = rng.randint(0, length)
            text = "".join(rng.choices(string.ascii_lowercase,
                                       k=rng.randint(1, 5)))
            contents = {"type": 0, "pos1": pos, "seg": text}
            length += len(text)
        else:
            a = rng.randint(0, length - 1)
            b = min(length, a + rng.randint(1, 4))
            contents = {"type": 1, "pos1": a, "pos2": b}
            length -= b - a
        raws.append({"kind": "op", "doc": "x", "client": 1,
                     "clientSeq": i + 1, "refSeq": i,
                     "contents": contents})

    handles = {}
    for impl, cls in (("scalar", DeliRole), ("kernel", KernelDeliRole)):
        d = str(tmp_path / f"{impl}")
        os.makedirs(os.path.join(d, "topics"), exist_ok=True)
        raw_topic = make_topic(
            os.path.join(d, "topics", "rawdeltas.jsonl"), log_format
        )
        raw_topic.append_many(raws)
        deli = cls(d, owner=impl, ttl_s=3600.0, log_format=log_format)
        deli.fence = 1
        reader = make_tail_reader(raw_topic)
        out = []
        if deli.ingest_batches and hasattr(reader, "poll_batches"):
            for unit in reader.poll_batches(10_000):
                if unit[0] == "batch":
                    deli.process_batch(unit[1], unit[2], out)
                else:
                    deli.process(unit[1], unit[2], out)
        else:
            for li, rec in reader.poll(10_000):
                deli.process(li, rec, out)
        deli.flush_batch(out)
        deli.out_topic.append_many(out, fence=1, owner=impl)
        drive_direct(d, [], summary_ops=16, log_format=log_format,
                     append_first=False)
        mans = manifests_of(d, log_format)
        assert mans and all(m["form"] == "mergetree" for m in mans)
        handles[impl] = [(m["doc"], m["seq"], m["handle"])
                        for m in mans]
        deltas = make_topic(
            os.path.join(d, "topics", "deltas.jsonl"), log_format
        )
        recs = [r for r in deltas.read_from(0)
                if isinstance(r, dict) and r.get("kind") == "op"]
        assert_all_boots_equal(d, "x", recs, log_format)
    assert handles["scalar"] == handles["kernel"]


def test_undecided_cadence_point_skipped_not_forked(tmp_path):
    """>= summary_ops join records before a doc's first op: the
    all-join cadence points are deterministically SKIPPED (no empty
    blob, no dangling trigger), whether the first op lands in the
    same pump or a later one, and summary + tail still equals cold
    replay (the review-found empty-'ops'-blob bug)."""
    n_joins, n = 6, 4  # joins alone cross the cadence at count 4
    base = []
    seq = 0
    for c in range(1, n_joins + 1):
        seq += 1
        base.append({"kind": "op", "doc": "j", "seq": seq, "msn": 0,
                     "client": c, "clientSeq": 0, "refSeq": seq - 1,
                     "type": "join", "contents": c})
    ops = []
    for i in range(1, 11):
        seq += 1
        ops.append({"kind": "op", "doc": "j", "seq": seq,
                    "msn": max(0, seq - 4), "client": 1,
                    "clientSeq": i, "refSeq": seq - 1, "type": "op",
                    "contents": {"i": i}})
    records = base + ops
    for variant, batches in (("one_pump", [records]),
                             ("split_pump", [base, ops])):
        d = str(tmp_path / variant)
        os.makedirs(os.path.join(d, "topics"))
        make_topic(os.path.join(d, "topics", "deltas.jsonl"),
                   "json").append_many(records)
        role = SummarizerRole(d, owner="t", ttl_s=3600.0,
                              summary_ops=n)
        role.fence = 1
        li = 0
        for chunk in batches:
            out = []
            for rec in chunk:
                role.process(li, rec, out)
                li += 1
            role.flush_batch(out)
            if out:
                role.out_topic.append_many(out, fence=1, owner="t")
        mans = manifests_of(d)
        # Multiples 4 (all joins) and 8 skipped/emitted rule: count 4
        # is pre-decision -> skipped; 8, 12, 16 emitted.
        assert [m["count"] for m in mans] == [8, 12, 16], (variant, mans)
        store = open_summary_store(d)
        for m in mans:
            blob = json.loads(store.get(m["handle"]).decode())
            assert blob["form"] == "ops"
            assert len(blob["records"]) == m["count"]  # never empty
        assert_all_boots_equal(d, "j", records)
