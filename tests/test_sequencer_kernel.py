"""Differential test: batched sequencer kernel vs the scalar oracle.

Random traffic (joins, leaves, valid ops, and deliberately invalid
submissions: stale/future refSeqs, clientSeq gaps, unknown clients) is
driven through `ops.sequencer_kernel.sequence_batch` and through one
`server.sequencer.DocumentSequencer` per document; sequence stamps,
nack codes, and MSNs must match exactly (the deli ticketing contract,
reference server/routerlicious/packages/lambdas/src/deli/lambda.ts:818).
"""

from __future__ import annotations

import random

import jax.numpy as jnp
import numpy as np
import pytest

from fluidframework_tpu.ops.sequencer_kernel import (
    ACCEPT,
    NACK_OUT_OF_ORDER,
    NACK_UNKNOWN_CLIENT,
    NO_GROUP,
    SUB_JOIN,
    SUB_LEAVE,
    SUB_OP,
    SUB_PAD,
    SUB_SYSTEM,
    SeqBatch,
    grow_state,
    make_state,
    sequence_batch,
)
from fluidframework_tpu.protocol.messages import DocumentMessage, MessageType
from fluidframework_tpu.server.sequencer import DocumentSequencer


def _gen_traffic(rng: random.Random, n_ops: int, n_clients: int):
    """One document's submission list: (kind, client, client_seq, ref_seq).

    Maintains a shadow model only to *generate* mostly-plausible traffic
    (including invalid cases); correctness is judged by the oracle.
    """
    subs = []
    connected: dict[int, int] = {}  # client -> client_seq counter
    seq_guess = 0  # tracks stamps to produce plausible ref_seqs
    for _ in range(n_ops):
        r = rng.random()
        if r < 0.08 or not connected:
            c = rng.randrange(n_clients)
            subs.append((SUB_JOIN, c, 0, 0))
            connected[c] = 0
            seq_guess += 1
        elif r < 0.12:
            c = rng.randrange(n_clients)
            was = c in connected
            subs.append((SUB_LEAVE, c, 0, 0))
            connected.pop(c, None)
            if was:
                seq_guess += 1
        elif r < 0.16:
            subs.append((SUB_PAD, 0, 0, 0))
        else:
            c = rng.choice(list(connected.keys()))
            cs = connected[c] + 1
            ref = rng.randint(max(0, seq_guess - 4), seq_guess)
            bad = rng.random()
            if bad < 0.05:
                cs += rng.randint(1, 3)  # clientSeq gap
            elif bad < 0.08:
                ref = seq_guess + rng.randint(1, 5)  # future refSeq
            elif bad < 0.11:
                ref = -1 if rng.random() < 0.5 else 0  # often stale
            elif bad < 0.13:
                c2 = rng.randrange(n_clients)
                if c2 not in connected:
                    c = c2  # unknown client
            subs.append((SUB_OP, c, cs, ref))
            # only advance the shadow counter when plausibly valid
            if cs == connected.get(c, -10) + 1 and 0 <= ref <= seq_guess:
                connected[c] = cs
                seq_guess += 1
    return subs


def _oracle_run(subs, n_clients: int):
    doc = DocumentSequencer("d")
    seqs, msns, nacks = [], [], []
    for kind, client, client_seq, ref_seq in subs:
        if kind == SUB_JOIN:
            m = doc.join(client, now=0.0)
            seqs.append(m.sequence_number)
            msns.append(m.minimum_sequence_number)
            nacks.append(ACCEPT)
        elif kind == SUB_LEAVE:
            m = doc.leave(client)
            seqs.append(m.sequence_number if m else 0)
            msns.append(m.minimum_sequence_number if m else doc.min_seq)
            nacks.append(ACCEPT)
        elif kind == SUB_PAD:
            seqs.append(0)
            msns.append(doc.min_seq)
            nacks.append(ACCEPT)
        else:
            out = doc.sequence(
                client,
                DocumentMessage(
                    client_seq=client_seq, ref_seq=ref_seq, type=MessageType.OP
                ),
                now=0.0,
            )
            if hasattr(out, "sequence_number"):
                seqs.append(out.sequence_number)
                msns.append(out.minimum_sequence_number)
                nacks.append(ACCEPT)
            else:
                seqs.append(0)
                msns.append(doc.min_seq)
                nacks.append(out.code)
    return seqs, msns, nacks


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_kernel_matches_oracle(seed):
    n_docs, n_clients, n_ops = 8, 8, 200
    rng = random.Random(seed)
    traffic = [_gen_traffic(rng, n_ops, n_clients) for _ in range(n_docs)]

    batch = SeqBatch(
        kind=jnp.asarray([[s[0] for s in t] for t in traffic], jnp.int32),
        client=jnp.asarray([[s[1] for s in t] for t in traffic], jnp.int32),
        client_seq=jnp.asarray([[s[2] for s in t] for t in traffic], jnp.int32),
        ref_seq=jnp.asarray([[s[3] for s in t] for t in traffic], jnp.int32),
    )
    state = make_state(n_docs, n_clients)
    new_state, res = sequence_batch(state, batch)

    for d in range(n_docs):
        seqs, msns, nacks = _oracle_run(traffic[d], n_clients)
        np.testing.assert_array_equal(
            np.asarray(res.seq[d]), np.asarray(seqs, np.int32), err_msg=f"doc {d} seq"
        )
        np.testing.assert_array_equal(
            np.asarray(res.nack[d]), np.asarray(nacks, np.int32), err_msg=f"doc {d} nack"
        )
        np.testing.assert_array_equal(
            np.asarray(res.min_seq[d]), np.asarray(msns, np.int32), err_msg=f"doc {d} msn"
        )


def test_boxcar_group_nack_masks_tail():
    """A nack inside a boxcar group masks the group's remaining
    submissions (no stamp, no nack — `skipped`); later groups and
    standalone ops are unaffected."""
    state = make_state(1, 4)
    kinds = [SUB_JOIN, SUB_OP, SUB_OP, SUB_OP, SUB_OP]
    #         join      ok     gap!   masked  next group: ok
    batch = SeqBatch(
        kind=jnp.asarray([kinds], jnp.int32),
        client=jnp.asarray([[1, 1, 1, 1, 1]], jnp.int32),
        client_seq=jnp.asarray([[0, 1, 5, 2, 2]], jnp.int32),
        ref_seq=jnp.asarray([[0, 0, 0, 0, 0]], jnp.int32),
    )
    groups = jnp.asarray([[NO_GROUP, 0, 0, 0, 1]], jnp.int32)
    state, res = sequence_batch(state, batch, groups)
    assert res.nack[0].tolist() == [0, 0, NACK_OUT_OF_ORDER, 0, 0]
    assert res.skipped[0].tolist() == [False, False, False, True, False]
    assert res.seq[0].tolist() == [1, 2, 0, 0, 3]
    assert int(state.seq[0]) == 3


def test_dedup_mode_drops_resubmissions_silently():
    state = make_state(1, 4)
    batch = SeqBatch(
        kind=jnp.asarray([[SUB_JOIN, SUB_OP, SUB_OP, SUB_OP, SUB_OP]], jnp.int32),
        client=jnp.asarray([[1, 1, 1, 1, 2]], jnp.int32),
        client_seq=jnp.asarray([[0, 1, 1, 2, 1]], jnp.int32),  # dup cseq 1
        ref_seq=jnp.asarray([[0, 0, 0, 0, 0]], jnp.int32),
    )
    state, res = sequence_batch(state, batch, dedup=True)
    # dup is skipped silently; unknown client still nacks (dedup needs
    # a known client).
    assert res.skipped[0].tolist() == [False, False, True, False, False]
    assert res.nack[0].tolist() == [0, 0, 0, 0, NACK_UNKNOWN_CLIENT]
    assert res.seq[0].tolist() == [1, 2, 0, 3, 0]


def test_system_stamp_bypasses_validation():
    """SUB_SYSTEM stamps unconditionally (deli's control path) without
    touching the client table; MSN follows the oracle's rules."""
    state = make_state(1, 4)
    batch = SeqBatch(
        kind=jnp.asarray([[SUB_SYSTEM, SUB_JOIN, SUB_SYSTEM]], jnp.int32),
        client=jnp.asarray([[0, 2, 0]], jnp.int32),
        client_seq=jnp.asarray([[0, 0, 0]], jnp.int32),
        ref_seq=jnp.asarray([[0, 0, 0]], jnp.int32),
    )
    state, res = sequence_batch(state, batch)
    assert res.seq[0].tolist() == [1, 2, 3]
    # no clients yet -> MSN trails head; after the join, MSN = join ref.
    assert res.min_seq[0].tolist() == [1, 1, 1]
    assert not bool(state.connected[0, 0])  # system never joins


def test_grow_state_preserves_and_pads():
    state = make_state(2, 2)
    batch = SeqBatch(
        kind=jnp.asarray([[SUB_JOIN], [SUB_JOIN]], jnp.int32),
        client=jnp.asarray([[1], [0]], jnp.int32),
        client_seq=jnp.asarray([[0], [0]], jnp.int32),
        ref_seq=jnp.asarray([[0], [0]], jnp.int32),
    )
    state, _ = sequence_batch(state, batch)
    grown = grow_state(state, 4, 8)
    assert grown.connected.shape == (4, 8)
    assert grown.seq.tolist()[:2] == state.seq.tolist()
    assert grown.seq.tolist()[2:] == [0, 0]
    assert bool(grown.connected[0, 1]) and bool(grown.connected[1, 0])
    assert not bool(grown.connected[2, 0])


def test_empty_doc_msn_trails_head():
    # With no connected clients the MSN follows the head (deli: allows
    # summaries to collect everything once the doc quiesces).
    state = make_state(1, 4)
    batch = SeqBatch(
        kind=jnp.asarray([[SUB_JOIN, SUB_OP, SUB_LEAVE]], jnp.int32),
        client=jnp.asarray([[2, 2, 2]], jnp.int32),
        client_seq=jnp.asarray([[0, 1, 0]], jnp.int32),
        ref_seq=jnp.asarray([[0, 1, 0]], jnp.int32),
    )
    new_state, res = sequence_batch(state, batch)
    assert int(new_state.seq[0]) == 3
    # after the leave there are no clients: MSN == seq
    assert int(new_state.min_seq[0]) == 3
