"""Sequence-field mark calculus: law-based fuzz (the
verifyChangeRebaser contract, core/rebase/verifyChangeRebaser.ts) plus
targeted mark-algebra cases (sequence-field/{rebase,compose,invert}.ts
semantics: shifts, mutes, slides, moves)."""

from __future__ import annotations

import random

import pytest

from fluidframework_tpu.tree.sequence_field import (
    apply_marks,
    compose_marks,
    delete,
    insert,
    invert_marks,
    move_in,
    move_out,
    normalize,
    rebase_marks,
    skip,
)


def rand_marks(rng: random.Random, seq_len: int, allow_moves: bool = False):
    """A random well-formed mark stream over a sequence of seq_len."""
    marks = []
    i = 0
    mid = 0
    while i < seq_len:
        r = rng.random()
        if r < 0.35:
            n = rng.randint(1, min(3, seq_len - i))
            marks.append(skip(n))
            i += n
        elif r < 0.55:
            marks.append(insert([f"n{rng.randint(0, 99)}"
                                 for _ in range(rng.randint(1, 3))]))
        elif r < 0.8:
            n = rng.randint(1, min(3, seq_len - i))
            marks.append(delete(n))
            i += n
        elif allow_moves and seq_len - i >= 1:
            n = rng.randint(1, min(2, seq_len - i))
            marks.append(move_out(n, f"m{mid}"))
            marks.append(move_in(f"m{mid}"))
            mid += 1
            i += n
        else:
            n = rng.randint(1, min(3, seq_len - i))
            marks.append(skip(n))
            i += n
    if rng.random() < 0.5:
        marks.append(insert(["tail"]))
    return marks


def seq(n):
    return [f"s{i}" for i in range(n)]


@pytest.mark.parametrize("seed", range(30))
def test_compose_law(seed):
    """apply(apply(s, A), B) == apply(s, compose(A, B))."""
    rng = random.Random(seed)
    s = seq(rng.randint(0, 10))
    a = rand_marks(rng, len(s))
    mid = apply_marks(s, a)
    b = rand_marks(rng, len(mid))
    direct = apply_marks(mid, b)
    composed = apply_marks(s, compose_marks(a, b))
    assert direct == composed, f"compose law failed (seed {seed})"


@pytest.mark.parametrize("seed", range(30))
def test_invert_law(seed):
    """apply(apply(s, A), invert(A)) == s (after capture)."""
    rng = random.Random(seed)
    s = seq(rng.randint(0, 10))
    a = rand_marks(rng, len(s), allow_moves=True)
    applied = apply_marks(s, a)  # captures delete content in-place
    back = apply_marks(applied, invert_marks(a))
    assert back == s, f"invert law failed (seed {seed})"


@pytest.mark.parametrize("seed", range(30))
def test_rebase_identity_and_composition_laws(seed):
    """rebase(A, []) == A and
    rebase(A, compose(B, C)) ~ rebase(rebase(A, B), C) (same effect)."""
    rng = random.Random(seed)
    s = seq(rng.randint(1, 10))
    a = rand_marks(rng, len(s))
    assert normalize(rebase_marks(a, [])) == normalize(a)

    b = rand_marks(rng, len(s))
    after_b = apply_marks(s, b)
    c = rand_marks(rng, len(after_b))
    after_bc = apply_marks(after_b, c)

    iterated = rebase_marks(rebase_marks(a, b), c)
    composed = rebase_marks(a, compose_marks(b, c))
    # The law holds on EFFECT (states can admit several normal forms).
    assert apply_marks(after_bc, iterated) == apply_marks(after_bc, composed), (
        f"rebase-composition law failed (seed {seed})"
    )


@pytest.mark.parametrize("seed", range(40))
def test_concurrent_convergence(seed):
    """Both replicas converge: state after [B, rebase(A over B)] is the
    same whether computed by A's author or B's author."""
    rng = random.Random(seed)
    s = seq(rng.randint(1, 10))
    a = rand_marks(rng, len(s))
    b = rand_marks(rng, len(s))
    # B sequenced first; A rebases over B.
    b_applied = apply_marks(s, [dict(m) for m in b])
    final_1 = apply_marks(b_applied, rebase_marks(a, b, base_first=True))
    # Recompute on another replica from scratch: identical inputs must
    # give identical output (determinism).
    b_applied_2 = apply_marks(s, [dict(m) for m in b])
    final_2 = apply_marks(b_applied_2, rebase_marks(a, b, base_first=True))
    assert final_1 == final_2


def test_rebase_shift_over_insert():
    # A inserts at index 2; base inserted 2 nodes at index 0.
    a = [skip(2), insert(["x"])]
    base = [insert(["p", "q"])]
    out = rebase_marks(a, base)
    assert apply_marks(["a", "b", "c"], base) == ["p", "q", "a", "b", "c"]
    assert apply_marks(["p", "q", "a", "b", "c"], out) == [
        "p", "q", "a", "b", "x", "c"]


def test_rebase_same_position_base_first():
    a = [insert(["mine"])]
    base = [insert(["theirs"])]
    out = rebase_marks(a, base, base_first=True)
    assert apply_marks(["theirs"], out) == ["theirs", "mine"]
    out2 = rebase_marks(a, base, base_first=False)
    assert apply_marks(["theirs"], out2) == ["mine", "theirs"]


def test_rebase_mute_over_delete():
    # A deletes node 1; base already deleted nodes 0-1: A's delete mutes.
    a = [skip(1), delete(1)]
    base = [delete(2)]
    out = rebase_marks(a, base)
    assert apply_marks(["c"], out) == ["c"]  # nothing left to delete


def test_rebase_insert_slides_to_deleted_range_start():
    # A inserts inside a range base deleted: lands at the range start.
    a = [skip(2), insert(["x"]), skip(1)]
    base = [skip(1), delete(2)]
    out = rebase_marks(a, base)
    assert apply_marks(["s0"], out) == ["s0", "x"]


def test_move_roundtrip():
    s = ["a", "b", "c", "d"]
    marks = [move_out(2, "m1"), skip(2), move_in("m1")]
    moved = apply_marks(s, marks)
    assert moved == ["c", "d", "a", "b"]
    assert apply_marks(moved, invert_marks(marks)) == s
