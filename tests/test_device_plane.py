"""2-D device plane (ISSUE 15): ONE ``docs x model`` mesh serving the
sequencer AND the summarizer folds.

Gates: typed slices (disjoint model columns, stable worker mapping),
the sequencer bit-identical on a plane slice vs single-device
(including the deferred per-shard GROW scatter and its logical→
physical slot map), cross-topology checkpoint interop extended to the
2-D layout (scalar ⇄ 1-dev ⇄ 1-D ⇄ plane slice), and the overlay-
pallas fold backend (`core.overlay_fold`) byte-identical to the
vmapped kernel fold at every emission — the content-addressed
no-fork contract is backend-invariant. Runs on the conftest-forced 8
virtual host CPU devices (overlay through the pallas interpreter);
the code is identical on a real slice.
"""

from __future__ import annotations

import json
import os
import random

import jax
import numpy as np
import pytest

from fluidframework_tpu.parallel.device_plane import (
    DevicePlane,
    PLANE_ENV,
    parse_plane_spec,
    plane_column_of,
    resolve_plane,
    shared_plane,
)
from fluidframework_tpu.server.deli_kernel import (
    KernelDeliLambda,
    PackedDeliCore,
    mesh_for_devices,
    mesh_for_plane,
)
from fluidframework_tpu.ops.sequencer_kernel import (
    NO_GROUP,
    SUB_JOIN,
    SUB_LEAVE,
    SUB_OP,
)


def _need_devices(n: int) -> None:
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} (virtual) devices")


# ---------------------------------------------------------------------------
# the plane itself
# ---------------------------------------------------------------------------


def test_plane_spec_parse_and_validation():
    assert parse_plane_spec("2x2") == (2, 2)
    assert parse_plane_spec("4X2") == (4, 2)
    assert parse_plane_spec("2*3") == (2, 3)
    assert parse_plane_spec((3, 1)) == (3, 1)
    with pytest.raises(ValueError, match="DOCSxMODEL"):
        parse_plane_spec("4")
    with pytest.raises(ValueError, match=">= 1"):
        parse_plane_spec("0x2")


def test_shared_plane_cache_and_slices():
    _need_devices(4)
    plane = shared_plane(2, 2)
    assert resolve_plane("2x2") is plane
    assert resolve_plane(plane) is plane
    assert resolve_plane(None) is None
    assert plane.size == 4
    assert dict(plane.mesh.shape) == {"docs": 2, "model": 2}
    m0, m1 = plane.seq_mesh(0), plane.seq_mesh(1)
    assert plane.seq_mesh(2) is m0  # columns wrap mod model
    assert tuple(m0.axis_names) == ("docs",)
    # Typed slices: the two ordering columns are DISJOINT device sets
    # tiling the pool — tenants don't contend for the same chips.
    assert not (set(m0.devices.flat) & set(m1.devices.flat))
    assert (set(m0.devices.flat) | set(m1.devices.flat)
            == set(plane.mesh.devices.flat))
    d = plane.describe()
    assert d["docs"] == 2 and d["model"] == 2 and d["devices"] == 4


def test_plane_env_resolution(monkeypatch):
    _need_devices(4)
    monkeypatch.setenv(PLANE_ENV, "2x2")
    assert resolve_plane(None, env=True) is shared_plane(2, 2)
    assert resolve_plane(None, env=False) is None
    monkeypatch.delenv(PLANE_ENV)
    assert resolve_plane(None, env=True) is None


def test_plane_column_mapping_stable():
    assert plane_column_of(0, 2) == 0
    assert plane_column_of(3, 2) == 1
    assert plane_column_of("w1", 2) == plane_column_of("w1", 2)
    assert plane_column_of("deli-r0-7fffffff", 4) in range(4)


# ---------------------------------------------------------------------------
# sequencer on a plane slice (+ the deferred GROW scatter)
# ---------------------------------------------------------------------------


def drive_core(core: PackedDeliCore, seed: int, pumps: int = 4,
               per_pump: int = 80, docs: int = 6, clients: int = 5):
    """Seeded mixed traffic (the test_deli_sharded driver shape):
    joins/leaves, boxcars, invalid ops, resubmissions."""
    rng = random.Random(seed)
    results = []
    recent: list = []
    for _ in range(pumps):
        core.begin()
        for _ in range(per_pump):
            doc = f"doc{rng.randrange(docs)}"
            h = core.touch(doc)
            slot = h["slot"]
            r = rng.random()
            if r < 0.15:
                cid = rng.randrange(1, clients + 1)
                core.add(slot, SUB_JOIN, core.pool.col_of_join(h, cid))
            elif r < 0.22:
                cid = rng.randrange(1, clients + 1)
                core.add(slot, SUB_LEAVE, h["cmap"].get(cid, 0))
            elif r < 0.35:
                g = core.new_group(slot)
                col = rng.randrange(0, clients + 1)
                for _k in range(rng.randrange(2, 5)):
                    core.add(slot, SUB_OP, col, rng.randrange(1, 9),
                             rng.randrange(0, 5), g)
            elif r < 0.45 and recent:
                core.add(*rng.choice(recent))  # resubmission -> dedup
            else:
                sub = (slot, SUB_OP, rng.randrange(0, clients + 1),
                       rng.randrange(1, 9), rng.randrange(0, 5),
                       NO_GROUP)
                recent.append(sub)
                if len(recent) > 32:
                    recent.pop(0)
                core.add(*sub)
        res = core.run()
        results.append((res.seq, res.msn, res.nack, res.skipped))
    return results


def test_plane_slice_core_matches_single_device():
    _need_devices(4)
    single = drive_core(PackedDeliCore(dedup=True), seed=51)
    for col in (0, 1):
        sliced = drive_core(
            PackedDeliCore(dedup=True,
                           mesh=shared_plane(2, 2).seq_mesh(col)),
            seed=51,
        )
        assert sliced == single


def test_placed_grow_stays_on_device_and_matches():
    """The deferred GROW scatter: doubling an already-placed pool pads
    each shard's slab device-locally (no full re-place), remaps the
    logical→physical slot map per shard, and the verdict stream stays
    bit-identical to the scalar pool's through repeated growth."""
    _need_devices(4)
    mesh = mesh_for_devices(4)
    core = PackedDeliCore(n_docs=4, dedup=True, mesh=mesh)
    single = PackedDeliCore(n_docs=4, dedup=True)
    a = drive_core(core, seed=52, docs=5)
    b = drive_core(single, seed=52, docs=5)
    assert a == b
    pool = core.pool
    assert pool._placed
    d0 = pool.n_docs
    # Growth traffic: many more docs force repeated doubling.
    a = drive_core(core, seed=53, docs=40)
    b = drive_core(single, seed=53, docs=40)
    assert a == b
    assert pool.n_docs > d0
    assert pool._placed, "grow fell back to a full re-place"
    assert pool.n_docs % pool._n_shards == 0
    # The slot map is a bijection and shard-preserving: every logical
    # slot's physical row stayed on the shard it lived on pre-grow.
    assert sorted(pool._phys.tolist()) == list(range(pool.n_docs))
    # And the checkpoint is still topology-free.
    assert pool.checkpoint_docs() == single.pool.checkpoint_docs()


def test_placed_grow_reuses_untouched_shard_buffers():
    """After a grow, the next queued-row scatter still takes the
    scoped path: shards owning no touched row keep their (padded)
    buffers by identity — nothing re-transfers."""
    _need_devices(4)
    mesh = mesh_for_devices(4)
    core = PackedDeliCore(n_docs=8, dedup=True, mesh=mesh)
    drive_core(core, seed=54, docs=24, pumps=3)  # grows while placed
    pool = core.pool
    assert pool._placed and pool.n_docs >= 16
    # Park + touch ONE doc: exactly one shard's slab is rebuilt.
    doc = next(iter(pool.slot_owner.values()))
    pool.park(doc)
    h = pool.touch(doc)
    assert pool._loads
    def ptrs(name):
        return [s.data.unsafe_buffer_pointer() for s in sorted(
            getattr(pool.state, name).addressable_shards,
            key=lambda s: (s.index[0].start or 0) if s.index else 0,
        )]

    before = {name: ptrs(name) for name in pool.state._fields}
    rows = pool.n_docs // pool._n_shards
    touched_shard = int(pool._phys[h["slot"]]) // rows
    pool.prepare()
    for name, olds in before.items():
        cur = ptrs(name)
        for si, (old, now) in enumerate(zip(olds, cur)):
            if si != touched_shard:
                assert now == old, (
                    f"{name} shard {si} was rebuilt though untouched"
                )


def test_plane_conflicts_are_loud():
    _need_devices(4)
    from fluidframework_tpu.server.log import MessageLog

    with pytest.raises(ValueError, match="exclusive"):
        KernelDeliLambda(MessageLog(), deli_devices=4,
                         device_plane="2x2")
    from fluidframework_tpu.server.shard_fabric import ShardWorker

    with pytest.raises(ValueError, match="deli_impl='kernel'"):
        ShardWorker("/tmp/nowhere-plane", "w0", device_plane="2x2")
    from fluidframework_tpu.server.supervisor import ServiceSupervisor

    with pytest.raises(ValueError, match="deli_impl='kernel'"):
        ServiceSupervisor("/tmp/nowhere-plane", device_plane="2x2")
    with pytest.raises(ValueError, match="exclusive"):
        ServiceSupervisor("/tmp/nowhere-plane", deli_impl="kernel",
                          deli_devices=4, device_plane="2x2")


def test_serve_role_plane_validation():
    from fluidframework_tpu.server.supervisor import serve_role

    with pytest.raises(ValueError, match="device_plane"):
        serve_role("/tmp/nowhere", "scriptorium", "o",
                   device_plane="2x2")
    with pytest.raises(ValueError, match="device_plane"):
        serve_role("/tmp/nowhere", "deli", "o", deli_impl="scalar",
                   device_plane="2x2")
    with pytest.raises(ValueError, match="fold_backend"):
        serve_role("/tmp/nowhere", "deli", "o", deli_impl="kernel",
                   fold_backend="overlay")


# ---------------------------------------------------------------------------
# cross-topology checkpoint interop at 2-D
# ---------------------------------------------------------------------------


def _interop(prefix, suffix, first, second):
    from fluidframework_tpu.server.lambdas import DeliLambda
    from fluidframework_tpu.server.log import MessageLog
    from test_deli_sharded import norm

    def build(log, ckpt, topo):
        if topo == "scalar":
            return DeliLambda(log, ckpt)
        if isinstance(topo, str) and "x" in topo:
            # 2-D: the plane's docs-axis slice (column 0).
            return KernelDeliLambda(log, ckpt, device_plane=topo)
        return KernelDeliLambda(log, ckpt, deli_devices=topo)

    log = MessageLog()
    log.topic("rawdeltas").append_many(prefix)
    a = build(log, None, first)
    while a.pump():
        pass
    ckpt = a.checkpoint()
    log.topic("rawdeltas").append_many(suffix)
    b = build(log, ckpt, second)
    while b.pump():
        pass
    return norm(log.topic("deltas").read(0))


def test_cross_topology_interop_includes_plane():
    """Satellite contract at 2-D: scalar ⇄ 1-dev ⇄ 1-D (4 devices) ⇄
    plane slice (2x2) checkpoints restore bit-identical — the
    checkpoint format stays topology-free under the plane too."""
    _need_devices(4)
    from test_deli_sharded import gen_raw

    recs = gen_raw(44, n=260)
    prefix, suffix = recs[:130], recs[130:]
    want = _interop(prefix, suffix, "scalar", "scalar")
    assert _interop(prefix, suffix, "2x2", "scalar") == want
    assert _interop(prefix, suffix, "scalar", "2x2") == want
    assert _interop(prefix, suffix, "2x2", 1) == want
    assert _interop(prefix, suffix, 4, "2x2") == want
    assert _interop(prefix, suffix, "2x2", 4) == want


# ---------------------------------------------------------------------------
# the overlay fold backend (canonical rows backend-invariant)
# ---------------------------------------------------------------------------


def _emission_sweep(backend: str, recs, summary_ops: int,
                    plane=None):
    """The summarizer's exact emission loop (boot-from-rows, encode,
    fold, canonical serialization, rebuild) for one doc's stream;
    returns every emission's canonical rows."""
    from fluidframework_tpu.core.overlay_fold import (
        boot_overlay,
        fold_jobs_overlay,
    )
    from fluidframework_tpu.server.summarizer import (
        _boot_mergetree,
        _canonical_rows,
        _encode_fold,
        _fold_jobs,
    )

    def boot(rows, msn):
        if backend == "overlay":
            return boot_overlay(rows, msn, interpret=True)
        return _boot_mergetree(rows, msn)

    rows, base_msn = [], 0
    out = []
    window = []
    count = msn = 0
    rep = None
    for rec in recs:
        window.append(rec)
        count += 1
        msn = max(msn, rec["msn"])
        if count % summary_ops == 0:
            if rep is None:
                rep = boot(rows, base_msn)
            _encode_fold(rep, window)
            window = []
            if backend == "overlay":
                fold_jobs_overlay([(rep, None)], plane=plane,
                                  interpret=True)
                rows = rep.canonical_rows(msn)
            else:
                _fold_jobs([(rep, None)], plane=plane)
                rows = _canonical_rows(rep, msn)
            base_msn = msn
            out.append(rows)
            rep = boot(rows, base_msn)
    return out


@pytest.mark.parametrize("seed,cadence", [(10, 60), (11, 25)])
def test_overlay_fold_canonical_rows_bit_identical(seed, cadence):
    """THE backend-invariance gate: the overlay-pallas fold's
    canonical rows equal the vmapped kernel fold's byte-for-byte at
    EVERY emission point — same blob bytes, same content-addressed
    handles, restart-stable across either engine."""
    from fluidframework_tpu.testing.deli_bench import (
        build_mergetree_stream,
    )

    recs = build_mergetree_stream(300, n_clients=4, seed=seed)
    k = _emission_sweep("kernel", recs, cadence)
    o = _emission_sweep("overlay", recs, cadence)
    assert len(k) == len(o) > 0
    assert json.dumps(k, sort_keys=True) == json.dumps(o,
                                                       sort_keys=True)


def test_boot_overlay_roundtrip_idempotent():
    """boot-from-rows then serialize-with-no-new-ops returns the SAME
    rows (the restart path's fixed point) for both backends."""
    from fluidframework_tpu.core.overlay_fold import boot_overlay
    from fluidframework_tpu.server.summarizer import (
        _boot_mergetree,
        _canonical_rows,
    )
    from fluidframework_tpu.testing.deli_bench import (
        build_mergetree_stream,
    )

    recs = build_mergetree_stream(200, n_clients=3, seed=12)
    rows = _emission_sweep("kernel", recs, 100)[-1]
    msn = max(r["msn"] for r in recs[:200])
    k = _canonical_rows(_boot_mergetree(rows, msn), msn)
    o = boot_overlay(rows, msn, interpret=True).canonical_rows(msn)
    assert k == rows and o == rows


def test_stacked_fold_group_over_plane_bit_identical():
    """Several docs folding in one round stack over the 2-D plane —
    kernel (rows sharded on 'model') and overlay (doc stack tiling
    the pool, dummy-padded to the mesh size) both byte-identical to
    the unplaced single-doc folds."""
    _need_devices(4)
    from fluidframework_tpu.testing.deli_bench import (
        build_mergetree_stream,
    )

    plane = shared_plane(2, 2)
    streams = {
        f"doc{i}": build_mergetree_stream(120, n_clients=3,
                                          seed=30 + i, doc=f"doc{i}")
        for i in range(3)
    }
    want = {d: _emission_sweep("kernel", r, 60)
            for d, r in streams.items()}
    for backend in ("kernel", "overlay"):
        got = {d: _emission_sweep(backend, r, 60, plane=plane)
               for d, r in streams.items()}
        assert got == want, f"{backend} diverged under the plane"


def test_mesh_for_plane_partition_key_routing():
    _need_devices(4)
    m_a = mesh_for_plane("2x2", partition_key=0)
    m_b = mesh_for_plane("2x2", partition_key=1)
    assert m_a is shared_plane(2, 2).seq_mesh(0)
    assert m_b is shared_plane(2, 2).seq_mesh(1)
    assert mesh_for_plane(None) is None


# ---------------------------------------------------------------------------
# the summarizer role on the overlay backend
# ---------------------------------------------------------------------------


def _drive_summ_role(shared, recs, log_format="json", **role_kw):
    from fluidframework_tpu.server.columnar_log import (
        make_tail_reader,
        make_topic,
    )
    from fluidframework_tpu.server.summarizer import SummarizerRole

    os.makedirs(os.path.join(shared, "topics"), exist_ok=True)
    deltas = make_topic(
        os.path.join(shared, "topics", "deltas.jsonl"), log_format
    )
    deltas.append_many(recs)
    role = SummarizerRole(shared, owner="t-summ", ttl_s=3600.0,
                          log_format=log_format, **role_kw)
    role.fence = 1
    reader = make_tail_reader(deltas)
    manifests = []
    while True:
        entries = reader.poll(4096)
        if not entries:
            break
        out = []
        for line_idx, rec in entries:
            role.process(line_idx, rec, out)
        role.flush_batch(out)
        if out:
            role.out_topic.append_many(out, fence=1, owner="t-summ")
            manifests.extend(out)
        role.offset = reader.next_line
    return role, manifests


def test_summarizer_role_overlay_backend_identical_handles(tmp_path):
    """The role-level gate: a summarizer folding through the OVERLAY
    backend emits the identical manifest sequence — same seqs, same
    content-addressed handles — as the kernel-backend role over the
    same stream (and the resolved-backend gauge says which engine
    actually ran)."""
    from fluidframework_tpu.testing.deli_bench import (
        build_mergetree_stream,
    )

    recs = build_mergetree_stream(260, n_clients=4, seed=60)
    _, mk = _drive_summ_role(str(tmp_path / "k"), recs,
                             summary_ops=64, fold_backend="kernel")
    role_o, mo = _drive_summ_role(str(tmp_path / "o"), recs,
                                  summary_ops=64,
                                  fold_backend="overlay",
                                  fold_interpret=True)
    assert role_o.fold_backend() == "overlay"
    key = lambda ms: [(m["doc"], m["seq"], m["handle"], m["count"])
                      for m in ms]  # noqa: E731
    assert len(mk) > 0 and key(mk) == key(mo)


def test_fold_backend_fallback_is_loud(tmp_path, capsys):
    """fold_backend=overlay WITHOUT the interpreter on a host where
    pallas cannot lower falls back to the kernel backend LOUDLY
    (stdout + fallback counter) — never silently."""
    from fluidframework_tpu.core.overlay_fold import overlay_available
    from fluidframework_tpu.server.summarizer import SummarizerRole

    if overlay_available(False):
        pytest.skip("pallas lowers here (real accelerator): no "
                    "fallback to test")
    role = SummarizerRole(str(tmp_path), owner="t", ttl_s=3600.0,
                          fold_backend="overlay",
                          fold_interpret=False)
    assert role.fold_backend() == "kernel"
    assert "FALLING BACK" in capsys.readouterr().out
    assert int(role._m_backend_fallbacks.value) == 1


def test_fold_backend_env_default(tmp_path, monkeypatch):
    from fluidframework_tpu.server.summarizer import SummarizerRole

    monkeypatch.setenv("FLUID_FOLD_BACKEND", "overlay")
    monkeypatch.setenv("FLUID_FOLD_INTERPRET", "1")
    role = SummarizerRole(str(tmp_path), owner="t", ttl_s=3600.0)
    assert role._fold_backend_requested == "overlay"
    assert role.fold_interpret
    monkeypatch.setenv("FLUID_FOLD_BACKEND", "bogus")
    with pytest.raises(ValueError, match="FLUID_FOLD_BACKEND"):
        SummarizerRole(str(tmp_path), owner="t2", ttl_s=3600.0)


# ---------------------------------------------------------------------------
# the chaos acceptance gate (2-D farm vs scalar golden)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_device_plane_chaos_kill_converges(tmp_path):
    """ISSUE 15 acceptance: a supervised kernel+columnar farm on a
    2x2 plane — deli children sharding on the plane's docs slice, the
    summarizer folding through the OVERLAY backend (interpreter) —
    survives kill faults bit-identical to the scalar golden with
    summary integrity intact (blobs == cold scalar replay, no
    fork/dup). The workload's contents are merge-tree wire ops, so
    the overlay engine demonstrably RAN (mergetree-form blobs), not
    just resolved."""
    from fluidframework_tpu.server.columnar_log import make_topic
    from fluidframework_tpu.testing.chaos import ChaosConfig, run_chaos

    d = str(tmp_path / "plane-chaos")
    res = run_chaos(ChaosConfig(
        seed=151, faults=("kill",), n_docs=2, n_clients=3,
        ops_per_client=12, timeout_s=420.0, deli_impl="kernel",
        log_format="columnar", summarizer=True, summary_ops=8,
        device_plane="2x2", fold_backend="overlay", shared_dir=d,
    ))
    assert res.converged, res.detail
    assert res.summaries_ok and res.summary_manifests > 0
    assert res.duplicate_seqs == 0 and res.skipped_seqs == 0
    mans = [r for r in make_topic(
        os.path.join(d, "topics", "summaries.jsonl"), "columnar"
    ).read_from(0) if isinstance(r, dict)
        and r.get("kind") == "summary"]
    assert mans and all(m["form"] == "mergetree" for m in mans), (
        "fold backend never engaged: no mergetree-form blobs"
    )


def test_chaos_plane_validation():
    from fluidframework_tpu.testing.chaos import ChaosConfig, run_chaos

    with pytest.raises(ValueError, match="deli_impl='kernel'"):
        run_chaos(ChaosConfig(device_plane="2x2"))
    with pytest.raises(ValueError, match="exclusive"):
        run_chaos(ChaosConfig(deli_impl="kernel", device_plane="2x2",
                              deli_devices=4))
    with pytest.raises(ValueError, match="summarizer"):
        run_chaos(ChaosConfig(fold_backend="overlay"))
    with pytest.raises(ValueError, match="DOCSxMODEL"):
        run_chaos(ChaosConfig(deli_impl="kernel", device_plane="4"))
