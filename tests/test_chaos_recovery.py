"""Chaos harness + self-healing supervisor: fenced, exactly-once
lambda recovery.

The convergence claim (identical deterministic replay of one totally
ordered stream) exercised OFF the happy path: the lambda pipeline runs
as supervised child processes (`server.supervisor`), faults are
injected at seeded points (`testing.chaos`), and the farm must
converge bit-identical to the no-fault GOLDEN digest with zero
duplicate and zero skipped sequence numbers — while a deposed lease
holder's writes are demonstrably REJECTED by the fence.

Quick single-fault runs stay in tier-1; the full five-fault suite is
`slow` + `chaos` (tools/chaos_run.py is its CLI twin).
"""

from __future__ import annotations

import os
import time

import pytest

from fluidframework_tpu.server.queue import SharedFileTopic
from fluidframework_tpu.server.supervisor import ServiceSupervisor
from fluidframework_tpu.testing.chaos import (
    ChaosConfig,
    build_workload,
    golden_stream,
    run_chaos,
    sequence_integrity,
    stream_digest,
)


def _assert_converged(res):
    assert res.duplicate_seqs == 0, res.detail
    assert res.skipped_seqs == 0, res.detail
    assert res.digest == res.golden_digest, res.detail
    assert res.scribe_ok, res.detail
    assert res.converged, res.detail


def _assert_no_crash_restarts(res):
    """A NO-FAULT run must never see a child CRASH (`exit=` restart).
    A stale-heartbeat restart, by contrast, is the supervisor
    recovering a scheduler-STARVED child — on a loaded 2-core suite
    run a healthy role can miss its heartbeat window — and the run
    still converges bit-identically (asserted separately), so tolerate
    a bounded number of those rather than flake."""
    crashes = [e for e in res.events
               if e.startswith("restart") and "exit=" in e]
    assert not crashes, crashes
    assert sum(res.restarts.values()) <= 2, (res.restarts, res.events)


def test_supervised_farm_no_fault_matches_golden(tmp_path):
    """The multi-process farm with NO faults reproduces the in-proc
    golden stream bit-identically — the baseline every fault class is
    measured against."""
    # timeout is a deadline for a CONDITION poll inside run_chaos, not
    # a sleep: generous bounds deflake slow boxes without slowing the
    # happy path (240s: the old 120s still tripped on a contended
    # 2-core box when child spawns landed behind a bench run).
    res = run_chaos(ChaosConfig(
        seed=11, faults=(), n_docs=1, n_clients=2, ops_per_client=15,
        timeout_s=240, shared_dir=str(tmp_path),
    ))
    _assert_converged(res)
    _assert_no_crash_restarts(res)


def test_supervised_farm_no_fault_columnar_matches_golden(tmp_path):
    """The farm over the COLUMNAR binary op-log (every topic a
    record-batch log, ingress riding wire boxcars — the ROADMAP
    (a)/(d) storage path) reproduces the in-proc golden stream
    bit-identically: the wire form must never change the order."""
    res = run_chaos(ChaosConfig(
        seed=11, faults=(), n_docs=1, n_clients=2, ops_per_client=15,
        timeout_s=240, shared_dir=str(tmp_path),
        log_format="columnar", boxcar_rate=0.3,
    ))
    _assert_converged(res)
    _assert_no_crash_restarts(res)


@pytest.mark.chaos
def test_sharded_fabric_kill_lease_mid_boxcar_converges(tmp_path):
    """THE sharded-fabric acceptance gate (server.shard_fabric): kill
    a shard worker mid-stream (boxcars in flight) AND depose a
    partition owner via expired-lease takeover, on the KERNEL deli
    over COLUMNAR partition topics — the merged sequenced stream
    across all four deltas-p{k} must converge bit-identical to the
    single-partition in-proc golden with zero duplicated or skipped
    per-document sequence numbers, and the deposed owner's writes must
    be demonstrably fence-rejected."""
    res = run_chaos(ChaosConfig(
        seed=7, faults=("kill", "lease"), n_docs=4, n_clients=2,
        ops_per_client=12, timeout_s=240, shared_dir=str(tmp_path),
        deli_impl="kernel", log_format="columnar", boxcar_rate=0.25,
        n_partitions=4, n_workers=2,
    ))
    assert res.duplicate_seqs == 0, res.detail
    assert res.skipped_seqs == 0, res.detail
    assert res.digest == res.golden_digest, res.detail
    assert res.converged, res.detail
    assert res.fence_rejections >= 1  # deposed partition owner rejected
    # Both workers draw a seeded kill; a kill landing on an
    # already-dead slot is skipped, so >=1 restart is the hard floor.
    assert sum(res.restarts.values()) >= 1


@pytest.mark.chaos
def test_elastic_fabric_kill_split_merge_converges(tmp_path):
    """THE elastic-topology acceptance gate (ISSUE 8): a worker
    SIGKILLed mid-stream AND a live range split AND a live merge —
    kernel deli over columnar topics, 4 initial hash ranges, boxcars
    in flight, N changing mid-run twice — must converge bit-identical
    to the single-partition in-proc golden with zero duplicated or
    skipped sequence numbers, while the PRE-SPLIT owner's stale-fence
    write is demonstrably rejected. Capacity following load without a
    restart is exactly this: a topology change is just another fault
    the fenced-handoff machinery survives."""
    res = run_chaos(ChaosConfig(
        seed=7, faults=("kill", "split", "merge"), n_docs=4,
        n_clients=2, ops_per_client=12, timeout_s=300,
        shared_dir=str(tmp_path), deli_impl="kernel",
        log_format="columnar", boxcar_rate=0.25,
        n_partitions=4, n_workers=2,
    ))
    assert res.duplicate_seqs == 0, res.detail
    assert res.skipped_seqs == 0, res.detail
    assert res.digest == res.golden_digest, res.detail
    assert res.converged, res.detail
    assert res.fence_rejections >= 1  # pre-split owner rejected
    assert len(res.epochs) >= 3, res.epochs  # split AND merge committed
    assert sum(res.restarts.values()) >= 1  # the kill actually landed


@pytest.mark.chaos
def test_elastic_fabric_disk_faults_degrade_and_recover(tmp_path):
    """The storage fault classes (ISSUE 8): ENOSPC on the workers'
    topic/checkpoint writes plus a stalled-fsync episode. The fabric
    must degrade gracefully — bounded-retry backoff with `degraded`
    visible in health() while the fault holds — and converge with no
    lost acknowledged record once it clears."""
    res = run_chaos(ChaosConfig(
        seed=3, faults=("disk",), n_docs=2, n_clients=2,
        ops_per_client=10, timeout_s=240, shared_dir=str(tmp_path),
        n_partitions=2, n_workers=2,
    ))
    assert res.duplicate_seqs == 0, res.detail
    assert res.skipped_seqs == 0, res.detail
    assert res.digest == res.golden_digest, res.detail
    assert res.degraded_seen, res.detail
    assert res.converged, res.detail


@pytest.mark.chaos
def test_chaos_kill_torn_columnar_kernel_converges(tmp_path):
    """Kill + torn faults against the KERNEL deli over COLUMNAR topics
    (boxcarred ingress): exactly-once recovery, torn-tail sealing, and
    CRC-guarded framing must keep the binary log bit-identical to the
    scalar JSON golden."""
    res = run_chaos(ChaosConfig(
        seed=3, faults=("kill", "torn"), n_docs=2, n_clients=2,
        ops_per_client=12, timeout_s=150, shared_dir=str(tmp_path),
        deli_impl="kernel", log_format="columnar", boxcar_rate=0.25,
    ))
    _assert_converged(res)
    assert sum(res.restarts.values()) >= 4


def test_chaos_kill_every_role_exactly_once(tmp_path):
    """SIGKILL of each lambda role at seeded points: the supervisor
    restarts it, recovery replays deterministically from the fenced
    checkpoint, and the stream carries no duplicate or skipped seq."""
    res = run_chaos(ChaosConfig(
        seed=1, faults=("kill",), n_docs=1, n_clients=2,
        ops_per_client=25, timeout_s=90, shared_dir=str(tmp_path),
    ))
    _assert_converged(res)
    assert sum(res.restarts.values()) >= 4  # every role died once


def test_chaos_lease_takeover_rejects_deposed_writer(tmp_path):
    """Expired-lease takeover: the sequencer is stalled past its TTL,
    a usurper binds the next fence, and the deposed owner's topic AND
    checkpoint writes are rejected — convergence must still hold."""
    res = run_chaos(ChaosConfig(
        seed=2, faults=("lease",), n_docs=1, n_clients=2,
        ops_per_client=20, timeout_s=90, shared_dir=str(tmp_path),
    ))
    _assert_converged(res)
    assert res.fence_rejections >= 2  # topic + checkpoint both rejected


def test_chaos_torn_appends_and_resubmit_dedup(tmp_path):
    """Torn topic appends plus client mid-batch resubmissions: readers
    skip sealed junk without crashing and deli dedups duplicates, so
    the total order is byte-for-byte the no-fault one."""
    res = run_chaos(ChaosConfig(
        seed=4, faults=("torn", "client"), n_docs=1, n_clients=2,
        ops_per_client=20, timeout_s=90, shared_dir=str(tmp_path),
    ))
    _assert_converged(res)


def test_chaos_net_duplicated_delayed_delivery(tmp_path):
    """Duplicated/delayed delivery on the broadcast edge: the client
    gap/dedup guard reconstructs the exact stream."""
    res = run_chaos(ChaosConfig(
        seed=6, faults=("net",), n_docs=1, n_clients=2,
        ops_per_client=20, timeout_s=120, shared_dir=str(tmp_path),
    ))
    _assert_converged(res)
    assert res.client_digest == res.golden_digest


@pytest.mark.slow
@pytest.mark.chaos
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_full_chaos_suite_converges(seed, tmp_path):
    """The acceptance gate: all five fault classes composed (SIGKILL of
    every role, torn appends, expired-lease takeover with fence
    rejection, duplicated/delayed delivery, client disconnect
    mid-batch) at full workload size, per seed."""
    res = run_chaos(ChaosConfig(
        seed=seed, timeout_s=180, shared_dir=str(tmp_path),
    ))
    _assert_converged(res)
    assert res.fence_rejections > 0
    assert res.client_digest == res.golden_digest
    assert sum(res.restarts.values()) >= 4


def test_workload_and_golden_deterministic(tmp_path):
    """Same seed → byte-identical workload and golden digest; a
    different seed diverges (the suite is genuinely seeded)."""
    cfg = ChaosConfig(seed=9, n_docs=2, n_clients=2, ops_per_client=10)
    w1 = build_workload(cfg)
    w2 = build_workload(ChaosConfig(
        seed=9, n_docs=2, n_clients=2, ops_per_client=10
    ))
    assert w1 == w2
    g1 = golden_stream(w1, str(tmp_path / "a"))
    g2 = golden_stream(w2, str(tmp_path / "b"))
    assert stream_digest(g1) == stream_digest(g2)
    w3 = build_workload(ChaosConfig(
        seed=10, n_docs=2, n_clients=2, ops_per_client=10
    ))
    assert w3 != w1
    assert sequence_integrity(g1) == (0, 0)


def test_client_farm_survives_server_sigkill_live_reconnect(tmp_path):
    """Client-side chaos composed with a REAL process kill: containers
    stay live through `kill -9` of the ordering service. The
    FaultInjectionDriver wraps the socket driver (the test-service-load
    composition), the jittered ConnectionManager rides the restart on
    the same port, pending ops made while the service was DOWN
    resubmit exactly once, and the replicas converge."""
    import signal
    import subprocess
    import sys

    from fluidframework_tpu.dds import MapFactory, StringFactory
    from fluidframework_tpu.drivers import FaultInjectionDriver
    from fluidframework_tpu.drivers.socket_driver import SocketDriver
    from fluidframework_tpu.loader import ConnectionManager, Loader
    from fluidframework_tpu.runtime import ChannelRegistry

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    storage = str(tmp_path / "srv")

    def spawn(port=0):
        proc = subprocess.Popen(
            [sys.executable,
             os.path.join(repo, "tools", "socket_server_main.py"),
             str(port), "--storage-dir", storage, "--allow-anonymous"],
            stdout=subprocess.PIPE, text=True,
            env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=repo,
        )
        line = proc.stdout.readline().strip()
        assert line.startswith("LISTENING"), line
        _, host, p = line.split()
        return proc, host, int(p)

    proc, host, port = spawn()
    proc2 = None
    registry = ChannelRegistry([MapFactory(), StringFactory()])
    try:
        driver = FaultInjectionDriver(SocketDriver(host, port))
        loader = Loader(driver, registry)
        c1 = loader.create_detached()
        c1.runtime.create_datastore("default").create_channel(
            "s", StringFactory.type_name
        )
        doc = c1.attach()
        cm = ConnectionManager(
            c1, max_attempts=12, base_delay=0.05, max_delay=0.5,
            jitter=0.2, seed=13,
        )
        s1 = c1.runtime.get_datastore("default").get_channel("s")
        s1.insert_text(0, "before")
        c1.flush()

        def wait_clean(deadline_s=10.0):
            # Bounded condition poll (not a wall-clock sleep): the op
            # is ack'd round-trip once the runtime is no longer dirty,
            # which is exactly when the durable journal has it.
            deadline = time.time() + deadline_s
            while c1.runtime.is_dirty and time.time() < deadline:
                time.sleep(0.02)
            assert not c1.runtime.is_dirty, "op never became durable"

        wait_clean()

        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=10)
        s1.insert_text(0, "down:")  # pending while the service is dead
        proc2, _, _ = spawn(port)  # same port: clients reconnect blind

        deadline = time.time() + 20
        while not c1.connected and time.time() < deadline:
            time.sleep(0.05)
        assert c1.connected, f"reconnect failed (delays={cm.delays})"
        assert cm.delays, "the ladder must actually have backed off"
        c1.flush()
        wait_clean(20.0)

        c2 = Loader(SocketDriver(host, port), registry).resolve(doc)
        s2 = c2.runtime.get_datastore("default").get_channel("s")
        assert s2.get_text() == "down:before"
        assert s1.get_text() == "down:before"
        assert not c1.runtime.is_dirty
    finally:
        for p in (proc, proc2):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait(timeout=10)


def test_supervisor_restarts_stalled_child(tmp_path):
    """A live-but-wedged child (stale heartbeat) is killed and
    restarted — the second failure-detection signal next to process
    exit."""
    import signal

    sup = ServiceSupervisor(
        str(tmp_path), roles=("scribe",), ttl_s=0.4,
        heartbeat_timeout_s=1.0,
    ).start()
    try:
        proc = sup.procs["scribe"]
        deadline = time.time() + 5
        while sup._heartbeat_age("scribe") > 0.5 and time.time() < deadline:
            time.sleep(0.05)
        os.kill(proc.pid, signal.SIGSTOP)
        deadline = time.time() + 15
        while not sup.poll_once() and time.time() < deadline:
            time.sleep(0.1)
        assert sup.restarts["scribe"] == 1
        assert any("stale-heartbeat" in e for e in sup.events)
        assert sup.procs["scribe"].pid != proc.pid
    finally:
        sup.stop()


def test_supervised_farm_processes_after_restart(tmp_path):
    """End-to-end continuity: kill the sequencer AFTER it has
    checkpointed some work, feed more, and the restarted child resumes
    from the checkpoint (no reset, no gap, no dup)."""
    shared = str(tmp_path)
    sup = ServiceSupervisor(shared, ttl_s=0.4, batch=8).start()
    raw = SharedFileTopic(os.path.join(shared, "topics", "rawdeltas.jsonl"))
    durable = SharedFileTopic(os.path.join(shared, "topics", "durable.jsonl"))

    def wait_ops(n, timeout=30):
        deadline = time.time() + timeout
        while time.time() < deadline:
            sup.poll_once()
            ops = [r for r in durable.read_from(0)
                   if isinstance(r, dict) and r.get("kind") == "op"]
            if len(ops) >= n:
                return ops
            time.sleep(0.05)
        raise AssertionError(
            f"timed out waiting for {n} durable ops: {sup.events}"
        )

    try:
        raw.append_many(
            [{"kind": "join", "doc": "d", "client": 1}]
            + [{"kind": "op", "doc": "d", "client": 1,
                "clientSeq": i + 1, "refSeq": 0, "contents": i}
               for i in range(10)]
        )
        wait_ops(11)
        sup.procs["deli"].kill()
        raw.append_many(
            [{"kind": "op", "doc": "d", "client": 1,
              "clientSeq": i + 1, "refSeq": 0, "contents": i}
             for i in range(10, 20)]
        )
        ops = wait_ops(21)
        seqs = sorted(r["seq"] for r in ops)
        assert seqs == list(range(1, 22)), seqs
        assert sup.restarts["deli"] >= 1
    finally:
        sup.stop()


# ---------------------------------------------------------------------------
# front door + autoscale (ISSUE 12 acceptance gates)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_front_door_autoscale_storm_split_converges(tmp_path):
    """THE front-door acceptance gate: kernel x columnar ELASTIC
    fabric with the supervised admission ingress, per-partition
    downstream stages and the load-driven autoscale policy all on,
    kill faults landing on workers AND the front door, boxcars in
    flight — a POLICY-driven split must fire mid-stream, every
    unauthorized/oversized submit must be nacked-never-sequenced
    (exactly once, across the ingress kill), and the merged stream
    plus both downstream legs must converge bit-identical with zero
    dup/skip."""
    res = run_chaos(ChaosConfig(
        seed=12, faults=("kill",), n_docs=2, n_clients=3,
        ops_per_client=24, boxcar_rate=0.35, timeout_s=300.0,
        deli_impl="kernel", log_format="columnar",
        n_partitions=2, n_workers=2, elastic=True,
        ingress=True, autoscale=True, downstream="split",
        shared_dir=str(tmp_path),
    ))
    assert res.converged, res.detail
    assert res.duplicate_seqs == 0 and res.skipped_seqs == 0
    # A LOAD-driven topology change actually fired mid-stream.
    assert res.autoscale_actions > 0 and len(res.epochs) > 1, res.detail
    # The nack taxonomy on the wire: tampered/oversized/unknown-tenant
    # submits all rejected, never sequenced, exactly once each.
    assert res.never_sequenced_ok
    assert res.ingress_nacks.get("auth", 0) >= 2
    assert res.ingress_nacks.get("size", 0) >= 1
    # Downstream legs bit-identical through the policy split + kills.
    assert res.downstream_ok


@pytest.mark.chaos
def test_front_door_overload_throttle_retry_converges(tmp_path):
    """The overload episode: a small per-partition backlog budget
    forces throttle nacks mid-storm; the feeder retries per the
    client contract and the stream still converges bit-identical —
    overload degrades visibly, never unboundedly and never lossily."""
    res = run_chaos(ChaosConfig(
        seed=5, faults=(), n_docs=2, n_clients=3, ops_per_client=20,
        n_partitions=2, n_workers=2, timeout_s=240.0,
        ingress=True, ingress_backlog=6,
        shared_dir=str(tmp_path),
    ))
    assert res.converged, res.detail
    assert res.ingress_nacks.get("backpressure", 0) > 0, res.detail
    assert res.throttle_retries > 0
    assert res.never_sequenced_ok
