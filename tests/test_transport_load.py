"""16-process TCP load test: sustained throughput without reordering.

The verdict's transport gate: 16 client PROCESSES submit boxcarred op
batches through the socket service concurrently; the sequenced stream
must preserve every client's FIFO order (deli's clientSeq contract)
and aggregate ingest must sustain >= 10k ops/s end-to-end through the
real pipeline (alfred ingress -> deli -> scriptorium/broadcaster).
"""

import json
import os
import subprocess
import sys
import time

from fluidframework_tpu.drivers.socket_driver import SocketDriver
from fluidframework_tpu.protocol.messages import MessageType

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import sys, time
sys.path.insert(0, %(repo)r)
from fluidframework_tpu.drivers.socket_driver import _SocketConnection
from fluidframework_tpu.protocol.messages import DocumentMessage, MessageType

conn = _SocketConnection(%(host)r, %(port)d, %(doc)r, None)
n_ops, batch = %(n_ops)d, %(batch)d
print("READY", flush=True)
import os
while not os.path.exists(%(go_path)r):
    time.sleep(0.05)  # barrier: submit only once every worker is up
t0 = time.perf_counter()
cseq = 0
for lo in range(0, n_ops, batch):
    msgs = []
    for i in range(lo, min(lo + batch, n_ops)):
        cseq += 1
        msgs.append(DocumentMessage(
            client_seq=cseq, ref_seq=conn.join_seq, type=MessageType.OP,
            contents={"w": conn.client_id, "i": i},
        ))
    conn.submit_batch(msgs)
dt = time.perf_counter() - t0
print(f"WORKER {conn.client_id} {n_ops} {dt:.3f}", flush=True)
conn.disconnect()
"""


def _run_load_once(doc_id: str) -> float:
    """One 16-process load run against a fresh service; asserts the
    ORDERING contract unconditionally and returns the measured rate
    (the caller owns the throughput-bar policy)."""
    from fluidframework_tpu.server import LocalServer
    from fluidframework_tpu.server.socket_service import SocketDeltaServer

    srv = SocketDeltaServer(
        LocalServer(), port=0, allow_anonymous=True
    ).start()
    try:
        n_procs, n_ops, batch = 16, 1500, 500
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        import tempfile

        go_path = os.path.join(tempfile.mkdtemp(), "go")
        t0 = time.perf_counter()
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", WORKER % {
                    "repo": REPO, "host": srv.host, "port": srv.port,
                    "n_ops": n_ops, "batch": batch, "go_path": go_path,
                    "doc": doc_id,
                }],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True, env=env, cwd=REPO,
            )
            for _ in range(n_procs)
        ]
        for p in procs:
            line = p.stdout.readline().strip()
            assert line == "READY", line
        with open(go_path, "w") as f:
            f.write("go")
        outs = [p.communicate(timeout=180) for p in procs]
        elapsed = time.perf_counter() - t0
        for p, (out, err) in zip(procs, outs):
            assert p.returncode == 0, err[-800:]
            assert out.strip().startswith("WORKER"), (out, err[-400:])

        total = n_procs * n_ops

        # Verify: complete, per-client FIFO, globally sequenced.
        driver = SocketDriver(srv.host, srv.port)
        ops = driver.ops_from(doc_id, 0)
        data_ops = [m for m in ops if m.type == MessageType.OP]
        assert len(data_ops) == total, (len(data_ops), total)
        last_seq = 0
        per_client = {}
        for m in data_ops:
            assert m.sequence_number > last_seq  # total order, no dups
            last_seq = m.sequence_number
            w = m.contents["w"]
            assert m.contents["i"] == per_client.get(w, -1) + 1, (
                f"client {w} reordered"
            )
            per_client[w] = m.contents["i"]
        assert len(per_client) == n_procs
        assert all(v == n_ops - 1 for v in per_client.values())
        # Sustained ingest rate: first to last sequencing timestamp
        # (the service's end-to-end window — client interpreter
        # startup is not transport throughput; total wall reported
        # for context).
        window = data_ops[-1].timestamp - data_ops[0].timestamp
        rate = total / max(window, 1e-9)
        print(
            f"aggregate: {total} ops sequenced over {window:.2f}s = "
            f"{rate:,.0f} ops/s (wall incl. 16 interpreter startups: "
            f"{elapsed:.1f}s)"
        )
        return rate
    finally:
        srv.stop()


def test_16_process_load_no_reordering():
    # Throughput policy: on a multi-core box the 10k bar holds with
    # wide margin; with 17 processes sharing one or two cores the
    # scheduler adds heavy run-to-run variance (measured 4.5-10k ops/s
    # on one core), so the bar scales down rather than encoding one
    # machine's timing. Ordering/completeness asserts are UNGATED
    # either way. Up to TWO retries absorb scheduler outliers (one
    # retry still tripped ~1/30 runs on a contended 2-core CI box) —
    # a genuine throughput regression fails all three runs.
    cores = os.cpu_count() or 1
    bar = 10_000 if cores >= 4 else (4_000 if cores >= 2 else 3_000)
    rate = _run_load_once("loaddoc")
    for attempt in (2, 3):
        if rate >= bar:
            break
        print(f"below the {bar} bar at {rate:,.0f} ops/s; retry "
              f"{attempt - 1} to rule out a scheduler outlier")
        rate = max(rate, _run_load_once(f"loaddoc{attempt}"))
    assert rate >= bar, f"{rate:,.0f} ops/s below the {bar} bar (x3)"
