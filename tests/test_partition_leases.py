"""Multi-node coordination: ordering-log seam + partition leases.

The reference splits the document space across server pods via Kafka
partitions, with ZooKeeper arbitrating consumer ownership (SURVEY.md
§2.5 ⚙️). Here two OS processes — `server.shard_fabric.ShardWorker`
nodes via the tools/partition_worker_main.py wrapper — coordinate only
through a shared directory: each leases its fair share of partitions
and runs the production deli role per owned partition
(``rawdeltas-p{k}`` → ``deltas-p{k}``); killing one lets the
survivor's sweep take the expired leases over, restore the fenced
checkpoint, and resume EXACTLY once — per-document sequence numbers
contiguous across the ownership change, no duplicate (client,
clientSeq) ever sequenced twice (the fabric's inOff recovery scan —
stronger than the consumer-side dedup the pre-fabric worker needed).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import pytest

from fluidframework_tpu.server.queue import (
    FencedCheckpointStore,
    FencedError,
    LeaseManager,
    SharedFileConsumer,
    SharedFileProducer,
    SharedFileTopic,
    lease_table,
    partition_of,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tools", "partition_worker_main.py")


def _spawn(shared, wid, n_parts, ttl=1.0, max_parts=None):
    cmd = [sys.executable, WORKER, shared, wid, str(n_parts),
           "--ttl", str(ttl)]
    if max_parts is not None:
        cmd += ["--max-partitions", str(max_parts)]
    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=REPO,
    )
    line = proc.stdout.readline().strip()
    assert line == f"READY {wid}", line
    return proc


def _raw_topic(shared, p):
    return SharedFileTopic(
        os.path.join(shared, "topics", f"rawdeltas-p{p}.jsonl")
    )


def _submit_all(shared, n_parts, docs, ops_per_doc, base=0):
    """Joins (first wave only) + ops round-robin across 3 clients;
    returns expected (doc -> set of (client, clientSeq)) map."""
    expect = {}
    for doc in docs:
        topic = _raw_topic(shared, partition_of(doc, n_parts))
        recs = []
        if base == 0:
            recs.extend(
                {"kind": "join", "doc": doc, "client": c}
                for c in (1, 2, 3)
            )
        expect[doc] = set()
        for i in range(base, base + ops_per_doc):
            client, cseq = 1 + (i % 3), i // 3 + 1
            recs.append({
                "kind": "op", "doc": doc, "client": client,
                "clientSeq": cseq, "refSeq": 0, "contents": {"i": i},
            })
            expect[doc].add((client, cseq))
        topic.append_many(recs)
    return expect


def _read_sequenced(shared, n_parts):
    """Merged per-doc op records across every deltas-p{k} topic."""
    out = {}
    for p in range(n_parts):
        path = os.path.join(shared, "topics", f"deltas-p{p}.jsonl")
        if not os.path.exists(path):
            continue
        for m in SharedFileTopic(path).read_from(0):
            if isinstance(m, dict) and m.get("kind") == "op":
                out.setdefault(m["doc"], []).append(m)
    return out


def test_lease_manager_basics(tmp_path):
    # Logical clock throughout: the expiry semantics are tested
    # without wall-clock sleeps, so a loaded machine cannot expire a
    # "live" lease mid-assertion.
    t0 = 1000.0
    a = LeaseManager(str(tmp_path), "A", ttl_s=0.3)
    b = LeaseManager(str(tmp_path), "B", ttl_s=0.3)
    fa = a.try_acquire("p0", now=t0)
    assert fa == 1
    assert b.try_acquire("p0", now=t0 + 0.1) is None  # live foreign lease
    assert a.renew("p0", now=t0 + 0.2)
    fb = b.try_acquire("p0", now=t0 + 0.6)  # expired: takeover
    assert fb == 2  # fencing token advanced on takeover
    assert not a.renew("p0", now=t0 + 0.7)  # deposed
    assert b.owner_of("p0", now=t0 + 0.7) == "B"


def _race_acquire(shared, name, barrier, q):
    lm = LeaseManager(shared, name, ttl_s=10.0)
    barrier.wait()
    q.put((name, lm.try_acquire("p0")))


def test_expired_lease_race_single_winner(tmp_path):
    """The ADVICE.md medium race, closed: N workers racing for the
    SAME expired lease at the same instant — exactly one may win, and
    the fence must advance past the dead owner's (the old read-back
    arbitration let two winners share one fence)."""
    import multiprocessing as mp

    shared = str(tmp_path)
    dead = LeaseManager(shared, "dead", ttl_s=0.01)
    assert dead.try_acquire("p0") == 1
    time.sleep(0.05)  # expire

    q = mp.Queue()
    barrier = mp.Barrier(6)
    procs = [
        mp.Process(target=_race_acquire,
                   args=(shared, f"w{i}", barrier, q))
        for i in range(6)
    ]
    for p in procs:
        p.start()
    for p in procs:
        p.join(timeout=30)
    results = [q.get(timeout=10) for _ in procs]
    winners = [(n, f) for n, f in results if f is not None]
    assert len(winners) == 1, f"multiple lease winners: {winners}"
    assert winners[0][1] == 2  # fence strictly advanced, exactly once


def test_deposed_owner_checkpoint_write_rejected(tmp_path):
    """Two workers across a takeover: the successor's fence binds the
    checkpoint store, and the deposed owner's write RAISES — the
    exactly-once guarantee no longer rests on consumer-side dedup."""
    a = LeaseManager(str(tmp_path), "A", ttl_s=0.05)
    b = LeaseManager(str(tmp_path), "B", ttl_s=10.0)
    fa = a.try_acquire("p0")
    assert fa == 1
    time.sleep(0.1)  # A's lease expires (A crashed / stalled)
    fb = b.try_acquire("p0")
    assert fb == 2

    ckpt = FencedCheckpointStore(str(tmp_path))
    ckpt.save("p0", {"offset": 7}, fence=fb, owner="B")
    # The deposed owner wakes up and tries to roll the state back.
    with pytest.raises(FencedError):
        ckpt.save("p0", {"offset": 3}, fence=fa, owner="A")
    assert ckpt.load("p0")["state"] == {"offset": 7}

    # The topic write path rejects the zombie too — including the
    # pathological equal-fence case (fence binds to its first owner).
    topic = SharedFileTopic(os.path.join(str(tmp_path), "t.jsonl"))
    topic.append({"x": 1}, fence=fb, owner="B")
    with pytest.raises(FencedError):
        topic.append({"x": 2}, fence=fa, owner="A")
    with pytest.raises(FencedError):
        topic.append({"x": 3}, fence=fb, owner="A")
    assert topic.read_from(0) == [{"x": 1}]


def test_lease_expiry_race_under_clock_skew(tmp_path):
    """Satellite: a holder whose heartbeat/renewal stalls past the TTL
    must be fence-rejected on its next append EVEN IF its own clock
    says the lease is live. Logical clocks throughout (seeded, no
    sleeps): A's clock lags — it still believes t0+0.3 — while B's
    leads past the TTL, takes over, and binds the higher fence; A's
    subsequent write and renewal must both lose regardless of what A
    believes the time is."""
    t0 = 1000.0
    a = LeaseManager(str(tmp_path), "A", ttl_s=2.0)
    b = LeaseManager(str(tmp_path), "B", ttl_s=2.0)
    topic = SharedFileTopic(os.path.join(str(tmp_path), "t.jsonl"))
    fa = a.try_acquire("p0", now=t0)
    topic.append({"x": 1}, fence=fa, owner="A")
    # B (clock ahead / A stalled) sees the lease expired: takeover.
    fb = b.try_acquire("p0", now=t0 + 10.0)
    assert fb == fa + 1
    topic.append_many([], fence=fb, owner="B")  # successor binds
    # A wakes with its STALE local clock — the lease looks live to it.
    with pytest.raises(FencedError):
        topic.append({"x": 2}, fence=fa, owner="A")
    assert not a.renew("p0", now=t0 + 0.3)  # deposed, whatever A's clock
    assert topic.read_from(0) == [{"x": 1}]
    # The observer view tells the stale owner from the live one by
    # FENCE, not owner string (the lease_table satellite).
    info = lease_table(str(tmp_path), now=t0 + 10.5)["p0"]
    assert info["owner"] == "B" and info["fence"] == fb


def test_fence_monotonic_across_lease_file_loss(tmp_path):
    """The monotonic counter survives lease-file deletion: a takeover
    after the lease file vanished still advances the fence (no token
    reuse)."""
    a = LeaseManager(str(tmp_path), "A", ttl_s=0.05)
    assert a.try_acquire("p0") == 1
    os.remove(os.path.join(str(tmp_path), "p0.lease"))
    b = LeaseManager(str(tmp_path), "B", ttl_s=0.05)
    assert b.try_acquire("p0") == 2


def test_torn_line_never_crashes_concurrent_reader(tmp_path):
    """Satellite: a consumer polling concurrently with an in-progress
    append must never crash and never mis-parse. A writer thread
    appends; the main thread polls throughout; torn fragments injected
    between appends are sealed by the next append and skipped."""
    import threading

    path = os.path.join(str(tmp_path), "t.jsonl")
    topic = SharedFileTopic(path)
    N = 300
    stop = threading.Event()

    def writer():
        import fcntl

        for i in range(N):
            if i % 50 == 25:
                # A crashed writer's torn remnant (no newline), under
                # the same lock real writers take.
                with open(path, "ab") as f:
                    fcntl.flock(f.fileno(), fcntl.LOCK_EX)
                    f.write(b'{"torn": ')
                    fcntl.flock(f.fileno(), fcntl.LOCK_UN)
            topic.append({"i": i})
        stop.set()

    t = threading.Thread(target=writer)
    t.start()
    seen = []
    consumer = SharedFileConsumer(topic)
    deadline = time.time() + 30
    while time.time() < deadline:
        seen.extend(consumer.poll())  # must never raise
        if stop.is_set() and len(seen) >= N:
            break
    t.join(timeout=10)
    seen.extend(consumer.poll())
    assert [m["i"] for m in seen] == list(range(N))


def test_append_lock_timeout_instead_of_wedging(tmp_path):
    """A stalled (e.g. SIGSTOPped) writer holding the append lock must
    not wedge a bounded caller forever: `lock_timeout_s` raises
    TimeoutError so a takeover successor can have the zombie killed
    (the supervisor's stale-heartbeat role) and retry."""
    import fcntl
    import threading

    topic = SharedFileTopic(os.path.join(str(tmp_path), "t.jsonl"))
    held = threading.Event()
    release = threading.Event()

    def stalled_writer():
        with open(topic.path, "r+b") as f:
            fcntl.flock(f.fileno(), fcntl.LOCK_EX)
            held.set()
            release.wait(10)
            fcntl.flock(f.fileno(), fcntl.LOCK_UN)

    t = threading.Thread(target=stalled_writer)
    t.start()
    assert held.wait(10)
    try:
        with pytest.raises(TimeoutError):
            topic.append_many([{"x": 1}], fence=1, owner="B",
                              lock_timeout_s=0.2)
    finally:
        release.set()
        t.join(timeout=10)
    topic.append_many([{"x": 1}], fence=1, owner="B")  # lock free again
    assert topic.read_from(0) == [{"x": 1}]


def test_torn_final_line_reread_complete_next_poll(tmp_path):
    """A final line lacking its newline is NOT consumed; once the
    writer finishes it, the next poll reads it complete."""
    topic = SharedFileTopic(os.path.join(str(tmp_path), "t.jsonl"))
    topic.append({"i": 0})
    consumer = SharedFileConsumer(topic)
    with open(topic.path, "ab") as f:
        f.write(b'{"i": 1')  # append in progress
    assert consumer.poll() == [{"i": 0}]
    assert consumer.poll() == []  # torn tail invisible
    with open(topic.path, "ab") as f:
        f.write(b'}\n')  # the writer completes
    assert consumer.poll() == [{"i": 1}]


def test_two_workers_split_and_failover(tmp_path):
    """Two fabric worker processes split 4 partitions; killing one
    mid-stream hands its partitions to a replacement with EXACTLY-once
    sequencing across the takeover (contiguous per-doc seqs, no
    duplicate (client, clientSeq) — the fabric's fenced inOff
    recovery, not consumer-side dedup)."""
    shared = str(tmp_path)
    n_parts = 4
    # Two documents in EVERY partition (searched by name so the split
    # and the takeover both have real work regardless of hashing).
    docs = []
    per_part = {p: 0 for p in range(n_parts)}
    i = 0
    while any(c < 2 for c in per_part.values()):
        name = f"doc{i}"
        p = partition_of(name, n_parts)
        if per_part[p] < 2:
            docs.append(name)
            per_part[p] += 1
        i += 1
    ops_per_doc = 120

    # Phase 1: each worker capped at 2 partitions -> a true split.
    wa = _spawn(shared, "A", n_parts, ttl=1.0, max_parts=2)
    time.sleep(0.3)
    wb = _spawn(shared, "B", n_parts, ttl=1.0, max_parts=2)
    expect = _submit_all(shared, n_parts, docs, ops_per_doc)

    wc = None
    try:
        # Let both make progress, then verify the split is real.  Wait
        # for the ownership split too: A alone (capped at 2 parts, but
        # holding half the docs) can hit the progress bar before B has
        # swept up its leases.
        deadline = time.time() + 20
        owners = {}
        while time.time() < deadline:
            seqd = _read_sequenced(shared, n_parts)
            owners = {
                k: v["owner"] for k, v in
                lease_table(os.path.join(shared, "leases")).items()
                if k.startswith("deli-p")
            }
            if (sum(len(v) for v in seqd.values()) >= len(docs) * 30
                    and set(owners.values()) == {"A", "B"}):
                break
            time.sleep(0.1)
        assert set(owners.values()) == {"A", "B"}, owners
        assert sum(1 for o in owners.values() if o == "A") == 2
        a_partitions = {k for k, o in owners.items() if o == "A"}

        # Phase 2: kill A, then submit a second wave for every doc —
        # A's partitions now have pending work only a successor can
        # drain. B stays capped at 2, so a replacement worker C sweeps
        # up the expired leases.
        wa.kill()
        wa.wait(timeout=10)
        second = _submit_all(shared, n_parts, docs, 30, base=ops_per_doc)
        for doc in docs:
            expect[doc] |= second[doc]
        wc = _spawn(shared, "C", n_parts, ttl=1.0)
        deadline = time.time() + 30
        done = False
        got = {}
        while time.time() < deadline:
            seqd = _read_sequenced(shared, n_parts)
            got = {
                doc: {(m["client"], m["clientSeq"]) for m in ms
                      if m.get("clientSeq")}
                for doc, ms in seqd.items()
            }
            if all(got.get(d, set()) >= expect[d] for d in docs):
                done = True
                break
            time.sleep(0.2)
        assert done, {
            d: len(got.get(d, set())) for d in docs
        }

        seqd = _read_sequenced(shared, n_parts)
        for doc, ms in seqd.items():
            # EXACTLY-once: no (client, clientSeq) sequenced twice,
            # and seqs contiguous 1..N (3 join stamps + every op)
            # straight across the ownership change.
            keys = [(m["client"], m["clientSeq"]) for m in ms
                    if m.get("clientSeq")]
            assert len(keys) == len(set(keys)), f"{doc}: replayed ops"
            assert set(keys) == expect[doc]
            seqs = sorted(m["seq"] for m in ms)
            assert seqs == list(range(1, len(seqs) + 1)), (
                f"{doc}: seqs not contiguous across takeover"
            )
        # Ownership of A's partitions actually changed hands.
        owners = lease_table(os.path.join(shared, "leases"))
        moved = [p for p in a_partitions
                 if (owners.get(p) or {}).get("owner") == "C"]
        assert moved, f"no partition visibly changed hands: {owners}"
    finally:
        for proc in (wa, wb, wc):
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)


def test_checkpoint_resume_exact(tmp_path):
    """A worker killed between batches resumes from its fenced
    checkpoint: the successor continues the dead worker's numbering
    exactly (no reset, no gap, no replayed op)."""
    shared = str(tmp_path)
    topic = _raw_topic(shared, 0)
    topic.append_many(
        [{"kind": "join", "doc": "solo", "client": 1}]
        + [{"kind": "op", "doc": "solo", "client": 1, "clientSeq": i + 1,
            "refSeq": 0, "contents": None} for i in range(40)]
    )
    wa = _spawn(shared, "A", 1, ttl=0.8)
    try:
        deadline = time.time() + 15
        while time.time() < deadline:
            seqd = _read_sequenced(shared, 1).get("solo", [])
            if len(seqd) >= 10:
                break
            time.sleep(0.05)
        wa.kill()
        wa.wait(timeout=10)
        topic.append_many(
            [{"kind": "op", "doc": "solo", "client": 1, "clientSeq": i + 1,
              "refSeq": 0, "contents": None} for i in range(40, 80)]
        )
        wb = _spawn(shared, "B", 1, ttl=0.8)
        expected = 81  # 1 join + 80 ops
        deadline = time.time() + 20
        while time.time() < deadline:
            ms = _read_sequenced(shared, 1).get("solo", [])
            if len(ms) >= expected:
                break
            time.sleep(0.1)
        ms = _read_sequenced(shared, 1).get("solo", [])
        assert len(ms) == expected, len(ms)
        keys = [(m["client"], m["clientSeq"]) for m in ms
                if m.get("clientSeq")]
        assert len(keys) == len(set(keys)), "op replayed across takeover"
        seqs = sorted(m["seq"] for m in ms)
        assert seqs == list(range(1, expected + 1)), (
            "takeover reset, duplicated or skipped seqs"
        )
    finally:
        for proc in (wa, wb):
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)
