"""Cross-field move tests: laws, conflict rules, and convergence.

The move op (tree/changeset.py move_op) is the changeset-level form of
the reference's cross-field move machinery
(feature-libraries/sequence-field/moveEffectTable.ts): edits follow
moved subtrees, removes chase moved nodes, competing moves resolve
later-wins, and rebase-created cycles apply as deterministic no-ops.
The fuzz suites here run the verifyChangeRebaser-style laws (TP1
convergence, invert round-trip) over op mixes that include moves.
"""

import copy
import random

import pytest

from fluidframework_tpu.tree import (
    Forest,
    insert_op,
    invert,
    move_op,
    rebase_change,
    remove_op,
    set_value_op,
)
from fluidframework_tpu.tree.forest import make_node


def seeded_forest():
    root = make_node("root")
    root["fields"] = {
        "left": [make_node("n", value=i) for i in range(5)],
        "right": [make_node("n", value=10 + i) for i in range(5)],
    }
    # A nested container under left[0].
    root["fields"]["left"][0]["fields"] = {
        "kids": [make_node("k", value=100 + i) for i in range(3)],
    }
    return Forest(copy.deepcopy(root))


# ------------------------------------------------------------ basics


def test_move_applies_and_inverts():
    f = seeded_forest()
    ch = [move_op([], "left", 1, 2, [], "right", 0)]
    f.apply(ch)
    vals = [n["value"] for n in f.root["fields"]["right"]]
    assert vals == [1, 2, 10, 11, 12, 13, 14]
    assert [n["value"] for n in f.root["fields"]["left"]] == [0, 3, 4]
    f.apply(invert(ch))
    assert [n["value"] for n in f.root["fields"]["left"]] == [0, 1, 2, 3, 4]
    assert [n["value"] for n in f.root["fields"]["right"]] == [
        10, 11, 12, 13, 14
    ]


def test_mutual_moves_cycle_guard_converges():
    """A moves X under Y while B concurrently moves Y under X — a
    would-be containment cycle. Through the sequenced protocol
    (EditManager transform in total order) every replica resolves it
    identically: the later-sequenced move applies as a deterministic
    no-op (apply-time cycle guard) and one containment wins."""
    from fluidframework_tpu.tree.shared_tree import SharedTreeFactory
    from fluidframework_tpu.runtime import ChannelRegistry
    from fluidframework_tpu.testing.mocks import MultiClientHarness

    reg = ChannelRegistry([SharedTreeFactory()])
    h = MultiClientHarness(
        2, reg, channel_types=[("t", SharedTreeFactory.type_name)]
    )
    t0 = h.runtimes[0].get_datastore("default").get_channel("t")
    t1 = h.runtimes[1].get_datastore("default").get_channel("t")
    t0.insert_node([], "items", 0, [
        {"type": "X", "value": "x"}, {"type": "Y", "value": "y"},
    ])
    h.process_all()
    # Concurrent (pre-op frames: X at 0, Y at 1).
    t0.move_node([], "items", 0, 1, [["items", 1]], "kids", 0)  # X under Y
    t1.move_node([], "items", 1, 1, [["items", 0]], "kids", 0)  # Y under X
    h.process_all()
    assert t0.view() == t1.view()

    def count(node):
        return 1 + sum(
            count(c) for cs in node.get("fields", {}).values() for c in cs
        )

    assert count(t0.view()) == 3  # root + X + Y, one inside the other


def test_edit_follows_move():
    """A setValue on a node that base moved lands at the destination."""
    edit = [set_value_op([["left", 0], ["kids", 1]], "X")]
    base = [move_op([], "left", 0, 1, [], "right", 2)]
    f = seeded_forest()
    f.apply(copy.deepcopy(base))
    rebased = rebase_change(edit, base)
    f.apply(rebased)
    moved = f.root["fields"]["right"][2]
    assert moved["fields"]["kids"][1]["value"] == "X"


def test_remove_chases_moved_nodes():
    """A remove overlapping nodes that base moved removes them at the
    destination (removal wins over movement)."""
    rm = [remove_op([], "left", 1, 3)]  # values 1,2,3
    base = [move_op([], "left", 2, 2, [], "right", 1)]  # 2,3 -> right
    f = seeded_forest()
    f.apply(copy.deepcopy(base))
    f.apply(rebase_change(rm, base))
    assert [n["value"] for n in f.root["fields"]["left"]] == [0, 4]
    assert [n["value"] for n in f.root["fields"]["right"]] == [
        10, 11, 12, 13, 14
    ]


def test_competing_moves_later_wins():
    """Both clients move the same node; the later-sequenced move's
    destination wins on every replica (TP1 symmetry)."""
    a = [move_op([], "left", 1, 1, [], "right", 0)]  # earlier
    b = [move_op([], "left", 1, 1, [["left", 0]], "kids", 0)]  # later
    # Order 1: a then b-rebased-over-a.
    f1 = seeded_forest()
    a1 = copy.deepcopy(a)
    f1.apply(a1)
    f1.apply(rebase_change(b, a1, over_first=True))
    # Order 2: b then a-rebased-over-b (a sequenced earlier).
    f2 = seeded_forest()
    b2 = copy.deepcopy(b)
    f2.apply(b2)
    f2.apply(rebase_change(a, b2, over_first=False))
    assert f1.to_json() == f2.to_json()
    kids = f1.root["fields"]["left"][0]["fields"]["kids"]
    assert [n["value"] for n in kids][0] == 1  # later move (b) won


# --------------------------------------------------------------- fuzz


FIELDS = ("left", "right")


def random_change(rng: random.Random, forest: Forest, n_ops: int):
    """Valid ops against `forest` (applied as generated so later ops'
    coordinates are meaningful)."""
    sim = forest.clone()
    out = []
    for _ in range(n_ops):
        kind = rng.choice(["insert", "remove", "set", "move", "move"])
        field = rng.choice(FIELDS)
        children = sim.root["fields"].setdefault(field, [])
        if kind == "insert" or not children:
            content = [make_node("n", value=rng.randint(0, 999))]
            idx = rng.randint(0, len(children))
            op = insert_op([], field, idx, content)
        elif kind == "remove":
            idx = rng.randrange(len(children))
            cnt = rng.randint(1, min(2, len(children) - idx))
            op = remove_op([], field, idx, cnt)
        elif kind == "set":
            idx = rng.randrange(len(children))
            op = set_value_op([[field, idx]], rng.randint(0, 999))
        else:
            idx = rng.randrange(len(children))
            cnt = rng.randint(1, min(2, len(children) - idx))
            dfield = rng.choice(FIELDS)
            dlen = len(sim.root["fields"].setdefault(dfield, []))
            didx = rng.randint(0, dlen)  # pre-op frame gap
            op = move_op([], field, idx, cnt, [], dfield, didx)
        sim.apply([copy.deepcopy(op)])
        out.append(op)
    return out


@pytest.mark.parametrize("seed", range(40))
def test_tp1_convergence_with_moves(seed):
    """apply(A); apply(rebase(B,A)) == apply(B); apply(rebase(A,B))
    with flat cross-field moves in the mix."""
    rng = random.Random(seed)
    start = seeded_forest()
    A = random_change(rng, start, rng.randint(1, 3))
    B = random_change(rng, start, rng.randint(1, 3))

    left = start.clone()
    a1 = copy.deepcopy(A)
    left.apply(a1)
    left.apply(rebase_change(B, a1, over_first=True))

    right = start.clone()
    b1 = copy.deepcopy(B)
    right.apply(b1)
    right.apply(rebase_change(A, b1, over_first=False))

    assert left.to_json() == right.to_json(), f"seed {seed}"


@pytest.mark.parametrize("seed", range(20))
def test_invert_roundtrip_with_moves(seed):
    rng = random.Random(1000 + seed)
    start = seeded_forest()
    A = random_change(rng, start, rng.randint(1, 4))
    f = start.clone()
    applied = copy.deepcopy(A)
    f.apply(applied)
    f.apply(invert(applied))
    assert f.to_json() == start.to_json(), f"seed {seed}"


NESTED_TARGETS = [([], "left"), ([], "right"), ([["left", 0]], "kids")]




def random_nested_change(rng, forest, n_ops):
    from fluidframework_tpu.tree.forest import make_node

    sim = forest.clone()
    out = []
    for _ in range(n_ops):
        kind = rng.choice(["insert", "remove", "set", "move", "move"])
        path, field = rng.choice(NESTED_TARGETS)
        node = sim.node_at(path)
        if node is None:
            continue
        children = node.setdefault("fields", {}).setdefault(field, [])
        if kind == "insert" or not children:
            op = insert_op(path, field, rng.randint(0, len(children)),
                           [make_node("n", value=rng.randint(0, 999))])
        elif kind == "remove":
            idx = rng.randrange(len(children))
            op = remove_op(path, field, idx,
                           rng.randint(1, min(2, len(children) - idx)))
        elif kind == "set":
            op = set_value_op(
                path + [[field, rng.randrange(len(children))]],
                rng.randint(0, 999),
            )
        else:
            idx = rng.randrange(len(children))
            cnt = rng.randint(1, min(2, len(children) - idx))
            dpath, dfield = rng.choice(NESTED_TARGETS)
            dn = sim.node_at(dpath)
            if dn is None:
                continue
            dlen = len(dn.get("fields", {}).get(dfield, []))
            op = move_op(path, field, idx, cnt, dpath, dfield,
                         rng.randint(0, dlen))
        applied = copy.deepcopy(op)
        sim.apply([applied])
        if applied.get("muted"):
            continue  # self-cycle no-op: don't emit
        out.append(op)
    return out


@pytest.mark.parametrize("seed", range(500))
def test_tp1_convergence_nested_moves(seed):
    """TP1 over NESTED paths: moves in/out of subtrees, subtree
    removes chasing move-outs, moves into removed voids, edits
    following moves — the cross-field envelope. Round 4 closed the
    previously pinned 6 diverging seeds (identity moves canonicalize
    to no-ops; attach-gap ties preserve a gap's original adjacency to
    the moved block), so the FULL seed range runs; the remaining
    documented corner (overlapping node claims, needing the
    reference's per-move-id move-effect table) is pinned by
    test_same_field_move_pair_corner."""
    rng = random.Random(seed)
    start = seeded_forest()
    A = random_nested_change(rng, start, rng.randint(1, 3))
    B = random_nested_change(rng, start, rng.randint(1, 3))
    left = start.clone()
    a1 = copy.deepcopy(A)
    left.apply(a1)
    left.apply(rebase_change(B, a1, over_first=True))
    right = start.clone()
    b1 = copy.deepcopy(B)
    right.apply(b1)
    right.apply(rebase_change(A, b1, over_first=False))
    assert left.to_json() == right.to_json(), f"seed {seed}"


def test_shared_tree_move_convergence():
    """Cross-field moves through the production runtime stack: two
    clients, concurrent moves + edits, identical trees."""
    from fluidframework_tpu.tree.shared_tree import SharedTreeFactory
    from fluidframework_tpu.runtime import ChannelRegistry
    from fluidframework_tpu.testing.mocks import MultiClientHarness

    reg = ChannelRegistry([SharedTreeFactory()])
    h = MultiClientHarness(
        2, reg, channel_types=[("t", SharedTreeFactory.type_name)]
    )
    t0 = h.runtimes[0].get_datastore("default").get_channel("t")
    t1 = h.runtimes[1].get_datastore("default").get_channel("t")
    t0.insert_node([], "items", 0, [
        {"type": "n", "value": i} for i in range(6)
    ])
    t0.insert_node([], "done", 0, [{"type": "n", "value": "sentinel"}])
    h.process_all()
    # Concurrent: client0 moves [1:3] to "done"; client1 edits node 2
    # (inside the moved range) and moves node 4 within "items".
    t0.move_node([], "items", 1, 2, [], "done", 0)
    t1.set_value([["items", 2]], "edited")
    t1.move_node([], "items", 4, 1, [], "items", 0)
    h.process_all()
    assert t0.view() == t1.view()
    # The edit followed the move into "done".
    done_vals = [n.get("value") for n in t0.view()["fields"]["done"]]
    assert "edited" in done_vals


def _flat_move(i, c, d):
    return {"type": "move", "path": [], "field": "f", "index": i,
            "count": c, "dst_path": [], "dst_field": "f", "dst_index": d}


def _flat_forest(n=5):
    from fluidframework_tpu.tree.forest import Forest, make_node

    f = Forest()
    f.root = make_node("root")
    f.root.setdefault("fields", {})["f"] = [
        make_node("n", value=i) for i in range(n)
    ]
    return f


def _tp1(A, B, n=5):
    start = _flat_forest(n)
    left = start.clone()
    a1 = copy.deepcopy(A)
    left.apply(a1)
    left.apply(rebase_change(B, a1, over_first=True))
    right = start.clone()
    b1 = copy.deepcopy(B)
    right.apply(b1)
    right.apply(rebase_change(A, b1, over_first=False))
    return left.to_json() == right.to_json()


def test_identity_moves_are_neutral():
    """Identity moves (destination gap touching their own source)
    canonicalize to no-ops: they never shift concurrent attach-gap
    ties (the round-3 pinned divergence class)."""
    for noop in [(0, 1, 0), (0, 1, 1), (2, 2, 2), (2, 2, 3), (2, 2, 4)]:
        for other in [(1, 1, 0), (3, 2, 1), (4, 1, 2), (1, 2, 4)]:
            assert _tp1([_flat_move(*noop)], [_flat_move(*other)]), (
                noop, other
            )
            assert _tp1([_flat_move(*other)], [_flat_move(*noop)]), (
                other, noop
            )


def _pair_sweep(n, counts):
    """Exhaustive same-field single-move TP1 sweep over an n-node
    field; returns (total, diverging)."""
    import itertools

    diverging = 0
    total = 0
    for ai, ac, ad in itertools.product(range(n), counts, range(n + 1)):
        if ai + ac > n or ad > n:
            continue
        for bi, bc, bd in itertools.product(range(n), counts, range(n + 1)):
            if bi + bc > n or bd > n:
                continue
            total += 1
            if not _tp1([_flat_move(ai, ac, ad)],
                        [_flat_move(bi, bc, bd)], n=n):
                diverging += 1
    return total, diverging


def test_same_field_move_pair_corner():
    """Exhaustive same-field single-move pairs over a 5-node field:
    the formerly-pinned corner (competing/interleaved block claims,
    the reference's per-move-id move-effect table role,
    sequence-field/moveEffectTable.ts) is CLOSED — round 5's
    one-frame sequentialization + mutual-containment arbitration +
    traveled-destination follow rules take this from 52/2916
    diverging to ZERO. Any divergence is now a regression."""
    total, diverging = _pair_sweep(5, (1, 2))
    assert total == 2916
    assert diverging == 0, (
        f"same-field move-pair convergence regressed: {diverging}/2916"
    )


def test_same_field_move_pair_wide_sweep():
    """Wider exhaustive sweep: 6-node field, counts up to 3 — covers
    strict-containment and mutual-containment block claims the 5-node
    sweep cannot express. 11,025 pairs, zero divergence."""
    total, diverging = _pair_sweep(6, (1, 2, 3))
    assert total == 11025
    assert diverging == 0, (
        f"wide move-pair convergence regressed: {diverging}/11025"
    )
