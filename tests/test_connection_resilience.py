"""Connection-loss, reconnect-backoff, and repeated-reconnect tests.

Covers the reference's ConnectionManager semantics
(loader/container-loader/src/connectionManager.ts:170): transport
disconnect events propagate to the container, reconnects retry on a
backoff ladder, and pending local ops survive arbitrarily many
reconnect cycles (including resubmissions lost in flight —
client.ts:917 regeneratePendingOp across repeated reconnects).
"""

from __future__ import annotations

import random

import pytest

from fluidframework_tpu.dds import MapFactory, StringFactory
from fluidframework_tpu.drivers import FaultInjectionDriver, LocalDriver
from fluidframework_tpu.loader import ConnectionManager, Loader
from fluidframework_tpu.runtime import ChannelRegistry
from fluidframework_tpu.server import LocalServer

REGISTRY = ChannelRegistry([MapFactory(), StringFactory()])


def make_fault_stack():
    server = LocalServer()
    fdriver = FaultInjectionDriver(LocalDriver(server))
    return Loader(fdriver, REGISTRY), fdriver, server


def seed_container(loader):
    c = loader.create_detached()
    ds = c.runtime.create_datastore("default")
    ds.create_channel("s", StringFactory.type_name)
    ds.create_channel("m", MapFactory.type_name)
    return c


def chan(c, cid="s"):
    return c.runtime.get_datastore("default").get_channel(cid)


def test_driver_disconnect_propagates_to_container():
    loader, fdriver, server = make_fault_stack()
    c1 = seed_container(loader)
    doc = c1.attach()
    events = []
    c1.on("disconnected", lambda: events.append("disconnected"))
    fdriver.disconnect_all()
    assert not c1.connected
    assert events == ["disconnected"]
    # Locally initiated disconnect after transport loss is a no-op.
    c1.disconnect()
    assert events == ["disconnected"]


def test_resubmission_lost_then_second_reconnect_converges():
    """A rebased resubmission dropped in flight must survive the NEXT
    reconnect too (round-1 advisor finding: stale pending-group
    metadata silently dropped the op and replicas diverged)."""
    loader, fdriver, server = make_fault_stack()
    c1 = seed_container(loader)
    doc = c1.attach()
    c2 = loader.resolve(doc)

    # Two separate sequenced segments, so the pending remove spans a
    # multi-segment group and regeneration splits it.
    chan(c1).insert_text(0, "abc")
    c1.flush()
    chan(c1).insert_text(3, "def")
    c1.flush()
    assert chan(c2).get_text() == "abcdef"

    # Pending remove spanning both segments, then: reconnect #1 whose
    # resubmission is dropped in flight, then reconnect #2.
    chan(c1).remove_range(1, 5)
    fdriver.disconnect_all()
    fdriver.drop_submits = True
    c1.connect()
    c1.flush()  # resubmission lost (network partition)
    fdriver.drop_submits = False
    fdriver.disconnect_all()
    c1.connect()
    c2.connect()
    c1.flush()

    assert chan(c1).get_text() == "af"
    assert chan(c2).get_text() == "af"
    assert not c1.runtime.is_dirty
    # No leaked pending groups in the engine.
    assert not chan(c1).engine.pending


def test_annotate_resubmission_survives_repeated_reconnects():
    loader, fdriver, server = make_fault_stack()
    c1 = seed_container(loader)
    doc = c1.attach()
    c2 = loader.resolve(doc)
    chan(c1).insert_text(0, "ab")
    c1.flush()
    chan(c1).insert_text(2, "cd")
    c1.flush()

    chan(c1).annotate_range(1, 3, {"bold": True})
    for _ in range(3):  # several lost resubmissions in a row
        fdriver.disconnect_all()
        fdriver.drop_submits = True
        c1.connect()
        c1.flush()
        fdriver.drop_submits = False
    fdriver.disconnect_all()
    c1.connect()
    c2.connect()
    c1.flush()
    assert chan(c1).annotated_spans() == chan(c2).annotated_spans()
    assert not c1.runtime.is_dirty


def test_connection_manager_backoff_ladder():
    loader, fdriver, server = make_fault_stack()
    c1 = seed_container(loader)
    doc = c1.attach()
    slept = []
    cm = ConnectionManager(c1, base_delay=0.01, max_delay=0.04, sleep=slept.append)

    chan(c1).insert_text(0, "x")
    fdriver.connects_fail_remaining = 3
    fdriver.disconnect_all()
    # The manager retried through the ladder and reconnected.
    assert c1.connected
    assert slept == [0.01, 0.02, 0.04]
    assert slept == cm.delays
    c1.flush()
    c2 = loader.resolve(doc)
    assert chan(c2).get_text() == "x"


def test_backoff_jitter_capped_seeded_deterministic():
    """Satellite: the reconnect ladder with jitter is (a) still capped
    at max_delay, (b) actually jittered away from the bare exponential,
    and (c) bit-reproducible given a seed — chaos runs replay."""
    loader, fdriver, server = make_fault_stack()
    c1 = seed_container(loader)
    c1.attach()

    def ladder(jitter, seed):
        cm = ConnectionManager(
            c1, base_delay=0.05, max_delay=1.0,
            sleep=lambda _: None, jitter=jitter, seed=seed,
        )
        cm.enabled = False  # schedule probing only
        return [cm.delay_for(i) for i in range(10)]

    bare = ladder(0.0, 0)
    assert bare == [min(0.05 * 2 ** i, 1.0) for i in range(10)]
    j1 = ladder(0.25, 42)
    j2 = ladder(0.25, 42)
    j3 = ladder(0.25, 43)
    assert j1 == j2, "same seed must reproduce the exact schedule"
    assert j1 != j3, "different seeds must diverge"
    assert j1 != bare, "jitter must actually perturb the ladder"
    assert all(d <= 1.0 for d in j1), "cap must bind AFTER jitter"
    assert all(
        abs(d - b) <= 0.25 * b + 1e-12 for d, b in zip(j1, bare)
    ), "jitter bounded by ±jitter·delay"


def test_jittered_reconnect_ladder_still_reconnects():
    """The jittered ladder drives a real reconnect to completion and
    records the schedule it used."""
    loader, fdriver, server = make_fault_stack()
    c1 = seed_container(loader)
    doc = c1.attach()
    slept = []
    cm = ConnectionManager(
        c1, base_delay=0.01, max_delay=0.04,
        sleep=slept.append, jitter=0.2, seed=7,
    )
    chan(c1).insert_text(0, "x")
    fdriver.connects_fail_remaining = 3
    fdriver.disconnect_all()
    assert c1.connected
    assert len(slept) == 3 and slept == cm.delays
    assert all(d <= 0.04 for d in slept)
    c1.flush()
    assert chan(loader.resolve(doc)).get_text() == "x"


def test_midbatch_disconnect_resubmission_deduped_exactly_once():
    """Satellite: a batch that DID reach the server but whose acks were
    lost to a mid-batch disconnect must not be double-sequenced — the
    reconnect catch-up acks the pending ops under the old identity, so
    nothing is resubmitted and the server-side op log carries each op
    exactly once."""
    from fluidframework_tpu.drivers import FaultInjectionDriver, LocalDriver
    from fluidframework_tpu.loader import Loader
    from fluidframework_tpu.protocol.messages import MessageType
    from fluidframework_tpu.server import LocalServer

    server = LocalServer(deferred=True)
    fdriver = FaultInjectionDriver(LocalDriver(server))
    loader = Loader(fdriver, REGISTRY)
    c1 = seed_container(loader)
    doc = c1.attach()
    server.process_all()

    baseline = sum(
        1 for m in server.scriptorium.store.get(doc, [])
        if m.type == MessageType.OP
    )
    # The batch reaches the server; the connection dies BEFORE the
    # pump runs, so no acks ever come back (the lost-ack window).
    chan(c1).insert_text(0, "abc")
    chan(c1, "m").set("k", 1)
    c1.flush()
    fdriver.disconnect_all()
    server.process_all()  # sequenced under the old identity

    assert c1.runtime.is_dirty  # client still believes ops are unacked
    c1.connect()  # catch-up acks them; nothing resubmits
    server.process_all()
    c1.flush()
    server.process_all()

    ops = [
        m for m in server.scriptorium.store.get(doc, [])
        if m.type == MessageType.OP
    ]
    assert len(ops) == baseline + 2, (
        f"expected exactly-once sequencing, got {len(ops) - baseline} "
        f"copies of the batch"
    )
    assert not c1.runtime.is_dirty
    c2 = loader.resolve(doc)
    assert chan(c2).get_text() == "abc"
    assert chan(c2, "m").get("k") == 1
    seqs = [m.sequence_number for m in server.scriptorium.store[doc]]
    assert len(seqs) == len(set(seqs))


def test_connection_manager_gives_up_and_reports():
    loader, fdriver, server = make_fault_stack()
    c1 = seed_container(loader)
    c1.attach()
    failures = []
    c1.on("connectionFailure", failures.append)
    ConnectionManager(c1, max_attempts=2, base_delay=0.0, sleep=lambda _: None)
    fdriver.connects_fail_remaining = 99
    fdriver.disconnect_all()
    assert not c1.connected
    assert len(failures) == 1 and isinstance(failures[0], ConnectionError)
    fdriver.connects_fail_remaining = 0
    c1.connect()
    assert c1.connected


def test_stashed_ops_rebase_past_remote_edits():
    """Stashed ops re-apply at the recorded baseSeq perspective, not at
    the caught-up head (round-1 advisor finding: a stashed tail-insert
    landed mid-word after a remote prepend)."""
    loader, fdriver, server = make_fault_stack()
    c1 = seed_container(loader)
    chan(c1).insert_text(0, "hello")
    doc = c1.attach()
    c2 = loader.resolve(doc)

    chan(c1).insert_text(5, "!")  # pending at close
    state = c1.close_and_get_pending_state()

    # Remote edits sequenced AFTER the stash point.
    chan(c2).insert_text(0, "XXX")
    c2.flush()

    c3 = loader.resolve(doc, pending_state=state)
    assert chan(c3).get_text() == "XXXhello!"
    assert chan(c2).get_text() == "XXXhello!"
    assert not c3.is_dirty


def test_stash_includes_pending_attach_op():
    """A dynamically created channel whose attach op was unacked at
    close must reach the resumed session (round-1 advisor finding:
    the attach op was filtered out of the stash)."""
    loader, fdriver, server = make_fault_stack()
    c1 = seed_container(loader)
    doc = c1.attach()
    c2 = loader.resolve(doc)

    ds = c1.runtime.get_datastore("default")
    ch = ds.create_channel("dyn", MapFactory.type_name)
    c1.runtime.submit_attach_op("default", ch)
    ds.attach_channel(ch)
    ch.on_connected()
    ch.set("k", 42)
    state = c1.close_and_get_pending_state()  # attach + set both stashed

    c3 = loader.resolve(doc, pending_state=state)
    assert c3.runtime.get_datastore("default").get_channel("dyn").get("k") == 42
    assert c2.runtime.get_datastore("default").get_channel("dyn").get("k") == 42
    assert not c3.is_dirty


@pytest.mark.parametrize("seed", range(6))
def test_fault_injection_farm(seed):
    """Full-stack convergence farm with random disconnect injection:
    every round each container makes random edits; random clients get
    their connections killed mid-round and reconnect (replaying
    pending ops); all replicas must converge exactly (the reference's
    reconnectFarm + faultInjectionDriver shapes combined)."""
    rng = random.Random(seed)
    loader, fdriver, server = make_fault_stack()
    c0 = seed_container(loader)
    chan(c0).insert_text(0, "seedtext")
    doc = c0.attach()
    containers = [c0] + [loader.resolve(doc) for _ in range(3)]

    for _ in range(12):
        for c in containers:
            for _ in range(rng.randint(0, 3)):
                s = chan(c)
                n = len(s.get_text())
                r = rng.random()
                if r < 0.5 or n == 0:
                    s.insert_text(rng.randint(0, n), rng.choice("abcdef") * rng.randint(1, 3))
                elif r < 0.8:
                    start = rng.randint(0, n - 1)
                    s.remove_range(start, rng.randint(start + 1, min(n, start + 5)))
                else:
                    start = rng.randint(0, n - 1)
                    s.annotate_range(start, rng.randint(start + 1, n), {"b": rng.randint(0, 3)})
            if rng.random() < 0.25:
                c.disconnect()  # voluntary drop with pending ops
            elif rng.random() < 0.15 and c.connected:
                # transport-initiated kill of just this container
                c.runtime.connection.inject_disconnect()
        for c in containers:
            if not c.connected and not c.closed:
                c.connect()
            c.flush()

    texts = [chan(c).get_text() for c in containers]
    assert len(set(texts)) == 1, f"divergence (seed={seed}): {texts}"
    spans = [chan(c).annotated_spans() for c in containers]
    assert all(s == spans[0] for s in spans)
    for c in containers:
        assert not c.runtime.is_dirty
