"""Multi-chip sharding validation on the virtual 8-device CPU mesh.

Exercises the same path the driver validates via
`__graft_entry__.dryrun_multichip`: the full multi-document pipeline
step jitted over an 8-device `jax.sharding.Mesh` (documents sharded,
MSN/error reduced across devices over ICI-style collectives).
"""

from __future__ import annotations

import jax
import pytest


def test_dryrun_multichip_8():
    # dryrun_multichip seals its own platform (subprocess with
    # JAX_PLATFORMS=cpu + 8 virtual host devices), so this never skips
    # regardless of how many devices the test process sees.
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)


def test_dryrun_impl_inline_on_virtual_mesh():
    # Under conftest the test process itself has 8 virtual CPU
    # devices; exercise the inner body directly too (no subprocess).
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    import __graft_entry__

    __graft_entry__._dryrun_impl(8)


def test_graft_entry_compiles():
    import __graft_entry__

    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    assert int(out.n_rows) > 1
    assert int(out.error) == 0
