"""Multi-chip sharding validation on the virtual 8-device CPU mesh.

Exercises the same path the driver validates via
`__graft_entry__.dryrun_multichip`: the full multi-document pipeline
step jitted over an 8-device `jax.sharding.Mesh` (documents sharded,
MSN/error reduced across devices over ICI-style collectives).
"""

from __future__ import annotations

import jax
import pytest


@pytest.mark.slow
def test_dryrun_multichip_8():
    # dryrun_multichip seals its own platform (subprocess with
    # JAX_PLATFORMS=cpu + 8 virtual host devices), so this never skips
    # regardless of how many devices the test process sees. Full
    # scale and a fresh interpreter make it minutes on a small CPU
    # host — slow-marked; tier-1 covers the identical body inline
    # below (and the driver exercises this exact entry point for its
    # MULTICHIP validation).
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)


def test_dryrun_impl_inline_on_virtual_mesh():
    # Under conftest the test process itself has 8 virtual CPU
    # devices; exercise the inner body directly (no subprocess), at
    # reduced stream scale — every section and digest contract of the
    # full dry run, sized for the tier-1 budget on CPU hosts.
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    import __graft_entry__

    __graft_entry__._dryrun_impl(8, scale=0.25)


def test_graft_entry_compiles():
    import __graft_entry__

    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    assert int(out.n_rows) > 1
    assert int(out.error) == 0


def test_sharded_overlay_replay_digest_equality_4dev():
    """The flagship overlay engine doc-sharded over a 4-device mesh:
    per-document digests must equal independent single-device fused
    replays (the north-star bit-identity contract on the mesh), and
    the MSN min-reduce must ride the mesh axis."""
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 (virtual) devices")
    import numpy as np

    from fluidframework_tpu.core.overlay_replay import (
        OverlayDeviceReplica,
        restore_shard,
        stack_replicas,
    )
    from fluidframework_tpu.parallel import (
        make_docs_mesh,
        sharded_overlay_replay,
    )
    from fluidframework_tpu.testing.digest import state_digest
    from fluidframework_tpu.testing.synthetic import generate_lagged_stream

    n_dev, n_ops, chunk, window = 4, 256, 64, 1024
    mesh = make_docs_mesh(n_dev)
    step = sharded_overlay_replay(mesh, chunk, interpret=True)
    streams = [
        generate_lagged_stream(
            n_ops, n_clients=6, seed=200 + d, window=48, initial_len=12
        )
        for d in range(n_dev)
    ]

    def make_rep(s):
        return OverlayDeviceReplica(
            s, initial_len=12, chunk_size=chunk, window=window,
            n_removers=10, interpret=True,
        )

    reps = [make_rep(s) for s in streams]
    for r in reps:
        r.prepare()
    tables, ops, logs, counts, msns = stack_replicas(reps)

    out_tables, out_logs, out_counts, cursors, gmsn, gerr = step(
        tables, ops, logs, counts, msns
    )
    assert int(gerr) == 0
    assert int(gmsn) == min(int(m[-1]) for m in np.asarray(msns))
    for d, (s, ref) in enumerate(zip(streams, reps)):
        ref.replay()
        ref.check_errors()
        sharded = restore_shard(
            make_rep(s), out_tables, out_logs, out_counts, cursors, d
        )
        assert state_digest(sharded.annotated_spans()) == state_digest(
            ref.annotated_spans()
        )
