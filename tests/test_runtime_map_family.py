"""Runtime stack + map-family DDS tests.

Convergence and conflict-policy tests for SharedMap/SharedDirectory/
SharedCell/SharedCounter running through the real ContainerRuntime →
DataStoreRuntime → channel seam over the in-proc ordering service
(the reference's mock-runtime DDS unit layer, SURVEY.md §4, plus
map-specific cases after packages/dds/map/src/test/map.spec.ts).
"""

from __future__ import annotations

import pytest

from fluidframework_tpu.dds import (
    CellFactory,
    CounterFactory,
    DirectoryFactory,
    MapFactory,
)
from fluidframework_tpu.runtime import ChannelRegistry, FlushMode
from fluidframework_tpu.testing.mocks import MultiClientHarness

REGISTRY = ChannelRegistry(
    [MapFactory(), DirectoryFactory(), CellFactory(), CounterFactory()]
)


def make_harness(n=2, channels=(("m", MapFactory.type_name),), **kw):
    return MultiClientHarness(n, REGISTRY, channel_types=list(channels), **kw)


# ---------------------------------------------------------------- SharedMap


def test_map_basic_set_get_converges():
    h = make_harness()
    a, b = h.channel(0, "m"), h.channel(1, "m")
    a.set("k", 1)
    b.set("other", "x")
    h.process_all()
    for m in (a, b):
        assert m.get("k") == 1
        assert m.get("other") == "x"


def test_map_concurrent_set_last_sequenced_wins():
    h = make_harness()
    a, b = h.channel(0, "m"), h.channel(1, "m")
    a.set("k", "from-a")
    b.set("k", "from-b")
    # a's op sequences first (flush order), so b's wins everywhere.
    h.process_all()
    assert a.get("k") == "from-b"
    assert b.get("k") == "from-b"


def test_map_pending_local_shadows_remote():
    h = make_harness()
    a, b = h.channel(0, "m"), h.channel(1, "m")
    a.set("k", "a1")
    h.process_all()
    # b writes and its op is sequenced; a has a new pending write that
    # must shadow b's sequenced value until a's own op lands.
    b.set("k", "b1")
    h.runtimes[1].flush()
    a.set("k", "a2")  # pending at a
    h.service.process_all()  # delivers b's op only
    assert a.get("k") == "a2"  # shadowed (mapKernel pending rule)
    h.process_all()  # now a's op sequences after b's: a2 wins
    assert a.get("k") == "a2"
    assert b.get("k") == "a2"


def test_map_delete_and_clear():
    h = make_harness()
    a, b = h.channel(0, "m"), h.channel(1, "m")
    a.set("x", 1)
    a.set("y", 2)
    h.process_all()
    b.delete("x")
    h.process_all()
    assert not a.has("x") and a.get("y") == 2
    a.clear()
    h.process_all()
    assert len(a) == 0 and len(b) == 0


def test_map_remote_clear_reapplies_pending_local():
    h = make_harness()
    a, b = h.channel(0, "m"), h.channel(1, "m")
    b.clear()
    h.runtimes[1].flush()
    a.set("k", "local")  # pending at a when the clear arrives
    h.service.process_all()
    assert a.get("k") == "local"  # survived the remote clear
    h.process_all()
    assert b.get("k") == "local"  # and wins globally once sequenced


# ------------------------------------------------------------ SharedDirectory


def test_directory_subdirs_and_values_converge():
    h = make_harness(channels=(("d", DirectoryFactory.type_name),))
    a, b = h.channel(0, "d"), h.channel(1, "d")
    a.set("root-key", 1)
    sub = a.create_subdirectory("sub")
    sub.set("inner", "v")
    nested = sub.create_subdirectory("nested")
    nested.set("deep", [1, 2])
    h.process_all()
    for d in (a, b):
        assert d.get("root-key") == 1
        w = d.get_working_directory("/sub")
        assert w.get("inner") == "v"
        assert d.get_working_directory("/sub/nested").get("deep") == [1, 2]


def test_directory_delete_subdirectory():
    h = make_harness(channels=(("d", DirectoryFactory.type_name),))
    a, b = h.channel(0, "d"), h.channel(1, "d")
    a.create_subdirectory("gone").set("k", 1)
    h.process_all()
    b.root.delete_subdirectory("gone")
    h.process_all()
    assert a.get_subdirectory("gone") is None
    assert b.get_subdirectory("gone") is None


# ---------------------------------------------------------------- SharedCell


def test_cell_lww_and_pending_shadow():
    h = make_harness(channels=(("c", CellFactory.type_name),))
    a, b = h.channel(0, "c"), h.channel(1, "c")
    a.set("first")
    h.process_all()
    assert b.get() == "first"
    b.set("second")
    h.runtimes[1].flush()
    a.set("third")
    h.service.process_all()
    assert a.get() == "third"  # pending local shadows b's sequenced op
    h.process_all()
    assert a.get() == "third" and b.get() == "third"
    a.delete()
    h.process_all()
    assert a.is_empty and b.is_empty


# -------------------------------------------------------------- SharedCounter


def test_counter_concurrent_increments_sum():
    h = make_harness(n=3, channels=(("n", CounterFactory.type_name),))
    cs = [h.channel(i, "n") for i in range(3)]
    cs[0].increment(5)
    cs[1].increment(-2)
    cs[2].increment(10)
    cs[0].increment(1)
    h.process_all()
    assert [c.value for c in cs] == [14, 14, 14]


def test_counter_rejects_non_int():
    h = make_harness(channels=(("n", CounterFactory.type_name),))
    with pytest.raises(TypeError):
        h.channel(0, "n").increment(1.5)


# ------------------------------------------------------------ runtime behavior


def test_immediate_flush_mode():
    h = make_harness(flush_mode=FlushMode.IMMEDIATE)
    a, b = h.channel(0, "m"), h.channel(1, "m")
    a.set("k", 1)
    # No explicit flush: immediate mode already submitted.
    h.service.process_all()
    assert b.get("k") == 1


def test_batch_atomicity_metadata():
    """A turn's ops travel as one marked batch and apply back-to-back
    (outbox.ts:40 batch markers; scheduleManager.ts:99 atomicity)."""
    h = make_harness()
    a = h.channel(0, "m")
    a.set("x", 1)
    a.set("y", 2)
    a.set("z", 3)
    h.runtimes[0].flush()
    log = h.service.op_log[h.doc_id]
    batch_msgs = [m for m in log if isinstance(m.contents, dict)]
    metas = [m.metadata for m in batch_msgs[-3:]]
    # Key-based checks: metadata also carries the op-lifecycle trace
    # stamp ("tr_sub"); the batch-marker contract is the KEY, readers
    # ignore the rest (outbox.ts:40 semantics).
    assert metas[0]["batch"] is True
    assert "batch" not in metas[1]
    assert metas[2]["batch"] is False
    # One flush == one submit instant: all three share the stamp.
    assert metas[0]["tr_sub"] == metas[1]["tr_sub"] == metas[2]["tr_sub"]
    h.process_all()
    assert h.channel(1, "m").get("z") == 3


def test_runtime_is_dirty_tracking():
    h = make_harness()
    rt = h.runtimes[0]
    a = h.channel(0, "m")
    assert not rt.is_dirty
    a.set("k", 1)
    assert rt.is_dirty  # in outbox
    rt.flush()
    assert rt.is_dirty  # pending ack
    h.process_all()
    assert not rt.is_dirty


def test_pending_echo_mismatch_asserts():
    h = make_harness()
    rt = h.runtimes[0]
    a = h.channel(0, "m")
    a.set("k", 1)
    rt.flush()
    # Corrupt the pending queue to simulate a lost op.
    rt._pending.clear()
    with pytest.raises(AssertionError):
        h.service.process_all()


# ------------------------------------------------------- summarize/load boot


def test_container_summarize_and_load_roundtrip():
    h = make_harness(
        channels=(
            ("m", MapFactory.type_name),
            ("d", DirectoryFactory.type_name),
            ("c", CellFactory.type_name),
            ("n", CounterFactory.type_name),
        )
    )
    a = h.channel(0, "m")
    a.set("k", {"nested": True})
    h.channel(0, "d").create_subdirectory("s").set("i", 7)
    h.channel(0, "c").set("cv")
    h.channel(0, "n").increment(3)
    h.process_all()

    summary = h.runtimes[0].summarize()
    wire = summary.to_json()

    from fluidframework_tpu.runtime import ContainerRuntime
    from fluidframework_tpu.runtime.summary import SummaryTree

    rt = ContainerRuntime(REGISTRY)
    rt.load(SummaryTree.from_json(wire))
    ds = rt.get_datastore("default")
    assert ds.get_channel("m").get("k") == {"nested": True}
    assert (
        ds.get_channel("d").get_working_directory("/s").get("i") == 7
    )
    assert ds.get_channel("c").get() == "cv"
    assert ds.get_channel("n").value == 3
    assert rt.current_seq == h.runtimes[0].current_seq

    # The loaded container can join the session and keep collaborating.
    conn = h.service.connect(h.doc_id, client_id=99)
    rt.connect(conn)
    ds.get_channel("n").increment(10)
    rt.flush()
    h.process_all()
    assert ds.get_channel("n").value == 13
    assert h.channel(1, "n").value == 13
