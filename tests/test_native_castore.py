"""Native (C++) content-addressed store: parity with the Python store.

The castore.cpp backend (ctypes-bound, the libgit2-role native
component) must produce byte-identical digests and behavior to the
pure-Python fallback.
"""

from __future__ import annotations

import hashlib

import pytest

from fluidframework_tpu.native import load_castore, NativeContentStore
from fluidframework_tpu.server.castore import ContentAddressedStore, _PyStore

NATIVE = load_castore()


@pytest.mark.skipif(NATIVE is None, reason="no C++ toolchain")
def test_native_digest_matches_hashlib():
    s = NativeContentStore(NATIVE)
    for payload in (b"", b"x", b"hello world", bytes(range(256)) * 999):
        key = s.put(payload)
        assert key == hashlib.sha256(payload).hexdigest()
        assert s.get(key) == payload
        assert s.contains(key)
    assert not s.contains("0" * 64)
    with pytest.raises(KeyError):
        s.get("0" * 64)


@pytest.mark.skipif(NATIVE is None, reason="no C++ toolchain")
def test_native_refs_and_parity_with_python():
    n = NativeContentStore(NATIVE)
    p = _PyStore()
    blobs = [b"summary-1", b"summary-2" * 1000, "unicode é中".encode()]
    for b in blobs:
        assert n.put(b) == p.put(b)
    k = hashlib.sha256(blobs[0]).hexdigest()
    n.set_ref("docA", k)
    p.set_ref("docA", k)
    assert n.get_ref("docA") == p.get_ref("docA") == k
    assert n.get_ref("nope") is None and p.get_ref("nope") is None
    with pytest.raises(KeyError):
        n.set_ref("docB", "f" * 64)
    n.set_ref("docB", n.put(b"another"))
    assert n.list_refs() == ["docA", "docB"]


def test_store_facade_reports_backend():
    s = ContentAddressedStore()
    assert s.backend in ("native", "python")
    key = s.put("facade blob")
    assert s.get(key) == b"facade blob"
    s2 = ContentAddressedStore(prefer_native=False)
    assert s2.backend == "python"
    assert s2.put("facade blob") == key  # identical digests across backends


def test_server_uses_store_transparently():
    from fluidframework_tpu.server import LocalServer

    srv = LocalServer()
    handle = srv.upload_summary('{"type": "tree", "entries": {}}')
    srv.storage.set_ref("d", handle)
    assert srv.download_summary("d") == '{"type": "tree", "entries": {}}'
