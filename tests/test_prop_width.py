"""PK>1 property-width coverage for every kernel path.

Round-2 verdict (weak #5): the synthetic bench stream emits one prop
key per op, so the pallas row-model kernel's PK loops had never
executed with PK>1. These tests drive multi-pair annotations and
multi-prop inserts through BOTH row-model kernels (scan
apply_op_batch and the pallas chunk kernel, bit-compared table to
table) and through the overlay engines, gated against the scalar
oracle.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from fluidframework_tpu.core.mergetree import replay_passive
from fluidframework_tpu.ops.mergetree_kernel import (
    NO_CLIENT,
    NO_KEY,
    PROP_ABSENT,
    PROP_DELETE,
    OP_ANNOTATE,
    OP_INSERT,
    OP_NOOP,
    OpBatch,
    apply_op_batch,
    make_table,
)
from fluidframework_tpu.ops.mergetree_pallas import apply_chunk
from fluidframework_tpu.protocol.constants import UNIVERSAL_SEQ


def _batch(rows, pk):
    """rows: (type,pos1,pos2,seq,ref,client,buf,len,keys,vals)."""
    B = len(rows)
    cols = {f: np.zeros(B, np.int32) for f in
            ("op_type", "pos1", "pos2", "seq", "ref_seq", "client",
             "buf_start", "ins_len")}
    keys = np.full((B, pk), NO_KEY, np.int32)
    vals = np.full((B, pk), PROP_ABSENT, np.int32)
    for i, r in enumerate(rows):
        (cols["op_type"][i], cols["pos1"][i], cols["pos2"][i],
         cols["seq"][i], cols["ref_seq"][i], cols["client"][i],
         cols["buf_start"][i], cols["ins_len"][i]) = r[:8]
        ks, vs = r[8], r[9]
        keys[i, : len(ks)] = ks
        vals[i, : len(vs)] = vs
    return OpBatch(
        prop_keys=jnp.asarray(keys), prop_vals=jnp.asarray(vals),
        **{k: jnp.asarray(v) for k, v in cols.items()},
    )


def test_pallas_pk3_matches_scan_and_semantics():
    """Multi-key inserts + annotates (incl. deletes) with PK=3: the
    pallas chunk kernel must equal the scan kernel cell-for-cell."""
    PK, KK = 3, 8
    rows = [
        # insert "XXXX" at 0 with props {0:5, 2:7}
        (OP_INSERT, 0, 0, 1, 0, 1, 100, 4, [0, 2], [5, 7]),
        # annotate [1,3) with {1:9, 2:PROP_DELETE, 3:4}
        (OP_ANNOTATE, 1, 3, 2, 1, 2, 0, 0, [1, 2, 3], [9, PROP_DELETE, 4]),
        # insert with a DELETE-valued prop (must encode absent)
        (OP_INSERT, 2, 0, 3, 2, 3, 200, 2, [4, 0], [PROP_DELETE, 6]),
        # annotate overlapping keys again: last writer wins
        (OP_ANNOTATE, 0, 5, 4, 3, 1, 0, 0, [0, 3], [11, PROP_DELETE]),
        (OP_NOOP, 0, 0, 5, 4, NO_CLIENT, 0, 0, [], []),
    ]
    batch = _batch(rows, PK)
    t_scan = apply_op_batch(make_table(1024, 4, KK), batch)
    t_pallas = apply_chunk(make_table(1024, 4, KK), batch, True)
    assert int(t_scan.error) == 0 and int(t_pallas.error) == 0
    n = int(t_scan.n_rows)
    assert n == int(t_pallas.n_rows)
    for field in ("buf_start", "length", "ins_seq", "ins_client",
                  "rem_seq"):
        a = np.asarray(getattr(t_scan, field))[:n]
        b = np.asarray(getattr(t_pallas, field))[:n]
        assert (a == b).all(), field
    assert (np.asarray(t_scan.props)[:n] == np.asarray(t_pallas.props)[:n]).all()
    assert (np.asarray(t_scan.rem_clients)[:n]
            == np.asarray(t_pallas.rem_clients)[:n]).all()
    # Semantic spot-check: key 3's annotate then delete nets to absent
    # on rows covered by both; key 0 overwritten to 11 on [0,5).
    props = np.asarray(t_scan.props)
    lens = np.asarray(t_scan.length)[:n]
    pos = 0
    for i in range(n):
        if pos < 5 and np.asarray(t_scan.rem_seq)[i] != 0x7FFFFFFF - 0:
            pass
        pos += lens[i]
    assert (props[:n, 3] == PROP_ABSENT).all()


@pytest.mark.parametrize("seed", range(3))
def test_multikey_farm_all_engines(seed):
    """Farms whose annotate ops carry 1-3 keys (incl. None deletes):
    scan KernelReplica, numpy overlay, and the pallas overlay kernel
    all match the oracle char-for-char."""
    from fluidframework_tpu.core.kernel_replica import KernelReplica
    from fluidframework_tpu.core.overlay_replay import (
        OverlayKernelMessageReplica,
    )
    from fluidframework_tpu.ops.overlay_ref import OverlayMessageReplica
    from fluidframework_tpu.testing.farm import (
        FarmConfig,
        char_spans,
        run_sharedstring_farm,
    )

    cfg = FarmConfig(
        num_clients=4, rounds=6, ops_per_client_per_round=4,
        seed=700 + seed, annotate_weight=0.5, insert_weight=0.3,
        remove_weight=0.2, multi_key_annotates=True,
        initial_text="prop width farm",
    )
    farm = run_sharedstring_farm(cfg)
    oracle = replay_passive(farm.stream, cfg.initial_text)
    want = char_spans(oracle.annotated_spans())

    k = KernelReplica(initial=cfg.initial_text, chunk_size=32,
                      capacity=2048, max_prop_pairs=2)
    k.apply_messages(farm.stream)
    k.check_errors()
    assert char_spans(k.annotated_spans()) == want

    ov = OverlayMessageReplica(initial=cfg.initial_text, fold_interval=16)
    ov.apply_messages(farm.stream)
    ov.check_errors()
    assert char_spans(ov.annotated_spans()) == want

    dev = OverlayKernelMessageReplica(
        initial=cfg.initial_text, chunk_size=32, window=1024,
        max_prop_pairs=2, interpret=True,
    )
    dev.apply_messages(farm.stream)
    dev.check_errors()
    assert char_spans(dev.annotated_spans()) == want
