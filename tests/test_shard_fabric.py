"""Sharded ordering fabric: lease-balanced multi-partition deli farm
with fenced partition handoff (`server.shard_fabric`).

The reference splits the document space across Kafka partitions with
ZooKeeper arbitrating ownership (SURVEY.md §2.5); these tests prove
the reproduction's form of that topology: consistent-hash ingress
routing (boxcar-aware), emergent lease balance across workers
(membership change IS the rebalance trigger), fenced handoff with
exactly-once resumption, per-partition metric labels, and the
`LocalServer(n_partitions=)` in-proc face. The multi-process
supervised form under faults lives in tests/test_chaos_recovery.py;
throughput scaling in bench_configs ``config6_shard_scaling``.
"""

from __future__ import annotations

import os
import time

import pytest

from fluidframework_tpu.server.columnar_log import make_topic
from fluidframework_tpu.server.queue import (
    FencedError,
    LeaseManager,
    lease_table,
    partition_of,
    record_partition,
)
from fluidframework_tpu.server.shard_fabric import (
    ShardFabricSupervisor,
    ShardRouter,
    ShardWorker,
    partition_lease_name,
    spread_doc_names,
)
from fluidframework_tpu.server.supervisor import (
    DeliRole,
    _topic_path,
    partitioned_role_class,
)


def _fabric_workload(docs, n_clients=1, ops=8):
    recs = []
    for doc in docs:
        for c in range(1, n_clients + 1):
            recs.append({"kind": "join", "doc": doc, "client": c})
        for i in range(ops):
            for c in range(1, n_clients + 1):
                recs.append({"kind": "op", "doc": doc, "client": c,
                             "clientSeq": i + 1, "refSeq": 0,
                             "contents": {"i": i}})
    return recs


def _merged_ops(router):
    out = []
    for t in router.deltas_topics():
        out.extend(r for r in t.read_from(0)
                   if isinstance(r, dict) and r.get("kind") == "op")
    return out


def _drain(workers, router, expected, deadline_s=30):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        moved = sum(w.step() for w in workers)
        ops = _merged_ops(router)
        if len(ops) >= expected and moved == 0:
            return ops
    raise AssertionError(
        f"drain timed out: {len(_merged_ops(router))}/{expected}"
    )


# ---------------------------------------------------------------------------
# routing
# ---------------------------------------------------------------------------


def test_record_partition_and_router_split():
    recs = [
        {"kind": "op", "doc": "a", "client": 1, "clientSeq": 1,
         "refSeq": 0, "contents": None},
        {"kind": "boxcar", "doc": "a", "client": 1, "ops": []},
        {"kind": "join", "doc": "b", "client": 2},
        {"weird": True},          # doc-less junk pins to partition 0
        "not even a dict",
    ]
    n = 4
    pa, pb = partition_of("a", n), partition_of("b", n)
    assert record_partition(recs[0], n) == pa
    assert record_partition(recs[1], n) == pa  # boxcar rides its doc
    assert record_partition(recs[3], n) == 0
    assert record_partition(recs[4], n) == 0
    assert record_partition(recs[0], 1) == 0  # single-partition: all p0


def test_router_appends_per_partition_in_order(tmp_path):
    shared = str(tmp_path)
    docs = spread_doc_names(4, 2)
    router = ShardRouter(shared, 2)
    recs = _fabric_workload(docs, ops=3)
    counts = router.append(recs)
    assert sum(counts.values()) == len(recs)
    assert len(counts) == 2  # both partitions got traffic
    for p in range(2):
        got = router.topics[p].read_from(0)
        want = [r for r in recs if record_partition(r, 2) == p]
        assert got == want  # arrival order preserved within partition


def test_spread_doc_names_covers_partitions():
    for n in (2, 4, 8):
        docs = spread_doc_names(2 * n, n)
        assert len(docs) == 2 * n
        per = {}
        for d in docs:
            per[partition_of(d, n)] = per.get(partition_of(d, n), 0) + 1
        assert set(per) == set(range(n))
        assert all(v == 2 for v in per.values())


# ---------------------------------------------------------------------------
# partitioned role identity
# ---------------------------------------------------------------------------


def test_partitioned_role_class_identity(tmp_path):
    cls = partitioned_role_class(DeliRole, 3)
    assert cls.name == "deli-p3"
    assert cls.in_topic_name == "rawdeltas-p3"
    assert cls.out_topic_name == "deltas-p3"
    assert cls.partition == 3 and cls.role_base == "deli"
    role = cls(str(tmp_path), owner="w", ttl_s=3600.0)
    assert role.in_topic.path.endswith("rawdeltas-p3.jsonl")
    assert role._metric_labels() == {"role": "deli", "partition": "3"}
    # Unpartitioned roles keep the historic label shape.
    plain = DeliRole(str(tmp_path / "plain"), owner="w", ttl_s=3600.0)
    assert plain._metric_labels() == {"role": "deli"}


def test_serve_role_partition_flag_runs_one_pinned_shard(tmp_path):
    """`serve_role --partition` (the supervisor CLI surface) serves
    exactly one partition's topic pair under its own lease."""
    import subprocess
    import sys

    shared = str(tmp_path)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    raw = make_topic(_topic_path(shared, "rawdeltas-p1"))
    raw.append_many(_fabric_workload(["solo"], ops=5))
    proc = subprocess.Popen(
        [sys.executable, "-c",
         "from fluidframework_tpu.server.supervisor import main; main()",
         "--role", "deli", "--dir", shared, "--owner", "W",
         "--partition", "1", "--ttl", "2.0"],
        stdout=subprocess.PIPE, text=True, cwd=repo,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    try:
        line = proc.stdout.readline().strip()
        assert line == "READY deli-p1 W", line
        deltas = make_topic(_topic_path(shared, "deltas-p1"))
        deadline = time.time() + 20
        ops = []
        while time.time() < deadline:
            ops = [r for r in deltas.read_from(0)
                   if isinstance(r, dict) and r.get("kind") == "op"]
            if len(ops) >= 6:
                break
            time.sleep(0.05)
        assert [r["seq"] for r in ops] == list(range(1, 7))
        # Poll: an instantaneous read can catch the lease mid-expiry
        # when the child is scheduler-starved past the TTL on a loaded
        # box — it renews on its next step, so ownership converges.
        owner = None
        deadline = time.time() + 20
        while time.time() < deadline:
            owner = (lease_table(
                os.path.join(shared, "leases")
            ).get("deli-p1") or {}).get("owner")
            if owner == "W":
                break
            time.sleep(0.05)
        assert owner == "W"
    finally:
        proc.kill()
        proc.wait(timeout=10)


# ---------------------------------------------------------------------------
# lease balance + handoff (in-proc workers: fast, deterministic-ish)
# ---------------------------------------------------------------------------


def test_workers_balance_on_membership_change(tmp_path):
    """A lone worker grabs every partition; a joining peer makes it
    shed down to its fair share (graceful fenced release → immediate
    takeover, no TTL wait)."""
    shared = str(tmp_path)
    wa = ShardWorker(shared, "wA", n_partitions=4, ttl_s=1.0)
    wa.heartbeat()
    wa.sweep()
    for _ in range(8):
        wa.step()
    assert sorted(wa.roles) == [0, 1, 2, 3]
    wb = ShardWorker(shared, "wB", n_partitions=4, ttl_s=1.0)
    wb.heartbeat()

    def settled():
        return (len(wa.roles) == 2 and len(wb.roles) == 2
                and all(r.fence is not None
                        for w in (wa, wb) for r in w.roles.values()))

    deadline = time.time() + 15
    while time.time() < deadline and not settled():
        wa.step()
        wb.step()
    assert settled(), (sorted(wa.roles), sorted(wb.roles))
    assert set(wa.roles) | set(wb.roles) == {0, 1, 2, 3}
    owners = lease_table(os.path.join(shared, "leases"))
    assert {v["owner"] for v in owners.values()} == {"wA", "wB"}
    # The fence field distinguishes every ownership generation.
    assert all(v["fence"] >= 1 for v in owners.values())
    wa.stop()
    wb.stop()


def test_dead_worker_partitions_resume_exactly_once(tmp_path):
    """Kill a worker (stop stepping + stale heartbeat): the survivor's
    target rises, it sweeps the expired leases, restores the fenced
    checkpoints and resumes with contiguous per-doc seqs — no dup, no
    skip, across the handoff."""
    shared = str(tmp_path)
    docs = spread_doc_names(4, 2)
    router = ShardRouter(shared, 2)
    router.append(_fabric_workload(docs, ops=6))
    wa = ShardWorker(shared, "wA", n_partitions=2, ttl_s=0.5,
                     max_partitions=1)
    wb = ShardWorker(shared, "wB", n_partitions=2, ttl_s=0.5,
                     max_partitions=1)
    for w in (wa, wb):
        w.heartbeat()
        w.sweep()
    _drain((wa, wb), router, 4 + 4 * 6, deadline_s=20)
    assert len(wa.roles) == 1 and len(wb.roles) == 1
    dead_parts = set(wa.roles)

    # "Kill" A: it stops stepping and its heartbeat goes stale; B's cap
    # rises so it may take both partitions.
    os.remove(wa._hb_path())
    wb.max_partitions = 2
    second = []
    for doc in docs:
        for i in range(6, 12):
            second.append({"kind": "op", "doc": doc, "client": 1,
                           "clientSeq": i + 1, "refSeq": 0,
                           "contents": {"i": i}})
    router.append(second)
    # Deflake: poll the LEASE TABLE for A's leases to expire instead
    # of a sleep-bounded guess — the fence/expiry fields make the
    # condition exact (a loaded box can stretch "1 second" well past
    # the TTL or not far enough).
    dead_leases = {f"deli-p{p}" for p in dead_parts}
    deadline = time.time() + 15
    while time.time() < deadline:
        live = lease_table(os.path.join(shared, "leases"))
        if not dead_leases & set(live):
            break
        time.sleep(0.05)
    ops = _drain((wb,), router, 4 + 4 * 12, deadline_s=25)
    per = {}
    for r in ops:
        per.setdefault(r["doc"], []).append(r["seq"])
    for doc, seqs in per.items():
        assert sorted(seqs) == list(range(1, len(seqs) + 1)), doc
        assert len(seqs) == 13  # 1 join + 12 ops, exactly once
    assert dead_parts <= set(wb.roles)
    wb.stop()


def test_deposed_partition_owner_write_rejected(tmp_path):
    """The write-path half of fenced handoff: after a takeover, the
    old owner's append to the partition's deltas topic (with its old
    fence) raises FencedError — exactly-once does not rest on the
    loser politely standing down."""
    shared = str(tmp_path)
    router = ShardRouter(shared, 2)
    docs = spread_doc_names(2, 2)
    router.append(_fabric_workload(docs, ops=2))
    wa = ShardWorker(shared, "wA", n_partitions=2, ttl_s=0.4)
    wa.heartbeat()
    wa.sweep()
    _drain((wa,), router, 2 + 2 * 2, deadline_s=15)
    p = sorted(wa.roles)[0]
    old_fence = wa.roles[p].fence
    deltas = wa.roles[p].out_topic
    assert old_fence is not None

    # A stops renewing; its lease expires — polled off the lease
    # table (exact: the entry vanishes at expiry) instead of a
    # sleep-bounded guess. A successor then takes over and its FENCE
    # must strictly advance past the deposed owner's.
    os.remove(wa._hb_path())
    deadline = time.time() + 10
    while time.time() < deadline:
        if partition_lease_name(p) not in lease_table(
                os.path.join(shared, "leases")):
            break
        time.sleep(0.05)
    wb = ShardWorker(shared, "wB", n_partitions=2, ttl_s=5.0)
    wb.heartbeat()
    deadline = time.time() + 10
    while time.time() < deadline:
        wb.step()
        if p in wb.roles and wb.roles[p].fence is not None:
            break
    assert wb.roles[p].fence is not None
    assert wb.roles[p].fence > old_fence
    # And the observer view carries the successor's fence.
    info = lease_table(os.path.join(shared, "leases"))[
        partition_lease_name(p)]
    assert info["fence"] == wb.roles[p].fence > old_fence
    with pytest.raises(FencedError):
        deltas.append_many(
            [{"kind": "op", "doc": "zombie", "seq": -1}],
            fence=old_fence, owner=wa.owner,
        )
    wb.stop()


def test_graceful_release_skips_ttl_wait(tmp_path):
    """ShardWorker.stop() hands partitions off with expires=0: a
    successor acquires IMMEDIATELY instead of waiting out the TTL."""
    shared = str(tmp_path)
    wa = ShardWorker(shared, "wA", n_partitions=1, ttl_s=30.0)
    wa.heartbeat()
    wa.sweep()
    for _ in range(4):
        wa.step()
    assert 0 in wa.roles and wa.roles[0].fence is not None
    wa.stop()
    lm = LeaseManager(os.path.join(shared, "leases"), "wB", ttl_s=30.0)
    fence = lm.try_acquire(partition_lease_name(0))
    assert fence is not None  # no 30s wait: released, not expired


def test_worker_metrics_carry_partition_labels(tmp_path):
    """Per-partition metric labels (role="deli", partition="k") ride
    the worker heartbeat so the supervisor scrape can merge workers
    without collapsing partitions."""
    import json

    from fluidframework_tpu.utils import metrics as M

    shared = str(tmp_path)
    router = ShardRouter(shared, 2)
    router.append(_fabric_workload(spread_doc_names(2, 2), ops=2))
    reg = M.MetricsRegistry()
    prev = M.set_registry(reg)
    try:
        w = ShardWorker(shared, "wA", n_partitions=2, ttl_s=2.0)
        w.heartbeat()
        w.sweep()
        _drain((w,), router, 2 + 2 * 2, deadline_s=15)
        w.heartbeat()
    finally:
        M.set_registry(prev)
    hb = json.load(open(w._hb_path()))
    assert hb["partitions"] == [0, 1]
    labels = {
        (m.get("labels") or {}).get("partition")
        for m in hb["metrics"].get("counters", [])
        if m.get("name") == "role_records_total"
    }
    assert labels == {"0", "1"}
    w.stop()


# ---------------------------------------------------------------------------
# supervised fabric (multi-process, no faults — chaos runs the faults)
# ---------------------------------------------------------------------------


def test_supervised_fabric_drains_and_reports(tmp_path):
    shared = str(tmp_path)
    docs = spread_doc_names(4, 4)
    router = ShardRouter(shared, 4)
    sup = ShardFabricSupervisor(
        shared, n_workers=2, n_partitions=4, ttl_s=0.6,
        heartbeat_timeout_s=3.0,
    ).start()
    try:
        recs = _fabric_workload(docs, ops=4)
        router.append(recs)
        deadline = time.time() + 40
        ops = []
        while time.time() < deadline:
            sup.poll_once()
            ops = _merged_ops(router)
            if len(ops) >= len(recs):
                break
            time.sleep(0.05)
        assert len(ops) == len(recs)
        # The drain can finish before the second worker's rebalance
        # lands; give ownership a moment to settle across BOTH workers.
        deadline = time.time() + 20
        owners = {}
        while time.time() < deadline:
            sup.poll_once()
            owners = sup.partition_owners()
            if (set(owners) == {f"deli-p{k}" for k in range(4)}
                    and len({o.split("-g")[0]
                             for o in owners.values()}) == 2):
                break
            time.sleep(0.1)
        assert set(owners) == {f"deli-p{k}" for k in range(4)}
        assert len({o.split("-g")[0] for o in owners.values()}) == 2
        h = sup.health()
        assert h["status"] == "ok" and h["n_partitions"] == 4
        reg = sup.collect_metrics()
        assert reg.gauge("shard_partitions_total").value == 4
        assert reg.gauge("shard_partitions_owned_live").value == 4
    finally:
        sup.stop()


def test_chatty_child_stdout_drained_no_wedge(tmp_path):
    """A long-lived worker prints one line per deposed/fenced partition;
    the supervisor must drain its stdout pipe or the child's print()
    blocks once 64KB accumulate and the whole worker stalls with no
    real fault. Drive a child that outprints the pipe capacity many
    times over and prove it neither blocks nor gets restarted."""
    import sys

    from fluidframework_tpu.server.supervisor import ServiceSupervisor

    shared = str(tmp_path)
    progress = str(tmp_path / "progress")
    child_src = (
        "import json, os, sys, time\n"
        "hb, prog = sys.argv[1], sys.argv[2]\n"
        "print('READY chatty', flush=True)\n"
        "n, t0 = 0, time.time()\n"
        "while time.time() - t0 < 8:\n"
        "    print('DEPOSED ' + 'x' * 1000, flush=True)\n"
        "    n += 1\n"
        "    if n % 100 == 0:\n"
        "        with open(hb + '.tmp', 'w') as f:\n"
        "            json.dump({'t': time.time()}, f)\n"
        "        os.replace(hb + '.tmp', hb)\n"
        "        with open(prog + '.tmp', 'w') as f:\n"
        "            f.write(str(n))\n"
        "        os.replace(prog + '.tmp', prog)\n"
    )

    class ChattySup(ServiceSupervisor):
        def _child_cmd(self, role, owner):
            return [sys.executable, "-c", child_src,
                    self._hb_file(role), progress]

    sup = ChattySup(shared, roles=("chatty",), heartbeat_timeout_s=6.0)
    sup.start()
    try:
        deadline = time.time() + 4
        while time.time() < deadline:
            sup.poll_once()
            time.sleep(0.05)
        lines = int(open(progress).read())
        # 64KB of 1KB lines is ~65 — well past that means the pipe is
        # being drained, not filled.
        assert lines * 1009 > 4 * 65536, f"child stalled at {lines} lines"
        assert sup.procs["chatty"].poll() is None
        assert sup.restarts["chatty"] == 0
        # The bounded tail survives for restart diagnostics.
        assert 0 < len(sup._stdout_tails["chatty"]) <= 2048
    finally:
        sup.stop()


# ---------------------------------------------------------------------------
# LocalServer(n_partitions=)
# ---------------------------------------------------------------------------


def test_localserver_sharded_ingress_and_restart(tmp_path):
    from fluidframework_tpu.protocol.messages import DocumentMessage
    from fluidframework_tpu.server import LocalServer

    persist = str(tmp_path / "srv")
    srv = LocalServer(persist_dir=persist, n_partitions=2)
    docs = spread_doc_names(4, 2)
    for doc in docs:
        sock = srv.connect(doc)
        sock.submit(DocumentMessage(client_seq=1, ref_seq=0,
                                    contents={"d": doc}))
        sock.submit_batch([
            DocumentMessage(client_seq=2, ref_seq=0, contents=1),
            DocumentMessage(client_seq=3, ref_seq=0, contents=2),
        ])
    for doc in docs:
        seqs = [m.sequence_number for m in srv.ops_from(doc, 0)]
        assert seqs == list(range(1, len(seqs) + 1))
    # Both partitions actually carried traffic and checkpoint per-k.
    cps = srv.checkpoints()
    assert "deli-p0" in cps and "deli-p1" in cps and "deli" not in cps
    assert all(
        srv.log.topic(f"rawdeltas-p{k}").head > 0 for k in range(2)
    )
    # Restart: per-partition journals + checkpoints resume the docs.
    srv2 = LocalServer(persist_dir=persist, n_partitions=2)
    for doc in docs:
        seqs = [m.sequence_number for m in srv2.ops_from(doc, 0)]
        assert seqs == list(range(1, len(seqs) + 1))
    sock = srv2.connect(docs[0])
    assert sock.client_id == 2  # join replay covered partition topics


def test_localserver_sharded_summary_controls_route(tmp_path):
    """Scribe's summary ack controls route back through the doc's
    partition (the raw_router seam): the summarize round-trip — client
    summary → scribe validate → SUMMARY_ACK via deli — works
    sharded."""
    from fluidframework_tpu.dds import StringFactory
    from fluidframework_tpu.runtime import ChannelRegistry, ContainerRuntime
    from fluidframework_tpu.runtime.summary_manager import SummaryManager
    from fluidframework_tpu.server import LocalServer

    registry = ChannelRegistry([StringFactory()])
    srv = LocalServer(n_partitions=4)
    rt = ContainerRuntime(registry)
    rt.create_datastore("default").create_channel(
        "s", StringFactory.type_name
    )
    rt.connect(srv.connect("doc0"))
    mgr = SummaryManager(rt, srv, max_ops=1)
    s = rt.get_datastore("default").get_channel("s")
    for i in range(3):
        s.insert_text(0, f"{i}")
        rt.flush()
    acks = []
    mgr.collection.on("ack", acks.append)
    assert mgr.maybe_summarize()
    assert len(acks) == 1  # ack came back through the partition topic
    assert srv.storage.get_ref("doc0") == acks[0]["handle"]


def test_localserver_rejects_bad_n_partitions():
    from fluidframework_tpu.server import LocalServer

    with pytest.raises(ValueError):
        LocalServer(n_partitions=0)


# ---------------------------------------------------------------------------
# shard bench machinery (tiny smoke; the real guard is bench_configs)
# ---------------------------------------------------------------------------


def test_shard_bench_gates_bit_identity(tmp_path):
    from fluidframework_tpu.testing.deli_bench import run_shard_bench

    res = run_shard_bench(
        n_docs=24, n_clients=2, ops_per_client=2, partitions=(1, 2),
        deli_impl="scalar", log_format="columnar", batch=4096,
        work_dir=str(tmp_path),
    )
    assert res["gate"] == "bit-identical across partitions"
    assert res["runs"][0]["partitions"] == 1
    assert res["runs"][1]["partitions"] == 2
    assert sum(res["runs"][1]["per_partition_records"]) == res["records"]
    assert res["speedup"] > 0

# ---------------------------------------------------------------------------
# per-partition downstream stages (static fabric, front-door PR)
# ---------------------------------------------------------------------------


def test_worker_runs_fused_downstream_per_partition(tmp_path):
    """ShardWorker(downstream="fused"): every owned partition gets its
    own fused durable+broadcast consumer (deltas-p{k} -> durable-p{k}
    + broadcast-p{k}) and scribe, riding deli ownership under their
    own fenced leases."""
    import json as _json

    from fluidframework_tpu.server.supervisor import canonical_record

    shared = str(tmp_path)
    n_p = 2
    router = ShardRouter(shared, n_p)
    w = ShardWorker(shared, "wA", n_partitions=n_p, ttl_s=5.0,
                    downstream="fused")
    w.heartbeat()
    w.sweep()
    assert set(w.down_roles) == set(w.roles)
    fused = w.down_roles[0][0]
    assert fused.bc_topic_name == "broadcast-p0"
    assert fused.name == "scriptorium_broadcaster-p0"
    docs = spread_doc_names(6, n_p)
    workload = _fabric_workload(docs, ops=4)
    router.append(workload)
    deadline = time.time() + 30
    while time.time() < deadline:
        moved = w.step()
        durable = []
        for p in range(n_p):
            t = make_topic(_topic_path(shared, f"durable-p{p}"))
            durable.extend(r for r in t.read_from(0)
                           if isinstance(r, dict)
                           and r.get("kind") == "op")
        if len(durable) >= len(workload) and moved == 0:
            break
    deltas_ops = _merged_ops(router)
    assert len(deltas_ops) == len(workload)
    want = sorted(_json.dumps(canonical_record(r), sort_keys=True)
                  for r in deltas_ops)
    for base in ("durable", "broadcast"):
        got = []
        for p in range(n_p):
            t = make_topic(_topic_path(shared, f"{base}-p{p}"))
            got.extend(r for r in t.read_from(0)
                       if isinstance(r, dict) and r.get("kind") == "op")
        assert sorted(
            _json.dumps(canonical_record(r), sort_keys=True)
            for r in got
        ) == want, f"{base} legs diverged"
    # Scribe folded every partition's stream under its own lease.
    total = 0
    for roles in w.down_roles.values():
        scribe = next(r for r in roles if r.role_base == "scribe")
        total += sum(int(st["count"]) for st in scribe.docs.values())
    assert total == len(deltas_ops)
    # Downstream leases are real: per-partition names, fenced.
    owners = lease_table(os.path.join(shared, "leases"))
    assert "scriptorium_broadcaster-p0" in owners
    assert "scribe-p1" in owners
    w.stop()


def test_downstream_validation():
    with pytest.raises(ValueError):
        ShardWorker("/tmp/x-nonexistent-vald", "w", downstream="bogus")
    with pytest.raises(ValueError):
        ShardWorker("/tmp/x-nonexistent-vald", "w", elastic=True,
                    downstream="fused")
    from fluidframework_tpu.server.shard_fabric import ranged_role_class
    from fluidframework_tpu.server.supervisor import (
        ScriptoriumBroadcasterRole,
    )

    with pytest.raises(ValueError):
        ranged_role_class(
            ScriptoriumBroadcasterRole,
            {"rid": "r0", "lo": 0, "hi": 10, "preds": []}, 1,
        )


def test_merged_reader_reads_downstream_stage(tmp_path):
    """MergedDeltasReader(base=...) is the elastic read surface for
    ANY stage's legs, not just deltas."""
    shared = str(tmp_path)
    router = ShardRouter(shared, 2)
    for p in range(2):
        t = make_topic(_topic_path(shared, f"durable-p{p}"))
        t.append_many([{"kind": "op", "doc": f"d{p}", "seq": 1,
                        "inOff": 0}])
    reader = router.merged_reader("durable")
    recs = reader.poll()
    assert {r["doc"] for r in recs} == {"d0", "d1"}
    assert reader.poll() == []  # incremental: nothing new
