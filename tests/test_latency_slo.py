"""Tail-latency SLO layer (ISSUE 9): topic doorbells (event-driven
consumer wakeups with poll fallback), the /slo + /traces endpoints,
the slow-op flight recorder, and the open-loop latency bench's
trace/quantile correctness contract.

Determinism is the standing constraint: doorbells are advisory-only
(every consumer keeps its bounded-timeout poll loop, so fencing and
torn-read semantics never depend on a FIFO), and wire traces ride a
side "tr" key that `canonical_record`/digests never see — the chaos
suites (tests/test_chaos_recovery.py) run with doorbells on and still
converge bit-identical.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.request

import pytest

from fluidframework_tpu.server.monitor import MetricsServer
from fluidframework_tpu.server.queue import (
    SharedFileTopic,
    TopicDoorbell,
    doorbells_enabled,
    wait_doorbells,
)
from fluidframework_tpu.utils import metrics as M


def scrape(url: str):
    return urllib.request.urlopen(url, timeout=10).read().decode()


# ---------------------------------------------------------------------------
# doorbells
# ---------------------------------------------------------------------------


def test_doorbell_rings_on_append_and_times_out_idle(tmp_path):
    assert doorbells_enabled()
    t = SharedFileTopic(str(tmp_path / "t.jsonl"))
    bell = TopicDoorbell(t.path)
    try:
        t0 = time.perf_counter()
        assert bell.wait(0.05) is False  # nothing appended: timeout
        assert time.perf_counter() - t0 >= 0.04
        threading.Timer(
            0.02, lambda: t.append_many([{"x": 1}])
        ).start()
        t0 = time.perf_counter()
        assert bell.wait(2.0) is True
        assert time.perf_counter() - t0 < 0.5  # woke on the ring
    finally:
        bell.close()


def test_doorbell_pending_ring_wakes_next_wait(tmp_path):
    """A ring that lands while the consumer is mid-step is retained in
    the FIFO: the next wait returns immediately — wakeups are never
    lost, only (harmlessly) early."""
    t = SharedFileTopic(str(tmp_path / "t.jsonl"))
    bell = TopicDoorbell(t.path)
    try:
        t.append_many([{"x": 1}])  # consumer is "busy", not waiting
        t0 = time.perf_counter()
        assert bell.wait(1.0) is True
        assert time.perf_counter() - t0 < 0.05
        assert bell.wait(0.02) is False  # drained: back to timeout
    finally:
        bell.close()


def test_doorbell_multiple_consumers_all_ring(tmp_path):
    t = SharedFileTopic(str(tmp_path / "t.jsonl"))
    a, b = TopicDoorbell(t.path), TopicDoorbell(t.path)
    try:
        t.append_many([{"x": 1}])
        assert a.wait(1.0) and b.wait(1.0)
        # wait_doorbells: ANY of several bells wakes the caller.
        t.append_many([{"x": 2}])
        assert wait_doorbells([a, b], 1.0) is True
    finally:
        a.close()
        b.close()


def test_doorbell_dead_consumer_reaped_and_empty_append_no_ring(tmp_path):
    t = SharedFileTopic(str(tmp_path / "t.jsonl"))
    bell = TopicDoorbell(t.path)
    live = TopicDoorbell(t.path)
    try:
        bell.close()  # "crashed" consumer: FIFO file left behind
        t.append_many([{"x": 1}])  # ring reaps the dead bell
        assert live.wait(1.0) is True
        names = os.listdir(t.path + ".bells")
        assert len(names) == 1  # only the live bell remains
        # An empty append (the fence-bind probe) must not ring.
        t.append_many([])
        assert live.wait(0.03) is False
    finally:
        live.close()


def test_doorbell_env_kill_switch(tmp_path, monkeypatch):
    monkeypatch.setenv("FLUID_DOORBELL", "0")
    assert not doorbells_enabled()
    from fluidframework_tpu.server.supervisor import DeliRole

    role = DeliRole(str(tmp_path), owner="w", ttl_s=3600.0)
    assert role.doorbell() is None  # poll fallback
    role.close_doorbell()


def test_role_idle_wait_uses_doorbell_and_cleanup(tmp_path):
    from fluidframework_tpu.server.supervisor import DeliRole

    role = DeliRole(str(tmp_path), owner="w", ttl_s=3600.0)
    raw = SharedFileTopic(str(tmp_path / "topics" / "rawdeltas.jsonl"))
    raw.append_many([{"kind": "join", "doc": "d", "client": 1}])
    while role.step() == 0:
        pass
    # Idle step creates the bell lazily; an append wakes the next one.
    role.step(idle_sleep=0.01)
    assert role._bell is not None
    threading.Timer(0.02, lambda: raw.append_many([
        {"kind": "op", "doc": "d", "client": 1, "clientSeq": 1,
         "refSeq": 0, "contents": {}},
    ])).start()
    t0 = time.perf_counter()
    moved = 0
    while moved == 0 and time.perf_counter() - t0 < 2.0:
        moved = role.step(idle_sleep=0.2)
    assert moved == 1
    assert time.perf_counter() - t0 < 0.6  # woke well inside a tick
    bell_path = role._bell.path
    role.close_doorbell()
    assert not os.path.exists(bell_path)


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_flight_recorder_threshold_and_ring_bound():
    fr = M.FlightRecorder(capacity=3, threshold_ms=10.0)
    for i, v in enumerate((1.0, 12.0, 3.0, 15.0, 11.0, 20.0)):
        if fr.note(v):
            fr.add(v, {"i": i})
    spans = fr.snapshot()
    assert [s["e2e_ms"] for s in spans] == [15.0, 11.0, 20.0]  # ring
    assert fr.seen == 6 and fr.recorded == 4
    fr.clear()
    assert fr.snapshot() == [] and fr.seen == 0


def test_flight_recorder_rolling_p99_mode():
    fr = M.FlightRecorder(capacity=8, threshold_ms=None,
                          window=128, min_samples=32)
    # Below min_samples nothing qualifies (no p99 to speak of).
    assert not any(fr.note(float(v)) for v in range(1, 32))
    # A spread distribution + one spike: only the tail qualifies.
    for v in range(1, 97):
        fr.note(float(v % 96 + 1))
    assert fr.note(500.0) is True
    fr.add(500.0, {"slow": 1})
    assert fr.snapshot()[-1]["e2e_ms"] == 500.0
    # The spike fed the window, but a median op still doesn't qualify.
    assert fr.note(40.0) is False


def test_default_flight_recorder_swap():
    old = M.get_flight_recorder()
    mine = M.FlightRecorder(capacity=2, threshold_ms=0.0)
    prev = M.set_flight_recorder(mine)
    try:
        assert prev is old
        assert M.get_flight_recorder() is mine
        M.get_flight_recorder().observe(1.0, {"x": 1})
        assert mine.snapshot() == [{"e2e_ms": 1.0, "x": 1}]
    finally:
        M.set_flight_recorder(prev)


def test_runtime_apply_feeds_flight_recorder():
    """The in-proc pipeline's apply side records slow ops: with a zero
    threshold every traced op qualifies, and the span carries the
    stage timestamps plus seq/client identity."""
    from fluidframework_tpu.dds import StringFactory
    from fluidframework_tpu.runtime import ChannelRegistry, ContainerRuntime
    from fluidframework_tpu.server import LocalServer

    prev = M.set_flight_recorder(
        M.FlightRecorder(capacity=16, threshold_ms=0.0)
    )
    try:
        server = LocalServer()
        rt = ContainerRuntime(ChannelRegistry([StringFactory()]))
        ds = rt.create_datastore("default")
        ds.create_channel("s", StringFactory.type_name)
        rt.connect(server.connect("doc", 1))
        ds.get_channel("s").insert_text(0, "hello")
        rt.flush()
        spans = M.get_flight_recorder().snapshot()
        assert spans, "no slow-op span recorded"
        s = spans[-1]
        assert s["seq"] > 0 and s["client"] == 1
        st = s["stages"]
        assert st["submit"] <= st["stamp"] <= st["apply"]
    finally:
        M.set_flight_recorder(prev)


# ---------------------------------------------------------------------------
# /slo + /traces endpoints
# ---------------------------------------------------------------------------


def test_slo_and_traces_endpoints():
    reg = M.MetricsRegistry()
    h = reg.histogram("op_stage_ms", stage="submit_to_broadcast")
    for v in (1.0, 2.0, 3.0, 40.0):
        h.observe(v)
    fr = M.FlightRecorder(capacity=4, threshold_ms=0.0)
    fr.observe(40.0, {"doc": "d", "seq": 4})
    mon = MetricsServer(registry=reg, traces=fr.snapshot).start()
    try:
        slo = json.loads(scrape(mon.url + "/slo"))
        [entry] = slo["histograms"]
        assert entry["name"] == "op_stage_ms"
        assert entry["count"] == 4
        assert entry["p50"] is not None and entry["p99"] is not None
        assert entry["p50"] <= entry["p95"] <= entry["p99"]
        traces = json.loads(scrape(mon.url + "/traces"))
        assert traces["slow_ops"] == [
            {"e2e_ms": 40.0, "doc": "d", "seq": 4}
        ]
    finally:
        mon.stop()


def test_traces_endpoint_defaults_to_process_recorder():
    prev = M.set_flight_recorder(
        M.FlightRecorder(capacity=2, threshold_ms=0.0)
    )
    mon = MetricsServer(registry=M.MetricsRegistry()).start()
    try:
        M.get_flight_recorder().observe(7.0, {"seq": 1})
        traces = json.loads(scrape(mon.url + "/traces"))
        assert traces["slow_ops"][0]["e2e_ms"] == 7.0
    finally:
        mon.stop()
        M.set_flight_recorder(prev)


# ---------------------------------------------------------------------------
# the open-loop bench's correctness contract (scaled down; the
# p99-improvement judgment is bench_configs.config9_latency)
# ---------------------------------------------------------------------------


def test_latency_variant_traces_quantiles_and_slow_ops(tmp_path):
    """One doorbell variant at low rate: every op exactly-once in
    broadcast, monotone spans, the child-reported histogram
    bucket-identical to the wire spans (asserted inside), and the
    slow-op spans naming real ops."""
    from fluidframework_tpu.testing.deli_bench import _run_latency_variant

    res = _run_latency_variant(
        str(tmp_path), True, rate_hz=50.0, duration_s=1.2,
        n_docs=2, n_clients=2, ttl_s=0.75, timeout_s=60.0,
    )
    assert res["records"] == 60 + res["lead_in"]
    q = res["submit_to_broadcast_ms"]
    assert q["count"] == 60 and q["p50"] <= q["p95"] <= q["p99"]
    for s in res["slow_ops"]:
        st = s["stages"]
        assert st["sub"] <= st["stamp"] <= st["bc"]
        assert s["doc"] in ("doc0", "doc1")
