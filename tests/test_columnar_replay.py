"""Columnar replay engine vs the scalar oracle, including compaction.

Same differential contract as tests/test_kernel_vs_oracle.py (the
project's bit-identity gate, BASELINE.json north_star), driven through
the high-throughput columnar path of core/columnar_replay.py.
"""

import numpy as np
import pytest

from fluidframework_tpu.core.columnar_replay import ColumnarReplica
from fluidframework_tpu.core.mergetree import replay_passive
from fluidframework_tpu.testing.synthetic import generate_stream

INITIAL = 16


def _oracle_text(stream):
    initial = "".join(map(chr, stream.text[:INITIAL]))
    return replay_passive(stream.as_messages(), initial=initial).get_text()


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_columnar_matches_oracle(seed):
    stream = generate_stream(
        1500, n_clients=16, seed=seed, window=64, initial_len=INITIAL
    )
    rep = ColumnarReplica(
        stream, initial_len=INITIAL, chunk_size=128, capacity=1024,
        compact_watermark=0.5,
    )
    rep.replay()
    rep.check_errors()
    assert rep.compactions > 0, "test must exercise compaction"
    assert rep.get_text() == _oracle_text(stream)


def test_columnar_emergency_growth():
    # A tiny capacity forces the emergency compact+grow path.
    stream = generate_stream(
        600, n_clients=8, seed=9, window=32, initial_len=INITIAL,
        insert_weight=0.9, remove_weight=0.05, annotate_weight=0.05,
    )
    rep = ColumnarReplica(
        stream, initial_len=INITIAL, chunk_size=64, capacity=128,
        compact_watermark=0.9,
    )
    rep.replay()
    rep.check_errors()
    assert rep.capacity > 128
    assert rep.get_text() == _oracle_text(stream)


def test_columnar_mid_stream_state_is_consistent():
    # Interleave replay with compaction at every chunk and verify the
    # final annotated state length matches the oracle's.
    stream = generate_stream(
        800, n_clients=4, seed=5, window=16, initial_len=INITIAL
    )
    rep = ColumnarReplica(
        stream, initial_len=INITIAL, chunk_size=32, capacity=512,
        compact_watermark=0.1,  # compact constantly
    )
    rep.replay()
    rep.check_errors()
    assert rep.get_text() == _oracle_text(stream)
