"""Columnar replay engine vs the scalar oracle, including compaction.

Same differential contract as tests/test_kernel_vs_oracle.py (the
project's bit-identity gate, BASELINE.json north_star), driven through
the high-throughput columnar path of core/columnar_replay.py.
"""

import numpy as np
import pytest

from fluidframework_tpu.core.columnar_replay import ColumnarReplica
from fluidframework_tpu.core.mergetree import replay_passive
from fluidframework_tpu.testing.synthetic import generate_stream

INITIAL = 16


def _oracle_text(stream):
    initial = "".join(map(chr, stream.text[:INITIAL]))
    return replay_passive(stream.as_messages(), initial=initial).get_text()


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_columnar_matches_oracle(seed):
    stream = generate_stream(
        1500, n_clients=16, seed=seed, window=64, initial_len=INITIAL
    )
    rep = ColumnarReplica(
        stream, initial_len=INITIAL, chunk_size=128, capacity=1024,
        compact_watermark=0.5,
    )
    rep.replay()
    rep.check_errors()
    assert rep.compactions > 0, "test must exercise compaction"
    assert rep.get_text() == _oracle_text(stream)


def test_columnar_emergency_growth():
    # A tiny capacity forces the emergency compact+grow path.
    stream = generate_stream(
        600, n_clients=8, seed=9, window=32, initial_len=INITIAL,
        insert_weight=0.9, remove_weight=0.05, annotate_weight=0.05,
    )
    rep = ColumnarReplica(
        stream, initial_len=INITIAL, chunk_size=64, capacity=128,
        compact_watermark=0.9,
    )
    rep.replay()
    rep.check_errors()
    assert rep.capacity > 128
    assert rep.get_text() == _oracle_text(stream)


def test_columnar_mid_stream_state_is_consistent():
    # Interleave replay with compaction at every chunk and verify the
    # final annotated state length matches the oracle's.
    stream = generate_stream(
        800, n_clients=4, seed=5, window=16, initial_len=INITIAL
    )
    rep = ColumnarReplica(
        stream, initial_len=INITIAL, chunk_size=32, capacity=512,
        compact_watermark=0.1,  # compact constantly
    )
    rep.replay()
    rep.check_errors()
    assert rep.get_text() == _oracle_text(stream)


# ---------------------------------------------------------------- pallas

def test_pallas_engine_matches_oracle_interpret():
    """The pallas chunk kernel + device compaction path (the TPU fast
    path) must be bit-identical to the scalar oracle; on CPU it runs
    through the pallas interpreter."""
    from fluidframework_tpu.testing.digest import state_digest

    for seed in (0, 1):
        stream = generate_stream(
            900, n_clients=12, seed=seed, window=48, initial_len=INITIAL
        )
        oracle = replay_passive(
            stream.as_messages(),
            initial="".join(map(chr, stream.text[:INITIAL])),
        )
        rep = ColumnarReplica(
            stream, initial_len=INITIAL, chunk_size=128, capacity=1024,
            engine="pallas", interpret=True, sync_interval=2,
        )
        rep.replay()
        rep.check_errors()
        assert rep.get_text() == oracle.get_text()
        assert state_digest(rep.annotated_spans()) == state_digest(
            oracle.annotated_spans()
        )


def test_pallas_engine_tiered_capacity_growth():
    stream = generate_stream(
        1200, n_clients=8, seed=11, window=32, initial_len=INITIAL,
        insert_weight=0.8, remove_weight=0.1, annotate_weight=0.1,
    )
    oracle = replay_passive(
        stream.as_messages(), initial="".join(map(chr, stream.text[:INITIAL]))
    )
    rep = ColumnarReplica(
        stream, initial_len=INITIAL, chunk_size=128, capacity=1024,
        engine="pallas", interpret=True, sync_interval=1,
    )
    rep.replay()
    rep.check_errors()
    assert rep.get_text() == oracle.get_text()


def test_zamboni_device_semantics():
    """Device zamboni (tombstone drop + adjacency coalesce) preserves
    visible state for every still-possible perspective."""
    import jax.numpy as jnp

    from fluidframework_tpu.ops.zamboni import zamboni_device

    stream = generate_stream(
        400, n_clients=6, seed=3, window=16, initial_len=INITIAL
    )
    rep = ColumnarReplica(
        stream, initial_len=INITIAL, chunk_size=64, capacity=1024,
        compact_watermark=1.1, engine="scan",  # no host compaction
    )
    rep.replay()
    before = rep.get_text()
    rows_before = int(rep.table.n_rows)
    rep.table = zamboni_device(rep.table, jnp.int32(rep._applied_min_seq))
    assert rep.get_text() == before
    assert int(rep.table.n_rows) <= rows_before
