"""Scenario observability plane (ISSUE 13): the traffic-profile
scenario layer (testing/scenarios.py — hot-doc storm, reconnect
stampede, read swarm, tenant-skewed mix), fabric-wide trace coverage
(partition-tagged slow-op spans + /traces under ShardWorker),
per-partition p99 quantiles and the autoscale trigger on them, the
`admit_to_stamp` ingress stage, and the storm-during-faults chaos
gate.

The standing constraints: every scenario is OPEN-LOOP (offered load
never waits on completion), every run ends in a convergence digest
(a scenario cannot pass by dropping work), and trace observation is
recovery-silent (the trace_stage_once contract — a restart's replay
must not double-observe a stage)."""

from __future__ import annotations

import json
import os
import time
import urllib.request

import pytest

from fluidframework_tpu.testing.chaos import (
    ChaosConfig,
    build_workload,
    run_chaos,
)
from fluidframework_tpu.testing.scenarios import (
    run_hotdoc_storm,
    run_read_swarm,
    run_reconnect_stampede,
    run_tenant_mix,
)
from fluidframework_tpu.utils import metrics as M


def scrape(url: str):
    return urllib.request.urlopen(url, timeout=10).read().decode()


# ---------------------------------------------------------------------------
# scenario primitives (scaled-down; gates are inside the primitives,
# these assert the CONTRACT surface they return)
# ---------------------------------------------------------------------------


def test_hotdoc_storm_open_loop_contract(tmp_path):
    """Scaled storm: the skew is real (hot doc dominates), the feed is
    open-loop (wall clock tracks the schedule, not the pipeline), and
    the run carries all three evidence artifacts — /slo quantiles,
    slow-op spans, and the convergence digest the internal gates
    already enforced (exactly-once + contiguous seqs)."""
    res = run_hotdoc_storm(
        n_writers=24, cold_docs=3, rate_hz=150.0, duration_s=1.2,
        hot_fraction=0.85, timeout_s=90.0,
        work_dir=str(tmp_path / "storm"),
    )
    assert res["open_loop"] is True
    assert res["records"] == res["hot_ops"] + res["cold_ops"]
    assert res["hot_ops"] > res["cold_ops"]  # the skew is the point
    # Open loop: the feed finished near its schedule (records/rate),
    # backlog or not. A completion-waiting feeder would stretch with
    # the pipeline instead.
    assert res["feed_wall_s"] < 3.0 * (res["records"] / res["rate_hz"])
    # Evidence artifacts.
    assert res["digest"]
    assert res["slow_ops"], "no flight-recorder spans"
    stages = {h["labels"].get("stage")
              for h in res["slo"]["histograms"]
              if h["name"] == "op_stage_ms"}
    assert "submit_to_broadcast" in stages
    q = res["submit_to_broadcast_ms"]
    assert q["count"] == res["records"] and q["p50"] <= q["p99"]
    assert res["scenario_p99_ms"] == q["p99"]
    # Hot and cold tails are reported separately.
    assert res["hot_submit_to_broadcast_ms"]["count"] == res["hot_ops"]


def test_reconnect_stampede_converges_and_measures(tmp_path):
    """Scaled stampede: concurrent catch-ups all land one signature,
    boots stay bit-identical to cold replay, and the latency evidence
    (quantiles + slow sessions) is attached."""
    res = run_reconnect_stampede(
        n_sessions=48, log_len=2048, summary_ops=256, threads=8,
        work_dir=str(tmp_path / "stampede"),
    )
    assert res["sessions"] == 48
    assert res["boots_bit_identical"] is True
    assert res["digest"]  # the single-valued catch-up signature
    assert res["catchup_ms"]["count"] == 48
    assert res["slow_ops"], "no slow-session spans"
    stages = {h["labels"].get("stage")
              for h in res["slo"]["histograms"]}
    assert "read_catchup" in stages
    assert res["tail_ops"] >= 0 and res["summary_seq"] > 0


def test_reconnect_stampede_elastic_ranges_single_signature(tmp_path):
    """ISSUE 15 satellite (PR 13 follow-up b): the stampede through
    PER-RANGE elastic summaries — the stream split into hash-range
    ``deltas-{rid}`` topics, a RANGED summarizer per range, and every
    session catching up through the MERGED `SummaryIndex` over the
    ``summaries-{rid}`` topics. One catch-up signature across the
    burst, hot-doc boots bit-identical to cold replay, and the merged
    surface resolves every background range's doc too (asserted
    inside the scenario)."""
    res = run_reconnect_stampede(
        n_sessions=32, log_len=1024, summary_ops=128, threads=8,
        elastic_ranges=3,
        work_dir=str(tmp_path / "stampede-elastic"),
    )
    assert res["elastic_ranges"] == 3
    assert res["boots_bit_identical"] is True
    assert res["digest"]  # one signature across the whole burst
    assert res["catchup_ms"]["count"] == 32
    assert res["summary_seq"] > 0


def test_read_swarm_scaled_loud_skip_and_convergence(tmp_path):
    """A scaled swarm must SAY it is scaled: below the 100k-session
    bar the throughput evidence carries an explicit skip reason (the
    host-capability rule every perf gate follows), while the fan-out
    convergence gate still ran over every session — in-proc and TCP."""
    res = run_read_swarm(
        n_sessions=250, n_docs=2, n_records=24, n_tcp=3,
        work_dir=str(tmp_path / "swarm"),
    )
    assert res["sessions"] == 250 and res["tcp_sessions"] == 3
    assert "skipped" in res and "100000-session bar" in res["skipped"]
    assert res["deliveries"] == 250 * 24
    assert res["deliveries_per_sec"] > 0
    assert res["digest"]
    # TCP sessions measured the push stage off the wire.
    stages = {h["labels"].get("stage")
              for h in res["slo"]["histograms"]}
    assert "broadcast_to_push" in stages


def test_tenant_mix_throttles_hot_tenant_only(tmp_path):
    """Scaled tenant mix through the real front door: the hot tenant
    is visibly throttled (and ONLY the hot tenant), the throttled tail
    retries to exactly-once, and the /slo body carries both the
    admit_to_stamp quantiles and the ingress refusal counters."""
    res = run_tenant_mix(
        n_tenants=5, records=240, rate_hz=240.0, rate_limit=60.0,
        n_partitions=2, timeout_s=90.0,
        work_dir=str(tmp_path / "mix"),
    )
    assert set(res["throttle_nacks"]) == {"t0"}
    assert res["throttle_nacks"]["t0"] > 0 and res["retries"] > 0
    assert res["admit_to_stamp_ms"]["count"] > 0
    assert res["scenario_p99_ms"] == res["admit_to_stamp_ms"]["p99"]
    names = {c["name"] for c in res["slo"].get("counters", ())}
    assert "ingress_nacks_total" in names
    assert "ingress_admitted_total" in names
    stages = {h["labels"].get("stage")
              for h in res["slo"]["histograms"]
              if h["name"] == "op_stage_ms"}
    assert "admit_to_stamp" in stages
    assert res["slow_ops"], "no slow-admission spans"


# ---------------------------------------------------------------------------
# admit_to_stamp: one clock read, recovery-silent (trace_stage_once)
# ---------------------------------------------------------------------------


def _mix_roles(shared, monkeypatch):
    from fluidframework_tpu.server.ingress import (
        IngressRole,
        write_tenants,
    )
    from fluidframework_tpu.server.riddler import sign_token
    from fluidframework_tpu.server.supervisor import DeliRole

    monkeypatch.setenv("FLUID_TRACE_WIRE", "1")
    write_tenants(shared, {"t0": "k0"})
    tok = sign_token("k0", "t0", "d0", ["doc:write"],
                     lifetime_s=3600.0)
    ing = IngressRole(shared, "ing", ttl_s=3600.0, batch=512)
    return ing, tok, DeliRole


def test_admit_to_stamp_monotone_and_observed(tmp_path, monkeypatch):
    """The front door stamps `tr_adm` on admitted records (one clock
    read); the deli folds it into the wire `tr` dict as `adm` and
    observes op_stage_ms{stage=admit_to_stamp} — adm <= stamp on every
    record, histogram count == sequenced ops."""
    from fluidframework_tpu.server.columnar_log import make_topic

    shared = str(tmp_path)
    reg = M.MetricsRegistry()
    prev = M.set_registry(reg)
    try:
        ing, tok, DeliRole = _mix_roles(shared, monkeypatch)
        deli = DeliRole(shared, "deli-1", ttl_s=3600.0, batch=512,
                        ckpt_interval_s=3600.0)
        ingt = make_topic(
            os.path.join(shared, "topics", "ingress.jsonl"), "json"
        )
        ingt.append_many(
            [{"kind": "auth", "doc": "d0", "client": 1, "tenant": "t0",
              "token": tok},
             {"kind": "join", "doc": "d0", "client": 1}]
            + [{"kind": "op", "doc": "d0", "client": 1,
                "clientSeq": i + 1, "refSeq": 0, "contents": {"i": i},
                "tr_sub": time.time()} for i in range(8)]
        )
        while ing.step() > 0:
            pass
        while deli.step() > 0:
            pass
        deltas = make_topic(
            os.path.join(shared, "topics", "deltas.jsonl"), "json"
        )
        ops = [r for r in deltas.read_from(0)
               if isinstance(r, dict) and r.get("kind") == "op"
               and r.get("type") == "op"]
        assert len(ops) == 8
        for r in ops:
            tr = r["tr"]
            assert tr["adm"] <= tr["stamp"], tr
            assert tr["sub"] <= tr["stamp"], tr  # sub rode through too
        h = reg.histogram("op_stage_ms", stage="admit_to_stamp")
        assert h.count == 8
    finally:
        M.set_registry(prev)


def test_admit_to_stamp_kernel_deli_parity(tmp_path, monkeypatch):
    """The KERNEL deli threads the admission stamp too (the plan
    tuple carries adm_ts next to sub_ts): same wire shape, same
    histogram, one clock read per flush — the config12 kernel+ingress
    topology must not silently lose the stage the scalar role has."""
    from fluidframework_tpu.server.columnar_log import make_topic
    from fluidframework_tpu.server.supervisor import resolve_role_class

    shared = str(tmp_path)
    reg = M.MetricsRegistry()
    prev = M.set_registry(reg)
    try:
        ing, tok, _DeliRole = _mix_roles(shared, monkeypatch)
        deli = resolve_role_class("deli", "kernel")(
            shared, "kdeli", ttl_s=3600.0, batch=512,
            ckpt_interval_s=3600.0,
        )
        ingt = make_topic(
            os.path.join(shared, "topics", "ingress.jsonl"), "json"
        )
        ingt.append_many(
            [{"kind": "auth", "doc": "d0", "client": 1, "tenant": "t0",
              "token": tok},
             {"kind": "join", "doc": "d0", "client": 1}]
            + [{"kind": "op", "doc": "d0", "client": 1,
                "clientSeq": i + 1, "refSeq": 0, "contents": {"i": i},
                "tr_sub": time.time()} for i in range(8)]
            + [{"kind": "boxcar", "doc": "d0", "client": 1,
                "ops": [{"clientSeq": 9, "refSeq": 0,
                         "contents": {"b": 1}},
                        {"clientSeq": 10, "refSeq": 0,
                         "contents": {"b": 2}}],
                "tr_sub": time.time()}]
        )
        while ing.step() > 0:
            pass
        while deli.step() > 0:
            pass
        deltas = make_topic(
            os.path.join(shared, "topics", "deltas.jsonl"), "json"
        )
        ops = [r for r in deltas.read_from(0)
               if isinstance(r, dict) and r.get("kind") == "op"
               and r.get("type") == "op"]
        assert len(ops) == 10  # 8 singles + the 2-op boxcar
        for r in ops:
            tr = r["tr"]
            assert tr["adm"] <= tr["stamp"], tr
        h = reg.histogram("op_stage_ms", stage="admit_to_stamp")
        assert h.count == 10
    finally:
        M.set_registry(prev)


def test_admit_to_stamp_recovery_silent_across_restart(tmp_path,
                                                       monkeypatch):
    """trace_stage_once: a deli successor's recovery replays the
    checkpoint→durable gap SILENTLY — the admit_to_stamp histogram
    must not grow by a single observation, and the on-disk records'
    stamps stay monotone (no re-stamping of already-durable output)."""
    from fluidframework_tpu.server.columnar_log import make_topic

    shared = str(tmp_path)
    reg = M.MetricsRegistry()
    prev = M.set_registry(reg)
    try:
        ing, tok, DeliRole = _mix_roles(shared, monkeypatch)
        deli = DeliRole(shared, "deli-g1", ttl_s=0.4, batch=512,
                        ckpt_interval_s=3600.0, ckpt_bytes=1 << 30)
        ingt = make_topic(
            os.path.join(shared, "topics", "ingress.jsonl"), "json"
        )
        ingt.append_many(
            [{"kind": "auth", "doc": "d0", "client": 1, "tenant": "t0",
              "token": tok},
             {"kind": "join", "doc": "d0", "client": 1}]
            + [{"kind": "op", "doc": "d0", "client": 1,
                "clientSeq": i + 1, "refSeq": 0, "contents": {"i": i}}
               for i in range(6)]
        )
        while ing.step() > 0:
            pass
        while deli.step() > 0:
            pass
        h = reg.histogram("op_stage_ms", stage="admit_to_stamp")
        observed = h.count
        assert observed == 6
        before = make_topic(
            os.path.join(shared, "topics", "deltas.jsonl"), "json"
        ).read_from(0)
        # "Crash": the role never checkpointed (cadence pinned high),
        # so a successor recovers from offset 0 and must silently
        # replay the whole durable gap.
        time.sleep(0.5)  # the dead owner's lease expires
        deli2 = DeliRole(shared, "deli-g2", ttl_s=0.4, batch=512,
                         ckpt_interval_s=3600.0, ckpt_bytes=1 << 30)
        deli2.step()  # acquire + recover
        assert deli2.fence is not None
        assert h.count == observed, (
            "recovery replay re-observed admit_to_stamp "
            f"({h.count} vs {observed})"
        )
        after = make_topic(
            os.path.join(shared, "topics", "deltas.jsonl"), "json"
        ).read_from(0)
        assert after == before  # replay emitted nothing new
    finally:
        M.set_registry(prev)


# ---------------------------------------------------------------------------
# per-partition p99: labeled series, merged scrape, autoscale trigger
# ---------------------------------------------------------------------------


def test_partitioned_stage_histograms_carry_partition_label(tmp_path):
    from fluidframework_tpu.server.supervisor import (
        BroadcasterRole,
        partitioned_role_class,
    )

    reg = M.MetricsRegistry()
    prev = M.set_registry(reg)
    try:
        role = partitioned_role_class(BroadcasterRole, 3)(
            str(tmp_path), "w0", ttl_s=3600.0
        )
        role._observe_stage("submit_to_broadcast", 5.0)
        snap = reg.snapshot()
        h = next(x for x in snap["histograms"]
                 if x["name"] == "op_stage_ms")
        assert h["labels"] == {"partition": "3",
                               "stage": "submit_to_broadcast"}
    finally:
        M.set_registry(prev)


def test_per_partition_p99_merge_and_q_gauges():
    """Worker heartbeats carry op_stage_ms{stage=...,partition=k}
    histograms; the supervisor scrape merges them, `stage_p99s` reads
    a farm-wide quantile (bucket-sum, not quantile-of-quantiles) plus
    the per-partition ones, and the Prometheus exposition grows
    partition-labeled `_q` gauges."""
    from fluidframework_tpu.server.shard_fabric import stage_p99s

    workers = []
    for rid, lat in (("ra", 2.0), ("rb", 60.0)):
        w = M.MetricsRegistry()
        h = w.histogram("op_stage_ms", stage="submit_to_stamp",
                        partition=rid)
        for _ in range(100):
            h.observe(lat)
        workers.append(w)
    merged = M.MetricsRegistry()
    for w in workers:
        merged.merge(w.snapshot())
    farm, per = stage_p99s(merged.snapshot(), "submit_to_stamp")
    assert set(per) == {"ra", "rb"}
    assert per["ra"] < 5.0 < per["rb"]
    # Farm-wide sits inside rb's bucket (half the mass at 60ms puts
    # the 99th percentile there), not at an average of quantiles.
    assert farm is not None and farm > per["ra"]
    text = merged.to_prometheus()
    assert 'fluid_op_stage_ms_q{partition="rb"' in text
    assert 'quantile="0.99"' in text


def test_autoscale_p99_per_partition_triggers_hot_range():
    """A single hot range's OWN p99 (not the farm-wide quantile, not
    the busiest range) drives the split when p99_per_partition is on;
    with it off, the old farm-wide behavior is unchanged."""
    from fluidframework_tpu.server.shard_fabric import AutoscalePolicy

    topo = {"epoch": 1, "ranges": [
        {"rid": "ra", "lo": 0, "hi": 8, "preds": []},
        {"rid": "rb", "lo": 8, "hi": 16, "preds": []},
    ]}
    pol = AutoscalePolicy(split_rate=1e9, merge_rate=0.0,
                          sustain_s=0.0, min_interval_s=0.0,
                          p99_hot_ms=10.0, p99_per_partition=True)
    # rb is latency-hot on its own series while ra carries more rate.
    cmd = pol.observe(1.0, {"ra": 5.0, "rb": 1.0}, topo,
                      p99_ms=None,
                      p99_by_partition={"ra": 2.0, "rb": 50.0})
    assert cmd == {"op": "split", "rid": "rb", "why": "autoscale-hot"}
    # Old behavior: farm-wide p99 marks the HIGHEST-RATE range hot.
    pol2 = AutoscalePolicy(split_rate=1e9, merge_rate=0.0,
                           sustain_s=0.0, min_interval_s=0.0,
                           p99_hot_ms=10.0)
    cmd2 = pol2.observe(1.0, {"ra": 5.0, "rb": 1.0}, topo,
                        p99_ms=50.0,
                        p99_by_partition={"ra": 2.0, "rb": 50.0})
    assert cmd2 == {"op": "split", "rid": "ra", "why": "autoscale-hot"}


# ---------------------------------------------------------------------------
# /slo counters + fabric /traces over HTTP
# ---------------------------------------------------------------------------


def test_slo_summary_surfaces_ingress_counters_only():
    reg = M.MetricsRegistry()
    reg.counter("ingress_nacks_total", reason="rate",
                role="ingress").inc(3)
    reg.counter("ingress_admitted_total", role="ingress").inc(7)
    reg.counter("role_records_total", role="deli").inc(100)
    body = M.slo_summary(reg.snapshot())
    names = {c["name"] for c in body["counters"]}
    assert names == {"ingress_nacks_total", "ingress_admitted_total"}
    json.dumps(body)  # the /slo body must stay strict-JSON-able


def test_fabric_traces_and_partition_slo_over_http(tmp_path):
    """THE fabric trace-coverage gate (ISSUE 13 satellite a+b over the
    wire): an ELASTIC fabric run with per-partition downstream stages
    and wire traces must serve NON-EMPTY partition-tagged spans on
    `/traces` and partition-labeled stage quantiles on `/slo` from the
    supervisor's monitor — the blind spot PR 9 left (spans were
    classic-runner-only) is closed."""
    from fluidframework_tpu.server.shard_fabric import (
        ShardFabricSupervisor,
        ShardRouter,
        spread_doc_names,
    )
    from fluidframework_tpu.testing.deli_bench import (
        build_pipeline_workload,
    )

    shared = str(tmp_path)
    env = {"FLUID_TRACE_WIRE": "1", "FLUID_TRACE_SLOW_MS": "0",
           "FLUID_DOORBELL": "1"}
    docs = spread_doc_names(4, 2)
    workload = build_pipeline_workload(4, 2, 4, doc_names=docs)
    sup = ShardFabricSupervisor(
        shared, n_workers=1, n_partitions=2, ttl_s=0.75,
        heartbeat_timeout_s=8.0, elastic=True, downstream="split",
        child_env=env,
    ).start()
    try:
        router = ShardRouter(shared, 2, elastic=True)
        now = time.time()
        router.append([{**r, "tr_sub": now} for r in workload])
        expected = len(workload)
        reader = router.merged_reader(base="broadcast")
        got = 0
        deadline = time.time() + 90.0
        while time.time() < deadline:
            sup.poll_once()
            got += sum(1 for r in reader.poll()
                       if isinstance(r, dict) and r.get("kind") == "op")
            if got >= expected:
                break
            time.sleep(0.02)
        assert got >= expected, f"fabric drained {got}/{expected}"
        time.sleep(0.6)  # one more worker heartbeat with the spans
        sup.poll_once()
        mon = sup.serve_metrics(port=0)
        traces = json.loads(scrape(mon.url + "/traces"))
        assert traces["slow_ops"], "/traces empty on the elastic fabric"
        assert any("partition" in s for s in traces["slow_ops"])
        slo = json.loads(scrape(mon.url + "/slo"))
        part_stages = [
            h for h in slo["histograms"]
            if h["name"] == "op_stage_ms" and "partition" in h["labels"]
        ]
        assert part_stages, "no partition-labeled stage quantiles"
        assert any(h["labels"]["stage"] == "submit_to_broadcast"
                   for h in part_stages)
    finally:
        sup.stop()


# ---------------------------------------------------------------------------
# chaos scenario: a storm DURING the faults
# ---------------------------------------------------------------------------


def test_scenario_workload_shape_and_validation():
    cfg = ChaosConfig(seed=3, n_docs=2, n_clients=3, ops_per_client=8,
                      scenario="hotdoc")
    base = ChaosConfig(seed=3, n_docs=2, n_clients=3, ops_per_client=8)
    w = build_workload(cfg)
    w0 = build_workload(base)
    assert len(w) > len(w0)
    storm = [i for i, r in enumerate(w)
             if isinstance(r.get("client"), int)
             and r["client"] > cfg.n_clients]
    assert storm, "no storm records"
    # Contiguous block in the middle (joins first, then the burst).
    assert storm == list(range(storm[0], storm[0] + len(storm)))
    assert 0 < storm[0] < len(w) - len(storm)
    # All storm records ride ONE viral doc.
    assert len({w[i]["doc"] for i in storm}) == 1
    with pytest.raises(ValueError, match="unknown scenario"):
        run_chaos(ChaosConfig(scenario="blizzard"))
    with pytest.raises(ValueError, match="summarizer"):
        run_chaos(ChaosConfig(scenario="hotdoc", summarizer=True))


@pytest.mark.chaos
def test_storm_during_split_and_kill_converges(tmp_path):
    """THE scenario-chaos acceptance gate: a hot-doc storm is IN
    FLIGHT while a kill and a live range split land (the seeded fault
    points are clamped into the storm window), kernel deli over
    columnar topics, per-partition downstream stages, wire traces on —
    the merged stream must converge bit-identical with zero dup/skip,
    the pre-split owner demonstrably fence-rejected, and the worker
    heartbeats must carry partition-tagged e2e spans."""
    res = run_chaos(ChaosConfig(
        seed=13, faults=("kill", "split"), n_docs=2, n_clients=3,
        ops_per_client=12, timeout_s=300.0, shared_dir=str(tmp_path),
        deli_impl="kernel", log_format="columnar",
        n_partitions=2, n_workers=2, elastic=True,
        trace_wire=True, downstream="split", scenario="hotdoc",
    ))
    assert res.converged, res.detail
    assert res.digest == res.golden_digest, res.detail
    assert res.duplicate_seqs == 0 and res.skipped_seqs == 0
    assert res.fence_rejections >= 1  # pre-split owner rejected
    assert len(res.epochs) > 1, res.epochs  # the split fired mid-storm
    assert res.downstream_ok
    assert any("storm spans chunks" in e for e in res.events)
    assert res.slow_ops, "elastic fabric produced no slow-op spans"
    assert any(s.get("partition") for s in res.slow_ops)
