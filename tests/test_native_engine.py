"""Differential tests: native C++ host engine vs the Python oracle.

The native engine (native/hostmerge.cpp via core/native_engine.py) is
a port of the oracle's exact segment-list algorithm; these farms gate
it bit-for-bit on real concurrency (lagging refSeqs, tie-breaks,
overlapping removes, pending-prop shadowing, acks, zamboni), plus the
reconnect regeneration path and the permutation-vector queries the
matrix DDS uses.
"""

import random

import pytest

from fluidframework_tpu.core.mergetree import (
    CollabClient,
    MergeTreeEngine,
    replay_passive,
)
from fluidframework_tpu.core.native_engine import (
    NativeMergeEngine,
    native_available,
)
from fluidframework_tpu.protocol.constants import UNASSIGNED_SEQ
from fluidframework_tpu.protocol.messages import MessageType
from fluidframework_tpu.testing.farm import (
    FarmConfig,
    char_spans,
    run_sharedstring_farm,
)

pytestmark = pytest.mark.skipif(
    not native_available(), reason="no C++ compiler for hostmerge"
)


def replay_native(stream, initial=""):
    """Passive native replica over a sequenced message stream (the
    native analog of replay_passive)."""
    from fluidframework_tpu.core.mergetree import apply_remote_op

    eng = NativeMergeEngine()
    if initial:
        eng.load(initial)

    class _Shim:
        pass

    for msg in stream:
        if msg.type == MessageType.OP and msg.contents is not None:
            apply_remote_op(
                eng, msg.contents, msg.ref_seq, msg.client_id,
                msg.sequence_number,
            )
        eng.current_seq = msg.sequence_number
        eng.update_min_seq(max(eng.min_seq, msg.minimum_sequence_number))
    return eng


def farm_native_vs_oracle(cfg: FarmConfig):
    farm = run_sharedstring_farm(cfg)
    oracle = replay_passive(farm.stream, cfg.initial_text)
    native = replay_native(farm.stream, cfg.initial_text)
    assert native.get_text() == oracle.get_text()
    assert char_spans(native.annotated_spans()) == char_spans(
        oracle.annotated_spans()
    )


@pytest.mark.parametrize("seed", range(5))
def test_native_passive_matches_oracle(seed):
    farm_native_vs_oracle(
        FarmConfig(num_clients=4, rounds=8, ops_per_client_per_round=4,
                   seed=seed)
    )


def test_native_remove_heavy():
    farm_native_vs_oracle(
        FarmConfig(
            num_clients=4, rounds=10, ops_per_client_per_round=4, seed=12,
            insert_weight=0.3, remove_weight=0.6, annotate_weight=0.1,
            initial_text="the quick brown fox jumps over the lazy dog",
        )
    )


def test_native_annotate_heavy():
    farm_native_vs_oracle(
        FarmConfig(
            num_clients=6, rounds=10, ops_per_client_per_round=4, seed=99,
            insert_weight=0.2, remove_weight=0.2, annotate_weight=0.6,
            initial_text="annotation heavy doc " * 3,
        )
    )


class NativeCollabClient(CollabClient):
    """CollabClient on the native engine (local pending ops + acks)."""

    def __init__(self, client_id: int, initial: str = ""):
        self.client_id = client_id
        self.engine = NativeMergeEngine(client_id)
        if initial:
            self.engine.load(initial)
        self.client_seq = 0


def test_native_interactive_farm_convergence():
    """Mixed farm: native and oracle clients collaborate in one
    session and must converge identically — the strongest gate (local
    pending state, acks, tie-breaks exercised on BOTH engines)."""
    from fluidframework_tpu.server.sequencer import DocumentSequencer

    rng = random.Random(7)
    seqr = DocumentSequencer("mixed")
    initial = "shared starting text"
    clients = [
        NativeCollabClient(1, initial),
        CollabClient(2, initial),
        NativeCollabClient(3, initial),
        CollabClient(4, initial),
    ]
    stream = []
    for c in clients:
        stream.append(seqr.join(c.client_id))
    for c in clients:
        for m in stream:
            c.apply_msg(m)
        c.engine.current_seq = seqr.seq
    from fluidframework_tpu.testing.farm import FarmConfig, random_op_for

    cfg = FarmConfig()
    for rnd in range(12):
        pending = []
        for c in clients:
            for _ in range(3):
                msg = random_op_for(c, rng, cfg)
                if msg is not None:
                    pending.append((c.client_id, msg))
        seqd = []
        for cid, msg in pending:
            out = seqr.sequence(cid, msg)
            assert out.__class__.__name__ == "SequencedMessage", out
            seqd.append(out)
        for c in clients:
            for m in seqd:
                c.apply_msg(m)
        texts = [c.get_text() for c in clients]
        assert len(set(texts)) == 1, f"round {rnd}: divergence"
    spans = [char_spans(c.engine.annotated_spans()) for c in clients]
    assert all(s == spans[0] for s in spans[1:])


def test_native_regenerate_insert_and_remove():
    """Reconnect regeneration parity: run the same pending state on
    both engines, regenerate, and compare the resubmitted ops."""
    from fluidframework_tpu.protocol.mergetree_ops import InsertOp, RemoveOp

    for Engine in (MergeTreeEngine, NativeMergeEngine):
        eng = (
            Engine(local_client_id=9)
            if Engine is MergeTreeEngine else Engine(9)
        )
        eng.collaborating = True
        eng.load("abcdefgh")
        eng.insert(4, "XY", 0, 9, UNASSIGNED_SEQ)
        grp_ins = (
            list(eng.pending)[-1]
            if Engine is MergeTreeEngine else eng.pending[-1]
        )
        eng.remove_range(1, 3, 0, 9, UNASSIGNED_SEQ)
        grp_rem = (
            list(eng.pending)[-1]
            if Engine is MergeTreeEngine else eng.pending[-1]
        )
        op_i, g_i = eng.regenerate_pending([grp_ins], InsertOp(pos=4, text="XY"))
        op_r, g_r = eng.regenerate_pending([grp_rem], RemoveOp(start=1, end=3))
        assert isinstance(op_i, InsertOp) and op_i.pos == 4
        assert op_i.text == "XY"
        assert isinstance(op_r, RemoveOp)
        assert (op_r.start, op_r.end) == (1, 3)
        assert len(g_i) == 1 and len(g_r) == 1


def test_native_permutation_queries():
    eng = NativeMergeEngine(5)
    eng.collaborating = True
    eng.load([10, 11, 12, 13])
    eng.insert(2, [50, 51], 0, 5, UNASSIGNED_SEQ)
    assert eng.get_items() == [10, 11, 50, 51, 12, 13]
    assert eng.item_at(2, eng.current_seq, 5) == 50
    assert eng.position_of_item(12, eng.current_seq, 5) == 4
    assert eng.position_of_item(999, eng.current_seq, 5) is None
    eng.remove_range(0, 2, 0, 5, UNASSIGNED_SEQ)
    assert eng.get_items() == [50, 51, 12, 13]
