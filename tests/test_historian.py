"""Historian cache tier (server/historian role): immutable blobs LRU-
cache in front of any store; refs invalidate on write-through and TTL
against out-of-band writers; a LocalServer runs transparently over
it."""

import pytest

from fluidframework_tpu.server.castore import ContentAddressedStore
from fluidframework_tpu.server.historian import HistorianCache


class CountingStore:
    def __init__(self):
        self.inner = ContentAddressedStore()
        self.reads = 0
        self.ref_reads = 0

    def put(self, content):
        return self.inner.put(content)

    def get(self, key):
        self.reads += 1
        return self.inner.get(key)

    def contains(self, key):
        return self.inner.contains(key)

    def set_ref(self, name, key):
        self.inner.set_ref(name, key)

    def get_ref(self, name):
        self.ref_reads += 1
        return self.inner.get_ref(name)

    def list_refs(self):
        return self.inner.list_refs()


def test_blob_cache_hits_and_lru_eviction():
    backing = CountingStore()
    h = HistorianCache(backing, blob_budget_bytes=100)
    k1 = h.put(b"a" * 40)
    k2 = h.put(b"b" * 40)
    assert h.get(k1) == b"a" * 40 and backing.reads == 0  # write-admit
    assert h.get(k2) == b"b" * 40 and backing.reads == 0
    k3 = h.put(b"c" * 40)  # evicts k1 (LRU after k1 touch... k2)
    assert h.get(k3) == b"c" * 40 and backing.reads == 0
    # k1 (LRU) was evicted: re-reading it misses and its readmission
    # evicts k2, which then misses too — 2 backing reads.
    h.get(k1)
    h.get(k2)
    assert backing.reads == 2
    # Oversized blobs pass through uncached.
    big = h.put(b"z" * 500)
    h.get(big)
    h.get(big)
    assert backing.reads == 4


def test_ref_cache_invalidation_and_ttl():
    backing = CountingStore()
    h = HistorianCache(backing, ref_ttl=3600.0)
    k1 = h.put(b"one")
    k2 = h.put(b"two")
    h.set_ref("doc", k1)
    assert h.get_ref("doc") == k1 and backing.ref_reads == 0
    # Write-through invalidates immediately.
    h.set_ref("doc", k2)
    assert h.get_ref("doc") == k2 and backing.ref_reads == 0
    # Out-of-band write: served stale within TTL...
    backing.set_ref("doc", k1)
    assert h.get_ref("doc") == k2
    # ...and refreshed once the TTL lapses.
    h.ref_ttl = 0.0
    assert h.get_ref("doc") == k1
    assert backing.ref_reads == 1


def test_local_server_over_historian():
    from fluidframework_tpu.dds import StringFactory
    from fluidframework_tpu.loader import Loader
    from fluidframework_tpu.drivers.local_driver import LocalDriver
    from fluidframework_tpu.runtime import ChannelRegistry
    from fluidframework_tpu.server import LocalServer

    srv = LocalServer(historian_budget=1 << 20)
    registry = ChannelRegistry([StringFactory()])
    loader = Loader(LocalDriver(srv), registry)
    c = loader.create_detached()
    c.runtime.create_datastore("default").create_channel(
        "s", StringFactory.type_name
    )
    doc = c.attach()
    c.runtime.get_datastore("default").get_channel("s").insert_text(0, "hi")
    c.runtime.flush()
    srv.process_all()
    # A second load hits the historian cache for the summary blobs.
    before = srv.storage.stats()
    c2 = loader.resolve(doc)
    after = srv.storage.stats()
    assert after["hits"] > before["hits"]
    assert (
        c2.runtime.get_datastore("default").get_channel("s").get_text()
        in ("", "hi")  # summary predates the op; catch-up delivers it
    )
    srv.process_all()
    assert (
        c2.runtime.get_datastore("default").get_channel("s").get_text()
        == "hi"
    )


# ---------------------------------------------------------------------------
# hardening: eviction at the budget boundary, ref races, metrics
# ---------------------------------------------------------------------------


def test_evict_under_budget_at_boundary_sizes():
    """Blobs sized AT and AROUND blob_budget_bytes: the cache must
    never exceed its budget, a budget-sized blob is admissible alone,
    and an over-budget blob passes through uncached."""
    budget = 100
    backing = CountingStore()
    h = HistorianCache(backing, blob_budget_bytes=budget)
    k_exact = h.put(b"e" * budget)  # == budget: admissible, fills it
    assert h.stats()["cached_bytes"] == budget
    assert h.get(k_exact) == b"e" * budget and backing.reads == 0
    k_one = h.put(b"a" * 1)  # admitting 1 byte must evict the filler
    assert h.stats()["cached_bytes"] <= budget
    assert h.get(k_one) == b"a" and backing.reads == 0
    h.get(k_exact)  # evicted: backing read, readmission evicts k_one
    assert backing.reads == 1
    assert h.stats()["cached_bytes"] <= budget
    k_over = h.put(b"z" * (budget + 1))  # > budget: never cached
    h.get(k_over)
    h.get(k_over)
    assert backing.reads == 3
    assert h.stats()["cached_bytes"] <= budget
    # Near-boundary churn: every admission keeps the invariant.
    for i in range(10):
        h.put(bytes([i]) * (budget - 3))
        assert h.stats()["cached_bytes"] <= budget


def test_get_ref_set_ref_race():
    """Concurrent set_ref/get_ref hammering one name: no exception,
    no torn read (every observed value is one some writer wrote), and
    the final read-through agrees with the backing store."""
    import threading

    backing = CountingStore()
    h = HistorianCache(backing, blob_budget_bytes=1 << 20, ref_ttl=0.001)
    keys = [h.put(f"blob-{i}".encode()) for i in range(8)]
    stop = threading.Event()
    seen = []
    errors = []

    def writer(i):
        try:
            j = 0
            while not stop.is_set():
                h.set_ref("doc", keys[(i + j) % len(keys)])
                j += 1
        except Exception as exc:  # pragma: no cover - the assertion
            errors.append(exc)

    def reader():
        try:
            while not stop.is_set():
                v = h.get_ref("doc")
                if v is not None:
                    seen.append(v)
        except Exception as exc:  # pragma: no cover - the assertion
            errors.append(exc)

    threads = [threading.Thread(target=writer, args=(i,))
               for i in range(2)] + [threading.Thread(target=reader)
                                     for _ in range(2)]
    for t in threads:
        t.start()
    import time as _time

    _time.sleep(0.3)
    stop.set()
    for t in threads:
        t.join(timeout=5)
    assert not errors
    assert seen and all(v in keys for v in seen)
    # Write-through means the cache and backing converge once quiet.
    h.ref_ttl = 0.0
    assert h.get_ref("doc") == backing.inner.get_ref("doc")


def test_historian_metrics_gauges():
    """The Prometheus surface: historian_blob_bytes tracks the cached
    payload, hits/misses count, evictions count — per-cache labels."""
    from fluidframework_tpu.utils import metrics as M

    reg = M.MetricsRegistry()
    prev = M.set_registry(reg)
    try:
        h = HistorianCache(CountingStore(), blob_budget_bytes=100,
                           name="t")
    finally:
        M.set_registry(prev)
    k1 = h.put(b"a" * 60)
    h.put(b"b" * 60)  # evicts k1
    assert reg.gauge("historian_blob_bytes", cache="t").value == 60
    assert reg.gauge("historian_blobs", cache="t").value == 1
    assert reg.counter("historian_evictions_total", cache="t").value == 1
    h.get(k1)  # miss (evicted)
    hits0 = reg.counter("historian_hits_total", cache="t").value
    h.get(k1)  # hit (readmitted)
    assert reg.counter("historian_misses_total", cache="t").value >= 1
    assert reg.counter("historian_hits_total", cache="t").value \
        == hits0 + 1
    text = reg.to_prometheus()
    assert "historian_blob_bytes" in text
    assert 'cache="t"' in text
