"""Historian cache tier (server/historian role): immutable blobs LRU-
cache in front of any store; refs invalidate on write-through and TTL
against out-of-band writers; a LocalServer runs transparently over
it."""

import pytest

from fluidframework_tpu.server.castore import ContentAddressedStore
from fluidframework_tpu.server.historian import HistorianCache


class CountingStore:
    def __init__(self):
        self.inner = ContentAddressedStore()
        self.reads = 0
        self.ref_reads = 0

    def put(self, content):
        return self.inner.put(content)

    def get(self, key):
        self.reads += 1
        return self.inner.get(key)

    def contains(self, key):
        return self.inner.contains(key)

    def set_ref(self, name, key):
        self.inner.set_ref(name, key)

    def get_ref(self, name):
        self.ref_reads += 1
        return self.inner.get_ref(name)

    def list_refs(self):
        return self.inner.list_refs()


def test_blob_cache_hits_and_lru_eviction():
    backing = CountingStore()
    h = HistorianCache(backing, blob_budget_bytes=100)
    k1 = h.put(b"a" * 40)
    k2 = h.put(b"b" * 40)
    assert h.get(k1) == b"a" * 40 and backing.reads == 0  # write-admit
    assert h.get(k2) == b"b" * 40 and backing.reads == 0
    k3 = h.put(b"c" * 40)  # evicts k1 (LRU after k1 touch... k2)
    assert h.get(k3) == b"c" * 40 and backing.reads == 0
    # k1 (LRU) was evicted: re-reading it misses and its readmission
    # evicts k2, which then misses too — 2 backing reads.
    h.get(k1)
    h.get(k2)
    assert backing.reads == 2
    # Oversized blobs pass through uncached.
    big = h.put(b"z" * 500)
    h.get(big)
    h.get(big)
    assert backing.reads == 4


def test_ref_cache_invalidation_and_ttl():
    backing = CountingStore()
    h = HistorianCache(backing, ref_ttl=3600.0)
    k1 = h.put(b"one")
    k2 = h.put(b"two")
    h.set_ref("doc", k1)
    assert h.get_ref("doc") == k1 and backing.ref_reads == 0
    # Write-through invalidates immediately.
    h.set_ref("doc", k2)
    assert h.get_ref("doc") == k2 and backing.ref_reads == 0
    # Out-of-band write: served stale within TTL...
    backing.set_ref("doc", k1)
    assert h.get_ref("doc") == k2
    # ...and refreshed once the TTL lapses.
    h.ref_ttl = 0.0
    assert h.get_ref("doc") == k1
    assert backing.ref_reads == 1


def test_local_server_over_historian():
    from fluidframework_tpu.dds import StringFactory
    from fluidframework_tpu.loader import Loader
    from fluidframework_tpu.drivers.local_driver import LocalDriver
    from fluidframework_tpu.runtime import ChannelRegistry
    from fluidframework_tpu.server import LocalServer

    srv = LocalServer(historian_budget=1 << 20)
    registry = ChannelRegistry([StringFactory()])
    loader = Loader(LocalDriver(srv), registry)
    c = loader.create_detached()
    c.runtime.create_datastore("default").create_channel(
        "s", StringFactory.type_name
    )
    doc = c.attach()
    c.runtime.get_datastore("default").get_channel("s").insert_text(0, "hi")
    c.runtime.flush()
    srv.process_all()
    # A second load hits the historian cache for the summary blobs.
    before = srv.storage.stats()
    c2 = loader.resolve(doc)
    after = srv.storage.stats()
    assert after["hits"] > before["hits"]
    assert (
        c2.runtime.get_datastore("default").get_channel("s").get_text()
        in ("", "hi")  # summary predates the op; catch-up delivers it
    )
    srv.process_all()
    assert (
        c2.runtime.get_datastore("default").get_channel("s").get_text()
        == "hi"
    )
