"""The observability layer (ISSUE 3): metrics registry semantics and
concurrency, op-lifecycle tracing across a LocalServer round-trip, the
live /metrics + /healthz endpoint, checkpoint cadence, and the
supervisor-side heartbeat-snapshot merge.

Determinism contract checked elsewhere but relied on here: traces and
metrics are observational only — chaos suites (tests/
test_chaos_recovery.py) and the deli differential suites (tests/
test_deli_kernel.py) run with tracing enabled (it is always on) and
still converge bit-identical to their goldens.
"""

from __future__ import annotations

import json
import re
import threading
import time
import urllib.request

import pytest

from fluidframework_tpu.dds import StringFactory
from fluidframework_tpu.runtime import ChannelRegistry, ContainerRuntime
from fluidframework_tpu.server import LocalServer
from fluidframework_tpu.server.monitor import MetricsServer
from fluidframework_tpu.utils import metrics as M

REGISTRY = ChannelRegistry([StringFactory()])


@pytest.fixture
def fresh_registry():
    """Isolate each test's instruments from the process default (the
    default registry is process-global by design)."""
    reg = M.MetricsRegistry()
    old = M.set_registry(reg)
    yield reg
    M.set_registry(old)


def connect_runtime(server, doc="doc", client_id=None):
    rt = ContainerRuntime(REGISTRY)
    ds = rt.create_datastore("default")
    ds.create_channel("s", StringFactory.type_name)
    rt.connect(server.connect(doc, client_id))
    return rt


def scrape(url: str):
    return urllib.request.urlopen(url, timeout=10).read().decode()


def parse_prometheus(text: str):
    """Line form -> {metric{labels}: float} (scrape-parses cleanly)."""
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = re.fullmatch(r"([a-zA-Z_:][\w:]*(?:\{[^}]*\})?) (\S+)", line)
        assert m, f"unparseable exposition line: {line!r}"
        out[m.group(1)] = float(m.group(2))
    return out


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------


def test_counter_gauge_basics():
    reg = M.MetricsRegistry()
    c = reg.counter("ops_total", role="deli")
    c.inc()
    c.inc(2.5)
    assert reg.counter("ops_total", role="deli") is c  # create-or-return
    assert c.value == 3.5
    g = reg.gauge("fill", role="deli")
    g.set(4)
    g.inc()
    g.dec(2)
    assert g.value == 3.0
    # Same name different labels = distinct instrument.
    assert reg.counter("ops_total", role="scribe").value == 0
    # Same name different KIND is a registration error.
    with pytest.raises(ValueError):
        reg.gauge("ops_total", role="deli")


def test_histogram_bucket_edges():
    """Prometheus `le` semantics: an observation exactly on a bound
    lands IN that bucket; just above goes to the next; beyond the last
    bound goes to +Inf."""
    reg = M.MetricsRegistry()
    h = reg.histogram("lat_ms", buckets=(1.0, 5.0, 10.0))
    for v in (0.0, 1.0, 1.0000001, 5.0, 10.0, 10.1):
        h.observe(v)
    assert h.counts == [2, 2, 1, 1]  # [<=1, <=5, <=10, +Inf]
    assert h.count == 6
    assert h.sum == pytest.approx(27.1000001)
    # Re-registering with different buckets is an error; same is fine.
    assert reg.histogram("lat_ms", buckets=(1.0, 5.0, 10.0)) is h
    with pytest.raises(ValueError):
        reg.histogram("lat_ms", buckets=(1.0, 2.0))
    # Quantile interpolation stays inside the right bucket.
    snap = reg.snapshot()["histograms"][0]
    assert 0 <= M.histogram_quantile(snap, 0.25) <= 1.0
    assert M.histogram_quantile(snap, 1.0) == float("inf")


def test_registry_concurrency_exact_totals():
    """The lock-safety contract: concurrent increments/observations
    lose nothing."""
    reg = M.MetricsRegistry()
    n_threads, n_iter = 8, 5000
    c = reg.counter("hits")
    h = reg.histogram("obs_ms", buckets=(1.0, 10.0))

    def work():
        for i in range(n_iter):
            c.inc()
            h.observe(i % 20)

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == n_threads * n_iter
    assert h.count == n_threads * n_iter
    assert sum(h.counts) == h.count


def test_merge_and_report():
    a = M.MetricsRegistry()
    a.counter("x_total", role="deli").inc(3)
    a.histogram("lat_ms", buckets=(1.0, 2.0)).observe(1.5)
    a.gauge("fill").set(0.25)
    b = M.MetricsRegistry()
    b.merge(a.snapshot())
    b.merge(a.snapshot())  # counters/histograms ADD, gauges last-write
    assert b.counter("x_total", role="deli").value == 6
    h = b.histogram("lat_ms", buckets=(1.0, 2.0))
    assert h.count == 2 and h.counts == [0, 2, 0]
    assert b.gauge("fill").value == 0.25
    report = M.format_report([a.snapshot(), a.snapshot()])
    assert "lat_ms" in report and "x_total" in report
    assert "role=deli" in report


def test_histogram_snapshot_consistent_under_concurrent_observe():
    """The ISSUE-9 satellite fix: a snapshot's explicit sum/count must
    agree with its buckets even while observers race — the fields are
    copied under the instruments' lock, so no torn (sum != counts)
    snapshot can reach merge()/quantile estimation."""
    reg = M.MetricsRegistry()
    h = reg.histogram("lat_ms", buckets=(10.0,))
    stop = threading.Event()

    def work():
        while not stop.is_set():
            h.observe(5.0)  # every observation adds exactly 5 to sum

    threads = [threading.Thread(target=work) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        for _ in range(200):
            snap = reg.snapshot()["histograms"][0]
            assert snap["sum"] == pytest.approx(5.0 * snap["count"])
            assert sum(snap["counts"]) == snap["count"]
    finally:
        stop.set()
        for t in threads:
            t.join()


def test_merge_preserves_sum_count_quantiles_across_processes():
    """Two 'process' snapshots (JSON round-tripped, as the heartbeat
    channel carries them) merged into one registry must reproduce the
    exact sum/count and the same quantile estimates as a registry that
    observed every value directly."""
    values_a = [0.3, 1.5, 4.0, 9.0, 60.0]
    values_b = [0.7, 2.0, 30.0, 400.0]
    a, b, direct = (M.MetricsRegistry() for _ in range(3))
    for reg, vals in ((a, values_a), (b, values_b),
                      (direct, values_a + values_b)):
        h = reg.histogram("lat_ms")
        for v in vals:
            h.observe(v)
    merged = M.MetricsRegistry()
    for reg in (a, b):
        merged.merge(json.loads(json.dumps(reg.snapshot())))
    got = merged.snapshot()["histograms"][0]
    want = direct.snapshot()["histograms"][0]
    assert got["counts"] == want["counts"]
    assert got["count"] == want["count"] == 9
    assert got["sum"] == pytest.approx(want["sum"])
    for q in (0.5, 0.95, 0.99):
        assert M.histogram_quantile(got, q) == pytest.approx(
            M.histogram_quantile(want, q)
        )
    assert got["quantiles"] == want["quantiles"]


def test_histogram_stats_and_slo_summary():
    reg = M.MetricsRegistry()
    h = reg.histogram("op_stage_ms", buckets=(1.0, 10.0, 100.0),
                      stage="submit_to_broadcast")
    for v in (0.5, 5.0, 50.0, 500.0):
        h.observe(v)
    reg.histogram("empty_ms", buckets=(1.0,))  # no observations
    snap = reg.snapshot()
    stats = M.histogram_stats(
        next(x for x in snap["histograms"] if x["name"] == "op_stage_ms")
    )
    assert stats["count"] == 4
    assert stats["mean"] == pytest.approx(555.5 / 4)
    assert 0 < stats["p50"] <= 10.0
    assert stats["p99"] == float("inf")  # beyond the last bucket
    slo = M.slo_summary(snap)
    [entry] = slo["histograms"]  # empty histograms are omitted
    assert entry["name"] == "op_stage_ms"
    assert entry["labels"] == {"stage": "submit_to_broadcast"}
    assert entry["p99"] is None  # JSON-safe overflow marker
    json.dumps(slo)  # the /slo body must be strict-JSON-able


def test_prometheus_quantile_series():
    reg = M.MetricsRegistry()
    h = reg.histogram("lat_ms", buckets=(1.0, 10.0))
    for v in (0.5, 0.6, 0.8, 5.0):
        h.observe(v)
    vals = parse_prometheus(reg.to_prometheus())
    assert 0 < vals['fluid_lat_ms_q{quantile="0.5"}'] <= 1.0
    assert 1.0 < vals['fluid_lat_ms_q{quantile="0.99"}'] <= 10.0
    # An estimate beyond the last finite bucket is omitted, not faked.
    h.observe(100.0)
    h.observe(100.0)
    vals = parse_prometheus(reg.to_prometheus())
    assert 'fluid_lat_ms_q{quantile="0.99"}' not in vals


def test_prometheus_exposition_parses():
    reg = M.MetricsRegistry()
    reg.counter("ops_total", role="deli").inc(7)
    h = reg.histogram("lat_ms", buckets=(1.0, 5.0))
    for v in (0.5, 3.0, 9.0):
        h.observe(v)
    vals = parse_prometheus(reg.to_prometheus())
    assert vals['fluid_ops_total{role="deli"}'] == 7
    # Cumulative buckets, +Inf == _count.
    assert vals['fluid_lat_ms_bucket{le="1"}'] == 1
    assert vals['fluid_lat_ms_bucket{le="5"}'] == 2
    assert vals['fluid_lat_ms_bucket{le="+Inf"}'] == 3
    assert vals["fluid_lat_ms_count"] == 3
    assert vals["fluid_lat_ms_sum"] == pytest.approx(12.5)


def test_set_enabled_swaps_null_registry():
    old = M.set_enabled(False)
    try:
        reg = M.get_registry()
        assert isinstance(reg, M.NullRegistry)
        reg.counter("whatever", role="x").inc()  # no-op, no error
        assert reg.to_prometheus() == ""
    finally:
        M.set_enabled(old)
    assert not isinstance(M.get_registry(), M.NullRegistry)


# ---------------------------------------------------------------------------
# op-lifecycle tracing across the live pipeline
# ---------------------------------------------------------------------------


def test_trace_monotone_across_localserver_roundtrip(fresh_registry):
    """Every sequenced op carries monotone per-stage timestamps
    (submit ≤ stamp ≤ durable ≤ broadcast) and the apply side folds
    them into nonzero stage histograms."""
    server = LocalServer()
    a = connect_runtime(server, client_id=1)
    b = connect_runtime(server, client_id=2)
    a.get_datastore("default").get_channel("s").insert_text(0, "hello")
    a.flush()
    b.get_datastore("default").get_channel("s").insert_text(0, ">> ")
    b.flush()
    order = {"submit": 0, "stamp": 1, "durable": 2, "broadcast": 3}
    data_ops = 0
    for msg in server.ops_from("doc", 0):
        assert msg.traces, f"untraced sequenced message seq={msg.sequence_number}"
        stages = [s for s, _ in msg.traces]
        assert stages == sorted(stages, key=order.__getitem__)
        ts = [t for _, t in msg.traces]
        assert ts == sorted(ts), f"non-monotone trace {msg.traces}"
        if "submit" in stages:
            data_ops += 1
            assert stages[0] == "submit"  # client-driver origin stamp
    assert data_ops == 2
    # All four stage histograms observed something.
    for stage in ("submit_to_stamp", "stamp_to_durable",
                  "stamp_to_broadcast", "broadcast_to_apply",
                  "submit_to_apply"):
        h = fresh_registry.histogram("op_stage_ms", stage=stage)
        assert h.count > 0, f"stage {stage} never observed"
    # Wire-format semantics for batch markers are unchanged by the
    # trace stamp: the trace rides metadata under its own key.
    raws = server.log.topic("rawdeltas").read(0)
    op_raws = [r for r in raws if r.get("kind") == "op"]
    assert all("tr_sub" in r["msg"].metadata for r in op_raws)


def test_metrics_endpoint_scrape_localserver(fresh_registry):
    server = LocalServer()
    rt = connect_runtime(server, client_id=1)
    rt.get_datastore("default").get_channel("s").insert_text(0, "x")
    rt.flush()
    mon = server.serve_metrics()
    try:
        assert server.serve_metrics() is mon  # idempotent
        vals = parse_prometheus(scrape(mon.url + "/metrics"))
        assert vals['fluid_op_stage_ms_count{stage="submit_to_stamp"}'] >= 1
        assert vals['fluid_deli_pump_records_count{impl="scalar"}'] >= 1
        hz = json.loads(scrape(mon.url + "/healthz"))
        assert hz["status"] == "ok" and hz["docs"] == 1
        snap = json.loads(scrape(mon.url + "/metrics.json"))
        assert any(
            h["name"] == "op_stage_ms" and h["count"] > 0
            for h in snap["histograms"]
        )
        with pytest.raises(urllib.error.HTTPError):
            scrape(mon.url + "/nope")
    finally:
        server.stop_metrics()


def test_kernel_deli_occupancy_gauges(fresh_registry):
    """The acceptance-criteria shape: a kernel-deli LocalServer run
    serves /metrics with nonzero op-latency histograms AND kernel
    occupancy gauges."""
    server = LocalServer(deli_impl="kernel")
    for d in range(3):
        rt = connect_runtime(server, doc=f"doc{d}", client_id=1)
        rt.get_datastore("default").get_channel("s").insert_text(0, "k")
        rt.flush()
    mon = server.serve_metrics()
    try:
        vals = parse_prometheus(scrape(mon.url + "/metrics"))
        assert vals["fluid_deli_pool_resident_docs"] == 3
        assert vals["fluid_deli_pool_doc_slots"] >= 3
        assert 0 < vals["fluid_deli_pool_fill_ratio"] <= 1
        assert vals['fluid_deli_pump_records_count{impl="kernel"}'] >= 3
        assert vals['fluid_op_stage_ms_count{stage="submit_to_stamp"}'] >= 3
        assert vals['fluid_op_stage_ms_count{stage="submit_to_apply"}'] >= 3
    finally:
        server.stop_metrics()


def test_kernel_pool_grow_evict_counters(fresh_registry):
    """Doc-slot pool growth and eviction are visible as counters."""
    from fluidframework_tpu.server.deli_kernel import SeqPool

    pool = SeqPool(n_docs=2, n_clients=2, max_resident=2)
    for i in range(5):
        pool.begin()
        pool.touch(f"doc{i}")
    grows = fresh_registry.counter("deli_pool_grows_total").value
    evicts = fresh_registry.counter("deli_pool_evictions_total").value
    assert evicts >= 3  # max_resident=2 parked the cold docs
    assert grows == 0  # eviction kept the pool at its cap
    # Touching everything in ONE pump forces growth (actives can't park).
    pool.begin()
    for i in range(5):
        pool.touch(f"doc{i}")
    assert fresh_registry.counter("deli_pool_grows_total").value >= 1


# ---------------------------------------------------------------------------
# checkpoint cadence (ROADMAP item (b))
# ---------------------------------------------------------------------------


def _mk_deli_role(tmp_path, fresh_registry, **kw):
    from fluidframework_tpu.server.queue import SharedFileTopic
    from fluidframework_tpu.server.supervisor import DeliRole

    role = DeliRole(str(tmp_path), owner="cadence-test", ttl_s=3600.0,
                    batch=8, **kw)
    raw = SharedFileTopic(str(tmp_path / "topics" / "rawdeltas.jsonl"))
    return role, raw


def test_checkpoint_cadence_time_byte_bounds(tmp_path, fresh_registry):
    """With both bounds huge, steps stop writing per-step checkpoints
    (the seed behavior); dropping either bound to zero resumes them.
    Durability is unaffected: recovery replays the checkpoint→durable
    gap (chaos suites prove that under kills)."""
    role, raw = _mk_deli_role(
        tmp_path, fresh_registry,
        ckpt_interval_s=3600.0, ckpt_bytes=1 << 40,
    )
    writes = fresh_registry.counter("checkpoint_writes_total", role="deli")
    raw.append_many([
        {"kind": "join", "doc": "d", "client": 1},
        {"kind": "op", "doc": "d", "client": 1, "clientSeq": 1,
         "refSeq": 0, "contents": {"i": 0}},
    ])
    assert role.step() == 2
    baseline = writes.value  # _recover()'s forced anchor checkpoint
    for i in range(2, 6):
        raw.append({"kind": "op", "doc": "d", "client": 1,
                    "clientSeq": i, "refSeq": 0, "contents": {"i": i}})
        assert role.step() == 1
    assert writes.value == baseline  # cadence held: no per-step writes
    assert role._ckpt_dirty
    # Byte bound: one more appended byte crosses it -> checkpoint.
    role.ckpt_bytes = 1
    raw.append({"kind": "op", "doc": "d", "client": 1, "clientSeq": 6,
                "refSeq": 0, "contents": {"i": 6}})
    role.step()
    assert writes.value == baseline + 1
    assert not role._ckpt_dirty
    # Time bound: interval 0 == the seed's every-step policy.
    role.ckpt_bytes = 1 << 40
    role.ckpt_interval_s = 0.0
    raw.append({"kind": "op", "doc": "d", "client": 1, "clientSeq": 7,
                "refSeq": 0, "contents": {"i": 7}})
    role.step()
    assert writes.value == baseline + 2
    # The durable checkpoint offset matches everything consumed, and
    # bytes/duration metrics recorded every write.
    env = role.ckpt.load("deli")
    assert env["state"]["offset"] == role.offset
    assert fresh_registry.counter(
        "checkpoint_bytes_total", role="deli").value > 0
    assert fresh_registry.histogram(
        "checkpoint_ms", role="deli").count == writes.value


def test_checkpoint_cadence_idle_flush(tmp_path, fresh_registry):
    """Progress folded before quiescence goes durable from the IDLE
    step once the interval elapses — a quiet stream cannot pin dirty
    state in memory forever."""
    # ckpt_duty=0 disables the storm guard: this test is about the
    # idle-flush contract alone, and an fsync stall on a loaded box
    # (last write cost S -> next gated for 5*S with the default duty)
    # would otherwise outlast the 60ms sleep below and flake.
    role, raw = _mk_deli_role(
        tmp_path, fresh_registry,
        ckpt_interval_s=0.05, ckpt_bytes=1 << 40, ckpt_duty=0.0,
    )
    writes = fresh_registry.counter("checkpoint_writes_total", role="deli")
    raw.append({"kind": "join", "doc": "d", "client": 1})
    role.step()
    before = writes.value
    if not role._ckpt_dirty:
        # The batch step itself crossed the 50ms interval and flushed;
        # make new dirty progress to exercise the idle path.
        raw.append({"kind": "op", "doc": "d", "client": 1,
                    "clientSeq": 1, "refSeq": 0, "contents": {}})
        role.step()
        before = writes.value
    if role._ckpt_dirty:
        time.sleep(0.06)
        role.step(idle_sleep=0.0)  # no new input: the idle branch
        assert writes.value >= before + 1
    assert not role._ckpt_dirty


# ---------------------------------------------------------------------------
# supervisor-side merge + endpoint
# ---------------------------------------------------------------------------


def test_supervisor_merges_heartbeat_metrics(tmp_path, fresh_registry):
    """Children report metrics up through the heartbeat channel; the
    supervisor's registry (and /metrics endpoint) merges the
    snapshots per scrape, plus its own liveness gauges."""
    from fluidframework_tpu.server.supervisor import ServiceSupervisor

    child = M.MetricsRegistry()
    child.counter("role_records_total", role="deli").inc(42)
    child.histogram("checkpoint_ms", role="deli").observe(3.0)
    hb_dir = tmp_path / "hb"
    hb_dir.mkdir(exist_ok=True)
    (hb_dir / "deli.json").write_text(json.dumps({
        "pid": 1, "owner": "deli-g1", "t": time.time(),
        "metrics": child.snapshot(),
    }))
    sup = ServiceSupervisor(str(tmp_path), roles=("deli", "scribe"))
    reg = sup.collect_metrics()
    assert reg.counter("role_records_total", role="deli").value == 42
    assert reg.gauge("supervisor_child_alive", role="deli").value == 0
    assert reg.gauge("supervisor_restarts", role="scribe").value == 0
    health = sup.health()
    assert health["status"] == "degraded"  # nothing actually running
    assert health["roles"]["deli"]["alive"] is False
    mon = sup.serve_metrics()
    try:
        vals = parse_prometheus(scrape(mon.url + "/metrics"))
        assert vals['fluid_role_records_total{role="deli"}'] == 42
        assert vals['fluid_checkpoint_ms_count{role="deli"}'] == 1
        assert 'fluid_supervisor_restarts{role="deli"}' in vals
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            scrape(mon.url + "/healthz")
        assert exc_info.value.code == 503  # degraded farm -> 503
        assert json.loads(exc_info.value.read())["status"] == "degraded"
    finally:
        sup.stop()
    assert sup._monitor is None
