"""SharedTree transactions + repair-data undo/redo.

Reference seams: `SharedTreeBranch` transactions
(packages/dds/tree/src/shared-tree-core/branch.ts:95 startTransaction,
transactionStack.ts:12) — squash-on-commit, abort-via-repair-data —
and the undo/redo path through captured repair data rebased over
subsequent commits.
"""

import pytest

from fluidframework_tpu.framework.undo_redo import (
    SharedTreeUndoRedoHandler,
    UndoRedoStackManager,
)
from fluidframework_tpu.runtime import ChannelRegistry
from fluidframework_tpu.testing.mocks import MultiClientHarness
from fluidframework_tpu.tree.shared_tree import SharedTreeFactory


def _harness(n=2):
    reg = ChannelRegistry([SharedTreeFactory()])
    h = MultiClientHarness(
        n, reg, channel_types=[("t", SharedTreeFactory.type_name)]
    )
    trees = [
        rt.get_datastore("default").get_channel("t") for rt in h.runtimes
    ]
    return h, trees


def _vals(tree, field="f"):
    return [n.get("value") for n in tree.view()["fields"].get(field, [])]


# ---------------------------------------------------------------------------
# branch transactions
# ---------------------------------------------------------------------------


def test_branch_transaction_commit_squashes():
    h, (t0, t1) = _harness()
    t0.insert_node([], "f", 0, [{"type": "n", "value": i} for i in range(3)])
    h.process_all()
    b = t0.branch()
    b.start_transaction()
    b.insert_node([], "f", 3, [{"type": "n", "value": 3}])
    b.insert_node([], "f", 4, [{"type": "n", "value": 4}])
    b.remove_node([], "f", 0)
    squashed = b.commit_transaction()
    # One composed commit replaced the three.
    assert len(b.commits) == 1
    assert len(squashed) == 3
    assert [n.get("value") for n in b.view()["fields"]["f"]] == [1, 2, 3, 4]
    b.merge_into()
    h.process_all()
    assert _vals(t0) == [1, 2, 3, 4]
    assert t0.view() == t1.view()


def test_branch_transaction_abort_restores_via_repair_data():
    h, (t0, _) = _harness()
    t0.insert_node([], "f", 0, [{"type": "n", "value": i} for i in range(3)])
    h.process_all()
    b = t0.branch()
    b.set_value([["f", 1]], "kept")
    b.start_transaction()
    b.remove_node([], "f", 0, 2)          # repair data: removed subtrees
    b.set_value([["f", 0]], "scratch")    # repair data: prior value
    b.move_node([], "f", 0, 1, [], "g", 0)
    b.insert_node([], "f", 0, [{"type": "n", "value": 99}])
    b.abort_transaction()
    # Back to the pre-transaction branch state, pre-tx edit intact.
    assert [n.get("value") for n in b.view()["fields"]["f"]] == [0, "kept", 2]
    assert "g" not in b.view()["fields"]
    assert len(b.commits) == 1  # the pre-transaction set_value


def test_branch_nested_transactions():
    h, (t0, _) = _harness()
    t0.insert_node([], "f", 0, [{"type": "n", "value": 0}])
    h.process_all()
    b = t0.branch()
    b.start_transaction()
    b.insert_node([], "f", 1, [{"type": "n", "value": 1}])
    b.start_transaction()                 # nested
    b.insert_node([], "f", 2, [{"type": "n", "value": 2}])
    b.abort_transaction()                 # inner aborts alone
    assert [n.get("value") for n in b.view()["fields"]["f"]] == [0, 1]
    b.start_transaction()
    b.insert_node([], "f", 2, [{"type": "n", "value": 22}])
    b.commit_transaction()                # inner commits into outer
    assert b.in_transaction
    b.commit_transaction()                # outer: everything squashes
    assert not b.in_transaction
    assert len(b.commits) == 1
    assert [n.get("value") for n in b.view()["fields"]["f"]] == [0, 1, 22]


# ---------------------------------------------------------------------------
# tree-level transactions
# ---------------------------------------------------------------------------


def test_tree_transaction_lands_one_atomic_commit():
    h, (t0, t1) = _harness()
    t0.insert_node([], "f", 0, [{"type": "n", "value": 0}])
    h.process_all()
    sent = []
    t0.on("localCommit", lambda c: sent.append(c))
    t0.start_transaction()
    t0.insert_node([], "f", 1, [{"type": "n", "value": 1}])
    t0.insert_node([], "f", 2, [{"type": "n", "value": 2}])
    assert t0.in_transaction
    # Uncommitted edits visible locally, NOT on the wire.
    assert _vals(t0) == [0, 1, 2]
    h.process_all()
    assert _vals(t1) == [0]
    t0.commit_transaction()
    assert len(sent) == 1 and len(sent[0].change) == 2  # one squashed commit
    h.process_all()
    assert _vals(t1) == [0, 1, 2]
    assert t0.view() == t1.view()


def test_tree_transaction_abort_leaves_no_trace():
    h, (t0, t1) = _harness()
    t0.insert_node([], "f", 0, [{"type": "n", "value": 0}])
    h.process_all()
    t0.start_transaction()
    t0.remove_node([], "f", 0)
    t0.insert_node([], "f", 0, [{"type": "n", "value": 9}])
    assert _vals(t0) == [9]
    t0.abort_transaction()
    assert _vals(t0) == [0]
    h.process_all()
    assert t0.view() == t1.view()


def test_tree_transaction_with_concurrent_remote_edits():
    """Remote commits integrate mid-transaction; the squashed commit
    rebases over them at land time and replicas converge."""
    h, (t0, t1) = _harness()
    t0.insert_node([], "f", 0, [{"type": "n", "value": i} for i in range(4)])
    h.process_all()
    t0.start_transaction()
    t0.remove_node([], "f", 3)
    t0.insert_node([], "f", 0, [{"type": "n", "value": "tx"}])
    # Concurrent remote edit sequences while the transaction is open.
    t1.insert_node([], "f", 2, [{"type": "n", "value": "remote"}])
    h.process_all()
    t0.commit_transaction()
    h.process_all()
    assert t0.view() == t1.view()
    vals = _vals(t0)
    assert "tx" in vals and "remote" in vals and 3 not in vals


def test_tree_transaction_context_manager():
    h, (t0, t1) = _harness()
    t0.insert_node([], "f", 0, [{"type": "n", "value": 0}])
    h.process_all()
    with t0.transaction():
        t0.insert_node([], "f", 1, [{"type": "n", "value": 1}])
    h.process_all()
    assert _vals(t1) == [0, 1]
    with pytest.raises(ValueError):
        with t0.transaction():
            t0.insert_node([], "f", 0, [{"type": "n", "value": "x"}])
            raise ValueError("boom")
    assert _vals(t0) == [0, 1]  # aborted
    h.process_all()
    assert t0.view() == t1.view()


# ---------------------------------------------------------------------------
# undo / redo through the repair store
# ---------------------------------------------------------------------------


def _with_undo(tree):
    stack = UndoRedoStackManager()
    SharedTreeUndoRedoHandler(stack, tree)
    return stack


def test_tree_undo_insert_remove_setvalue():
    h, (t0, t1) = _harness()
    t0.insert_node([], "f", 0, [{"type": "n", "value": i} for i in range(3)])
    h.process_all()
    stack = _with_undo(t0)
    t0.remove_node([], "f", 1)
    stack.close_current_operation()
    t0.set_value([["f", 0]], "edited")
    stack.close_current_operation()
    h.process_all()
    assert _vals(t0) == ["edited", 2]
    assert stack.undo_operation()          # undo setValue
    h.process_all()
    assert _vals(t0) == [0, 2]
    assert stack.undo_operation()          # undo remove: content restores
    h.process_all()
    assert _vals(t0) == [0, 1, 2]
    assert t0.view() == t1.view()
    assert stack.redo_operation()          # redo the remove
    h.process_all()
    assert _vals(t0) == [0, 2]
    assert t0.view() == t1.view()


def test_tree_undo_rebases_over_concurrent_edits():
    """Undo an ACKED commit with remote commits sequenced after it:
    the inverse rebases over the interleaved history and every
    replica converges."""
    h, (t0, t1) = _harness()
    t0.insert_node([], "f", 0, [{"type": "n", "value": i} for i in range(4)])
    h.process_all()
    stack = _with_undo(t0)
    t0.remove_node([], "f", 1)             # removes node 1
    stack.close_current_operation()
    h.process_all()                        # acked into the trunk
    t1.insert_node([], "f", 0, [{"type": "n", "value": "r"}])
    h.process_all()                        # remote lands after it
    assert _vals(t0) == ["r", 0, 2, 3]
    assert stack.undo_operation()
    h.process_all()
    assert t0.view() == t1.view()
    assert _vals(t0) == ["r", 0, 1, 2, 3]  # node 1 restored, remote kept


def test_tree_undo_move():
    h, (t0, t1) = _harness()
    t0.insert_node([], "f", 0, [{"type": "n", "value": i} for i in range(3)])
    t0.insert_node([], "g", 0, [{"type": "n", "value": "g0"}])
    h.process_all()
    stack = _with_undo(t0)
    t0.move_node([], "f", 0, 2, [], "g", 1)
    stack.close_current_operation()
    h.process_all()
    assert _vals(t0) == [2] and _vals(t0, "g") == ["g0", 0, 1]
    assert stack.undo_operation()
    h.process_all()
    assert _vals(t0) == [0, 1, 2] and _vals(t0, "g") == ["g0"]
    assert t0.view() == t1.view()


def test_tree_undo_transaction_as_one_operation():
    """A squashed transaction undoes atomically (one revertible)."""
    h, (t0, t1) = _harness()
    t0.insert_node([], "f", 0, [{"type": "n", "value": 0}])
    h.process_all()
    stack = _with_undo(t0)
    with t0.transaction():
        t0.insert_node([], "f", 1, [{"type": "n", "value": 1}])
        t0.set_value([["f", 0]], "x")
        t0.insert_node([], "f", 2, [{"type": "n", "value": 2}])
    stack.close_current_operation()
    assert stack.undo_stack_size == 1
    h.process_all()
    assert _vals(t0) == ["x", 1, 2]
    assert stack.undo_operation()
    h.process_all()
    assert _vals(t0) == [0]
    assert t0.view() == t1.view()


def test_tree_transaction_carries_id_count():
    """ids allocated inside a transaction ride the squashed commit's
    idCount so remote compressors finalize the session range."""
    h, (t0, t1) = _harness()
    t0.insert_node([], "f", 0, [{"type": "n", "value": 0}])
    h.process_all()
    t0.start_transaction()
    i1 = t0.generate_id()
    t0.insert_node([], "f", 1, [{"type": "n", "value": i1}], id_count=1)
    i2 = t0.generate_id()
    t0.insert_node([], "f", 2, [{"type": "n", "value": i2}], id_count=1)
    t0.commit_transaction()
    h.process_all()
    assert t0.view() == t1.view()
    # The remote compressor finalized both ids: the author's session
    # range advanced by 2 on BOTH replicas.
    sess = str(h.runtimes[0].client_id)
    assert t1.id_compressor._finalized.get(sess) == 2
    assert t0.id_compressor._finalized.get(sess) == 2


def test_tree_transaction_abort_still_ships_id_allocation():
    """ids generated inside an ABORTED transaction advanced the
    session's local ordinal space; the allocation must still ride the
    wire (empty commit) or every replica's stable-id mapping shifts."""
    h, (t0, t1) = _harness()
    t0.insert_node([], "f", 0, [{"type": "n", "value": 0}])
    h.process_all()
    t0.start_transaction()
    t0.generate_id()
    t0.insert_node([], "f", 1, [{"type": "n", "value": 1}], id_count=1)
    t0.abort_transaction()
    # Post-abort: a fresh id rides a normal commit; replicas agree on
    # the session's finalized count (2: the aborted one + this one).
    t0.generate_id()
    t0.insert_node([], "f", 1, [{"type": "n", "value": 2}], id_count=1)
    h.process_all()
    sess = str(h.runtimes[0].client_id)
    assert t0.id_compressor._finalized.get(sess) == 2
    assert t1.id_compressor._finalized.get(sess) == 2
    assert t0.view() == t1.view()


def test_tree_empty_transaction_still_ships_id_allocation():
    """A transaction that squashes to NOTHING but allocated ids must
    still ship the allocation (same invariant as the abort path)."""
    h, (t0, t1) = _harness()
    t0.insert_node([], "f", 0, [{"type": "n", "value": 0}])
    h.process_all()
    t0.start_transaction()
    t0.generate_id()
    t0.edit([], id_count=1)
    t0.commit_transaction()
    t0.generate_id()
    t0.insert_node([], "f", 1, [{"type": "n", "value": 1}], id_count=1)
    h.process_all()
    sess = str(h.runtimes[0].client_id)
    assert t0.id_compressor._finalized.get(sess) == 2
    assert t1.id_compressor._finalized.get(sess) == 2
    assert t0.view() == t1.view()


def test_tree_undo_refused_while_transaction_open():
    h, (t0, _) = _harness()
    t0.insert_node([], "f", 0, [{"type": "n", "value": 0}])
    h.process_all()
    stack = _with_undo(t0)
    t0.set_value([["f", 0]], "x")
    stack.close_current_operation()
    t0.start_transaction()
    t0.insert_node([], "f", 1, [{"type": "n", "value": 1}])
    with pytest.raises(RuntimeError, match="transaction is open"):
        stack.undo_operation()
    # The refused group went back on the undo stack intact.
    assert stack.undo_stack_size == 1
    t0.abort_transaction()
    assert stack.undo_operation()
    assert _vals(t0) == [0]


def test_revert_group_exception_safety():
    """A raising revertible mid-group: the unreverted prefix returns
    to its stack; the reverted suffix's capture lands as a partial
    inverse group."""
    class _Boom:
        def revert(self):
            raise RuntimeError("boom")

    class _Ok:
        def __init__(self, stack):
            self.stack = stack

        def revert(self):
            self.stack.push(_Ok(self.stack))  # captured inverse

    stack = UndoRedoStackManager()
    stack.push(_Boom())
    stack.push(_Ok(stack))  # reverts first (reversed order)
    stack.close_current_operation()
    with pytest.raises(RuntimeError, match="boom"):
        stack.undo_operation()
    # Unreverted prefix (_Boom) is back on undo; partial inverse on redo.
    assert stack.undo_stack_size == 1
    assert len(stack._redo) == 1


def test_tree_undo_fuzz_convergence():
    """Randomized interleaving of edits + undos across two clients:
    replicas stay convergent after every drain."""
    import random

    rng = random.Random(7)
    h, (t0, t1) = _harness()
    t0.insert_node([], "f", 0, [{"type": "n", "value": i} for i in range(5)])
    h.process_all()
    stack = _with_undo(t0)
    counter = 100
    for step in range(40):
        for tree, is_t0 in ((t0, True), (t1, False)):
            r = rng.random()
            n = len(tree.view()["fields"].get("f", []))
            if r < 0.35:
                tree.insert_node([], "f", rng.randint(0, n),
                                 [{"type": "n", "value": counter}])
                counter += 1
            elif r < 0.6 and n > 1:
                tree.remove_node([], "f", rng.randint(0, n - 1))
            elif r < 0.8 and n > 0:
                tree.set_value([["f", rng.randint(0, n - 1)]], counter)
                counter += 1
            elif is_t0 and stack.undo_stack_size > 0 and rng.random() < 0.5:
                stack.undo_operation()
            if is_t0:
                stack.close_current_operation()
        if rng.random() < 0.6:
            h.process_all()
    h.process_all()
    assert t0.view() == t1.view()
