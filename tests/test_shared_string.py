"""SharedString through the full runtime stack + interval collections.

The farm tests (tests/test_farm_convergence.py) already fuzz the
merge-tree semantics against the sequencer directly; these tests drive
the same engine through the production ContainerRuntime → DataStore →
channel path (the reference's dds/sequence test layer, e.g.
packages/dds/sequence/src/test/sharedString.spec.ts and
intervalCollection.spec.ts).
"""

from __future__ import annotations

import random
import string as _string

import pytest

from fluidframework_tpu.dds import StringFactory
from fluidframework_tpu.runtime import ChannelRegistry, ContainerRuntime
from fluidframework_tpu.runtime.summary import SummaryTree
from fluidframework_tpu.testing.mocks import MultiClientHarness

REGISTRY = ChannelRegistry([StringFactory()])


def make_harness(n=2):
    return MultiClientHarness(n, REGISTRY, channel_types=[("s", StringFactory.type_name)])


def test_basic_insert_remove_converges():
    h = make_harness()
    a, b = h.channel(0, "s"), h.channel(1, "s")
    a.insert_text(0, "hello world")
    h.process_all()
    b.insert_text(5, ",")
    a.remove_text(0, 1)
    h.process_all()
    assert a.get_text() == b.get_text() == "ello, world"


def test_concurrent_insert_same_position():
    h = make_harness()
    a, b = h.channel(0, "s"), h.channel(1, "s")
    a.insert_text(0, "base")
    h.process_all()
    a.insert_text(0, "AA")
    b.insert_text(0, "BB")
    h.process_all()
    # a's op sequences first; b's later op wins position 0 (breakTie:
    # later seq beats earlier at the same spot).
    assert a.get_text() == b.get_text() == "BBAAbase"


def test_annotate_and_markers():
    h = make_harness()
    a, b = h.channel(0, "s"), h.channel(1, "s")
    a.insert_text(0, "styled text")
    h.process_all()
    b.annotate_range(0, 6, {"bold": True})
    a.insert_marker(0, ref_type=1, props={"tag": "pg"})
    h.process_all()
    assert a.get_text() == b.get_text() == "styled text"
    assert len(a.get_markers()) == len(b.get_markers()) == 1
    assert a.annotated_spans() == b.annotated_spans()


def test_overlapping_concurrent_removes():
    h = make_harness(3)
    chans = [h.channel(i, "s") for i in range(3)]
    chans[0].insert_text(0, "abcdefghij")
    h.process_all()
    chans[0].remove_text(2, 6)
    chans[1].remove_text(4, 8)
    chans[2].insert_text(5, "XY")
    h.process_all()
    texts = {c.get_text() for c in chans}
    assert len(texts) == 1, texts


def test_random_farm_through_runtime():
    """Seeded random op mix over 3 clients through the real stack —
    the conflictFarm shape (client.conflictFarm.spec.ts) with the
    production runtime in the loop."""
    h = make_harness(3)
    chans = [h.channel(i, "s") for i in range(3)]
    chans[0].insert_text(0, "initial text here")
    h.process_all()
    rng = random.Random(42)
    for _ in range(30):
        for c in chans:
            n = len(c.get_text())
            r = rng.random()
            if r < 0.5 or n == 0:
                pos = rng.randint(0, n)
                txt = "".join(
                    rng.choice(_string.ascii_lowercase) for _ in range(rng.randint(1, 5))
                )
                c.insert_text(pos, txt)
            elif r < 0.8:
                s = rng.randint(0, n - 1)
                e = rng.randint(s + 1, min(n, s + 6))
                c.remove_text(s, e)
            else:
                s = rng.randint(0, n - 1)
                e = rng.randint(s + 1, min(n, s + 6))
                c.annotate_range(s, e, {"k": rng.randint(0, 3)})
        h.process_all()
    final = {c.get_text() for c in chans}
    assert len(final) == 1, final
    spans = {tuple(map(repr, c.annotated_spans())) for c in chans}
    assert len(spans) == 1


# ------------------------------------------------------------- intervals


def test_interval_add_and_slide_on_remove():
    h = make_harness()
    a, b = h.channel(0, "s"), h.channel(1, "s")
    a.insert_text(0, "0123456789")
    h.process_all()
    coll = a.get_interval_collection("comments")
    iv = coll.add(3, 7, {"author": "a"})
    h.process_all()
    b_coll = b.get_interval_collection("comments")
    assert len(b_coll) == 1
    b_iv = b_coll.get_interval_by_id(iv.interval_id)
    assert b_iv.bounds(b.engine) == (3, 7)
    assert b_iv.props == {"author": "a"}
    # Remove a range containing the start anchor: it slides forward.
    b.remove_text(2, 5)
    h.process_all()
    assert a.get_text() == "0156789"
    assert iv.bounds(a.engine) == (2, 4)
    assert b_iv.bounds(b.engine) == (2, 4)


def test_interval_change_and_delete():
    h = make_harness()
    a, b = h.channel(0, "s"), h.channel(1, "s")
    a.insert_text(0, "abcdefgh")
    h.process_all()
    coll = a.get_interval_collection("x")
    iv = coll.add(1, 3)
    h.process_all()
    coll.change(iv.interval_id, 4, 6)
    h.process_all()
    b_iv = b.get_interval_collection("x").get_interval_by_id(iv.interval_id)
    assert b_iv.bounds(b.engine) == (4, 6)
    coll.remove_interval_by_id(iv.interval_id)
    h.process_all()
    assert len(b.get_interval_collection("x")) == 0


def test_interval_endpoints_track_inserts():
    h = make_harness()
    a, b = h.channel(0, "s"), h.channel(1, "s")
    a.insert_text(0, "hello world")
    h.process_all()
    iv = a.get_interval_collection("c").add(6, 11)  # "world"
    h.process_all()
    b.insert_text(0, ">>> ")
    h.process_all()
    assert a.get_text() == ">>> hello world"
    assert iv.bounds(a.engine) == (10, 15)
    b_iv = b.get_interval_collection("c").get_interval_by_id(iv.interval_id)
    assert b_iv.bounds(b.engine) == (10, 15)


# --------------------------------------------------------- summarize/load


def test_string_summary_roundtrip_with_intervals():
    h = make_harness()
    a = h.channel(0, "s")
    a.insert_text(0, "persistent content")
    a.annotate_range(0, 10, {"bold": True})
    a.get_interval_collection("marks").add(2, 8, {"note": 1})
    h.process_all()

    wire = h.runtimes[0].summarize().to_json()
    rt = ContainerRuntime(REGISTRY)
    rt.load(SummaryTree.from_json(wire))
    s = rt.get_datastore("default").get_channel("s")
    assert s.get_text() == "persistent content"
    assert s.annotated_spans() == a.annotated_spans()
    iv = list(s.get_interval_collection("marks"))[0]
    assert iv.bounds(s.engine) == (2, 8)
    assert iv.props == {"note": 1}

    # Rejoin the session and keep editing.
    rt.connect(h.service.connect(h.doc_id, client_id=50))
    s.insert_text(0, "! ")
    rt.flush()
    h.process_all()
    assert s.get_text() == "! persistent content"
    assert h.channel(1, "s").get_text() == "! persistent content"


def test_detached_edits_then_attach_summary():
    """Detached-container workflow: edit before any connection, then
    boot a second runtime from the attach summary (reference
    Container.createDetached → attach, container.ts:376,1056)."""
    rt = ContainerRuntime(REGISTRY)
    ds = rt.create_datastore("default")
    s = ds.create_channel("s", StringFactory.type_name)
    s.insert_text(0, "offline draft")
    s.remove_text(0, 3)
    assert s.get_text() == "line draft"
    wire = rt.summarize().to_json()
    rt2 = ContainerRuntime(REGISTRY)
    rt2.load(SummaryTree.from_json(wire))
    assert rt2.get_datastore("default").get_channel("s").get_text() == "line draft"
