"""Render run metrics snapshots into a per-stage latency table.

Input: one or more files, each either a metrics JSONL (one
`{"t": ..., "source": ..., "snapshot": {...}}` line per registry dump
— `utils.metrics.dump_snapshot_line`, as written by
`tools/chaos_run.py --metrics-out` and the chaos harness's
`<shared_dir>/metrics.jsonl`) or a bare JSON snapshot
(`MetricsRegistry.snapshot()` / a `/metrics.json` scrape body).

All snapshots are merged (counters/histograms add, gauges last-write)
and printed as:

- the per-stage latency table — every histogram with observations:
  count, mean, p50/p90/p99 (bucket-interpolated);
- counters and gauges, one row each.

Usage: python tools/metrics_report.py FILE [FILE...]
       python tools/metrics_report.py --json FILE...   (merged snapshot
       as JSON instead of the table)
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fluidframework_tpu.utils.metrics import (  # noqa: E402
    format_report,
    merge_snapshots,
)


def load_snapshots(path: str) -> list:
    """Snapshot dicts from a metrics JSONL or a bare-snapshot JSON
    (compact or pretty-printed — e.g. this tool's own --json output)."""
    with open(path) as f:
        text = f.read()
    stripped = text.strip()
    if not stripped:
        return []
    try:
        one = json.loads(stripped)
        return [one] if isinstance(one, dict) else list(one)
    except ValueError:
        pass  # not a single document: treat as JSONL
    return [
        json.loads(line)
        for line in stripped.splitlines()
        if line.strip()
    ]


def main() -> int:
    args = [a for a in sys.argv[1:]]
    as_json = "--json" in args
    if as_json:
        args.remove("--json")
    if not args:
        print(__doc__, file=sys.stderr)
        return 2
    snaps = []
    for path in args:
        snaps.extend(load_snapshots(path))
    if not snaps:
        print("no snapshots found", file=sys.stderr)
        return 1
    if as_json:
        print(json.dumps(merge_snapshots(snaps).snapshot(), indent=1))
    else:
        print(f"merged {len(snaps)} snapshot(s) from {len(args)} file(s)")
        print(format_report(snaps))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
