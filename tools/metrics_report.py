"""Render run metrics snapshots into a per-stage latency table.

Input: one or more files, each either a metrics JSONL (one
`{"t": ..., "source": ..., "snapshot": {...}}` line per registry dump
— `utils.metrics.dump_snapshot_line`, as written by
`tools/chaos_run.py --metrics-out` and the chaos harness's
`<shared_dir>/metrics.jsonl`) or a bare JSON snapshot
(`MetricsRegistry.snapshot()` / a `/metrics.json` scrape body).

All snapshots are merged (counters/histograms add, gauges last-write)
and printed as:

- the per-stage latency table — every histogram with observations:
  count, mean, p50/p95/p99 (bucket-interpolated);
- counters and gauges, one row each;
- a codec summary — the columnar op-log's encode/decode throughput
  (records, bytes, wall time, MB/s) from the `codec_*` metrics
  `protocol.record_batch` reports;
- the slow-op flight recorder — when input lines carry ``slow_ops``
  spans (`chaos_run --trace-wire --metrics-out`), the slowest ops
  with their full stage timestamps.

Usage: python tools/metrics_report.py FILE [FILE...]
       python tools/metrics_report.py --json FILE...   (merged snapshot
       as JSON instead of the table)
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fluidframework_tpu.utils.metrics import (  # noqa: E402
    format_report,
    merge_snapshots,
)


def load_snapshots(path: str) -> list:
    """Snapshot dicts from a metrics JSONL or a bare-snapshot JSON
    (compact or pretty-printed — e.g. this tool's own --json output)."""
    with open(path) as f:
        text = f.read()
    stripped = text.strip()
    if not stripped:
        return []
    try:
        one = json.loads(stripped)
        return [one] if isinstance(one, dict) else list(one)
    except ValueError:
        pass  # not a single document: treat as JSONL
    return [
        json.loads(line)
        for line in stripped.splitlines()
        if line.strip()
    ]


def codec_report(merged: dict) -> str:
    """The columnar-codec summary: encode/decode records, bytes, wall
    time, and derived MB/s from the `codec_*` metrics
    `protocol.record_batch` reports (empty string when no codec metric
    is present — JSON-log runs)."""
    counters = {
        (c["name"], tuple(sorted(c["labels"].items()))): c["value"]
        for c in merged.get("counters", [])
    }
    hists = {
        h["name"]: h for h in merged.get("histograms", [])
        if h["name"].startswith("codec_")
    }
    lines = []
    for side in ("encode", "decode"):
        recs = bytes_ = None
        for (name, labels), value in counters.items():
            if name == f"codec_{side}_records_total":
                recs = (recs or 0) + value
            elif name == f"codec_{side}_bytes_total":
                bytes_ = (bytes_ or 0) + value
        if recs is None and bytes_ is None:
            continue
        h = hists.get(f"codec_{side}_ms")
        ms = h["sum"] if h else 0.0
        rate = (bytes_ or 0) / (ms / 1000.0) / 1e6 if ms else 0.0
        lines.append(
            f"  {side:6s}  records={int(recs or 0):>10d}  "
            f"bytes={int(bytes_ or 0):>12d}  wall={ms / 1000.0:8.3f}s  "
            f"{rate:8.1f} MB/s"
        )
    if not lines:
        return ""
    return "columnar codec (protocol.record_batch):\n" + "\n".join(lines)


def slow_ops_report(snaps: list, top: int = 10) -> str:
    """The slow-op flight-recorder section: spans attached to any
    input line (`chaos_run --trace-wire --metrics-out`), slowest
    first (empty string when none are present)."""
    spans = []
    for line in snaps:
        v = line.get("slow_ops") if isinstance(line, dict) else None
        if isinstance(v, list):
            spans.extend(s for s in v if isinstance(s, dict))
    if not spans:
        return ""
    spans.sort(key=lambda s: -float(s.get("e2e_ms", 0.0)))
    lines = [f"slow-op flight recorder ({len(spans)} spans, "
             f"slowest {min(top, len(spans))} shown):"]
    for s in spans[:top]:
        lines.append(
            f"  {s.get('e2e_ms'):>9}ms  doc={s.get('doc')} "
            f"seq={s.get('seq')} client={s.get('client')} "
            f"clientSeq={s.get('clientSeq')} stages={s.get('stages')}"
        )
    return "\n".join(lines)


def main() -> int:
    args = [a for a in sys.argv[1:]]
    as_json = "--json" in args
    if as_json:
        args.remove("--json")
    if not args:
        print(__doc__, file=sys.stderr)
        return 2
    snaps = []
    for path in args:
        snaps.extend(load_snapshots(path))
    if not snaps:
        print("no snapshots found", file=sys.stderr)
        return 1
    merged = merge_snapshots(snaps).snapshot()
    if as_json:
        print(json.dumps(merged, indent=1))
    else:
        print(f"merged {len(snaps)} snapshot(s) from {len(args)} file(s)")
        print(format_report(snaps))
        codec = codec_report(merged)
        if codec:
            print(codec)
        slow = slow_ops_report(snaps)
        if slow:
            print(slow)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
