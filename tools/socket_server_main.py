"""Standalone ordering-service process: LocalServer behind TCP.

Run: python tools/socket_server_main.py [port]
Prints "LISTENING <host> <port>" once ready, then serves until killed.
Containers in other processes collaborate through it via
drivers.socket_driver.SocketDriver (tests/test_socket_transport.py).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fluidframework_tpu.server import LocalServer  # noqa: E402
from fluidframework_tpu.server.socket_service import SocketDeltaServer  # noqa: E402


def main() -> None:
    port = int(sys.argv[1]) if len(sys.argv) > 1 else 0
    srv = SocketDeltaServer(LocalServer(), port=port).start()
    print(f"LISTENING {srv.host} {srv.port}", flush=True)
    try:
        srv._thread.join()
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
