"""Standalone ordering-service process: LocalServer behind TCP.

Run: python tools/socket_server_main.py [port] [--storage-dir DIR]
    (--tenant id:key [repeatable] | --allow-anonymous)
Secure by default: starting without tenants requires the explicit
--allow-anonymous opt-out.
Prints "LISTENING <host> <port>" once ready, then serves until killed.
Containers in other processes collaborate through it via
drivers.socket_driver.SocketDriver (tests/test_socket_transport.py).

With --storage-dir, the service is DURABLE: summaries/blobs persist in
the content-addressed store, sequenced ops in topic journals, and
lambda checkpoints on disk — kill the process, start a new one on the
same dir, and clients boot documents from the persisted summary + op
tail (tests/test_durable_storage.py).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fluidframework_tpu.server import LocalServer  # noqa: E402
from fluidframework_tpu.server.socket_service import SocketDeltaServer  # noqa: E402


def main() -> None:
    args = sys.argv[1:]
    storage_dir = None
    if "--storage-dir" in args:
        i = args.index("--storage-dir")
        storage_dir = args[i + 1]
        del args[i: i + 2]
    tenants = None
    while "--tenant" in args:
        # --tenant id:key enables the riddler gate (repeatable); every
        # request must then carry a signed per-document token.
        from fluidframework_tpu.server.riddler import TenantManager

        i = args.index("--tenant")
        tid, key = args[i + 1].split(":", 1)
        del args[i: i + 2]
        tenants = tenants or TenantManager()
        tenants.create_tenant(tid, key)
    allow_anonymous = False
    if "--allow-anonymous" in args:
        allow_anonymous = True
        args.remove("--allow-anonymous")
    port = int(args[0]) if args else 0
    if tenants is None and not allow_anonymous:
        print(
            "refusing to start open: pass --tenant id:key (secure) or "
            "--allow-anonymous (explicit open dev mode)",
            file=sys.stderr,
        )
        raise SystemExit(2)
    srv = SocketDeltaServer(
        LocalServer(persist_dir=storage_dir), port=port, tenants=tenants,
        allow_anonymous=allow_anonymous,
    ).start()
    print(f"LISTENING {srv.host} {srv.port}", flush=True)
    try:
        srv._thread.join()
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
