"""Record GOLDEN.json for the LAGGED headline stream (round 4).

Verification chain (each link independently farm-tested):

1. The scalar Python oracle (core/mergetree.py — slow, obviously
   correct) replays a PREFIX of the stream; its digest must equal the
   native engine's digest at the same point. This grounds the chain
   in the oracle.
2. The native C++ engine (native/hostmerge.cpp — oracle-exact
   semantics, differentially farm-gated by tests/test_native_engine.py
   and tests/test_lagged_stream.py) replays the FULL stream, recording
   staged digests every `stage` ops and the final digest — the
   recorded ground truth. This closes the round-3 gap where oracle
   grounding stopped at 300k: the native chain covers all stages.
3. An independent engine's stage log (numpy overlay from
   tools/overlay_golden-style runs, or the pure oracle extending past
   its prefix) can be merged via --merge-log to cross-check stages
   from a second implementation family.
4. bench.py requires the pallas overlay engine's full-stream digest to
   equal the recorded digest (the north-star bit-identity contract).

Usage: python tools/lagged_golden.py [n_ops] [oracle_prefix]
       python tools/lagged_golden.py --merge-log LOG TAG
"""

from __future__ import annotations

import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fluidframework_tpu.testing.digest import state_digest  # noqa: E402

GOLDEN = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "GOLDEN.json",
)
STAGE = 100_000


def _native_replay(stream, initial_len, checkpoints):
    """Replay `stream` through the native engine, returning
    {op_index: digest} at each checkpoint index."""
    from fluidframework_tpu.core.native_engine import NativeMergeEngine

    eng = NativeMergeEngine(local_client_id=-3)
    eng.load("".join(map(chr, stream.text[:initial_len])))
    marks = sorted(set(checkpoints))
    out = {}
    t0 = time.perf_counter()
    for i, msg in enumerate(stream.as_messages()):
        eng.apply_sequenced(msg)
        if (i + 1) % 997 == 0:
            eng.pack_settled()
        if marks and i + 1 == marks[0]:
            marks.pop(0)
            out[i + 1] = state_digest(eng.annotated_spans())
            print(
                f"[native] {i + 1}/{len(stream)} ops, "
                f"{time.perf_counter() - t0:.0f}s, "
                f"digest {out[i + 1][:16]}...",
                flush=True,
            )
    return out


def merge_log(path: str, tag: str) -> None:
    """Merge an independent engine's stage log (lines like
    '[tag] N/M ops, Ss, digest HEX...') into GOLDEN.json, verifying
    against the native chain where stages overlap."""
    with open(GOLDEN) as f:
        golden = json.load(f)
    pat = re.compile(r"\[(\w[\w-]*)\] (\d+)/\d+ ops, \d+s, digest ([0-9a-f]+)")
    stages = {}
    with open(path) as f:
        for line in f:
            m = pat.search(line)
            if m:
                stages[m.group(2)] = m.group(3)
    native = golden["chain"]["native_stage_digests"]
    verified = []
    for k, d in sorted(stages.items(), key=lambda kv: int(kv[0])):
        if k in native:
            full = native[k]
            assert full.startswith(d) or d.startswith(full[: len(d)]), (
                f"stage {k}: {tag} digest {d[:16]} != native {full[:16]}"
            )
            verified.append(int(k))
    golden["chain"][f"{tag}_stage_digests"] = stages
    golden["chain"][f"{tag}_stages_verified_vs_native"] = sorted(verified)
    with open(GOLDEN, "w") as f:
        json.dump(golden, f, indent=1)
    print(f"merged {len(stages)} {tag} stages; {len(verified)} verified "
          "against the native chain")


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == "--merge-log":
        merge_log(sys.argv[2], sys.argv[3])
        return
    n_ops = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    n_prefix = int(sys.argv[2]) if len(sys.argv) > 2 else 20_000
    n_clients, seed, initial_len, window = 1024, 7, 64, 1024

    from fluidframework_tpu.core.mergetree import replay_passive
    from fluidframework_tpu.testing.synthetic import generate_lagged_stream

    cache = os.path.join(os.path.dirname(GOLDEN), ".stream_cache")
    stream = generate_lagged_stream(
        n_ops, n_clients=n_clients, seed=seed, window=window,
        initial_len=initial_len, cache_dir=cache,
    )

    # 1. oracle grounding on the prefix
    t0 = time.perf_counter()
    oracle = replay_passive(
        (m for i, m in zip(range(n_prefix), stream.as_messages())),
        initial="".join(map(chr, stream.text[:initial_len])),
    )
    t_oracle = time.perf_counter() - t0
    oracle_digest = state_digest(oracle.annotated_spans())
    print(f"[oracle] {n_prefix} ops in {t_oracle:.0f}s, "
          f"digest {oracle_digest[:16]}...", flush=True)

    # 2. native full replay with stages
    checkpoints = [n_prefix] + [
        s for s in range(STAGE, n_ops + 1, STAGE)
    ] + [n_ops]
    t0 = time.perf_counter()
    native = _native_replay(stream, initial_len, checkpoints)
    t_native = time.perf_counter() - t0

    assert native[n_prefix] == oracle_digest, (
        "native/oracle divergence on the prefix — do not record"
    )

    golden = {
        "params": {
            "n_ops": n_ops, "n_clients": n_clients, "seed": seed,
            "initial_len": initial_len, "lagged": True,
            "window": window,
        },
        "digest": native[n_ops],
        "chain": {
            "oracle_prefix_ops": n_prefix,
            "oracle_prefix_digest": oracle_digest,
            "oracle_seconds": round(t_oracle, 1),
            "full_engine": "native-cpp",
            "native_seconds": round(t_native, 1),
            "native_stage_digests": {
                str(k): v for k, v in sorted(native.items())
                if k % STAGE == 0 or k == n_ops
            },
        },
    }
    with open(GOLDEN, "w") as f:
        json.dump(golden, f, indent=1)
    print(f"GOLDEN.json recorded: {native[n_ops][:16]}... "
          f"(native {t_native:.0f}s, oracle prefix {n_prefix})")


if __name__ == "__main__":
    main()
