"""Ground GOLDEN.json's FULL-stream digest in the scalar Python oracle.

Round-2 verdict (weak #4): the recorded 1M-op digest was produced by
the scan engine, with the oracle grounding only a 50k prefix. This
tool replays the ENTIRE stream through the scalar oracle
(core/mergetree.py — slow, obviously correct), recording a staged
digest every `stage` ops, and verifies the final state against the
recorded digest. On success it rewrites GOLDEN.json with
`full_engine: "oracle"` plus the staged checkpoint digests, so every
engine (scan / pallas row-model / overlay) is gated against an
oracle-produced digest, not an engine-produced one.

Usage: python tools/oracle_golden.py [n_ops] [stage]
Runtime: ~45 min for 1M ops; run detached.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fluidframework_tpu.testing.digest import state_digest  # noqa: E402


def main() -> None:
    n_ops = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    stage = int(sys.argv[2]) if len(sys.argv) > 2 else 100_000
    n_clients, seed, initial_len = 1024, 7, 64

    from fluidframework_tpu.core.mergetree import replay_passive
    from fluidframework_tpu.testing.synthetic import generate_stream

    stream = generate_stream(
        n_ops, n_clients=n_clients, seed=seed, initial_len=initial_len
    )

    stages = {}
    t0 = time.perf_counter()

    def checkpoint(i0: int, engine) -> None:
        i = i0 + 1
        if i % stage == 0 or i == n_ops:
            d = state_digest(engine.annotated_spans())
            stages[str(i)] = d
            el = time.perf_counter() - t0
            print(
                f"[oracle] {i}/{n_ops} ops, {el:.0f}s, digest {d[:16]}...",
                flush=True,
            )

    # The staged replay runs THROUGH replay_passive itself (per-message
    # hook), so the recorded ground truth cannot drift from the oracle
    # semantics every engine is gated against.
    replay_passive(
        stream.as_messages(),
        initial="".join(map(chr, stream.text[:initial_len])),
        on_message=checkpoint,
    )

    digest = stages[str(n_ops)]
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "GOLDEN.json",
    )
    with open(path) as f:
        golden = json.load(f)
    params = {
        "n_ops": n_ops, "n_clients": n_clients, "seed": seed,
        "initial_len": initial_len,
    }
    if golden.get("params") != params:
        print("params mismatch with existing GOLDEN.json", file=sys.stderr)
        sys.exit(1)
    if golden["digest"] != digest:
        print(
            f"FATAL: oracle full-stream digest {digest} != recorded "
            f"{golden['digest']} — scan engine digest was WRONG",
            file=sys.stderr,
        )
        sys.exit(1)
    golden["chain"]["full_engine"] = "oracle"
    golden["chain"]["oracle_full_seconds"] = round(
        time.perf_counter() - t0, 1
    )
    golden["chain"]["oracle_stage_digests"] = stages
    golden["chain"]["note"] = (
        "full-stream digest produced by the scalar Python oracle itself "
        "(tools/oracle_golden.py); scan/pallas/overlay engines are "
        "gated against it"
    )
    with open(path, "w") as f:
        json.dump(golden, f, indent=1)
    print("GOLDEN.json oracle-grounded: full digest matches", flush=True)


if __name__ == "__main__":
    main()
