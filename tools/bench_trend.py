"""Bench trend ledger: append results, fail loudly on regression.

Feeds on the one-line JSON bodies the bench CLIs print
(`tools/bench_deli.py` in any mode, `tools/bench_configs.py` entries,
`bench.py`) — one result object per file, or JSONL with several — and
folds each into a ``trend`` section of BENCH_DETAIL.json keyed by the
result's ``metric``/``config`` name:

    {"trend": {"deli_pipeline_raw_to_deltas": [
        {"t": ..., "value": 26900.0, "unit": "records/s"}, ...]}}

Every result's HEADLINE number (ops/s for throughput metrics, the
p99-improvement ratio for the latency SLO bench — higher is better —
or a LOWER-is-better latency like the scenario benches'
``scenario_p99_ms``) is compared against the BEST prior run of the
same metric: moving past ``--tolerance`` (default 20%) in the wrong
direction exits nonzero with the offending numbers, so a perf
regression fails CI the moment it lands instead of surfacing as a
slowly sagging ledger. Results whose
headline cannot be identified are appended but never gated (named on
stderr, not silently dropped). Skipped gate results (a ``skipped``
key) are recorded with ``"skipped": true`` and never gated — a CI
host downgrade must not look like a regression or retire history.

Usage: python tools/bench_trend.py RESULT.json [RESULT.json ...]
       python tools/bench_deli.py | python tools/bench_trend.py -
       (env: BENCH_TREND_PATH overrides the ledger location)
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, List, Optional, Tuple

DEFAULT_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "BENCH_DETAIL.json",
)

# Headline fields in preference order — the first present (and
# numeric) names the metric's one comparable number. These are
# higher-is-better, so the regression rule is one inequality.
HEADLINE_FIELDS = (
    "p99_improvement",          # latency_slo_open_loop (ratio)
    "ops_per_sec",
    "aggregate_ops_per_sec",
    "submissions_per_sec",
    "op_rebases_per_sec",
    "speedup",                  # scaling benches (ratio)
    "columnar_vs_json",         # log-format guard (ratio)
    "hop_fsync_reduction",      # fused durable+broadcast hop (ratio)
    "fold_backend_speedup",     # overlay vs vmapped summarizer fold
    #                             (ratio; carries a skipped flag on
    #                             hosts where pallas cannot lower —
    #                             interpreter timings never gate)
    "fused_vs_split_p99",       # fused-hop open-loop latency (ratio;
    #                             recorded with a skipped flag — the
    #                             jitter-bound ratio is never gated)
)

# LOWER-is-better headlines: regression means rising ABOVE the best
# (lowest) prior run by more than the tolerance. Scenario benches
# report their tail as `scenario_p99_ms` (testing/scenarios.py); the
# retention churn gate reports its steady-state on-disk high-water
# mark as `retention_disk_mb` (config14_retention) — a farm whose
# disk footprint regresses >20% fails as loudly as a latency drop.
LOW_HEADLINE_FIELDS = ("scenario_p99_ms", "retention_disk_mb")


def headline(result: dict) -> Optional[Tuple[str, float]]:
    for f in HEADLINE_FIELDS + LOW_HEADLINE_FIELDS:
        v = result.get(f)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return f, float(v)
    return None


def load_results(path: str) -> List[dict]:
    text = (sys.stdin.read() if path == "-" else open(path).read())
    stripped = text.strip()
    if not stripped:
        return []
    try:
        one = json.loads(stripped)
        return [one] if isinstance(one, dict) else [
            r for r in one if isinstance(r, dict)
        ]
    except ValueError:
        pass  # not one document: JSONL
    return [json.loads(line) for line in stripped.splitlines()
            if line.strip()]


def append_and_gate(ledger_path: str, results: List[dict],
                    tolerance: float = 0.20) -> List[str]:
    """Fold `results` into the ledger's trend section; returns the
    regression messages (empty = all clear). The ledger write happens
    EITHER WAY — a regression should be recorded, not suppressed."""
    try:
        with open(ledger_path) as f:
            ledger = json.load(f)
    except (OSError, ValueError):
        ledger = {}
    if not isinstance(ledger, dict):
        ledger = {}
    trend = ledger.setdefault("trend", {})
    failures: List[str] = []
    for result in results:
        key = result.get("metric") or result.get("config")
        if not isinstance(key, str):
            print(f"bench_trend: result without metric/config key "
                  f"skipped: {str(result)[:120]}", file=sys.stderr)
            continue
        runs = trend.setdefault(key, [])
        head = headline(result)
        skipped = "skipped" in result
        entry: dict = {"t": time.time()}
        if head is not None:
            entry["field"], entry["value"] = head
        if skipped:
            entry["skipped"] = True
        if isinstance(result.get("unit"), str):
            entry["unit"] = result["unit"]
        if head is None:
            print(f"bench_trend: no headline field in {key!r}; "
                  f"appended ungated", file=sys.stderr)
        elif not skipped:
            prior = [r["value"] for r in runs
                     if isinstance(r.get("value"), (int, float))
                     and r.get("field") == head[0]
                     and not r.get("skipped")]
            if prior and head[0] in LOW_HEADLINE_FIELDS:
                best = min(prior)
                ceiling = best * (1.0 + tolerance)
                if head[1] > ceiling:
                    failures.append(
                        f"{key}: {head[0]}={head[1]:g} regressed "
                        f">{tolerance:.0%} above the best prior "
                        f"{best:g} (ceiling {ceiling:g}, "
                        f"{len(prior)} prior runs)"
                    )
            elif prior:
                best = max(prior)
                floor = best * (1.0 - tolerance)
                if head[1] < floor:
                    failures.append(
                        f"{key}: {head[0]}={head[1]:g} regressed "
                        f">{tolerance:.0%} below the best prior "
                        f"{best:g} (floor {floor:g}, "
                        f"{len(prior)} prior runs)"
                    )
        runs.append(entry)
    tmp = ledger_path + f".tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(ledger, f, indent=1)
    os.replace(tmp, ledger_path)
    return failures


def main() -> int:
    args = [a for a in sys.argv[1:]]
    tolerance = 0.20
    if "--tolerance" in args:
        i = args.index("--tolerance")
        tolerance = float(args[i + 1])
        del args[i:i + 2]
    if not args:
        print(__doc__, file=sys.stderr)
        return 2
    results: List[dict] = []
    for path in args:
        results.extend(load_results(path))
    if not results:
        print("bench_trend: no results found", file=sys.stderr)
        return 1
    ledger_path = os.environ.get("BENCH_TREND_PATH", DEFAULT_PATH)
    failures = append_and_gate(ledger_path, results, tolerance)
    for key in {r.get("metric") or r.get("config") for r in results}:
        print(f"bench_trend: recorded {key} -> {ledger_path}")
    if failures:
        for msg in failures:
            print(f"REGRESSION: {msg}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
