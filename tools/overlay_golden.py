"""Ground GOLDEN.json's full-stream digest via the numpy overlay
reference, cross-checked against scalar-oracle staged digests.

The scalar oracle's full 1M-op replay is O(document)/op and takes
~15h on this box (tools/oracle_golden.py); its STAGED digests (every
100k ops, logged as it goes) are the practical oracle grounding. This
tool replays the same stream through the numpy overlay reference
(ops/overlay_ref.py — an INDEPENDENT engine with a structurally
different representation, farm-gated against the oracle), records its
staged digests, verifies them against every oracle stage available,
and rewrites GOLDEN.json's chain accordingly.

Usage: python tools/overlay_golden.py [oracle_log]
The oracle log is tools/oracle_golden.py's stdout (lines like
"[oracle] 100000/1000000 ops, 1296s, digest acc185a9b273a5ba...").
"""

from __future__ import annotations

import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fluidframework_tpu.testing.digest import state_digest  # noqa: E402


def main() -> None:
    oracle_log = sys.argv[1] if len(sys.argv) > 1 else None
    n_ops, n_clients, seed, initial_len, stage = (
        1_000_000, 1024, 7, 64, 100_000
    )

    from fluidframework_tpu.ops.overlay_ref import OverlayReplica
    from fluidframework_tpu.testing.synthetic import generate_stream

    stream = generate_stream(
        n_ops, n_clients=n_clients, seed=seed, initial_len=initial_len
    )
    r = OverlayReplica(stream, initial_len=initial_len, fold_interval=2048)

    stages = {}
    t0 = time.perf_counter()
    s = stream
    d = r.doc
    for i in range(n_ops):
        d.apply(
            int(s.op_type[i]), int(s.pos1[i]), int(s.pos2[i]),
            int(s.seq[i]), int(s.ref_seq[i]), int(s.client[i]),
            int(s.buf_start[i]), int(s.ins_len[i]),
            [int(s.prop_key[i])], [int(s.prop_val[i])],
        )
        if (i + 1) % 2048 == 0 or i + 1 == n_ops:
            d.fold(int(s.min_seq[i]))
        if (i + 1) % stage == 0 or i + 1 == n_ops:
            dig = state_digest(r.annotated_spans())
            stages[str(i + 1)] = dig
            print(
                f"[overlay] {i + 1}/{n_ops} ops, "
                f"{time.perf_counter() - t0:.0f}s, digest {dig[:16]}...",
                flush=True,
            )
    r.check_errors()

    oracle_stages = {}
    if oracle_log and os.path.exists(oracle_log):
        pat = re.compile(r"\[oracle\] (\d+)/\d+ ops, \d+s, digest ([0-9a-f]+)")
        with open(oracle_log) as f:
            for line in f:
                m = pat.search(line)
                if m:
                    oracle_stages[m.group(1)] = m.group(2)
    mismatches = [
        k for k, prefix in oracle_stages.items()
        if not stages.get(k, "").startswith(prefix)
    ]
    if mismatches:
        print(f"FATAL: overlay diverges from oracle at stages {mismatches}",
              file=sys.stderr)
        sys.exit(1)

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "GOLDEN.json",
    )
    with open(path) as f:
        golden = json.load(f)
    params = {"n_ops": n_ops, "n_clients": n_clients, "seed": seed,
              "initial_len": initial_len}
    if golden.get("params") != params:
        print("params mismatch with existing GOLDEN.json", file=sys.stderr)
        sys.exit(1)
    if golden["digest"] != stages[str(n_ops)]:
        print(
            f"FATAL: overlay full digest {stages[str(n_ops)]} != recorded "
            f"{golden['digest']}", file=sys.stderr,
        )
        sys.exit(1)
    golden["chain"]["full_engine"] = "overlay-numpy"
    golden["chain"]["overlay_stage_digests"] = stages
    golden["chain"]["oracle_stage_digests_verified"] = sorted(
        int(k) for k in oracle_stages
    )
    golden["chain"]["note"] = (
        "full-stream digest produced by the numpy overlay reference "
        "(ops/overlay_ref.py, an independent engine farm-gated against "
        "the scalar oracle); staged digests cross-checked against the "
        "scalar oracle's staged replay for every stage the oracle has "
        "completed (tools/oracle_golden.py log). scan/pallas/overlay-"
        "device engines are gated against this digest."
    )
    with open(path, "w") as f:
        json.dump(golden, f, indent=1)
    print(
        f"GOLDEN.json overlay-grounded; oracle-verified stages: "
        f"{golden['chain']['oracle_stage_digests_verified']}", flush=True,
    )


if __name__ == "__main__":
    main()
