"""Benchmark the remaining BASELINE.json configs (1, 3, 4, 5) plus the streaming-ingress pipeline-overlap comparison.

Every config runs under the statistical runner
(fluidframework_tpu/utils/benchmark.py — the @fluid-tools/benchmark
Runner.ts role): warm-up + N timed repeats with mean/stddev/
percentiles, plus a separate memory-traced pass (MemoryTestRunner.ts
role) for the host-side configs.

The headline bench (bench.py) covers config 2 (1M-op 1024-client
replay). This tool measures the rest and writes BENCH_DETAIL.json:

- config 1: SharedString 2-client random insert/remove, 10k ops —
  the interactive client path (host-side merge engine through the
  sequencer), reference harness mergeTreeOperationRunner.ts.
- config 3: SharedMatrix 256x256, row/col insert + setCell mix
  through the production runtime stack (matrix.ts:80 shape).
- config 4: SharedTree rebase over a trunk window at 100k-node
  scale — the batched rebase kernel (one XLA dispatch for the whole
  pending range; editManager.ts:47 / config-4 shape).
- config 5: deli batch sequencing, 10k docs x 64 clients — the
  vectorized sequencer kernel (deli/lambda.ts:818 ticket loop), plus
  its LIVE-pipeline twin (raw topic → stamped deltas through the
  supervised deli datapath, kernel vs scalar pump, bit-identity
  gated — tools/bench_deli.py at full scale).
- metrics-overhead guard: the instrumented config-5 pipeline
  (utils.metrics on, the default) vs the same run with the no-op
  registry; FAILS LOUDLY if instrumentation costs more than 5%.
- log-format guard: the config-5 pipeline over the columnar binary
  op-log (`log_format="columnar"`, server.columnar_log) vs the same
  run over JSONL topics; FAILS LOUDLY if columnar ever drops below
  1x JSON (the codec must never lose to per-record json.dumps).
- config 6: shard-fabric scaling guard — the same pipeline drained
  through 4 parallel partition processes (server.shard_fabric
  slicing, kernel deli over columnar topics) must reach >= 1.5x the
  single-partition aggregate ops/s, bit-identity gated across
  partitions; SKIPS LOUDLY on hosts with < 4 cores.
- config 7: multi-device deli scaling guard — the sharded sequencer
  kernel (shard_map over a docs mesh, server.deli_kernel seam) must
  reach >= 2x single-device aggregate submissions/s at 4 devices
  with a near-linear trend to 8, bit-identity gated across every
  device count; the SCALING assert skips loudly where only
  forced-host virtual devices over fewer cores are available (the
  correctness gate still runs there).
- config 8: elastic-rebalance guard — a live range split committed
  mid-run over the elastic hash-range fabric must cost < 25% of the
  steady aggregate ops/s, with the mid-split stream bit-identical to
  the steady topology's (the convergence half runs on every host;
  the perf assert skips loudly on < 4 cores).
- config 9: tail-latency SLO guard — with topic doorbells on (the
  default), submit→broadcast p99 under a steady open-loop load must
  improve >= 3x over the polling baseline at the same load
  (testing.deli_bench.run_latency_bench); the trace/quantile
  correctness assertions and a chaos kill-fault convergence run with
  doorbells enabled always run; the ratio assert skips loudly on
  < 4 cores.
- config 10: summary catch-up guard — with a summary present
  (server.summarizer), a cold join must stay flat in log length and
  beat full-log replay >= 10x at 100k+ ops; the boot-equivalence
  digest gate and a chaos summarizer-kill convergence run always
  run; the perf asserts skip loudly on < 4 cores or a sub-100k
  scaled run.
- config 12: front-door guard — the supervised admission ingress
  (server.ingress: riddler tokens, size caps, rate/backpressure
  nacks) must cost the config-5 pipeline < 5% end-to-end (pipelined
  definition; serial view reported), the overload episode must keep
  the raw backlog bounded with visible throttle nacks and converge
  exactly-once after retries, and a kernel x columnar ELASTIC chaos
  run with ingress + load-driven autoscale + per-partition
  downstream stages must converge bit-identical through kill faults
  and a POLICY-driven split (every host).

- config 13: scenario-suite guard — the traffic-profile scenario
  layer (testing/scenarios.py: hot-doc storm, reconnect stampede,
  100k-session read swarm, tenant-skewed mix), every primitive
  open-loop with /slo quantiles, slow-op spans, and a convergence
  digest; plus the storm-during-split/kill chaos gate on the elastic
  fabric with partition-tagged /traces spans (every host). The
  per-scenario p99s feed the bench_trend ledger as lower-is-better
  `scenario_p99_ms` lines, marked recorded-not-gated on hosts below
  the core/wake-jitter honesty bar.

The TypeScript baselines for these configs cannot be measured in this
environment: the reference's harnesses need node + a pnpm/lerna
monorepo install, and no node runtime is present (see BASELINE.md).

Usage: python tools/bench_configs.py  (env: BC_SCALE=1.0 shrink knob)
"""

from __future__ import annotations

import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".jax_cache"),
)

SCALE = float(os.environ.get("BC_SCALE", "1.0"))
REPEATS = int(os.environ.get("BC_REPEATS", "5"))


def config1_sharedstring_2client(n_ops: int = 10_000) -> dict:
    from fluidframework_tpu.testing.farm import FarmConfig, run_sharedstring_farm
    from fluidframework_tpu.utils.benchmark import run_benchmark

    n_ops = int(n_ops * SCALE)
    rounds = max(1, n_ops // (2 * 10))
    total = rounds * 2 * 10

    def workload():
        run_sharedstring_farm(
            FarmConfig(
                num_clients=2, rounds=rounds, ops_per_client_per_round=10,
                seed=1, check_annotations=False, annotate_weight=0.0,
                insert_weight=0.6, remove_weight=0.4, check_every=32,
            )
        )

    stats = run_benchmark(workload, repeats=REPEATS, warmups=1, memory=True)
    return {
        "config": "sharedstring_2client_insert_remove",
        "ops": total, "seconds": stats["mean"],
        "ops_per_sec": round(total / stats["mean"], 1),
        "stats": stats,
    }


def config3_matrix(size: int = 256, n_ops: int = 10_000) -> dict:
    from fluidframework_tpu.dds import MatrixFactory
    from fluidframework_tpu.runtime import ChannelRegistry
    from fluidframework_tpu.testing.mocks import MultiClientHarness

    from fluidframework_tpu.utils.benchmark import run_benchmark

    n_ops = int(n_ops * SCALE)

    last = {}

    def workload():
        last.clear()  # don't hold the previous run's harness alive
        registry = ChannelRegistry([MatrixFactory()])
        h = MultiClientHarness(
            2, registry, channel_types=[("mx", MatrixFactory.type_name)]
        )
        a = h.runtimes[0].get_datastore("default").get_channel("mx")
        a.insert_rows(0, size)
        a.insert_cols(0, size)
        h.process_all()
        rng = random.Random(3)
        done = 0
        while done < n_ops:
            r = rng.random()
            if r < 0.9:
                a.set_cell(rng.randrange(size), rng.randrange(size), done)
            elif r < 0.95:
                a.insert_rows(rng.randrange(a.row_count + 1), 1)
            else:
                a.insert_cols(rng.randrange(a.col_count + 1), 1)
            done += 1
            if done % 512 == 0:
                h.process_all()
        h.process_all()
        last["h"] = h  # convergence gate runs OUTSIDE the timed region

    stats = run_benchmark(workload, repeats=REPEATS, warmups=1, memory=True)
    # Correctness gate on the final run's state (the reference's perf
    # harness likewise keeps verification out of timed sections).
    h = last["h"]
    a = h.runtimes[0].get_datastore("default").get_channel("mx")
    b = h.runtimes[1].get_datastore("default").get_channel("mx")
    assert a.to_dense() == b.to_dense(), "matrix replicas diverged"
    return {
        "config": "matrix_256x256_setcell_insert_mix",
        "ops": n_ops, "seconds": stats["mean"],
        "ops_per_sec": round(n_ops / stats["mean"], 1),
        "stats": stats,
    }


def config4_tree_rebase(n_pending: int = 100_000, window: int = 64) -> dict:
    import numpy as np

    from fluidframework_tpu.tree.rebase_kernel import rebase_ops_columnar

    n_pending = int(n_pending * SCALE)
    rng = np.random.default_rng(4)
    # Full calculus: insert/remove/MOVE marks in both streams (moves
    # carry a destination gap; the kernel handles travel/absorb/
    # relocate natively and flags arbitration corners to the scalar
    # path — measured by flagged_for_scalar_path).
    kinds = rng.integers(0, 3, n_pending)
    ops = np.stack(
        [kinds, rng.integers(0, 100_000, n_pending),
         rng.integers(1, 4, n_pending),
         np.where(kinds == 2, rng.integers(0, 100_000, n_pending), 0)],
        axis=1,
    ).astype(np.int32)
    bkinds = rng.integers(0, 3, window)
    base = np.stack(
        [bkinds, rng.integers(0, 100_000, window),
         rng.integers(1, 4, window),
         np.where(bkinds == 2, rng.integers(0, 100_000, window), 0)],
        axis=1,
    ).astype(np.int32)
    from fluidframework_tpu.utils.benchmark import run_benchmark

    flagged_box = {}

    def workload():
        out, spares, flagged = rebase_ops_columnar(ops, base)
        flagged_box["n"] = int(flagged.sum())
        flagged_box["splits"] = int(((spares[:, 2] > 0) & ~flagged).sum())

    stats = run_benchmark(workload, repeats=REPEATS, warmups=1,
                          memory=True)
    rebases = n_pending * window
    return {
        "config": "tree_rebase_100k_ops_over_64_commit_window",
        "calculus": "insert+remove+move",
        "pending_ops": n_pending, "window": window,
        "seconds": stats["mean"],
        "op_rebases_per_sec": round(rebases / stats["mean"], 1),
        "flagged_for_scalar_path": flagged_box["n"],
        "native_splits": flagged_box["splits"],
        "stats": stats,
    }


def config5_deli(n_docs: int = 10_000, n_clients: int = 64,
                 ops_per_doc: int = 128) -> dict:
    import numpy as np
    import jax
    import jax.numpy as jnp

    from fluidframework_tpu.ops.sequencer_kernel import (
        SUB_JOIN, SUB_OP, SeqBatch, make_state, sequence_batch_jit,
    )

    n_docs = max(8, int(n_docs * SCALE))
    rng = np.random.default_rng(5)
    # Every doc: joins for all clients, then random ops.
    kind = np.full((n_docs, ops_per_doc), SUB_OP, np.int32)
    kind[:, :n_clients] = SUB_JOIN
    client = rng.integers(0, n_clients, (n_docs, ops_per_doc)).astype(np.int32)
    client[:, :n_clients] = np.arange(n_clients)[None, :]
    cseq = np.zeros((n_docs, ops_per_doc), np.int32)
    # client_seq must be contiguous per (doc, client): compute by count.
    counts = np.zeros((n_docs, n_clients), np.int32)
    for j in range(n_clients, ops_per_doc):
        c = client[:, j]
        counts[np.arange(n_docs), c] += 1
        cseq[:, j] = counts[np.arange(n_docs), c]
    ref = np.zeros((n_docs, ops_per_doc), np.int32)  # refSeq 0 is valid
    batch = SeqBatch(
        kind=jnp.asarray(kind), client=jnp.asarray(client),
        client_seq=jnp.asarray(cseq), ref_seq=jnp.asarray(ref),
    )
    from fluidframework_tpu.utils.benchmark import run_benchmark

    def workload():
        state = make_state(n_docs, n_clients)
        new_state, res = sequence_batch_jit(state, batch)
        jax.block_until_ready(res.seq)
        # Force completion on tunneled backends (block_until_ready
        # can return before the device finishes there).
        int(res.seq[0, 0])

    stats = run_benchmark(workload, repeats=REPEATS, warmups=1)
    total = n_docs * ops_per_doc
    return {
        "config": "deli_batch_sequencing",
        "docs": n_docs, "clients_per_doc": n_clients,
        "submissions": total, "seconds": stats["mean"],
        "submissions_per_sec": round(total / stats["mean"], 1),
        "stats": stats,
    }


def config5_deli_pipeline(n_docs: int = 4_000, n_clients: int = 32) -> dict:
    """Config 5's LIVE-pipeline twin: the same batched sequencer, but
    measured raw-topic-in → deltas-topic-out through the supervised
    deli datapath (tools/bench_deli.py / testing.deli_bench) — JSON
    parse, doc-slot mapping, pack, kernel, scatter, durable batched
    append — against the scalar pump, with a bit-identity gate."""
    from fluidframework_tpu.testing.deli_bench import run_pipeline_bench

    return {
        "config": "deli_pipeline_raw_to_deltas",
        **run_pipeline_bench(
            n_docs=max(8, int(n_docs * SCALE)),
            n_clients=n_clients,
            ops_per_client=1,
            seed_records=200,
        ),
    }


def config5_metrics_overhead(n_docs: int = 2_000, n_clients: int = 32,
                             max_pct: float = 5.0,
                             attempts: int = 3) -> dict:
    """Observability overhead guard: the instrumented config-5 deli
    pipeline (utils.metrics ON, the default) must stay within
    `max_pct` percent of the uninstrumented run (`set_enabled(False)`
    swaps in the no-op NullRegistry). Best-of-N per mode to damp I/O
    jitter; FAILS LOUDLY (AssertionError) on regression, so the bench
    harness catches an instrumentation hot-path leak the moment it
    lands."""
    import shutil
    import tempfile

    from fluidframework_tpu.server.queue import SharedFileTopic
    from fluidframework_tpu.testing.deli_bench import (
        build_pipeline_workload,
        run_pipeline,
    )
    from fluidframework_tpu.utils import metrics as M

    n_docs = max(8, int(n_docs * SCALE))
    scratch = tempfile.mkdtemp(prefix="metrics-overhead-")
    try:
        workload = build_pipeline_workload(n_docs, n_clients, 1)
        raw_path = os.path.join(scratch, "rawdeltas.jsonl")
        SharedFileTopic(raw_path).append_many(workload)
        run_pipeline("kernel", raw_path, scratch)  # jit warm-up

        def best(enabled: bool) -> float:
            prev = M.set_enabled(enabled)
            try:
                return min(
                    run_pipeline("kernel", raw_path, scratch)["seconds"]
                    for _ in range(attempts)
                )
            finally:
                M.set_enabled(prev)

        with_metrics = best(True)
        without = best(False)
        overhead_pct = (with_metrics / without - 1.0) * 100.0
        result = {
            "config": "deli_pipeline_metrics_overhead_guard",
            "records": len(workload),
            "instrumented_s": round(with_metrics, 4),
            "uninstrumented_s": round(without, 4),
            "overhead_pct": round(overhead_pct, 2),
            "max_pct": max_pct,
            "ops_per_sec": round(len(workload) / with_metrics, 1),
        }
        assert overhead_pct <= max_pct, (
            f"instrumentation overhead {overhead_pct:.2f}% exceeds the "
            f"{max_pct}% budget on the config-5 deli pipeline: {result}"
        )
        return result
    finally:
        shutil.rmtree(scratch, ignore_errors=True)


def config5_log_format(n_docs: int = 10_000, n_clients: int = 16,
                       ops_per_client: int = 4, attempts: int = 3,
                       min_ratio: float = 1.0) -> dict:
    """Columnar-op-log regression guard (ROADMAP (a)): the config-5
    pipeline (kernel deli, 10k docs) over `log_format="columnar"`
    topics vs the same run over JSONL topics. Paired best-of-N per
    format damps I/O jitter; FAILS LOUDLY (AssertionError) if the
    binary record-batch log ever drops below `min_ratio` x the JSON
    log — the moment a codec hot-path regression lands, the bench
    harness says so.

    Two gates run on EVERY host regardless of the timing outcome:
    (1) bit-identity — the columnar run's deltas must decode to
    exactly the JSON run's records; (2) the columnar run must have
    taken the pre-columnized EMIT path (`codec_encode_columns_total`
    covering every output record) — a silent fallback to dict-path
    emission would invalidate the very number this guard protects."""
    import shutil
    import tempfile

    from fluidframework_tpu.server.columnar_log import make_topic
    from fluidframework_tpu.server.queue import SharedFileTopic
    from fluidframework_tpu.testing.deli_bench import (
        _read_canonical,
        build_pipeline_workload,
        run_pipeline,
    )

    n_docs = max(8, int(n_docs * SCALE))
    scratch = tempfile.mkdtemp(prefix="log-format-bench-")
    try:
        workload = build_pipeline_workload(n_docs, n_clients,
                                           ops_per_client)
        raw_json = os.path.join(scratch, "raw.jsonl")
        SharedFileTopic(raw_json).append_many(workload)
        raw_col = os.path.join(scratch, "raw-col.jsonl")
        col = make_topic(raw_col, "columnar")
        for lo in range(0, len(workload), 16384):
            col.append_many(workload[lo:lo + 16384])
        run_pipeline("kernel", raw_json, scratch)  # jit warm-up

        last: dict = {}

        def best(fmt: str, path: str) -> float:
            runs = [
                run_pipeline("kernel", path, scratch, log_format=fmt)
                for _ in range(attempts)
            ]
            last[fmt] = runs[-1]
            return min(r["seconds"] for r in runs)

        t_json = best("json", raw_json)
        t_col = best("columnar", raw_col)
        # Bit-identity gate (EVERY host): same stamps/nacks/MSNs
        # through both wire forms.
        a = _read_canonical(last["json"]["out_path"])
        b = _read_canonical(last["columnar"]["out_path"])
        assert a == b, (
            f"columnar deltas diverge from JSON deltas "
            f"({len(a)} vs {len(b)} records)"
        )
        # Emit-path gate (EVERY host): the columnar run must emit
        # through encode_columns, covering all its output records.
        emit = last["columnar"]["metrics"]["emit"]
        assert emit["codec_encode_columns_records"] >= \
            last["columnar"]["outputs"], (
                f"columnar run fell back to dict-path emission: "
                f"{emit} vs {last['columnar']['outputs']} outputs"
            )
        ratio = t_json / t_col
        result = {
            "config": "deli_pipeline_log_format_guard",
            "records": len(workload),
            "json_ops_per_sec": round(len(workload) / t_json, 1),
            "columnar_ops_per_sec": round(len(workload) / t_col, 1),
            "columnar_vs_json": round(ratio, 2),
            "emit_codec": emit,
            "min_ratio": min_ratio,
            "gate": "bit-identical + columns-emitted",
        }
        assert ratio >= min_ratio, (
            f"columnar op-log regressed to {ratio:.2f}x the JSON log "
            f"(must stay >= {min_ratio}x) on the config-5 pipeline: "
            f"{result}"
        )
        return result
    finally:
        shutil.rmtree(scratch, ignore_errors=True)


def config6_shard_scaling(n_docs: int = 2_048, n_clients: int = 8,
                          ops_per_client: int = 2,
                          min_ratio: float = 1.5,
                          min_cores: int = 4) -> dict:
    """Sharded-fabric scaling guard (server.shard_fabric): the config-5
    pipeline drained through 4 parallel partition pipelines (one OS
    process each, kernel deli over columnar topics) must reach at
    least `min_ratio` x the single-partition aggregate ops/s,
    bit-identity gated across partitions. FAILS LOUDLY on regression.

    SKIPS LOUDLY on hosts with fewer than `min_cores` cores: four
    partitions time-slicing two cores measures the scheduler, not the
    fabric — the skip is explicit in the result so a CI host downgrade
    can't silently retire the guard."""
    from fluidframework_tpu.testing.deli_bench import run_shard_bench

    cores = os.cpu_count() or 1
    if cores < min_cores:
        result = {
            "config": "shard_fabric_scaling_guard",
            "skipped": (
                f"host has {cores} cores < {min_cores}: 4-partition "
                f"scaling cannot be measured honestly here"
            ),
            "cores": cores, "min_ratio": min_ratio,
        }
        print(
            f"SKIP config6_shard_scaling: {result['skipped']}",
            file=sys.stderr,
        )
        return result
    res = run_shard_bench(
        n_docs=max(8, int(n_docs * SCALE)), n_clients=n_clients,
        ops_per_client=ops_per_client, partitions=(1, 4),
        deli_impl="kernel", log_format="columnar",
    )
    result = {"config": "shard_fabric_scaling_guard",
              "min_ratio": min_ratio, **res}
    assert res["speedup"] >= min_ratio, (
        f"4-partition fabric reached only {res['speedup']:.2f}x the "
        f"single-partition aggregate (must be >= {min_ratio}x) on a "
        f"{cores}-core host: {result}"
    )
    return result


def config7_multichip(min_ratio: float = 2.0,
                      min_trend_8v4: float = 1.5,
                      devices: tuple = (1, 4, 8)) -> dict:
    """Multi-device deli scaling guard (ROADMAP open item 1): the
    sharded sequencer kernel (`ops.sequencer_kernel.sharded_sequence_fn`
    over a `parallel.mesh` docs mesh) must reach >= `min_ratio` x the
    single-device aggregate submissions/s at 4 devices and keep a
    near-linear trend to 8 (8-device >= `min_trend_8v4` x 4-device).
    FAILS LOUDLY on regression.

    The CORRECTNESS gate always runs: every device count sequences the
    identical workload and the verdict digests must match bit for bit
    (run_multichip_bench asserts it even on the forced-host fallback).
    The SCALING assert skips LOUDLY when the host cannot measure it
    honestly (utils.devices.parity_skip_reason: no 4-device
    accelerator and fewer than 4 cores — forced virtual host devices
    time-slicing 2 cores measure the scheduler); the skip is explicit
    in the result so a CI host downgrade can't silently retire the
    guard."""
    from fluidframework_tpu.testing.deli_bench import run_multichip_bench
    from fluidframework_tpu.utils.devices import parity_skip_reason

    res = run_multichip_bench(
        devices=devices,
        n_docs=max(8, int(4096 * SCALE)),
        ops_per_doc=64, n_clients=8, repeats=REPEATS,
    )
    result = {"config": "deli_multichip_scaling_guard",
              "min_ratio": min_ratio,
              "min_trend_8v4": min_trend_8v4, **res}
    reason = parity_skip_reason(4)
    if reason is not None:
        result["skipped"] = (
            f"scaling assert skipped ({reason}); correctness gate ran: "
            f"{res['gate']}"
        )
        print(f"SKIP config7_multichip scaling assert: {reason}",
              file=sys.stderr)
        return result
    by_n = {r["n_devices"]: r for r in res["runs"]}
    r4 = by_n[4]["ops_per_sec"] / by_n[1]["ops_per_sec"]
    result["speedup_4_vs_1"] = round(r4, 2)
    assert r4 >= min_ratio, (
        f"4-device sharded sequencer reached only {r4:.2f}x the "
        f"single-device aggregate (must be >= {min_ratio}x): {result}"
    )
    if 8 in by_n:
        r8v4 = by_n[8]["ops_per_sec"] / by_n[4]["ops_per_sec"]
        result["speedup_8_vs_4"] = round(r8v4, 2)
        assert r8v4 >= min_trend_8v4, (
            f"8-device trend broke near-linear: {r8v4:.2f}x the "
            f"4-device aggregate (must be >= {min_trend_8v4}x): "
            f"{result}"
        )
    return result


def config8_rebalance(max_cost_pct: float = 25.0,
                      min_cores: int = 4) -> dict:
    """Elastic-rebalance guard (server.shard_fabric hash-range
    topology): a range SPLIT committed mid-run over the config-5-shape
    workload (10k docs x 64 clients -> 1.28M records at full scale)
    must cost the fabric less than `max_cost_pct` percent of its
    steady aggregate ops/s. FAILS LOUDLY on regression.

    The CONVERGENCE gate always runs — even on hosts too small to
    measure the cost honestly (< `min_cores` cores: the split's extra
    child processes time-slice the same cores and the ratio measures
    the scheduler), a scaled-down run still proves the mid-run split
    leaves the merged stream bit-identical to the steady topology's;
    only the PERF assert is skipped, loudly."""
    from fluidframework_tpu.testing.deli_bench import run_rebalance_bench

    cores = os.cpu_count() or 1
    if cores < min_cores:
        res = run_rebalance_bench(
            n_docs=max(8, int(256 * SCALE)), n_clients=4,
            ops_per_client=1,
        )
        result = {
            "config": "elastic_rebalance_guard",
            "skipped": (
                f"host has {cores} cores < {min_cores}: split cost "
                f"cannot be measured honestly here; convergence gate "
                f"ran ({res['gate']})"
            ),
            "cores": cores, "max_cost_pct": max_cost_pct,
            "convergence_records": res["records"],
            "split_cost_pct_unreliable": res["split_cost_pct"],
        }
        print(
            f"SKIP config8_rebalance perf assert: {result['skipped']}",
            file=sys.stderr,
        )
        return result
    res = run_rebalance_bench(
        n_docs=max(8, int(10_000 * SCALE)), n_clients=64,
        ops_per_client=1,
    )
    result = {"config": "elastic_rebalance_guard",
              "max_cost_pct": max_cost_pct, **res}
    assert res["split_cost_pct"] < max_cost_pct, (
        f"mid-run split cost the fabric {res['split_cost_pct']:.1f}% "
        f"aggregate ops/s (budget {max_cost_pct}%) on a {cores}-core "
        f"host: {result}"
    )
    return result


def config9_latency(min_p99_improvement: float = 3.0,
                    min_cores: int = 4) -> dict:
    """Tail-latency SLO guard (ROADMAP item 3): with topic doorbells
    ON (the default), submit→broadcast p99 of the supervised farm
    under a steady OPEN-loop load must improve at least
    `min_p99_improvement` x over the polling baseline at the same
    load. FAILS LOUDLY on regression.

    The trace/quantile CORRECTNESS assertions always run, on every
    host, inside `run_latency_bench` itself: every submitted op
    observed exactly once in broadcast, per-op stage stamps monotone
    (sub ≤ stamp ≤ dur/bc), the child-heartbeat-reported
    `op_stage_ms` histogram bucket-identical to one rebuilt from the
    wire spans, and the bucket-interpolated p99 landing in the exact
    sample p99's bucket. Also always run: a chaos KILL-fault
    convergence run with doorbells enabled — event wakeups must not
    cost a single bit of the exactly-once contract.

    The RATIO assert skips LOUDLY when the host cannot measure it
    honestly: fewer than `min_cores` cores (four waking processes
    time-slice the same cores — the ratio measures the scheduler), or
    a wake-jitter probe p99 above `max_wake_jitter_p99_ms` (an
    oversubscribed VM parks idle vCPUs; when a single select() wake
    costs ~10ms at the tail, that floor sits under the event-driven
    pipeline's p99 no matter how the consumers wake — the honest-
    measurement rule config7_multichip's parity_skip_reason set)."""
    from fluidframework_tpu.testing.chaos import ChaosConfig, run_chaos
    from fluidframework_tpu.testing.deli_bench import (
        run_latency_bench,
        wake_jitter_probe,
    )

    max_wake_jitter_p99_ms = 2.0
    cores = os.cpu_count() or 1
    small = cores < min_cores
    probe = wake_jitter_probe()
    res = run_latency_bench(
        rate_hz=60.0 if small else 150.0,
        duration_s=max(1.0, (2.0 if small else 4.0) * SCALE),
        # Third variant: the fused durable+broadcast consumer at the
        # same load — the open-loop p99 delta of one fewer wake+fsync
        # (ROADMAP item-1 follow-up c), recorded in this config's
        # MEASURED section and (ungated — the ratio is wake-jitter-
        # bound on small hosts) in the bench_trend ledger.
        fused_hop=True,
    )
    # Doorbells ride every farm topic by default — prove the chaos
    # exactly-once contract still holds with them waking consumers
    # (kill faults land mid-wake; convergence must be bit-identical
    # with zero duplicated/skipped seqs).
    chaos = run_chaos(ChaosConfig(
        seed=9, faults=("kill",), n_docs=2, n_clients=3,
        ops_per_client=30, timeout_s=240.0,
    ))
    assert chaos.converged, (
        f"chaos kill run with doorbells enabled diverged: "
        f"{chaos.detail}"
    )
    assert chaos.duplicate_seqs == 0 and chaos.skipped_seqs == 0
    # The FUSED durable+broadcast hop must survive the same kill
    # schedule bit-identically (its broadcast leg is unfsynced — this
    # is the gate that proves recovery regenerates it exactly-once).
    # Runs on EVERY host, like the bit-identity gates above.
    chaos_fused = run_chaos(ChaosConfig(
        seed=9, faults=("kill", "torn"), n_docs=2, n_clients=3,
        ops_per_client=30, timeout_s=240.0, fused_hop=True,
        log_format="columnar", deli_impl="kernel",
    ))
    assert chaos_fused.converged, (
        f"chaos kill+torn run on the FUSED hop diverged: "
        f"{chaos_fused.detail}"
    )
    assert chaos_fused.duplicate_seqs == 0 \
        and chaos_fused.skipped_seqs == 0
    result = {
        "config": "latency_slo_guard",
        "min_p99_improvement": min_p99_improvement,
        "chaos_kill_converged": True,
        "chaos_fused_hop_converged": True,
        "chaos_restarts": chaos.restarts,
        "wake_jitter_probe_ms": probe,
        **res,
        # The fused-hop p99 delta rides the ledger as its OWN metric
        # line, recorded-but-never-gated (a ~1x ratio on a jittery CI
        # host must not flap the regression gate).
        "_extra_trend": [{
            "metric": "latency_fused_hop",
            "fused_vs_split_p99": res.get("fused_vs_split_p99"),
            "fused_p99_ms": res.get("fused_p99_ms"),
            "skipped": ("recorded-not-gated: open-loop p99 ratio is "
                        "wake-jitter-bound on small hosts"),
        }],
    }
    jittery = probe["p99"] > max_wake_jitter_p99_ms
    if small or jittery:
        why = (
            f"host has {cores} cores < {min_cores}" if small else
            f"host wake-jitter probe p99 {probe['p99']}ms > "
            f"{max_wake_jitter_p99_ms}ms (a single event wake pays "
            f"multi-ms at the tail here — that floor sits under the "
            f"doorbell pipeline's p99 regardless of the poll stack)"
        )
        result["skipped"] = (
            f"{why}: the p99 ratio cannot be measured honestly; "
            f"correctness assertions, the chaos kill gate, and the "
            f"measured improvements (p50 {res['p50_improvement']}x, "
            f"p99 {res['p99_improvement']}x) are still reported"
        )
        print(f"SKIP config9_latency ratio assert: {result['skipped']}",
              file=sys.stderr)
        return result
    assert res["p99_improvement"] >= min_p99_improvement, (
        f"doorbells improved submit→broadcast p99 only "
        f"{res['p99_improvement']:.2f}x over the polling baseline "
        f"(must be >= {min_p99_improvement}x) on a {cores}-core host: "
        f"{result}"
    )
    return result


def config10_catchup(min_speedup: float = 10.0,
                     max_flatness: float = 3.0,
                     min_cores: int = 4) -> dict:
    """Summary catch-up guard (ROADMAP item 5, the read-heavy
    workload): with a summary present, a cold join must cost the
    nearest summary + op tail, not the log — `run_catchup_bench`
    sweeps log lengths and the with-summary join must stay FLAT
    (≤ `max_flatness` x from the smallest to the largest length) and
    beat full-log replay by ≥ `min_speedup` x at the 100k-op top end.
    FAILS LOUDLY on regression.

    The CORRECTNESS gate always runs, on every host and scale:
    summary + tail boots bit-identical (document-state digest) to the
    full-log replay at every swept length, and a chaos KILL run with
    the summarizer in the farm must converge with summary integrity
    (deterministic manifest count, no (doc, seq) fork/duplicate —
    restarts re-emit byte-identical content-addressed summaries).

    The PERF asserts skip LOUDLY when the host cannot measure them
    honestly: fewer than `min_cores` cores, or BC_SCALE shrinking the
    top length below the 100k-op regime the claim is about."""
    from fluidframework_tpu.testing.chaos import ChaosConfig, run_chaos
    from fluidframework_tpu.testing.deli_bench import run_catchup_bench

    cores = os.cpu_count() or 1
    lengths = tuple(max(512, int(x * SCALE))
                    for x in (10_000, 30_000, 100_000))
    res = run_catchup_bench(log_lengths=lengths)
    # The summarizer-kill chaos gate ALWAYS runs: kills mid-cadence
    # must neither fork a summary nor break the boot equivalence.
    chaos = run_chaos(ChaosConfig(
        seed=10, faults=("kill",), n_docs=2, n_clients=3,
        ops_per_client=30, timeout_s=240.0,
        summarizer=True, summary_ops=16,
    ))
    assert chaos.converged, (
        f"chaos summarizer-kill run diverged: {chaos.detail}"
    )
    assert chaos.summaries_ok and chaos.duplicate_seqs == 0 \
        and chaos.skipped_seqs == 0
    result = {
        "config": "summary_catchup_guard",
        "min_speedup": min_speedup, "max_flatness": max_flatness,
        "chaos_summarizer_kill_converged": True,
        "chaos_summary_manifests": chaos.summary_manifests,
        **res,
    }
    small = cores < min_cores
    under_regime = max(lengths) < 100_000
    if small or under_regime:
        why = (f"host has {cores} cores < {min_cores}" if small else
               f"BC_SCALE shrank the top length to {max(lengths)} "
               f"< 100000 ops — below the regime the >= "
               f"{min_speedup}x claim is about")
        result["skipped"] = (
            f"{why}; correctness gates ran ({res['gate']}; chaos "
            f"summarizer-kill converged) and the measured numbers "
            f"(speedup {res['speedup']}x, flatness "
            f"{res['join_flatness']}x) are still reported"
        )
        print(f"SKIP config10_catchup perf asserts: {result['skipped']}",
              file=sys.stderr)
        return result
    assert res["speedup"] >= min_speedup, (
        f"summary join beat full replay only {res['speedup']:.2f}x at "
        f"{max(lengths)} ops (must be >= {min_speedup}x): {result}"
    )
    assert res["join_flatness"] <= max_flatness, (
        f"with-summary join cost grew {res['join_flatness']:.2f}x "
        f"from {min(lengths)} to {max(lengths)} ops (must stay <= "
        f"{max_flatness}x — flat in log length): {result}"
    )
    return result


def config11_fused_hop(min_reduction: float = 1.5) -> dict:
    """Fused durable+broadcast hop guard (ROADMAP item 1's per-hop
    floor): the fused consumer must cut the hop pair's fsyncs by at
    least `min_reduction` x vs the split scriptorium+broadcaster pair
    over the same workload. The number is COUNT-based (fsyncs per
    record off the children's heartbeat counters, not wall time), so
    the guard runs honestly on every host — no core-count skip — and
    `run_hop_bench` internally gates both topologies' durable and
    broadcast streams bit-identical before reporting anything."""
    from fluidframework_tpu.testing.deli_bench import run_hop_bench

    res = run_hop_bench(
        n_docs=max(8, int(64 * SCALE)), n_clients=8, ops_per_client=4,
        log_format="columnar", deli_impl="kernel",
    )
    result = {"config": "fused_hop_farm",
              "min_reduction": min_reduction, **res}
    assert res["hop_fsync_reduction"] >= min_reduction, (
        f"fused hop cut hop-pair fsyncs only "
        f"{res['hop_fsync_reduction']:.2f}x (must be >= "
        f"{min_reduction}x): {result}"
    )
    return result


def config12_front_door(max_overhead_pct: float = 5.0) -> dict:
    """Front-door guard (ROADMAP item 2, the alfred admission edge):

    - ADMISSION OVERHEAD: the supervised ingress (riddler token
      validation, size caps, routing — auth ON with per-doc signed
      tokens) must cost the config-5 pipeline less than
      `max_overhead_pct` percent end-to-end. Stages run as separate
      farm processes, so the pipelined definition applies: overhead is
      the bottleneck slowdown, zero while admission outruns the
      sequencing stage (the serial extra-hop view rides the MEASURED
      section as `serial_overhead_pct`). Count/ratio-based on in-proc
      roles — no core-count skip.
    - OVERLOAD: `run_ingress_bench` asserts internally (the gate runs
      before any number is reported) that a storm against a small
      backlog budget keeps the rawdeltas backlog BOUNDED while
      throttle nacks flow, and that the retried storm converges
      exactly-once once pressure lifts.
    - CHAOS (every host): a kernel × columnar ELASTIC run with the
      front door and the load-driven autoscale policy on, kill faults
      landing on workers AND the ingress, boxcars in flight — a
      POLICY-driven split must fire mid-stream, every bad submit must
      be nacked-never-sequenced, and the merged stream (plus the
      per-partition downstream durable/broadcast legs) must converge
      bit-identical with zero dup/skip."""
    from fluidframework_tpu.testing.chaos import ChaosConfig, run_chaos
    from fluidframework_tpu.testing.deli_bench import run_ingress_bench

    res = run_ingress_bench(
        n_docs=max(8, int(2000 * SCALE)), n_clients=16,
        ops_per_client=2,
        overload_records=max(256, int(1200 * SCALE)),
    )
    chaos = run_chaos(ChaosConfig(
        seed=12, faults=("kill",), n_docs=2, n_clients=3,
        ops_per_client=24, boxcar_rate=0.35, timeout_s=300.0,
        deli_impl="kernel", log_format="columnar",
        n_partitions=2, n_workers=2, elastic=True,
        ingress=True, autoscale=True, downstream="split",
    ))
    assert chaos.converged, (
        f"front-door chaos run diverged: {chaos.detail}"
    )
    assert chaos.never_sequenced_ok and chaos.downstream_ok
    assert chaos.autoscale_actions > 0 and len(chaos.epochs) > 1, (
        f"no policy-driven split fired: epochs={chaos.epochs} "
        f"actions={chaos.autoscale_actions}"
    )
    result = {
        "config": "front_door_guard",
        "max_overhead_pct": max_overhead_pct,
        "chaos_front_door_converged": True,
        "chaos_epochs": chaos.epochs,
        "chaos_autoscale_actions": chaos.autoscale_actions,
        "chaos_ingress_nacks": chaos.ingress_nacks,
        **res,
    }
    assert res["admission_overhead_pct"] < max_overhead_pct, (
        f"front-door admission cost the pipeline "
        f"{res['admission_overhead_pct']:.1f}% end-to-end "
        f"(budget {max_overhead_pct}%): {result}"
    )
    return result


def config13_scenarios(min_cores: int = 4,
                       max_wake_jitter_p99_ms: float = 2.0) -> dict:
    """Scenario-suite guard (ROADMAP item 4, the traffic shapes real
    Fluid load actually has): the four open-loop scenario primitives
    (`testing.scenarios.run_scenario_suite` — hot-doc storm,
    reconnect stampede, 100k-session read swarm, tenant-skewed mix)
    plus the storm-during-faults chaos gate.

    ALWAYS run, on every host and scale:

    - every scenario's CONVERGENCE + EVIDENCE gates (asserted inside
      the primitives: exactly-once / complete-delivery digests, /slo
      quantiles present, slow-op spans recorded);
    - the CHAOS gate — a kernel x columnar ELASTIC run with
      per-partition downstream stages and wire traces where a hot-doc
      storm is in flight WHILE the kill and split faults land: the
      merged stream must converge bit-identical with zero dup/skip,
      the pre-split owner demonstrably fence-rejected, and the worker
      heartbeats must carry partition-tagged e2e spans (the
      fabric-wide /traces surface is populated, not vacuously empty).

    The per-scenario p99s feed the bench_trend ledger as their own
    LOWER-is-better ``scenario_p99_ms`` lines (a >20% tail regression
    fails loudly) — marked skipped (recorded-not-gated) when the host
    cannot measure latency honestly: fewer than `min_cores` cores, or
    a wake-jitter probe p99 above `max_wake_jitter_p99_ms` (the
    config9 honesty rule — a multi-ms event-wake tail floors any
    pipeline's p99 regardless of the code under test)."""
    from fluidframework_tpu.testing.chaos import ChaosConfig, run_chaos
    from fluidframework_tpu.testing.deli_bench import wake_jitter_probe
    from fluidframework_tpu.testing.scenarios import run_scenario_suite

    cores = os.cpu_count() or 1
    probe = wake_jitter_probe()
    # The kernel deli exercises the [D, C] pool's column axis under
    # the storm (the tentpole's point) — but only where the host can
    # actually run the farm + jit warm-up honestly.
    deli_impl = "kernel" if cores >= min_cores else "scalar"
    suite = run_scenario_suite(scale=SCALE, deli_impl=deli_impl)
    chaos = run_chaos(ChaosConfig(
        seed=13, faults=("kill", "split"), n_docs=2, n_clients=3,
        ops_per_client=16, timeout_s=300.0, deli_impl="kernel",
        log_format="columnar", n_partitions=2, n_workers=2,
        elastic=True, trace_wire=True, downstream="split",
        scenario="hotdoc",
    ))
    assert chaos.converged, (
        f"storm-during-split/kill chaos run diverged: {chaos.detail}"
    )
    assert chaos.duplicate_seqs == 0 and chaos.skipped_seqs == 0
    assert len(chaos.epochs) > 1 and chaos.fence_rejections > 0, (
        f"split never fired mid-storm: epochs={chaos.epochs} "
        f"rejections={chaos.fence_rejections}"
    )
    assert chaos.downstream_ok
    assert chaos.slow_ops, (
        "elastic-fabric run produced no slow-op spans — the fabric "
        "/traces surface is dark"
    )
    jittery = probe["p99"] > max_wake_jitter_p99_ms
    small = cores < min_cores
    honest = not (small or jittery)
    skip_reason = None
    if not honest:
        skip_reason = (
            f"host has {cores} cores < {min_cores}" if small else
            f"wake-jitter probe p99 {probe['p99']}ms > "
            f"{max_wake_jitter_p99_ms}ms"
        ) + (": scenario p99s recorded-not-gated")
        print(f"SKIP config13_scenarios p99 ledger gating: "
              f"{skip_reason}", file=sys.stderr)
    extra = []
    for name, p99 in suite["scenario_p99s"].items():
        if not isinstance(p99, (int, float)):
            continue
        line = {"metric": f"scenario_{name}",
                "scenario_p99_ms": p99, "unit": "ms"}
        if not honest:
            line["skipped"] = skip_reason
        extra.append(line)
    result = {
        "config": "scenario_suite_guard",
        "deli_impl": deli_impl,
        "scenario_p99s": suite["scenario_p99s"],
        "storm_writers": suite["storm"]["writers"],
        "stampede_sessions": suite["stampede"]["sessions"],
        "swarm_sessions": suite["swarm"]["sessions"],
        "swarm_deliveries_per_sec":
            suite["swarm"]["deliveries_per_sec"],
        "tenant_throttle_nacks": suite["tenant_mix"]["throttle_nacks"],
        "chaos_storm_converged": True,
        "chaos_epochs": chaos.epochs,
        "chaos_slow_op_spans": len(chaos.slow_ops),
        "wake_jitter_probe_ms": probe,
        "gate": ("scenario convergence digests + evidence on every "
                 "host; storm-during-split/kill bit-identical with "
                 "partition-tagged /traces spans"),
        "_extra_trend": extra,
    }
    if skip_reason is not None:
        result["skipped"] = skip_reason
    return result


def config14_retention(min_cycles: int = 3) -> dict:
    """Retention-plane guard (ROADMAP item 3, ISSUE 14): the
    week-of-traffic churn gate plus the kill-mid-truncate /
    kill-mid-GC chaos gate — BOTH always run, on every host.

    - **Churn** (`testing.scenarios.run_week_of_traffic`): cycles of
      churning writers (storm-shaped hot doc + cold mix) stream
      bounded merge-tree edits through the supervised columnar farm
      (fused hop + summarizer + retention) while a swarm of
      subscribed readers and a mid-run reconnect stampede ride along.
      Gates: on-disk bytes (op logs + castore) hold a bounded
      high-water mark after the first retention cycle, every swarm
      session sees every record, and a live client, a cold boot from
      the newest summary, and a long-offline reconnector (its op gap
      physically reclaimed — it must REBOOT from the summary) all
      converge bit-identical with zero dup/skip.
    - **Chaos**: `--retention`-shaped run — the retention role in the
      kill schedule AND the two seeded kill points firing (between
      the fenced truncate commit and the physical reclaim, and
      mid-GC-sweep); recovery must roll every committed cut forward,
      converging bit-identical with zero dup/skip and summary
      integrity intact.

    The steady-state high-water mark feeds the bench_trend ledger as
    the LOWER-is-better ``retention_disk_mb`` headline."""
    from fluidframework_tpu.testing.chaos import ChaosConfig, run_chaos
    from fluidframework_tpu.testing.scenarios import run_week_of_traffic

    cycles = max(min_cycles, int(4 * SCALE))
    churn = run_week_of_traffic(
        cycles=cycles,
        hot_writers=max(6, int(12 * SCALE)),
        cold_docs=max(1, int(2 * SCALE)),
        ops_per_writer=max(12, int(30 * SCALE)),
        summary_ops=max(24, int(64 * SCALE)),
        rate_hz=max(300.0, 500.0 * SCALE),
        stampede_sessions=max(8, int(16 * SCALE)),
        swarm_sessions=max(12, int(48 * SCALE)),
        keep_tail=max(48, int(256 * SCALE)),
        timeout_s=300.0,
    )
    chaos = run_chaos(ChaosConfig(
        seed=14, faults=("kill",), n_docs=2, n_clients=3,
        ops_per_client=40, timeout_s=300.0, deli_impl="scalar",
        log_format="columnar", summarizer=True, summary_ops=16,
        retention=True,
    ))
    assert chaos.converged, (
        f"retention chaos run diverged: {chaos.detail}"
    )
    assert chaos.retention_ok and chaos.truncations > 0, (
        f"retention integrity failed: truncations={chaos.truncations}"
    )
    assert chaos.summaries_ok
    assert chaos.duplicate_seqs == 0 and chaos.skipped_seqs == 0
    return {
        "config": "retention_churn_guard",
        "cycles": churn["cycles"],
        "records": churn["records"],
        "retention_disk_mb": churn["retention_disk_mb"],
        "unit": "MB",
        "disk_bytes_per_cycle": churn["disk_bytes_per_cycle"],
        "churn_truncations": churn["truncations"],
        "chaos_retention_converged": True,
        "chaos_truncations": chaos.truncations,
        "chaos_gc_deleted": chaos.gc_deleted,
        "chaos_retention_base": chaos.retention_base_records,
        "gate": ("disk hwm bounded + tri-view bit-identity on every "
                 "host; kill-mid-truncate/GC rolls forward with zero "
                 "dup/skip"),
    }


def config15_device_plane(min_seq_ratio: float = 2.0,
                          min_fold_ratio: float = 5.0,
                          min_preserve: float = 0.9,
                          plane: str = "4x2") -> dict:
    """2-D device-plane guard (ROADMAP item 5, ISSUE 15): ONE
    ``docs x model`` mesh (`parallel.device_plane.DevicePlane`) must
    serve BOTH device tenants — the sequencer on its docs-axis slice
    and the summarizer folds over the whole pool — with no loss of
    either's contract:

    - **sequencer** (config7 extended to the 2-D layout): on real
      accelerator devices the plane slice must keep >=
      `min_seq_ratio` x the single-device aggregate submissions/s at
      4 docs-axis devices; on forced-host emulation (where even the
      plain 1-D mesh demonstrably does not scale like chips — the
      scheduler, not the sharding) the gate is PRESERVATION instead:
      the 2-D slice must keep >= `min_preserve` of whatever the 1-D
      docs mesh measures on the same grid. Verdict digests
      bit-identical across 1-dev / 1-D / plane is the ALWAYS-on gate;
    - **fold backend**: the overlay-pallas summarizer fold
      (`core.overlay_fold`, BENCH_r04/r05's ~38x engine) must reach
      >= `min_fold_ratio` x the vmapped kernel fold where HONESTLY
      measurable (`deli_bench.fold_parity_skip_reason`: pallas must
      actually lower — interpreter timing measures the interpreter),
      with canonical rows byte-identical across backends at every
      emission (the ALWAYS-on gate: content-addressed handles are
      backend-invariant);
    - **chaos** (always): a supervised kernel+columnar farm on a 2x2
      plane with the summarizer folding through the OVERLAY backend
      (interpreter mode) survives kill faults bit-identical to the
      scalar golden with summary integrity intact — blobs/handles
      equal to cold scalar replay on every host.

    Scaling asserts skip LOUDLY (explicit in the result, never
    silently retired) when `utils.devices.parity_skip_reason` /
    `fold_parity_skip_reason` say this host cannot measure them."""
    from fluidframework_tpu.parallel.device_plane import \
        parse_plane_spec
    from fluidframework_tpu.testing.chaos import ChaosConfig, run_chaos
    from fluidframework_tpu.testing.deli_bench import (
        fold_parity_skip_reason,
        run_device_plane_bench,
    )
    from fluidframework_tpu.utils.devices import parity_skip_reason

    d, m = parse_plane_spec(plane)
    seq_reason = parity_skip_reason(d * m)
    fold_reason = fold_parity_skip_reason()
    # Correctness-only hosts run the digest gates at sanity scale —
    # the interpreter-mode overlay fold is ~100x the engine's cost,
    # and the numbers are skipped anyway.
    small = seq_reason is not None or (os.cpu_count() or 1) < d * m
    res = run_device_plane_bench(
        plane=plane,
        n_docs=max(8, int((256 if small else 4096) * SCALE)),
        ops_per_doc=64, n_clients=8,
        repeats=1 if small else REPEATS,
        fold_docs=4,
        fold_ops=max(64, int((240 if fold_reason else 3000) * SCALE)),
    )
    chaos = run_chaos(ChaosConfig(
        seed=15, faults=("kill",), n_docs=2, n_clients=3,
        ops_per_client=30, timeout_s=420.0, deli_impl="kernel",
        log_format="columnar", summarizer=True, summary_ops=16,
        device_plane="2x2", fold_backend="overlay",
    ))
    assert chaos.converged, (
        f"device-plane chaos run diverged: {chaos.detail}"
    )
    assert chaos.summaries_ok and chaos.duplicate_seqs == 0 \
        and chaos.skipped_seqs == 0
    result = {
        "config": "device_plane_guard",
        "plane": plane,
        "min_seq_ratio": min_seq_ratio,
        "min_fold_ratio": min_fold_ratio,
        "min_preserve": min_preserve,
        "sequencer_speedup": res["sequencer"]["speedup"],
        "sequencer_oned_speedup": res["sequencer"]["oned_speedup"],
        "forced_host": res["sequencer"]["forced_host"],
        "fold_backend_speedup": res["fold_backend_speedup"],
        "fold_interpret": res["fold"]["interpret"],
        "emissions": res["fold"]["emissions"],
        "chaos_converged": True,
        "chaos_manifests": chaos.summary_manifests,
        "cores": res["cores"],
        "gate": res["gate"] + "; plane chaos kill run converged with "
                "summary integrity (overlay backend)",
        "unit": res["unit"],
    }
    skips = []
    if not res["sequencer"]["forced_host"]:
        # Real accelerator devices: the absolute config7 bar holds
        # on the 2-D layout.
        assert res["sequencer"]["speedup"] >= min_seq_ratio, (
            f"plane-slice sequencer reached only "
            f"{res['sequencer']['speedup']:.2f}x the single-device "
            f"aggregate (must be >= {min_seq_ratio}x): {result}"
        )
    elif seq_reason is not None:
        skips.append(f"sequencer scaling asserts skipped ({seq_reason})")
    else:
        # Forced-host emulation with enough cores: virtual devices
        # measure the scheduler, not chips (the plain 1-D mesh does
        # not reach the chip bar here either) — so gate PRESERVATION:
        # the 2-D slice keeps what the 1-D mesh measures on the SAME
        # grid, and the absolute bar is a loud skip.
        preserve = (res["sequencer"]["speedup"]
                    / max(res["sequencer"]["oned_speedup"], 1e-9))
        result["sequencer_preservation"] = round(preserve, 2)
        assert preserve >= min_preserve, (
            f"the 2-D plane slice LOST 1-D mesh scaling: "
            f"{res['sequencer']['speedup']:.2f}x vs the 1-D mesh's "
            f"{res['sequencer']['oned_speedup']:.2f}x "
            f"(preservation {preserve:.2f} < {min_preserve}): {result}"
        )
        skips.append(
            f"absolute >= {min_seq_ratio}x sequencer assert skipped "
            f"(forced virtual host devices measure the scheduler — "
            f"the 1-D mesh measures "
            f"{res['sequencer']['oned_speedup']:.2f}x here); "
            f"preservation gate RAN: plane slice "
            f"{res['sequencer']['speedup']:.2f}x >= {min_preserve} x "
            f"1-D"
        )
    if fold_reason is not None:
        skips.append(f"fold speedup assert skipped ({fold_reason})")
    else:
        assert res["fold_backend_speedup"] >= min_fold_ratio, (
            f"overlay fold backend reached only "
            f"{res['fold_backend_speedup']:.2f}x the vmapped kernel "
            f"fold (must be >= {min_fold_ratio}x): {result}"
        )
    if skips:
        result["skipped"] = "; ".join(
            skips + [f"digest + chaos gates ran: {result['gate']}"]
        )
        print(f"SKIP config15_device_plane: {result['skipped']}",
              file=sys.stderr)
    return result


def config_streaming_ingress(n_ops: int = 100_000,
                             n_segments: int = 8) -> dict:
    """Ingest-in-the-loop vs pre-staged replay (SURVEY §2.6 row 4
    pipeline overlap): the same lagged stream replayed (a) fully
    pre-staged on device, (b) fed host->device in segments with each
    transfer overlapping the previous segment's compute. The streaming
    number should sit within ~20% of pre-staged — the transfer rides
    the pipeline, not the critical path."""
    import jax

    from fluidframework_tpu.core.overlay_replay import OverlayDeviceReplica
    from fluidframework_tpu.testing.synthetic import generate_lagged_stream
    from fluidframework_tpu.utils.benchmark import run_benchmark

    n_ops = max(2048, int(n_ops * SCALE))
    interpret = jax.default_backend() not in ("tpu", "axon")
    if interpret:
        n_ops = min(n_ops, 4096)  # CPU interpreter sanity scale
    stream = generate_lagged_stream(
        n_ops, n_clients=64, seed=9, window=1024, initial_len=64,
        cache_dir=os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            ".bench_cache",
        ),
    )

    def rep():
        return OverlayDeviceReplica(
            stream, initial_len=64, chunk_size=128, window=2048,
            n_removers=24, interpret=interpret,
        )

    # Shared decode/upload OUTSIDE the timed regions: the pre-staged
    # number excludes its load phase (the headline's framing); the
    # streaming number INCLUDES its in-loop host->device feeds —
    # that delta is exactly what this config measures.
    staged = rep()
    staged.prepare()
    hosted = rep()
    hosted.prepare_host()

    def pre_workload():
        r = rep()
        r._dev = staged._dev
        r._msn_by_chunk = staged._msn_by_chunk
        r.replay()
        int(r.table.error)  # value fetch closes the timed region
        r.check_errors()

    def stream_workload():
        r = rep()
        r._host = hosted._host
        r._host_msn = hosted._host_msn
        r.replay_streaming(n_segments=n_segments)
        int(r.table.error)
        r.check_errors()

    pre_workload()  # warm both executables once
    stream_workload()
    pre = run_benchmark(pre_workload, repeats=REPEATS, warmups=0)
    strm = run_benchmark(stream_workload, repeats=REPEATS, warmups=0)
    return {
        "config": "streaming_ingress_vs_prestaged",
        "ops": n_ops, "segments": n_segments,
        "prestaged_ops_per_sec": round(n_ops / pre["mean"], 1),
        "streaming_ops_per_sec": round(n_ops / strm["mean"], 1),
        "streaming_overhead_pct": round(
            (strm["mean"] / pre["mean"] - 1) * 100, 1
        ),
        "stats": {"prestaged": pre, "streaming": strm},
    }


def main() -> None:
    results = []
    extra_trend = []
    for fn in (config1_sharedstring_2client, config3_matrix,
               config4_tree_rebase, config5_deli, config5_deli_pipeline,
               config5_metrics_overhead, config5_log_format,
               config6_shard_scaling, config7_multichip,
               config8_rebalance, config9_latency, config10_catchup,
               config11_fused_hop, config12_front_door,
               config13_scenarios, config14_retention,
               config15_device_plane,
               config_streaming_ingress):
        r = fn()
        # Side metrics a config wants in the trend ledger as their own
        # lines (e.g. config9's fused-hop latency delta) ride out via
        # "_extra_trend" — recorded, popped from the config's row.
        extra_trend.extend(r.pop("_extra_trend", []))
        results.append(r)
        print(json.dumps(r), file=sys.stderr)
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_DETAIL.json")
    # Preserve the bench_trend ledger across this file's wholesale
    # rewrite — history is the thing the regression gate compares to.
    try:
        with open(path) as f:
            trend = json.load(f).get("trend", {})
    except (OSError, ValueError):
        trend = {}
    with open(path, "w") as f:
        json.dump(
            {
                "note": (
                    "BASELINE.json configs 1/3/4/5; config 2 is bench.py. "
                    "TS baselines unmeasurable here: no node runtime "
                    "(see BASELINE.md)."
                ),
                "scale": SCALE,
                "results": results,
                "trend": trend,
            },
            f, indent=1,
        )
    # Fold this run into the trend ledger and FAIL LOUDLY on a >20%
    # drop vs the best prior run of any config (tools/bench_trend.py).
    try:
        from bench_trend import append_and_gate
    except ImportError:  # imported as a module, not run from tools/
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from bench_trend import append_and_gate

    failures = append_and_gate(path, results + extra_trend)
    for msg in failures:
        print(f"REGRESSION: {msg}", file=sys.stderr)
    print(json.dumps({"configs": len(results),
                      "trend_regressions": len(failures)}))
    if failures:
        sys.exit(1)


if __name__ == "__main__":
    main()
