"""Layer-check: enforce the package layering mechanically.

The reference enforces its layer DAG with a build-tools lint
(build-tools/packages/build-tools/src/layerCheck, surfaced in
PACKAGES.md); this is that role for fluidframework_tpu: every
intra-package import must point to the SAME or a LOWER layer. Run
directly or via tests/test_layer_check.py.

Layering (bottom-up, mirroring SURVEY.md §1):

    protocol, utils                 L0  definitions + plumbing
    native                          L0  (C++ bindings; imports nothing)
    core, ops, parallel             L1  engines/kernels
    testing                         L2  harnesses (may reach anything
                                        below, incl. server mocks)
    runtime                         L2  container/datastore runtime
    dds, tree                       L3  data structures
    drivers, loader                 L4  service adapters + loader
    framework                       L5  public API
    server                          L4s the service (peer of loader;
                                        shares L0-L2)
    tooling                         L6  offline analysis (any layer)

Exceptions (mirroring the reference's own):
- drivers.local_driver/socket_driver import `server` — the reference's
  local-driver likewise depends on local-server (SURVEY.md §2.3).
- server.socket_service imports drivers.file_driver's wire codec (a
  shared L0-shape concern living next to its primary consumer).
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Dict, List, Set, Tuple

PKG = "fluidframework_tpu"

LAYERS: Dict[str, int] = {
    "protocol": 0, "utils": 0, "native": 0,
    "core": 1, "ops": 1, "parallel": 1,
    "runtime": 2, "testing": 2,
    "dds": 3, "tree": 3,
    "drivers": 4, "loader": 4, "server": 4,
    "framework": 5,
    "tooling": 6,
}

# (from_subpackage, to_subpackage) pairs allowed despite layer order.
EXCEPTIONS: Set[Tuple[str, str]] = {
    ("drivers", "server"),   # local/socket drivers meet the service
    ("server", "drivers"),   # wire codec shared with file_driver
    ("testing", "server"),   # harnesses wire mock services
    ("testing", "dds"),
    ("core", "testing"),     # replicas consume synthetic streams
    ("core", "ops"),
}


def _subpackage(module: str) -> str:
    parts = module.split(".")
    if len(parts) < 2 or parts[0] != PKG:
        return ""
    return parts[1]


def check(root: str) -> List[str]:
    pkg_root = os.path.join(root, PKG)
    violations: List[str] = []
    for dirpath, _, files in os.walk(pkg_root):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root).replace(os.sep, ".")[:-3]
            sub = _subpackage(rel)
            if sub not in LAYERS:
                continue
            tree = ast.parse(open(path).read(), filename=path)
            for node in ast.walk(tree):
                targets: List[str] = []
                if isinstance(node, ast.Import):
                    targets = [a.name for a in node.names]
                elif isinstance(node, ast.ImportFrom):
                    if node.level:  # relative: resolve against rel
                        base = rel.split(".")[: -node.level]
                        mod = ".".join(base + ([node.module] if node.module else []))
                        targets = [mod]
                    elif node.module:
                        targets = [node.module]
                for t in targets:
                    tsub = _subpackage(t)
                    if not tsub or tsub == sub or tsub not in LAYERS:
                        continue
                    if (sub, tsub) in EXCEPTIONS:
                        continue
                    if LAYERS[tsub] > LAYERS[sub]:
                        violations.append(
                            f"{rel}: layer {LAYERS[sub]} ({sub}) imports "
                            f"layer {LAYERS[tsub]} ({tsub}) via {t}"
                        )
    return violations


def main() -> None:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    v = check(root)
    for line in v:
        print(line)
    print(f"{len(v)} layering violations")
    sys.exit(1 if v else 0)


if __name__ == "__main__":
    main()
