"""Produce a pinned summary+op-tail fixture for the back-compat
corpus (the packages/test/snapshots role).

Runs a deterministic two-client session over the runtime stack,
summarizes MID-SESSION, records the post-summary op tail, and writes
tests/fixtures/summary_v{N}.json with the expected final state. The
fixture is CHECKED IN; tests/test_snapshot_compat.py boots every
pinned fixture forever after — a loader change that cannot boot an old
round's summary + tail fails CI.

Usage: python tools/make_compat_fixture.py
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fluidframework_tpu.dds import (  # noqa: E402
    MapFactory,
    MatrixFactory,
    StringFactory,
)
from fluidframework_tpu.runtime import ChannelRegistry  # noqa: E402
from fluidframework_tpu.runtime.container_runtime import (  # noqa: E402
    SUMMARY_FORMAT_VERSION,
)
from fluidframework_tpu.drivers.file_driver import (  # noqa: E402
    message_to_json,
)
from fluidframework_tpu.testing.mocks import MultiClientHarness  # noqa: E402

FIXTURES = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tests", "fixtures",
)


def registry() -> ChannelRegistry:
    return ChannelRegistry([MapFactory(), StringFactory(), MatrixFactory()])


def main() -> None:
    h = MultiClientHarness(
        2, registry(),
        channel_types=[
            ("text", StringFactory.type_name),
            ("kv", MapFactory.type_name),
            ("grid", MatrixFactory.type_name),
        ],
    )
    a = h.runtimes[0].get_datastore("default")
    text, kv, grid = (
        a.get_channel("text"), a.get_channel("kv"), a.get_channel("grid")
    )
    text.insert_text(0, "hello world")
    text.annotate_range(0, 5, {"bold": 1})
    kv.set("k1", "v1")
    kv.set("k2", [1, 2, 3])
    grid.insert_rows(0, 4)
    grid.insert_cols(0, 4)
    grid.set_cell(1, 2, 42)
    h.process_all()
    b = h.runtimes[1].get_datastore("default")
    b.get_channel("text").insert_text(5, ", brave")
    b.get_channel("kv").set("k3", {"nested": True})
    h.process_all()

    wire = h.runtimes[0].summarize().to_json()
    summary_seq = h.runtimes[0].current_seq

    # Post-summary tail: more edits, recorded as sequenced messages.
    text.insert_text(0, ">> ")
    grid.set_cell(3, 3, 99)
    b.get_channel("text").remove_text(3, 5)
    h.process_all()
    tail = [
        message_to_json(m)
        for m in h.service.ops_from("doc", summary_seq)
    ]

    fixture = {
        "formatVersion": SUMMARY_FORMAT_VERSION,
        "summarySeq": summary_seq,
        "wire": wire,
        "tail": tail,
        "expect": {
            "text": text.get_text(),
            "kv": {"k1": "v1", "k2": [1, 2, 3], "k3": {"nested": True}},
            "grid_cells": {"1,2": 42, "3,3": 99},
        },
    }
    os.makedirs(FIXTURES, exist_ok=True)
    path = os.path.join(
        FIXTURES, f"summary_v{SUMMARY_FORMAT_VERSION}.json"
    )
    with open(path, "w") as f:
        json.dump(fixture, f, indent=1, sort_keys=True)
    print(f"wrote {path} (text={fixture['expect']['text']!r})")


if __name__ == "__main__":
    main()
